#!/usr/bin/env python
"""Benchmark harness: LUBM L1-L7 geomean latency on the TPU engine.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "us", "vs_baseline": N}

Methodology (round 1):
- dataset: LUBM(N) synthesized at WUKONG_BENCH_SCALE (default 160; 2560 when
  its cache exists), single chip, blind mode (results not shipped — matching
  the reference's silent-mode latency tables).
- selective const-start queries (L4-L6) run through the batched chain at
  B=1024 instances; index-origin heavies (L1-L3, L7) run through the batched
  index chain (qid dimension, replicate mode) at the largest B whose
  intermediates fit the capacity ceiling. Per-query latency = batch_time / B
  (the BASELINE.json metric is "at batch=1024").
- vs_baseline = reference GPU-engine geomean / our geomean on LUBM-2560
  (docs/performance/S1C24(MEEPO)-GPU-LUBM2560-20191121.md:143-157). >1 means
  faster than the reference's CUDA engine. When benching a smaller scale the
  ratio is reported against the same baseline and the metric names the scale.

Dataset + built-store caches live in .cache/ (gitignored) so later rounds
skip the multi-minute single-core CSR build.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)
# overridable so a frozen working-tree snapshot (the opportunistic bench
# loop) shares world caches + partial results with the live tree
CACHE = os.environ.get("WUKONG_CACHE_DIR") or os.path.join(REPO, ".cache")

# reference CUDA engine, LUBM-2560 L1-L7 (µs)
REF_GPU_LUBM2560 = [96157, 57383, 98915, 56, 45, 126, 51926]

# nominal HBM peak of the bench backend, for the roofline fields (round-4
# verdict #4): v5e = 819 GB/s per chip (public spec). The CPU fallback has
# no honest single number (DRAM peak varies with the VM), so peak stays
# null there and gbps is reported without a ratio.
PEAK_GBPS = {"tpu": 819.0}


def _attach_roofline(out: dict, eng, q, B: int, mode: str,
                     backend: str) -> None:
    """Roofline fields for one measured query: the host-computed HBM-traffic
    model (MergeExecutor.bytes_model — segment arrays streamed + table state
    touched at learned capacities) and the achieved GB/s it implies at the
    measured per-query latency. bytes_model is per CHAIN (one batch), us is
    per QUERY (chain / B), so achieved = bytes / (us * B). A lower bound on
    real traffic (each array counted once); `gbps_frac_peak` near 1 means
    the chain is HBM-bound and the latency is near the hardware floor."""
    from wukong_tpu.config import Global

    # observability add-on: it must never be able to destroy a measurement
    # that already succeeded, so every failure is swallowed to stderr
    try:
        if out.get("planner_empty") or not out.get("us") \
                or getattr(q, "planner_empty", False):
            # (the query-object check covers call sites that don't put the
            # flag in the detail dict, e.g. watdiv: a short-circuit latency
            # must never be divided into a full-chain byte count)
            return
        if not (Global.enable_merge_join and eng.merge.supports(q)):
            return  # the v1 probe path ran; merge-chain model doesn't apply
        bm = eng.merge.bytes_model(q, B, mode)
        if not bm:
            return
        chain_s = out["us"] * 1e-6 * B
        gbps = bm["total_bytes"] / chain_s / 1e9 if chain_s > 0 else 0.0
        out["bytes_model"] = bm
        out["gbps"] = round(gbps, 2)
        peak = PEAK_GBPS.get(backend)
        if peak:
            out["peak_gbps"] = peak
            out["gbps_frac_peak"] = round(gbps / peak, 4)
    except Exception as e:
        print(f"# roofline model failed (measurement kept): {e}",
              file=sys.stderr)

BASIC = "/root/reference/scripts/sparql_query/lubm/basic"
BATCH = 1024


def _geomean(xs):
    # floor at 0.1 us: planner-proved-empty queries answer in ~0, and a true
    # zero would zero the whole geomean (and log(0) is a warning)
    arr = np.maximum(np.asarray(xs, dtype=np.float64), 0.1)
    return float(np.exp(np.mean(np.log(arr))))


# round-4 verdict Weak #5 / Next #8: the synthesized datasets are NOT the
# reference generators' data — oracle parity (independent-engine equivalence)
# is exact, reference-table parity is approximate. Every artifact carries the
# caveat so the two are never conflated.
DATASET_NOTES = {
    "lubm": "synthetic-lubm (loader/lubm.py), not UBA-generated; result "
            "counts approximate vs the reference's published tables "
            "(q2@2560: 2,781,086 rows here vs 2,765,067 published)",
    "watdiv": "synthetic watdiv-shaped data (loader/watdiv.py), not the "
              "WatDiv generator's",
    "dbpedia": "synthetic dbpedia-shaped data (loader/generic_rdf.py); "
               "dbpsb template shapes, not DBpedia data",
}

# round-4 verdict Weak #1: the driver records a bounded tail of stdout, and
# round 4's final line (full per-query detail inline) outgrew it —
# BENCH_r04.json parsed as null and the round's headline was lost. Keep the
# final line comfortably under the window.
HEADLINE_MAX_BYTES = 2000


def _emit_final(obj: dict, detail_name: str | None = None) -> None:
    """Emit a bench result: the FULL object goes to a committed side file
    (`detail_name` at the repo root), and the LAST stdout line is a compact
    headline hard-capped at HEADLINE_MAX_BYTES — scalar fields plus
    per-query us only, dropping optional fields in order if it ever grows.
    Subprocess-protocol entries (--one, --at-scale-verify) do NOT use this:
    their full last line is consumed in-process, never through a tail."""
    head = {k: v for k, v in obj.items()
            if k not in ("detail", "verification")}
    det = obj.get("detail") or {}
    per_q = {qn: round(d["us"], 1) for qn, d in det.items()
             if isinstance(d, dict) and isinstance(d.get("us"), (int, float))}
    if per_q:
        head["per_query_us"] = per_q
    emu = det.get("sparql_emu")
    if isinstance(emu, dict):
        for src, dst in (("qps", "emu_qps"), ("warm_qps", "emu_warm_qps")):
            if isinstance(emu.get(src), (int, float)):
                head[dst] = round(emu[src], 1)
    if detail_name is not None:
        try:
            path = os.path.join(REPO, detail_name)
            with open(path + ".tmp", "w") as f:
                json.dump(obj, f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(path + ".tmp", path)
            head["detail_file"] = detail_name
        except Exception as e:
            print(f"# detail side file failed: {e}", file=sys.stderr)
    line = json.dumps(head)
    for drop in ("toggles", "dataset", "per_query_us"):
        if len(line) <= HEADLINE_MAX_BYTES:
            break
        head.pop(drop, None)
        line = json.dumps(head)
    if len(line) > HEADLINE_MAX_BYTES and isinstance(head.get("metric"), str):
        head["metric"] = head["metric"][:300] + "..."
        line = json.dumps(head)
    print(line, flush=True)


def _ensure_world(scale: int):
    from wukong_tpu.loader.lubm import (
        DATASET_VERSION,
        VirtualLubmStrings,
        generate_lubm,
    )
    from wukong_tpu.store.gstore import build_partition
    from wukong_tpu.store.persist import load_gstore, save_gstore
    from wukong_tpu.utils.errors import WukongError

    from wukong_tpu.planner.stats import Stats

    os.makedirs(CACHE, exist_ok=True)
    v = f"v{DATASET_VERSION}"
    store_path = os.path.join(CACHE, f"lubm{scale}_{v}_p0.npz")
    stats_path = os.path.join(CACHE, f"lubm{scale}_{v}_stats.npz")
    ss = VirtualLubmStrings(scale, seed=0)
    triples = None

    def load_tri():
        tri_path = os.path.join(REPO, f".cache_lubm{scale}_{v}_triples.npy")
        if os.path.exists(tri_path):
            return np.asarray(np.load(tri_path, mmap_mode="r"))
        tri = generate_lubm(scale, seed=0)[0]
        if scale >= 640:  # cache the multi-minute generation
            try:
                np.save(tri_path, tri)
            except Exception as e:
                print(f"# triples cache save failed: {e}", file=sys.stderr)
        return tri

    g = None
    if os.path.exists(store_path):
        try:
            g = load_gstore(store_path)
        except WukongError as e:  # corrupt/stale cache: rebuild, don't die
            print(f"# store cache invalid ({e}); rebuilding", file=sys.stderr)
            os.remove(store_path)
    if g is None:
        triples = load_tri()
        g = build_partition(triples, 0, 1)
        try:
            save_gstore(g, store_path)
        except Exception as e:
            print(f"# store cache save failed: {e}", file=sys.stderr)
    if os.path.exists(stats_path):
        stats = Stats.load(stats_path)
    else:
        if triples is None:
            triples = load_tri()
        stats = Stats.generate(triples)
        try:
            stats.save(stats_path)
        except Exception as e:
            print(f"# stats cache save failed: {e}", file=sys.stderr)
    del triples
    return g, ss, stats


def _probe_backend(deadline_s: int | None = None) -> bool:
    """Probe the TPU backend in a subprocess (a crashed relay worker hangs
    jax initialization indefinitely). Retries on a loop — a flaky relay often
    comes back within minutes, and one long attempt conflates "slow init"
    with "dead" (round-2 verdict #1). Returns True when the device backend is
    healthy; False means the bench must degrade to the CPU backend — a round
    must never end with no captured number (round-1 verdict Weak #3)."""
    import subprocess

    if deadline_s is None:
        deadline_s = int(os.environ.get("WUKONG_PROBE_TIMEOUT", "240"))
    attempt_s = int(os.environ.get("WUKONG_PROBE_ATTEMPT", "90"))
    t_end = time.time() + deadline_s
    attempt = 0
    while True:
        attempt += 1
        budget = min(attempt_s, max(int(t_end - time.time()), 30))
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax, jax.numpy as jnp; "
                 "jax.device_get(jnp.arange(2) + 1); "
                 "print(jax.devices()[0].platform)"],
                check=True, timeout=budget, capture_output=True)
            platform = r.stdout.decode().strip().splitlines()[-1]
            if platform == "cpu":
                print("# ambient JAX platform is cpu — labeling cpu-fallback",
                      file=sys.stderr)
                return False
            return True
        except subprocess.TimeoutExpired:
            print(f"# probe attempt {attempt} unresponsive after {budget}s",
                  file=sys.stderr, flush=True)
        except subprocess.CalledProcessError as e:
            print(f"# probe attempt {attempt} failed:\n"
                  f"# {e.stderr.decode()[-300:]}", file=sys.stderr, flush=True)
        if time.time() >= t_end:
            print(f"# device backend unreachable within {deadline_s}s — "
                  "falling back to CPU backend", file=sys.stderr)
            return False
        time.sleep(min(15, max(t_end - time.time(), 0)))


# ----------------------------------------------------------------------
# partial-result persistence: every successful per-query TPU measurement is
# written to .cache/bench_partial.json so a mid-round relay death costs the
# remaining queries, not the round's evidence. The final assembly prefers the
# best TPU-backend result per (scale, query, toggles) over a same-run CPU
# fallback (round-2 verdict "Next round" #1).
# ----------------------------------------------------------------------
PARTIAL_PATH = os.path.join(CACHE, "bench_partial.json")
# entries older than this never enter the final assembly: partials exist to
# stitch ONE round's flaky-relay captures together, not to let a previous
# round's (older code, possibly faster-but-wrong) numbers mask regressions
PARTIAL_MAX_AGE_S = 24 * 3600


_TOGGLE_DEFAULTS = (("WUKONG_ENABLE_MERGE", "1"), ("WUKONG_ENABLE_PALLAS", "1"),
                    ("WUKONG_ENABLE_FP_PROBE", "1"),
                    ("WUKONG_ENABLE_STREAM", "1"),
                    ("WUKONG_ENABLE_STREAM_MHOT", "1"),
                    ("WUKONG_CAP_MAX", "0"))  # 0 = config default


def _toggles_key() -> str:
    # EVERY measured-config env knob must appear here, or the partial
    # store would serve numbers measured under a different configuration
    return ",".join(f"{k}={os.environ.get(k, dflt)}"
                    for k, dflt in _TOGGLE_DEFAULTS)


def _partial_key(scale: int, qn: str, backend: str) -> str:
    # DATASET_VERSION in the key: a regenerated world must never be served
    # numbers measured against the old data
    from wukong_tpu.loader.lubm import DATASET_VERSION

    return f"lubm{scale}v{DATASET_VERSION}:{qn}:{backend}:{_toggles_key()}"


def _legacy_partial_key(scale: int, qn: str, backend: str) -> str | None:
    """Pre-CAP_MAX key format (round-3 snapshot code): same measured
    configuration whenever CAP_MAX is at its default, so entries recorded
    under the old format must keep serving — a key-format change must
    never silently drop captured on-chip evidence."""
    if os.environ.get("WUKONG_CAP_MAX", "0") != "0":
        return None  # a non-default CAP_MAX is a genuinely new config
    from wukong_tpu.loader.lubm import DATASET_VERSION

    old = ",".join(f"{k}={os.environ.get(k, d)}"
                   for k, d in _TOGGLE_DEFAULTS[:-1])
    return f"lubm{scale}v{DATASET_VERSION}:{qn}:{backend}:{old}"


def _load_partial() -> dict:
    try:
        with open(PARTIAL_PATH) as f:
            return json.load(f)
    except Exception:
        return {}


def _record_partial(scale: int, qn: str, backend: str, detail: dict) -> None:
    """Keep the best (lowest-latency) result per (scale, query, backend,
    toggles). flock-serialized read-modify-write: the opportunistic bench
    loop and a driver-run bench share this file BY DESIGN, and an unlocked
    RMW would let one silently drop the other's on-chip measurements."""
    import fcntl

    try:
        os.makedirs(CACHE, exist_ok=True)
        with open(PARTIAL_PATH + ".lock", "w") as lk:
            fcntl.flock(lk, fcntl.LOCK_EX)
            store = _load_partial()
            key = _partial_key(scale, qn, backend)
            prev = store.get(key)
            if prev is None or detail["us"] < prev["us"]:
                store[key] = dict(detail,
                                  ts=time.strftime("%Y-%m-%dT%H:%M:%S"))
                tmp = PARTIAL_PATH + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(store, f, indent=1, sort_keys=True)
                os.replace(tmp, PARTIAL_PATH)
    except Exception as e:
        print(f"# partial-result persist failed: {e}", file=sys.stderr)


def _partial_fresh(d: dict) -> bool:
    try:
        age = time.time() - time.mktime(
            time.strptime(d["ts"], "%Y-%m-%dT%H:%M:%S"))
        return age <= PARTIAL_MAX_AGE_S
    except Exception:
        return False


def _ab_partials(scale: int, qn: str, store: dict) -> dict:
    """On-chip measurements of the SAME query under non-default kernel
    toggles (the loop cycles WUKONG_ENABLE_MERGE=0 / WUKONG_ENABLE_STREAM=0
    / WUKONG_ENABLE_STREAM_MHOT=0 passes): {toggle-diff: us}. Surfaces the
    kernel A/B in the artifact.
    Same freshness contract as _best_tpu_partial (stale entries measured
    older code and must not masquerade as the current A/B)."""
    from wukong_tpu.loader.lubm import DATASET_VERSION

    prefix = f"lubm{scale}v{DATASET_VERSION}:{qn}:tpu:"
    default = _toggles_key().split(",")
    out = {}
    for key, d in store.items():
        if not key.startswith(prefix) or not _partial_fresh(d):
            continue
        toggles = key[len(prefix):].split(",")
        if len(toggles) == len(default) - 1:
            # pre-CAP_MAX key format == same config at the default value
            toggles = toggles + ["WUKONG_CAP_MAX=0"]
        if toggles == default or len(toggles) != len(default):
            continue  # other legacy formats would zip-truncate badly
        diff = ",".join(t for t, t0 in zip(toggles, default) if t != t0)
        out[diff] = d["us"]
    return out


def _drop_partial(scale: int, qn: str, backend: str,
                  above_batch: int) -> None:
    """Remove banked entries (current + legacy key) that an OOM
    batch-halving restart just invalidated: anything provisional, or
    measured at a batch above the size we are falling back to, claims a
    configuration this chip just refused — and _record_partial's
    keep-the-min rule would otherwise let its lower per-query latency
    mask the honest smaller-batch result forever. Complete entries at or
    below the new batch stay."""
    import fcntl

    def _stale(d: dict) -> bool:
        return bool(d.get("provisional")) or d.get("batch", 0) > above_batch

    try:
        # mirror _record_partial: in a fresh cache dir the lock file's
        # parent may not exist yet (ADVICE.md round-5 #4)
        os.makedirs(CACHE, exist_ok=True)
        with open(PARTIAL_PATH + ".lock", "w") as lk:
            fcntl.flock(lk, fcntl.LOCK_EX)
            store = _load_partial()
            keys = [_partial_key(scale, qn, backend),
                    _legacy_partial_key(scale, qn, backend)]
            hit = [k for k in keys
                   if k and k in store and _stale(store[k])]
            if hit:
                for k in hit:
                    del store[k]
                tmp = PARTIAL_PATH + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(store, f, indent=1, sort_keys=True)
                os.replace(tmp, PARTIAL_PATH)
    except Exception as e:
        print(f"# partial drop failed: {e}", file=sys.stderr)


def _best_tpu_partial(scale: int, qn: str, store: dict | None = None) -> dict | None:
    store = _load_partial() if store is None else store
    d = store.get(_partial_key(scale, qn, "tpu"))
    if not d or not _partial_fresh(d):
        legacy = _legacy_partial_key(scale, qn, "tpu")
        d = store.get(legacy) if legacy else None
    if not d or not _partial_fresh(d):
        return None
    return dict(d)


LADDER_SCALES = (40, 160, 2560)  # bench_loop.sh rungs


def _other_scale_tpu_evidence(target_scale: int, queries: list,
                              store: dict) -> dict:
    """Best banked on-chip numbers at every ladder rung OTHER than the
    target scale: real evidence on a degraded-relay round (whose only TPU
    captures may live at LUBM-40/160), kept OUT of the headline geomean —
    a different scale is a different workload — but IN the artifact.
    _best_tpu_partial applies the store's freshness / dataset-version /
    toggles contracts, so stale or regenerated-world entries never
    surface."""
    other = {}
    for s2 in LADDER_SCALES:
        if s2 == target_scale:
            continue
        per = {qn: b["us"] for qn in queries
               if (b := _best_tpu_partial(s2, qn, store)) and "us" in b}
        if per:
            other[str(s2)] = per
    return other


REF_EMU_QPS_LUBM2560 = 73_400.0  # 1-node sparql-emu A1-A6 @ p=30
# (docs/performance/S1C24-LUBM2560-20181203.md:139-145)


def emu_main(device_ok: bool) -> None:
    """`bench.py --emu`: sparql-emu mixed throughput with the reference
    A1-A6 mix (scripts/sparql_query/lubm/emulator/mix_config) — light
    templates ride the TPU device-batch path, the rest the host pool.
    Prints one JSON line; persists the per-query-cost equivalent
    (us = 1e6/qps) to the partial store so opportunistic on-chip captures
    survive a relay death."""
    scale = int(os.environ.get("WUKONG_BENCH_SCALE", "0"))
    if scale == 0:
        from wukong_tpu.loader.lubm import DATASET_VERSION

        v = f"v{DATASET_VERSION}"
        scale = 2560 if device_ok and (
            os.path.exists(os.path.join(CACHE, f"lubm2560_{v}_p0.npz"))
            or os.path.exists(
                os.path.join(REPO, f".cache_lubm2560_{v}_triples.npy"))
        ) else (160 if device_ok else 40)
    if not device_ok and scale > 40 \
            and os.environ.get("WUKONG_EMU_FORCE") != "1":
        # the clamp protects the orchestrated bench's deadline; an explicit
        # WUKONG_EMU_FORCE=1 runs the requested scale on the CPU backend
        # (the at-scale throughput evidence, BENCH_2560_CPU-style)
        print(f"# emu cpu-fallback: clamping scale {scale} -> 40",
              file=sys.stderr)
        scale = 40
    g, ss, stats = _ensure_world(scale)
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.runtime.emulator import Emulator, load_mix_config
    from wukong_tpu.runtime.proxy import Proxy

    proxy = Proxy(g, ss, cpu_engine=CPUEngine(g, ss),
                  tpu_engine=TPUEngine(g, ss, stats=stats),
                  planner=Planner(stats))
    mix = load_mix_config(
        "/root/reference/scripts/sparql_query/lubm/emulator/mix_config", ss)
    emu = Emulator(proxy)
    dur = float(os.environ.get("WUKONG_EMU_DURATION", "10"))
    p_cap = int(os.environ.get("WUKONG_EMU_P", "8"))
    # at-scale runs need the warmup window to cover one-time segment
    # staging + first compiles (~90 s at LUBM-2560), or the measured
    # window is mostly cold work
    warm = float(os.environ.get("WUKONG_EMU_WARMUP", "2"))
    res = emu.run(mix, duration_s=dur, warmup_s=warm, parallel=p_cap)
    qps = res["thpt_qps"]
    backend = "tpu" if device_ok else "cpu"
    if qps > 0:
        _record_partial(scale, "sparql_emu", backend,
                        {"us": round(1e6 / qps, 3), "qps": round(qps, 1),
                         "warm_qps": round(res.get("warm_qps") or qps, 1),
                         "wall_qps": res.get("wall_qps"),
                         "scale": scale, "backend": backend,
                         "p": p_cap, "duration_s": dur,
                         "class_mode": res.get("class_mode", {})})
    comparable = device_ok and scale == 2560
    _emit_final({
        "metric": f"LUBM-{scale} sparql-emu A1-A6 mixed throughput, "
                  f"{'TPU device-batch + host pool' if device_ok else 'cpu-fallback'},"
                  f" p={p_cap}, {dur:.0f}s (baseline: reference 73.4K q/s"
                  " 1-node @ LUBM-2560)",
        "value": round(qps, 1),
        "unit": "q/s",
        "vs_baseline": (round(qps / REF_EMU_QPS_LUBM2560, 3)
                        if comparable else None),
        "backend": backend,
        "dataset": DATASET_NOTES["lubm"],
        **({"warm_qps": round(res["warm_qps"], 1)}
           if res.get("warm_qps") else {}),
        "detail": {"errors": res["errors"],
                   "class_mode": res.get("class_mode", {}),
                   "warm_qps": res.get("warm_qps"),
                   "wall_qps": res.get("wall_qps"),
                   "precompiled_classes": res.get("precompiled_classes"),
                   "cdf_p50_us": {c: v.get(0.5) for c, v in
                                  res["cdf"].items() if v}},
    }, "BENCH_EMU_DETAIL.json")


def serve_main(device_ok: bool) -> None:
    """`bench.py --serve-batched`: serving-path throughput before/after
    continuous micro-batching (runtime/batcher.py) on a same-template
    open-loop workload — closed-loop client threads submitting query TEXTS
    through proxy.serve_query (parse cache -> plan cache -> batcher or
    direct engine). The OFF number is the seed serving path; the ON number
    coalesces compatible queries into fused chain dispatches. Also runs
    the overhead guards (interleaved on/off 2-hop micro — each off knob
    must be zero-touch; p25..p75 bands must overlap) for the admission
    plane, the device observatory, and the compiled-template route
    chooser, plus the `device_compiled_template` rung: an unanchored
    2-hop chain served host-walk vs whole-plan fused program.
    Artifact: BENCH_SERVE.json with both numbers, the speedup, the
    template headline, and the per-plane overhead detail."""
    import numpy as np

    from wukong_tpu.config import Global
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.loader.lubm import UB
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.runtime.emulator import Emulator
    from wukong_tpu.runtime.proxy import Proxy
    from wukong_tpu.types import OUT

    scale = int(os.environ.get("WUKONG_BENCH_SCALE", "0")) or 1
    g, ss, stats = _ensure_world(scale)
    proxy = Proxy(g, ss, cpu_engine=CPUEngine(g, ss),
                  tpu_engine=TPUEngine(g, ss, stats=stats),
                  planner=Planner(stats))
    # the default serving route (device engine when enable_tpu) on a light
    # same-template class: one device dispatch per query unbatched, one per
    # GROUP batched — the serving-path analogue of the emulator's
    # device-batch win. WUKONG_SERVE_HOST=1 pins the host engines instead.
    if os.environ.get("WUKONG_SERVE_HOST") == "1":
        Global.enable_tpu = False
    pid = ss.str2id(f"<{UB}advisor>")
    anchors = np.asarray(g.get_index(pid, OUT))
    texts = [f"SELECT ?s WHERE {{ ?s <{UB}advisor> "
             f"{ss.id2str(int(a))} . }}" for a in anchors[:512]]
    dur = float(os.environ.get("WUKONG_SERVE_DURATION", "10"))
    clients = int(os.environ.get("WUKONG_SERVE_CLIENTS", "16"))
    emu = Emulator(proxy)
    for t in texts[:8]:  # warm parse/plan caches + engine jit shapes
        proxy.serve_query(t, blind=True)

    Global.enable_batching = False
    off = emu.run_serving(texts, duration_s=dur, warmup_s=1.0,
                          clients=clients, seed=1)
    Global.enable_batching = True
    on = emu.run_serving(texts, duration_s=dur, warmup_s=1.0,
                         clients=clients, seed=1)
    Global.enable_batching = False
    speedup = round(on["qps"] / off["qps"], 2) if off["qps"] else None
    from wukong_tpu.obs import get_registry

    snap = get_registry().snapshot()
    batch_metrics = {
        name: [{**s["labels"], "value": s["value"]}
               for s in snap.get(name, {}).get("series", [])]
        for name in ("wukong_batch_flush_total", "wukong_batch_bypass_total",
                     "wukong_batch_fallback_total",
                     "wukong_batch_fused_queries_total")}
    occ = snap.get("wukong_batch_occupancy", {}).get("series", [])
    mean_occ = (round(occ[0]["sum"] / occ[0]["count"], 2)
                if occ and occ[0].get("count") else None)

    # admission-plane overhead guard: the off knob must be zero-touch on
    # the serving path. Single-threaded 2-hop micro, interleaved
    # admission-off / admission-on (armed but uncontended: no quotas, no
    # overload) chunks; the p25..p75 latency bands must overlap — a
    # disjoint band means the plane taxes the hot path even when idle/off
    from wukong_tpu.runtime.admission import get_admission
    from wukong_tpu.utils.timer import get_usec

    two_hop = (f"SELECT ?x ?y WHERE {{ ?x <{UB}advisor> "
               f"{ss.id2str(int(anchors[0]))} . "
               f"?x <{UB}memberOf> ?y . }}")
    for _ in range(30):  # warm the 2-hop parse/plan/engine shapes
        proxy.serve_query(two_hop, blind=True)
    lat = {"off": [], "on": []}
    prev_adm = Global.enable_admission
    get_admission().reset()
    try:
        for _round in range(30):
            for mode in ("off", "on"):
                Global.enable_admission = mode == "on"
                for _ in range(10):
                    t0 = get_usec()
                    proxy.serve_query(two_hop, blind=True)
                    lat[mode].append(get_usec() - t0)
    finally:
        Global.enable_admission = prev_adm
        get_admission().reset()

    # device-observatory overhead guard, same shape: when off the seams
    # are one knob check each; when on the charge is post-sync dict
    # updates under leaf locks — neither may shift the micro's band
    from wukong_tpu.obs.device import get_device_obs

    dlat = {"off": [], "on": []}
    prev_dev = Global.enable_device_obs
    get_device_obs().reset()
    try:
        for _round in range(30):
            for mode in ("off", "on"):
                Global.enable_device_obs = mode == "on"
                for _ in range(10):
                    t0 = get_usec()
                    proxy.serve_query(two_hop, blind=True)
                    dlat[mode].append(get_usec() - t0)
    finally:
        Global.enable_device_obs = prev_dev

    def band(xs: list) -> dict:
        xs = sorted(xs)
        return {"p25_us": int(xs[len(xs) // 4]),
                "p50_us": int(xs[len(xs) // 2]),
                "p75_us": int(xs[(3 * len(xs)) // 4])}

    b_off, b_on = band(lat["off"]), band(lat["on"])
    bands_overlap = (b_off["p25_us"] <= b_on["p75_us"]
                     and b_on["p25_us"] <= b_off["p75_us"])
    admission_overhead = {
        "query": "2-hop chain micro, single-threaded, interleaved",
        "samples_per_mode": len(lat["off"]),
        "off": b_off, "on": b_on,
        "bands_overlap": bands_overlap,
    }
    db_off, db_on = band(dlat["off"]), band(dlat["on"])
    device_bands_overlap = (db_off["p25_us"] <= db_on["p75_us"]
                            and db_on["p25_us"] <= db_off["p75_us"])
    device_observatory = {
        "query": "2-hop chain micro, single-threaded, interleaved",
        "samples_per_mode": len(dlat["off"]),
        "off": db_off, "on": db_on,
        "bands_overlap": device_bands_overlap,
    }

    # COMPILED TEMPLATE serving rung: an UNANCHORED 2-hop chain (the
    # whole advisor->memberOf join, large enough to clear the route's
    # row floor) served through proxy.serve_query with the template
    # route pinned host vs device — the device number is the whole plan
    # as ONE fused XLA dispatch on the live serving path (plan cache,
    # admission, metrics all armed). Ratio trends in bench_report; the
    # gate is structural: the route must actually compile (programs
    # staged, zero fallbacks) and agree with the host walk byte-for-byte
    big_chain = (f"SELECT ?x ?y WHERE {{ ?x <{UB}advisor> ?y . "
                 f"?y <{UB}worksFor> ?z . }}")
    treps = int(os.environ.get("WUKONG_SERVE_TEMPLATE_REPS", "5"))
    prev_tmpl = Global.template_device
    tmpl_ms = {"host": None, "device": None}
    tmpl_rows = {"host": None, "device": None}
    try:
        for mode in ("host", "device"):
            Global.template_device = mode
            for _ in range(2):  # warm plan cache + stage the program
                proxy.serve_query(big_chain, blind=True)
            for _ in range(treps):
                t0 = get_usec()
                qq = proxy.serve_query(big_chain, blind=True)
                dt = get_usec() - t0
                tmpl_ms[mode] = (dt if tmpl_ms[mode] is None
                                 else min(tmpl_ms[mode], dt))
                tmpl_rows[mode] = int(qq.result.nrows)
        tmpl_programs = proxy.template_engine().program_count()
    finally:
        Global.template_device = prev_tmpl
    device_compiled_template = (
        round(tmpl_ms["host"] / tmpl_ms["device"], 2)
        if tmpl_ms["host"] and tmpl_ms["device"] else None)
    template_serving = {
        "query": "unanchored advisor->worksFor 2-hop, blind, "
                 "single-threaded best-of-reps",
        "host_us": tmpl_ms["host"], "device_us": tmpl_ms["device"],
        "ratio": device_compiled_template,
        "rows_match": bool(tmpl_rows["host"] == tmpl_rows["device"]
                           and tmpl_rows["host"] is not None),
        "programs_staged": tmpl_programs,
        "reps": treps,
    }

    # ...and the template plane's zero-touch guard: template_device
    # "host" (plane off) vs "auto" (armed — the chooser runs, memoized
    # off the plan cache, and routes this small anchored micro back to
    # the walk via template_min_rows) interleaved on the same 2-hop
    # micro; the bands must overlap or the chooser taxes every query
    tlat = {"off": [], "on": []}
    try:
        for _round in range(30):
            for mode in ("off", "on"):
                Global.template_device = "host" if mode == "off" else "auto"
                for _ in range(10):
                    t0 = get_usec()
                    proxy.serve_query(two_hop, blind=True)
                    tlat[mode].append(get_usec() - t0)
    finally:
        Global.template_device = prev_tmpl
    tb_off, tb_on = band(tlat["off"]), band(tlat["on"])
    template_bands_overlap = (tb_off["p25_us"] <= tb_on["p75_us"]
                              and tb_on["p25_us"] <= tb_off["p75_us"])
    template_overhead = {
        "query": "2-hop chain micro, single-threaded, interleaved",
        "samples_per_mode": len(tlat["off"]),
        "off": tb_off, "on": tb_on,
        "bands_overlap": template_bands_overlap,
    }

    # transport-seam zero-touch pin: the default loopback transport must
    # leave the 2-hop micro where the previous PR's artifact put it. The
    # loopback has no on/off knob to interleave (it IS the off state), so
    # the guard is cross-artifact: this run's clean off band vs the band
    # committed in the prior BENCH_SERVE.json. Generous threshold (new
    # p50 <= 2x prior p75 — machines and loads differ between runs);
    # record-only on the first run after the seam lands
    prior_band = None
    try:
        with open(os.path.join(REPO, "BENCH_SERVE.json")) as f:
            prior = json.load(f)
        prior_band = (prior.get("detail", {})
                      .get("transport_zero_touch", {}).get("band")
                      or prior.get("detail", {})
                      .get("admission_overhead", {}).get("off"))
    except (OSError, ValueError):
        pass
    transport_zero_touch = {
        "query": "2-hop chain micro, single-threaded (admission-off band)",
        "transport_mode": Global.transport_mode,
        "band": b_off,
        "prior_band": prior_band,
        "within_band": (bool(b_off["p50_us"] <= 2 * prior_band["p75_us"])
                        if prior_band else None),
    }
    _emit_final({
        "metric": f"LUBM-{scale} serving-path throughput, {clients} clients "
                  f"x {dur:.0f}s same-template closed loop "
                  "(batched vs unbatched serving, device-engine route)",
        "value": on["qps"],
        "unit": "q/s",
        "unbatched_qps": off["qps"],
        "batched_qps": on["qps"],
        "speedup": speedup,
        # whole-plan compiled template vs host walk on the live serving
        # path (wall ratio; backend-dependent — the structural win, one
        # dispatch instead of a per-step sync chain, gates in
        # BENCH_CYCLIC's compiled rung)
        "device_compiled_template": device_compiled_template,
        "backend": "tpu" if device_ok else "cpu",
        "detail": {
            "before": off, "after": on,
            "knobs": {"batch_window_us": Global.batch_window_us,
                      "batch_max_size": Global.batch_max_size,
                      "clients": clients, "scale": scale},
            "mean_batch_occupancy": mean_occ,
            "batch_metrics": batch_metrics,
            "admission_overhead": admission_overhead,
            "device_observatory": device_observatory,
            "template_serving": template_serving,
            "template_overhead": template_overhead,
            "transport_zero_touch": transport_zero_touch,
            "dataset": DATASET_NOTES["lubm"],
        },
    }, "BENCH_SERVE.json")
    # overhead guards self-gate (WUKONG_SERVE_NOGATE=1 skips for noisy
    # local runs): an idle admission plane may not shift the micro's band
    if os.environ.get("WUKONG_SERVE_NOGATE") != "1" and not bands_overlap:
        raise SystemExit(
            f"serve drill FAILED: admission on/off p50 bands disjoint on "
            f"the 2-hop micro (off={b_off}, on={b_on}) — the off knob "
            "must be zero-touch")
    # ...and neither may the device observatory's dispatch seams
    if os.environ.get("WUKONG_SERVE_NOGATE") != "1" \
            and not device_bands_overlap:
        raise SystemExit(
            f"serve drill FAILED: device-observatory on/off p50 bands "
            f"disjoint on the 2-hop micro (off={db_off}, on={db_on}) — "
            "the dispatch seam may not tax the hot path")
    # the compiled-template headline must be REAL: the device mode must
    # have staged+run a fused program and agreed with the host walk
    if os.environ.get("WUKONG_SERVE_NOGATE") != "1":
        if device_compiled_template is None or not tmpl_programs:
            raise SystemExit(
                "serve drill FAILED: device_compiled_template headline "
                f"missing (ratio={device_compiled_template}, programs="
                f"{tmpl_programs}) — the template route never compiled")
        if not template_serving["rows_match"]:
            raise SystemExit(
                f"serve drill FAILED: compiled-template serving rows "
                f"{tmpl_rows['device']} != host walk {tmpl_rows['host']}")
        if not template_bands_overlap:
            raise SystemExit(
                f"serve drill FAILED: template-route on/off p50 bands "
                f"disjoint on the 2-hop micro (off={tb_off}, on={tb_on}) "
                "— the route chooser may not tax the hot path")
        if transport_zero_touch["within_band"] is False:
            raise SystemExit(
                f"serve drill FAILED: 2-hop micro p50 {b_off['p50_us']}us "
                f"blew past 2x the prior artifact's p75 "
                f"({prior_band['p75_us']}us) — the loopback transport "
                "seam must stay zero-touch on the serving path")


def graphrag_main(device_ok: bool) -> None:
    """`bench.py --graphrag`: the hybrid graph+vector serving benchmark
    (wukong_tpu/vector/). Three measurements in one artifact:

    - pure-scan kernel rate: brute-force k-NN over a >=100k x 128d
      embedding block, XLA device route vs NumPy host route, as a GFLOP
      rate + device/host ratio. When the ratio clears 3x the device
      route carries wide scans; otherwise the measured-demotion drill
      must engage cleanly (per-scan device failure falls back to host
      with the demotion latched for the route memo) — one of the two is
      the acceptance bar (WUKONG_GRAPHRAG_NOGATE=1 skips).
    - hybrid q/s: Emulator.run_graphrag drives a Zipfian mixed workload
      (pure graph 1-hops + knn()-seeded chains over LUBM professors)
      through the live serving path — the headline.
    - vectors-off zero-touch: the 2-hop serving micro interleaved with
      enable_vectors off/on (query knn-free both ways); the p25..p75
      latency bands must overlap — the vector plane may not tax graph
      traffic.
    Artifact: BENCH_GRAPHRAG.json."""
    import numpy as np

    from wukong_tpu.config import Global
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.loader.datagen import make_vectors
    from wukong_tpu.loader.lubm import UB
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.runtime.emulator import Emulator
    from wukong_tpu.runtime.proxy import Proxy
    from wukong_tpu.types import OUT
    from wukong_tpu.utils.timer import get_usec
    from wukong_tpu.vector import knn as vknn
    from wukong_tpu.vector.vstore import VectorStore, upsert_batch_into

    # ---- pure-scan kernel rate (standalone block, no graph needed) ----
    N = int(os.environ.get("WUKONG_GRAPHRAG_N", "120000"))
    D = int(os.environ.get("WUKONG_GRAPHRAG_DIM", "128"))
    K, METRIC, REPS = 10, "cosine", 5
    rng = np.random.default_rng(7)
    block = rng.standard_normal((N, D)).astype(np.float32)
    svids = np.arange(N, dtype=np.int64)
    salive = np.ones(N, dtype=bool)
    anchor = block[0].copy()
    vknn.topk_device(svids, block, salive, anchor, K, METRIC)  # jit warm

    def best_of(fn) -> float:
        best = float("inf")
        for _ in range(REPS):
            t0 = get_usec()
            fn()
            best = min(best, (get_usec() - t0) / 1e6)
        return best

    t_host = best_of(lambda: vknn.topk_host(
        svids, block, salive, anchor, K, METRIC))
    t_dev = best_of(lambda: vknn.topk_device(
        svids, block, salive, anchor, K, METRIC))
    flops = 2.0 * N * D  # one dot product per candidate row
    ratio = round(t_host / t_dev, 2) if t_dev > 0 else None
    scan = {
        "n": N, "dim": D, "k": K, "metric": METRIC,
        "host_s": round(t_host, 6), "device_s": round(t_dev, 6),
        "host_gflops": round(flops / t_host / 1e9, 2),
        "device_gflops": round(flops / t_dev / 1e9, 2),
        "device_vs_host": ratio,
        "backend": "tpu" if device_ok else "cpu",
    }

    # ---- measured-demotion drill (the JOIN_ROUTES posture) ----
    vs_small = VectorStore(0, 1, 16)
    vs_small.upsert(np.arange(256, dtype=np.int64),
                    rng.standard_normal((256, 16)).astype(np.float32))
    want_v, want_s, _ = vknn.scan_topk(vs_small, np.asarray(
        vs_small.get(0)), 5, METRIC, route="host")
    prev_hook = vknn._DEVICE_FAIL_HOOK

    def _boom():
        raise RuntimeError("injected device failure (graphrag drill)")

    try:
        vknn._DEVICE_FAIL_HOOK = _boom
        got_v, got_s, demoted = vknn.scan_topk(
            vs_small, np.asarray(vs_small.get(0)), 5, METRIC,
            route="device")
    finally:
        vknn._DEVICE_FAIL_HOOK = prev_hook
    demotion_clean = bool(demoted is not None
                          and np.array_equal(got_v, want_v)
                          and np.allclose(got_s, want_s))

    # ---- hybrid serving throughput (Zipfian GraphRAG mix) ----
    scale = int(os.environ.get("WUKONG_BENCH_SCALE", "0")) or 1
    g, ss, stats = _ensure_world(scale)
    proxy = Proxy(g, ss, cpu_engine=CPUEngine(g, ss),
                  tpu_engine=TPUEngine(g, ss, stats=stats),
                  planner=Planner(stats))
    pid = ss.str2id(f"<{UB}advisor>")
    profs = np.unique(np.asarray(g.get_index(pid, OUT), dtype=np.int64))
    Global.enable_vectors = True
    prev_dim = Global.vector_dim
    Global.vector_dim = 64
    upsert_batch_into([g], profs, make_vectors(profs, 64))
    graph_texts = [f"SELECT ?s WHERE {{ ?s <{UB}advisor> "
                   f"{ss.id2str(int(a))} . }}" for a in profs[:256]]
    hybrid_template = ("SELECT ?p ?d WHERE { knn(?p, {anchor}, 8) . "
                      f"?p <{UB}worksFor> ?d }}")
    anchors = [ss.id2str(int(a)) for a in profs[:64]]
    dur = float(os.environ.get("WUKONG_GRAPHRAG_DURATION", "5"))
    clients = int(os.environ.get("WUKONG_GRAPHRAG_CLIENTS", "8"))
    emu = Emulator(proxy)
    for t in graph_texts[:4]:
        proxy.serve_query(t, blind=True)
    proxy.serve_query(hybrid_template.replace("{anchor}", anchors[0]),
                      blind=True)
    mix = emu.run_graphrag(graph_texts, hybrid_template, anchors,
                           duration_s=dur, warmup_s=1.0, clients=clients,
                           seed=1)

    # ---- vectors-off zero-touch on the 2-hop serving micro ----
    two_hop = (f"SELECT ?x ?y WHERE {{ ?x <{UB}advisor> "
               f"{ss.id2str(int(profs[0]))} . "
               f"?x <{UB}memberOf> ?y . }}")
    for _ in range(30):
        proxy.serve_query(two_hop, blind=True)
    lat = {"off": [], "on": []}
    for _round in range(30):
        for mode in ("off", "on"):
            Global.enable_vectors = mode == "on"
            for _ in range(10):
                t0 = get_usec()
                proxy.serve_query(two_hop, blind=True)
                lat[mode].append(get_usec() - t0)
    Global.enable_vectors = False
    Global.vector_dim = prev_dim

    def band(xs: list) -> dict:
        xs = sorted(xs)
        return {"p25_us": int(xs[len(xs) // 4]),
                "p50_us": int(xs[len(xs) // 2]),
                "p75_us": int(xs[(3 * len(xs)) // 4])}

    b_off, b_on = band(lat["off"]), band(lat["on"])
    bands_overlap = (b_off["p25_us"] <= b_on["p75_us"]
                     and b_on["p25_us"] <= b_off["p75_us"])

    _emit_final({
        "metric": f"LUBM-{scale} GraphRAG hybrid serving throughput, "
                  f"{clients} clients x {dur:.0f}s Zipfian graph+knn mix; "
                  f"pure-scan {N//1000}k x {D}d device-vs-host "
                  "detail + vectors-off zero-touch band",
        "value": mix["hybrid"]["qps"],
        "unit": "q/s",
        "hybrid_qps": mix["hybrid"]["qps"],
        "graph_qps": mix["graph"]["qps"],
        "scan_device_vs_host": ratio,
        "scan_device_gflops": scan["device_gflops"],
        "demotion_clean": demotion_clean,
        "backend": "tpu" if device_ok else "cpu",
        "detail": {
            "mix": mix,
            "pure_scan": scan,
            "demotion_drill": {
                "engaged": demoted is not None,
                "reason": demoted,
                "host_identical": demotion_clean,
            },
            "vectors_off_overhead": {
                "query": "2-hop chain micro, single-threaded, interleaved",
                "samples_per_mode": len(lat["off"]),
                "off": b_off, "on": b_on,
                "bands_overlap": bands_overlap,
            },
            "knobs": {"vector_dim": 64, "knn_metric": METRIC,
                      "knn_device": Global.knn_device,
                      "knn_split_threshold": Global.knn_split_threshold,
                      "clients": clients, "scale": scale},
            "dataset": DATASET_NOTES["lubm"],
        },
    }, "BENCH_GRAPHRAG.json")
    if os.environ.get("WUKONG_GRAPHRAG_NOGATE") == "1":
        return
    if not ((ratio is not None and ratio >= 3.0) or demotion_clean):
        raise SystemExit(
            f"graphrag drill FAILED: device route only {ratio}x host on "
            f"the {N}x{D} scan AND the measured-demotion drill did not "
            "engage cleanly — one of the two must hold")
    if not bands_overlap:
        raise SystemExit(
            f"graphrag drill FAILED: enable_vectors off/on latency bands "
            f"disjoint on the knn-free 2-hop micro (off={b_off}, "
            f"on={b_on}) — the off knob must be zero-touch")


def serve_mixed_main(device_ok: bool) -> None:
    """`bench.py --serve-mixed`: closed-loop MIXED light+heavy serving
    throughput (weighted LUBM light template + index-origin heavy
    queries). Baseline = the PR 4 posture (light batching on, heavy lane
    OFF: index-origin queries run one-at-a-time); after = the heavy lane
    fusing index-origin traffic into sliced device dispatches. Artifact:
    BENCH_SERVE_MIXED.json (picked up by scripts/bench_report.py)."""
    import numpy as np

    from wukong_tpu.config import Global
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.loader.lubm import UB
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.runtime.emulator import Emulator
    from wukong_tpu.runtime.proxy import Proxy
    from wukong_tpu.types import OUT

    scale = int(os.environ.get("WUKONG_BENCH_SCALE", "0")) or 1
    g, ss, stats = _ensure_world(scale)
    proxy = Proxy(g, ss, cpu_engine=CPUEngine(g, ss),
                  tpu_engine=TPUEngine(g, ss, stats=stats),
                  planner=Planner(stats))
    if os.environ.get("WUKONG_SERVE_HOST") == "1":
        Global.enable_tpu = False
    # the mix: the --serve-batched light template (const-start 1-hop)
    # plus index-origin 3-hop heavies at WUKONG_MIX_HEAVY_SHARE of
    # arrivals (default 30%) — the "mixed production traffic" shape
    # ROADMAP item 1 names, where unfused heavy queries collapse
    # throughput back toward the unbatched ceiling
    pid = ss.str2id(f"<{UB}advisor>")
    anchors = np.asarray(g.get_index(pid, OUT))
    texts = [f"SELECT ?s WHERE {{ ?s <{UB}advisor> "
             f"{ss.id2str(int(a))} . }}" for a in anchors[:512]]
    heavy_texts = [
        ("SELECT ?x ?y ?z WHERE { ?x "
         "<http://www.w3.org/1999/02/22-rdf-syntax-ns#type> "
         f"<{UB}UndergraduateStudent> . ?x <{UB}takesCourse> ?y . "
         f"?x <{UB}memberOf> ?z . }}"),
        ("SELECT ?x ?y ?z WHERE { ?x "
         "<http://www.w3.org/1999/02/22-rdf-syntax-ns#type> "
         f"<{UB}UndergraduateStudent> . ?x <{UB}takesCourse> ?y . "
         f"?x <{UB}advisor> ?z . }}"),
    ]
    heavy_share = float(os.environ.get("WUKONG_MIX_HEAVY_SHARE", "0.3"))
    all_texts = texts + heavy_texts
    classes = [0] * len(texts) + [1] * len(heavy_texts)
    weights = ([(1.0 - heavy_share) / len(texts)] * len(texts)
               + [heavy_share / len(heavy_texts)] * len(heavy_texts))
    dur = float(os.environ.get("WUKONG_SERVE_DURATION", "10"))
    # more clients than --serve-batched: the heavy lane's win IS the
    # collapsing of concurrent heavy waiters, which needs concurrency
    clients = int(os.environ.get("WUKONG_SERVE_CLIENTS", "24"))
    emu = Emulator(proxy)
    # the heavy lane NEEDS the pool: without one, fused heavy dispatches
    # run inline on the batcher's flusher thread and serialize the light
    # groups behind them — the exact starvation the scheduler's weighted
    # heavy lane exists to prevent
    proxy.engine_pool()
    for t in texts[:8] + heavy_texts:  # warm caches + jit shapes
        proxy.serve_query(t, blind=True)
    # precompile the fused heavy dispatch shapes (single + split) before
    # the measurement window — steady state, the PR 4 measurement posture
    import copy as _copy

    for ht in heavy_texts:
        hq = proxy._parse_text(ht)
        proxy._plan_prepared(hq, True, None)
        b = proxy.heavy_index_batch(hq)
        proxy.tpu.execute_batch_index(hq, b, slice_mode=True)
        S = min(int(Global.heavy_split_max), Global.num_engines)
        if S > 1:
            for k in range(S):
                hk = _copy.deepcopy(hq)
                hk.mt_factor, hk.mt_tid = S, k
                proxy.tpu.execute_batch_index(hk, b, slice_mode=True)

    def run() -> dict:
        return emu.run_serving(all_texts, duration_s=dur, warmup_s=1.0,
                               clients=clients, seed=1, weights=weights,
                               classes=classes)

    # baseline: light batching on, heavy one-at-a-time (the pre-heavy-lane
    # serving path on the same mix)
    Global.enable_batching = True
    Global.heavy_lane = False
    base = run()
    # after: the heavy lane fuses index-origin traffic
    Global.heavy_lane = True
    on = run()
    Global.enable_batching = False
    speedup = round(on["qps"] / base["qps"], 2) if base["qps"] else None
    from wukong_tpu.obs import get_registry

    snap = get_registry().snapshot()
    heavy_metrics = {
        name: [{**s["labels"], "value": s["value"]}
               for s in snap.get(name, {}).get("series", [])]
        for name in ("wukong_batch_heavy_dispatch_total",
                     "wukong_batch_heavy_fused_total",
                     "wukong_batch_heavy_slices_total",
                     "wukong_batch_heavy_fallback_total",
                     "wukong_batch_heavy_split_total",
                     "wukong_lane_routed_total")}
    # heavy_split_threshold tuning surface: how often fused dispatches
    # split vs ran whole under the current threshold (each split part
    # pays the per-dispatch fixed cost — see the README knob row)
    split_counts = {s["labels"].get("decision", "?"): s["value"]
                    for s in snap.get("wukong_batch_heavy_split_total",
                                      {}).get("series", [])}
    print(f"# heavy split decisions @threshold="
          f"{Global.heavy_split_threshold}: "
          f"split={split_counts.get('split', 0)} "
          f"no_split={split_counts.get('no_split', 0)}", file=sys.stderr)
    from wukong_tpu.obs.metrics import snapshot_histogram_mean

    occ = snapshot_histogram_mean(snap, "wukong_batch_heavy_occupancy")
    mean_occ = round(occ, 2) if occ is not None else None
    _emit_final({
        "metric": f"LUBM-{scale} MIXED light+heavy serving throughput, "
                  f"{clients} clients x {dur:.0f}s closed loop "
                  f"({heavy_share:.0%} index-origin heavy; heavy lane "
                  "vs unbatched-heavy baseline)",
        "value": on["qps"],
        "unit": "q/s",
        "mixed_qps": on["qps"],
        "unbatched_heavy_qps": base["qps"],
        "speedup": speedup,
        "backend": "tpu" if device_ok else "cpu",
        "detail": {
            "baseline": base, "heavy_lane": on,
            "knobs": {"batch_window_us": Global.batch_window_us,
                      "batch_max_size": Global.batch_max_size,
                      "heavy_batch_max": Global.heavy_batch_max,
                      "heavy_split_threshold": Global.heavy_split_threshold,
                      "heavy_lane_pct": Global.heavy_lane_pct,
                      "heavy_share": heavy_share,
                      "clients": clients, "scale": scale},
            "mean_heavy_occupancy": mean_occ,
            "heavy_metrics": heavy_metrics,
            "dataset": DATASET_NOTES["lubm"],
        },
    }, "BENCH_SERVE_MIXED.json")


def tenants_main(device_ok: bool) -> None:
    """`bench.py --tenants`: the multi-tenant SLO scenario
    (Emulator.run_tenants — ROADMAP item 4's acceptance fixture) on the
    LUBM-1 serving world: three conflicting tenant classes drive
    closed-loop clients through proxy.serve_query with tenant identity;
    per-tenant compliance / error budget / burn rates land in the SLO
    tracker and the artifact. A chaos sub-run injects transient failures
    at the proxy.serve boundary and records which tenants' budgets trip
    the burn sentinel. A third sub-run is the admission control plane's
    2x-capacity overload drill (clients doubled, quotas armed): it
    self-gates that the protected tenant stays compliant and un-degraded
    while bulk is shed lowest-weight-first. Artifact: BENCH_TENANT.json
    (tenant_qps headline + protected_qps secondary, trended by
    scripts/bench_report.py; the `overload` detail carries per-tenant
    partial/rejected counts, decisions, and shed-by-cause)."""
    import numpy as np

    from wukong_tpu.config import Global
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.loader.lubm import UB
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.runtime.emulator import Emulator
    from wukong_tpu.runtime.proxy import Proxy
    from wukong_tpu.types import OUT

    scale = int(os.environ.get("WUKONG_BENCH_SCALE", "0")) or 1
    g, ss, stats = _ensure_world(scale)
    proxy = Proxy(g, ss, cpu_engine=CPUEngine(g, ss),
                  tpu_engine=TPUEngine(g, ss, stats=stats),
                  planner=Planner(stats))
    pid = ss.str2id(f"<{UB}advisor>")
    anchors = np.asarray(g.get_index(pid, OUT))
    texts = [f"SELECT ?s WHERE {{ ?s <{UB}advisor> "
             f"{ss.id2str(int(a))} . }}" for a in anchors[:512]]
    dur = float(os.environ.get("WUKONG_TENANT_DURATION", "8"))
    emu = Emulator(proxy)
    for t in texts[:8]:  # warm parse/plan caches + engine jit shapes
        proxy.serve_query(t, blind=True)

    normal = emu.run_tenants(texts, duration_s=dur, warmup_s=1.0, seed=1)
    chaos = emu.run_tenants(texts, duration_s=min(dur, 4.0), warmup_s=0.5,
                            chaos=True, seed=1)

    # the admission plane's 2x-capacity overload variant: same three
    # classes, every client count doubled, quotas armed — gold:8 /
    # silver:4 / bulk:1 with a bulk q/s + in-flight quota and a small
    # global in-flight ceiling so the degrade ladder engages. The drill
    # self-gates below: the protected (top-weight) tenant must stay
    # SLO-compliant and un-degraded while bulk absorbs the shed.
    from wukong_tpu.runtime.admission import get_admission

    prev_adm = (Global.enable_admission, Global.admission_quotas,
                Global.admission_max_inflight)
    Global.enable_admission = True
    Global.admission_quotas = "gold:8:0:0:0;silver:4:0:0:0;bulk:1:25:4:0"
    Global.admission_max_inflight = 6
    get_admission().reset()
    try:
        over = emu.run_tenants(texts, duration_s=dur, warmup_s=1.0,
                               overload_x=2.0, seed=1)
    finally:
        (Global.enable_admission, Global.admission_quotas,
         Global.admission_max_inflight) = prev_adm
        get_admission().reset()

    decisions = over.get("admission", {}).get("decisions", {})
    gold_slo = over["tenants"]["gold"]["slo"] or {}
    gold_compliant = bool(
        gold_slo.get("latency_met")
        and (gold_slo.get("error_budget_remaining") or 0.0) >= 0.0)
    # shed evidence comes from the decision counts (a rung-2 partial that
    # happened to finish under its tightened budget still counts as shed)
    bulk_shed = sum(n for k, n in decisions.items()
                    if k.endswith("/bulk") and not k.startswith("admit/"))
    gold_degraded = sum(n for k, n in decisions.items()
                        if k.endswith("/gold") and not k.startswith("admit/"))
    protected_qps = over["tenants"]["gold"]["qps"]

    def slim(out: dict) -> dict:
        # the committed detail keeps the per-tenant story and drops the
        # full signal/registry dumps (scrape surfaces carry those live)
        return {k: out[k] for k in ("duration_s", "chaos", "chaos_p",
                                    "qps", "tenants", "alerts",
                                    "burn_dumps")}

    _emit_final({
        "metric": f"LUBM-{scale} multi-tenant SLO scenario: 3 conflicting "
                  "tenant classes (gold/silver/bulk), closed-loop serving "
                  "with per-tenant SLO accounting + chaos burn variant",
        "value": normal["qps"],
        "unit": "q/s",
        "tenant_qps": normal["qps"],
        "chaos_alerts": chaos["alerts"],
        "chaos_burn_dumps": len(chaos["burn_dumps"]),
        "protected_qps": protected_qps,
        "backend": "tpu" if device_ok else "cpu",
        "detail": {
            "normal": slim(normal),
            "chaos": slim(chaos),
            "overload": {
                **slim(over),
                "overload_x": over["overload_x"],
                "protected_qps": protected_qps,
                "gold_compliant": gold_compliant,
                "gold_degraded_decisions": gold_degraded,
                "bulk_shed_decisions": bulk_shed,
                "decisions": decisions,
                "shed_by_cause":
                    over["signals"].get("shed_by_cause", {}),
                "admission_quotas": "gold:8:0:0:0;silver:4:0:0:0;"
                                    "bulk:1:25:4:0",
            },
            "slo_report": normal["slo_report"],
            "knobs": {"max_tenants": Global.max_tenants,
                      "slo_burn_fast_x": Global.slo_burn_fast_x,
                      "slo_burn_slow_x": Global.slo_burn_slow_x,
                      "slo_dump_cooldown_s": Global.slo_dump_cooldown_s},
            "dataset": DATASET_NOTES["lubm"],
        },
    }, "BENCH_TENANT.json")
    # the overload drill self-gates (ci_check runs it): the plane must
    # shed bulk, never degrade the protected class, and keep it
    # compliant under 2x load. WUKONG_TENANT_NOGATE=1 skips the gates
    # for reduced-scale local runs
    if os.environ.get("WUKONG_TENANT_NOGATE") != "1":
        if bulk_shed <= 0:
            raise SystemExit(
                "tenant overload drill FAILED: no bulk shed decisions at "
                "2x capacity — the admission plane never engaged")
        if gold_degraded > 0:
            raise SystemExit(
                f"tenant overload drill FAILED: {gold_degraded} degrade "
                "decisions hit the protected tenant (top weight class "
                "must never be ladder-degraded)")
        if not gold_compliant:
            raise SystemExit(
                f"tenant overload drill FAILED: protected tenant out of "
                f"SLO under 2x overload while bulk was sheddable "
                f"(slo={gold_slo})")


def hotspot_main(device_ok: bool) -> None:
    """`bench.py --hotspot`: the Zipfian hot-spot observatory drill
    (Emulator.run_hotspot — ROADMAP item 3's acceptance fixture, now end
    to end): drive skewed fetches through a 4-shard store's resilience
    path, then run the observe-only PlacementAdvisor over the tsdb trend
    window it produced. Headline: the load-rate separation between the
    seeded hot shard and the hottest cold shard (unit-less — reported in
    BENCH_TRAJECTORY, never gated). The artifact also records the
    MigrationPlan (donor must be the seeded hot shard), the predicted
    move bytes vs the donor's measured checkpoint size, and the
    observe-only proof (store versions untouched)."""
    import tempfile

    import numpy as np

    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
    from wukong_tpu.parallel.sharded_store import ShardedDeviceStore
    from wukong_tpu.runtime.emulator import Emulator
    from wukong_tpu.runtime.proxy import Proxy
    from wukong_tpu.runtime.recovery import RecoveryManager
    from wukong_tpu.store.gstore import build_partition

    n_shards = 4
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    stores = [build_partition(triples, i, n_shards)
              for i in range(n_shards)]

    class _Mesh:
        devices = np.empty(n_shards, dtype=object)

    sstore = ShardedDeviceStore(stores, _Mesh(), replication_factor=1)
    proxy = Proxy(g, ss, cpu_engine=CPUEngine(g, ss))
    # a checkpoint first, so the advisor's predicted-move bytes come from
    # MEASURED part sizes (the acceptance's ±25% contract), not estimates
    with tempfile.TemporaryDirectory() as ckpt_dir:
        from wukong_tpu.store.persist import checkpoint_part_path

        rm = RecoveryManager(lambda: list(sstore.stores), sstore=sstore,
                             ckpt_dir=ckpt_dir)
        ckpt = rm.checkpoint()
        part_bytes = {i: os.path.getsize(checkpoint_part_path(ckpt, i))
                      for i in range(n_shards)}
        emu = Emulator(proxy)
        rep = emu.run_hotspot(n_ops=1500, zipf_a=1.6, seed=7,
                              sstore=sstore)
    plan = rep["plan"] or {}
    donor = plan.get("donor_shard")
    actual = part_bytes.get(donor)
    # predicted_vs_checkpoint is 1.0 whenever a checkpoint preceded the
    # plan (the prediction IS the measured part size then — exact by
    # construction). The ±25% band's real teeth are on the ESTIMATE
    # path: the live-store fallback (memory_bytes) must stay calibrated
    # against what a checkpoint would actually measure, or advisors on
    # never-checkpointed clusters predict garbage.
    ratio = (round(plan["predicted_move_bytes"] / actual, 3)
             if actual else None)
    est_ratio = (round(stores[donor].memory_bytes() / actual, 3)
                 if actual and donor is not None else None)
    _emit_final({
        "metric": "LUBM-1 Zipfian hot-spot drill: heat-plane load-rate "
                  "separation (hot shard p50 access rate / hottest cold "
                  "shard's) + the observe-only MigrationPlan",
        "value": round(rep["separation"], 2),
        "unit": "x",
        "hotspot_separation": round(rep["separation"], 2),
        "plan_donor_is_hot": rep["plan_donor_is_hot"],
        "store_untouched": rep["store_untouched"],
        "backend": "cpu",  # host-side fetch path; no device work
        "detail": {
            "hot": rep["hot"],
            "ranked": rep["ranked"],
            "plan": plan or None,
            "predicted_vs_checkpoint_bytes": ratio,
            "estimate_vs_checkpoint_bytes": est_ratio,
            "donor_checkpoint_bytes": actual,
            "zipf_a": 1.6,
            "n_ops": 1500,
            "shards": n_shards,
        },
    }, "BENCH_HOTSPOT.json")


def rebalance_main(device_ok: bool) -> None:
    """`bench.py --rebalance`: the hot-spot drill flipped from
    observe-only to EXECUTED (Emulator.run_rebalance — the elastic data
    plane's acceptance drill). The Zipfian scenario produces the
    advisor's MigrationPlan, the live shard-migration actuator
    (runtime/migration.py) drives it through clone/catch-up/cutover/
    retire with a byte-identical probe after every phase, then the SAME
    skew replays against the post-move placement. Headline:
    `rebalance_gain` — pre-move over post-move host load-rate imbalance
    (>1 means the move paid for itself; the drill FAILS unless the
    post-move imbalance lands under `placement_imbalance_x` and every
    probe matched the pre-migration oracle). Artifact:
    BENCH_REBALANCE.json with moved bytes + measured cutover pause."""
    import numpy as np

    from wukong_tpu.config import Global
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
    from wukong_tpu.parallel.sharded_store import ShardedDeviceStore
    from wukong_tpu.runtime.emulator import Emulator
    from wukong_tpu.runtime.proxy import Proxy
    from wukong_tpu.store.gstore import build_partition

    n_shards = 4
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    stores = [build_partition(triples, i, n_shards)
              for i in range(n_shards)]

    class _Mesh:
        devices = np.empty(n_shards, dtype=object)

    sstore = ShardedDeviceStore(stores, _Mesh(), replication_factor=1)
    proxy = Proxy(g, ss, cpu_engine=CPUEngine(g, ss))
    prev = Global.migration_enable
    Global.migration_enable = True  # the drill IS the armed posture
    try:
        emu = Emulator(proxy)
        rep = emu.run_rebalance(n_ops=1500, zipf_a=1.6, seed=7,
                                sstore=sstore)
    finally:
        Global.migration_enable = prev
    if not (rep["rebalanced"] and rep["queries_identical"]):
        raise SystemExit(
            f"rebalance drill FAILED: rebalanced={rep['rebalanced']} "
            f"queries_identical={rep['queries_identical']} "
            f"probes={rep['probes']}")
    job = rep["job"]
    _emit_final({
        "metric": "LUBM-1 Zipfian rebalance drill: pre/post host "
                  "load-rate imbalance ratio across one executed shard "
                  "migration (clone/catch-up/cutover/retire, probes "
                  "byte-identical throughout)",
        "value": round(rep["rebalance_gain"], 2),
        "unit": "x",
        "rebalance_gain": round(rep["rebalance_gain"], 2),
        "rebalanced": rep["rebalanced"],
        "queries_identical": rep["queries_identical"],
        "backend": "cpu",  # host-side fetch path; no device work
        "detail": {
            "hot": rep["hot"],
            "plan": rep["plan"],
            "job": job,
            "probes": rep["probes"],
            "imbalance_before": rep["imbalance_before"],
            "imbalance_after": rep["imbalance_after"],
            "decision_after": rep["decision_after"],
            "moved_bytes": job["bytes_moved"],
            "cutover_pause_us": job["cutover_pause_us"],
            "wal_records_caught_up": job["replayed"],
            "donor_rotated": job["rotated"],
            "threshold": max(float(Global.placement_imbalance_x), 1.0),
            "zipf_a": 1.6,
            "n_ops": 1500,
            "shards": n_shards,
        },
    }, "BENCH_REBALANCE.json")


def readmostly_main(device_ok: bool) -> None:
    """`bench.py --readmostly`: the Zipfian read-mostly serving-cache
    drill (Emulator.run_readmostly — ROADMAP item 7's acceptance fixture,
    observe-only). Closed-loop template+const reads drawn Zipf over ~400
    instances of four LUBM light-template families (up to 128 constants
    each — the exact count rides the artifact's knobs.templates; some
    predicates have fewer anchors) through proxy.serve_query, once
    per write-rate phase (0 / 2% / 8% dynamic-insert batches per read).
    Headline: `predicted_hit_rate` — the zero-write phase's shadow-cache
    hit rate, i.e. what a version-keyed result cache (plan signature +
    consts + store version) would have served without executing. The
    drill FAILS unless the skewed mix predicts >= 0.5, hit rate degrades
    monotonically as the write rate rises, and the store content digest
    is bit-identical across the read-only phase (the observatory touched
    nothing). Artifact: BENCH_READMOSTLY.json (ratio unit — trended by
    scripts/bench_report.py, never direction-gated)."""
    import numpy as np

    from wukong_tpu.config import Global
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.loader.lubm import UB, VirtualLubmStrings, generate_lubm
    from wukong_tpu.planner.optimizer import make_planner
    from wukong_tpu.runtime.emulator import Emulator
    from wukong_tpu.runtime.proxy import Proxy
    from wukong_tpu.store.gstore import build_partition
    from wukong_tpu.types import OUT

    # a private world (not _ensure_world's cache): the write phases
    # append duplicate edges, and a mutated store must never leak into
    # the other benches' cached partitions
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    proxy = Proxy(g, ss, cpu_engine=CPUEngine(g, ss),
                  planner=make_planner(triples))
    # several template FAMILIES (distinct plan-cache signatures), each
    # instantiated over many constants: the Zipf draw over the flat list
    # piles mass on the first family's hot constants, so the ledger sees
    # a skewed TEMPLATE ranking (zipf_alpha) on top of the skewed
    # per-key ranking the shadow cache sees
    texts = []
    for pred in ("advisor", "takesCourse", "memberOf", "teacherOf"):
        pid = ss.str2id(f"<{UB}{pred}>")
        anchors = np.asarray(g.get_index(pid, OUT))
        texts += [f"SELECT ?s WHERE {{ ?s <{UB}{pred}> "
                  f"{ss.id2str(int(a))} . }}" for a in anchors[:128]]
    for t in texts[::128]:  # warm parse/plan caches before the drill
        proxy.serve_query(t, blind=True)
    rng = np.random.default_rng(7)
    write_pool = triples[rng.integers(0, len(triples), 4096)]
    emu = Emulator(proxy)
    zipf_a = float(os.environ.get("WUKONG_READMOSTLY_ZIPF", "1.2"))
    rep = emu.run_readmostly(texts, reads=600, warmup_reads=300,
                             write_rates=(0.0, 0.02, 0.08),
                             zipf_a=zipf_a, seed=7,
                             write_batch=write_pool,
                             tenants=["gold", "bulk"])
    ok = (rep["predicted_hit_rate"] is not None
          and rep["predicted_hit_rate"] >= 0.5
          and rep["degrades"] and rep["store_untouched"])
    if not ok:
        raise SystemExit(
            f"readmostly drill FAILED: predicted_hit_rate="
            f"{rep['predicted_hit_rate']} degrades={rep['degrades']} "
            f"store_untouched={rep['store_untouched']}")
    # phase 2: the ACTUATOR (wukong_tpu/serve/), both rungs armed — the
    # same Zipfian loop with the real result cache + materialized views.
    # Self-gating: every measured reply byte-identical to an uncached
    # oracle execution, the real zero-write hit rate at least the
    # shadow-predicted one, the q/s headline >= 3x PR 8's 1,764
    # light-only serving baseline, and (rung ii's whole point) the
    # 8%-write-rate hit rate within 15 points of the zero-write rate —
    # vs the shadow's 86 -> 28 collapse.
    Global.view_promote_edges = 1  # drill cadence: promote on the first
    Global.views_max = 256         # surviving refill; plenty of views
    crep = emu.run_readmostly(texts, reads=600, warmup_reads=300,
                              write_rates=(0.0, 0.02, 0.08),
                              zipf_a=zipf_a, seed=7,
                              write_batch=write_pool,
                              tenants=["gold", "bulk"],
                              cached=True, views=True)
    real = crep["real"]
    baseline_qps = 1764.0  # PR 8's light-only serving headline
    cok = (real["identical"] and real["beats_shadow"]
           and real["readmostly_qps"] is not None
           and real["readmostly_qps"] >= 3 * baseline_qps
           and real["hit_rate_drop_pts"] is not None
           and real["hit_rate_drop_pts"] <= 15.0)
    if not cok:
        raise SystemExit(
            f"readmostly CACHED drill FAILED: identical="
            f"{real['identical']} (mismatches {real['mismatches']}), "
            f"real={real['hit_rate']} vs shadow="
            f"{real['shadow_predicted']}, qps={real['readmostly_qps']} "
            f"(need >= {3 * baseline_qps:.0f}), "
            f"drop={real['hit_rate_drop_pts']}pts (need <= 15)")
    _emit_final({
        "metric": "LUBM-1 Zipfian read-mostly drill: cached-serving q/s "
                  "with the materialized-view plane armed (rungs i+ii; "
                  "byte-identical to uncached execution, real hit rate "
                  ">= shadow-predicted, flat hit-rate curve under "
                  "writes), plus the observe-only shadow phases",
        "readmostly_qps": real["readmostly_qps"],
        "value": real["readmostly_qps"],
        "unit": "q/s",
        "predicted_hit_rate": rep["predicted_hit_rate"],
        "hit_rate": real["hit_rate"],
        "identical": real["identical"],
        "speedup_vs_uncached": real["speedup_vs_uncached"],
        "speedup_vs_pr8_headline": round(
            real["readmostly_qps"] / baseline_qps, 2),
        "hit_rate_drop_pts": real["hit_rate_drop_pts"],
        "degrades": rep["degrades"],
        "store_untouched": rep["store_untouched"],
        "zipf_alpha_est": rep["zipf_alpha"],
        "backend": "cpu",  # host serving path; no device work
        "detail": {
            "phases": rep["phases"],
            "cached": {
                "phases": crep["phases"],
                "real": {k: v for k, v in real.items()
                         if k not in ("cache", "views")},
                "cache": real["cache"],
                "views": {k: v for k, v in real["views"].items()
                          if k != "views"},
                "top_views": real["views"]["views"][:4],
            },
            "bytes_saved": rep["bytes_saved"],
            "uncacheable_by_reason": rep["uncacheable_by_reason"],
            "trend": rep["trend"],
            "knobs": {"shadow_cache_size": Global.shadow_cache_size,
                      "reuse_sample_every": Global.reuse_sample_every,
                      "reuse_templates_max": Global.reuse_templates_max,
                      "result_cache_mb": Global.result_cache_mb,
                      "result_cache_min_reads":
                          Global.result_cache_min_reads,
                      "view_promote_edges": Global.view_promote_edges,
                      "views_max": Global.views_max,
                      "zipf_a": zipf_a, "templates": len(texts)},
            "top_templates": rep["report"]["popularity"]["ranked"][:4],
            "dataset": DATASET_NOTES["lubm"],
        },
    }, "BENCH_READMOSTLY.json")


def cyclic_main(device_ok: bool) -> None:
    """`bench.py --cyclic`: the cyclic workload suite (triangle / diamond /
    4-clique synthetic worlds + the WatDiv-based cyclic query set), each
    executed with the walk forced and the WCOJ tensor join forced on the
    SAME planned query, rows verified identical. Headline: the triangle
    speedup (the walk materializes the quadratic wedge set; acceptance
    >= 5x). Artifact: BENCH_CYCLIC.json (scripts/bench_report.py trends
    the headline, higher-is-better)."""
    import numpy as np

    from wukong_tpu.config import Global
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.join.wcoj import WCOJExecutor
    from wukong_tpu.loader.datagen import (
        generate_clique4,
        generate_diamond,
        generate_triangle,
        watdiv_cyclic_patterns,
    )
    from wukong_tpu.loader.watdiv import generate_watdiv
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.planner.stats import Stats
    from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
    from wukong_tpu.store.gstore import build_partition
    from wukong_tpu.types import OUT

    m_tri = int(os.environ.get("WUKONG_CYCLIC_M", "2000"))
    reps = int(os.environ.get("WUKONG_CYCLIC_REPS", "3"))

    def mkq(spec):
        q = SPARQLQuery()
        q.pattern_group.patterns = [Pattern(s, p, OUT, o)
                                    for (s, p, o) in spec["patterns"]]
        q.result.nvars = len(spec["vars"])
        q.result.required_vars = list(spec["vars"])
        q.result.blind = True
        return q

    worlds = [
        ("triangle", *generate_triangle(m=m_tri, noise=8, seed=0)),
        ("diamond", *generate_diamond(m=400, noise=4, seed=0)),
        ("clique4", *generate_clique4(n=1200, fan=10, ncliques=40, seed=0)),
    ]
    detail = {}
    for name, triples, meta in worlds:
        g = build_partition(triples, 0, 1)
        stats = Stats.generate(triples)
        planner = Planner(stats)
        detail[name] = _cyclic_case(name, g, stats, planner, meta, mkq,
                                    CPUEngine, WCOJExecutor, reps)
    # WatDiv-based cyclic set (social triangles/pentagon over the shaped
    # e-commerce world)
    wscale = int(os.environ.get("WUKONG_CYCLIC_WATDIV_SCALE", "60"))
    wtriples, _lay = generate_watdiv(wscale, seed=0)
    wg = build_partition(wtriples, 0, 1)
    wstats = Stats.generate(wtriples)
    wplanner = Planner(wstats)
    for name, spec in watdiv_cyclic_patterns().items():
        detail[name] = _cyclic_case(name, wg, wstats, wplanner, spec, mkq,
                                    CPUEngine, WCOJExecutor, reps)
    tri = detail["triangle"]
    rows_identical = all(d["rows_identical"] for d in detail.values())
    device_speedups = {n: d["device_speedup"] for n, d in detail.items()}
    # default=None: a reduced-scale run can round every device_ms to 0.0
    # (speedup None) — the artifact must still emit and the NOGATE escape
    # hatch must still work instead of crashing on an empty max()
    device_speedup_max = max(
        (v for v in device_speedups.values() if v is not None),
        default=None)
    pentagon_auto = detail["w_pentagon"]["auto_vs_walk"]
    # the compiled-template rung: device-vs-host round trips per query
    # (per-step device syncs over the whole-plan program's single sync),
    # gated on the LARGE cyclic shapes — the synthetic worlds whose
    # chains are long enough that the per-step tax is structural
    large = [n for n, _t, _m in worlds]
    compiled_reduction = {n: d["compiled_roundtrip_reduction"]
                          for n, d in detail.items()}
    compiled_device_vs_host = min(
        (compiled_reduction[n] for n in large if compiled_reduction.get(n)),
        default=None)
    compiled_identical = all(
        d["compiled_rows_identical"] in (True, None)
        for d in detail.values())
    _emit_final({
        "metric": f"cyclic suite: WCOJ vs walk (triangle m={m_tri} "
                  f"headline; diamond/clique4 + WatDiv-{wscale} cyclic "
                  "set + the XLA device route in detail)",
        "value": tri["speedup"],
        "unit": "speedup",
        "triangle_speedup": tri["speedup"],
        "triangle_walk_ms": tri["walk_ms"],
        "triangle_wcoj_ms": tri["wcoj_ms"],
        "rows_identical": rows_identical,
        "auto_strategies": {n: d["auto_strategy"] for n, d in detail.items()},
        # settled-auto wall over the forced walk, per case (>= ~1.0 means
        # the measured feedback loops keep auto from losing to the walk;
        # the w_pentagon >= 1.0 gate below is the PR 10 exception, closed
        # by the device route)
        "auto_vs_walk": {n: d["auto_vs_walk"] for n, d in detail.items()},
        "auto_vs_walk_min": min(d["auto_vs_walk"] for d in detail.values()),
        # device-vs-host WCOJ per case, plus the w_pentagon headline the
        # trajectory trends (bench_report.py secondary series): pentagon
        # is the shape whose loss WAS closing-level intersection cost
        "device_speedup": device_speedups,
        "device_speedup_max": device_speedup_max,
        "pentagon_device_speedup": detail["w_pentagon"]["device_speedup"],
        # COMPILED TEMPLATE rung: device<->host round trips per query,
        # per-step route over whole-plan fused program (the program pays
        # exactly ONE dispatch+sync; the step engine pays one per chain
        # segment). Deterministic — gated >= 5x on the large shapes.
        # compiled_vs_walk is the wall-clock trend (backend-dependent).
        "compiled_roundtrip_reduction": compiled_reduction,
        "compiled_device_vs_host": compiled_device_vs_host,
        "compiled_vs_walk": {n: d["compiled_vs_walk"]
                             for n, d in detail.items()},
        "compiled_rows_identical": compiled_identical,
        "backend": "cpu",  # host walk/wcoj; the device route is the same
        # XLA kernels the TPU path jits (CPU backend in this container)
        "detail": {**detail,
                   "knobs": {"wcoj_ratio": Global.wcoj_ratio,
                             "wcoj_min_rows": Global.wcoj_min_rows,
                             "join_device": Global.join_device,
                             "join_device_min_candidates":
                                 Global.join_device_min_candidates,
                             "reps": reps}},
    }, "BENCH_CYCLIC.json")
    # the drill self-gates (ci_check runs it): byte-identity across all
    # three executors on every case, the w_pentagon auto-routing
    # exception closed (>= 1.0 vs the walk with the device route on),
    # and a real device win somewhere (>= 1.5x device-vs-host).
    # WUKONG_CYCLIC_NOGATE=1 skips the gates for reduced-scale local runs
    if os.environ.get("WUKONG_CYCLIC_NOGATE") != "1":
        if not rows_identical:
            raise SystemExit("cyclic drill FAILED: rows not identical "
                             "across walk/wcoj/device")
        if pentagon_auto is None or pentagon_auto < 1.0:
            raise SystemExit(
                f"cyclic drill FAILED: w_pentagon auto_vs_walk "
                f"{pentagon_auto} < 1.0 (the auto-routing exception "
                "must stay closed)")
        if device_speedup_max is None or device_speedup_max < 1.5:
            raise SystemExit(
                f"cyclic drill FAILED: best device-vs-host speedup "
                f"{device_speedup_max} < 1.5")
        if not compiled_identical:
            raise SystemExit("cyclic drill FAILED: compiled-template "
                             "rows differ from the host walk")
        if compiled_device_vs_host is None or compiled_device_vs_host < 5.0:
            raise SystemExit(
                f"cyclic drill FAILED: compiled-template device-vs-host "
                f"round-trip reduction {compiled_device_vs_host} < 5.0 "
                "on the large cyclic shapes (the whole-plan program must "
                "replace the per-step sync chain with ONE dispatch)")


def _cyclic_case(name, g, stats, planner, spec, mkq, CPUEngine,
                 WCOJExecutor, reps: int) -> dict:
    """One cyclic-suite case: plan once, run walk-forced, wcoj-forced
    (host route), and wcoj device-forced (XLA level path), compare rows
    and best-of-reps wall time. Additionally runs the AUTO route through
    a real proxy so the measured-blowup + measured-candidate feedback
    loops (Proxy._record_wcoj_feedback / _record_route_feedback) settle
    the strategy and route the way live serving would — the artifact
    records both the first (estimate-driven) and the settled
    (measurement-corrected) decision plus the settled auto wall time."""
    from wukong_tpu.config import Global
    from wukong_tpu.runtime.proxy import Proxy

    def planned():
        q = mkq(spec)
        planner.generate_plan(q)
        return q

    cpu = CPUEngine(g)
    wc = WCOJExecutor(g, stats=stats)
    wc.tables.clear()

    proxy = Proxy(g, None, cpu)
    proxy.planner = planner

    def auto_run():
        q = planned()
        q.join_strategy = proxy.classify_join_strategy(q)
        if q.join_strategy == "wcoj":
            q.join_route = proxy.classify_join_route(q)
        t0 = time.perf_counter()
        proxy._serve_execute(q, cpu)
        assert q.result.status_code == 0, (name, q.result.status_code)
        return (time.perf_counter() - t0) * 1e3, q.join_strategy

    def run(engine, blind=True):
        best, rows = None, None
        nonblind = None
        for _ in range(reps):
            q = planned()
            t0 = time.perf_counter()
            engine.execute(q)
            dt = (time.perf_counter() - t0) * 1e3
            best = dt if best is None else min(best, dt)
            rows = q.result.nrows
            assert q.result.status_code == 0, (name, q.result.status_code)
        # one non-blind run for row-level comparison
        q = planned()
        q.result.blind = False
        engine.execute(q)
        nonblind = {tuple(r) for r in q.result.table.tolist()}
        return best, rows, nonblind

    walk_ms, walk_rows, walk_set = run(cpu)
    wcoj_ms, wcoj_rows, wcoj_set = run(wc)
    # the DEVICE route forced on the same planned query (shared table
    # cache — the sorted tables are route-independent; the device twins
    # build once and stay resident across reps, the serving steady state)
    prev_dev = Global.join_device
    Global.join_device = "device"
    try:
        wcd = WCOJExecutor(g, stats=stats, tables=wc.tables)
        device_ms, device_rows, device_set = run(wcd)
    finally:
        Global.join_device = prev_dev
    # the auto route with measured feedback: the first run may route wcoj
    # on the over-predicted estimate, measure its prefix blowup, and
    # demote; best-of-reps is taken AFTER the decision settles
    first_ms, first_strategy = auto_run()
    auto_ms, settled = None, first_strategy
    for _ in range(reps):
        dt, settled = auto_run()
        auto_ms = dt if auto_ms is None else min(auto_ms, dt)
    # the COMPILED TEMPLATE rung: the whole plan as ONE fused XLA program
    # (one dispatch, one D2H sync) against the per-step device engine
    # that pays one round trip per chain segment. The gated quantity is
    # the device<->host round-trip reduction — dispatch records charged
    # on the device observatory per query — which is deterministic on
    # any backend; wall clocks ride along as trends (on the CPU backend
    # the round trips are nearly free and compute dominates, on a real
    # TPU each sync is the millisecond-class cost the fused program
    # deletes, which is the whole point of compiling the template).
    from wukong_tpu.engine.template_compile import TemplateCompiledEngine
    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.obs.device import get_device_obs

    obs = get_device_obs()
    prev_obs = Global.enable_device_obs
    Global.enable_device_obs = True
    compiled_ms = compiled_trips = stepdev_trips = None
    compiled_identical = None
    try:
        tce = TemplateCompiledEngine(g)
        q = planned()
        if tce.try_execute(q):  # stages + warms the program
            obs.reset()
            q = planned()
            assert tce.try_execute(q), name
            compiled_trips = int(
                obs.dispatch_ledger.dispatch_counts()["count"])
            for _ in range(reps):
                q = planned()
                t0 = time.perf_counter()
                served = tce.try_execute(q)
                dt = (time.perf_counter() - t0) * 1e3
                assert served and q.result.status_code == 0, name
                compiled_ms = (dt if compiled_ms is None
                               else min(compiled_ms, dt))
            # one non-blind run folded into the byte-identity posture
            q = planned()
            q.result.blind = False
            assert tce.try_execute(q), name
            compiled_identical = bool(
                q.result.nrows == walk_rows
                and {tuple(r) for r in q.result.table.tolist()} == walk_set)
            # the per-step device baseline: ONE execution, count its
            # charged sync points (counts are shape-determined, not
            # timing-dependent, so a single run is exact)
            try:
                tpu = TPUEngine(g, stats=stats)
                q = planned()
                obs.reset()
                tpu.execute(q)
                assert q.result.status_code == 0, name
                stepdev_trips = int(
                    obs.dispatch_ledger.dispatch_counts()["count"])
            except Exception:
                stepdev_trips = None  # shape the step engine can't run
    finally:
        Global.enable_device_obs = prev_obs
    return {
        "walk_ms": round(walk_ms, 1), "wcoj_ms": round(wcoj_ms, 1),
        "speedup": round(walk_ms / wcoj_ms, 2) if wcoj_ms else None,
        "rows": int(walk_rows),
        "rows_identical": bool(walk_rows == wcoj_rows == device_rows
                               and walk_set == wcoj_set == device_set),
        "device_ms": round(device_ms, 1),
        "device_speedup": (round(wcoj_ms / device_ms, 2)
                           if device_ms else None),
        "device_vs_walk": (round(walk_ms / device_ms, 2)
                           if device_ms else None),
        "auto_strategy": settled,
        "auto_first_strategy": first_strategy,
        "auto_first_ms": round(first_ms, 1),
        "auto_ms": round(auto_ms, 1),
        "auto_vs_walk": round(walk_ms / auto_ms, 2) if auto_ms else None,
        "est_peak_over_final": _est_ratio(planner, planned()),
        # None throughout = the shape has no compilable template (the
        # host walk serves it; nothing to gate)
        "compiled_ms": (round(compiled_ms, 1)
                        if compiled_ms is not None else None),
        "compiled_vs_walk": (round(walk_ms / compiled_ms, 2)
                             if compiled_ms else None),
        "compiled_roundtrips": compiled_trips,
        "stepdev_roundtrips": stepdev_trips,
        "compiled_roundtrip_reduction": (
            round(stepdev_trips / compiled_trips, 1)
            if compiled_trips and stepdev_trips else None),
        "compiled_rows_identical": compiled_identical,
    }


def _est_ratio(planner, q) -> float | None:
    ests = planner.estimate_chain(q.pattern_group.patterns)
    if not ests:
        return None
    return round(max(ests) / max(ests[-1], 1.0), 1)


def watdiv_main(device_ok: bool) -> None:
    """`bench.py --watdiv`: S1-S7/F1-F5 star/snowflake templates, batched
    (BASELINE.json configs[3] — no published reference number for this
    hardware, so vs_baseline is null)."""
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.loader.watdiv import TEMPLATES, VirtualWatdivStrings, generate_watdiv
    from wukong_tpu.runtime.proxy import Proxy
    from wukong_tpu.sparql.parser import Parser
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.store.persist import load_gstore, save_gstore
    from wukong_tpu.store.gstore import build_partition

    scale = int(os.environ.get("WUKONG_WATDIV_SCALE", "0"))
    if scale == 0:
        scale = 28000 if os.path.exists(
            os.path.join(CACHE, "watdiv28000_p0.npz")) else 2000
    if not device_ok and scale > 2000 \
            and os.environ.get("WUKONG_EMU_FORCE") != "1":
        # same contract as the emu clamp: explicit force runs the cached
        # at-scale world on the CPU backend (honest backend label)
        scale = 2000
    os.makedirs(CACHE, exist_ok=True)
    store_path = os.path.join(CACHE, f"watdiv{scale}_p0.npz")
    ss = VirtualWatdivStrings(scale, seed=0)
    t0 = time.time()
    from wukong_tpu.utils.errors import WukongError

    g = None
    if os.path.exists(store_path):
        try:
            g = load_gstore(store_path)
        except WukongError as e:  # corrupt/stale cache: rebuild, don't die
            print(f"# store cache invalid ({e}); rebuilding", file=sys.stderr)
            os.remove(store_path)
    if g is None:
        triples, _ = generate_watdiv(scale, seed=0)
        g = build_partition(triples, 0, 1)
        del triples
        try:
            save_gstore(g, store_path)
        except Exception as e:
            print(f"# store cache save failed: {e}", file=sys.stderr)
    print(f"# watdiv-{scale} ready in {time.time() - t0:.0f}s "
          f"({g.stats_str()})", file=sys.stderr)

    eng = TPUEngine(g, ss)
    proxy = Proxy(g, ss, CPUEngine(g, ss), eng)
    rng = np.random.default_rng(0)
    lat_us = []
    details = {}
    failed = []
    for name in sorted(TEMPLATES):
        try:
            tmpl = Parser(ss).parse_template(TEMPLATES[name])
            proxy.fill_template(tmpl)
            cand = tmpl.candidates[0]
            bw = BATCH  # per-template: star templates at WatDiv-28000 can
            # exceed the capacity ceiling at B=1024 — halve and restart,
            # like the LUBM heavies' OOM backoff
            best, q_best, rows_best = None, None, 0
            trial = 0
            while trial < 3:
                consts = np.asarray(
                    cand[rng.integers(0, len(cand), bw)], dtype=np.int64)
                q = tmpl.instantiate(rng)
                heuristic_plan(q)
                q.result.blind = True
                t = time.perf_counter()
                try:
                    counts = eng.execute_batch(q, consts)
                except Exception as e:
                    s = str(e)
                    if bw > 1 and ("exceeds capacity" in s  # merge path
                                   or "table_capacity_max" in s  # v1 chain
                                   or "RESOURCE_EXHAUSTED" in s):  # HBM OOM
                        bw = max(bw // 2, 1)
                        best, q_best, trial = None, None, 0
                        continue
                    raise
                dt = (time.perf_counter() - t) * 1e6 / bw
                if best is None or dt < best:
                    # us, rows, and roofline must all describe the SAME
                    # instantiation (rev-list sizes, learned caps, and
                    # result counts differ per instance)
                    best, q_best, rows_best = dt, q, int(counts[0])
                trial += 1
            lat_us.append(best)
            details[name] = {"us": round(best, 1), "rows": rows_best,
                             "batch": bw}
            _attach_roofline(details[name], eng, q_best, bw, "const",
                             "tpu" if device_ok else "cpu")
            print(f"# {name}: {best:,.0f} us (batch={bw})", file=sys.stderr)
        except Exception as e:
            failed.append(name)
            details[name] = {"error": str(e)[:200]}
            print(f"# {name}: FAILED ({e})", file=sys.stderr)
    if not lat_us:
        raise SystemExit("all watdiv templates failed")
    backend = "TPU single chip" if device_ok else "cpu-fallback"
    _emit_final({
        "metric": f"WatDiv-{scale} S/F templates geomean latency, {backend},"
                  f" blind, batch={_batch_label(details)}"
                  + (f"; FAILED: {','.join(failed)}" if failed else ""),
        "value": round(_geomean(lat_us), 1),
        "unit": "us",
        "vs_baseline": None,
        "backend": "tpu" if device_ok else "cpu",
        "dataset": DATASET_NOTES["watdiv"],
        "detail": details,
    }, "BENCH_WATDIV_DETAIL.json")


def _batch_label(details: dict) -> str:
    """Honest batch label: the single batch when uniform, the range when
    per-template capacity backoff diverged them."""
    bs = sorted({v["batch"] for v in details.values()
                 if isinstance(v, dict) and "batch" in v})
    if not bs:
        return str(BATCH)
    return str(bs[0]) if len(bs) == 1 else f"{bs[0]}-{bs[-1]} (backoff)"


def dbpedia_main(device_ok: bool) -> None:
    """`bench.py --dbpedia`: DBpedia-shaped workload with the type-centric
    planner on (BASELINE.json configs[4]). Queries are built in id space
    from the synthesizer's metadata and data, covering EVERY reference
    dbpsb shape (scripts/sparql_query/dbpsb/dbpsb_q1-q5: type+property
    star, literal-anchored lookup, reverse join to a const anchor, 4-wide
    property star, DISTINCT star) plus hub-anchor and deep-chain variants
    (round-4 verdict Weak #6 / next #7 — >=8 templates). After the latency
    section a closed-loop mixed window (concurrency 1, round-robin) gives
    a dbpsb-emu q/s figure. vs_baseline is null (no published reference
    number for this hardware)."""
    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.loader.generic_rdf import generate_generic
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.planner.stats import Stats
    from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
    from wukong_tpu.types import OUT, TYPE_ID

    n_ent = int(os.environ.get("WUKONG_DBPEDIA_ENTITIES", "0")) or \
        (2_000_000 if device_ok else 100_000)
    t0 = time.time()
    triples, meta = generate_generic(n_ent, n_preds=200, n_types=50, seed=1)
    from wukong_tpu.store.gstore import build_partition

    g = build_partition(triples, 0, 1)
    stats = Stats.generate(triples)
    planner = Planner(stats)
    print(f"# dbpedia-shaped world ({len(triples):,} triples) ready "
          f"in {time.time() - t0:.0f}s", file=sys.stderr)
    eng = TPUEngine(g, None, stats=stats)
    pids = sorted(stats.pred_edges, key=lambda p: -stats.pred_edges[p])
    pids = [p for p in pids if p != TYPE_ID][:6]
    types = sorted((t for t in stats.tyscount if t > 0),
                   key=lambda t: -stats.tyscount[t])[:4]
    hub = int(meta["hubs"][0])

    def mk(pats, nvars):
        q = SPARQLQuery()
        q.pattern_group.patterns = [Pattern(*p) for p in pats]
        q.result.nvars = nvars
        q.result.required_vars = [-(i + 1) for i in range(nvars)]
        q.result.blind = True
        return q

    # data-driven anchors so the const-anchored shapes are non-empty: a
    # typed subject with an outgoing normal edge (dbpsb_q2's labeled
    # person), and a 2-hop reverse pair b --pB--> a --pA--> c (dbpsb_q3's
    # developer/foundationPlace join)
    norm = triples[(triples[:, 1] != TYPE_ID)]
    typed_s = triples[triples[:, 1] == TYPE_ID]
    type_of = dict(zip(typed_s[::-1, 0].tolist(), typed_s[::-1, 2].tolist()))
    rs = rp = ro = t_rs = None
    omitted: list[str] = []
    p0_subjects = set(norm[norm[:, 1] == pids[0]][:, 0].tolist())
    for s, p, o in norm[:5000].tolist():
        # the witness must satisfy ALL THREE Q2 patterns (typed, has the
        # rp->ro edge, AND a pids[0] out-edge) or the benchmark could
        # silently measure a planner-proved-empty shortcircuit
        if s in type_of and s in p0_subjects:
            rs, rp, ro, t_rs = s, p, o, type_of[s]
            break
    rev = None  # (a, pA, c, b, pB, t_b)
    obj_first: dict = {}
    for i, o in enumerate(norm[:50000, 2].tolist()):
        obj_first.setdefault(int(o), i)
    for a, pA, c_ in norm[:20000].tolist():
        j = obj_first.get(int(a))
        if j is not None and int(norm[j, 0]) in type_of:
            b, pB = int(norm[j, 0]), int(norm[j, 1])
            rev = (int(a), int(pA), int(c_), b, pB, type_of[b])
            break

    cases = {
        # dbpsb_q1: type + property star
        "Q1_star": mk([(-1, TYPE_ID, OUT, types[0]),
                       (-1, pids[0], OUT, -2)], 2),
        # dbpsb_q4: type + 4-wide property star
        "Q4_star4": mk([(-1, TYPE_ID, OUT, types[2]),
                        (-1, pids[0], OUT, -2), (-1, pids[1], OUT, -3),
                        (-1, pids[2], OUT, -4), (-1, pids[3], OUT, -5)], 5),
        # dbpsb_q5: DISTINCT type + 2-property star
        "Q5_distinct": mk([(-1, TYPE_ID, OUT, types[3]),
                           (-1, pids[1], OUT, -2),
                           (-1, pids[2], OUT, -3)], 3),
        # C: type-filtered 2-hop chain
        "C1": mk([(-1, TYPE_ID, OUT, types[1]), (-1, pids[1], OUT, -2),
                  (-2, pids[2], OUT, -3)], 3),
        # F: hub anchor + expansion (skew stress)
        "F1": mk([(-1, pids[0], OUT, hub), (-1, pids[3], OUT, -2)], 2),
        # F2: hub anchor + 2-hop chain off it
        "F2": mk([(-1, pids[0], OUT, hub), (-1, pids[3], OUT, -2),
                  (-2, pids[4], OUT, -3)], 3),
    }
    cases["Q5_distinct"].distinct = True
    # DISTINCT must actually dedup: measured non-blind through the final
    # phase (blind mode would drop the table before projection)
    cases["Q5_distinct"].result.blind = False
    if rs is not None:
        # dbpsb_q2: const-anchored lookup + type check + property
        cases["Q2_anchor"] = mk([(-1, rp, OUT, ro),
                                 (-1, TYPE_ID, OUT, t_rs),
                                 (-1, pids[0], OUT, -2)], 2)
    else:
        # a missing template must be VISIBLE, not a silently smaller suite
        # (the round-4 verdict's done-bar is >=8 templates)
        omitted.append("Q2_anchor")
        print("# Q2_anchor: no witness row in the scan window — template "
              "omitted", file=sys.stderr)
    if rev is not None:
        a, pA, c_, b, pB, t_b = rev
        # dbpsb_q3: ?v2 pA CONST ; ?v4 pB ?v2 ; ?v4 type T
        cases["Q3_reverse"] = mk([(-1, pA, OUT, c_), (-2, pB, OUT, -1),
                                  (-2, TYPE_ID, OUT, t_b)], 2)
    else:
        omitted.append("Q3_reverse")
        print("# Q3_reverse: no 2-hop typed witness in the scan window — "
              "template omitted", file=sys.stderr)
    lat_us, details, failed = [], {}, list(omitted)
    for n in omitted:
        details[n] = {"error": "no witness row found in the scan window"}
    import copy

    for name, q0 in cases.items():
        try:
            best = None
            nrows = -1
            for _trial in range(3):
                q = copy.deepcopy(q0)
                if not planner.generate_plan(q):
                    raise RuntimeError("planner failed to produce a plan")
                t = time.perf_counter()
                # from_proxy so the final phase (DISTINCT dedup) executes
                eng.execute(q, from_proxy=True)
                dt = (time.perf_counter() - t) * 1e6
                if q.result.status_code != 0:
                    raise RuntimeError(f"status {q.result.status_code!r}")
                nrows = q.result.nrows
                best = dt if best is None else min(best, dt)
            lat_us.append(best)
            details[name] = {"us": round(best, 1), "rows": nrows}
            print(f"# {name}: {best:,.0f} us (rows={nrows})", file=sys.stderr)
        except Exception as e:
            failed.append(name)
            details[name] = {"error": str(e)[:200]}
            print(f"# {name}: FAILED ({e})", file=sys.stderr)
    if not lat_us:
        raise SystemExit("all dbpedia cases failed")

    # dbpsb-emu: CLOSED-loop mixed window at concurrency 1 (back-to-back
    # execution, round-robin over the templates — NOT comparable to an
    # open-loop peak-throughput figure; the label in the artifact says so).
    # The reference ships no dbpsb mix_config; weights documented uniform.
    emu_s = float(os.environ.get("WUKONG_DBPSB_EMU_S", "8"))
    ok_cases = {n: q for n, q in cases.items() if n not in failed}
    if emu_s > 0 and ok_cases:
        names = sorted(ok_cases)
        planned = {}
        for n in names:  # plan ONCE per template (the reference's emulator
            # also plans per template, not per instance; planning dominated
            # the draw), keep the pristine planned copy, precompile the
            # blind chain before the window
            q = copy.deepcopy(ok_cases[n])
            if not planner.generate_plan(q):
                continue
            q.result.blind = True
            planned[n] = copy.deepcopy(q)
            eng.execute(q, from_proxy=False)
        names = sorted(planned)
        served = 0
        t_end = time.perf_counter() + emu_s
        while names and time.perf_counter() < t_end:
            q = copy.deepcopy(planned[names[served % len(names)]])
            eng.execute(q, from_proxy=False)
            served += 1
        qps = served / emu_s
        details["dbpsb_emu"] = {"qps": round(qps, 1),
                                "window_s": emu_s,
                                "mix": "uniform round-robin",
                                "loop": "closed, concurrency 1",
                                "templates": len(planned)}
        print(f"# dbpsb-emu: {qps:,.0f} q/s over {emu_s:.0f}s "
              f"(closed loop, {len(planned)} templates)", file=sys.stderr)
    backend = "TPU single chip" if device_ok else "cpu-fallback"
    _emit_final({
        "metric": f"DBpedia-shaped ({len(triples):,} triples) mixed "
                  f"{'/'.join(sorted({n[0] for n in cases}))} "
                  f"({len(cases)} dbpsb-shaped templates) geomean latency, "
                  f"{backend}, planner on"
                  + (f"; FAILED: {','.join(failed)}" if failed else ""),
        "value": round(_geomean(lat_us), 1),
        "unit": "us",
        "vs_baseline": None,
        "backend": "tpu" if device_ok else "cpu",
        "dataset": DATASET_NOTES["dbpedia"],
        "detail": details,
    }, "BENCH_DBPEDIA_DETAIL.json")


def yago_main(device_ok: bool) -> None:
    """`bench.py --yago`: the reference yago suite (yago_q1-q4) executed
    VERBATIM against the yago-shaped synthesized world (loader/yago.py —
    the files' own constants resolve through YagoStrings). q3 is the
    heavy: a 3-hop self-join over the power-law wiki-link relation.
    vs_baseline null (the reference publishes no yago numbers for
    comparable hardware)."""
    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.loader.yago import YagoStrings, generate_yago
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.planner.stats import Stats
    from wukong_tpu.sparql.parser import Parser
    from wukong_tpu.store.gstore import build_partition

    n_person = int(os.environ.get("WUKONG_YAGO_PERSONS", "0")) or \
        (200_000 if device_ok else 30_000)
    t0 = time.time()
    triples, _meta = generate_yago(n_person, seed=0)
    ss = YagoStrings(n_person, seed=0)
    g = build_partition(triples, 0, 1)
    stats = Stats.generate(triples)
    planner = Planner(stats)
    eng = TPUEngine(g, ss, stats=stats)
    print(f"# yago-shaped world ({len(triples):,} triples, "
          f"{n_person:,} persons) ready in {time.time() - t0:.0f}s",
          file=sys.stderr)
    lat_us, details, failed = [], {}, []
    for k in range(1, 5):
        qn = f"yago_q{k}"
        try:
            text = open(
                f"/root/reference/scripts/sparql_query/yago/{qn}").read()
            best, nrows = None, -1
            for _trial in range(3):
                q = Parser(ss).parse(text)
                planner.generate_plan(q)
                q.result.blind = True
                t = time.perf_counter()
                eng.execute(q, from_proxy=False)
                dt = (time.perf_counter() - t) * 1e6
                if q.result.status_code != 0:
                    raise RuntimeError(f"status {q.result.status_code!r}")
                nrows = q.result.nrows
                best = dt if best is None else min(best, dt)
            lat_us.append(best)
            details[qn] = {"us": round(best, 1), "rows": nrows}
            print(f"# {qn}: {best:,.0f} us (rows={nrows})", file=sys.stderr)
        except Exception as e:
            failed.append(qn)
            details[qn] = {"error": str(e)[:200]}
            print(f"# {qn}: FAILED ({e})", file=sys.stderr)
    if not lat_us:
        raise SystemExit("all yago queries failed")
    backend = "TPU single chip" if device_ok else "cpu-fallback"
    _emit_final({
        "metric": f"yago-shaped ({len(triples):,} triples) reference "
                  f"yago_q1-q4 geomean latency, {backend}, planner on"
                  + (f"; FAILED: {','.join(failed)}" if failed else ""),
        "value": round(_geomean(lat_us), 1),
        "unit": "us",
        "vs_baseline": None,
        "backend": "tpu" if device_ok else "cpu",
        "dataset": "synthetic yago-shaped data (loader/yago.py); the "
                   "reference query files execute verbatim, data is not "
                   "YAGO",
        "detail": details,
    }, "BENCH_YAGO_DETAIL.json")


def _apply_kernel_toggles() -> None:
    """Env-driven kernel A/B switches — read in EVERY process (the --one
    measurement subprocesses inherit the env, not the parent's Global)."""
    from wukong_tpu.config import Global

    if os.environ.get("WUKONG_ENABLE_PALLAS", "1") == "0":
        Global.enable_pallas = False
        print("# pallas disabled via WUKONG_ENABLE_PALLAS=0", file=sys.stderr)
    if os.environ.get("WUKONG_ENABLE_FP_PROBE", "1") == "0":
        Global.enable_fp_probe = False
        print("# fp probe disabled via WUKONG_ENABLE_FP_PROBE=0",
              file=sys.stderr)
    if os.environ.get("WUKONG_ENABLE_MERGE", "1") == "0":
        Global.enable_merge_join = False
        print("# sort-merge path disabled via WUKONG_ENABLE_MERGE=0",
              file=sys.stderr)
    if os.environ.get("WUKONG_ENABLE_STREAM", "1") == "0":
        Global.enable_stream_expand = False
        print("# streaming expand disabled via WUKONG_ENABLE_STREAM=0",
              file=sys.stderr)
    cap_max = int(os.environ.get("WUKONG_CAP_MAX", "0") or 0)
    if cap_max:
        # heavy-batch HBM trade: raising the per-level row ceiling lets
        # suggest_index_batch fit a larger replicate B, amortizing each
        # batch's whole-segment sorts over more queries (2^25 default =
        # 256 MiB/level; a 16 GiB chip has room for 2^26-2^27 when the
        # chain is shallow). On-chip calibration knob for the capture loop.
        Global.table_capacity_max = cap_max
        print(f"# table_capacity_max={cap_max:,} via WUKONG_CAP_MAX",
              file=sys.stderr)


def _setup_jax_caches() -> None:
    """Persistent XLA compilation cache: the axon-tunneled backend compiles
    slowly (tens of seconds per program), so repeated bench runs must reuse
    compiled programs across processes."""
    from wukong_tpu.utils.compilecache import setup_persistent_cache

    if setup_persistent_cache(os.path.join(CACHE, "xla")) is None:
        print("# compilation cache unavailable", file=sys.stderr)


def _measure_one(qn: str, scale: int) -> dict:
    """Measure one LUBM query (3 trials, batched); returns its detail dict.
    Runs inside the per-query subprocess in the default orchestrated mode."""
    g, ss, stats = _ensure_world(scale)
    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.sparql.parser import Parser

    eng = TPUEngine(g, ss, stats=stats)
    # learned capacities survive the per-query subprocess boundary, so
    # best-of-3 measures steady state, not first-call overflow retries
    memo_path = os.path.join(CACHE, f"cap_memo_lubm{scale}.json")
    eng.merge.load_cap_memo(memo_path)
    # the type-centric planner, exactly as the proxy runs it (q1 peak
    # intermediates: 130K planner vs 10.1M heuristic at LUBM-40) — the
    # heuristic was leaving an order of magnitude on the table for heavies
    planner = Planner(stats)

    def plan(qq):
        planner.generate_plan(qq)

    text = open(f"{BASIC}/{qn}").read()
    q0 = Parser(ss).parse(text)
    plan(q0)
    from wukong_tpu.types import NORMAL_ID_START

    const_start = q0.pattern_group.patterns[0].subject >= NORMAL_ID_START
    bq = BATCH if const_start else eng.suggest_index_batch(q0)
    # lights: K in-flight batches per measurement (the open-loop emulator
    # window) so the fixed ~45-70 ms relay sync amortizes across K * B
    # queries, not B. Heavies keep K=1 (compute-bound, sync irrelevant).
    K = 8 if const_start else 1
    from wukong_tpu.config import Global

    best = None
    nrows = -1
    trial = 0
    warmed = False
    while trial < 3:
        q = Parser(ss).parse(text)
        plan(q)
        q.result.blind = True
        try:
            if const_start:
                consts = np.full(bq, q.pattern_group.patterns[0].subject,
                                 dtype=np.int64)
                use_many = (Global.enable_merge_join
                            and eng.merge.supports(q))
                if not warmed:  # learn capacities once, untimed
                    counts = eng.execute_batch(q, consts)
                    warmed = True
                if use_many:
                    t = time.perf_counter()
                    many = eng.merge.run_batch_const_many(q, [consts] * K)
                    dt = (time.perf_counter() - t) * 1e6 / (bq * K)
                    counts = many[0]
                else:
                    K = 1
                    t = time.perf_counter()
                    counts = eng.execute_batch(q, consts)
                    dt = (time.perf_counter() - t) * 1e6 / bq
            else:
                t = time.perf_counter()
                counts = eng.execute_batch_index(q, bq)
                dt = (time.perf_counter() - t) * 1e6 / bq
        except Exception as e:  # HBM OOM at this batch: halve and restart
            if "RESOURCE_EXHAUSTED" in str(e) and bq > 1:
                bq = max(bq // 2, 1)
                print(f"# {qn}: OOM, retrying at batch={bq}",
                      file=sys.stderr, flush=True)
                # any provisional stub banked at the unsustainable larger
                # batch must not outlive the restart (its lower per-query
                # us would mask the honest smaller-batch result)
                _drop_partial(scale, qn,
                              os.environ.get("WUKONG_BENCH_BACKEND", "tpu"),
                              above_batch=bq)
                best = None
                trial = 0
                warmed = False
                continue
            raise
        nrows = int(counts[0])
        best = dt if best is None else min(best, dt)
        trial += 1
        # bank the best-so-far IMMEDIATELY: a relay death or the
        # orchestrator's deadline kill between trials must not cost the
        # whole query (us nudged up ~0.1% so the complete final detail —
        # same latency, plus rooflines/caps/capability fields — replaces
        # this stub in the store)
        try:
            _record_partial(
                scale, qn, os.environ.get("WUKONG_BENCH_BACKEND", "tpu"),
                {"us": max(round(best * 1.001, 1), 0.1) + 0.1,
                 "rows": nrows, "batch": bq, "inflight": K,
                 "provisional": True,
                 **({"planner_empty": True} if q0.planner_empty else {})})
        except Exception as e:
            print(f"# provisional bank failed: {e}", file=sys.stderr)
    # retry evidence for the BATCHED chain only (the slice measurement
    # below learns its own capacity classes and must not contaminate it)
    batched_retries = eng.merge.total_retries
    # planner-proved-empty queries short-circuit to ~0; floor at 0.1 us so
    # the geomean stays finite, and FLAG them: the reference's published
    # number for such a query measured full execution, so a raw ratio
    # would be inflated ~7x by a query neither engine ran comparably —
    # the assembly counts flagged queries at PARITY (1.0) in vs_baseline
    out = {"us": max(round(best, 1), 0.1), "rows": nrows, "batch": bq,
           "inflight": K}
    if q0.planner_empty:
        out["planner_empty"] = True
    if not const_start and not q0.planner_empty:
        # single-QUERY latency via slice mode (one query, its index split
        # into B slices inside one program — the mt_factor analogue,
        # sparql.hpp:98-108): the reference's published tables are
        # single-query latencies, so the artifact carries the
        # apples-to-apples number next to the batched-throughput one
        try:
            sq = None
            for _ in range(2):  # warm (learn slice caps) + steady
                qs = Parser(ss).parse(text)
                plan(qs)
                qs.result.blind = True
                t = time.perf_counter()
                eng.execute_batch_index(qs, bq, slice_mode=True)
                dt = (time.perf_counter() - t) * 1e6
                sq = dt if sq is None else min(sq, dt)
            out["single_query_us"] = round(sq, 1)
        except Exception as e:
            out["single_query_us"] = None
            out["single_query_error"] = str(e)[:200]
    # AFTER the slice block: its learned ('slice'-keyed) classes must
    # reach the memo file too, or every bench subprocess re-pays the
    # slice chain's overflow retries
    eng.merge.save_cap_memo(memo_path)
    if os.environ.get("WUKONG_BENCH_BACKEND", "tpu") == "tpu":
        # kernel capability evidence (round-3 weak #1: a Mosaic lowering
        # failure silently demotes every dense expand to the XLA emit —
        # the artifact must SAY whether the stream kernel exists on this
        # silicon, not leave it to A/B archaeology)
        try:
            from wukong_tpu.engine import tpu_kernels, tpu_stream

            out["stream_available"] = bool(tpu_stream.stream_available())
            out["pallas_probe_available"] = bool(
                tpu_kernels.pallas_available())
        except Exception as e:
            # capability evidence must stay machine-checkable: a probe
            # CRASH means the kernels are not available
            out["stream_available"] = False
            out["pallas_probe_available"] = False
            out["kernel_probe_error"] = str(e)[:200]
    # per-step time breakdown (observability PR): ONE traced single-query
    # execution AFTER the timed trials — the measured numbers above never
    # see a trace (tracing default-off is the guarded hot path), and the
    # artifact gains where the time goes (chain vs host steps, rows in/out)
    if os.environ.get("WUKONG_BENCH_TRACE", "1") != "0":
        try:
            from wukong_tpu.obs import QueryTrace
            from wukong_tpu.runtime.resilience import Deadline

            qt = Parser(ss).parse(text)
            plan(qt)
            qt.result.blind = True
            qt.trace = QueryTrace(kind="bench", text=qn)
            qt.deadline = Deadline(timeout_ms=60_000)  # bounded, not open
            eng.execute(qt)
            out["step_breakdown"] = {
                "status": qt.result.status_code.name,
                "spans": qt.trace.step_summary(),
            }
        except Exception as e:
            out["step_breakdown_error"] = str(e)[:200]
    _attach_roofline(out, eng, q0, bq, "const" if const_start else "rep",
                     os.environ.get("WUKONG_BENCH_BACKEND", "tpu"))
    # capacity-class behavior evidence (the at-scale de-risk artifact):
    # which pow2 classes the chain settled on, and how many whole-chain
    # overflow retries it took to learn them this process
    out["overflow_retries"] = batched_retries
    memo = eng.merge._cap_memo.get(eng.merge._key(
        q0.pattern_group.patterns, bq, "const" if const_start else "rep"))
    if memo:
        out["cap_classes"] = {str(s): int(c) for s, c in sorted(memo.items())}
    return out


def micro_main(device_ok: bool) -> None:
    """`bench.py --micro`: the kernel-cost microbenchmarks behind every
    dispatch constant (ROADMAP.md "Measured on-chip facts"): sort /
    variadic sort / gather / scatter-max / cumsum at heavy-table sizes,
    plus the host<->device sync RTT. One JSON line, ns/elem per op — a
    healthy session re-derives the sort-vs-gather economics (the
    PROBE_LOOKUP_FACTOR = 16 basis) in one command instead of ad-hoc
    probes."""
    import jax
    import jax.numpy as jnp

    N = int(os.environ.get("WUKONG_MICRO_N", str(16 * 2**20)))
    rng = np.random.default_rng(0)
    vals = jnp.asarray(rng.integers(0, 2**31 - 2, N, dtype=np.int32))
    idx = jnp.asarray(rng.integers(0, N, N, dtype=np.int32))
    payload = jnp.asarray(rng.integers(0, 2**31 - 2, N, dtype=np.int32))

    def timed(fn, *args, reps=3):
        fn_j = jax.jit(fn)
        jax.block_until_ready(fn_j(*args))  # compile + warm
        best = None
        for _ in range(reps):
            t = time.perf_counter()
            jax.block_until_ready(fn_j(*args))
            dt = time.perf_counter() - t
            best = dt if best is None else min(best, dt)
        return best * 1e9 / N  # ns per element

    detail = {}
    detail["sort_1op"] = round(timed(jnp.sort, vals), 3)
    detail["sort_kv2"] = round(timed(
        lambda k, p: jax.lax.sort((k, p), num_keys=1), vals, payload), 3)
    detail["sort_kv3"] = round(timed(
        lambda k, p, q: jax.lax.sort((k, p, q), num_keys=2),
        vals, payload, idx), 3)
    detail["gather_random"] = round(timed(lambda v, i: v[i], vals, idx), 3)
    detail["cumsum"] = round(timed(jnp.cumsum, vals), 3)
    detail["cummax"] = round(timed(jax.lax.cummax, vals), 3)
    detail["scatter_max"] = round(timed(
        lambda v, i: jnp.zeros(N, jnp.int32).at[i].max(v), vals, idx), 3)
    # host<->device sync RTT (flat cost every chain pays exactly once)
    t_best = None
    for _ in range(5):
        t = time.perf_counter()
        jax.device_get(vals[:1])
        dt = time.perf_counter() - t
        t_best = dt if t_best is None else min(t_best, dt)
    detail["sync_rtt_ms"] = round(t_best * 1e3, 2)
    # the dispatch economics this justifies
    detail["gather_over_sort"] = round(
        detail["gather_random"] / max(detail["sort_1op"], 1e-9), 2)
    backend = "tpu" if device_ok else "cpu"
    print(json.dumps({
        "metric": f"kernel-cost microbenchmarks at N={N:,} int32 "
                  f"({backend} backend): ns/elem per op + sync RTT "
                  "(the sort-vs-gather economics behind the lookup "
                  "dispatch factors)",
        "value": detail["sort_1op"],
        "unit": "ns/elem",
        "vs_baseline": None,
        "backend": backend,
        "detail": detail,
    }))


def _at_scale_verify_main() -> None:
    """`bench.py --at-scale-verify <qn,...>`: oracle-verification subprocess
    for the at-scale run. Loads the world ONCE, then per query:

    - const-start lights: sample 8 distinct constants from the start
      pattern's segment keys, run the SAME planned chain through the merge
      executor batched (each const x32), and check every sampled per-
      instance count against a single-instance CPUEngine run.
    - index-origin heavies: run the CPUEngine once (SIGALRM time-boxed,
      WUKONG_ORACLE_TIMEOUT) and compare total rows to the merge count
      (which the caller took from the measurement pass).

    Prints one JSON object as the last stdout line:
    {qn: {"ok": bool, ...evidence}}. This is the round-4 verdict #2
    de-risk: counts at 582M edges verified against an independent engine,
    not just measured."""
    import copy
    import signal

    qns = sys.argv[sys.argv.index("--at-scale-verify") + 1].split(",")
    scale = int(os.environ.get("WUKONG_BENCH_SCALE") or 2560)
    heavy_rows = json.loads(os.environ.get("WUKONG_ORACLE_HEAVY_ROWS", "{}"))
    oracle_box = int(os.environ.get("WUKONG_ORACLE_TIMEOUT", "1800"))
    _apply_kernel_toggles()
    import jax

    if os.environ.get("WUKONG_BENCH_BACKEND", "cpu") != "tpu":
        jax.config.update("jax_platforms", "cpu")
    _setup_jax_caches()
    g, ss, stats = _ensure_world(scale)
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.sparql.parser import Parser

    cpu = CPUEngine(g, ss)
    eng = TPUEngine(g, ss, stats=stats)
    eng.merge.load_cap_memo(os.path.join(CACHE, f"cap_memo_lubm{scale}.json"))
    planner = Planner(stats)

    class _OracleTimeout(Exception):
        pass

    def _alarm(_sig, _frm):
        raise _OracleTimeout()

    signal.signal(signal.SIGALRM, _alarm)
    out = {}
    for qn in qns:
        t_q = time.time()
        try:
            q = Parser(ss).parse(open(f"{BASIC}/{qn}").read())
            planner.generate_plan(q)
            q.result.blind = True
            pats = q.pattern_group.patterns
            if q.planner_empty:
                out[qn] = {"ok": True, "planner_empty": True}
                continue
            from wukong_tpu.types import NORMAL_ID_START

            if pats[0].subject >= NORMAL_ID_START:  # const start: sampled
                pid, d = int(pats[0].predicate), int(pats[0].direction)
                seg = g.segments.get((pid, d))
                if seg is None or len(seg.keys) == 0:
                    out[qn] = {"ok": False, "error": "no start segment"}
                    continue
                rng = np.random.default_rng(7)
                sample = np.unique(rng.choice(
                    seg.keys, size=min(8, len(seg.keys)), replace=False))
                consts = np.repeat(sample, 32).astype(np.int64)
                counts = eng.merge.run_batch_const(q, consts)
                mism = []
                for i, c in enumerate(sample):
                    qc = copy.deepcopy(q)
                    qc.pattern_group.patterns[0].subject = int(c)
                    signal.alarm(oracle_box)
                    try:
                        cpu.execute(qc, from_proxy=False)
                    finally:
                        signal.alarm(0)
                    want = qc.result.nrows
                    got = int(counts[i * 32])
                    if want != got:
                        mism.append({"const": int(c), "cpu": int(want),
                                     "merge": got})
                out[qn] = {"ok": not mism, "sampled_consts": len(sample),
                           "mismatches": mism,
                           "verify_s": round(time.time() - t_q, 1)}
            else:  # index-origin heavy: one full CPU-oracle run, time-boxed
                qc = copy.deepcopy(q)
                signal.alarm(oracle_box)
                try:
                    cpu.execute(qc, from_proxy=False)
                except _OracleTimeout:
                    out[qn] = {"ok": None,
                               "error": f"oracle timeout ({oracle_box}s)"}
                    continue
                finally:
                    signal.alarm(0)
                want = int(qc.result.nrows)
                got = heavy_rows.get(qn)
                out[qn] = {"ok": (got == want) if got is not None else None,
                           "cpu_rows": want, "merge_rows": got,
                           "verify_s": round(time.time() - t_q, 1)}
        except _OracleTimeout:
            out[qn] = {"ok": None, "error": f"oracle timeout ({oracle_box}s)"}
        except Exception as e:
            out[qn] = {"ok": False, "error": repr(e)[:300]}
        print(f"# verify {qn}: {out[qn]}", file=sys.stderr, flush=True)

    # the beyond-reference VERSATILE family at the same scale: ?x ?p ?y
    # with x bound, device engine vs CPU oracle, full table multiset
    # (the reference accelerator refuses the shape outright)
    if os.environ.get("WUKONG_VERIFY_VERSATILE", "1") == "1":
        import copy

        t_v = time.time()
        try:
            vtext = (
                "PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>\n"
                "SELECT ?X ?P ?Y WHERE { ?X ub:worksFor "
                "<http://www.Department0.University0.edu> . ?X ?P ?Y . }")
            qd = Parser(ss).parse(vtext)
            planner.generate_plan(qd)
            qc = copy.deepcopy(qd)  # identical plan on both engines
            # separate time boxes: a slow device run must not eat the
            # oracle's budget, and a device stall must not be blamed on
            # the oracle
            stage = "device"
            signal.alarm(oracle_box)
            try:
                eng.execute(qd, from_proxy=False)
                signal.alarm(0)
                stage = "oracle"
                signal.alarm(oracle_box)
                cpu.execute(qc, from_proxy=False)
            finally:
                signal.alarm(0)
            got = sorted(map(tuple, np.asarray(qd.result.table).tolist()))
            want = sorted(map(tuple, np.asarray(qc.result.table).tolist()))
            # witness that the DEVICE versatile chain actually ran: the
            # combined-adjacency serve counter (eviction-proof — the 2560
            # staging exceeds the cache budget and is dropped right after
            # unpinning, so cache presence alone would false-negative).
            # Without it, both runs came from the host path and the
            # compare would be vacuous.
            device_ran = eng.dstore.versatile_hits > 0
            out["versatile_xpy"] = {
                "ok": (qd.result.status_code == 0
                       and qc.result.status_code == 0 and got == want
                       and device_ran),
                "device_status": int(qd.result.status_code),
                "oracle_status": int(qc.result.status_code),
                "device_rows": len(got), "oracle_rows": len(want),
                "device_versatile_staged": device_ran,
                "verify_s": round(time.time() - t_v, 1)}
        except _OracleTimeout:
            out["versatile_xpy"] = {
                "ok": None, "error": f"{stage} timeout ({oracle_box}s)"}
        except Exception as e:
            out["versatile_xpy"] = {"ok": False, "error": repr(e)[:300]}
        print(f"# verify versatile_xpy: {out['versatile_xpy']}",
              file=sys.stderr, flush=True)
    print(json.dumps(out))


def at_scale_main() -> None:
    """`bench.py --at-scale`: the batch executors at a cached at-scale world
    on an explicitly-labeled backend (default cpu) — round-4 verdict #2:
    LUBM-2560 must not meet the merge/stream chains for the first time
    during a rare healthy-relay window. Measures a query subset through the
    normal per-query subprocess machinery (same `--one` path the real bench
    uses, so capacity memos/partials persist identically), then runs the
    oracle-verification subprocess. Prints ONE JSON line; the committed
    artifact is BENCH_2560_CPU.json."""
    import subprocess

    scale = int(os.environ.get("WUKONG_BENCH_SCALE", "0") or 0) or 2560
    from wukong_tpu.loader.lubm import DATASET_VERSION

    v = f"v{DATASET_VERSION}"
    if not (os.path.exists(os.path.join(CACHE, f"lubm{scale}_{v}_p0.npz"))
            or os.path.exists(
                os.path.join(REPO, f".cache_lubm{scale}_{v}_triples.npy"))):
        raise SystemExit(f"--at-scale needs a cached LUBM-{scale} world")
    backend = os.environ.get("WUKONG_BENCH_BACKEND", "cpu")
    # fast-first order: lights land numbers before any heavy can blow the
    # soft deadline
    queries = (os.environ.get("WUKONG_BENCH_QUERIES")
               or "lubm_q4,lubm_q5,lubm_q6,lubm_q2,lubm_q7,lubm_q1").split(",")
    q_deadline = int(os.environ.get("WUKONG_QUERY_TIMEOUT", "3600"))
    soft_deadline = int(os.environ.get("WUKONG_BENCH_DEADLINE", "14400"))
    env = dict(os.environ, WUKONG_BENCH_SCALE=str(scale),
               WUKONG_BENCH_BACKEND=backend)
    t0 = time.time()
    details = {}
    failed = []
    for qn in queries:
        if time.time() - t0 > soft_deadline:
            failed.append(qn)
            details[qn] = {"error": "skipped: at-scale soft deadline"}
            continue
        print(f"# [{time.strftime('%H:%M:%S')}] {qn} starting",
              file=sys.stderr, flush=True)
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--one", qn],
                env=env, timeout=q_deadline, capture_output=True)
            if r.returncode != 0:
                raise RuntimeError(
                    f"rc={r.returncode}: {r.stderr.decode()[-300:]}")
            d = json.loads(r.stdout.decode().strip().splitlines()[-1])
            d["backend"] = backend
            d["scale"] = scale
            details[qn] = d
            print(f"# {qn}: {d['us']:,.0f} us (rows={d['rows']}, "
                  f"batch={d['batch']}, retries={d.get('overflow_retries')})",
                  file=sys.stderr, flush=True)
        except subprocess.TimeoutExpired:
            failed.append(qn)
            details[qn] = {"error": f"timeout after {q_deadline}s"}
            print(f"# {qn}: TIMEOUT ({q_deadline}s)", file=sys.stderr)
        except Exception as e:
            failed.append(qn)
            details[qn] = {"error": str(e)[:300]}
            print(f"# {qn}: FAILED ({e})", file=sys.stderr)

    # oracle verification (skippable: WUKONG_SKIP_VERIFY=1)
    verification = None
    measured = [qn for qn in queries if "us" in details.get(qn, {})]
    if os.environ.get("WUKONG_SKIP_VERIFY") != "1" and measured:
        heavy_rows = {qn: details[qn]["rows"] for qn in measured
                      if not details[qn].get("planner_empty")
                      and details[qn].get("inflight") == 1}
        try:
            print(f"# [{time.strftime('%H:%M:%S')}] oracle verification "
                  f"starting ({','.join(measured)})",
                  file=sys.stderr, flush=True)
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--at-scale-verify", ",".join(measured)],
                env=dict(env, WUKONG_ORACLE_HEAVY_ROWS=json.dumps(heavy_rows)),
                timeout=int(os.environ.get("WUKONG_VERIFY_TIMEOUT", "7200")),
                capture_output=True)
            sys.stderr.write(r.stderr.decode()[-2000:])
            if r.returncode == 0:
                verification = json.loads(
                    r.stdout.decode().strip().splitlines()[-1])
        except Exception as e:
            print(f"# verification pass failed: {e}", file=sys.stderr)

    us = [d["us"] for qn, d in details.items()
          if d.get("us") and not d.get("planner_empty")]
    bad = [qn for qn, v in (verification or {}).items() if v.get("ok") is False]
    _emit_final({
        "metric": f"LUBM-{scale} at-scale de-risk: "
                  f"{','.join(qn for qn in queries if qn not in failed)} "
                  f"batch executors on backend={backend}, oracle-verified"
                  + (f"; FAILED: {','.join(failed)}" if failed else "")
                  + (f"; VERIFY-FAILED: {','.join(bad)}" if bad else ""),
        "value": round(_geomean(us), 1) if us else None,
        "unit": "us",
        "vs_baseline": None,
        "backend": backend,
        "dataset": DATASET_NOTES["lubm"],
        "detail": details,
        "verification": verification,
    }, "BENCH_ATSCALE_DETAIL.json")


def dist_main() -> None:
    """`bench.py --dist`: L1-L7 blind latency through the distributed
    engine (compiled shard_map chains + all-to-all exchanges) on a D-way
    mesh. Multi-chip hardware is unreachable from this VM, so by default
    the mesh is 8 virtual CPU devices and the backend label says so
    (`cpu-mesh-8`, vs_baseline null — never a cross-fabric ratio); set
    WUKONG_DIST_TPU=1 on a real multi-chip host to measure the ICI path
    with the same mode."""
    import jax

    D = min(8, len(jax.devices()))
    platform = jax.devices()[0].platform
    backend = f"{platform}-mesh-{D}"
    scale = int(os.environ.get("WUKONG_BENCH_SCALE", "0") or 0) or 40
    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
    from wukong_tpu.parallel.dist_engine import DistEngine
    from wukong_tpu.parallel.mesh import make_mesh
    from wukong_tpu.sparql.parser import Parser
    from wukong_tpu.store.gstore import build_all_partitions

    t0 = time.time()
    triples, _ = generate_lubm(scale, seed=42)
    ss = VirtualLubmStrings(scale, seed=42)
    stores = build_all_partitions(triples, D)
    dist = DistEngine(stores, ss, make_mesh(D))
    # learned capacity classes persist across processes (with the XLA
    # persistent cache this makes cold chains trace one already-compiled
    # program; round-4 verdict Weak #3 / next #6)
    from wukong_tpu.loader.lubm import DATASET_VERSION

    memo_path = os.path.join(
        CACHE, f"dist_caps_lubm{scale}_v{DATASET_VERSION}_D{D}.json")
    dist.load_cap_memo(memo_path)
    # the type-centric Planner, like the single-chip bench: plan quality and
    # the planner-empty short-circuit (q3) are part of the measured system
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.planner.stats import Stats

    planner = Planner(Stats.generate(triples))
    print(f"# dist world ready in {time.time() - t0:.0f}s "
          f"({len(triples):,} triples over {D} shards)", file=sys.stderr)
    details = {}
    for k in range(1, 8):
        qn = f"lubm_q{k}"
        try:
            text = open(os.path.join(BASIC, qn)).read()
            # rep 1 pays compilation (reported separately as first_us —
            # round-4 verdict #3: the artifact must separate compile/retry
            # cost from steady state); steady = best of the next 3 reps,
            # which reuse the compiled chain via the plan-signature cache
            first, best, rows, status, empty = None, None, 0, 0, False
            for rep in range(4):
                q = Parser(ss).parse(text)
                planner.generate_plan(q)
                q.result.blind = True
                t = time.perf_counter()
                dist.execute(q, from_proxy=False)
                dt = (time.perf_counter() - t) * 1e6
                status = int(q.result.status_code)
                if status != 0:
                    first = best = None
                    break
                rows = q.result.nrows
                empty = bool(q.planner_empty)
                if rep == 0:
                    first = dt
                else:
                    best = dt if best is None else min(best, dt)
            d = {"us": max(round(best, 1), 0.1) if best is not None else None,
                 "first_us": (max(round(first, 1), 0.1)
                              if first is not None else None),
                 "rows": int(rows), "status": status,
                 "backend": backend, "scale": scale, "D": D}
            if empty:
                d["planner_empty"] = True
            elif best is not None:
                # per-step chain evidence + padded-traffic model for the
                # steady-state time (the first_us/us gap plus these fields
                # is the 42x diagnosis). mode discloses the route: light
                # const starts ride the owner-routed in-place fast path
                # (zero collectives, no compiled chain) by default
                st = dist.last_chain_stats
                d["mode"] = (st or {}).get("mode", "collective")
                if st is not None:
                    d["chain"] = st
                bm = dist.bytes_model()
                if bm:
                    d["bytes_model"] = bm
                    d["gbps"] = round(
                        bm["total_bytes"] / (best * 1e-6) / 1e9, 2)
        except Exception as e:  # one bad query must not kill the artifact
            d = {"us": None, "rows": 0, "status": -1, "error": repr(e),
                 "backend": backend, "scale": scale, "D": D}
        details[qn] = d
        dist.save_cap_memo(memo_path)  # per query: a crash keeps the rest
        print(f"# {qn}: {d['us']} us (first {d.get('first_us')}), "
              f"{d['rows']} rows", file=sys.stderr, flush=True)
    # planner-proved-empty queries short-circuit in ~us; including them
    # would deflate the geomean (same disclosure as the default mode)
    us = [d["us"] for d in details.values()
          if d["us"] and d["status"] == 0 and not d.get("planner_empty")]
    failed = [qn for qn, d in details.items()
              if d["status"] != 0 or d["us"] is None]
    empties = [qn for qn, d in details.items() if d.get("planner_empty")]
    ncores = os.cpu_count() or 1
    mesh_note = (f"{D}-chip ICI mesh" if platform == "tpu" else
                 f"{D} virtual devices sharing {ncores} host core(s) — "
                 "collectives and shard compute serialize")
    inplace_qs = [qn for qn, d in details.items()
                  if d.get("mode") == "inplace"]
    metric = (f"LUBM-{scale} L1-L7 STEADY-STATE geomean latency "
              f"(compiled shard_map chains for index-origin heavies; "
              f"owner-routed IN-PLACE host walk for light const starts"
              + (f" [{','.join(inplace_qs)}]" if inplace_qs else "")
              + f"; first_us + per-query mode in detail), distributed "
              f"engine on a {backend} mesh ({mesh_note}; baseline: "
              "reference 8-node CUDA @ LUBM-10240; not scale- or "
              "fabric-matched)")
    if empties:
        metric += f"; planner-empty, excluded: {','.join(empties)}"
    if failed:
        metric += f"; FAILED: {','.join(failed)}"
    _emit_final({
        "metric": metric,
        "value": round(_geomean(us), 1) if us else None,
        "unit": "us",
        "vs_baseline": None,
        "backend": backend,
        "dataset": DATASET_NOTES["lubm"],
        "detail": details,
    }, "BENCH_DIST_DETAIL.json")


def proc_main(device_ok: bool) -> None:
    """`bench.py --proc`: the multi-process rung of the BENCH_DIST trail —
    the same distributed world served twice over the same query stream:
    first on the default in-proc loopback transport, then with the worker
    pool live (process-per-shard-group, length-prefixed + CRC framed
    socket wire). Stagings are invalidated every round so each query's
    shard fetches actually cross the transport instead of a warm cache.
    Self-gates (WUKONG_PROC_NOGATE=1 skips): every socket reply must be
    byte-identical to its loopback twin, and the proc qps must land
    within 2x of the same-run in-proc number — the wire serialize/frame/
    syscall tax on a localhost hop, not a cross-host latency claim.
    Artifact: BENCH_PROC.json."""
    import tempfile

    import jax

    from wukong_tpu.config import Global
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
    from wukong_tpu.obs import get_registry
    from wukong_tpu.parallel.dist_engine import DistEngine
    from wukong_tpu.parallel.mesh import make_mesh
    from wukong_tpu.runtime.emulator import Emulator, _replies_identical
    from wukong_tpu.runtime.procs import ProcSupervisor
    from wukong_tpu.runtime.proxy import Proxy
    from wukong_tpu.store.gstore import build_all_partitions, build_partition

    D = min(8, len(jax.devices()))
    platform = jax.devices()[0].platform
    backend = f"{platform}-mesh-{D}"
    scale = int(os.environ.get("WUKONG_BENCH_SCALE", "0") or 0) or 1
    rounds = int(os.environ.get("WUKONG_PROC_ROUNDS", "6"))
    # the fetch path IS the measurement: no owner-routed in-place shortcut,
    # and the heartbeat stays out of the way (kill/restart is the chaos
    # drill's job, not the throughput rung's)
    Global.enable_tpu = False
    Global.enable_dist_inplace = False
    Global.proc_heartbeat_ms = 60_000
    t0 = time.time()
    triples, _ = generate_lubm(scale, seed=42)
    ss = VirtualLubmStrings(scale, seed=42)
    dist = DistEngine(build_all_partitions(triples, D), ss, make_mesh(D))
    g = build_partition(triples, 0, 1)
    proxy = Proxy(g, ss, CPUEngine(g, ss), None, dist)
    emu = Emulator(proxy)
    sstore = dist.sstore
    # probe mix: the synthesized one-hop index scan (None), a const-start
    # one-hop and a 2-hop join built from the dataset's own vocabulary
    # (self-contained — no reference checkout needed), plus the light
    # const-start LUBM query texts when reachable. Every probe must
    # execute cleanly on loopback or it is dropped from the stream
    from wukong_tpu.loader.lubm import UB
    from wukong_tpu.types import OUT

    probes: list = [None]
    anchors = np.asarray(g.get_index(ss.str2id(f"<{UB}advisor>"), OUT))
    if anchors.size:
        a = ss.id2str(int(anchors[0]))
        probes.append(f"SELECT ?x WHERE {{ ?x <{UB}advisor> {a} . }}")
        probes.append(f"SELECT ?x ?y WHERE {{ ?x <{UB}advisor> {a} . "
                      f"?x <{UB}memberOf> ?y . }}")
    for qn in ("lubm_q4", "lubm_q5", "lubm_q6"):
        try:
            probes.append(open(os.path.join(BASIC, qn)).read())
        except OSError:
            pass

    def ask(t):
        q = emu._drill_query(t)
        q.result.blind = False  # byte-identity needs the real table
        proxy._serve_execute(q, proxy.dist, pinned=True)
        return q

    probes = [t for t in probes
              if ask(t).result.status_code == 0]
    print(f"# proc world ready in {time.time() - t0:.0f}s "
          f"({len(triples):,} triples over {D} shards, "
          f"{len(probes)} probes)", file=sys.stderr)

    def measure(n_rounds: int):
        replies = []
        t0 = time.perf_counter()
        for _ in range(max(n_rounds, 1)):
            sstore.invalidate_stagings()
            for t in probes:
                replies.append(ask(t))
        dt = time.perf_counter() - t0
        return round(len(replies) / dt, 1), replies

    measure(1)  # warm parse/plan + staged shapes
    loopback_qps, oracle = measure(rounds)
    ckpt = tempfile.mkdtemp(prefix="wukong_bench_proc_")
    sup = ProcSupervisor(sstore, ckpt)
    t_spawn = time.time()
    sup.start()
    spawn_s = round(time.time() - t_spawn, 2)
    try:
        measure(1)  # warm the connections
        proc_qps, got = measure(rounds)
        identical = all(_replies_identical(a, b)
                        for a, b in zip(oracle, got))
        groups = {gid: sorted(grp.shard_ids)
                  for gid, grp in sup.groups.items()}
        mode = sstore.transport.mode
    finally:
        sup.stop()
    _post_qps, post = measure(1)
    loopback_restored = all(
        _replies_identical(oracle[k % len(probes)], q)
        for k, q in enumerate(post))
    snap = get_registry().snapshot()
    transport_metrics = {
        name: [{**s["labels"], "value": s["value"]}
               for s in snap.get(name, {}).get("series", [])]
        for name in ("wukong_transport_messages_total",
                     "wukong_transport_bytes_total")}
    overhead_x = (round(loopback_qps / proc_qps, 2)
                  if proc_qps else None)
    _emit_final({
        "metric": f"LUBM-{scale} multi-process serving throughput "
                  f"({D} shards over {len(groups)} worker processes, "
                  "framed socket transport, stagings invalidated every "
                  "round; gated byte-identical and within 2x of the "
                  "same-run in-proc loopback rung)",
        "value": proc_qps,
        "unit": "q/s",
        "proc_qps": proc_qps,
        "loopback_qps": loopback_qps,
        "overhead_x": overhead_x,
        "identical": identical,
        "backend": backend,
        "detail": {
            "rounds": rounds, "probes": len(probes), "scale": scale,
            "groups": {str(k): v for k, v in groups.items()},
            "transport_mode_under_pool": mode,
            "loopback_restored": loopback_restored,
            "spawn_s": spawn_s,
            "knobs": {"proc_workers": Global.proc_workers,
                      "transport_max_frame_mb": Global.transport_max_frame_mb,
                      "transport_timeout_ms": Global.transport_timeout_ms},
            "transport_metrics": transport_metrics,
            "dataset": DATASET_NOTES["lubm"],
        },
    }, "BENCH_PROC.json")
    if os.environ.get("WUKONG_PROC_NOGATE") == "1":
        return
    if not identical:
        raise SystemExit(
            "proc rung FAILED: socket replies diverged from the loopback "
            "oracle — the wire must be byte-for-byte")
    if not loopback_restored:
        raise SystemExit(
            "proc rung FAILED: replies after stop() diverged — loopback "
            "must be restored untouched")
    if proc_qps * 2 < loopback_qps:
        raise SystemExit(
            f"proc rung FAILED: {proc_qps} q/s over the worker pool is "
            f"more than 2x below the in-proc rung ({loopback_qps} q/s)")


def _one_query_main() -> None:
    """`bench.py --one <qn>`: subprocess entry. The orchestrator has already
    probed the backend (env WUKONG_BENCH_BACKEND) and built the world caches;
    this process measures one query and prints its JSON detail as the last
    stdout line. Isolation means a TPU worker crash or a relay hang costs one
    query, not the whole round (the round-1 failure mode)."""
    qn = sys.argv[sys.argv.index("--one") + 1]
    scale = int(os.environ.get("WUKONG_BENCH_SCALE") or 160)
    device_ok = os.environ.get("WUKONG_BENCH_BACKEND", "tpu") == "tpu"
    _setup_jax_caches()
    _apply_kernel_toggles()
    if not device_ok:
        import jax

        jax.config.update("jax_platforms", "cpu")
    print(json.dumps(_measure_one(qn, scale)))


def devicecost_main(device_ok: bool) -> None:
    """`bench.py --devicecost`: device-observatory cost accounting over
    the cyclic device-route suite, run TWICE in-process. The first pass
    pays every jit variant cold (compile included); the second reuses
    them — the compile ledger must show the amortization (second-pass
    cold count strictly below the first). Headline: whole-suite padding
    efficiency (live rows / pad_pow2 padded capacity over every charged
    dispatch), reported per capacity class in detail. Self-gates: the
    route stayed device, efficiency recorded for every minted capacity
    class, cold amortization, and the residency high-water within
    `device_budget_mb`. Artifact: BENCH_DEVICE.json
    (WUKONG_DEVICE_NOGATE=1 records without gating)."""
    from wukong_tpu.config import Global
    from wukong_tpu.join.wcoj import WCOJExecutor
    from wukong_tpu.loader.datagen import (
        generate_clique4,
        generate_diamond,
        generate_triangle,
    )
    from wukong_tpu.obs.device import get_device_obs, read_device_input
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.planner.stats import Stats
    from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
    from wukong_tpu.store.gstore import build_partition
    from wukong_tpu.types import OUT
    from wukong_tpu.utils.timer import get_usec

    m_tri = int(os.environ.get("WUKONG_DEVICECOST_M", "800"))
    reps = int(os.environ.get("WUKONG_DEVICECOST_REPS", "2"))
    Global.enable_device_obs = True
    Global.join_device = "device"
    Global.wcoj_min_rows = 1
    Global.wcoj_ratio = 1

    def mkq(spec):
        q = SPARQLQuery()
        q.pattern_group.patterns = [Pattern(s, p, OUT, o)
                                    for (s, p, o) in spec["patterns"]]
        q.result.nvars = len(spec["vars"])
        q.result.required_vars = list(spec["vars"])
        q.result.blind = True
        return q

    worlds = [
        ("triangle", *generate_triangle(m=m_tri, noise=8, seed=0)),
        ("diamond", *generate_diamond(m=300, noise=4, seed=0)),
        ("clique4", *generate_clique4(n=800, fan=8, ncliques=30, seed=0)),
    ]
    suites = []
    for name, triples, spec in worlds:
        g = build_partition(triples, 0, 1)
        stats = Stats.generate(triples)
        suites.append((name, WCOJExecutor(g, stats=stats),
                       Planner(stats), spec))

    obs = get_device_obs()
    obs.reset()
    routes_device = True

    def run_pass() -> float:
        nonlocal routes_device
        t0 = get_usec()
        for name, ex, planner, spec in suites:
            for _ in range(reps):
                q = mkq(spec)
                planner.generate_plan(q)
                ex.execute(q)
                assert q.result.status_code == 0, (name,
                                                   q.result.status_code)
                levels = getattr(q, "join_stats", []) or []
                if not levels or any(lv.get("route") != "device"
                                     for lv in levels):
                    routes_device = False
        return round((get_usec() - t0) / 1e3, 1)

    pass1_ms = run_pass()
    c1 = read_device_input("dispatches")
    pass2_ms = run_pass()
    c2 = read_device_input("dispatches")
    pass1_cold, pass2_cold = c1["cold"], c2["cold"] - c1["cold"]

    # padding efficiency per pad_pow2 capacity class, over both passes
    per_class: dict = {}
    for r in obs.dispatch_ledger.report(1_000_000):
        if not r["padded_rows"]:
            continue
        a = per_class.setdefault(r["capacity"], [0, 0])
        a[0] += r["live_rows"]
        a[1] += r["padded_rows"]
    padding_by_class = {str(c): round(lv / pad, 4)
                        for c, (lv, pad) in sorted(per_class.items())}
    eff = read_device_input("padding_efficiency")
    res = obs.residency.stats()
    high_water_mb = round(res["high_water_bytes"] / (1 << 20), 3)

    _emit_final({
        "metric": f"device observatory: padding efficiency over the "
                  f"cyclic device-route suite run twice (triangle "
                  f"m={m_tri} + diamond + clique4, reps={reps}; cold "
                  "amortization + residency budget self-gated)",
        "value": round(eff, 4) if eff is not None else None,
        "unit": "ratio",
        "padding_efficiency": round(eff, 4) if eff is not None else None,
        "pass1_cold": pass1_cold,
        "pass2_cold": pass2_cold,
        "dispatches": c2["count"],
        "residency_high_water_mb": high_water_mb,
        "device_budget_mb": int(Global.device_budget_mb),
        "backend": "tpu" if device_ok else "cpu",
        "detail": {
            "padding_efficiency_by_capacity": padding_by_class,
            "pass1_ms": pass1_ms, "pass2_ms": pass2_ms,
            "dispatch_counts": c2,
            "variants": read_device_input("variants"),
            "residency": res,
            "ranked": obs.dispatch_ledger.report(20),
            "routes_device": routes_device,
            "knobs": {"device_budget_mb": int(Global.device_budget_mb),
                      "device_variant_limit":
                          int(Global.device_variant_limit),
                      "reps": reps, "m_tri": m_tri},
        },
    }, "BENCH_DEVICE.json")
    if os.environ.get("WUKONG_DEVICE_NOGATE") != "1":
        if not routes_device:
            raise SystemExit(
                "devicecost drill FAILED: a level left the device route "
                "— the observatory measured a degraded run")
        if eff is None or not padding_by_class:
            raise SystemExit(
                "devicecost drill FAILED: no padding efficiency recorded "
                "— the dispatch seam never charged a capacity class")
        if pass2_cold >= pass1_cold:
            raise SystemExit(
                f"devicecost drill FAILED: second-pass cold dispatches "
                f"({pass2_cold}) not strictly below the first "
                f"({pass1_cold}) — jit variants are not being reused")
        if res["high_water_bytes"] > res["budget_bytes"]:
            raise SystemExit(
                f"devicecost drill FAILED: residency high-water "
                f"{high_water_mb} MiB exceeds device_budget_mb "
                f"{Global.device_budget_mb}")


def main():
    if "--one" in sys.argv:
        _one_query_main()
        return
    if "--at-scale-verify" in sys.argv:
        _at_scale_verify_main()
        return
    if "--at-scale" in sys.argv:
        at_scale_main()
        return
    if "--dist" in sys.argv:
        # the virtual-device flag must land before JAX initializes any
        # backend (same discipline as tests/conftest.py)
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        _setup_jax_caches()
        import jax

        if os.environ.get("WUKONG_DIST_TPU") != "1":
            jax.config.update("jax_platforms", "cpu")
        dist_main()
        return
    if "--proc" in sys.argv:
        # same virtual-mesh discipline as --dist: the flag must land
        # before JAX initializes any backend
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        _setup_jax_caches()
        import jax

        if os.environ.get("WUKONG_DIST_TPU") != "1":
            jax.config.update("jax_platforms", "cpu")
        proc_main(os.environ.get("WUKONG_DIST_TPU") == "1")
        return
    if "--emu" in sys.argv and "WUKONG_BENCH_BACKEND" in os.environ:
        # spawned by the default-mode orchestrator, which already probed:
        # honor its verdict instead of burning this subprocess's deadline
        # re-probing a dead relay (same contract as the --one entry)
        device_ok = os.environ["WUKONG_BENCH_BACKEND"] == "tpu"
    else:
        device_ok = _probe_backend()
    _setup_jax_caches()
    _apply_kernel_toggles()
    if not device_ok:
        # sitecustomize already registered the axon plugin at startup; the
        # config update (not env vars) is what pins the CPU backend now.
        import jax

        jax.config.update("jax_platforms", "cpu")
    if "--micro" in sys.argv:
        micro_main(device_ok)
        return
    if "--serve-batched" in sys.argv:
        serve_main(device_ok)
        return
    if "--serve-mixed" in sys.argv:
        serve_mixed_main(device_ok)
        return
    if "--graphrag" in sys.argv:
        graphrag_main(device_ok)
        return
    if "--emu" in sys.argv:
        emu_main(device_ok)
        return
    if "--cyclic" in sys.argv:
        cyclic_main(device_ok)
        return
    if "--devicecost" in sys.argv:
        devicecost_main(device_ok)
        return
    if "--tenants" in sys.argv:
        tenants_main(device_ok)
        return
    if "--hotspot" in sys.argv:
        hotspot_main(device_ok)
        return
    if "--rebalance" in sys.argv:
        rebalance_main(device_ok)
        return
    if "--readmostly" in sys.argv:
        readmostly_main(device_ok)
        return
    if "--watdiv" in sys.argv:
        watdiv_main(device_ok)
        return
    if "--dbpedia" in sys.argv:
        dbpedia_main(device_ok)
        return
    if "--yago" in sys.argv:
        yago_main(device_ok)
        return
    scale = int(os.environ.get("WUKONG_BENCH_SCALE", "0"))
    if scale == 0:
        from wukong_tpu.loader.lubm import DATASET_VERSION

        v = f"v{DATASET_VERSION}"
        scale = 2560 if (
            os.path.exists(os.path.join(CACHE, f"lubm2560_{v}_p0.npz"))
            or os.path.exists(
                os.path.join(REPO, f".cache_lubm2560_{v}_triples.npy"))
        ) else 160
    target_scale = scale  # the scale TPU partials are looked up at
    queries = [f"lubm_q{k}" for k in range(1, 8)]
    # queries already covered by a persisted on-chip measurement need no
    # same-run fallback; only still-missing ones run on the CPU backend
    tpu_partials = {qn: _best_tpu_partial(target_scale, qn) for qn in queries}
    if not device_ok:
        missing = [qn for qn in queries if tpu_partials[qn] is None]
        if missing and scale > 40:
            print(f"# cpu-fallback: clamping scale {scale} -> 40 for "
                  f"{len(missing)} queries without persisted TPU results "
                  "(single-core host must still capture a number)",
                  file=sys.stderr)
            scale = 40
        run_queries = missing
    else:
        run_queries = queries
    # fast-first run order (assembly keeps the canonical q1..q7 indexing):
    # lights bank numbers in minutes; on a degraded relay the old q1-first
    # order burned 45 min of a live window on three heavy timeouts before
    # the first light even started
    order = (os.environ.get("WUKONG_BENCH_ORDER")
             or "lubm_q4,lubm_q5,lubm_q6,lubm_q2,lubm_q7,lubm_q3,lubm_q1"
             ).split(",")
    run_queries = sorted(
        run_queries,
        key=lambda qn: order.index(qn) if qn in order else len(order))
    if run_queries:
        t0 = time.time()
        g, ss, stats = _ensure_world(scale)  # builds .cache/ artifacts once
        print(f"# world ready in {time.time() - t0:.0f}s "
              f"({g.stats_str()})", file=sys.stderr)
        del g, ss, stats

    # Each query measures in its own subprocess with a hard deadline: a TPU
    # worker crash ("kernel fault") or an indefinitely-hung relay costs that
    # one query, and the round still records every other number (round-1
    # ended with parsed:null; never again). The persistent XLA cache keeps
    # the per-process compile cost to one cold run.
    import subprocess

    q_deadline = int(os.environ.get(
        "WUKONG_QUERY_TIMEOUT", "900" if device_ok else "600"))
    env = dict(os.environ,
               WUKONG_BENCH_SCALE=str(scale),
               WUKONG_BENCH_BACKEND="tpu" if device_ok else "cpu")
    run_backend = "tpu" if device_ok else "cpu"
    details = {}
    failed = []
    # global soft deadline: the driver runs this once per round with its own
    # (unknown) timeout; printing the JSON line with whatever was captured
    # ALWAYS beats being killed mid-run with nothing (round-1 parsed:null)
    t_bench0 = time.time()
    soft_deadline = int(os.environ.get("WUKONG_BENCH_DEADLINE", "5400"))
    for qn in run_queries:
        if time.time() - t_bench0 > soft_deadline:
            failed.append(qn)
            details[qn] = {"error": "skipped: bench soft deadline"}
            print(f"# {qn}: skipped (soft deadline {soft_deadline}s)",
                  file=sys.stderr)
            continue
        print(f"# [{time.strftime('%H:%M:%S')}] {qn} starting",
              file=sys.stderr, flush=True)
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--one", qn],
                env=env, timeout=q_deadline, capture_output=True)
            if r.returncode != 0:
                raise RuntimeError(
                    f"rc={r.returncode}: {r.stderr.decode()[-300:]}")
            d = json.loads(r.stdout.decode().strip().splitlines()[-1])
        except subprocess.TimeoutExpired:
            failed.append(qn)
            details[qn] = {"error": f"timeout after {q_deadline}s"}
            print(f"# {qn}: TIMEOUT ({q_deadline}s)", file=sys.stderr)
            continue
        except Exception as e:  # one bad query must not zero the whole bench
            failed.append(qn)
            details[qn] = {"error": str(e)[:300]}
            print(f"# {qn}: FAILED ({e})", file=sys.stderr)
            continue
        d["backend"] = run_backend
        d["scale"] = scale
        _record_partial(scale, qn, run_backend, d)
        details[qn] = d
        print(f"# {qn}: {d['us']:,.0f} us (rows={d['rows']}, "
              f"batch={d['batch']})", file=sys.stderr)

    # throughput half of the metric (round-2 verdict item 3): a sparql-emu
    # pass in its own subprocess; it persists its own partial on success
    emu_detail = None
    if os.environ.get("WUKONG_SKIP_EMU") != "1" \
            and time.time() - t_bench0 <= soft_deadline:
        try:
            print(f"# [{time.strftime('%H:%M:%S')}] sparql-emu starting",
                  file=sys.stderr, flush=True)
            r = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py"), "--emu"],
                env=env, timeout=900 if device_ok else 400,
                capture_output=True)
            emu_detail = json.loads(
                r.stdout.decode().strip().splitlines()[-1])
            print(f"# sparql-emu: {emu_detail['value']:,.0f} q/s",
                  file=sys.stderr)
        except Exception as e:
            print(f"# sparql-emu pass failed: {e}", file=sys.stderr)

    # assemble: per query prefer the best persisted TPU measurement at the
    # target scale (includes this run's, when on-chip) over any CPU fallback
    lat_us, ref_us = [], []  # ref entries for the SAME surviving queries
    n_parity = 0  # planner-empty queries: ratio 1.0 contributions
    backends_used, scales_used = set(), set()
    partial_store = _load_partial()  # one read serves the whole assembly
    for i, qn in enumerate(queries):
        best_tpu = _best_tpu_partial(target_scale, qn, partial_store)
        d = best_tpu and dict(best_tpu, backend="tpu", scale=target_scale)
        if d is None:
            d = details.get(qn)
        if d is None or "error" in d:
            if qn not in failed:
                failed.append(qn)
            details[qn] = d or {"error": "not measured"}
            continue
        if qn in failed:  # a persisted partial covered this run's failure
            failed.remove(qn)
        ab = _ab_partials(target_scale, qn, partial_store)
        if ab:
            d["ab_us"] = ab  # kernel A/B comparison points (on-chip only)
        details[qn] = d
        backends_used.add(d["backend"])
        scales_used.add(d["scale"])
        if d.get("planner_empty"):
            # short-circuited here; the reference also short-circuits
            # provably-empty queries (planner.hpp:1505-1509) but its
            # PUBLISHED number measured full execution — not a comparable
            # pair. Round-4 verdict weak #5: count the query at PARITY in
            # the ratio (contributes 1.0) instead of dropping it, and keep
            # it out of the displayed latency geomean (a ~0.1 us entry
            # would deflate the value without information).
            d["ratio_parity"] = ("planner-proved empty: counted at 1.0 in "
                                 "vs_baseline, excluded from the latency "
                                 "geomean")
            n_parity += 1
            continue
        lat_us.append(d["us"])
        ref_us.append(REF_GPU_LUBM2560[i])
    if not lat_us:
        raise SystemExit("all bench queries failed")

    ours = _geomean(lat_us)
    ref = _geomean(ref_us)
    # ratio over ALL surviving queries: comparable pairs contribute
    # ref/ours, planner-empty pairs contribute exactly 1.0 — algebraically
    # the comparable-set ratio raised to its share of the query count
    n_ratio = len(lat_us) + n_parity
    ratio = float((ref / ours) ** (len(lat_us) / max(n_ratio, 1)))
    backend = ("tpu" if backends_used == {"tpu"}
               else "cpu" if backends_used == {"cpu"} else "mixed")
    scale_str = "/".join(str(s) for s in sorted(scales_used))
    # honest ratio (round-2 verdict Weak #1): the baseline was measured at
    # LUBM-2560 on the reference's accelerator; a ratio is only defensible
    # when every surviving query ran on-chip at that same scale
    default_toggles = _toggles_key() == ",".join(
        f"{k}={d}" for k, d in _TOGGLE_DEFAULTS)
    comparable = (backend == "tpu" and scales_used == {2560}
                  and default_toggles)
    label = {"tpu": "TPU single chip", "cpu": "cpu-fallback",
             "mixed": "mixed TPU + cpu-fallback"}[backend]
    # merge the throughput figure: best persisted on-chip first, then this
    # run's pass (lat_us/vs_baseline stay latency-only; q/s rides in detail)
    best_emu = _best_tpu_partial(target_scale, "sparql_emu")
    if best_emu is not None:
        details["sparql_emu"] = dict(best_emu, backend="tpu")
    elif emu_detail is not None:
        details["sparql_emu"] = {
            "qps": emu_detail["value"], "backend": emu_detail["backend"],
            "vs_baseline_qps": emu_detail["vs_baseline"],
            "metric": emu_detail["metric"]}

    other_tpu = _other_scale_tpu_evidence(target_scale, queries,
                                          partial_store)
    if other_tpu:
        details["tpu_at_other_scales_us"] = other_tpu

    excl = [qn for qn in queries
            if isinstance(details.get(qn), dict)
            and details[qn].get("ratio_parity")]
    _emit_final({
        "metric": f"LUBM-{scale_str} L1-L7 geomean latency, {label}, blind,"
                  f" all queries batched (lights x{BATCH}, heavies x fit;"
                  f" baseline: reference CUDA engine @ LUBM-2560)"
                  + (f"; planner-empty, at parity in ratio, out of the "
                     f"latency geomean: {','.join(excl)}" if excl else "")
                  + (f"; FAILED: {','.join(failed)}" if failed else ""),
        "value": round(ours, 1),
        "unit": "us",
        "vs_baseline": round(ratio, 3) if comparable else None,
        "backend": backend,
        "dataset": DATASET_NOTES["lubm"],
        **({} if default_toggles else {"toggles": _toggles_key()}),
        "detail": details,
    }, "BENCH_DETAIL.json")


if __name__ == "__main__":
    main()
