#!/usr/bin/env python
"""LUBM-10240 on the CPU backend, one process, in RAM (round-4 verdict #3).

The north-star scale (BASELINE.json: reference 5-node CUDA cluster,
S5C24(MEEPO)-LUBM10240-20181212.md:130-152) cannot be cached on this VM's
disk (~68 GB store > free space), so everything happens in one process:
synthesize -> build a single partition (versatile off: no query in L1-L7
needs the combined adjacency, and it saves ~22 GB) -> measure the lights
batched through the merge executor + as many heavies as the time budget
allows -> oracle-verify by sampled per-constant counts against the CPU
engine (lights) / a time-boxed CPU run (heavies).

Writes BENCH_10240_CPU.json (compact) + BENCH_10240_DETAIL.json at the repo
root. Peak RSS is logged per phase; the 125 GB host fits the int64 build
with versatile off (HBM_BUDGET.md "LUBM-10240 exact planning headers").

Usage: detached, one at a time on this 1-core host:
  setsid python scripts/at_scale_10240.py > .cache/at10240.log 2>&1 &
Env: WUKONG_10240_QUERIES (csv, default q4,q5,q6,q3,q2,q7,q1),
     WUKONG_10240_BUDGET_S (wall budget for the query/oracle loop,
     counted from store-build completion — the build pipeline alone is
     hours at this scale; default 7200),
     WUKONG_ORACLE_TIMEOUT (heavy CPU-oracle box, default 3600),
     WUKONG_10240_CACHE_GB (device-segment cache budget, default 32 —
     host RAM plays the device here; lower it on smaller hosts).
"""

import json
import os
import resource
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SCALE = int(os.environ.get("WUKONG_10240_SCALE", "10240"))  # override = smoke
BASIC = "/root/reference/scripts/sparql_query/lubm/basic"
BATCH = 1024


def rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 2**20


def log(msg: str) -> None:
    print(f"# [{time.strftime('%H:%M:%S')}] {msg} (peak rss {rss_gb():.1f} GB)",
          file=sys.stderr, flush=True)


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from bench import DATASET_NOTES, _emit_final, _geomean
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.planner.stats import Stats
    from wukong_tpu.sparql.parser import Parser
    from wukong_tpu.store.gstore import build_partition
    from wukong_tpu.types import NORMAL_ID_START
    from wukong_tpu.utils.compilecache import setup_persistent_cache

    setup_persistent_cache()
    # device-cache budget: the default Global.tpu_mem_cache_gb = 4 models
    # v5e HBM, but this run's "device" IS host RAM — keeping the 4 GB
    # budget just measures LRU re-staging of the ~4 GB start segments
    # (first run: q4 at 139 ms/query, pure restage). The v5e-8 fit
    # question is answered by BUDGET_10240.json (per-chip 1/8 shards),
    # not by throttling this artifact.
    from wukong_tpu.config import Global

    # 32 GB covers the ENTIRE padded store (~28 GB int32) with margin, so
    # nothing ever restages, while capping worst-case RSS at
    # store + cache + stats + chain buffers ≈ 75 GB on this 125 GB host
    Global.tpu_mem_cache_gb = int(
        os.environ.get("WUKONG_10240_CACHE_GB", "32"))
    budget_s = int(os.environ.get("WUKONG_10240_BUDGET_S", "7200"))
    qnames = [f"lubm_{q}" if not q.startswith("lubm") else q
              for q in os.environ.get(
                  "WUKONG_10240_QUERIES",
                  "q4,q5,q6,q3,q2,q7,q1").split(",")]

    # disk-space-gated caches: generation + stats are ~75 min of 1-core
    # work per attempt; a crash or budget misjudgment must not pay them
    # twice. The int32 triples npy is ~15 GB, the stats npz ~5 GB — both
    # skipped when free disk is short (the in-RAM path still works).
    tri_cache = os.path.join(REPO, ".cache", f"lubm{SCALE}_i32_triples.npy")
    stats_cache = os.path.join(REPO, ".cache", f"lubm{SCALE}_stats.npz")

    def _free_gb(path=REPO) -> float:
        st = os.statvfs(path)
        return st.f_bavail * st.f_frsize / 2**30

    triples = None
    if os.path.exists(tri_cache):
        log(f"loading cached triples {tri_cache}")
        try:
            triples = np.load(tri_cache)
        except Exception as e:  # truncated/corrupt cache: regenerate
            log(f"triples cache unreadable ({e}); regenerating")
            os.unlink(tri_cache)
    if triples is None:
        log("synthesizing LUBM-10240")
        triples, _lay = generate_lubm(SCALE, seed=0)
        log(f"{len(triples):,} triples")
        # ids < 2^31 by the store contract (gstore.check_vid_range) —
        # asserted HERE because Stats.generate consumes the narrowed array
        # long before build_partition would catch a silent wrap. int32
        # halves every downstream sort/copy — the int64 run OOMed at 130 GB
        assert int(triples.max()) < 2**31 - 1, "ids overflow int32"
        triples = triples.astype(np.int32)
        log("narrowed to int32")
        need = triples.nbytes / 2**30 + 2
        if _free_gb() > need + 10:
            try:  # tmp + rename: a crash/ENOSPC mid-save must never leave
                # a truncated cache that aborts every later run at startup
                np.save(tri_cache + ".tmp.npy", triples)
                os.replace(tri_cache + ".tmp.npy", tri_cache)
                log(f"triples cached ({triples.nbytes / 2**30:.1f} GB)")
            except Exception as e:
                log(f"triples cache save failed: {e}")
        else:
            log(f"triples cache skipped (free {_free_gb():.0f} GB)")
    stats = None
    if os.path.exists(stats_cache):
        try:
            stats = Stats.load(stats_cache)
            log("stats loaded from cache")
        except Exception as e:
            log(f"stats cache unreadable ({e}); regenerating")
            os.unlink(stats_cache)
    if stats is None:
        stats = Stats.generate(triples)
        log("stats done")
        if _free_gb() > 20:
            try:
                stats.save(stats_cache + ".tmp")
                os.replace(stats_cache + ".tmp.npz", stats_cache)
                log("stats cached")
            except Exception as e:
                log(f"stats cache save failed: {e}")
    g = build_partition(triples, 0, 1, versatile=False)
    log(f"store built: {g.stats_str()}")
    del triples
    # the query/oracle budget starts NOW: at this scale the build pipeline
    # alone exceeds the old from-process-start budget, which would have
    # skipped every query and emitted an empty artifact
    t0 = time.time()

    ss = VirtualLubmStrings(SCALE, seed=0)
    eng = TPUEngine(g, ss, stats=stats)
    cpu = CPUEngine(g, ss)
    planner = Planner(stats)
    rng = np.random.default_rng(0)
    details, failed = {}, []

    for qn in qnames:
        if time.time() - t0 > budget_s:
            print(f"# {qn}: skipped (budget {budget_s}s)", file=sys.stderr)
            continue
        try:
            text = open(f"{BASIC}/{qn}").read()
            q = Parser(ss).parse(text)
            planner.generate_plan(q)
            q.result.blind = True
            if q.planner_empty:
                details[qn] = {"us": 0.1, "rows": 0, "planner_empty": True}
                log(f"{qn}: planner-proved empty")
                continue
            const_start = q.pattern_group.patterns[0].subject >= NORMAL_ID_START
            if const_start:
                bq = BATCH
                consts = np.full(
                    bq, q.pattern_group.patterns[0].subject, dtype=np.int64)
                best, rows = None, 0
                for trial in range(3):
                    qt = Parser(ss).parse(text)
                    planner.generate_plan(qt)
                    qt.result.blind = True
                    t = time.perf_counter()
                    counts = eng.execute_batch(qt, consts)
                    dt = (time.perf_counter() - t) * 1e6 / bq
                    rows = int(counts[0])
                    best = dt if best is None else min(best, dt)
                d = {"us": round(best, 1), "rows": rows, "batch": bq}
                # oracle: 8 sampled distinct constants through the SAME
                # planned chain vs single-instance CPU runs
                seg = g.segments.get(
                    (int(q.pattern_group.patterns[0].predicate),
                     int(q.pattern_group.patterns[0].direction)))
                ver = {"ok": True, "sampled": 0}
                if seg is not None and len(seg.keys):
                    picks = np.unique(seg.keys[rng.integers(
                        0, len(seg.keys), 8)])
                    qv = Parser(ss).parse(text)
                    planner.generate_plan(qv)
                    qv.result.blind = True
                    batch_counts = eng.execute_batch(
                        qv, np.asarray(picks, dtype=np.int64))
                    for i, c0 in enumerate(picks):
                        qc = Parser(ss).parse(text)
                        planner.generate_plan(qc)
                        qc.pattern_group.patterns[0].subject = int(c0)
                        qc.result.blind = True
                        cpu.execute(qc, from_proxy=False)
                        if qc.result.nrows != int(batch_counts[i]):
                            ver = {"ok": False, "const": int(c0),
                                   "merge": int(batch_counts[i]),
                                   "cpu": int(qc.result.nrows)}
                            break
                        ver["sampled"] = i + 1
                d["oracle"] = ver
            else:
                bq = eng.suggest_index_batch(q)
                best, rows = None, 0
                for trial in range(2):
                    qt = Parser(ss).parse(text)
                    planner.generate_plan(qt)
                    qt.result.blind = True
                    t = time.perf_counter()
                    counts = eng.execute_batch_index(qt, bq)
                    dt = (time.perf_counter() - t) * 1e6 / bq
                    rows = int(counts[0])
                    best = dt if best is None else min(best, dt)
                d = {"us": round(best, 1), "rows": rows, "batch": bq}
                # heavy oracle: time-boxed CPU run compares total rows
                box = int(os.environ.get("WUKONG_ORACLE_TIMEOUT", "3600"))
                if time.time() - t0 + box < budget_s * 1.5:
                    import signal

                    def bail(_s, _f):
                        raise TimeoutError()

                    qc = Parser(ss).parse(text)
                    planner.generate_plan(qc)
                    qc.result.blind = True
                    old = signal.signal(signal.SIGALRM, bail)
                    signal.alarm(box)
                    try:
                        cpu.execute(qc, from_proxy=False)
                        d["oracle"] = {"ok": qc.result.nrows == rows,
                                       "cpu": int(qc.result.nrows)}
                    except TimeoutError:
                        d["oracle"] = {"ok": None,
                                       "note": f"cpu oracle > {box}s"}
                    finally:
                        signal.alarm(0)
                        signal.signal(signal.SIGALRM, old)
            details[qn] = d
            log(f"{qn}: {d['us']:,.1f} us/query (rows={d['rows']}, "
                f"oracle={d.get('oracle')})")
        except Exception as e:
            failed.append(qn)
            details[qn] = {"error": str(e)[:300]}
            log(f"{qn}: FAILED {e!r:.200}")

    us = [d["us"] for d in details.values()
          if d.get("us") and not d.get("planner_empty")]
    bad = [qn for qn, d in details.items()
           if isinstance(d.get("oracle"), dict)
           and d["oracle"].get("ok") is False]
    os.chdir(REPO)
    obj = {
        "metric": f"LUBM-{SCALE} at-scale: {','.join(details)} on the CPU "
                  f"backend (single 1-core host, in-RAM build, no disk "
                  f"cache), oracle-sampled"
                  + (f"; FAILED: {','.join(failed)}" if failed else "")
                  + (f"; VERIFY-FAILED: {','.join(bad)}" if bad else ""),
        "value": round(_geomean(us), 1) if us else None,
        "unit": "us",
        "vs_baseline": None,
        "backend": "cpu",
        "scale": SCALE,
        "dataset": DATASET_NOTES["lubm"],
        "detail": details,
    }
    _emit_final(obj, "BENCH_10240_DETAIL.json")
    with open("BENCH_10240_CPU.json", "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.write("\n")
    log("done")


if __name__ == "__main__":
    main()
