#!/bin/bash
# Opportunistic on-chip bench capture (round-2 verdict "Next round" #1):
# probe the TPU backend on a loop all round long; whenever it answers, run
# bench.py from the frozen snapshot — every successful per-query measurement
# persists to .cache/bench_partial.json, so a mid-run relay death costs only
# the in-flight query. The final driver-run bench merges the best persisted
# TPU results.
REPO="$(cd "$(dirname "$0")/.." && pwd)"
SNAP="$REPO/.cache/benchsnap"
LOG="$REPO/.cache/bench_loop.log"
export WUKONG_CACHE_DIR="$REPO/.cache"
export WUKONG_BENCH_SCALE="${WUKONG_BENCH_SCALE:-2560}"
export WUKONG_PROBE_TIMEOUT=90
cd "$SNAP" || exit 1
PASS=0
while true; do
  if timeout 90 python -c "
import jax, jax.numpy as jnp, sys
jax.device_get(jnp.arange(2) + 1)
sys.exit(0 if jax.devices()[0].platform != 'cpu' else 1)" >/dev/null 2>&1; then
    # cycle kernel A/Bs so the partial store accumulates comparison points:
    # default first (the headline), then merge-off, stream-off, mhot-off,
    # then the heavy-batch HBM trade (2^26-row classes -> bigger B)
    case $((PASS % 5)) in
      0) AB="" ;;
      1) AB="WUKONG_ENABLE_MERGE=0" ;;
      2) AB="WUKONG_ENABLE_STREAM=0" ;;
      3) AB="WUKONG_ENABLE_STREAM_MHOT=0" ;;
      4) AB="WUKONG_CAP_MAX=67108864" ;;
    esac
    echo "[$(date +%F' '%T)] backend healthy -> bench @ LUBM-$WUKONG_BENCH_SCALE ${AB:-default}" >> "$LOG"
    env $AB timeout 10800 python bench.py >> "$LOG" 2>&1
    rc=$?  # captured before $(date) in the echo resets $?
    echo "[$(date +%F' '%T)] bench pass done (rc=$rc)" >> "$LOG"
    PASS=$((PASS + 1))
    sleep 60
  else
    echo "[$(date +%F' '%T)] backend unreachable" >> "$LOG"
    sleep 180
  fi
done
