#!/bin/bash
# Opportunistic on-chip bench capture (round-2 verdict "Next round" #1):
# probe the TPU backend on a loop all round long; whenever it answers, run
# bench.py from the frozen snapshot — every successful per-query trial
# persists to .cache/bench_partial.json, so a mid-run relay death costs only
# the in-flight trial. The final driver-run bench merges the best persisted
# TPU results.
#
# Scale ladder (added after the 2026-07-31 degraded-relay session, where a
# half-healthy tunnel timed out every query at LUBM-160 for 75 min): prove a
# full default pass at LUBM-40 first, then 160, then 2560. A rung escalates
# only after a pass banks at least one on-chip partial at its scale, so a
# degraded window keeps collecting numbers at the scale it can actually
# serve instead of burning itself on staging it can't finish. Kernel A/B
# arms cycle only at the top rung, after the default 2560 pass has banked.
REPO="$(cd "$(dirname "$0")/.." && pwd)"
SNAP="$REPO/.cache/benchsnap"
LOG="$REPO/.cache/bench_loop.log"
RUNG_FILE="$REPO/.cache/loop_rung"
export WUKONG_CACHE_DIR="$REPO/.cache"
export WUKONG_PROBE_TIMEOUT=90
cd "$SNAP" || exit 1
PASS=0
banked_at() {  # count persisted TPU partials at scale $1
  # second arg "default": only entries measured under default kernel
  # toggles (the helper runs OUTSIDE `env $AB`, so bench._toggles_key()
  # is the default string) — the A/B gate must not fire on arm-run or
  # pre-ladder entries
  # the gates at the call sites are numeric [ -gt ] tests: ANY failure here
  # must still print a well-formed 0, or the tests become bash syntax
  # errors that silently disable escalation and the A/B arms
  python - "$1" "${2:-any}" <<'EOF' 2>/dev/null || echo 0
import json, os, sys
try:
    store = json.load(open(os.path.join(os.environ["WUKONG_CACHE_DIR"],
                                        "bench_partial.json")))
    scale, mode = sys.argv[1], sys.argv[2]
    sys.path.insert(0, os.getcwd())
    from bench import _toggles_key
    suffix = f":tpu:{_toggles_key()}" if mode == "default" else ":tpu:"
    print(sum(1 for k in store
              if k.startswith(f"lubm{scale}v") and suffix in k))
except Exception:
    print(0)
EOF
}
while true; do
  if timeout 90 python -c "
import jax, jax.numpy as jnp, sys
jax.device_get(jnp.arange(2) + 1)
sys.exit(0 if jax.devices()[0].platform != 'cpu' else 1)" >/dev/null 2>&1; then
    RUNG=$(cat "$RUNG_FILE" 2>/dev/null || echo 0)
    case $RUNG in
      0) SCALE=40;   QT=1500 ;;
      1) SCALE=160;  QT=1500 ;;
      *) SCALE=2560; QT=2700 ;;
    esac
    AB=""
    if [ "$RUNG" -ge 2 ] && [ "$(banked_at 2560 default)" -gt 0 ]; then
      # top rung has its default numbers: cycle comparison arms
      case $((PASS % 5)) in
        1) AB="WUKONG_ENABLE_MERGE=0" ;;
        2) AB="WUKONG_ENABLE_STREAM=0" ;;
        3) AB="WUKONG_ENABLE_STREAM_MHOT=0" ;;
        4) AB="WUKONG_CAP_MAX=67108864" ;;
      esac
    fi
    echo "[$(date +%F' '%T)] backend healthy -> bench @ LUBM-$SCALE rung=$RUNG ${AB:-default}" >> "$LOG"
    BEFORE=$(banked_at "$SCALE")
    env $AB WUKONG_BENCH_SCALE=$SCALE WUKONG_QUERY_TIMEOUT=$QT \
        WUKONG_BENCH_DEADLINE=9000 timeout 10800 python bench.py >> "$LOG" 2>&1
    rc=$?  # captured before $(date) in the echo resets $?
    AFTER=$(banked_at "$SCALE")
    echo "[$(date +%F' '%T)] bench pass done (rc=$rc, banked $BEFORE->$AFTER at $SCALE)" >> "$LOG"
    # escalate on newly-banked on-chip keys, OR on a fully-completed pass
    # (rc=0) that has on-chip evidence at this scale — a healthy pass that
    # only IMPROVES already-banked entries leaves the key count unchanged
    # but still proves this rung serves. bench exits 0 on its internal
    # cpu-fallback too, hence the AFTER>0 guard: banked :tpu: keys only.
    if { [ "$AFTER" -gt "$BEFORE" ] || { [ "$rc" -eq 0 ] && [ "$AFTER" -gt 0 ]; }; } \
        && [ "$RUNG" -lt 2 ]; then
      echo $((RUNG + 1)) > "$RUNG_FILE"
      echo "[$(date +%F' '%T)] rung escalated to $((RUNG + 1))" >> "$LOG"
    fi
    PASS=$((PASS + 1))
    sleep 60
  else
    echo "[$(date +%F' '%T)] backend unreachable" >> "$LOG"
    sleep 180
  fi
done
