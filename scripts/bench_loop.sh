#!/bin/bash
# Opportunistic on-chip bench capture (round-2 verdict "Next round" #1):
# probe the TPU backend on a loop all round long; whenever it answers, run
# bench.py from the frozen snapshot — every successful per-query trial
# persists to .cache/bench_partial.json, so a mid-run relay death costs only
# the in-flight trial. The final driver-run bench merges the best persisted
# TPU results.
#
# Scale ladder (added after the 2026-07-31 degraded-relay session, where a
# half-healthy tunnel timed out every query at LUBM-160 for 75 min): prove a
# full default pass at LUBM-40 first, then 160, then 2560. A rung escalates
# only after a pass banks at least one on-chip partial at its scale, so a
# degraded window keeps collecting numbers at the scale it can actually
# serve instead of burning itself on staging it can't finish. Kernel A/B
# arms cycle only at the top rung, after the default 2560 pass has banked.
REPO="$(cd "$(dirname "$0")/.." && pwd)"
SNAP="$REPO/.cache/benchsnap"
LOG="$REPO/.cache/bench_loop.log"
RUNG_FILE="$REPO/.cache/loop_rung"
export WUKONG_CACHE_DIR="$REPO/.cache"
export WUKONG_PROBE_TIMEOUT=90
cd "$SNAP" || exit 1
PASS=0
# Reset the persisted ladder rung at loop startup (ADVICE.md round-5 #2):
# the rung only ever escalates within a session, so a stale top-rung file
# from a healthy round would send a later degraded-relay session straight
# to LUBM-2560 — the exact failure mode the ladder exists to prevent. Each
# session re-proves the lower rungs first (they are cheap when healthy).
rm -f "$RUNG_FILE"
banked_at() {  # TPU-partial evidence at scale $1
  # mode (arg 2): "any" counts :tpu: keys; "default" counts only entries
  # measured under default kernel toggles (the helper runs OUTSIDE
  # `env $AB`, so bench._toggles_key() is the default string — imported
  # only in this mode, so the escalation gates never depend on bench
  # importability); "sig" prints a hash over (key, us, ts) of the scale's
  # :tpu: entries — it changes when a pass banks a NEW key or IMPROVES an
  # existing one (_record_partial refreshes ts on replacement), and stays
  # put across passes that bank nothing, stale history included.
  # the gates at the call sites are numeric/string [ ] tests: ANY failure
  # here must still print a well-formed 0, or the tests become bash
  # errors that silently disable escalation and the A/B arms
  python - "$1" "${2:-any}" <<'EOF' 2>/dev/null || echo 0
import hashlib, json, os, sys
try:
    store = json.load(open(os.path.join(os.environ["WUKONG_CACHE_DIR"],
                                        "bench_partial.json")))
    scale, mode = sys.argv[1], sys.argv[2]
    if mode == "default":
        sys.path.insert(0, os.getcwd())
        from bench import _toggles_key
        suffix = f":tpu:{_toggles_key()}"
    else:
        suffix = ":tpu:"
    hits = {k: (store[k].get("us"), store[k].get("ts")) for k in store
            if k.startswith(f"lubm{scale}v") and suffix in k}
    if mode == "sig":
        blob = json.dumps(sorted(hits.items())).encode()
        print(int(hashlib.sha256(blob).hexdigest()[:12], 16) if hits else 0)
    else:
        print(len(hits))
except Exception:
    print(0)
EOF
}
while true; do
  if timeout 90 python -c "
import jax, jax.numpy as jnp, sys
jax.device_get(jnp.arange(2) + 1)
sys.exit(0 if jax.devices()[0].platform != 'cpu' else 1)" >/dev/null 2>&1; then
    RUNG=$(cat "$RUNG_FILE" 2>/dev/null || echo 0)
    case $RUNG in
      0) SCALE=40;   QT=1500 ;;
      1) SCALE=160;  QT=1500 ;;
      *) SCALE=2560; QT=2700 ;;
    esac
    AB=""
    if [ "$RUNG" -ge 2 ] && [ "$(banked_at 2560 default)" -gt 0 ]; then
      # top rung has its default numbers: cycle comparison arms
      case $((PASS % 5)) in
        1) AB="WUKONG_ENABLE_MERGE=0" ;;
        2) AB="WUKONG_ENABLE_STREAM=0" ;;
        3) AB="WUKONG_ENABLE_STREAM_MHOT=0" ;;
        4) AB="WUKONG_CAP_MAX=67108864" ;;
      esac
    fi
    echo "[$(date +%F' '%T)] backend healthy -> bench @ LUBM-$SCALE rung=$RUNG ${AB:-default}" >> "$LOG"
    BEFORE=$(banked_at "$SCALE" sig)
    PASS_LOG=$(mktemp)
    env $AB WUKONG_BENCH_SCALE=$SCALE WUKONG_QUERY_TIMEOUT=$QT \
        WUKONG_BENCH_DEADLINE=9000 timeout 10800 python bench.py > "$PASS_LOG" 2>&1
    rc=$?  # captured before anything else resets $?
    cat "$PASS_LOG" >> "$LOG"
    AFTER=$(banked_at "$SCALE" sig)
    # on-chip proof for this pass: the final headline labels backend tpu
    # only when every surviving query has on-chip evidence passing the
    # 24h freshness filter (prior-ROUND history can't fake it)
    # grep the WHOLE pass log, not tail -1: stdout and stderr are merged,
    # and any stderr after the headline JSON (JAX shutdown warnings, atexit
    # messages) would hide the backend line from a last-line check and
    # silently suppress the fully-green escalation path (ADVICE.md r5 #3)
    ONCHIP=0
    [ "$rc" -eq 0 ] && grep -q '^{.*"backend": *"tpu"' "$PASS_LOG" && ONCHIP=1
    rm -f "$PASS_LOG"
    echo "[$(date +%F' '%T)] bench pass done (rc=$rc, sig $BEFORE->$AFTER, onchip=$ONCHIP at $SCALE)" >> "$LOG"
    # escalate when THIS pass changed the scale's on-chip evidence (new
    # key banked or an entry improved — both move the sig), OR when a
    # fully-green pass proved the whole rung serves on-chip even without
    # beating the banked bests (sig alone would wedge the ladder at a low
    # rung forever once good numbers are on file). A cpu-fallback-only
    # pass moves neither: sig stays put and the headline says cpu.
    if { { [ "$AFTER" != "$BEFORE" ] && [ "$AFTER" != 0 ]; } || [ "$ONCHIP" = 1 ]; } \
        && [ "$RUNG" -lt 2 ]; then
      echo $((RUNG + 1)) > "$RUNG_FILE"
      echo "[$(date +%F' '%T)] rung escalated to $((RUNG + 1))" >> "$LOG"
    fi
    PASS=$((PASS + 1))
    sleep 60
  else
    echo "[$(date +%F' '%T)] backend unreachable" >> "$LOG"
    sleep 180
  fi
done
