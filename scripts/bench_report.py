#!/usr/bin/env python3
"""Consolidate the BENCH_*.json artifacts into one perf-trajectory table.

Seven PRs of benchmarks left ~30 ``BENCH_*.json`` files whose history is
only legible by diffing git. This script makes the trajectory a first-class
artifact:

- ``BENCH_TRAJECTORY.md`` — one markdown table per benchmark *series*
  (``BENCH_DIST_r03/r04/r05`` is the series ``DIST`` at rungs 3..5; files
  without a ``_rNN`` suffix are single-point series), newest rung last,
  with the delta vs the prior rung.
- ``BENCH_TRAJECTORY.json`` — the same, machine-readable (the next PR's
  rung appends instead of re-deriving).
- ``--check`` — exit non-zero when any series' newest rung regressed
  >``--threshold`` percent (default 20) against the prior rung. Direction
  comes from the unit: latency-like units (us/ms/ns) regress upward,
  rate-like units (q/s, rows/s) regress downward; unit-less series are
  reported but never fail the check.

Artifact shapes handled: headline files ({metric, value, unit, ...}),
bench_loop wrapper files ({parsed: {…headline…}, tail, rc}), and composite
files without a scalar headline (listed, excluded from the check).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

RUNG_RE = re.compile(r"^(BENCH(?:_[A-Za-z0-9]+)*?)_r(\d+)$")

#: secondary headlines: (field, unit) pairs an artifact may carry IN
#: ADDITION to its primary headline; each present field becomes its own
#: `<series>.<field>` trend series (e.g. BENCH_CYCLIC's
#: pentagon_device_speedup — the device-vs-host WCOJ win on the shape
#: whose loss was closing-level intersection cost — trends next to the
#: triangle walk-vs-wcoj primary instead of displacing it)
SECONDARY_HEADLINES = (
    ("pentagon_device_speedup", "speedup"),
    # BENCH_TENANT's protected-tenant q/s under the 2x-capacity
    # admission overload drill — the throughput the plane preserves for
    # the top weight class while bulk is shed
    ("protected_qps", "q/s"),
    # BENCH_GRAPHRAG's pure-scan device-vs-host ratio on the >=100k x
    # 128d brute-force k-NN block (unit "x" is direction-less here: on a
    # CPU-emulated backend the drill self-gates on the measured-demotion
    # path instead, so the ratio is trended but never threshold-checked)
    ("scan_device_vs_host", "x"),
    # ...and the pure-graph q/s share of the same mixed GraphRAG loop,
    # trended beside the hybrid headline so a vector-plane tax on graph
    # traffic shows up as a divergence between the two series
    ("graph_qps", "q/s"),
    # BENCH_CYCLIC's compiled-template rung: device<->host round trips
    # per query, per-step device route over the whole-plan fused program
    # (min across the large cyclic shapes; deterministic — cyclic_main
    # self-gates it >= 5x, so unit "x" trends it without a second check)
    ("compiled_device_vs_host", "x"),
    # BENCH_SERVE's whole-plan-compiled vs host-walk wall ratio on the
    # live serving path (unit "x" is direction-less: on the CPU backend
    # the sync chain the program deletes is nearly free, so the ratio is
    # trended, while serve_main gates the structural facts — programs
    # staged, rows identical, route chooser zero-touch)
    ("device_compiled_template", "x"),
)

LOWER_BETTER = ("us", "ms", "ns", "sec")
HIGHER_BETTER = ("q/s", "qps", "/s", "speedup")


def _direction(unit: str) -> int:
    """-1 lower-better, +1 higher-better, 0 unknown (never checked)."""
    u = (unit or "").lower()
    if any(tok in u for tok in HIGHER_BETTER):
        return 1
    if any(u.startswith(tok) or f"{tok}/" in u or u == tok
           for tok in LOWER_BETTER):
        return -1
    return 0


def _headline(d: dict) -> dict | None:
    """{value, unit, metric} from one artifact, unwrapping bench_loop
    wrappers; None when the file has no scalar headline."""
    if isinstance(d.get("parsed"), dict):
        d = d["parsed"]
    # hot-spot observatory drill: the heat plane's load-rate separation
    # (BENCH_HOTSPOT.json; unit "x" is direction-less — the scenario's
    # Zipf skew sets the number, so it is trended but never gated).
    # Checked BEFORE the generic value branch: the artifact also carries
    # a top-level "value", which would bury the short series name under
    # the long metric sentence
    if isinstance(d.get("hotspot_separation"), (int, float)):
        return {"value": float(d["hotspot_separation"]), "unit": "x",
                "metric": "hotspot_separation"}
    # rebalance drill: pre/post host load-rate imbalance across one
    # EXECUTED shard migration (BENCH_REBALANCE.json; unit "x" is
    # direction-less and the drill self-gates — bench.py --rebalance
    # exits non-zero unless post-move imbalance clears the threshold and
    # every mid-migration probe was byte-identical)
    if isinstance(d.get("rebalance_gain"), (int, float)):
        return {"value": float(d["rebalance_gain"]), "unit": "x",
                "metric": "rebalance_gain"}
    # read-mostly CACHED serving drill: real result-cache q/s with the
    # materialized-view plane armed (BENCH_READMOSTLY.json since PR 14;
    # the drill self-gates on byte-identity, real >= shadow hit rate,
    # >= 3x the PR 8 light-only baseline, and the flat write-rate
    # curve). Checked before predicted_hit_rate: the artifact still
    # carries the shadow ratio for the observe-only trend
    if isinstance(d.get("readmostly_qps"), (int, float)):
        return {"value": float(d["readmostly_qps"]), "unit": "q/s",
                "metric": "readmostly_qps"}
    # read-mostly serving-cache drill: the achievable version-keyed
    # result-cache hit rate on the Zipfian mix (BENCH_READMOSTLY.json;
    # unit "ratio" is direction-less — the drill self-gates at >= 0.5
    # with monotone write-rate degradation, so it is trended but never
    # threshold-checked here). Before the generic value branch for the
    # same reason as hotspot_separation
    if isinstance(d.get("predicted_hit_rate"), (int, float)):
        return {"value": float(d["predicted_hit_rate"]), "unit": "ratio",
                "metric": "predicted_hit_rate"}
    # device-observatory drill: whole-suite live/padded ratio over the
    # cyclic device route run twice (BENCH_DEVICE.json; unit "ratio" is
    # direction-less — the drill self-gates on cold amortization and
    # the residency budget, so it is trended but never threshold-checked
    # here). Before the generic value branch so the series keeps the
    # short name instead of the long metric sentence
    if isinstance(d.get("padding_efficiency"), (int, float)):
        return {"value": float(d["padding_efficiency"]), "unit": "ratio",
                "metric": "padding_efficiency"}
    # multi-process rung: serving qps over the worker pool's framed
    # socket transport (BENCH_PROC.json; the drill self-gates on
    # byte-identity with loopback and on landing within 2x of the
    # same-run in-proc number, so it is trended but never
    # threshold-checked here). Before the generic value branch so the
    # series keeps the short name instead of the long metric sentence
    if isinstance(d.get("proc_qps"), (int, float)):
        return {"value": float(d["proc_qps"]), "unit": "q/s",
                "metric": "proc_qps"}
    if isinstance(d.get("value"), (int, float)):
        return {"value": float(d["value"]), "unit": d.get("unit", ""),
                "metric": str(d.get("metric", ""))[:160]}
    # serving artifact: qps headline without a value field (mixed_qps:
    # the --serve-mixed light+heavy closed loop, BENCH_SERVE_MIXED.json;
    # tenant_qps: the --tenants multi-tenant SLO scenario, BENCH_TENANT.json)
    for key in ("batched_qps", "mixed_qps", "tenant_qps", "qps", "thpt_qps"):
        if isinstance(d.get(key), (int, float)):
            return {"value": float(d[key]), "unit": "q/s", "metric": key}
    # cyclic suite: the triangle walk-vs-wcoj ratio (BENCH_CYCLIC.json;
    # higher is better via the "speedup" unit)
    if isinstance(d.get("triangle_speedup"), (int, float)):
        return {"value": float(d["triangle_speedup"]), "unit": "speedup",
                "metric": "triangle_speedup"}
    return None


def collect(bench_dir: str) -> dict:
    """series -> {unit, metric, points: [{rung, file, value}] newest last,
    plus a list of headline-less composite files}."""
    series: dict[str, dict] = {}
    composites = []
    for path in sorted(glob.glob(os.path.join(bench_dir, "BENCH_*.json"))):
        base = os.path.splitext(os.path.basename(path))[0]
        if base == "BENCH_TRAJECTORY":
            continue  # this script's own output is not an input

        try:
            d = json.load(open(path))
        except (OSError, json.JSONDecodeError) as e:
            composites.append({"file": base, "note": f"unreadable: {e}"})
            continue
        m = RUNG_RE.match(base)
        name, rung = (m.group(1), int(m.group(2))) if m else (base, None)
        head = _headline(d)
        if head is None:
            composites.append({"file": base,
                               "note": "no scalar headline (composite)"})
            continue
        s = series.setdefault(name, {"unit": head["unit"],
                                     "metric": head["metric"], "points": []})
        s["points"].append({"rung": rung, "file": base,
                            "value": head["value"]})
        body = d["parsed"] if isinstance(d.get("parsed"), dict) else d
        for field, unit in SECONDARY_HEADLINES:
            if isinstance(body.get(field), (int, float)):
                s2 = series.setdefault(
                    f"{name}.{field}",
                    {"unit": unit, "metric": field, "points": []})
                s2["points"].append({"rung": rung, "file": base,
                                     "value": float(body[field])})
    for s in series.values():
        s["points"].sort(key=lambda p: (p["rung"] is not None, p["rung"]))
        s["direction"] = _direction(s["unit"])
    return {"series": series, "composites": composites}


def _delta_pct(prev: float, cur: float) -> float | None:
    if prev == 0:
        return None
    return (cur - prev) / prev * 100.0


def check(data: dict, threshold: float) -> list[str]:
    """Regression messages for series whose newest rung is worse than the
    prior rung by more than ``threshold`` percent."""
    bad = []
    for name, s in sorted(data["series"].items()):
        pts, d = s["points"], s["direction"]
        if len(pts) < 2 or d == 0:
            continue
        prev, cur = pts[-2], pts[-1]
        pct = _delta_pct(prev["value"], cur["value"])
        if pct is None:
            continue
        regressed = pct > threshold if d < 0 else pct < -threshold
        if regressed:
            bad.append(
                f"{name}: {prev['file']} -> {cur['file']} moved "
                f"{prev['value']:,.1f} -> {cur['value']:,.1f} {s['unit']} "
                f"({pct:+.1f}%, allowed ±{threshold:.0f}% "
                f"{'lower' if d < 0 else 'higher'}-is-better)")
    return bad


def render_md(data: dict, threshold: float) -> str:
    lines = [
        "# BENCH trajectory",
        "",
        "Consolidated view of every `BENCH_*.json` headline across PR "
        "rungs (`scripts/bench_report.py`; regenerate after adding a "
        "rung). `Δ%` compares each rung to the prior one; `--check` "
        f"fails the build past ±{threshold:.0f}% in the unit's regression "
        "direction.",
        "",
        "| series | unit | rung trail (oldest → newest) | latest | Δ% vs prior |",
        "|---|---|---|---:|---:|",
    ]
    for name, s in sorted(data["series"].items()):
        pts = s["points"]
        trail = " → ".join(
            (f"r{p['rung']:02d}:" if p["rung"] is not None else "")
            + f"{p['value']:,.1f}" for p in pts)
        latest = pts[-1]
        pct = (_delta_pct(pts[-2]["value"], latest["value"])
               if len(pts) >= 2 else None)
        arrow = "" if s["direction"] == 0 or pct is None else (
            " ⚠" if (pct > threshold if s["direction"] < 0
                     else pct < -threshold) else "")
        lines.append(
            f"| {name} | {s['unit'] or '-'} | {trail} "
            f"| {latest['value']:,.1f} "
            f"| {'-' if pct is None else f'{pct:+.1f}%'}{arrow} |")
    if data["composites"]:
        lines += ["", "Composite artifacts (no scalar headline, not "
                      "trended): "
                  + ", ".join(f"`{c['file']}`" for c in data["composites"])]
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=None,
                    help="directory holding BENCH_*.json (default: repo root)")
    ap.add_argument("--out", default=None,
                    help="output directory (default: same as --dir)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on a >threshold%% regression vs the "
                         "newest prior rung")
    ap.add_argument("--threshold", type=float, default=20.0)
    ns = ap.parse_args(argv)
    bench_dir = ns.dir or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    out_dir = ns.out or bench_dir
    data = collect(bench_dir)
    data["threshold_pct"] = ns.threshold
    md = render_md(data, ns.threshold)
    with open(os.path.join(out_dir, "BENCH_TRAJECTORY.md"), "w") as f:
        f.write(md)
    with open(os.path.join(out_dir, "BENCH_TRAJECTORY.json"), "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
    print(f"bench-report: {len(data['series'])} series, "
          f"{len(data['composites'])} composites -> "
          f"{os.path.join(out_dir, 'BENCH_TRAJECTORY.md')}")
    if ns.check:
        bad = check(data, ns.threshold)
        for b in bad:
            print(f"REGRESSION: {b}", file=sys.stderr)
        if bad:
            return 1
        print(f"bench-report: no series regressed past "
              f"{ns.threshold:.0f}% vs its prior rung")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
