"""Streaming micro-benchmark: sustained insert throughput + standing-query lag.

Replays a seeded LUBM datagen graph into a DynamicGStore as fixed-size epoch
batches — first bare (ingest-only inserts/sec), then with standing queries
registered (per-epoch eval latency and commit-to-results lag from the
Monitor's stream CDFs). Emits BENCH_STREAM.json next to the other BENCH_*
artifacts.

Usage:
    JAX_PLATFORMS=cpu python scripts/bench_stream.py \
        [--scale 1] [--batch 4096] [--base-frac 0.5] [--out BENCH_STREAM.json]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

STANDING = {
    "onehop": """
PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
SELECT ?X ?Y WHERE { ?X ub:memberOf ?Y . }""",
    "chain2": """
PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
SELECT ?X ?Y ?Z WHERE {
    ?X ub:memberOf ?Y .
    ?Y ub:subOrganizationOf ?Z .
}""",
    "const_type": """
PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
SELECT ?X WHERE {
    ?X ub:worksFor <http://www.Department0.University0.edu> .
    ?X rdf:type ub:FullProfessor .
}""",
}


def _run(base, live, ss, batch, queries):
    from wukong_tpu.runtime.monitor import Monitor
    from wukong_tpu.store.gstore import build_partition
    from wukong_tpu.stream import ReplaySource, StreamContext

    mon = Monitor()
    ctx = StreamContext([build_partition(base, 0, 1)], ss, monitor=mon)
    qids = {name: ctx.register(text) for name, text in queries.items()}
    t0 = time.perf_counter()
    recs = ctx.feed_source(ReplaySource(live, batch_size=batch))
    wall_s = time.perf_counter() - t0
    stats = mon.stream_stats()
    return {
        "epochs": len(recs),
        "triples_streamed": int(sum(r.n_triples for r in recs)),
        "edges_inserted": int(sum(r.n_inserted for r in recs)),
        "wall_s": wall_s,
        "inserts_per_s": sum(r.n_triples for r in recs) / wall_s,
        "epochs_per_s": len(recs) / wall_s,
        "ingest_us_cdf": stats["ingest_us_cdf"],
        "eval_us_cdf": stats["eval_us_cdf"],
        "lag_us_cdf": stats["lag_us_cdf"],
        "standing_rows": {name: int(len(ctx.result_set(qid)))
                          for name, qid in qids.items()},
    }


def _traced_sample(base, live, ss, batch, queries, epochs=8):
    """A short traced replay (tracing ON, outside the timed runs): the
    artifact's per-phase breakdown — ingest vs eval vs per-query eval time
    aggregated over `epochs` epoch traces from the flight recorder."""
    from wukong_tpu.config import Global
    from wukong_tpu.obs import get_recorder
    from wukong_tpu.store.gstore import build_partition
    from wukong_tpu.stream import ReplaySource, StreamContext

    prev = Global.enable_tracing
    Global.enable_tracing = True
    rec = get_recorder()
    rec.clear()
    try:
        ctx = StreamContext([build_partition(base, 0, 1)], ss)
        for text in queries.values():
            ctx.register(text)
        ctx.feed_source(ReplaySource(live, batch_size=batch),
                        max_epochs=epochs)
    finally:
        Global.enable_tracing = prev
    agg = {}
    traces = [t for t in rec.last() if t.kind == "stream"]
    for tr in traces:
        for name, s in tr.step_summary().items():
            d = agg.setdefault(name, {"count": 0, "total_us": 0})
            d["count"] += s["count"]
            d["total_us"] += s["total_us"]
    return {"epochs_traced": len(traces), "spans": agg}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scale", type=int, default=1, help="LUBM universities")
    ap.add_argument("--batch", type=int, default=4096, help="epoch batch size")
    ap.add_argument("--base-frac", type=float, default=0.5,
                    help="fraction of the graph preloaded before streaming")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--out", default="BENCH_STREAM.json")
    args = ap.parse_args()

    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm

    triples, _ = generate_lubm(args.scale, seed=args.seed)
    ss = VirtualLubmStrings(args.scale, seed=args.seed)
    rng = np.random.default_rng(args.seed)
    perm = rng.permutation(len(triples))
    n_base = int(len(triples) * args.base_frac)
    base, live = triples[perm[:n_base]], triples[perm[n_base:]]

    out = {
        "bench": "stream",
        "scale": args.scale,
        "batch": args.batch,
        "seed": args.seed,
        "n_base": int(n_base),
        "n_live": int(len(live)),
        # ingest-only ceiling first, then the standing-query runs on top
        "ingest_only": _run(base, live, ss, args.batch, {}),
        "with_standing": _run(base, live, ss, args.batch, STANDING),
        # observability: per-phase breakdown from a short traced replay
        # (tracing stays OFF for the timed runs above)
        "trace_breakdown": _traced_sample(base, live, ss, args.batch,
                                          STANDING),
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
    io, ws = out["ingest_only"], out["with_standing"]
    print(json.dumps({
        "ingest_only_inserts_per_s": round(io["inserts_per_s"]),
        "with_standing_inserts_per_s": round(ws["inserts_per_s"]),
        "lag_p50_us": ws["lag_us_cdf"].get(0.5),
        "lag_p99_us": ws["lag_us_cdf"].get(0.99),
        "standing_rows": ws["standing_rows"],
    }))


if __name__ == "__main__":
    main()
