#!/usr/bin/env bash
# One-shot CI gate runner: static analysis + tier-1 tests + bench trend
# check — the three checks a PR must pass, in the order that fails
# fastest. Mirrors ROADMAP.md's tier-1 verify command (without the log
# plumbing the driver adds) so local runs and CI agree on what "green"
# means. Usage: scripts/ci_check.sh [extra pytest args...]
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== wukong-analyze (static gates) =="
# all registered gates, incl. the telemetry trio (heat / slo /
# placement-telemetry) that pin the observatory's decision surfaces
python -m wukong_tpu.analysis  # exits non-zero on any gate violation

echo "== tier-1 pytest (-m 'not slow') =="
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider "$@"

echo "== elastic rebalance drill (executed shard migration) =="
# the hot-spot drill, armed: the actuator must move the advisor's donor
# shard with byte-identical probes at every phase and land the post-move
# host imbalance under placement_imbalance_x (exits non-zero otherwise)
JAX_PLATFORMS=cpu python bench.py --rebalance

echo "== read-mostly serving drill (shadow + CACHED acceptance) =="
# the Zipfian read-mostly closed loop, twice: observe-only (predicted
# shadow hit rate >= 0.5, monotone degradation, store digest untouched)
# then with the materialized-view serving plane armed (every reply
# byte-identical to uncached execution, real hit rate >= shadow's,
# >= 3x the PR 8 light-only q/s baseline, and the 8%-write hit rate
# within 15 points of zero-write — exits non-zero otherwise)
JAX_PLATFORMS=cpu python bench.py --readmostly

echo "== cyclic device-route drill (WCOJ host/device/walk identity) =="
# the cyclic suite with the XLA device route: every case byte-identical
# across walk / host-wcoj / device-wcoj, the w_pentagon auto-routing
# exception closed (auto >= 1.0 vs the walk), >= 1.5x device-vs-host
# on at least one case, AND the compiled-template rung: the whole-plan
# fused program must answer byte-identically to the walk and delete
# >= 5x of the per-step device route's host<->device round trips on the
# large cyclic shapes (exits non-zero otherwise; see cyclic_main gates)
JAX_PLATFORMS=cpu python bench.py --cyclic

echo "== serving drill (batching + compiled template + zero-touch) =="
# the serving-path suite: batched-vs-unbatched qps, the
# device_compiled_template rung (unanchored 2-hop via the whole-plan
# fused program — must stage, agree with the host walk, and leave the
# 2-hop micro's latency band untouched with the route chooser armed),
# and the admission / device-observatory zero-touch band guards (exits
# non-zero otherwise; see serve_main gates). Short closed loop: the
# qps headline trends, the gates are structural
WUKONG_SERVE_DURATION=4 JAX_PLATFORMS=cpu python bench.py --serve-batched

echo "== device-cost drill (padding efficiency + cold amortization) =="
# the cyclic device-route suite run twice with the device observatory
# on: padding efficiency recorded per capacity class, the second pass's
# cold-dispatch count strictly below the first (jit variants reused),
# and the residency high-water within device_budget_mb (exits non-zero
# otherwise; see devicecost_main gates)
JAX_PLATFORMS=cpu python bench.py --devicecost

echo "== tenant admission drill (2x-capacity overload ladder) =="
# the multi-tenant SLO scenario incl. the admission plane's overload
# variant: clients doubled, quotas armed — the protected tenant must
# stay compliant and un-degraded while bulk is shed lowest-weight-first
# (exits non-zero otherwise; see tenants_main gates)
JAX_PLATFORMS=cpu python bench.py --tenants

echo "== multi-process rung (worker pool vs in-proc loopback) =="
# the same distributed world served over the in-proc loopback transport
# and then over the live worker pool (process-per-shard-group, framed +
# CRC socket wire, stagings invalidated every round): every socket
# reply must be byte-identical to its loopback twin, loopback must come
# back untouched after stop(), and the pool's qps must land within 2x
# of the in-proc number (exits non-zero otherwise; see proc_main gates)
JAX_PLATFORMS=cpu python bench.py --proc

echo "== graphrag hybrid drill (k-NN route + vectors-off zero-touch) =="
# the hybrid graph+vector serving loop: pure-scan device route must
# clear 3x host on the >=100k x 128d block OR the measured-demotion
# drill must engage cleanly (device failure -> host-identical answer,
# demotion latched), AND the enable_vectors off/on latency bands on the
# knn-free 2-hop micro must overlap (exits non-zero otherwise)
JAX_PLATFORMS=cpu python bench.py --graphrag

echo "== bench trajectory check =="
python scripts/bench_report.py --check

echo "ci_check: all green"
