#!/usr/bin/env python
"""Observability + serving-path sanity gates for wukong_tpu/ library code.

Gate 1 — no bare ``print(``: everything in the library reports through the
leveled logger (utils/logger.py) or the metrics registry (obs/metrics.py) —
stdout belongs to report surfaces only. Allowed:

- ``runtime/console.py`` and ``runtime/monitor.py`` (the interactive
  console and the rolling report are stdout surfaces by design)
- calls lexically inside a function named ``main`` (CLI entry points:
  datagen/lubm emit their JSON meta to stdout like any Unix tool)

Gate 2 — no direct ``engine.execute(`` under ``runtime/`` outside the
allowlisted bypass sites: interactive dispatches must flow through
``Proxy._serve_execute`` (the batcher entry point, runtime/batcher.py) so
future code can't silently reopen a one-query-per-dispatch path next to the
coalescer. The allowlist names the sites that ARE the serving machinery.

Gate 3 — mutation durability: any function that calls ``insert_triples(``
(the primary-store mutation entry) must route through the WAL append hook
``maybe_wal_append(`` in the same top-level function, or be allowlisted.
Acknowledged mutations that skip the WAL are silently lost on a crash —
exactly the gap this gate keeps closed. The allowlist names derived-state
writers (window stores rebuild from WAL-logged epochs) and the recovery
replay itself (which applies records under WAL suppression).

Run standalone (``python scripts/lint_obs.py``) or via the test suite
(tests/test_obs.py::test_lint_obs_gate, tests/test_batcher.py). Exit code 1
+ one line per violation when a gate fails.
"""

from __future__ import annotations

import ast
import os
import sys

ALLOWED_FILES = {
    os.path.join("runtime", "console.py"),
    os.path.join("runtime", "monitor.py"),
}
ALLOWED_FUNCS = {"main"}

# (runtime-relative file, enclosing function) pairs allowed to call
# ``<obj>.execute(...)`` directly — the serving machinery itself
EXECUTE_ALLOWLIST = {
    ("proxy.py", "_serve_execute"),   # THE batcher entry / bypass site
    ("proxy.py", "_run_repeats"),     # shape/capacity degradation re-runs
    ("scheduler.py", "_engine_loop"),  # pool engines executing popped work
    ("batcher.py", "_run_single"),    # per-query fallback of a fused group
    ("batcher.py", "_run_fused"),     # the fused dispatch itself
}

# (package-relative file, top-level function) pairs allowed to call
# ``insert_triples(`` without the WAL append hook
WAL_ALLOWLIST = {
    # the per-partition mutation primitive itself (hooked at batch level)
    ("store/dynamic.py", "insert_triples"),
    # private window store: derived state, rebuilt from WAL-logged epochs
    ("stream/continuous.py", "_on_epoch_windowed"),
    # recovery replay re-applies durable records under WAL suppression
    # (boot) or onto a not-yet-promoted partition under the mutation lock
    ("runtime/recovery.py", "_replay_wal"),
    ("runtime/recovery.py", "_rebuild_shard_locked"),
}


class _PrintFinder(ast.NodeVisitor):
    def __init__(self):
        self.func_stack: list[str] = []
        self.hits: list[int] = []  # line numbers of disallowed prints

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        if (isinstance(node.func, ast.Name) and node.func.id == "print"
                and not (set(self.func_stack) & ALLOWED_FUNCS)):
            self.hits.append(node.lineno)
        self.generic_visit(node)


class _MutationFinder(ast.NodeVisitor):
    """Per TOP-LEVEL function: does it (or any nested def) call
    ``insert_triples`` / the WAL hook ``maybe_wal_append``? Nested defs
    attribute to their outermost function — the hook protects the whole
    batch path, wherever the loop body lives."""

    def __init__(self):
        self.func_stack: list[str] = []
        # top-level func -> (first insert lineno, saw_hook)
        self.funcs: dict[str, list] = {}

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _name_of(self, func) -> str:
        if isinstance(func, ast.Name):
            return func.id
        if isinstance(func, ast.Attribute):
            return func.attr
        return ""

    def visit_Call(self, node):
        name = self._name_of(node.func)
        if name in ("insert_triples", "maybe_wal_append") and self.func_stack:
            top = self.func_stack[0]
            ent = self.funcs.setdefault(top, [None, False])
            if name == "insert_triples" and ent[0] is None:
                ent[0] = node.lineno
            if name == "maybe_wal_append":
                ent[1] = True
        self.generic_visit(node)


class _ExecuteFinder(ast.NodeVisitor):
    """Direct ``<obj>.execute(...)`` calls with their enclosing function."""

    def __init__(self):
        self.func_stack: list[str] = []
        self.hits: list[tuple[int, str]] = []  # (lineno, enclosing func)

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        if isinstance(node.func, ast.Attribute) and node.func.attr == "execute":
            self.hits.append(
                (node.lineno, self.func_stack[-1] if self.func_stack else ""))
        self.generic_visit(node)


def violations(pkg_root: str) -> list[str]:
    out: list[str] = []
    for dirpath, _dirs, files in os.walk(pkg_root):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, pkg_root)
            with open(path) as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError as e:
                    out.append(f"{rel}: syntax error: {e}")
                    continue
            if rel not in ALLOWED_FILES:
                finder = _PrintFinder()
                finder.visit(tree)
                out.extend(f"{rel}:{ln}: bare print() in library code "
                           "(use utils.logger or obs.metrics)"
                           for ln in finder.hits)
            if os.path.basename(dirpath) == "runtime":
                ef = _ExecuteFinder()
                ef.visit(tree)
                out.extend(
                    f"{rel}:{ln}: direct engine.execute() bypasses the "
                    "batcher entry point (route through "
                    "Proxy._serve_execute or extend EXECUTE_ALLOWLIST)"
                    for ln, func in ef.hits
                    if (fn, func) not in EXECUTE_ALLOWLIST)
            mf = _MutationFinder()
            mf.visit(tree)
            rel_posix = rel.replace(os.sep, "/")
            out.extend(
                f"{rel}:{ln}: insert_triples() without the WAL append "
                "hook — an acknowledged mutation this path commits is "
                "lost on crash (call maybe_wal_append before mutating, "
                "or extend WAL_ALLOWLIST for derived-state writers)"
                for func, (ln, hooked) in sorted(mf.funcs.items())
                if ln is not None and not hooked
                and (rel_posix, func) not in WAL_ALLOWLIST)
    return out


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "wukong_tpu")
    bad = violations(root)
    for line in bad:
        print(line)
    if bad:
        print(f"lint_obs: {len(bad)} violation(s)")
        return 1
    print("lint_obs: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
