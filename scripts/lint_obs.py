#!/usr/bin/env python
"""Back-compat shim over wukong_tpu.analysis (the lint gates' new home).

Until PR 6 this script owned three hand-rolled AST gates (bare prints,
batcher-bypass ``engine.execute`` calls, WAL-less mutations). Those now
live as plugins in ``wukong_tpu/analysis/obs_gates.py`` next to the rest
of the project's gates; this shim keeps the CLI contract stable for CI
and the existing tests:

- ``python scripts/lint_obs.py [PKG_ROOT]`` exits 0/1 with one line per
  violation, exactly as before;
- ``violations(pkg_root)`` returns the legacy list-of-strings form;
- the allowlists are re-exported so forks that extended them keep
  working.

The full gate suite (lock discipline, drift gates, ...) runs via
``python -m wukong_tpu.analysis`` — this shim runs only the three legacy
gates, which are the ones that make sense on a bare package tree.
"""

from __future__ import annotations

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # standalone invocation from anywhere
    sys.path.insert(0, _REPO_ROOT)

from wukong_tpu.analysis.framework import run_analysis  # noqa: E402
from wukong_tpu.analysis.obs_gates import (  # noqa: E402,F401 (re-exports)
    ALLOWED_FILES,
    ALLOWED_FUNCS,
    EXECUTE_ALLOWLIST,
    LEGACY_GATES,
    WAL_ALLOWLIST,
)


def violations(pkg_root: str) -> list[str]:
    """Legacy form: one ``path:line: message`` string per violation from
    the three original gates (parse failures included, as before)."""
    out = []
    for v in run_analysis(pkg_root, plugins=list(LEGACY_GATES)):
        out.append(f"{v.path}:{v.line}: {v.message}" if v.gate != "parse"
                   else f"{v.path}: {v.message}")
    return out


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else os.path.join(_REPO_ROOT, "wukong_tpu")
    bad = violations(root)
    for line in bad:
        print(line)
    if bad:
        print(f"lint_obs: {len(bad)} violation(s)")
        return 1
    print("lint_obs: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
