"""On-silicon differential for the Pallas stream kernels: replays the
committed adversarial suite (tests/test_stream_adversarial.py) with
interpret=False on the REAL TPU backend, comparing stream_expand's Mosaic
lowering against merge_expand (XLA) compiled for the same chip. First run
green 2026-07-31 (55 cases, 0 failures, 145 s incl. compiles) after the
three round-5 silicon fixes: [G,R,128] block layout, i1-reshape avoidance,
precision=HIGHEST on all kernel dots."""
import sys, time, itertools, inspect, numpy as np
sys.path.insert(0, '/root/repo/tests'); sys.path.insert(0, '/root/repo')
import jax
assert jax.devices()[0].platform == 'tpu'
import jax.numpy as jnp
import test_stream_adversarial as adv
from wukong_tpu.engine.tpu_kernels import merge_expand
from wukong_tpu.engine import tpu_stream
from wukong_tpu.engine.tpu_stream import stream_expand, MDUP

assert tpu_stream.stream_available()
FAILS, CASES = [], [0]

def _check(sk, ss, sd, e, cur, n, live, cap, mdup=MDUP, mxu=None,
           expect_bitwise=False):
    CASES[0] += 1
    a = merge_expand(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
                     jnp.asarray(e), jnp.asarray(cur), jnp.int32(n),
                     jnp.asarray(live), cap_out=cap)
    b = stream_expand(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
                      jnp.asarray(e), jnp.asarray(cur), jnp.int32(n),
                      jnp.asarray(live), cap_out=cap, interpret=False,
                      mdup=mdup, mxu=mxu)
    av, ap, an, at = [np.asarray(x) for x in a]
    bv, bp, bn, bt = [np.asarray(x) for x in b]
    assert int(at) == int(bt), f"totals {int(at)} != {int(bt)}"
    assert int(an) == int(bn), f"out_n {int(an)} != {int(bn)}"
    k = int(an)
    if expect_bitwise:
        # same contract as the interpret-mode suite (full-array equality,
        # padding included): a DMA block landing at a wrong-but-content-
        # compensating offset must fail here, not pass as a bag
        assert np.array_equal(av, bv) and np.array_equal(ap, bp), \
            'bitwise mismatch'
    elif int(at) <= cap:
        assert (sorted(zip(av[:k].tolist(), ap[:k].tolist()))
                == sorted(zip(bv[:k].tolist(), bp[:k].tolist()))), \
            'bag mismatch'
    return int(at), int(an)

adv._check = _check
t0 = time.time()
for name in sorted(n for n in dir(adv) if n.startswith('test_')):
    fn = getattr(adv, name)
    pmarks = [m for m in getattr(fn, 'pytestmark', []) if m.name == 'parametrize']
    # each mark: (argnames_str, values). Stacked marks -> cartesian product.
    axes = []
    for m in pmarks:
        argnames = [a.strip() for a in m.args[0].split(',')]
        vals = []
        for v in m.args[1]:
            if len(argnames) == 1:
                vals.append({argnames[0]: v})
            else:
                vals.append(dict(zip(argnames, v)))
        axes.append(vals)
    combos = [{}]
    for ax in axes:
        combos = [dict(c, **d) for c in combos for d in ax]
    sig = set(inspect.signature(fn).parameters)
    try:
        ran = 0
        for kw in combos:
            if set(kw) != sig:
                continue
            fn(**kw); ran += 1
        if ran:
            print(f'{name}: OK x{ran}')
        else:
            print(f'{name}: SKIP sig={sig}')
    except Exception as ex:
        FAILS.append(name); print(f'{name}: FAIL {str(ex)[:160]}')
print(f'== {CASES[0]} on-silicon differential cases, {len(FAILS)} failures, {time.time()-t0:.0f}s')
