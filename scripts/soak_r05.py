"""End-of-round-5 soak: fresh seeds through the adversarial stream fuzzer
and the lookup-dispatch differential fuzzer (run standalone with
JAX_PLATFORMS=cpu; the committed test suites run the canonical seeds)."""

import sys

sys.path.insert(0, "/root/repo")
import jax

jax.config.update("jax_platforms", "cpu")


def main():
    import pytest

    import tests.test_merge_path as M
    import tests.test_stream_adversarial as A

    n = 0
    for seed in range(200, 240):
        A.test_adversarial_mix_fuzz(seed)
        n += 1
        if n % 10 == 0:
            print(f"adversarial mix: {n} seeds OK", flush=True)
    for seed in range(70, 90):
        mp = pytest.MonkeyPatch()
        try:
            M.test_probe_vs_merge_arm_fuzz(seed, mp)
        finally:
            mp.undo()
        n += 1
        if n % 10 == 0:
            print(f"progress: {n}", flush=True)
    print(f"soak complete: {n} extra cases, zero divergence")


if __name__ == "__main__":
    main()
