#!/bin/bash
# Refresh the frozen working-tree snapshot the opportunistic bench loop runs
# from (.cache/benchsnap). Call after a green-tests commit so the loop never
# measures a half-edited tree. World caches + partial results stay shared via
# WUKONG_CACHE_DIR pointing back at the live tree's .cache.
set -e
REPO="$(cd "$(dirname "$0")/.." && pwd)"
SNAP="$REPO/.cache/benchsnap"
mkdir -p "$SNAP"
cd "$REPO"
# -co --exclude-standard: tracked AND new untracked sources (a new module
# imported by a tracked file would otherwise be silently dropped, breaking
# every bench pass in the loop with ModuleNotFoundError)
git ls-files -coz --exclude-standard | tar --null -T - -cf - | tar -xf - -C "$SNAP"
echo "benchsnap refreshed from $(git rev-parse --short HEAD)"
