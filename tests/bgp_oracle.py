"""Independent BGP evaluator used as a golden oracle in tests.

Evaluates basic graph patterns by naive index-nested-loop join directly over
the raw triple array — a completely different algorithm/code path from the
engine under test. Variables are negative ints, constants positive.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np


class TripleIndex:
    def __init__(self, triples: np.ndarray):
        self.by_s = defaultdict(list)
        self.by_o = defaultdict(list)
        self.by_p = defaultdict(list)
        for s, p, o in triples.tolist():
            self.by_s[s].append((p, o))
            self.by_o[o].append((p, s))
            self.by_p[p].append((s, o))


def eval_bgp(index: TripleIndex, patterns, required_vars):
    """patterns: (s, p, o) triples as written (vars < 0). Returns list of
    projected tuples (with multiplicity)."""
    bindings = [dict()]
    for (ps, pp, po) in patterns:
        new = []
        for b in bindings:
            s = b.get(ps, ps) if ps < 0 else ps
            p = b.get(pp, pp) if pp < 0 else pp
            o = b.get(po, po) if po < 0 else po
            s_res, p_res, o_res = s >= 0, p >= 0, o >= 0
            if s_res:
                cands = [(s, pc, oc) for (pc, oc) in index.by_s.get(s, [])]
            elif o_res:
                cands = [(sc, pc, o) for (pc, sc) in index.by_o.get(o, [])]
            elif p_res:
                cands = [(sc, p, oc) for (sc, oc) in index.by_p.get(p, [])]
            else:
                cands = [(sc, pc, oc) for pc, so in index.by_p.items()
                         for (sc, oc) in so]
            for (cs, cp, co) in cands:
                if s_res and cs != s:
                    continue
                if p_res and cp != p:
                    continue
                if o_res and co != o:
                    continue
                nb = dict(b)
                if not s_res:
                    nb[ps] = cs
                if not p_res:
                    nb[pp] = cp
                if not o_res:
                    nb[po] = co
                # consistency when one var appears twice in the pattern
                if (ps == pp and nb.get(ps) != nb.get(pp)) or \
                   (ps == po and nb.get(ps) != nb.get(po)) or \
                   (pp == po and nb.get(pp) != nb.get(po)):
                    continue
                new.append(nb)
        bindings = new
    return [tuple(b[v] for v in required_vars) for b in bindings]
