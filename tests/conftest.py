"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Multi-chip hardware is unavailable in CI; sharding correctness is validated on a
virtual CPU mesh (the reference has no such fake-cluster mode — multi-node there
means a real mpiexec cluster, SURVEY.md §4)."""

import os

# The axon sitecustomize registers the TPU PJRT plugin at interpreter start and
# pins JAX_PLATFORMS=axon, so env overrides alone don't stick. Setting XLA_FLAGS
# before any backend initializes + jax.config.update after import reliably
# selects an 8-device virtual CPU mesh for the test suite.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_cpu_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {devs}"
    return devs[:8]


@pytest.fixture(autouse=True, scope="module")
def _bound_compiled_program_accumulation():
    """Free compiled executables between test MODULES: a full one-shot
    `pytest tests/` accumulates thousands of distinct XLA:CPU programs in
    one process, and on single-core hosts the compiler segfaults once
    enough executables are live (observed twice at ~76% of the suite,
    crashing inside backend_compile_and_load while compiling yet another
    kernel; the same tests pass when the process starts closer to them).
    Clearing jit caches per module bounds the live-program count; modules
    re-jit lazily at a small cost."""
    yield
    import gc

    import jax

    jax.clear_caches()
    gc.collect()
