"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Multi-chip hardware is unavailable in CI; sharding correctness is validated on a
virtual CPU mesh (the reference has no such fake-cluster mode — multi-node there
means a real mpiexec cluster, SURVEY.md §4)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_cpu_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {devs}"
    return devs[:8]
