"""The admission control plane (ISSUE 16): per-tenant quotas,
weighted-fair scheduling, and graceful overload degradation.

Acceptance surface: the :class:`AdmissionController` reads ONLY the
``ADMISSION_INPUTS`` signals (through ``read_admission_input``, gate-held
literal by the ``admission-contract`` plugin) and enforces token-bucket
q/s, in-flight, and aggregate-row quotas; the degrade ladder sheds
lowest-weight-first (defer -> partial -> structured CAPACITY_EXCEEDED
with retry-after) and NEVER ladder-degrades the top weight class — the
ordering is pinned here; :class:`FairQueue` holds DRR fairness under a
hostile bulk flood; standing-query maintenance inherits its owner's
weight (priority inheritance); and the off knob degrades every hook to
one check. The whole module runs in lockdep-checked mode: every
admission lock created below is tracked, and teardown asserts the run
produced no ordering cycles and no acquisition under a declared leaf.
"""

import numpy as np
import pytest

from wukong_tpu.analysis import lockdep
from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.lubm import UB, VirtualLubmStrings, generate_lubm
from wukong_tpu.obs import get_recorder
from wukong_tpu.obs.events import EVENT_KINDS, get_journal
from wukong_tpu.obs.slo import (
    ADMISSION_INPUTS,
    get_overload,
    get_slo,
    read_admission_input,
    reset_labels,
)
from wukong_tpu.runtime import faults
from wukong_tpu.runtime.admission import (
    CONSUMED_INPUTS,
    SHED_CAUSES,
    AdmissionController,
    FairQueue,
    effective_tenant,
    get_admission,
    maybe_admission,
    parse_quotas,
    render_admission,
)
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.utils.errors import ErrorCode, WukongError

pytestmark = pytest.mark.admission

PREFIX = """
PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
"""
Q_CHAIN = PREFIX + """SELECT ?X ?Y WHERE {
    ?X ub:memberOf ?Y .
    ?Y ub:subOrganizationOf ?Z .
}"""

THREE_CLASSES = "gold:8:0:0:0;silver:4:0:0:0;bulk:1:0:0:0"


@pytest.fixture(autouse=True, scope="module")
def _lockdep():
    """Checked-lock mode for the whole module: the controller/queue/pool
    locks created below are DebugLocks, and the teardown asserts the run
    recorded no cycles and nothing acquired under a declared leaf."""
    lockdep.install(True)
    yield
    assert lockdep.cycles() == []
    assert lockdep.leaf_violations() == []
    lockdep.install(False)


@pytest.fixture(scope="module")
def world(_lockdep):
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    return {"g": g, "ss": ss, "triples": triples}


@pytest.fixture(scope="module")
def proxy(world):
    from wukong_tpu.planner.optimizer import make_planner

    p = Proxy(world["g"], world["ss"],
              CPUEngine(world["g"], world["ss"]))
    p.planner = make_planner(world["triples"])
    return p


@pytest.fixture(autouse=True)
def _hygiene(monkeypatch):
    """Admission knobs at defaults (plane OFF), controller/signal/label
    state clean, no journal or fault-plan leaks across tests."""
    monkeypatch.setattr(Global, "enable_tracing", False)
    monkeypatch.setattr(Global, "enable_tenant_accounting", True)
    monkeypatch.setattr(Global, "slo_specs", "")
    monkeypatch.setattr(Global, "enable_admission", False)
    monkeypatch.setattr(Global, "admission_quotas", "")
    monkeypatch.setattr(Global, "admission_default_weight", 1)
    monkeypatch.setattr(Global, "admission_max_inflight", 0)
    monkeypatch.setattr(Global, "admission_defer_ms", 0)
    get_admission().reset()
    get_slo().reset()
    get_overload().reset()
    reset_labels()
    get_recorder().clear()
    get_journal().clear()
    faults.clear()
    yield
    get_admission().reset()
    get_slo().reset()
    get_overload().reset()
    reset_labels()
    get_journal().clear()
    faults.clear()


def mk_controller(t0: int = 1_000_000):
    """A fresh controller on an injected usec clock (its state lock is a
    DebugLock under the module's checked mode)."""
    t = [t0]
    return AdmissionController(clock=lambda: t[0]), t


# ---------------------------------------------------------------------------
# quota enforcement: token bucket, in-flight cap, aggregate row budget
# ---------------------------------------------------------------------------

def test_parse_quotas_roundtrip_and_errors():
    qs = parse_quotas("gold:8:100:16:500000; bulk:1:10:2:0")
    assert qs["gold"].weight == 8 and qs["gold"].qps == 100.0
    assert qs["gold"].inflight == 16 and qs["gold"].rows_per_s == 500000
    assert qs["bulk"].weight == 1
    assert parse_quotas("") == {}
    with pytest.raises(ValueError):
        parse_quotas("gold:8:100")  # wrong arity
    with pytest.raises(ValueError):
        parse_quotas("gold:0:1:1:1")  # weight >= 1


def test_token_bucket_quota_rejects_and_refills(monkeypatch):
    monkeypatch.setattr(Global, "admission_quotas", "t:1:10:0:0")
    monkeypatch.setattr(Global, "admission_burst_x", 1.0)
    adm, t = mk_controller()
    for _ in range(10):  # the full burst admits
        assert adm.admit("t").action == "admit"
    d = adm.admit("t")  # bucket empty, refill 100ms away > defer window
    assert d.action == "reject" and d.cause == "admission_quota"
    assert d.reason == "quota_qps" and not d.admitted
    assert d.retry_after_s >= float(Global.admission_retry_after_s)
    t[0] += 200_000  # 0.2s at 10 q/s refills 2 tokens
    assert adm.admit("t").action == "admit"
    assert adm.admit("t").action == "admit"
    assert adm.admit("t").action == "reject"


def test_quota_shortfall_within_defer_window_defers(monkeypatch):
    """Degrade before drop: a shortfall the bucket refills within the
    defer window defers (pre-charging the bucket) instead of rejecting."""
    monkeypatch.setattr(Global, "admission_quotas", "t:1:10:0:0")
    monkeypatch.setattr(Global, "admission_burst_x", 1.0)
    monkeypatch.setattr(Global, "admission_defer_ms", 200)
    adm, _t = mk_controller()
    for _ in range(10):
        assert adm.admit("t").action == "admit"
    d = adm.admit("t")  # 100ms shortfall <= 200ms defer window
    assert d.action == "defer" and d.cause == "admission_defer"
    assert 0.0 < d.wait_s <= 0.2 and d.admitted


def test_inflight_quota_rejects(monkeypatch):
    monkeypatch.setattr(Global, "admission_quotas", "t:1:0:2:0")
    adm, _t = mk_controller()
    for _ in range(3):  # the proxy notes the arrival before consulting
        get_overload().note_admit("t")
    d = adm.admit("t")
    assert d.action == "reject" and d.reason == "quota_inflight"
    get_overload().note_done("t")
    assert adm.admit("t").action == "admit"  # 2 in flight == the cap


def test_row_budget_degrades_to_partial(monkeypatch):
    monkeypatch.setattr(Global, "admission_quotas", "t:1:0:0:100")
    adm, t = mk_controller()
    adm.note_reply("t", 0)  # baseline for the rows/s EWMA
    t[0] += 1_000_000
    adm.note_reply("t", 5_000)  # 5000 rows/s instantaneous -> EWMA 1000
    d = adm.admit("t")
    assert d.action == "partial" and d.cause == "admission_partial"
    assert d.reason == "quota_rows" and d.admitted
    # a result-cache hit consumes no engine capacity: rows quota waived
    assert adm.admit("t", cached=True).action == "admit"


# ---------------------------------------------------------------------------
# the degrade ladder: lowest-weight-first, top class never touched
# ---------------------------------------------------------------------------

def test_degrade_ladder_ordering_is_pinned(monkeypatch):
    """The acceptance ordering: bulk is deferred at level 1 and partialed
    at level 2 BEFORE silver is first touched at level 3, and gold (top
    weight class) never ladder-degrades while bulk is sheddable."""
    monkeypatch.setattr(Global, "admission_quotas", THREE_CLASSES)
    adm, _t = mk_controller()
    expect = {  # level -> {tenant: action}
        0: {"bulk": "admit", "silver": "admit", "gold": "admit"},
        1: {"bulk": "defer", "silver": "admit", "gold": "admit"},
        2: {"bulk": "partial", "silver": "admit", "gold": "admit"},
        3: {"bulk": "reject", "silver": "defer", "gold": "admit"},
    }
    for level, want in expect.items():
        adm.overload_level = lambda lvl=level: lvl
        for tenant, action in want.items():
            d = adm.admit(tenant)
            assert (d.tenant, d.action) == (tenant, action), (level, want)
    # the rung-3 rejection carries the retry-after hint
    adm.overload_level = lambda: 3
    d = adm.admit("bulk")
    assert d.retry_after_s >= float(Global.admission_retry_after_s)


def test_single_weight_class_is_never_ladder_degraded(monkeypatch):
    """With one active weight class everyone is the top class: overload
    alone sheds nobody (quotas and deadlines still apply)."""
    adm, _t = mk_controller()
    adm.overload_level = lambda: 3
    assert adm.admit("anyone").action == "admit"


def test_overload_level_tracks_signals(monkeypatch):
    monkeypatch.setattr(Global, "admission_max_inflight", 4)
    monkeypatch.setattr(Global, "admission_delay_budget_us", 20_000)
    adm, t = mk_controller()
    assert adm.overload_level() == 0
    for _ in range(8):  # 8 in flight vs a cap of 4 -> x=2 -> level 2
        get_overload().note_admit("t")
    t[0] += 5_000  # past the 2ms level-cache TTL
    assert adm.overload_level() == 2
    # within the TTL the cached level is reused (hot-path flatness)
    get_overload().reset()
    assert adm.overload_level() == 2
    t[0] += 5_000
    # worst-lane queue delay EWMA 1.5x the budget -> level 1
    get_overload().note_queue_delay("interactive", 30_000)
    assert adm.overload_level() == 1


# ---------------------------------------------------------------------------
# weighted-fair scheduling: DRR under a hostile bulk flood
# ---------------------------------------------------------------------------

def test_fair_queue_drr_under_hostile_bulk_flood():
    fq = FairQueue()
    for i in range(40):
        fq.push("bulk", ("b", i), weight=1)
    for i in range(16):
        fq.push("gold", ("g", i), weight=8)
    assert len(fq) == 56
    assert fq.depths() == {"bulk": 40, "gold": 16}
    order = [fq.pop() for _ in range(56)]
    gold_at = [i for i, it in enumerate(order) if it[0] == "g"]
    # 8:1 credit ratio: every gold item drains within the first ~20 pops
    # despite arriving behind a 40-deep bulk flood...
    assert len(gold_at) == 16 and max(gold_at) < 20
    # ...without starving bulk (every active tenant earns credit each
    # round), and FIFO holds within each tenant
    assert any(it[0] == "b" for it in order[:20])
    assert [it[1] for it in order if it[0] == "g"] == list(range(16))
    assert [it[1] for it in order if it[0] == "b"] == list(range(40))
    assert fq.pop() is None and len(fq) == 0


def test_fair_queue_idle_tenant_forfeits_deficit():
    fq = FairQueue()
    fq.push("a", "a0", weight=8)
    assert fq.pop() == "a0"
    assert fq.pop() is None  # queue empty; "a" left the round
    fq.push("b", "b0", weight=1)
    fq.push("a", "a1", weight=8)
    # "a" re-enters with zero deficit: no credit accumulated while idle
    assert {fq.pop(), fq.pop()} == {"b0", "a1"}


# ---------------------------------------------------------------------------
# priority inheritance: maintenance work runs at its owner's weight
# ---------------------------------------------------------------------------

def test_effective_tenant_precedence():
    from types import SimpleNamespace

    assert effective_tenant(SimpleNamespace(owner_tenant="gold",
                                            tenant="bulk")) == "gold"
    assert effective_tenant(SimpleNamespace(owner_tenant=None,
                                            tenant="bulk")) == "bulk"
    assert effective_tenant(SimpleNamespace()) == "default"


def test_standing_query_delta_inherits_owner_tenant(world):
    from wukong_tpu.stream import StreamContext

    ctx = StreamContext([build_partition(world["triples"][:4096], 0, 1)],
                        world["ss"])
    qid = ctx.register(Q_CHAIN, tenant="gold")
    sq = ctx.continuous.queries[qid]
    assert sq.tenant == "gold"
    dq = ctx.continuous._make_delta_query(
        sq, 0, [], np.empty((0, 0), dtype=np.int64))
    assert dq.owner_tenant == "gold"
    assert effective_tenant(dq) == "gold"


# ---------------------------------------------------------------------------
# the heavy lane: per-tenant weighted slot shares
# ---------------------------------------------------------------------------

def test_heavy_cap_weighted_share_is_work_conserving(monkeypatch):
    monkeypatch.setattr(Global, "admission_quotas", THREE_CLASSES)
    adm, _t = mk_controller()
    # a lone holder gets the whole lane (work-conserving)
    assert adm.heavy_cap_for("gold", 8, {}) == 8
    assert adm.heavy_cap_for("bulk", 8, {}) == 8
    # contended: slots split by weight across holders + requester
    assert adm.heavy_cap_for("gold", 8, {"bulk": 1}) == 7  # 8*8//9
    assert adm.heavy_cap_for("bulk", 8, {"gold": 3}) == 1  # floor >= 1
    assert adm.heavy_cap_for("silver", 12, {"gold": 2, "bulk": 1}) == 3


# ---------------------------------------------------------------------------
# pool integration: the fair sub-lane, and the off knob's zero touch
# ---------------------------------------------------------------------------

def _planned(proxy):
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.sparql.parser import Parser

    q = Parser(proxy.str_server).parse(Q_CHAIN)
    heuristic_plan(q)
    q.result.blind = True
    return q


def test_pool_fair_lane_executes_tenant_work(proxy, monkeypatch):
    from wukong_tpu.runtime.scheduler import EnginePool

    monkeypatch.setattr(Global, "enable_admission", True)
    monkeypatch.setattr(Global, "admission_quotas", THREE_CLASSES)
    pool = EnginePool(num_engines=2,
                      make_engine=lambda tid: CPUEngine(
                          proxy.g, proxy.str_server))
    pool.start()
    try:
        qids = []
        for tenant in ("bulk", "gold", "bulk", "silver"):
            q = _planned(proxy)
            q.tenant = tenant
            qids.append(pool.submit(q))
        outs = [pool.wait(qid, timeout=30) for qid in qids]
        assert all(o is not None and o.result.status_code == 0
                   for o in outs)
        assert all(o.result.nrows == outs[0].result.nrows for o in outs)
        assert pool._fair is not None and len(pool._fair) == 0
    finally:
        pool.stop()


def test_pool_off_knob_never_builds_the_fair_queue(proxy):
    from wukong_tpu.runtime.scheduler import EnginePool

    pool = EnginePool(num_engines=2,
                      make_engine=lambda tid: CPUEngine(
                          proxy.g, proxy.str_server))
    pool.start()
    try:
        q = _planned(proxy)
        q.tenant = "gold"
        out = pool.wait(pool.submit(q), timeout=30)
        assert out is not None and out.result.status_code == 0
        assert pool._fair is None  # zero-touch: the lane never exists
    finally:
        pool.stop()


# ---------------------------------------------------------------------------
# proxy integration: structured rejection, partial replies, zero touch
# ---------------------------------------------------------------------------

def test_proxy_rejects_with_capacity_exceeded(proxy, monkeypatch):
    monkeypatch.setattr(Global, "enable_admission", True)
    monkeypatch.setattr(Global, "admission_quotas", "bulk:1:0.5:0:0")
    q = proxy.serve_query(Q_CHAIN, blind=True, tenant="bulk")
    assert q.result.status_code == ErrorCode.SUCCESS  # burst admits one
    with pytest.raises(WukongError) as ei:
        proxy.serve_query(Q_CHAIN, blind=True, tenant="bulk")
    assert ei.value.code == ErrorCode.CAPACITY_EXCEEDED
    assert "retry after" in str(ei.value)
    # the shed charged the declared cause on the overload bus...
    assert read_admission_input("shed_by_cause").get(
        "admission_quota", 0) >= 1
    # ...the rejection reached tenant error accounting...
    assert get_slo().compliance("bulk")["errors"] == 1
    # ...and the journal carries the admission.quota event, findable
    # through the dotted-kind filter as one admission timeline
    evs = get_journal().last(kind="admission")
    assert any(e.kind == "admission.quota" and e.tenant == "bulk"
               for e in evs)
    # the in-flight slot was released through the error path
    assert read_admission_input("tenant_inflight").get("bulk", 0) == 0


def test_proxy_partial_reply_end_to_end(proxy, monkeypatch):
    """Rung 2 end to end: an over-row-budget tenant's reply degrades to
    a structured partial (PR 1 mark_partial machinery), not an error."""
    monkeypatch.setattr(Global, "enable_admission", True)
    monkeypatch.setattr(Global, "admission_quotas", "bulk:1:0:0:50")
    monkeypatch.setattr(Global, "admission_partial_deadline_ms", 10_000)
    monkeypatch.setattr(Global, "admission_partial_budget_rows", 1)
    adm = get_admission()
    adm.note_reply("bulk", 0)
    adm.note_reply("bulk", 1_000_000)  # row-rate EWMA far over budget
    q = proxy.serve_query(Q_CHAIN, blind=True, tenant="bulk")
    assert q.result.complete is False  # truncated, with rows kept
    assert q.result.dropped_patterns
    assert read_admission_input("shed_by_cause").get(
        "admission_partial", 0) >= 1


def test_proxy_off_knob_zero_touch(proxy):
    assert maybe_admission() is None
    q = proxy.serve_query(Q_CHAIN, blind=True, tenant="bulk")
    assert q.result.status_code == ErrorCode.SUCCESS
    rep = get_admission().report()
    assert rep["enabled"] is False and rep["decisions"] == {}


def test_admission_report_and_render(monkeypatch):
    monkeypatch.setattr(Global, "enable_admission", True)
    monkeypatch.setattr(Global, "admission_quotas", THREE_CLASSES)
    adm = get_admission()
    assert adm.admit("gold").action == "admit"
    rep = adm.report()
    assert rep["enabled"] is True
    assert rep["quotas"]["gold"]["weight"] == 8
    assert rep["decisions"] == {"admit/gold": 1}
    assert set(rep["signals"]) == set(CONSUMED_INPUTS)
    text, js = render_admission(4)
    assert "wukong-admission" in text
    assert js["decisions"] == {"admit/gold": 1}


# ---------------------------------------------------------------------------
# the consumer contract, closed sets, and the analysis gate
# ---------------------------------------------------------------------------

def test_contracts_are_literal_and_closed():
    """Runtime mirror of the admission-contract gate."""
    assert set(CONSUMED_INPUTS) <= set(ADMISSION_INPUTS)
    assert set(SHED_CAUSES) == {"admission_defer", "admission_partial",
                                "admission_reject", "admission_quota"}
    assert "admission.shed" in EVENT_KINDS
    assert "admission.quota" in EVENT_KINDS
    with pytest.raises(KeyError):
        read_admission_input("not_a_signal")


def test_admission_gate_fixtures(tmp_path):
    """Gate negatives: an undeclared consumed signal, an undeclared read,
    an unused declared cause, an undeclared shed cause, an undeclared
    leaf lock, and an unannotated shared container all surface; the
    clean shape and a tree without an admission plane do not."""
    from wukong_tpu.analysis import run_analysis

    def write(tree: dict) -> str:
        root = tmp_path / f"pkg{len(list(tmp_path.iterdir()))}"
        for rel, src in tree.items():
            p = root / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(src)
        return str(root)

    slo_src = "ADMISSION_INPUTS = {'lane_depth': 'wukong_pool_lane_depth'}\n"
    bad = write({"obs/slo.py": slo_src, "runtime/admission.py": (
        "CONSUMED_INPUTS = ('lane_depth', 'phantom_signal')\n"
        "SHED_CAUSES = ('admission_defer', 'admission_ghost')\n"
        "def f():\n"
        "    read_admission_input('lane_depth')\n"
        "    read_admission_input('undeclared_read')\n"
        "    maybe_note_shed('admission_defer', 't')\n"
        "    maybe_note_shed('not_declared', 't')\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self.tenants = {}\n"
        "        self.lock = make_lock('admission.state')\n")})
    msgs = "\n".join(str(v) for v in run_analysis(
        bad, plugins=["admission-contract"]))
    assert "'phantom_signal'" in msgs  # consumed but never promised
    assert "'undeclared_read'" in msgs  # read outside CONSUMED_INPUTS
    assert "'admission_ghost'" in msgs  # declared cause, no call site
    assert "'not_declared'" in msgs  # shed cause outside the closed set
    assert "admission.state" in msgs  # lock not declared a leaf
    assert "C.tenants" in msgs  # unannotated shared structure

    good = write({"obs/slo.py": slo_src, "runtime/admission.py": (
        "CONSUMED_INPUTS = ('lane_depth',)\n"
        "SHED_CAUSES = ('admission_defer',)\n"
        "declare_leaf('admission.state')\n"
        "def f():\n"
        "    read_admission_input('lane_depth')\n"
        "    maybe_note_shed('admission_defer', 't')\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self.tenants = {}  # guarded by: _lock\n"
        "        self.lock = make_lock('admission.state')\n")})
    assert run_analysis(good, plugins=["admission-contract"]) == []

    # a tree without an admission plane is not checked (partial fixtures)
    empty = write({"other.py": "x = 1\n"})
    assert run_analysis(empty, plugins=["admission-contract"]) == []


def test_admission_gate_holds_on_the_live_tree():
    import os

    from wukong_tpu.analysis import run_analysis

    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "wukong_tpu")
    assert run_analysis(pkg, plugins=["admission-contract"]) == []


# ---------------------------------------------------------------------------
# satellite: the result-cache cost model (pairs with the row quotas)
# ---------------------------------------------------------------------------

def test_result_cache_cost_model_admission_bar(monkeypatch):
    from types import SimpleNamespace

    from wukong_tpu.serve.result_cache import ResultCache

    monkeypatch.setattr(Global, "result_cache_min_reads", 2)
    monkeypatch.setattr(Global, "result_cache_cost_model", True)
    cheap_giant = SimpleNamespace(nbytes=1 << 20, cost_us=10.0)
    mid = SimpleNamespace(nbytes=51_200, cost_us=100.0)
    dear_small = SimpleNamespace(nbytes=100, cost_us=10_000.0)
    assert ResultCache._admit_bar(cheap_giant) == 8  # density >= 4096: 4x
    assert ResultCache._admit_bar(mid) == 4  # density >= 512: 2x
    assert ResultCache._admit_bar(dear_small) == 2  # base bar
    monkeypatch.setattr(Global, "result_cache_cost_model", False)
    assert ResultCache._admit_bar(cheap_giant) == 2  # off: flat bar


def test_result_cache_eviction_prefers_cheap_giants(monkeypatch):
    """Cheap-to-recompute giants stop evicting expensive small entries:
    the victim scan picks the lowest cost-per-byte, not FIFO order."""
    from types import SimpleNamespace

    from wukong_tpu.serve.result_cache import ResultCache

    monkeypatch.setattr(Global, "result_cache_cost_model", True)
    rc = ResultCache()
    rc._entries["dear"] = SimpleNamespace(nbytes=100, cost_us=50_000.0)
    rc._entries["cheap"] = SimpleNamespace(nbytes=1 << 20, cost_us=10.0)
    assert rc._pick_victim_locked(keep=None) == "cheap"
    assert rc._pick_victim_locked(keep="cheap") == "dear"
    monkeypatch.setattr(Global, "result_cache_cost_model", False)
    assert rc._pick_victim_locked(keep=None) == "dear"  # FIFO when off
