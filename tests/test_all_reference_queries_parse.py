"""Grammar coverage: EVERY query file the reference ships must parse.

The reference's acceptance surface is its scripts/sparql_query tree (lubm,
watdiv, dbpsb, yago — SURVEY §4). The LUBM suites are executed elsewhere
against real data; the other datasets are external, so the contract this
file pins is the FRONTEND's: lexer + parser + IR translation accept every
query shape the reference accepts (chains with `;`/`,`, language-tagged
literals, %templates, full-IRI predicates, corun/mt extensions), with the
`wrong` suite staying rejected."""

import glob

import pytest

from wukong_tpu.sparql.parser import Parser
from wukong_tpu.types import NORMAL_ID_START
from wukong_tpu.utils.errors import WukongError

ROOT = "/root/reference/scripts/sparql_query"

QUERY_FILES = sorted(
    f for pat in ("lubm/basic/lubm_q*", "lubm/union/q*", "lubm/optional/q*",
                  "lubm/filter/q*", "lubm/order/q*", "lubm/dedup/q*",
                  "lubm/attr/lubm_attr_q*", "lubm/batch/*",
                  "lubm/emulator/q*", "lubm/corun/q*",
                  "watdiv/watdiv_*", "watdiv/emulator/q*",
                  "dbpsb/dbpsb_q*", "yago/yago_q*")
    for f in glob.glob(f"{ROOT}/{pat}")
    if not f.endswith((".md", ".fmt")) and "plan" not in f)


class PermissiveStrings:
    """String server stub: every IRI/literal resolves (fresh ids), so parse
    coverage is about GRAMMAR, not about which dataset is loaded."""

    def __init__(self):
        self._ids: dict[str, int] = {}
        self.pid2type: dict[int, int] = {}  # no attr predicates

    def str2id(self, s: str) -> int:
        if s not in self._ids:
            # treat everything as a normal entity; type positions accept
            # normal ids in the translator
            self._ids[s] = NORMAL_ID_START + 10_000 + len(self._ids)
        return self._ids[s]

    def exist(self, s: str) -> bool:
        return True

    def exist_id(self, i: int) -> bool:
        return False

    def id2str(self, i: int) -> str:
        return f"<id{i}>"


def _is_query_text(text: str) -> bool:
    up = text.upper()
    return "SELECT" in up and "WHERE" in up


@pytest.mark.parametrize("qfile", QUERY_FILES,
                         ids=[f[len(ROOT) + 1:] for f in QUERY_FILES])
def test_reference_query_parses(qfile):
    text = open(qfile, errors="replace").read()
    if not _is_query_text(text):
        pytest.skip("not a SPARQL file (batch list / config)")
    ss = PermissiveStrings()
    p = Parser(ss)
    if "%" in text:
        t = p.parse_template(text)
        assert t.pos and t.query.pattern_group.patterns
    else:
        q = p.parse(text)
        assert (q.pattern_group.patterns or q.pattern_group.unions
                or q.pattern_group.optional)


def test_wrong_suite_still_rejected():
    """The `wrong` suite: q1-q4 are RUNTIME-wrong (unbound SELECT vars,
    bad regex, ...) and must parse; only `syntax` is a parse error — it
    must raise a clean WukongError, never crash or half-parse."""
    for qfile in sorted(glob.glob(f"{ROOT}/lubm/wrong/q*")):
        Parser(PermissiveStrings()).parse(
            open(qfile, errors="replace").read())
    with pytest.raises(WukongError):
        Parser(PermissiveStrings()).parse(
            open(f"{ROOT}/lubm/wrong/syntax", errors="replace").read())


def test_arrow_terminator_vs_negative_filter_literal():
    """'<-' is a pattern terminator ONLY at terminator position; inside a
    FILTER, '?y<-1' must still lex as '<' '-1' (a real regression once)."""
    ss = PermissiveStrings()
    q = Parser(ss).parse(
        "SELECT ?x ?y WHERE { ?x <http://p> ?y . FILTER(?y<-1) }")
    assert len(q.pattern_group.filters) == 1
    # and the terminators still parse (reference emulator q9 shape)
    q2 = Parser(ss).parse("""SELECT ?x ?y WHERE {
        ?y <http://p> ?x <-
        ?y <http://q> ?x ->
        ?y <http://r> ?x .
    }""")
    assert len(q2.pattern_group.patterns) == 3
