"""wukong-analyze framework tests: positive/negative fixtures per gate,
lockdep cycle/leaf detection, CLI/shim compatibility, and THE tier-1
repo-wide gate (`test_repo_is_clean`).

Fixture style: every static gate is exercised against a synthetic temp
tree (never the real package), so a gate's failure mode is pinned
independently of the repo's current state; `test_repo_is_clean` is the
one test that runs everything against the live tree.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from wukong_tpu.analysis import lockdep, plugin_names, run_analysis

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "wukong_tpu")


def write_tree(root, files: dict):
    """Lay out {relpath: source} under root; returns str(root)."""
    for rel, src in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    return str(root)


# ---------------------------------------------------------------------------
# THE tier-1 gate: every plugin, over the real tree
# ---------------------------------------------------------------------------

def test_repo_is_clean():
    """All analysis gates pass on the repo (the CI contract behind
    ``python -m wukong_tpu.analysis``)."""
    bad = run_analysis(PKG)
    assert bad == [], "\n".join(str(v) for v in bad)


def test_plugin_registry():
    assert set(plugin_names()) == {
        "no-bare-print", "batcher-route", "wal-hook", "guarded-by",
        "fault-sites", "config-readme", "metrics-readme", "error-taxonomy",
        "heat-telemetry", "join-strategy", "slo-telemetry",
        "placement-telemetry", "migration-safety", "cache-coherence",
        "admission-contract", "vector-coherence", "device-telemetry",
        "transport-contract"}


def test_unknown_plugin_rejected():
    with pytest.raises(KeyError):
        run_analysis(PKG, plugins=["no-such-gate"])


# ---------------------------------------------------------------------------
# guarded-by gate
# ---------------------------------------------------------------------------

GUARDED_BAD = '''
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._jobs = []  # guarded by: _lock

    def submit(self, j):
        self._jobs.append(j)

    def drain(self):
        with self._lock:
            out = list(self._jobs)
        return out
'''


def test_guarded_attr_access_outside_lock_flagged(tmp_path):
    pkg = write_tree(tmp_path, {"pool.py": GUARDED_BAD})
    bad = run_analysis(pkg, plugins=["guarded-by"])
    assert len(bad) == 1
    v = bad[0]
    assert v.path == "pool.py" and "submit" in v.message \
        and "_jobs" in v.message and "_lock" in v.message


def test_guarded_attr_access_under_lock_passes(tmp_path):
    good = GUARDED_BAD.replace(
        "    def submit(self, j):\n        self._jobs.append(j)\n",
        "    def submit(self, j):\n        with self._lock:\n"
        "            self._jobs.append(j)\n")
    pkg = write_tree(tmp_path, {"pool.py": good})
    assert run_analysis(pkg, plugins=["guarded-by"]) == []


def test_caller_holds_annotation_passes(tmp_path):
    good = GUARDED_BAD.replace(
        "    def submit(self, j):",
        "    def submit(self, j):  # caller holds: _lock")
    pkg = write_tree(tmp_path, {"pool.py": good})
    assert run_analysis(pkg, plugins=["guarded-by"]) == []


def test_unguarded_inline_allowlist_passes(tmp_path):
    good = GUARDED_BAD.replace(
        "        self._jobs.append(j)",
        "        self._jobs.append(j)  # unguarded: test fixture reason")
    pkg = write_tree(tmp_path, {"pool.py": good})
    assert run_analysis(pkg, plugins=["guarded-by"]) == []


def test_lockfree_declaration_not_enforced(tmp_path):
    good = GUARDED_BAD.replace("# guarded by: _lock",
                               "# lock-free: atomic list append")
    pkg = write_tree(tmp_path, {"pool.py": good})
    assert run_analysis(pkg, plugins=["guarded-by"]) == []


def test_single_entry_point_class_skipped(tmp_path):
    """One public method = cannot race with itself; the gate stays out."""
    src = GUARDED_BAD.replace("    def drain(self):",
                              "    def _drain(self):")
    pkg = write_tree(tmp_path, {"pool.py": src})
    assert run_analysis(pkg, plugins=["guarded-by"]) == []


def test_thread_target_counts_as_entry_point(tmp_path):
    """A private method used as a Thread target makes the class
    multi-threaded even with one public method."""
    src = GUARDED_BAD.replace(
        "    def drain(self):",
        "    def start(self):\n"
        "        threading.Thread(target=self._drain).start()\n\n"
        "    def _drain(self):")
    # now: submit (public) unguarded + _drain is a thread target
    src = src.replace("    def submit(self, j):\n        self._jobs.append",
                      "    def _submit(self, j):\n        self._jobs.append")
    pkg = write_tree(tmp_path, {"pool.py": src})
    bad = run_analysis(pkg, plugins=["guarded-by"])
    assert len(bad) == 1 and "_submit" in bad[0].message


def test_nested_class_attr_annotation_collected(tmp_path):
    """Class-level attribute annotations are anchored to cls.body
    membership, not a hardcoded indent column — a nested class's guarded
    attr must still be enforced."""
    src = '''
import threading

class Outer:
    class Inner:
        shared = {}  # guarded by: _lock

        def __init__(self):
            self._lock = threading.Lock()

        def put(self, k, v):
            self.shared[k] = v

        def get(self, k):
            with self._lock:
                return self.shared.get(k)
'''
    pkg = write_tree(tmp_path, {"mod.py": src})
    bad = run_analysis(pkg, plugins=["guarded-by"])
    assert len(bad) == 1 and "put" in bad[0].message \
        and "shared" in bad[0].message


def test_module_level_guarded_global(tmp_path):
    src = '''
import threading

_lock = threading.Lock()
_state = {}  # guarded by: _lock

def good(k, v):
    with _lock:
        _state[k] = v

def bad(k):
    return _state.get(k)
'''
    pkg = write_tree(tmp_path, {"mod.py": src})
    bad = run_analysis(pkg, plugins=["guarded-by"])
    assert len(bad) == 1 and "bad" not in bad[0].message  # flags the line
    assert bad[0].path == "mod.py" and "_state" in bad[0].message


def test_factory_call_lock_spec(tmp_path):
    """`# guarded by: mutation_lock()` matches `with mutation_lock():`."""
    src = '''
def mutation_lock():
    ...

class Ingestor:
    def __init__(self):
        self.epoch = 0  # guarded by: mutation_lock()

    def commit(self):
        with mutation_lock():
            self.epoch += 1

    def peek(self):
        return self.epoch
'''
    pkg = write_tree(tmp_path, {"ing.py": src})
    bad = run_analysis(pkg, plugins=["guarded-by"])
    assert len(bad) == 1 and "peek" in bad[0].message


# ---------------------------------------------------------------------------
# drift gates (synthetic repo with config/README/tests surfaces)
# ---------------------------------------------------------------------------

CONFIG_SRC = '''
from dataclasses import dataclass, field

@dataclass
class GlobalConfig:
    knob_a: int = 1
    knob_b: bool = False
    derived: int = field(default=0, init=False)
'''


def _drift_repo(tmp_path, readme: str, config: str = CONFIG_SRC,
                tests: dict | None = None):
    pkg = tmp_path / "pkg"
    write_tree(pkg, {"config.py": config})
    (tmp_path / "README.md").write_text(readme)
    tdir = tmp_path / "tests"
    tdir.mkdir(exist_ok=True)
    for name, src in (tests or {}).items():
        (tdir / name).write_text(src)
    return str(pkg), str(tmp_path / "README.md"), str(tdir)


def test_config_readme_missing_knob_flagged(tmp_path):
    pkg, readme, tdir = _drift_repo(tmp_path, "only `knob_a` documented")
    bad = run_analysis(pkg, plugins=["config-readme"], readme_path=readme,
                       tests_dir=tdir)
    assert len(bad) == 1 and "knob_b" in bad[0].message
    # derived (init=False) fields are never knobs
    assert not any("derived" in v.message for v in bad)


def test_config_readme_stale_table_row_flagged(tmp_path):
    readme = ("`knob_a` `knob_b`\n\n"
              "| knob | default |\n|---|---|\n| `ghost_knob` | 0 |\n")
    pkg, readme_p, tdir = _drift_repo(tmp_path, readme)
    bad = run_analysis(pkg, plugins=["config-readme"], readme_path=readme_p,
                       tests_dir=tdir)
    assert len(bad) == 1 and "ghost_knob" in bad[0].message


def test_metrics_readme_both_directions(tmp_path):
    src = ('from x import get_registry\n'
           'M = get_registry().counter("wukong_real_total", "h")\n')
    readme = ("| metric | type |\n|---|---|\n"
              "| `wukong_ghost_total` | counter |\n")
    pkg = write_tree(tmp_path / "pkg", {"m.py": src})
    (tmp_path / "README.md").write_text(readme)
    bad = run_analysis(pkg, plugins=["metrics-readme"],
                       readme_path=str(tmp_path / "README.md"))
    msgs = "\n".join(v.message for v in bad)
    assert "wukong_real_total" in msgs  # registered but undocumented
    assert "wukong_ghost_total" in msgs  # documented but unregistered
    assert len(bad) == 2


FAULTS_SRC = '''
KNOWN_FAULT_SITES = frozenset({"a.site", "b.site"})

def site(name, shard=None):
    ...
'''


def test_fault_sites_three_directions(tmp_path):
    pkg = write_tree(tmp_path / "pkg", {
        "runtime/faults.py": FAULTS_SRC,
        "eng.py": ('from . import faults\n'
                   'def f():\n'
                   '    faults.site("a.site")\n'
                   '    faults.site("rogue.site")\n'),
    })
    tdir = tmp_path / "tests"
    tdir.mkdir()
    (tdir / "test_x.py").write_text('PLAN = "a.site:transient"\n')
    bad = run_analysis(pkg, plugins=["fault-sites"], tests_dir=str(tdir))
    msgs = "\n".join(v.message for v in bad)
    assert "rogue.site" in msgs      # used but undeclared
    assert "b.site" in msgs          # declared but unused
    assert len(bad) == 2
    # now exercise the declared-but-untested direction
    (tdir / "test_x.py").write_text("nothing here\n")
    bad = run_analysis(pkg, plugins=["fault-sites"], tests_dir=str(tdir))
    msgs = "\n".join(v.message for v in bad)
    assert "never exercised" in msgs and "a.site" in msgs


def test_error_taxonomy_gate(tmp_path):
    src = '''
from wukong_tpu.utils.errors import ErrorCode, WukongError

def good():
    raise WukongError(ErrorCode.SYNTAX_ERROR, "x")

def propagated(child):
    raise WukongError(child.result.status_code, "child failed")

def bad():
    raise WukongError(13, "bare int")
'''
    pkg = write_tree(tmp_path, {"m.py": src})
    bad = run_analysis(pkg, plugins=["error-taxonomy"])
    assert len(bad) == 1 and bad[0].path == "m.py"


# ---------------------------------------------------------------------------
# CLI + shim compatibility
# ---------------------------------------------------------------------------

def test_cli_json_output():
    proc = subprocess.run(
        [sys.executable, "-m", "wukong_tpu.analysis", "--json"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["count"] == 0 and doc["violations"] == []
    assert set(doc["gates"]) == set(plugin_names())


def test_cli_nonzero_on_violation(tmp_path):
    pkg = write_tree(tmp_path, {"m.py": "def f():\n    print('x')\n"})
    proc = subprocess.run(
        [sys.executable, "-m", "wukong_tpu.analysis", "--gate",
         "no-bare-print", str(pkg)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1
    assert "bare print()" in proc.stdout


def test_lint_obs_shim_exit_codes(tmp_path):
    """`python scripts/lint_obs.py` keeps its exact CLI contract."""
    script = os.path.join(REPO, "scripts", "lint_obs.py")
    proc = subprocess.run([sys.executable, script], capture_output=True,
                          text=True)
    assert proc.returncode == 0 and "lint_obs: clean" in proc.stdout
    pkg = write_tree(tmp_path, {"m.py": "def f():\n    print('x')\n"})
    proc = subprocess.run([sys.executable, script, str(pkg)],
                          capture_output=True, text=True)
    assert proc.returncode == 1 and "1 violation(s)" in proc.stdout


# ---------------------------------------------------------------------------
# lockdep: the runtime half
# ---------------------------------------------------------------------------

@pytest.fixture()
def _lockdep_on():
    lockdep.install(True)
    yield
    lockdep.install(False)


def test_lockdep_detects_abba_cycle(_lockdep_on):
    """The synthetic ABBA interleaving: A->B recorded, then B->A closes
    the cycle — reported once, with BOTH acquisition stacks."""
    A, B = lockdep.make_lock("t.A"), lockdep.make_lock("t.B")
    with A:
        with B:
            pass
    assert lockdep.cycles() == []  # one order alone is fine
    with B:
        with A:
            pass
    cyc = lockdep.cycles()
    assert len(cyc) == 1
    c = cyc[0]
    assert c["cycle"] == ["t.A", "t.B", "t.A"]
    assert c["this_order"] == ("t.B", "t.A")
    # both stacks at first detection: the historical edge's and this one's
    assert "test_analysis" in c["stack_first"]
    assert "test_analysis" in c["stack_here"]
    # repeating the inversion does not re-report
    with B:
        with A:
            pass
    assert len(lockdep.cycles()) == 1


def test_lockdep_abba_across_threads(_lockdep_on):
    """The classic two-thread ABBA, serialized with events so it never
    actually deadlocks — lockdep still reports the potential."""
    A, B = lockdep.make_lock("x.A"), lockdep.make_lock("x.B")
    step = threading.Event()

    def t1():
        with A:
            with B:
                step.set()

    def t2():
        step.wait(2)
        with B:
            with A:
                pass

    ts = [threading.Thread(target=t1), threading.Thread(target=t2)]
    [t.start() for t in ts]
    [t.join(5) for t in ts]
    assert len(lockdep.cycles()) == 1
    c = lockdep.cycles()[0]
    assert c["thread"] != c["thread_first"]  # both sides named


def test_lockdep_consistent_order_is_silent(_lockdep_on):
    A, B, C = (lockdep.make_lock(f"o.{n}") for n in "ABC")
    for _ in range(3):
        with A:
            with B:
                with C:
                    pass
    assert lockdep.cycles() == []
    assert lockdep.leaf_violations() == []


def test_lockdep_leaf_violation(_lockdep_on):
    lockdep.declare_leaf("leaf.L")
    L = lockdep.make_lock("leaf.L")
    X = lockdep.make_lock("leaf.X")
    with L:
        with X:
            pass
    lv = lockdep.leaf_violations()
    assert len(lv) == 1
    assert lv[0]["holding"] == "leaf.L" and lv[0]["acquiring"] == "leaf.X"
    assert "test_analysis" in lv[0]["stack"]


def test_lockdep_flags_mutation_lock_under_leaf(_lockdep_on):
    """The WAL-specific rule from the issue: taking the coarse outer
    mutation_lock() while holding a declared-leaf lock (the WAL's own
    segment lock) is an inversion."""
    from wukong_tpu.store import wal

    seg = lockdep.make_lock("wal.segment")  # declared leaf in wal.py
    with seg:
        with wal.mutation_lock():
            pass
    lv = lockdep.leaf_violations()
    assert any(v["holding"] == "wal.segment"
               and v["acquiring"] == "wal.mutation_lock" for v in lv)


def test_lockdep_rlock_reentrancy_no_self_cycle(_lockdep_on):
    R = lockdep.make_rlock("t.R")
    with R:
        with R:  # reentrant: must not self-edge or double-record
            pass
    assert lockdep.cycles() == []
    assert lockdep.report()["edges"] == []


def test_lockdep_condition_wait_releases_held_state(_lockdep_on):
    """Condition.wait releases the underlying mutex through the wrapper:
    a lock taken by another thread during the wait must NOT look like a
    nested acquisition."""
    cond = lockdep.make_condition("t.cond")
    other = lockdep.make_lock("t.other")
    got = []

    def waiter():
        with cond:
            cond.wait(timeout=2)
            got.append(True)

    t = threading.Thread(target=waiter)
    t.start()
    import time

    time.sleep(0.1)
    with other:  # while the waiter sleeps inside wait()
        pass
    with cond:
        cond.notify()
    t.join(5)
    assert got and lockdep.cycles() == []
    # no edge cond->other was ever created: the wait had released it
    assert ("t.cond", "t.other") not in {
        (e["from"], e["to"]) for e in lockdep.report()["edges"]}


def test_lockdep_metrics_exported(_lockdep_on):
    from wukong_tpu.obs.metrics import get_registry

    L = lockdep.make_lock("m.L")
    with L:
        pass
    snap = get_registry().snapshot()
    hold = snap["wukong_lock_hold_us"]["series"]
    assert any(s["labels"].get("name") == "m.L" and s["count"] >= 1
               for s in hold)


def test_lockdep_contention_counted(_lockdep_on):
    from wukong_tpu.obs.metrics import get_registry

    L = lockdep.make_lock("m.C")
    entered = threading.Event()
    release = threading.Event()

    def holder():
        with L:
            entered.set()
            release.wait(2)

    t = threading.Thread(target=holder)
    t.start()
    entered.wait(2)
    t2 = threading.Thread(target=lambda: L.acquire() or L.release())
    t2.start()
    import time

    time.sleep(0.05)  # let t2 block
    release.set()
    t.join(5)
    t2.join(5)
    val = get_registry().counter(
        "wukong_lock_contended_total",
        labels=("name",)).labels(name="m.C").value
    assert val >= 1


def test_zero_cost_when_off():
    """The overhead contract: with debug_locks off the factories return
    PLAIN threading primitives — not pass-through wrappers."""
    assert not __import__("wukong_tpu.config", fromlist=["Global"]) \
        .Global.debug_locks
    assert type(lockdep.make_lock("z")) is type(threading.Lock())
    assert type(lockdep.make_rlock("z")) is type(threading.RLock())
    assert isinstance(lockdep.make_condition("z"), threading.Condition)
    assert type(lockdep.make_condition("z")._lock) is type(threading.RLock())


def test_install_rebinds_module_level_locks():
    """wal.mutation_lock() is created at import time; install() must swap
    it into checked mode and back."""
    from wukong_tpu.store import wal

    assert type(wal.mutation_lock()) is type(threading.RLock())
    lockdep.install(True)
    try:
        assert isinstance(wal.mutation_lock(), lockdep.DebugRLock)
        assert wal.mutation_lock().name == "wal.mutation_lock"
    finally:
        lockdep.install(False)
    assert type(wal.mutation_lock()) is type(threading.RLock())


def test_lockdep_wired_through_real_runtime(_lockdep_on):
    """Integration: a real EnginePool + WAL + breaker exercise under
    checked mode records edges and stays cycle-free — the same invariant
    the chaos/recovery/batch suites enforce at module teardown."""
    from wukong_tpu.runtime.scheduler import EnginePool

    class Echo:
        def execute(self, q):
            return q

    pool = EnginePool(num_engines=2, make_engine=lambda tid: Echo())
    pool.start()
    try:
        qids = [pool.submit(i) for i in range(16)]
        for qid in qids:
            pool.wait(qid, timeout=5)
    finally:
        pool.stop()
    rep = lockdep.report()
    assert rep["enabled"] and rep["cycles"] == []
    assert any(e["from"] == "pool.route" and e["to"] == "pool.queue"
               for e in rep["edges"])


# ---------------------------------------------------------------------------
# cache-coherence gate: the serving-plane (actuator) half
# ---------------------------------------------------------------------------

_REUSE_OK = (
    "CACHE_INPUTS = {'template_popularity': 'wukong_ok_total',"
    " 'uncacheable': 'wukong_ok_total'}\n"
    "INVALIDATION_CAUSES = ('insert', 'restore')\n"
    "def reg(r):\n"
    "    return r.counter('wukong_ok_total', 'h')\n")


def test_cache_gate_serve_plane_fixtures(tmp_path):
    """The actuator checks fire only on trees WITH serve/ files: consumed
    inputs must be declared CACHE_INPUTS signals, MUTATION_EDGES must
    equal INVALIDATION_CAUSES exactly, every cause must reach a
    notify_mutation call site, and serve locks/state follow the reuse
    module's leaf/annotation discipline."""
    from wukong_tpu.analysis import run_analysis

    bad = write_tree(tmp_path / "bad", {
        "obs/reuse.py": _REUSE_OK,
        "serve/result_cache.py": (
            "CONSUMED_INPUTS = ('template_popularity', 'phantom_signal')\n"
            "MUTATION_EDGES = {'insert': 'kill', 'ghost_edge': 'x'}\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.entries = {}\n"
            "        self.lock = make_lock('serve.x')\n"),
        "store/dynamic.py": (
            "def insert_batch(stores):\n"
            "    maybe_note_invalidation('insert')\n"
            "    notify_mutation('insert')\n"
            "    notify_mutation('bogus_edge')\n")})
    out = run_analysis(bad, plugins=["cache-coherence"])
    msgs = "\n".join(str(v) for v in out)
    assert "phantom_signal" in msgs      # consumed input not in CACHE_INPUTS
    assert "'restore'" in msgs           # journaled cause missing from EDGES
    assert "ghost_edge" in msgs          # phantom edge not a declared cause
    assert "bogus_edge" in msgs          # undeclared cause at a notify site
    assert "serve.x" in msgs             # undeclared leaf lock in serve/
    assert "C.entries" in msgs           # unannotated shared serve state

    good = write_tree(tmp_path / "good", {
        "obs/reuse.py": _REUSE_OK + "declare_leaf('serve.x')\n",
        "serve/result_cache.py": (
            "CONSUMED_INPUTS = ('template_popularity', 'uncacheable')\n"
            "MUTATION_EDGES = {'insert': 'kill stale', 'restore': 'purge'}\n"
            "declare_leaf('serve.x')\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self.entries = {}  # guarded by: lock\n"
            "        self.lock = make_lock('serve.x')\n"),
        "store/dynamic.py": (
            "def insert_batch(stores):\n"
            "    maybe_note_invalidation('insert')\n"
            "    notify_mutation('insert')\n"),
        "runtime/recovery.py": (
            "def recover():\n"
            "    maybe_note_invalidation('restore')\n"
            "    notify_mutation('restore')\n")})
    assert run_analysis(good, plugins=["cache-coherence"]) == []


def test_cache_gate_observe_only_tree_skips_serve_checks(tmp_path):
    """A tree WITHOUT serve/ (the PR 13 posture) is not required to have
    an actuator: the notify_mutation coverage rule must not fire."""
    from wukong_tpu.analysis import run_analysis

    tree = write_tree(tmp_path / "obs", {
        "obs/reuse.py": _REUSE_OK,
        "store/dynamic.py": (
            "def insert_batch(stores):\n"
            "    maybe_note_invalidation('insert')\n"),
        "runtime/recovery.py": (
            "def recover():\n"
            "    maybe_note_invalidation('restore')\n")})
    assert run_analysis(tree, plugins=["cache-coherence"]) == []


# ---------------------------------------------------------------------------
# vector-coherence gate: the hybrid graph+vector plane
# ---------------------------------------------------------------------------

def test_vector_gate_fixtures(tmp_path):
    """Declared VECTOR_METRICS must be registered (and vice versa for
    wukong_vector_* names), slot state is written only by the declared
    writers with a version bump, module mutation paths bump the store
    version, vector locks are leaves, and shared state is annotated."""
    from wukong_tpu.analysis import run_analysis

    bad = write_tree(tmp_path / "bad", {
        "vector/__init__.py": (
            "VECTOR_METRICS = {'upserts': 'wukong_vector_up_total',"
            " 'phantom': 'wukong_vector_ghost_total'}\n"),
        "vector/vstore.py": (
            "def reg(r):\n"
            "    r.counter('wukong_vector_up_total', 'h')\n"
            "    r.counter('wukong_vector_rogue_total', 'h')\n"
            "class VectorStore:\n"
            "    def __init__(self):\n"
            "        self.slot_of = {}\n"
            "        self._lock = make_lock('vector.slots')\n"
            "    def _apply_slots(self, vids):\n"
            "        with self._lock:\n"
            "            self.vids = vids\n"
            "    def refresh(self):\n"
            "        self.alive = None\n"
            "def apply_batch(g, vs):\n"
            "    return vs.upsert([1])\n")})
    out = run_analysis(bad, plugins=["vector-coherence"])
    msgs = "\n".join(str(v) for v in out)
    assert "wukong_vector_ghost_total" in msgs  # declared, never registered
    assert "wukong_vector_rogue_total" in msgs  # registered, undeclared
    assert "refresh() writes slot state" in msgs
    assert "never bumps `.version`" in msgs
    assert "apply_batch() applies a vector mutation" in msgs
    assert "vector.slots" in msgs              # undeclared leaf lock
    assert "VectorStore.slot_of" in msgs       # unannotated shared state

    good = write_tree(tmp_path / "good", {
        "vector/__init__.py": (
            "VECTOR_METRICS = {'upserts': 'wukong_vector_up_total'}\n"),
        "vector/vstore.py": (
            "declare_leaf('vector.slots')\n"
            "def reg(r):\n"
            "    r.counter('wukong_vector_up_total', 'h')\n"
            "class VectorStore:\n"
            "    def __init__(self):\n"
            "        self.slot_of = {}  # guarded by: _lock\n"
            "        self._lock = make_lock('vector.slots')\n"
            "    def _apply_slots(self, vids):\n"
            "        with self._lock:\n"
            "            self.vids = vids\n"
            "            self.version += 1\n"
            "def apply_batch(g, vs):\n"
            "    n = vs.upsert([1])\n"
            "    bump_store_version(g)\n"
            "    return n\n")})
    assert run_analysis(good, plugins=["vector-coherence"]) == []


def test_vector_gate_skips_trees_without_vector_plane(tmp_path):
    """Pre-vector trees (and foreign packages) are not required to grow
    a VECTOR_METRICS registry."""
    from wukong_tpu.analysis import run_analysis

    tree = write_tree(tmp_path / "plain", {
        "store/gstore.py": "def build():\n    return 1\n"})
    assert run_analysis(tree, plugins=["vector-coherence"]) == []


# ---------------------------------------------------------------------------
# device-telemetry gate: the device observatory
# ---------------------------------------------------------------------------

def test_device_gate_fixtures(tmp_path):
    """DEVICE_INPUTS must be registered (and vice versa for
    wukong_device_* names), every jit-minting engine/join/vector module
    charges the dispatch seam or justifies itself in the allowlist
    (non-empty, non-stale), device locks are leaves, and the
    observatory's shared state is annotated."""
    from wukong_tpu.analysis import run_analysis

    bad = write_tree(tmp_path / "bad", {
        "obs/device.py": (
            "DEVICE_INPUTS = {'dispatches': 'wukong_device_d_total',"
            " 'phantom': 'wukong_device_ghost_total'}\n"
            "DEVICE_DISPATCH_ALLOWLIST = {"
            "'engine/kernels.py': '',"              # empty justification
            "'engine/retired.py': 'charged at the chain seam'}\n"
            "def reg(r):\n"
            "    r.counter('wukong_device_d_total', 'h')\n"
            "    r.counter('wukong_device_rogue_total', 'h')\n"
            "class Ledger:\n"
            "    def __init__(self):\n"
            "        self.stats = {}\n"
            "        self._lock = make_lock('device.dispatch')\n"),
        # mints jax.jit, never charges the seam, not allowlisted
        "join/probe.py": (
            "import jax\n"
            "def mint():\n"
            "    return jax.jit(lambda x: x)\n"),
        # allowlisted as 'retired' but actually charges the seam → stale
        "engine/retired.py": (
            "import jax\n"
            "def run(fn):\n"
            "    out = jax.jit(fn)(1)\n"
            "    maybe_device_dispatch('engine.retired', live=1)\n"
            "    return out\n")})
    out = run_analysis(bad, plugins=["device-telemetry"])
    msgs = "\n".join(str(v) for v in out)
    assert "wukong_device_ghost_total" in msgs  # declared, unregistered
    assert "wukong_device_rogue_total" in msgs  # registered, undeclared
    assert "join/probe.py" in msgs              # uncharged jit site
    assert "empty" in msgs and "engine/kernels.py" in msgs
    assert "stale" in msgs and "engine/retired.py" in msgs
    assert "device.dispatch" in msgs            # undeclared leaf lock
    assert "Ledger.stats" in msgs               # unannotated shared state

    good = write_tree(tmp_path / "good", {
        "obs/device.py": (
            "declare_leaf('device.dispatch')\n"
            "DEVICE_INPUTS = {'dispatches': 'wukong_device_d_total'}\n"
            "DEVICE_DISPATCH_ALLOWLIST = {"
            "'engine/kernels.py': 'dispatched and charged in "
            "engine/run.py at the sync point'}\n"
            "def reg(r):\n"
            "    r.counter('wukong_device_d_total', 'h')\n"
            "class Ledger:\n"
            "    def __init__(self):\n"
            "        self.stats = {}  # guarded by: _lock\n"
            "        self._lock = make_lock('device.dispatch')\n"),
        # definition-only module, justified in the allowlist
        "engine/kernels.py": (
            "import jax\n"
            "compact = jax.jit(lambda x: x)\n"),
        # invoking module charges the seam itself
        "engine/run.py": (
            "import jax\n"
            "def run(fn, x):\n"
            "    out = jax.jit(fn)(x)\n"
            "    maybe_device_dispatch('engine.run', live=1)\n"
            "    return out\n")})
    assert run_analysis(good, plugins=["device-telemetry"]) == []


def test_device_gate_skips_trees_without_device_plane(tmp_path):
    """Pre-observatory trees (and foreign packages) are not required to
    grow a DEVICE_INPUTS registry."""
    from wukong_tpu.analysis import run_analysis

    tree = write_tree(tmp_path / "plain", {
        "engine/tpu.py": "import jax\nf = jax.jit(lambda x: x)\n"})
    assert run_analysis(tree, plugins=["device-telemetry"]) == []


_DEV_OK = (
    "declare_leaf('device.dispatch')\n"
    "DEVICE_INPUTS = {'dispatches': 'wukong_device_d_total',"
    " 'padding_efficiency': 'wukong_device_pe'}\n"
    "DEVICE_DISPATCH_ALLOWLIST = {}\n"
    "def reg(r):\n"
    "    r.counter('wukong_device_d_total', 'h')\n"
    "    r.gauge('wukong_device_pe', 'h')\n")


def test_template_coherence_fixtures(tmp_path):
    """PR 19's actuator contract: the compiled-program cache key fills
    on store version + the route-knob set, TEMPLATE_ROUTES is a literal
    registry, and the route chooser's every signal read is a
    read_device_input() call against a declared DEVICE_INPUTS member —
    never a direct reach into the observatory."""
    from wukong_tpu.analysis import run_analysis

    bad = write_tree(tmp_path / "bad", {
        "obs/device.py": _DEV_OK,
        "engine/template_compile.py": (
            # no TEMPLATE_ROUTES literal; key ignores store version and
            # knobs; chooser reads a ghost signal, a non-literal signal,
            # and pokes the observatory directly
            "def _program_key(tsig, caps):\n"
            "    return (tsig, tuple(caps))\n"
            "def choose_template_route(tsig, est):\n"
            "    sig = 'pad' + 'ding'\n"
            "    read_device_input(sig)\n"
            "    read_device_input('ghost_signal')\n"
            "    return 'device' if _observatory else 'host'\n")})
    msgs = "\n".join(str(v) for v in
                     run_analysis(bad, plugins=["device-telemetry"]))
    assert "TEMPLATE_ROUTES" in msgs
    assert "store_version" in msgs
    assert "knob" in msgs
    assert "non-literal signal" in msgs
    assert "ghost_signal" in msgs
    assert "directly" in msgs

    good = write_tree(tmp_path / "good", {
        "obs/device.py": _DEV_OK,
        "engine/template_compile.py": (
            "TEMPLATE_ROUTES = {'device': 'fused whole-plan program',"
            " 'host': 'the NumPy walk'}\n"
            "def _route_knobs():\n"
            "    return (str(Global.template_device),)\n"
            "def _program_key(tsig, store_version, caps):\n"
            "    return (tsig, store_version, tuple(caps),"
            " _route_knobs())\n"
            "def choose_template_route(tsig, est):\n"
            "    eff = read_device_input('padding_efficiency')\n"
            "    n = read_device_input('dispatches')\n"
            "    return 'host' if eff is None else 'device'\n")})
    assert run_analysis(good, plugins=["device-telemetry"]) == []


def test_template_coherence_skips_trees_without_template_plane(tmp_path):
    """A device plane without the compiled-template engine (PR 18
    trees) is exempt from the template-coherence checks."""
    from wukong_tpu.analysis import run_analysis

    tree = write_tree(tmp_path / "pre", {"obs/device.py": _DEV_OK})
    assert run_analysis(tree, plugins=["device-telemetry"]) == []
