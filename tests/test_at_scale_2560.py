"""LUBM-2560 store-metadata regression (round-4 verdict #2): the bench
chains' pin sets and capacity classes must fit v5e HBM at the scale the
flagship claim is made at — checked from the cached store's npz HEADERS
(zip member headers give every array's shape without touching the 16.9 GB
of data) plus the cached optimizer stats, so the test runs in seconds.

Math mirrors HBM_BUDGET.md:
- staged merge form per (pid, dir): edges + ekey int32 (pow2-padded) and
  skey/sstart/sdeg int32 (pow2-padded) = 8 B/edge + 12 B/key after padding
- chain state per expand level at table_capacity_max: (vals, parent) int32
- variadic-sort workspace ~3x the biggest level

Skipped when the 2560 caches are absent (fresh checkout / other machines).
"""

import os
import zipfile

import json
import numpy as np
import pytest
from numpy.lib import format as npf

from wukong_tpu.types import NORMAL_ID_START

CACHE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".cache")
STORE = os.path.join(CACHE, "lubm2560_v2_p0.npz")
STATS = os.path.join(CACHE, "lubm2560_v2_stats.npz")
BASIC = "/root/reference/scripts/sparql_query/lubm/basic"

pytestmark = pytest.mark.skipif(
    not (os.path.exists(STORE) and os.path.exists(STATS)
         and os.path.isdir(BASIC)),
    reason="LUBM-2560 caches not built on this machine")

HBM_BYTES = 16 * 2**30  # v5e: 16 GiB HBM per chip


def _pow2(n: int) -> int:
    p = 1
    while p < max(n, 1):
        p *= 2
    return p


@pytest.fixture(scope="module")
def store_meta():
    """{(pid, d): (num_keys, num_edges)} from npz headers + tiny meta blob."""
    shapes = {}
    with zipfile.ZipFile(STORE) as z:
        for name in z.namelist():
            with z.open(name) as f:
                version = npf.read_magic(f)
                shape, _fortran, _dtype = npf._read_array_header(f, version)
                shapes[name.removesuffix(".npy")] = shape
    meta = json.loads(bytes(np.load(STORE)["_meta"]).decode())
    segs = {}
    for i, (pid, d) in enumerate(meta["segments"]):
        segs[(int(pid), int(d))] = (shapes[f"seg{i}_k"][0],
                                    shapes[f"seg{i}_e"][0])
    return segs


def _staged_bytes(nk: int, ne: int) -> int:
    """Bytes of the staged merge form (device_store._stage_merge)."""
    return 12 * _pow2(nk) + 8 * _pow2(ne)


def test_staged_all_matches_hbm_budget_table(store_meta):
    """HBM_BUDGET.md's 'staged-ALL ~10.5 GiB' row stays honest."""
    total = sum(_staged_bytes(nk, ne) for nk, ne in store_meta.values())
    assert 8 * 2**30 < total < 13 * 2**30, f"{total / 2**30:.1f} GiB"
    biggest = max(_staged_bytes(nk, ne) for nk, ne in store_meta.values())
    assert biggest < 2.5 * 2**30  # "~1.4 GiB biggest single segment"


def test_planned_chains_fit_hbm(store_meta):
    """Every bench query's pin set + chain state + sort workspace fits one
    chip at LUBM-2560 — the single-chip feasibility claim behind the bench.
    Pins come from the REAL planned chains (type-centric Planner over the
    cached 2560 stats), sized by the staged-form math above; capacity
    classes are bounded by table_capacity_max exactly as the executor
    clamps them."""
    from wukong_tpu.config import Global
    from wukong_tpu.engine.tpu_merge import MergeExecutor
    from wukong_tpu.loader.lubm import VirtualLubmStrings
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.planner.stats import Stats
    from wukong_tpu.sparql.parser import Parser

    ss = VirtualLubmStrings(2560, seed=0)
    planner = Planner(Stats.load(STATS))
    cap_max = Global.table_capacity_max
    level_bytes = 2 * 4 * cap_max  # (vals, parent) int32 at full class
    for k in range(1, 8):
        q = Parser(ss).parse(open(f"{BASIC}/lubm_q{k}").read())
        planner.generate_plan(q)
        if q.planner_empty:
            continue
        pats = q.pattern_group.patterns
        if any(p.predicate < 0 for p in pats):
            continue  # host-path shape, no device chain to budget
        index_mode = pats[0].subject < NORMAL_ID_START
        folds = MergeExecutor._plan_folds(pats, index_mode=index_mode)
        pins = MergeExecutor._chain_pins(pats, folds, index_mode=index_mode)
        pin_bytes = 0
        for key in pins:
            if key[0] in ("mrg", "mrgf"):
                # expands pin both merge and bucket forms but stage only
                # ONE at runtime; the merge form bounds both (bucket form
                # is 3 flat bucket arrays + edges, same magnitude), so
                # count each expand once here and skip its bucket twin
                nk, ne = store_meta.get((key[1], key[2]), (0, 0))
                pin_bytes += _staged_bytes(nk, ne)  # mrgf <= unfiltered
            elif key[0] == "rev":  # rev list: bounded by the key count
                nk, _ = store_meta.get((key[1], key[2]), (0, 0))
                pin_bytes += 4 * _pow2(nk)
            # bare (pid, d) / ("segf", ...) bucket twins: counted above
        expands = sum(1 for (_s, _p, kind, _f) in MergeExecutor.classify(
            pats, folds, index_mode) if kind == "expand")
        state_bytes = (expands + 1) * level_bytes
        workspace = 3 * level_bytes
        need = pin_bytes + state_bytes + workspace
        assert need <= HBM_BYTES, (
            f"lubm_q{k}: pins {pin_bytes / 2**30:.2f} GiB + state "
            f"{state_bytes / 2**30:.2f} GiB + sort workspace "
            f"{workspace / 2**30:.2f} GiB = {need / 2**30:.2f} GiB > 16 GiB")
