"""Attribute-triple queries end-to-end (enable_vattr path)."""

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.engine.tpu import TPUEngine
from wukong_tpu.loader.lubm import (
    A,
    VirtualLubmStrings,
    generate_lubm,
    generate_lubm_attrs,
    write_dataset,
)
from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.sparql.parser import Parser
from wukong_tpu.store.gstore import build_partition

UB = "http://swat.cse.lehigh.edu/onto/univ-bench.owl#"


@pytest.fixture(scope="module")
def world():
    triples, lay = generate_lubm(1, seed=42)
    attrs = generate_lubm_attrs(1, seed=42)
    g = build_partition(triples, 0, 1, attr_triples=attrs)
    ss = VirtualLubmStrings(1, seed=42)
    return triples, attrs, lay, g, ss


def test_attr_storage(world):
    triples, attrs, lay, g, ss = world
    sv, aid, t, val = attrs[0]
    got, has = g.get_attr(sv, aid)
    assert has and got == val
    _, has2 = g.get_attr(123456789, aid)
    assert not has2


def test_attr_query_cpu(world, monkeypatch):
    triples, attrs, lay, g, ss = world
    monkeypatch.setattr(Global, "enable_vattr", True)
    ug0 = ss.id2str(int(lay.ug_base[0]))
    q = Parser(ss).parse(
        f"PREFIX ub: <{UB}>\nSELECT ?Y WHERE {{ {ug0} ub:age ?Y . }}")
    assert q.pattern_group.patterns[0].pred_type == 1  # INT_t from pid2type
    heuristic_plan(q)
    eng = CPUEngine(g, ss)
    eng.execute(q)
    assert q.result.status_code == 0
    want = next(v for (s, a, t, v) in attrs if s == int(lay.ug_base[0]))
    assert q.result.attr_table.tolist() == [[want]]


def test_attr_known_to_unknown(world, monkeypatch):
    triples, attrs, lay, g, ss = world
    monkeypatch.setattr(Global, "enable_vattr", True)
    d0 = "<http://www.Department0.University0.edu>"
    q = Parser(ss).parse(f"""PREFIX ub: <{UB}>
        SELECT ?X ?Y WHERE {{
            ?X ub:memberOf {d0} .
            ?X ub:age ?Y . }}""")
    heuristic_plan(q)
    eng = CPUEngine(g, ss)
    eng.execute(q)
    assert q.result.status_code == 0
    # every member with an age attr (all UG of dept0; GS have no age)
    from wukong_tpu.loader.lubm import P
    from wukong_tpu.types import IN

    by_s = {s: v for (s, a, t, v) in attrs if a == A["age"]}
    members = g.get_triples(int(lay.dept_id[0]), P["memberOf"], IN)
    want = sorted(v for m in members if (v := by_s.get(int(m))) is not None)
    got = sorted(int(r[0]) for r in q.result.attr_table)
    assert got == want


def test_attr_disabled_raises(world, monkeypatch):
    triples, attrs, lay, g, ss = world
    monkeypatch.setattr(Global, "enable_vattr", False)
    ug0 = ss.id2str(int(lay.ug_base[0]))
    q = Parser(ss).parse(
        f"PREFIX ub: <{UB}>\nSELECT ?Y WHERE {{ {ug0} ub:age ?Y . }}")
    heuristic_plan(q)
    eng = CPUEngine(g, ss)
    eng.execute(q)
    # TPU engine must fall back to host for attr patterns under vattr
    monkeypatch.setattr(Global, "enable_vattr", True)
    q2 = Parser(ss).parse(
        f"PREFIX ub: <{UB}>\nSELECT ?Y WHERE {{ {ug0} ub:age ?Y . }}")
    heuristic_plan(q2)
    tpu = TPUEngine(g, ss)
    tpu.execute(q2)
    assert q2.result.status_code == 0
    assert q2.result.attr_table.size == 1


def test_attr_files_roundtrip(tmp_path):
    from wukong_tpu.loader.base import load_attr_triples, load_dataset
    from wukong_tpu.store.string_server import StringServer

    meta = write_dataset(str(tmp_path), 1, seed=7)
    assert meta["num_attrs"] > 0
    rows = load_attr_triples(str(tmp_path))
    assert len(rows) == meta["num_attrs"]
    ss = StringServer(str(tmp_path))
    assert ss.pid2type[A["age"]] == 1
    stores = load_dataset(str(tmp_path), 1)
    sv, aid, t, val = rows[0]
    got, has = stores[0].get_attr(sv, aid)
    assert has and got == val
