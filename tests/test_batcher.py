"""Serving-path micro-batching (runtime/batcher.py) + parse/plan caches.

Pins the PR's contract: batched execution is byte-identical to sequential
execution (and to the independent BGP oracle), flushes happen on window age
vs size, deadline-tight and incompatible queries bypass, a mid-batch
deadline/budget event degrades only the affected member, a failing fused
dispatch falls back per-query (and trips the batch breaker), and the plan
cache invalidates on dynamic inserts / stream commits.
"""

import threading
import time

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.engine.tpu import TPUEngine
from wukong_tpu.loader.lubm import UB, VirtualLubmStrings, generate_lubm
from wukong_tpu.runtime import batcher as B
from wukong_tpu.runtime.batcher import (
    FusedGroup,
    QueryBatcher,
    _Pending,
    batchable,
    fused_key,
    template_signature,
)
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.runtime.resilience import Deadline
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.utils.errors import ErrorCode
from wukong_tpu.utils.lru import LRUCache

pytestmark = pytest.mark.batch


@pytest.fixture(autouse=True, scope="module")
def _lockdep_checked():
    """PR 6: the batch suite runs with the lockdep runtime checker on —
    the batcher condition / group locks / pool lanes feed the
    acquisition-order graph on every test. Teardown asserts zero order
    cycles and zero declared-leaf inversions."""
    from wukong_tpu.analysis import lockdep

    lockdep.install(True)
    yield
    try:
        assert lockdep.cycles() == [], lockdep.cycles()
        assert lockdep.leaf_violations() == [], lockdep.leaf_violations()
    finally:
        lockdep.install(False)


@pytest.fixture(scope="module")
def world():
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    proxy = Proxy(g, ss, CPUEngine(g, ss), TPUEngine(g, ss))
    return {"g": g, "ss": ss, "proxy": proxy, "triples": triples}


@pytest.fixture(autouse=True)
def _batching_off_after(monkeypatch):
    """Every test starts and ends with the default (batching off)."""
    monkeypatch.setattr(Global, "enable_batching", False)
    yield


def _texts(world, n=6, shape="chain"):
    """Same-template query texts differing only in the start constant."""
    ss, g = world["ss"], world["g"]
    from wukong_tpu.types import OUT

    pid = ss.str2id(f"<{UB}memberOf>")
    depts = np.asarray(g.get_index(pid, OUT))[:n]
    out = []
    for d in depts:
        diri = ss.id2str(int(d))
        if shape == "const":
            out.append(f"SELECT ?s WHERE {{ ?s <{UB}memberOf> {diri} . }}")
        elif shape == "chain":
            out.append(
                f"SELECT ?s ?c WHERE {{ ?s <{UB}memberOf> {diri} . "
                f"?s <{UB}takesCourse> ?c . }}")
        elif shape == "filter":
            out.append(
                f"SELECT ?s ?c WHERE {{ ?s <{UB}memberOf> {diri} . "
                f"?s <{UB}takesCourse> ?c . FILTER (?s != ?c) }}")
        else:
            raise AssertionError(shape)
    return out


def _planned(proxy, text, blind=True, deadline=None):
    """A parsed+planned query, serving-path style (no execution)."""
    q = proxy._parse_text(text)
    proxy._plan_prepared(q, blind, None)
    q.deadline = deadline
    return q


# ---------------------------------------------------------------------------
# result fidelity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("shape", ["const", "chain", "filter"])
def test_batched_byte_identical_to_sequential(world, monkeypatch, shape):
    proxy = world["proxy"]
    texts = _texts(world, n=6, shape=shape)
    seq = [proxy.serve_query(t, blind=False) for t in texts]
    seq_tables = [np.asarray(q.result.table) for q in seq]
    assert all(q.result.status_code == ErrorCode.SUCCESS for q in seq)
    assert any(len(t) for t in seq_tables)

    monkeypatch.setattr(Global, "enable_batching", True)
    monkeypatch.setattr(Global, "batch_window_us", 100_000)
    out = [None] * len(texts)

    def go(i):
        out[i] = proxy.serve_query(texts[i], blind=False)

    ths = [threading.Thread(target=go, args=(i,)) for i in range(len(texts))]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    for i, q in enumerate(out):
        assert q.result.status_code == ErrorCode.SUCCESS
        assert np.array_equal(np.asarray(q.result.table), seq_tables[i]), i
        assert q.result.v2c_map == seq[i].result.v2c_map


def test_batched_matches_oracle(world, monkeypatch):
    """Fused results == the independent index-nested-loop oracle."""
    from tests.bgp_oracle import TripleIndex, eval_bgp

    proxy, ss = world["proxy"], world["ss"]
    idx = TripleIndex(world["triples"])
    pid_m = ss.str2id(f"<{UB}memberOf>")
    pid_t = ss.str2id(f"<{UB}takesCourse>")
    texts = _texts(world, n=4, shape="chain")
    from wukong_tpu.types import OUT

    depts = np.asarray(world["g"].get_index(pid_m, OUT))[:4]

    monkeypatch.setattr(Global, "enable_batching", True)
    monkeypatch.setattr(Global, "batch_window_us", 100_000)
    out = [None] * len(texts)

    def go(i):
        out[i] = proxy.serve_query(texts[i], blind=False)

    ths = [threading.Thread(target=go, args=(i,)) for i in range(len(texts))]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    for i, q in enumerate(out):
        # oracle patterns as written: ?s memberOf <dept> . ?s takes ?c
        want = sorted(eval_bgp(idx, [(-1, pid_m, int(depts[i])),
                                     (-1, pid_t, -2)], [-1, -2]))
        got = sorted(tuple(int(x) for x in row)
                     for row in np.asarray(q.result.table))
        assert got == want, i


# ---------------------------------------------------------------------------
# coalescing mechanics: flush reasons, bypasses
# ---------------------------------------------------------------------------

def _counter(name, **labels):
    from wukong_tpu.obs import get_registry

    m = get_registry()._metrics.get(name)
    if m is None:
        return 0.0
    return m.value(**labels) if labels else m.value()


def _hold_inflight(bt):
    """Pretend a dispatch is executing, so offers accumulate instead of
    idle-flushing — the deterministic stand-in for concurrent load."""
    with bt._lock:
        bt._inflight += 1


def _release_inflight(bt):
    with bt._lock:
        bt._inflight = max(bt._inflight - 1, 0)


def test_flush_on_size(world, monkeypatch):
    proxy = world["proxy"]
    monkeypatch.setattr(Global, "enable_batching", True)
    monkeypatch.setattr(Global, "batch_window_us", 10_000_000)
    monkeypatch.setattr(Global, "batch_max_size", 4)
    bt = proxy.batcher()
    _hold_inflight(bt)  # a "running dispatch": arrivals must accumulate
    try:
        before = _counter("wukong_batch_flush_total", reason="size")
        texts = _texts(world, n=4, shape="chain")
        pends = [bt.offer(_planned(proxy, t)) for t in texts]
        assert all(p is not None for p in pends)
        for p in pends:  # the 4th offer flushed the group synchronously
            p.wait(timeout=30)
        assert _counter("wukong_batch_flush_total",
                        reason="size") == before + 1
        assert all(p.q.result.status_code == ErrorCode.SUCCESS
                   for p in pends)
    finally:
        _release_inflight(bt)


def test_flush_on_window_timeout(world, monkeypatch):
    proxy = world["proxy"]
    monkeypatch.setattr(Global, "enable_batching", True)
    monkeypatch.setattr(Global, "batch_window_us", 20_000)
    monkeypatch.setattr(Global, "batch_max_size", 64)
    bt = proxy.batcher()
    _hold_inflight(bt)  # arrivals accumulate behind the "running" dispatch
    try:
        before = _counter("wukong_batch_flush_total", reason="window")
        p = bt.offer(_planned(proxy, _texts(world, n=1)[0]))
        assert p is not None
        p.wait(timeout=30)  # nobody joined: the window must release it
        assert _counter("wukong_batch_flush_total",
                        reason="window") >= before + 1
        assert p.q.result.status_code == ErrorCode.SUCCESS
    finally:
        _release_inflight(bt)


def test_idle_flush_skips_window(world, monkeypatch):
    """Nothing executing, nothing queued: a lone query dispatches
    immediately (reason=idle) instead of waiting out the window."""
    proxy = world["proxy"]
    monkeypatch.setattr(Global, "enable_batching", True)
    monkeypatch.setattr(Global, "batch_window_us", 5_000_000)
    bt = proxy.batcher()
    before = _counter("wukong_batch_flush_total", reason="idle")
    t0 = time.monotonic()
    p = bt.offer(_planned(proxy, _texts(world, n=1)[0]))
    assert p is not None
    p.wait(timeout=30)
    assert time.monotonic() - t0 < 4  # never saw the 5s window
    assert _counter("wukong_batch_flush_total", reason="idle") == before + 1
    assert p.q.result.status_code == ErrorCode.SUCCESS


def test_deadline_tight_bypasses(world, monkeypatch):
    proxy = world["proxy"]
    monkeypatch.setattr(Global, "enable_batching", True)
    monkeypatch.setattr(Global, "batch_window_us", 50_000)
    bt = proxy.batcher()
    q = _planned(proxy, _texts(world, n=1)[0],
                 deadline=Deadline(timeout_ms=50))  # < 4x window
    before = _counter("wukong_batch_bypass_total", reason="deadline")
    assert bt.offer(q) is None
    assert _counter("wukong_batch_bypass_total",
                    reason="deadline") == before + 1


def test_row_budget_bypasses(world, monkeypatch):
    """Per-step row budgets can't be attributed inside a fused chain —
    budgeted queries keep exact sequential enforcement."""
    proxy = world["proxy"]
    monkeypatch.setattr(Global, "enable_batching", True)
    bt = proxy.batcher()
    q = _planned(proxy, _texts(world, n=1)[0],
                 deadline=Deadline(budget_rows=100))
    before = _counter("wukong_batch_bypass_total", reason="budget")
    assert bt.offer(q) is None
    assert _counter("wukong_batch_bypass_total",
                    reason="budget") == before + 1


def test_device_pin_bypasses_batcher(world, monkeypatch):
    """An explicit device= request must not be silently rerouted onto the
    batcher's engine choice."""
    proxy = world["proxy"]
    monkeypatch.setattr(Global, "enable_batching", True)
    offered = []
    orig = type(proxy.batcher()).offer

    def spy(self, q):
        offered.append(q)
        return orig(self, q)

    monkeypatch.setattr(type(proxy.batcher()), "offer", spy)
    q = proxy.run_single_query(_texts(world, n=1)[0], device="cpu",
                               blind=True)
    assert q.result.status_code == ErrorCode.SUCCESS
    assert offered == []  # pinned: never entered the batcher


def test_incompatible_shapes_bypass(world, monkeypatch):
    proxy = world["proxy"]
    bt = proxy.batcher()
    # index-origin query: no const start -> not LIGHT-batchable. Since the
    # heavy lane (PR 8) it fuses as the heavy class instead of bypassing,
    # so the shape-bypass exemplar is a NON-BLIND index query (the sliced
    # heavy dispatch returns counts, not tables)
    q = _planned(proxy, "SELECT ?x WHERE { ?x "
                 "<http://www.w3.org/1999/02/22-rdf-syntax-ns#type> "
                 f"<{UB}FullProfessor> . }}", blind=False)
    assert not batchable(q)
    before = _counter("wukong_batch_bypass_total", reason="shape")
    assert bt.offer(q) is None
    assert _counter("wukong_batch_bypass_total", reason="shape") == before + 1
    # and through the proxy, the bypass still executes correctly
    out = proxy.serve_query(
        "SELECT ?x WHERE { ?x "
        "<http://www.w3.org/1999/02/22-rdf-syntax-ns#type> "
        f"<{UB}FullProfessor> . }}", blind=True)
    assert out.result.status_code == ErrorCode.SUCCESS
    assert out.result.nrows > 0


def test_fused_key_groups_only_same_template(world):
    proxy = world["proxy"]
    chain = [_planned(proxy, t) for t in _texts(world, n=2, shape="chain")]
    const = [_planned(proxy, t) for t in _texts(world, n=2, shape="const")]
    filt = [_planned(proxy, t) for t in _texts(world, n=2, shape="filter")]
    assert fused_key(chain[0]) == fused_key(chain[1])
    assert fused_key(const[0]) == fused_key(const[1])
    assert fused_key(chain[0]) != fused_key(const[0])
    assert fused_key(chain[0]) != fused_key(filt[0])  # filters differ
    assert template_signature(chain[0]) == template_signature(chain[1])


# ---------------------------------------------------------------------------
# per-member resilience inside a fused dispatch
# ---------------------------------------------------------------------------

def test_member_deadline_degrades_only_that_member(world, monkeypatch):
    proxy = world["proxy"]
    texts = _texts(world, n=3, shape="chain")
    bt = proxy.batcher()
    t_frozen = [0.0]
    expired = Deadline(timeout_ms=1, clock=lambda: t_frozen[0])
    t_frozen[0] = 10.0  # expired before the flush
    members = [
        _Pending(_planned(proxy, texts[0], blind=False)),
        _Pending(_planned(proxy, texts[1], blind=False, deadline=expired)),
        _Pending(_planned(proxy, texts[2], blind=False)),
    ]
    FusedGroup(members, bt, engine=None).run(None)
    ok0, bad, ok2 = (m.q.result for m in members)
    assert ok0.status_code == ErrorCode.SUCCESS and ok0.nrows > 0
    assert ok2.status_code == ErrorCode.SUCCESS and ok2.nrows > 0
    assert bad.status_code == ErrorCode.QUERY_TIMEOUT
    assert not bad.complete


def test_member_budget_charged_per_member(world):
    """A fused dispatch charges each member its own rows: the tiny-budget
    member degrades to a partial result, co-members are untouched."""
    proxy = world["proxy"]
    texts = _texts(world, n=2, shape="chain")
    bt = proxy.batcher()
    members = [
        _Pending(_planned(proxy, texts[0], blind=False)),
        _Pending(_planned(proxy, texts[1], blind=False,
                          deadline=Deadline(budget_rows=1))),
    ]
    FusedGroup(members, bt, engine=None).run(None)
    ok, bad = (m.q.result for m in members)
    assert ok.status_code == ErrorCode.SUCCESS and ok.nrows > 0
    assert bad.status_code == ErrorCode.BUDGET_EXCEEDED
    assert not bad.complete


def test_fused_failure_falls_back_per_query(world, monkeypatch):
    """A failing fused dispatch degrades to per-query execution: every
    member still gets its correct result."""
    proxy = world["proxy"]
    texts = _texts(world, n=3, shape="chain")
    seq_rows = [proxy.serve_query(t, blind=True).result.nrows for t in texts]
    bt = QueryBatcher(proxy.cpu, None)
    try:
        monkeypatch.setattr(
            FusedGroup, "_run_fused",
            lambda self, live, engine: (_ for _ in ()).throw(
                RuntimeError("chain exploded")))
        before = _counter("wukong_batch_fallback_total",
                          reason="dispatch_error")
        members = [_Pending(_planned(proxy, t)) for t in texts]
        FusedGroup(members, bt, engine=None).run(None)
        assert _counter("wukong_batch_fallback_total",
                        reason="dispatch_error") == before + 1
        for m, want in zip(members, seq_rows):
            assert m.q.result.status_code == ErrorCode.SUCCESS
            assert m.q.result.nrows == want
    finally:
        bt.close()


def test_breaker_opens_after_repeated_fused_failures(world, monkeypatch):
    """Consecutive fused failures open the batch breaker; while open,
    groups go straight to per-query execution without attempting the
    fused dispatch."""
    proxy = world["proxy"]
    texts = _texts(world, n=2, shape="chain")
    bt = QueryBatcher(proxy.cpu, None)
    try:
        calls = []

        def boom(self, live, engine):
            calls.append(len(live))
            raise RuntimeError("chain exploded")

        monkeypatch.setattr(FusedGroup, "_run_fused", boom)
        for _ in range(Global.breaker_threshold):
            members = [_Pending(_planned(proxy, t)) for t in texts]
            FusedGroup(members, bt, engine=None).run(None)
        assert len(calls) == Global.breaker_threshold
        assert bt.breaker.state("batch.dispatch") == "open"
        before = _counter("wukong_batch_fallback_total",
                          reason="breaker_open")
        members = [_Pending(_planned(proxy, t)) for t in texts]
        FusedGroup(members, bt, engine=None).run(None)
        assert len(calls) == Global.breaker_threshold  # fused NOT attempted
        assert _counter("wukong_batch_fallback_total",
                        reason="breaker_open") == before + 1
        for m in members:  # still served, per-query
            assert m.q.result.status_code == ErrorCode.SUCCESS
    finally:
        bt.close()


# ---------------------------------------------------------------------------
# scheduler batch lane
# ---------------------------------------------------------------------------

def test_batch_lane_executes_group_as_unit(world):
    proxy = world["proxy"]
    pool = proxy.engine_pool()
    bt = proxy.batcher()
    texts = _texts(world, n=4, shape="chain")
    members = [_Pending(_planned(proxy, t)) for t in texts]
    group = FusedGroup(members, bt, engine=None)
    assert pool.submit(group, lane="batch") == -1
    for m in members:
        m.wait(timeout=30)
        assert m.q.result.status_code == ErrorCode.SUCCESS
    # fire-and-forget: no stranded pool completions for poll() consumers
    assert pool.poll() == []


# ---------------------------------------------------------------------------
# parse/plan caches
# ---------------------------------------------------------------------------

def test_parse_and_plan_cache_hit(world):
    proxy = world["proxy"]
    text = _texts(world, n=1)[0]
    h0 = proxy._parse_cache.hits
    p0 = proxy._plan_cache.stats()["hits"]
    proxy.serve_query(text, blind=True)
    proxy.serve_query(text, blind=True)
    assert proxy._parse_cache.hits > h0
    assert proxy._plan_cache.stats()["hits"] > p0


def test_plan_cache_shared_across_same_template(world):
    """Different constants, same template: the second query replays the
    first's plan recipe instead of replanning."""
    proxy = world["proxy"]
    t1, t2 = _texts(world, n=2, shape="chain")
    proxy._plan_cache.clear()
    proxy.serve_query(t1, blind=True)
    h0 = proxy._plan_cache.stats()["hits"]
    q2 = proxy.serve_query(t2, blind=True)
    assert proxy._plan_cache.stats()["hits"] == h0 + 1
    assert q2.result.status_code == ErrorCode.SUCCESS


def test_plan_cache_invalidated_on_dynamic_insert(world):
    """A store-version bump (dynamic insert / stream commit both go through
    insert_triples) makes every cached plan key stale: the next query
    re-plans instead of replaying."""
    from wukong_tpu.store.dynamic import insert_triples

    proxy, g = world["proxy"], world["g"]
    text = _texts(world, n=1)[0]
    proxy.serve_query(text, blind=True)
    m0 = proxy._plan_cache.stats()["misses"]
    proxy.serve_query(text, blind=True)
    assert proxy._plan_cache.stats()["misses"] == m0  # warm: replayed
    # re-insert an existing edge with dedup: zero data change, version bump
    tri = world["triples"][:1].copy()
    assert insert_triples(g, tri, dedup=True) == 0
    q = proxy.serve_query(text, blind=True)
    assert proxy._plan_cache.stats()["misses"] == m0 + 1  # stale key: replan
    assert q.result.status_code == ErrorCode.SUCCESS


def test_dynamic_load_clears_plan_cache(world, tmp_path):
    proxy = world["proxy"]
    text = _texts(world, n=1)[0]
    proxy.serve_query(text, blind=True)
    assert len(proxy._plan_cache._lru) > 0
    np.save(tmp_path / "id_triples.npy", world["triples"][:1])
    proxy.dynamic_load_data(str(tmp_path), check_dup=True)
    assert len(proxy._plan_cache._lru) == 0


# ---------------------------------------------------------------------------
# satellites: LRU est-cache, lint gate
# ---------------------------------------------------------------------------

def test_lru_cache_bounded_and_recency():
    lru = LRUCache(maxsize=3)
    for k in range(3):
        lru.put(k, k * 10)
    assert lru.get(0) == 0  # refresh 0's recency
    lru.put(3, 30)  # evicts 1 (coldest), not 0
    assert lru.get(0) == 0 and lru.get(3) == 30
    assert lru.get(1) is None
    assert len(lru) == 3


def test_est_cache_is_bounded_lru(world):
    eng = world["proxy"].tpu
    assert isinstance(eng._est_cache, LRUCache)
    assert eng._est_cache.maxsize == 4096


def test_lint_gate_flags_batcher_bypass(tmp_path):
    import importlib.util
    import os
    import sys

    spec = importlib.util.spec_from_file_location(
        "lint_obs", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "lint_obs.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    # the real tree is clean
    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "wukong_tpu")
    assert lint.violations(pkg) == []
    # an un-allowlisted direct execute under runtime/ is flagged
    rt = tmp_path / "runtime"
    rt.mkdir()
    (rt / "sneaky.py").write_text(
        "def fast_path(eng, q):\n    return eng.execute(q)\n")
    bad = lint.violations(str(tmp_path))
    assert len(bad) == 1 and "batcher entry point" in bad[0]
