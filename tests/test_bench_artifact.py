"""Artifact hygiene for the driver-facing bench headline.

The driver records a bounded tail of bench.py stdout; round 4's final line
carried full per-query detail inline, outgrew that window, and the round's
headline parsed as null (BENCH_r04.json). These tests pin the new contract:
the LAST stdout line is a compact headline hard-capped at
bench.HEADLINE_MAX_BYTES, and the full object lands in a committed side
file the headline points at."""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def bench(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # side files must land in the sandbox, not over the committed artifacts
    monkeypatch.setattr(mod, "REPO", str(tmp_path))
    return mod


def _last_line(capsys) -> str:
    out = capsys.readouterr().out.rstrip("\n")
    return out.splitlines()[-1]


def test_huge_detail_stays_under_cap(bench, tmp_path, capsys):
    detail = {f"lubm_q{i}": {"us": 1.5 * i, "rows": i,
                             "cap_classes": {str(j): 1 << 20 for j in range(9)},
                             "bytes_model": {"segment_bytes": 123456789,
                                             "table_bytes": 987654321,
                                             "total_bytes": 1111111110},
                             "chain": [{"step": j, "peak": j * 7}
                                       for j in range(12)]}
              for i in range(200)}
    bench._emit_final({"metric": "m" * 400, "value": 1.0, "unit": "us",
                       "vs_baseline": None, "backend": "cpu",
                       "dataset": bench.DATASET_NOTES["lubm"],
                       "detail": detail}, "SIDE.json")
    line = _last_line(capsys)
    assert len(line.encode()) <= bench.HEADLINE_MAX_BYTES
    head = json.loads(line)
    for k in ("metric", "value", "unit", "vs_baseline", "backend"):
        assert k in head
    side = json.load(open(tmp_path / "SIDE.json"))
    assert set(side["detail"]) == set(detail)  # nothing truncated in the file


def test_normal_headline_keeps_per_query_us_and_dataset(bench, tmp_path,
                                                        capsys):
    detail = {f"lubm_q{i}": {"us": float(i + 1), "rows": i} for i in range(7)}
    detail["sparql_emu"] = {"qps": 1234.5, "warm_qps": 9876.5}
    bench._emit_final({"metric": "small", "value": 2.0, "unit": "us",
                       "vs_baseline": 1.5, "backend": "tpu",
                       "dataset": bench.DATASET_NOTES["lubm"],
                       "detail": detail}, "SIDE.json")
    head = json.loads(_last_line(capsys))
    assert head["per_query_us"]["lubm_q3"] == 4.0
    assert head["emu_qps"] == 1234.5 and head["emu_warm_qps"] == 9876.5
    assert "synthetic-lubm" in head["dataset"]
    assert head["detail_file"] == "SIDE.json"
    assert len(json.dumps(head).encode()) <= bench.HEADLINE_MAX_BYTES


def test_runaway_metric_is_truncated(bench, capsys):
    bench._emit_final({"metric": "x" * 5000, "value": 1, "unit": "us",
                       "vs_baseline": None, "backend": "cpu"})
    line = _last_line(capsys)
    assert len(line.encode()) <= bench.HEADLINE_MAX_BYTES + 400
    json.loads(line)  # still one parseable JSON object


def test_side_file_failure_does_not_kill_headline(bench, monkeypatch,
                                                  capsys):
    monkeypatch.setattr(bench, "REPO", "/nonexistent/dir/zzz")
    bench._emit_final({"metric": "m", "value": 1, "unit": "us",
                       "vs_baseline": None, "backend": "cpu",
                       "detail": {"q": {"us": 1.0}}}, "SIDE.json")
    head = json.loads(_last_line(capsys))
    assert head["value"] == 1 and "detail_file" not in head


# ---------------------------------------------------------------------------
# Partial-store contracts behind the flaky-relay capture path: provisional
# stubs bank per trial, OOM restarts invalidate what they disprove, and
# ladder-rung evidence surfaces without violating freshness/version rules.
# ---------------------------------------------------------------------------


@pytest.fixture()
def pstore(bench, tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "CACHE", str(tmp_path))
    monkeypatch.setattr(bench, "PARTIAL_PATH",
                        str(tmp_path / "bench_partial.json"))
    return bench


def test_oom_drop_removes_provisional_stub(pstore):
    pstore._record_partial(40, "lubm_q1", "tpu",
                           {"us": 80.2, "batch": 1024, "provisional": True})
    assert pstore._best_tpu_partial(40, "lubm_q1") is not None
    pstore._drop_partial(40, "lubm_q1", "tpu", above_batch=512)
    assert pstore._best_tpu_partial(40, "lubm_q1") is None


def test_oom_drop_keeps_smaller_batch_complete_entry(pstore):
    pstore._record_partial(40, "lubm_q2", "tpu", {"us": 99.0, "batch": 256})
    pstore._drop_partial(40, "lubm_q2", "tpu", above_batch=512)
    got = pstore._best_tpu_partial(40, "lubm_q2")
    assert got is not None and got["us"] == 99.0


def test_oom_drop_removes_larger_batch_complete_entry(pstore):
    # a complete entry at a batch the chip just refused claims a
    # configuration this process disproved
    pstore._record_partial(40, "lubm_q3", "tpu", {"us": 50.0, "batch": 1024})
    pstore._drop_partial(40, "lubm_q3", "tpu", above_batch=512)
    assert pstore._best_tpu_partial(40, "lubm_q3") is None


def test_other_scale_evidence_filters_stale_and_groups(pstore, tmp_path):
    import json as _json

    queries = [f"lubm_q{i}" for i in range(1, 8)]
    pstore._record_partial(40, "lubm_q4", "tpu", {"us": 5.0, "batch": 1024})
    pstore._record_partial(160, "lubm_q7", "tpu", {"us": 7.0, "batch": 64})
    pstore._record_partial(40, "lubm_q5", "cpu", {"us": 2.0, "batch": 1024})
    # stale entry: must never surface (freshness contract)
    store = pstore._load_partial()
    key = pstore._partial_key(160, "lubm_q6", "tpu")
    store[key] = {"us": 1.0, "batch": 8, "ts": "2020-01-01T00:00:00"}
    with open(tmp_path / "bench_partial.json", "w") as f:
        _json.dump(store, f)
    got = pstore._other_scale_tpu_evidence(
        2560, queries, pstore._load_partial())
    assert got == {"40": {"lubm_q4": 5.0}, "160": {"lubm_q7": 7.0}}
    # entries at the target scale itself are excluded (they feed the
    # headline geomean instead)
    pstore._record_partial(2560, "lubm_q1", "tpu", {"us": 9.0, "batch": 2})
    got = pstore._other_scale_tpu_evidence(
        2560, queries, pstore._load_partial())
    assert "lubm_q1" not in got.get("2560", {})
