"""Core binding (reference: core/bind.hpp — topology, core.bind, setaffinity)."""

import os

import pytest

from wukong_tpu.runtime.bind import CoreBinder, _parse_cpulist


def test_parse_cpulist():
    assert _parse_cpulist("0-3,8,10-11") == [0, 1, 2, 3, 8, 10, 11]
    assert _parse_cpulist("0\n") == [0]
    assert _parse_cpulist("") == []


def test_topology_discovered():
    b = CoreBinder()
    assert b.num_cores >= 1
    assert len(b.cpu_topo) >= 1
    # default bindings cover every discovered core exactly once
    assert sorted(b.default_bindings) == sorted(
        c for node in b.cpu_topo for c in node)
    assert b.core_of(0) == b.default_bindings[0]
    # round-robin wrap
    assert b.core_of(b.num_cores) == b.default_bindings[0]


def test_core_bind_file(tmp_path):
    b = CoreBinder()
    # synthetic 2-node topology (the reference cluster shape, bind.hpp:37-61)
    b.cpu_topo = [[0, 2, 4], [1, 3, 5]]
    b.default_bindings = [0, 2, 4, 1, 3, 5]
    f = tmp_path / "core.bind"
    f.write_text("# comment\n0 1 4\n2 3\n")
    assert b.load_core_binding(str(f))
    assert b.enabled
    # line 1 -> node 0 cores in order; line 2 -> node 1
    assert b.core_bindings[0] == 0
    assert b.core_bindings[1] == 2
    assert b.core_bindings[4] == 4
    assert b.core_bindings[2] == 1
    assert b.core_bindings[3] == 3
    # unmapped tid falls back to default round-robin
    assert b.core_of(5) == b.default_bindings[5]


def test_core_bind_missing_file():
    b = CoreBinder()
    assert not b.load_core_binding("/nonexistent/core.bind")
    assert not b.enabled


@pytest.mark.skipif(not hasattr(os, "sched_setaffinity"),
                    reason="no sched_setaffinity on this platform")
def test_bind_and_unbind_roundtrip():
    b = CoreBinder()
    before = b.get_core_binding()
    if b.num_cores > 1:
        b.enabled = True
        assert b.bind_thread(0)
        assert b.get_core_binding() == {b.core_of(0)}
    else:
        # single-core host: binding is a documented no-op
        assert not b.bind_thread(0)
    b.bind_to_all()
    assert b.get_core_binding() == set(b.default_bindings) or not before


def test_engine_pool_binds_threads(monkeypatch):
    """EnginePool threads call bind_thread(tid) on startup."""
    import wukong_tpu.runtime.bind as bind_mod
    from wukong_tpu.runtime.scheduler import EnginePool

    seen = []

    class FakeBinder:
        def bind_thread(self, tid):
            seen.append(tid)
            return True

    monkeypatch.setattr(bind_mod, "_binder", FakeBinder())

    class Echo:
        def execute(self, q):
            return q

    pool = EnginePool(num_engines=2, make_engine=lambda tid: Echo())
    pool.start()
    try:
        qid = pool.submit("x")
        assert pool.wait(qid, timeout=5) == "x"
    finally:
        pool.stop()
    assert sorted(seen) == [0, 1]
