"""Planner-driven chain capacity estimation (ROADMAP lever 2).

estimate_chain walks the joint-type-table model over an ALREADY-ORDERED plan
(the engine's execution order) and must track true intermediate sizes closely
enough that capacity classes stop over-provisioning (each 2x of slack doubles
every kernel's cost). The oracle here is the CPU engine's actual row counts.
"""

import numpy as np
import pytest

from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.planner.optimizer import Planner
from wukong_tpu.planner.stats import Stats
from wukong_tpu.sparql.parser import Parser

BASIC = "/root/reference/scripts/sparql_query/lubm/basic"


@pytest.fixture(scope="module")
def world():
    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
    from wukong_tpu.store.gstore import build_partition

    triples, _ = generate_lubm(1, seed=0)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=0)
    stats = Stats.generate(triples)
    return g, ss, stats


def _true_step_rows(g, ss, q):
    """Actual row count after each pattern step, from the CPU oracle."""
    from wukong_tpu.engine.cpu import CPUEngine

    eng = CPUEngine(g, ss)
    rows = []
    while not q.done_patterns():
        eng._execute_one_pattern(q)
        rows.append(q.result.nrows)
    return rows


@pytest.mark.parametrize("qn", ["lubm_q1", "lubm_q2", "lubm_q4", "lubm_q7"])
def test_estimate_chain_tracks_true_rows(world, qn):
    g, ss, stats = world
    q = Parser(ss).parse(open(f"{BASIC}/{qn}").read())
    heuristic_plan(q)
    est = Planner(stats).estimate_chain(q.pattern_group.patterns)
    assert est is not None and len(est) == len(q.pattern_group.patterns)
    true_rows = _true_step_rows(g, ss, q)
    # each step's estimate must be within 8x of truth in both directions
    # (one capacity class of slack is 2x; 8x still saves >=2 classes vs the
    # old compounding-fanout estimates that overshot by 30x+)
    for k, (e, t) in enumerate(zip(est, true_rows)):
        if t == 0:
            continue  # empty intermediates: any small estimate is fine
        # over-provisioning is the perf-critical direction (capacity = cost);
        # underestimates only cost one overflow retry, so the lower bound is
        # a loose sanity check (LUBM-1's fine_type shares are noisy)
        assert e <= max(8 * t, 64), f"{qn} step {k}: est {e} >> true {t}"
        assert e >= t / 64, f"{qn} step {k}: est {e} << true {t}"


def test_estimate_chain_none_without_walkable_start(world):
    _, ss, stats = world
    q = Parser(ss).parse(open(f"{BASIC}/lubm_q4").read())
    heuristic_plan(q)
    pats = list(q.pattern_group.patterns)
    # drop the start pattern: the remaining chain anchors on an unbound var
    assert Planner(stats).estimate_chain(pats[1:]) is None
    assert Planner(stats).estimate_chain([]) is None


def test_tpu_engine_uses_estimates_and_stays_correct(world):
    """With estimates wired in, capacities shrink but results must not change
    (the overflow-retry net catches underestimates)."""
    jax = pytest.importorskip("jax")
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.engine.tpu import TPUEngine

    g, ss, stats = world
    eng = TPUEngine(g, ss, stats=stats)
    ref = CPUEngine(g, ss)
    for qn in ["lubm_q1", "lubm_q4", "lubm_q7"]:
        q1 = Parser(ss).parse(open(f"{BASIC}/{qn}").read())
        heuristic_plan(q1)
        eng.execute(q1, from_proxy=False)
        q2 = Parser(ss).parse(open(f"{BASIC}/{qn}").read())
        heuristic_plan(q2)
        ref.execute(q2, from_proxy=False)
        assert q1.result.nrows == q2.result.nrows, qn
        a = np.asarray(q1.result.table)
        b = np.asarray(q2.result.table)
        assert a.shape == b.shape
        ra = set(map(tuple, a.tolist()))
        rb = set(map(tuple, b.tolist()))
        assert ra == rb, qn


def test_underestimate_triggers_retry_not_row_loss(world):
    """Force tiny estimates: compact_to/expand must overflow, retry, and
    still produce the full result set."""
    pytest.importorskip("jax")
    from wukong_tpu.engine.tpu import TPUEngine

    g, ss, stats = world
    eng = TPUEngine(g, ss, stats=stats)
    orig = eng._chain_estimates
    eng._chain_estimates = lambda pats: {k: 1.0 for k in range(len(pats))}
    try:
        q = Parser(ss).parse(open(f"{BASIC}/lubm_q1").read())
        heuristic_plan(q)
        eng.execute(q, from_proxy=False)
        assert q.result.status_code == 0
        n_forced = q.result.nrows
    finally:
        eng._chain_estimates = orig
    q2 = Parser(ss).parse(open(f"{BASIC}/lubm_q1").read())
    heuristic_plan(q2)
    eng2 = TPUEngine(g, ss, stats=stats)
    eng2.execute(q2, from_proxy=False)
    assert n_forced == q2.result.nrows


def test_suggest_index_batch_scales_with_estimates(world):
    """Accurate estimates must allow a reasonable heavy-query batch size."""
    pytest.importorskip("jax")
    from wukong_tpu.engine.tpu import TPUEngine

    g, ss, stats = world
    eng = TPUEngine(g, ss, stats=stats)
    q = Parser(ss).parse(open(f"{BASIC}/lubm_q1").read())
    heuristic_plan(q)
    b_est = eng.suggest_index_batch(q)
    assert b_est >= 1
    eng_nostats = TPUEngine(g, ss)
    q2 = Parser(ss).parse(open(f"{BASIC}/lubm_q1").read())
    heuristic_plan(q2)
    assert eng_nostats.suggest_index_batch(q2) >= 1
