"""Chaos suite: deterministic fault injection over the resilience layer.

Every test runs off a seeded :class:`FaultPlan` (runtime/faults.py) or an
injected fake clock, so the "chaos" here is exactly replayable — same seed,
same failure schedule — and the suite is as deterministic as any other
module. Covers the Deadline/budget machinery, retry with backoff + jitter,
the circuit-breaker state machine, graceful degradation to partial results
(engine- and dist-level), and the engine pool's load-shedding path.
"""

import random

import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.runtime import faults
from wukong_tpu.runtime.faults import (
    FaultPlan,
    FaultSpec,
    TransientFault,
    parse_plan,
)
from wukong_tpu.runtime.resilience import CircuitBreaker, Deadline, retry_call
from wukong_tpu.runtime.scheduler import EnginePool
from wukong_tpu.sparql.parser import Parser
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.utils.errors import (
    BudgetExceeded,
    ErrorCode,
    QueryTimeout,
    RetryExhausted,
    ShardUnavailable,
    WukongError,
)

pytestmark = pytest.mark.chaos

# Inline queries (no dependency on the reference checkout): a 2-hop chain
# whose step-0 index scan seeds thousands of rows, and a const-anchored
# lookup — both inside the distributed engine's BGP support matrix.
Q2HOP = """
PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
SELECT ?X ?Y ?Z WHERE {
    ?X ub:memberOf ?Y .
    ?Y ub:subOrganizationOf ?Z .
}
"""
QDEPT = """
PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
SELECT ?X WHERE {
    ?X ub:worksFor <http://www.Department0.University0.edu> .
    ?X rdf:type ub:FullProfessor .
}
"""


@pytest.fixture(autouse=True, scope="module")
def _lockdep_checked():
    """PR 6: the whole chaos suite runs with the lockdep runtime checker
    enabled — every lock the suite's pools/batchers/WALs create is a
    Debug wrapper feeding the acquisition-order graph, so every existing
    concurrency test doubles as a lock-order regression test. Teardown
    asserts the suite produced zero order cycles and zero declared-leaf
    inversions."""
    from wukong_tpu.analysis import lockdep

    lockdep.install(True)
    yield
    try:
        assert lockdep.cycles() == [], lockdep.cycles()
        assert lockdep.leaf_violations() == [], lockdep.leaf_violations()
    finally:
        lockdep.install(False)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.clear()
    yield
    faults.clear()


class FakeClock:
    """Injectable monotonic clock; sleep() advances it (no real waiting)."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def sleep(self, s: float) -> None:
        self.t += s


class SteppingClock:
    """Advances by a fixed step on every read — expires a Deadline after a
    known number of checks without real time passing."""

    def __init__(self, step: float):
        self.t = 0.0
        self.step = step

    def __call__(self) -> float:
        t = self.t
        self.t += self.step
        return t


# ---------------------------------------------------------------------------
# FaultPlan determinism
# ---------------------------------------------------------------------------

def _run_schedule(seed: int, rounds: int = 40) -> list:
    plan = FaultPlan([FaultSpec("a", "transient", p=0.5),
                      FaultSpec("b", "transient", p=0.5)], seed=seed)
    outcomes = []
    for i in range(rounds):
        for site in ("a", "b"):
            try:
                plan.fire(site)
                outcomes.append((site, "ok"))
            except TransientFault:
                outcomes.append((site, "fault"))
    return outcomes


def test_same_seed_same_schedule():
    assert _run_schedule(seed=42) == _run_schedule(seed=42)


def test_different_seed_different_schedule():
    assert _run_schedule(seed=42) != _run_schedule(seed=43)


def test_sites_draw_independent_streams():
    # site b's decisions must not depend on whether site a was called at
    # all — each spec has its own RNG stream derived from (seed, site, idx)
    specs = lambda: [FaultSpec("a", "transient", p=0.5),  # noqa: E731
                     FaultSpec("b", "transient", p=0.5)]
    interleaved = FaultPlan(specs(), seed=7)
    b_only = FaultPlan(specs(), seed=7)

    def draw(plan, site):
        try:
            plan.fire(site)
            return "ok"
        except TransientFault:
            return "fault"

    got_interleaved = []
    got_b_only = []
    for _ in range(30):
        draw(interleaved, "a")
        got_interleaved.append(draw(interleaved, "b"))
        got_b_only.append(draw(b_only, "b"))
    assert got_interleaved == got_b_only


def test_spec_count_after_and_shard_filters():
    plan = FaultPlan([FaultSpec("s", "transient", count=2, after=1, shard=3)],
                     seed=0)
    # wrong shard never fires
    plan.fire("s", shard=1)
    # first matching call skipped (after=1), next two fire, then exhausted
    plan.fire("s", shard=3)
    for _ in range(2):
        with pytest.raises(TransientFault):
            plan.fire("s", shard=3)
    plan.fire("s", shard=3)  # count spent: no-op again
    assert [k for (_, _, k) in plan.history] == ["transient", "transient"]


def test_delay_kind_sleeps():
    clock = FakeClock()
    plan = FaultPlan([FaultSpec("s", "delay", delay_s=0.25)], seed=0,
                     sleep=clock.sleep)
    plan.fire("s")
    assert clock.t == pytest.approx(0.25)


def test_parse_plan_env_form():
    plan = parse_plan("seed=42; dist.shard_fetch:transient,p=0.3,count=2; "
                      "hdfs.read:delay,delay=0.05; pool.execute:shard_down,"
                      "shard=1,after=4")
    assert plan.seed == 42
    a, b, c = plan.specs
    assert (a.site, a.kind, a.p, a.count) == ("dist.shard_fetch",
                                              "transient", 0.3, 2)
    assert (b.site, b.kind, b.delay_s) == ("hdfs.read", "delay", 0.05)
    assert (c.site, c.kind, c.shard, c.after) == ("pool.execute",
                                                  "shard_down", 1, 4)
    with pytest.raises(ValueError):
        parse_plan("x:transient,bogus=1")
    with pytest.raises(ValueError):  # bad kind is a parse-time config error
        parse_plan("hdfs.read:delay=0.05")


def test_env_var_installs_plan(monkeypatch):
    monkeypatch.setenv("WUKONG_FAULT_PLAN", "seed=9;hdfs.read:transient")
    monkeypatch.setitem(faults._state, "plan", None)
    monkeypatch.setitem(faults._state, "env_checked", False)
    plan = faults.active()
    assert plan is not None and plan.seed == 9
    faults.clear()
    assert faults.active() is None  # explicit clear overrides the env var


# ---------------------------------------------------------------------------
# retry with exponential backoff + jitter
# ---------------------------------------------------------------------------

def test_retry_recovers_after_transients():
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise TransientFault("boom")
        return "ok"

    sleeps = []
    out = retry_call(fn, attempts=3, base_ms=10, max_ms=2000,
                     rng=random.Random(0), sleep=sleeps.append)
    assert out == "ok" and calls["n"] == 3
    # equal jitter: delay_i is uniform in [window/2, window] with
    # window = base * 2^i
    assert len(sleeps) == 2
    assert 0.005 <= sleeps[0] <= 0.010
    assert 0.010 <= sleeps[1] <= 0.020


def test_retry_backoff_is_capped():
    sleeps = []

    def fn():
        raise TransientFault("always")

    with pytest.raises(RetryExhausted):
        retry_call(fn, attempts=6, base_ms=10, max_ms=40,
                   rng=random.Random(0), sleep=sleeps.append)
    assert len(sleeps) == 5
    assert all(s <= 0.040 for s in sleeps)


def test_retry_exhausted_carries_last_exception():
    def fn():
        raise TransientFault("persistent")

    with pytest.raises(RetryExhausted) as ei:
        retry_call(fn, attempts=2, base_ms=1, sleep=lambda s: None)
    assert ei.value.code == ErrorCode.RETRY_EXHAUSTED
    assert isinstance(ei.value.last, TransientFault)


def test_retry_nonretryable_propagates_immediately():
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        retry_call(fn, attempts=5, base_ms=1, sleep=lambda s: None)
    assert calls["n"] == 1


def test_retry_respects_deadline_in_backoff():
    clock = FakeClock()
    dl = Deadline(timeout_ms=8, clock=clock)  # 8 ms left, first delay >= 5 ms

    def fn():
        raise TransientFault("boom")

    with pytest.raises(QueryTimeout):
        retry_call(fn, attempts=5, base_ms=20, max_ms=2000,
                   rng=random.Random(0), sleep=clock.sleep, deadline=dl)


# ---------------------------------------------------------------------------
# circuit breaker state machine
# ---------------------------------------------------------------------------

def test_breaker_trips_then_half_opens():
    clock = FakeClock()
    b = CircuitBreaker(threshold=3, cooldown_ms=1000, clock=clock)
    assert b.state("s") == "closed" and b.allow("s")
    for _ in range(3):
        b.record_failure("s")
    assert b.state("s") == "open" and b.tripped("s")
    assert not b.allow("s")  # open: calls short-circuit
    clock.t += 1.0
    assert b.state("s") == "half_open"
    assert b.allow("s")       # exactly one half-open trial admitted
    assert not b.allow("s")   # concurrent caller blocked during the trial
    b.record_success("s")
    assert b.state("s") == "closed" and b.allow("s")


def test_breaker_failed_trial_reopens():
    clock = FakeClock()
    b = CircuitBreaker(threshold=2, cooldown_ms=1000, clock=clock)
    b.record_failure("s")
    b.record_failure("s")
    clock.t += 1.0
    assert b.allow("s")      # half-open trial
    b.record_failure("s")    # trial fails
    assert b.state("s") == "open"
    assert not b.allow("s")  # a fresh cooldown must elapse
    clock.t += 1.0
    assert b.allow("s")


def test_breaker_keys_are_independent():
    b = CircuitBreaker(threshold=1, cooldown_ms=1000, clock=FakeClock())
    b.record_failure(0)
    assert b.tripped(0) and not b.tripped(1)
    assert b.tripped_keys() == [0]


def test_breaker_half_open_trial_settles_on_unexpected_error():
    clock = FakeClock()
    b = CircuitBreaker(threshold=1, cooldown_ms=1000, clock=clock)
    b.record_failure("s")
    clock.t += 1.0

    def fn():
        raise RuntimeError("not a transient")

    with pytest.raises(RuntimeError):
        retry_call(fn, breaker=b, key="s", sleep=lambda s: None)
    # the failed trial reopened the breaker instead of wedging half-open
    # with the trial slot held forever
    assert b.state("s") == "open"
    clock.t += 1.0
    assert b.allow("s")  # a later cooldown admits a fresh trial


def test_breaker_aborted_trial_releases_slot():
    clock = FakeClock()
    b = CircuitBreaker(threshold=1, cooldown_ms=1000, clock=clock)
    dl = Deadline(timeout_ms=100, clock=clock)
    b.record_failure("s")
    clock.t += 1.0  # past the cooldown AND past the deadline

    with pytest.raises(QueryTimeout):
        retry_call(lambda: "ok", breaker=b, key="s", deadline=dl,
                   sleep=lambda s: None)
    assert b.allow("s")  # the admitted trial slot was released, not wedged


def test_retry_call_short_circuits_on_open_breaker():
    b = CircuitBreaker(threshold=1, cooldown_ms=1000, clock=FakeClock())
    b.record_failure(3)
    calls = {"n": 0}

    def fn():
        calls["n"] += 1

    with pytest.raises(ShardUnavailable) as ei:
        retry_call(fn, breaker=b, key=3, sleep=lambda s: None)
    assert calls["n"] == 0 and ei.value.shard == 3


# ---------------------------------------------------------------------------
# Deadline / budget
# ---------------------------------------------------------------------------

def test_deadline_expiry_and_budget():
    clock = FakeClock()
    dl = Deadline(timeout_ms=100, clock=clock)
    dl.check("t0")  # fine
    clock.t += 0.2
    assert dl.expired()
    with pytest.raises(QueryTimeout) as ei:
        dl.check("step 3")
    assert ei.value.code == ErrorCode.QUERY_TIMEOUT

    budget = Deadline(timeout_ms=0, budget_rows=10, clock=clock)
    budget.charge_rows(6)
    with pytest.raises(BudgetExceeded):
        budget.charge_rows(5, "step 1")
    assert not budget.expired()  # no wall-clock limit configured


def test_deadline_from_config(monkeypatch):
    monkeypatch.setattr(Global, "query_deadline_ms", 0)
    monkeypatch.setattr(Global, "query_budget_rows", 0)
    assert Deadline.from_config() is None
    monkeypatch.setattr(Global, "query_budget_rows", 500)
    dl = Deadline.from_config()
    assert dl is not None and dl.budget_rows == 500


# ---------------------------------------------------------------------------
# engine-level graceful degradation (LUBM-1, single partition)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cpu_world():
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    return g, ss, CPUEngine(g, ss)


def _parse(ss, text):
    q = Parser(ss).parse(text)
    heuristic_plan(q)
    return q


def test_cpu_deadline_yields_partial_result(cpu_world):
    _, ss, cpu = cpu_world
    q = _parse(ss, Q2HOP)
    # 50 ms deadline on a clock stepping 30 ms per read: the step-0 check
    # passes, the step-1 check raises — exactly one pattern executes
    q.deadline = Deadline(timeout_ms=50, clock=SteppingClock(0.03))
    cpu.execute(q)  # must not raise: degradation, not a crash
    assert q.result.status_code == ErrorCode.QUERY_TIMEOUT
    assert q.result.complete is False
    assert q.result.dropped_patterns  # the unexecuted tail is reported
    assert q.result.nrows > 0  # rows produced before expiry are kept


def test_cpu_budget_yields_partial_result(cpu_world):
    _, ss, cpu = cpu_world
    q = _parse(ss, Q2HOP)
    q.deadline = Deadline(budget_rows=1)
    cpu.execute(q)
    assert q.result.status_code == ErrorCode.BUDGET_EXCEEDED
    assert q.result.complete is False
    assert q.result.nrows > 0


def test_partial_results_can_be_disabled(cpu_world, monkeypatch):
    _, ss, cpu = cpu_world
    monkeypatch.setattr(Global, "enable_partial_results", False)
    q = _parse(ss, Q2HOP)
    q.deadline = Deadline(budget_rows=1)
    cpu.execute(q)
    assert q.result.status_code == ErrorCode.BUDGET_EXCEEDED
    assert q.result.complete is False
    assert q.result.nrows == 0  # partial rows discarded by the knob


def test_no_deadline_is_zero_overhead_path(cpu_world):
    # the default (no resilience knobs set) must stay exactly as before:
    # complete result, SUCCESS status, no deadline attached
    _, ss, cpu = cpu_world
    q = _parse(ss, Q2HOP)
    assert q.deadline is None
    cpu.execute(q)
    assert q.result.status_code == ErrorCode.SUCCESS
    assert q.result.complete is True
    assert q.result.dropped_patterns == []


def test_proxy_degrades_capacity_exceeded_to_cpu(cpu_world):
    # the device capacity ceiling is a TPU constraint, not a query
    # property: the proxy must transparently re-run host-side
    from wukong_tpu.runtime.proxy import Proxy

    g, ss, cpu = cpu_world

    class CapacityBoundTPU:
        def execute(self, q, from_proxy=True):
            q.result.status_code = ErrorCode.CAPACITY_EXCEEDED
            return q

    proxy = Proxy(g, ss, cpu, CapacityBoundTPU())
    q = proxy.run_single_query(QDEPT, device="tpu", blind=False)
    assert q.result.status_code == ErrorCode.SUCCESS
    assert q.result.nrows > 0


# ---------------------------------------------------------------------------
# engine pool load shedding (no wedging)
# ---------------------------------------------------------------------------

def test_pool_sheds_expired_queries_and_keeps_serving():
    class Echo:
        def execute(self, q):
            return ("served", q)

    pool = EnginePool(num_engines=2, make_engine=lambda tid: Echo())
    pool.start()
    try:
        clock = FakeClock()
        expired = type("Q", (), {})()
        expired.deadline = Deadline(timeout_ms=10, clock=clock)
        clock.t = 1.0  # deadline long gone before the pool pops it
        out = pool.wait(pool.submit(expired), timeout=10)
        assert isinstance(out, QueryTimeout)  # structured, not a crash
        # the pool is not wedged: a healthy query still gets served
        healthy = type("Q", (), {})()
        out2 = pool.wait(pool.submit(healthy), timeout=10)
        assert out2 == ("served", healthy)
    finally:
        pool.stop()


def test_pool_fault_site_injects_per_engine(monkeypatch):
    # pool.execute faults (keyed by engine tid via the shard field) become
    # the query's reply — the engine thread itself survives
    class Echo:
        def execute(self, q):
            return "served"

    faults.install(FaultPlan([FaultSpec("pool.execute", "transient",
                                        count=1)], seed=0))
    pool = EnginePool(num_engines=1, make_engine=lambda tid: Echo())
    pool.start()
    try:
        q1 = type("Q", (), {})()
        out = pool.wait(pool.submit(q1), timeout=10)
        assert isinstance(out, TransientFault)
        out2 = pool.wait(pool.submit(q1), timeout=10)  # count spent
        assert out2 == "served"
    finally:
        pool.stop()


# ---------------------------------------------------------------------------
# HDFS reads through the retry layer
# ---------------------------------------------------------------------------

@pytest.fixture()
def _fake_hdfs(monkeypatch):
    from wukong_tpu.loader import hdfs

    monkeypatch.setenv("WUKONG_HDFS_CMD", "true")  # exits 0, ignores args
    monkeypatch.setitem(hdfs._state, "probed", False)
    monkeypatch.setitem(hdfs._state, "cmd", None)
    monkeypatch.setattr(Global, "retry_base_ms", 1)
    monkeypatch.setattr(Global, "retry_max_ms", 2)
    return hdfs


def test_hdfs_read_retries_through_transients(_fake_hdfs):
    faults.install(FaultPlan([FaultSpec("hdfs.read", "transient", count=2)],
                             seed=0))
    assert _fake_hdfs._run(["-ls", "/x"]) == ""  # 3rd attempt succeeds


def test_hdfs_read_exhaustion_surfaces_clean_error(_fake_hdfs):
    faults.install(FaultPlan([FaultSpec("hdfs.read", "transient")], seed=0))
    with pytest.raises(WukongError) as ei:
        _fake_hdfs._run(["-ls", "/x"])
    assert ei.value.code == ErrorCode.FILE_NOT_FOUND


# ---------------------------------------------------------------------------
# distributed engine: persistent shard-down -> flagged partial result
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def dist_world(eight_cpu_devices):
    from wukong_tpu.parallel.mesh import make_mesh
    from wukong_tpu.store.gstore import build_all_partitions

    triples, _ = generate_lubm(1, seed=42)
    ss = VirtualLubmStrings(1, seed=42)
    stores = build_all_partitions(triples, 8)
    mesh = make_mesh(8)
    return ss, stores, mesh


@pytest.fixture(autouse=True)
def _pin_collective_route(monkeypatch):
    # force the sharded route so shard fetches actually happen at LUBM-1
    monkeypatch.setattr(Global, "enable_dist_inplace", False)


def _dist_run_with_shard_down(dist_world, seed):
    from wukong_tpu.parallel.dist_engine import DistEngine

    ss, stores, mesh = dist_world
    plan = FaultPlan([FaultSpec("dist.shard_fetch", "shard_down", shard=1)],
                     seed=seed)
    faults.install(plan)
    dist = DistEngine(stores, ss, mesh)
    q = _parse(ss, Q2HOP)
    dist.execute(q)  # must not raise
    return q, plan


def test_shard_down_yields_flagged_partial_result(dist_world):
    q, plan = _dist_run_with_shard_down(dist_world, seed=7)
    assert q.result.status_code == ErrorCode.SUCCESS  # well-formed reply
    assert q.result.complete is False  # ... but flagged incomplete
    assert "shard:1" in q.result.dropped_patterns
    assert plan.history  # the fault actually fired
    assert all(site == "dist.shard_fetch" and shard == 1
               for (site, shard, _) in plan.history)


def test_shard_down_schedule_replays_identically(dist_world):
    q1, p1 = _dist_run_with_shard_down(dist_world, seed=7)
    q2, p2 = _dist_run_with_shard_down(dist_world, seed=7)
    assert p1.history == p2.history  # identical seed, identical schedule
    assert q1.result.nrows == q2.result.nrows
    assert q1.result.dropped_patterns == q2.result.dropped_patterns


def test_dist_results_complete_without_faults(dist_world):
    from wukong_tpu.parallel.dist_engine import DistEngine

    ss, stores, mesh = dist_world
    dist = DistEngine(stores, ss, mesh)
    q = _parse(ss, Q2HOP)
    dist.execute(q)
    assert q.result.status_code == ErrorCode.SUCCESS
    assert q.result.complete is True
    assert q.result.dropped_patterns == []


def test_shard_transients_are_retried_transparently(dist_world, monkeypatch):
    from wukong_tpu.parallel.dist_engine import DistEngine

    monkeypatch.setattr(Global, "retry_base_ms", 1)
    monkeypatch.setattr(Global, "retry_max_ms", 2)
    ss, stores, mesh = dist_world
    # one transient on shard 2's first fetch: the retry absorbs it and the
    # result is complete — clients never see the hiccup
    faults.install(FaultPlan([FaultSpec("dist.shard_fetch", "transient",
                                        shard=2, count=1)], seed=0))
    dist = DistEngine(stores, ss, mesh)
    q = _parse(ss, Q2HOP)
    dist.execute(q)
    assert q.result.status_code == ErrorCode.SUCCESS
    assert q.result.complete is True


def test_chain_dispatch_transient_retried_transparently(dist_world,
                                                        monkeypatch):
    """The ``dist.chain_dispatch`` fault site (fault-site coverage gap
    closed by the analysis gate): a transient on the compiled-chain
    dispatch is absorbed by retry_call and the reply is byte-identical to
    an unfaulted run."""
    from wukong_tpu.parallel.dist_engine import DistEngine

    monkeypatch.setattr(Global, "retry_base_ms", 1)
    monkeypatch.setattr(Global, "retry_max_ms", 2)
    ss, stores, mesh = dist_world
    dist = DistEngine(stores, ss, mesh)
    q0 = _parse(ss, Q2HOP)
    dist.execute(q0)  # unfaulted oracle
    plan = FaultPlan([FaultSpec("dist.chain_dispatch", "transient",
                                count=1)], seed=3)
    faults.install(plan)
    q = _parse(ss, Q2HOP)
    dist.execute(q)
    assert [h[0] for h in plan.history] == ["dist.chain_dispatch"]
    assert q.result.status_code == ErrorCode.SUCCESS
    assert q.result.complete is True
    assert q.result.nrows == q0.result.nrows
    import numpy as np

    assert np.array_equal(np.asarray(q.result.table),
                          np.asarray(q0.result.table))


def test_chain_dispatch_exhaustion_is_structured(dist_world, monkeypatch):
    """Persistent chain-dispatch transients exhaust the retry budget and
    surface as the structured RETRY_EXHAUSTED reply status (the engine
    contract: errors become the reply), never a raw TransientFault
    escaping the engine."""
    from wukong_tpu.parallel.dist_engine import DistEngine

    monkeypatch.setattr(Global, "retry_base_ms", 1)
    monkeypatch.setattr(Global, "retry_max_ms", 2)
    ss, stores, mesh = dist_world
    plan = FaultPlan([FaultSpec("dist.chain_dispatch", "transient")], seed=3)
    faults.install(plan)
    dist = DistEngine(stores, ss, mesh)
    q = _parse(ss, Q2HOP)
    dist.execute(q)  # must not raise
    assert q.result.status_code == ErrorCode.RETRY_EXHAUSTED
    # the retry layer really paid the full budget before giving up
    assert len(plan.history) == Global.retry_max_attempts


def test_shard_recovery_restores_complete_results(dist_world, monkeypatch):
    from wukong_tpu.parallel.dist_engine import DistEngine

    # cooldown 0: the breaker half-opens immediately once the fault clears
    monkeypatch.setattr(Global, "breaker_cooldown_ms", 0)
    ss, stores, mesh = dist_world
    faults.install(FaultPlan([FaultSpec("dist.shard_fetch", "shard_down",
                                        shard=1)], seed=0))
    dist = DistEngine(stores, ss, mesh)
    q = _parse(ss, Q2HOP)
    dist.execute(q)
    assert q.result.complete is False
    faults.clear()  # shard comes back
    # degraded stagings were never cached, so the next query re-fetches;
    # the stale outage must NOT keep flagging healthy replies incomplete
    q2 = _parse(ss, Q2HOP)
    dist.execute(q2)
    assert q2.result.status_code == ErrorCode.SUCCESS
    assert q2.result.complete is True
    assert q2.result.dropped_patterns == []


def test_breaker_opens_after_repeated_shard_down(dist_world):
    from wukong_tpu.parallel.dist_engine import DistEngine

    ss, stores, mesh = dist_world
    faults.install(FaultPlan([FaultSpec("dist.shard_fetch", "shard_down",
                                        shard=1)], seed=0))
    dist = DistEngine(stores, ss, mesh)
    for text in (Q2HOP, QDEPT):
        q = _parse(ss, text)
        dist.execute(q)
        assert q.result.complete is False
    assert dist.sstore.breaker.tripped(1)  # persistent faults trip it
    assert 1 in dist.sstore.degraded_shards


# ---------------------------------------------------------------------------
# shard replication: failover, breaker-open serving, healing (PR 5)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def replicated_dist(dist_world):
    """One DistEngine over the shared world with replication_factor=2:
    every shard's data mirrored onto its successor host."""
    from wukong_tpu.parallel.dist_engine import DistEngine

    ss, stores, mesh = dist_world
    old = Global.replication_factor
    Global.replication_factor = 2
    try:
        dist = DistEngine(stores, ss, mesh)
    finally:
        Global.replication_factor = old
    assert dist.sstore.replication_factor == 2
    return ss, dist


def _failover_count(shard: int) -> float:
    from wukong_tpu.obs.metrics import get_registry

    return get_registry().counter(
        "wukong_failover_total",
        "Shard fetches served by a replica after a primary failure",
        labels=("shard",)).value(shard=str(shard))


@pytest.mark.recovery
def test_default_replication_factor_means_no_replicas(dist_world):
    from wukong_tpu.parallel.sharded_store import ShardedDeviceStore

    ss, stores, mesh = dist_world
    sstore = ShardedDeviceStore(stores, mesh)  # replication_factor=1 default
    assert sstore.replication_factor == 1
    assert sstore.replicas == {} and sstore.replica_stores() == []


@pytest.mark.recovery
def test_failover_keeps_results_complete(replicated_dist):
    ss, dist = replicated_dist
    q0 = _parse(ss, Q2HOP)
    dist.execute(q0)
    assert q0.result.complete is True
    f0 = _failover_count(1)
    faults.install(FaultPlan([FaultSpec("dist.shard_fetch", "shard_down",
                                        shard=1)], seed=0))
    # the dead host's staged device data dies with it: force restaging
    dist.sstore.invalidate_stagings()
    q1 = _parse(ss, Q2HOP)
    dist.execute(q1)
    # the ISSUE acceptance: primary down + replica alive => complete=True
    # with the SAME rows, not an empty-shard partial
    assert q1.result.status_code == ErrorCode.SUCCESS
    assert q1.result.complete is True
    assert q1.result.dropped_patterns == []
    assert q1.result.nrows == q0.result.nrows
    assert _failover_count(1) > f0
    assert 1 in dist.sstore.failover_shards


@pytest.mark.recovery
def test_failover_under_breaker_open_skips_dead_primary(replicated_dist):
    ss, dist = replicated_dist
    breaker = dist.sstore.breaker
    old_cd = breaker.cooldown_s
    breaker.cooldown_s = 1e9  # no half-open probes during this test
    plan = FaultPlan([FaultSpec("dist.shard_fetch", "shard_down", shard=1)],
                     seed=0)
    faults.install(plan)
    try:
        # enough restaged queries to trip the primary's breaker
        for _ in range(2):
            dist.sstore.invalidate_stagings()
            q = _parse(ss, Q2HOP)
            dist.execute(q)
            assert q.result.complete is True  # replica served throughout
        assert breaker.tripped(1)
        fired_before = len(plan.history)
        dist.sstore.invalidate_stagings()
        q = _parse(ss, Q2HOP)
        dist.execute(q)
        # breaker open: the primary is not even touched — failover is the
        # first hop now, and results stay complete
        assert len(plan.history) == fired_before
        assert q.result.complete is True
    finally:
        breaker.cooldown_s = old_cd


@pytest.mark.recovery
def test_failover_exhausted_degrades_to_flagged_partial(replicated_dist):
    ss, dist = replicated_dist
    # shard 1's only replica lives on host 2: kill both => PR 1 posture
    faults.install(FaultPlan([
        FaultSpec("dist.shard_fetch", "shard_down", shard=1),
        FaultSpec("replica.fetch", "shard_down", shard=2)], seed=0))
    dist.sstore.invalidate_stagings()
    q = _parse(ss, Q2HOP)
    dist.execute(q)
    assert q.result.status_code == ErrorCode.SUCCESS
    assert q.result.complete is False
    assert "shard:1" in q.result.dropped_patterns
    assert 1 in dist.sstore.degraded_shards


@pytest.mark.recovery
def test_heal_rebuilds_promotes_and_closes_breaker(replicated_dist):
    from wukong_tpu.runtime.recovery import RecoveryManager

    ss, dist = replicated_dist
    # the exhausted test above tripped shard 1's replica-host breaker;
    # this test's replica is healthy again — settle that key first
    dist.sstore.breaker.record_success((1, 2))
    faults.install(FaultPlan([FaultSpec("dist.shard_fetch", "shard_down",
                                        shard=1)], seed=0))
    dist.sstore.invalidate_stagings()
    q = _parse(ss, Q2HOP)
    dist.execute(q)
    assert q.result.complete is True
    baseline = q.result.nrows
    faults.clear()  # the dead host is replaced
    rm = RecoveryManager(dist.sstore.stores, sstore=dist.sstore)
    healed = rm.heal_once()
    assert 1 in healed
    assert dist.sstore.breaker.state(1) == "closed"
    assert 1 not in dist.sstore.failover_shards
    assert not rm.sick_shards()
    f_after = _failover_count(1)
    q2 = _parse(ss, Q2HOP)
    dist.execute(q2)
    # the promoted primary serves: same rows, complete, no new failovers
    assert q2.result.complete is True and q2.result.nrows == baseline
    assert _failover_count(1) == f_after


@pytest.mark.recovery
def test_replicas_mirror_dynamic_inserts(replicated_dist):
    import numpy as np

    from wukong_tpu.store.dynamic import insert_batch_into
    from wukong_tpu.utils.mathutil import hash_mod

    ss, dist = replicated_dist
    q0 = _parse(ss, QDEPT)
    dist.execute(q0)
    n0 = q0.result.nrows
    dept = ss.str2id("<http://www.Department0.University0.edu>")
    works = ss.str2id(
        "<http://swat.cse.lehigh.edu/onto/univ-bench.owl#worksFor>")
    prof = ss.str2id(
        "<http://swat.cse.lehigh.edu/onto/univ-bench.owl#FullProfessor>")
    tyid = ss.str2id("<http://www.w3.org/1999/02/22-rdf-syntax-ns#type>")
    newv = 1_000_003
    while hash_mod(np.asarray([newv]), 8)[0] != 3:  # land on shard 3
        newv += 1
    tri = np.asarray([[newv, works, dept], [newv, tyid, prof]],
                     dtype=np.int64)
    # the proxy's insert fan-out: primaries AND replicas get the batch
    insert_batch_into(
        list(dist.sstore.stores) + dist.sstore.replica_stores(), tri)
    q1 = _parse(ss, QDEPT)
    dist.execute(q1)
    assert q1.result.nrows == n0 + 1  # visible on the healthy primary
    # kill the owning shard: the replica must serve the NEW row too —
    # a mirror that missed the write would silently revert it
    faults.install(FaultPlan([FaultSpec("dist.shard_fetch", "shard_down",
                                        shard=3)], seed=0))
    dist.sstore.invalidate_stagings()
    q2 = _parse(ss, QDEPT)
    dist.execute(q2)
    assert q2.result.complete is True
    assert q2.result.nrows == n0 + 1


@pytest.mark.recovery
def test_kill_and_recover_drill(replicated_dist, monkeypatch):
    """The emulator's drill mode end to end (console `recover -d`)."""
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.runtime.emulator import Emulator
    from wukong_tpu.runtime.proxy import Proxy
    from wukong_tpu.store.gstore import build_partition

    ss, dist = replicated_dist
    monkeypatch.setattr(Global, "replication_factor", 2)
    monkeypatch.setattr(Global, "enable_tpu", False)
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    proxy = Proxy(g, ss, CPUEngine(g, ss), None, dist)
    try:
        report = Emulator(proxy).run_drill(shard=5, rounds=2)
        assert report["replication_factor"] == 2
        assert report["outage"]["complete"] is True
        assert report["outage"]["nrows_match"] is True
        assert report["outage"]["failovers"] > 0
        assert report["healthy"] is True
        assert report["recovered"]["complete"] is True
        assert report["recovered"]["nrows_match"] is True
    finally:
        proxy.recovery().stop()
