"""CPU oracle engine vs independent BGP evaluation on LUBM-1.

Runs every basic LUBM query (the reference's acceptance suite,
scripts/sparql_query/lubm/basic) through parse -> plan -> execute and compares
the projected result multiset against the naive BGP oracle.
"""

import glob
import os

import numpy as np
import pytest

from bgp_oracle import TripleIndex, eval_bgp
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.planner.plan_file import set_plan
from wukong_tpu.sparql.parser import Parser
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.types import BLANK_ID

BASIC = "/root/reference/scripts/sparql_query/lubm/basic"


@pytest.fixture(scope="module")
def world():
    triples, lay = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    idx = TripleIndex(triples)
    return triples, g, ss, idx


def _run(world, text, plan_file=None):
    _, g, ss, idx = world
    q = Parser(ss).parse(text)
    raw_patterns = [(p.subject, p.predicate, p.object)
                    for p in q.pattern_group.patterns]
    if plan_file:
        assert set_plan(q.pattern_group, open(plan_file).read())
    else:
        heuristic_plan(q)
    eng = CPUEngine(g, ss)
    eng.execute(q)
    assert q.result.status_code == 0, q.result.status_code
    got = sorted(map(tuple, q.result.table.tolist()))
    want = sorted(eval_bgp(idx, raw_patterns, q.result.required_vars))
    return q, got, want


QUERIES = sorted(glob.glob(f"{BASIC}/lubm_q*"))
QUERIES = [f for f in QUERIES if os.path.isfile(f)]


@pytest.mark.parametrize("qfile", QUERIES, ids=[os.path.basename(f) for f in QUERIES])
def test_basic_suite_heuristic_plan(world, qfile):
    q, got, want = _run(world, open(qfile).read())
    assert got == want, f"{qfile}: {len(got)} vs {len(want)} rows"
    # q3 is empty even on real LUBM (docs/performance/S1C24-LUBM2560-20181203.md
    # Q3 #R=0); q10/q11 probe tiny constants that may not exist at LUBM-1
    name = os.path.basename(qfile)
    if name not in ("lubm_q3", "lubm_q10", "lubm_q11"):
        assert len(got) > 0, f"{name} unexpectedly empty"


OSDI_PLANS = sorted(glob.glob(f"{BASIC}/osdi16_plan/lubm_q*.fmt"))


@pytest.mark.parametrize("pfile", OSDI_PLANS,
                         ids=[os.path.basename(f) for f in OSDI_PLANS])
def test_basic_suite_osdi16_plans(world, pfile):
    qname = os.path.basename(pfile)[:-4]
    q, got, want = _run(world, open(f"{BASIC}/{qname}").read(), plan_file=pfile)
    assert got == want, f"{qname}: {len(got)} vs {len(want)} rows"


MANUAL_PLANS = [f for f in sorted(glob.glob(f"{BASIC}/manual_plan/lubm_q*.fmt"))
                if "q1_2" not in f]


@pytest.mark.parametrize("pfile", MANUAL_PLANS,
                         ids=[os.path.basename(f) for f in MANUAL_PLANS])
def test_basic_suite_manual_plans(world, pfile):
    qname = os.path.basename(pfile)[:-4]
    q, got, want = _run(world, open(f"{BASIC}/{qname}").read(), plan_file=pfile)
    assert got == want, f"{qname}: {len(got)} vs {len(want)} rows"


def test_union(world):
    triples, g, ss, idx = world
    text = """
    PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
    PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
    SELECT ?X WHERE {
        { ?X rdf:type ub:FullProfessor . } UNION { ?X rdf:type ub:Lecturer . }
    }"""
    q = Parser(ss).parse(text)
    for u in q.pattern_group.unions:
        pass
    heuristic_plan(q)
    eng = CPUEngine(g, ss)
    eng.execute(q)
    assert q.result.status_code == 0
    got = sorted(x[0] for x in q.result.table.tolist())
    fp = eval_bgp(idx, [(-1, 1, _t(ss, "FullProfessor"))], [-1])
    lec = eval_bgp(idx, [(-1, 1, _t(ss, "Lecturer"))], [-1])
    want = sorted([x[0] for x in fp] + [x[0] for x in lec])
    assert got == want


def _t(ss, name):
    return ss.str2id(f"<http://swat.cse.lehigh.edu/onto/univ-bench.owl#{name}>")


def _p(ss, name):
    return ss.str2id(f"<http://swat.cse.lehigh.edu/onto/univ-bench.owl#{name}>")


def test_optional(world):
    triples, g, ss, idx = world
    # every FullProfessor in Department0, optionally the department they head
    text = """
    PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
    PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
    SELECT ?X ?D WHERE {
        ?X ub:worksFor <http://www.Department0.University0.edu> .
        ?X rdf:type ub:FullProfessor .
        OPTIONAL { ?X ub:headOf ?D . }
    }"""
    q = Parser(ss).parse(text)
    heuristic_plan(q)
    eng = CPUEngine(g, ss)
    eng.execute(q)
    assert q.result.status_code == 0
    rows = q.result.table.tolist()
    # all FullProfessors of dept0 present exactly once (head count = 1)
    d0 = ss.str2id("<http://www.Department0.University0.edu>")
    profs = eval_bgp(idx, [(-1, _p(ss, "worksFor"), d0),
                           (-1, 1, _t(ss, "FullProfessor"))], [-1])
    assert len(rows) == len(profs)
    heads = [r for r in rows if r[1] != BLANK_ID]
    assert len(heads) == 1 and heads[0][1] == d0
    # non-heads carry BLANK_ID
    assert all(r[1] == BLANK_ID for r in rows if r[0] != heads[0][0])


def test_filter_regex_and_distinct(world):
    triples, g, ss, idx = world
    text = """
    PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
    PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
    SELECT DISTINCT ?Y1 WHERE {
        ?X ub:worksFor <http://www.Department0.University0.edu> .
        ?X rdf:type ub:FullProfessor .
        ?X ub:name ?Y1 .
        FILTER regex(?Y1, "FullProfessor[0-3]")
    }"""
    q = Parser(ss).parse(text)
    heuristic_plan(q)
    eng = CPUEngine(g, ss)
    eng.execute(q)
    assert q.result.status_code == 0
    names = sorted(ss.id2str(int(r[0])) for r in q.result.table)
    assert names == ['"FullProfessor0"', '"FullProfessor1"',
                     '"FullProfessor2"', '"FullProfessor3"']


def test_order_limit_offset(world):
    triples, g, ss, idx = world
    text = """
    PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
    PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
    SELECT ?X ?N WHERE {
        ?X ub:worksFor <http://www.Department0.University0.edu> .
        ?X rdf:type ub:FullProfessor .
        ?X ub:name ?N .
    } ORDER BY ?N LIMIT 3 OFFSET 1"""
    q = Parser(ss).parse(text)
    heuristic_plan(q)
    eng = CPUEngine(g, ss)
    eng.execute(q)
    names = [ss.id2str(int(r[1])) for r in q.result.table]
    assert len(names) == 3
    assert names == sorted(names)
    assert names[0] == '"FullProfessor1"'  # offset skipped FullProfessor0


def test_wrong_suite_engine_errors(world):
    """Reference 'wrong' suite: q2 without a plan must fail with a plan error."""
    from wukong_tpu.utils.errors import ErrorCode, WukongError

    triples, g, ss, idx = world
    text = open("/root/reference/scripts/sparql_query/lubm/wrong/q2").read()
    q = Parser(ss).parse(text)
    with pytest.raises(WukongError):
        heuristic_plan(q)


def test_corun(world):
    """CORUN: same kept rows as plain execution for a filter window, and
    EXISTS semantics for an expansion window (distinct main rows kept)."""
    from wukong_tpu.config import Global
    from wukong_tpu.sparql.ir import Pattern
    from wukong_tpu.types import IN

    triples, g, ss, idx = world
    eng = CPUEngine(g, ss)
    d0 = ss.str2id("<http://www.Department0.University0.edu>")
    memberOf = _p(ss, "memberOf")
    takes = _p(ss, "takesCourse")
    ug = _t(ss, "UndergraduateStudent")

    def run(pats, corun=None):
        from wukong_tpu.sparql.ir import SPARQLQuery

        q = SPARQLQuery()
        q.pattern_group.patterns = list(pats)
        q.result.nvars = 2
        q.result.required_vars = [-1]
        if corun:
            q.corun_enabled = True
            q.corun_step, q.fetch_step = corun
        old = Global.enable_corun
        Global.enable_corun = True
        try:
            eng.execute(q)
        finally:
            Global.enable_corun = old
        assert q.result.status_code == 0, q.result.status_code
        return sorted(map(tuple, q.result.table.tolist()))

    base = [Pattern(d0, memberOf, IN, -1), Pattern(-1, 1, 1, ug)]
    # filter-only window: identical rows
    assert run(base, corun=(1, 2)) == run(base)
    assert len(run(base)) > 0
    # expansion window: corun keeps each main row once (EXISTS semantics)
    pats2 = [Pattern(d0, memberOf, IN, -1), Pattern(-1, takes, 1, -2)]
    plain_distinct = sorted({r[0] for r in run(pats2)})
    corun_rows = run(pats2, corun=(1, 2))
    assert sorted(r[0] for r in corun_rows) == plain_distinct
