from wukong_tpu.loader.datagen import convert_dir
from wukong_tpu.types import NORMAL_ID_START


NT = """\
<http://a.org/s1> <http://www.w3.org/1999/02/22-rdf-syntax-ns#type> <http://a.org/T1> .
<http://a.org/s1> <http://a.org/knows> <http://a.org/s2> .
<http://a.org/s2> <http://a.org/knows> <http://a.org/s1> .
<http://a.org/s1> <http://a.org/age> "40"^^<http://www.w3.org/2001/XMLSchema#int> .
"""


def test_convert_dir(tmp_path):
    src = tmp_path / "nt"
    src.mkdir()
    (src / "f0.nt").write_text(NT)
    dst = tmp_path / "id"
    meta = convert_dir(str(src), str(dst))
    assert meta["index_vertex"] == 4  # __PREDICATE__, rdf:type, T1? no: knows + type + T1
    # id triples: 3 normal rows
    rows = [tuple(map(int, l.split("\t")))
            for l in (dst / "id_f0.nt").read_text().splitlines()]
    assert len(rows) == 3
    s2i = {}
    for line in (dst / "str_normal").read_text().splitlines():
        s, i = line.rsplit("\t", 1)
        s2i[s] = int(i)
    for line in (dst / "str_index").read_text().splitlines():
        s, i = line.rsplit("\t", 1)
        s2i[s] = int(i)
    assert s2i["__PREDICATE__"] == 0
    assert s2i["<http://www.w3.org/1999/02/22-rdf-syntax-ns#type>"] == 1
    assert s2i["<http://a.org/T1>"] < NORMAL_ID_START  # type object -> index id
    assert s2i["<http://a.org/s1>"] >= NORMAL_ID_START
    # type triple encodes the type as an index id
    t_row = [r for r in rows if r[1] == 1][0]
    assert t_row == (s2i["<http://a.org/s1>"], 1, s2i["<http://a.org/T1>"])
    # attr triple extracted with type tag 1 (int)
    attr = (dst / "attr_f0.nt").read_text().splitlines()
    assert len(attr) == 1
    sid, pid, t, val = attr[0].split("\t")
    assert int(t) == 1 and val == "40"
    # str_attr_index records the attr predicate
    assert "<http://a.org/age>" in (dst / "str_attr_index").read_text()


def test_prefix_expansion(tmp_path):
    src = tmp_path / "nt"
    src.mkdir()
    (src / "f0.nt").write_text(
        "@prefix ex: <http://ex.org/> .\n"
        "ex:a <http://ex.org/p> ex:b .\n"
    )
    dst = tmp_path / "id"
    convert_dir(str(src), str(dst))
    normal = (dst / "str_normal").read_text()
    assert "<http://ex.org/a>" in normal and "<http://ex.org/b>" in normal
