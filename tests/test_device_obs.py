"""Device-observatory tests: padding efficiency hand-computed across
pad_pow2 capacity classes (including the all-padding and empty edge
cases), residency byte accounting across a store-version invalidation,
the variant-storm sentinel's once-per-cooldown contract, DEVICE_INPUTS
<-> registry parity, the /device scrape + console verb + Monitor line
surfaces, the EXPLAIN ANALYZE device table on a device-routed cyclic
query, and the off-knob zero-touch guarantee. The whole module runs
fully lockdep-checked (the observatory-suite posture)."""

import json
import socket
import time
import urllib.request

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.join.kernels import pad_pow2
from wukong_tpu.join.wcoj import JoinTableCache
from wukong_tpu.loader.datagen import (
    CyclicStrings,
    cyclic_query_text,
    generate_triangle,
)
from wukong_tpu.obs.device import (
    DEVICE_INPUTS,
    CompileLedger,
    get_device_obs,
    maybe_device_dispatch,
    maybe_device_resident,
    note_feedback,
    read_device_input,
    render_device,
)
from wukong_tpu.obs.events import get_journal
from wukong_tpu.obs.metrics import get_registry, snapshot_labeled_value
from wukong_tpu.obs.tsdb import get_tsdb
from wukong_tpu.planner.optimizer import Planner
from wukong_tpu.planner.stats import Stats
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.store.gstore import build_partition

pytestmark = pytest.mark.device


@pytest.fixture(autouse=True, scope="module")
def _lockdep_checked():
    """Ledger charges fire from engine sync points — the suite runs with
    the lock-order checker live and teardown asserts zero cycles and
    zero declared-leaf inversions."""
    from wukong_tpu.analysis import lockdep

    lockdep.install(True)
    yield
    try:
        assert lockdep.cycles() == [], lockdep.cycles()
        assert lockdep.leaf_violations() == [], lockdep.leaf_violations()
    finally:
        lockdep.install(False)


@pytest.fixture(autouse=True)
def _hygiene(monkeypatch):
    """Device knobs at defaults, the process-wide observatory + journal
    + tsdb clean before and after every test."""
    monkeypatch.setattr(Global, "enable_device_obs", True)
    monkeypatch.setattr(Global, "enable_events", True)
    get_device_obs().reset()
    get_journal().clear()
    get_tsdb().reset()
    yield
    get_device_obs().reset()


# ---------------------------------------------------------------------------
# padding efficiency: hand-computed across pad_pow2 capacity classes
# ---------------------------------------------------------------------------

def test_padding_efficiency_hand_computed():
    """Charge live-row counts straight out of the engine's pad_pow2
    buckets and check live/padded to the digit, per site and overall."""
    lives = [1, 700, 1024, 1025, 5000]
    caps = [pad_pow2(n) for n in lives]
    assert caps == [1024, 1024, 1024, 2048, 8192]
    for n, c in zip(lives, caps):
        rec = maybe_device_dispatch("t.probe", template="p1",
                                    live=n, capacity=c, wall_us=10)
        assert rec["padding_efficiency"] == round(n / c, 4)
    want = sum(lives) / sum(caps)
    got = read_device_input("padding_efficiency", site="t.probe")
    assert got == pytest.approx(want)
    assert read_device_input("padding_efficiency") == pytest.approx(want)


def test_padding_efficiency_edge_cases():
    """All-padding dispatches (0 live rows against a full class) drive
    efficiency to 0.0; capacity-free dispatches (no padded tensor) leave
    it undefined rather than polluting the ratio."""
    assert read_device_input("padding_efficiency") is None  # nothing yet
    maybe_device_dispatch("t.empty", template="e", live=0, capacity=0)
    assert read_device_input("padding_efficiency") is None  # still no class
    rec = maybe_device_dispatch("t.allpad", template="a",
                                live=0, capacity=1024)
    assert rec["padding_efficiency"] == 0.0
    assert read_device_input("padding_efficiency", site="t.allpad") == 0.0
    # the capacity-free site stays absent from the per-site gauge map
    assert "t.empty" not in \
        get_device_obs().dispatch_ledger.site_efficiencies()


def test_dispatch_cold_warm_and_report_rows():
    """Cold = a (site, template, capacity) variant's first call; repeats
    of the same variant are warm, a new capacity class is cold again."""
    for _ in range(3):
        maybe_device_dispatch("t.chain", template="d2", live=500,
                              capacity=1024, wall_us=100)
    maybe_device_dispatch("t.chain", template="d2", live=1500,
                          capacity=2048, wall_us=100)
    counts = read_device_input("dispatches", site="t.chain")
    assert counts == {"count": 4, "cold": 2, "warm": 2, "wall_us": 400}
    rows = {(r["template"], r["capacity"]): r
            for r in get_device_obs().dispatch_ledger.report(10)}
    assert rows[("d2", 1024)]["dispatches"] == 3
    assert rows[("d2", 1024)]["cold"] == 1
    assert rows[("d2", 1024)]["warm"] == 2
    assert rows[("d2", 2048)]["cold"] == 1
    assert read_device_input("variants", site="t.chain") == 2


# ---------------------------------------------------------------------------
# residency: byte accounting across a store-version invalidation
# ---------------------------------------------------------------------------

class _FakeStore:
    version = 7


def test_residency_bytes_across_version_invalidation(monkeypatch):
    """JoinTableCache dseg fills charge their exact device bytes; a
    store-version bump reaps the stale tables as ONE invalidate edge
    carrying their summed bytes; the high-water survives the drop."""
    monkeypatch.setattr(Global, "join_table_cache", 64)
    g = _FakeStore()
    cache = JoinTableCache(g)
    a = np.zeros(100, dtype=np.int32)   # 400 B each
    t1 = (a, a, a, 2)                   # dseg tuple: 1200 B device-side
    t2 = (a, a, a, 3)
    cache._put((7, "dseg", 11, 0), t1)
    cache._put((7, "dseg", 12, 0), t2)
    res = get_device_obs().residency
    assert res.totals() == {"join_table": 2400}
    assert read_device_input("residency_high_water") == 2400
    snap0 = get_registry().snapshot()

    g.version = 8  # store mutation: the old tables are unreachable
    cache._put((8, "dseg", 11, 0), t1)
    assert res.totals() == {"join_table": 2400 - 2400 + 1200}
    assert read_device_input("resident_bytes") == {"join_table": 1200}
    assert read_device_input("residency_high_water") == 2400
    snap1 = get_registry().snapshot()
    inv = (snapshot_labeled_value(snap1, "wukong_device_residency_total",
                                  kind="join_table", event="invalidate")
           - snapshot_labeled_value(snap0, "wukong_device_residency_total",
                                    kind="join_table", event="invalidate"))
    assert inv == 1  # one edge, not one per reaped entry

    # same-version edge dedup: a second invalidate on version 8 still
    # drops bytes but does not mint a second edge
    assert res.invalidate("join_table", 1200, version=8) is False
    assert res.totals()["join_table"] == 0
    snap2 = get_registry().snapshot()
    assert snapshot_labeled_value(
        snap2, "wukong_device_residency_total",
        kind="join_table", event="invalidate") == snapshot_labeled_value(
        snap1, "wukong_device_residency_total",
        kind="join_table", event="invalidate")


def test_residency_lru_evict_charges_bytes(monkeypatch):
    """LRU pressure on the join-table cache surfaces as evict edges and
    the byte total returns to the survivors' sum."""
    monkeypatch.setattr(Global, "join_table_cache", 2)
    cache = JoinTableCache(_FakeStore())
    a = np.zeros(64, dtype=np.int32)  # 256 B
    for i in range(3):
        cache._put((7, "dseg", i, 0), (a, a, a, 2))
    res = get_device_obs().residency
    assert res.totals()["join_table"] == 2 * 768  # one entry evicted
    snap = get_registry().snapshot()
    assert snapshot_labeled_value(snap, "wukong_device_residency_total",
                                  kind="join_table", event="evict") >= 1


def test_residency_budget_flag(monkeypatch):
    monkeypatch.setattr(Global, "device_budget_mb", 1)
    maybe_device_resident("fill", "segment", 2 << 20)
    st = get_device_obs().residency.stats()
    assert st["over_budget"] is True
    assert "OVER BUDGET" in render_device()[0]


# ---------------------------------------------------------------------------
# variant-storm sentinel: trips once per cooldown
# ---------------------------------------------------------------------------

def test_storm_trips_once_per_cooldown():
    led = CompileLedger(limit=3, cooldown_s=0.05)
    storms = []
    for i in range(8):  # 8 distinct variants minted back-to-back
        _cold, storm = led.note("s", f"t{i}", 1024)
        if storm is not None:
            storms.append((i, storm))
    assert len(storms) == 1  # trips when the window crosses the limit...
    assert storms[0][0] == 3 and storms[0][1] == 4
    time.sleep(0.06)  # ...and not again until the cooldown elapses
    for i in range(8, 13):
        _cold, storm = led.note("s", f"t{i}", 1024)
        if storm is not None:
            storms.append((i, storm))
    assert len(storms) == 2
    # warm re-dispatches never count as mints
    assert led.note("s", "t0", 1024) == (False, None)


def test_storm_journals_event_once(monkeypatch):
    """Through the facade: a storm journals ONE device.variant_storm
    ClusterEvent (and survives an empty FlightRecorder ring)."""
    monkeypatch.setattr(Global, "device_variant_limit", 2)
    monkeypatch.setattr(Global, "device_storm_cooldown_s", 60.0)
    for i in range(6):
        maybe_device_dispatch("t.storm", template=f"v{i}", live=1,
                              capacity=1024)
    evs = get_journal().last(kind="device.variant_storm")
    assert len(evs) == 1
    assert evs[0].attrs["site"] == "t.storm"
    assert evs[0].attrs["minted_in_window"] == 3
    assert evs[0].attrs["limit"] == 2
    snap = get_registry().snapshot()
    assert snapshot_labeled_value(snap, "wukong_device_variant_storms_total",
                                  site="t.storm") == 1


# ---------------------------------------------------------------------------
# DEVICE_INPUTS <-> registry parity and the read contract
# ---------------------------------------------------------------------------

def test_device_inputs_all_registered():
    registered = set(get_registry().snapshot())
    for signal, metric in DEVICE_INPUTS.items():
        assert metric in registered, (signal, metric)


def test_read_device_input_contract():
    with pytest.raises(KeyError):
        read_device_input("no_such_signal")
    with pytest.raises(KeyError):
        # declared, but metric-backed only: the reader must say so
        read_device_input("bytes_moved")
    assert read_device_input("dispatches")["count"] == 0
    assert read_device_input("resident_bytes") == {}


def test_trend_reads_through_tsdb():
    from wukong_tpu.obs.device import device_trend

    assert device_trend() == {}  # cold start: no samples, no rates
    for _ in range(4):
        maybe_device_dispatch("t.trend", template="d1", live=10,
                              capacity=1024)
        get_tsdb().sample_once()
        time.sleep(0.01)
    tr = device_trend()
    assert tr and tr["dispatches_per_s"] > 0


# ---------------------------------------------------------------------------
# surfaces: /device scrape, console verb, Monitor line, EXPLAIN table
# ---------------------------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(port, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5).read().decode()


def test_device_scrape_endpoint(monkeypatch):
    from wukong_tpu.obs import maybe_start_metrics_http, stop_metrics_http

    port = _free_port()
    monkeypatch.setattr(Global, "metrics_host", "127.0.0.1")
    assert maybe_start_metrics_http(port=port) is not None
    try:
        maybe_device_dispatch("t.http", template="d1", live=512,
                              capacity=1024, wall_us=250)
        maybe_device_resident("fill", "segment", 4096)
        body = _get(port, "/device")
        assert "wukong-device" in body and "DISPATCH" in body
        assert "RESIDENT" in body
        js = json.loads(_get(port, "/device.json"))
        assert js["dispatches"]["count"] == 1
        assert js["by_site_efficiency"]["t.http"] == 0.5
        assert js["residency"]["by_kind"]["segment"] == 4096
        assert js["inputs"] == DEVICE_INPUTS
    finally:
        stop_metrics_http()


@pytest.fixture()
def tri_proxy():
    triples, meta = generate_triangle(m=60, noise=3, seed=1)
    g = build_partition(triples, 0, 1)
    ss = CyclicStrings(meta)
    stats = Stats.generate(triples)
    proxy = Proxy(g, ss, cpu_engine=CPUEngine(g, ss),
                  planner=Planner(stats))
    return proxy, cyclic_query_text(meta)


def _force_device_wcoj(monkeypatch):
    monkeypatch.setattr(Global, "wcoj_min_rows", 1)
    monkeypatch.setattr(Global, "wcoj_ratio", 1)
    monkeypatch.setattr(Global, "join_device", "device")


def test_console_device_verb(tri_proxy, monkeypatch, capsys):
    from wukong_tpu.runtime.console import Console

    proxy, text = tri_proxy
    _force_device_wcoj(monkeypatch)
    proxy.serve_query(text, blind=True)
    con = Console(proxy)
    assert con.run_command("device") is True
    out = capsys.readouterr().out
    assert "wukong-device" in out and "wcoj.probe" in out
    assert con.run_command("device -j -k 2") is True
    js = json.loads(capsys.readouterr().out)
    assert js["dispatches"]["count"] >= 1
    assert js["residency"]["by_kind"].get("join_table", 0) > 0


def test_monitor_device_line(tri_proxy, monkeypatch):
    from wukong_tpu.runtime.monitor import Monitor

    mon = Monitor()
    assert mon.device_lines() == []  # quiet before any dispatch
    proxy, text = tri_proxy
    _force_device_wcoj(monkeypatch)
    proxy.serve_query(text, blind=True)
    lines = mon.device_lines()
    assert len(lines) == 1 and lines[0].startswith("Device[")
    assert "pad_eff" in lines[0] and "resident" in lines[0]


def test_explain_analyze_device_table(tri_proxy, monkeypatch):
    """EXPLAIN ANALYZE on a device-routed cyclic query renders the
    per-step device table: every WCOJ probe level shows up with its
    capacity class, live rows, and cold/warm temperature."""
    proxy, text = tri_proxy
    _force_device_wcoj(monkeypatch)
    rep = proxy.explain_query(text, analyze=True)
    assert rep["route"] == "device"
    steps = rep["device_steps"]
    assert steps and all(s["site"] == "wcoj.probe" for s in steps)
    assert all(s["capacity"] >= s["live"] > 0 for s in steps)
    assert all(s["temp"] in ("cold", "warm") for s in steps)
    rendered = rep["rendered"]
    assert "device:" in rendered and "wcoj.probe" in rendered
    # the observatory's ledger saw the same dispatches the table shows
    counts = read_device_input("dispatches", site="wcoj.probe")
    assert counts["count"] >= len(steps)


# ---------------------------------------------------------------------------
# off knob: zero-touch
# ---------------------------------------------------------------------------

def test_off_knob_is_zero_touch(tri_proxy, monkeypatch):
    """enable_device_obs=False: the seams return None / no-op, the
    ledgers stay empty across a full device-routed query, and the
    feedback counter holds still."""
    monkeypatch.setattr(Global, "enable_device_obs", False)
    snap0 = get_registry().snapshot()
    assert maybe_device_dispatch("t.off", template="x", live=1,
                                 capacity=1024) is None
    maybe_device_resident("fill", "segment", 1 << 20)
    note_feedback("join_route", "demote_host")
    proxy, text = tri_proxy
    _force_device_wcoj(monkeypatch)
    monkeypatch.setattr(Global, "enable_device_obs", False)
    proxy.serve_query(text, blind=True)
    obs = get_device_obs()
    assert obs.dispatch_ledger.report(10) == []
    assert obs.residency.totals() == {}
    assert obs.compile_ledger.variant_counts() == {}
    snap1 = get_registry().snapshot()
    for metric in DEVICE_INPUTS.values():
        assert (snap1.get(metric) or {}).get("series", []) == \
            (snap0.get(metric) or {}).get("series", []), metric
    text_out, js = render_device()
    assert "enable_device_obs is OFF" in text_out
    assert js["enabled"] is False
