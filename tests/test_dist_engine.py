"""Distributed engine vs CPU oracle on an 8-way partitioned LUBM-1 (CPU mesh)."""

import glob
import os

import numpy as np
import pytest

from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
from wukong_tpu.parallel.dist_engine import DistEngine
from wukong_tpu.parallel.mesh import make_mesh
from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.sparql.parser import Parser
from wukong_tpu.store.gstore import build_all_partitions, build_partition

BASIC = "/root/reference/scripts/sparql_query/lubm/basic"

# BGP-only, const-predicate queries (the distributed v1 support matrix —
# same scope as the reference's GPU engine)
DIST_QUERIES = ["lubm_q1", "lubm_q2", "lubm_q3", "lubm_q4", "lubm_q5",
                "lubm_q6", "lubm_q7", "lubm_q12"]


@pytest.fixture(autouse=True)
def _pin_collective_route():
    """At LUBM-1 every const-start chain is light, so the default in-place
    routing would answer most of this module without touching the
    collective machinery it validates. Pin the sharded route; the
    test_inplace_* cases flip the flag back on explicitly."""
    from wukong_tpu.config import Global

    old = Global.enable_dist_inplace
    Global.enable_dist_inplace = False
    yield
    Global.enable_dist_inplace = old


@pytest.fixture(scope="module")
def world(eight_cpu_devices):
    triples, _ = generate_lubm(1, seed=42)
    ss = VirtualLubmStrings(1, seed=42)
    g1 = build_partition(triples, 0, 1)
    stores = build_all_partitions(triples, 8)
    mesh = make_mesh(8)
    dist = DistEngine(stores, ss, mesh)
    cpu = CPUEngine(g1, ss)
    return ss, cpu, dist


@pytest.mark.parametrize("qn", DIST_QUERIES)
def test_dist_matches_cpu(world, qn):
    ss, cpu, dist = world
    text = open(f"{BASIC}/{qn}").read()
    qc = Parser(ss).parse(text)
    heuristic_plan(qc)
    cpu.execute(qc)
    qd = Parser(ss).parse(text)
    heuristic_plan(qd)
    dist.execute(qd)
    assert qd.result.status_code == 0, (qn, qd.result.status_code)
    # compare row multisets over the shared bound variables (the dist engine
    # now projects via the host final phase; the raw-variable comparison below
    # still validates the full binding set)
    qc2 = Parser(ss).parse(text)
    heuristic_plan(qc2)
    cpu.execute(qc2, from_proxy=False)
    cols_c2 = [qc2.result.v2c_map[v] for v in sorted(qd.result.v2c_map)]
    want = sorted(map(tuple, qc2.result.table[:, cols_c2].tolist()))
    cols_d = [qd.result.v2c_map[v] for v in sorted(qd.result.v2c_map)]
    got = sorted(map(tuple, qd.result.table[:, cols_d].tolist()))
    assert got == want, f"{qn}: dist {len(got)} vs cpu {len(want)} rows"


def test_dist_blind_counts(world):
    ss, cpu, dist = world
    text = open(f"{BASIC}/lubm_q2").read()
    qc = Parser(ss).parse(text)
    heuristic_plan(qc)
    cpu.execute(qc, from_proxy=False)
    qd = Parser(ss).parse(text)
    heuristic_plan(qd)
    qd.result.blind = True
    dist.execute(qd)
    assert qd.result.status_code == 0
    assert qd.result.nrows == qc.result.nrows


def test_dist_versatile_const_start(world):
    """?X ?P <const> flips to a versatile const start (owner-partition CSR
    walk) and must match the CPU engine; bound-object versatile stays
    rejected (CPU parity — no such reference kernel)."""
    ss, cpu, dist = world
    text = ("SELECT ?X ?P WHERE "
            "{ ?X ?P <http://www.Department0.University0.edu> . }")
    qc = Parser(ss).parse(text)
    heuristic_plan(qc)
    cpu.execute(qc)
    assert qc.result.status_code == 0 and qc.result.nrows > 0
    qd = Parser(ss).parse(text)
    heuristic_plan(qd)
    dist.execute(qd)
    assert qd.result.status_code == 0
    assert _rows_of(qd.result) == _rows_of(qc.result)

    # bound-object versatile (?x ?p ?y, BOTH bound): unsupported everywhere
    from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
    from wukong_tpu.types import IN, OUT, PREDICATE_ID

    works = ss.str2id("<http://swat.cse.lehigh.edu/onto/univ-bench.owl#worksFor>")
    q = SPARQLQuery()
    q.result.nvars = 3
    q.pattern_group.patterns = [
        Pattern(works, PREDICATE_ID, IN, -1),
        Pattern(-1, works, OUT, -2),
        Pattern(-1, -3, OUT, -2),
    ]
    q.result.required_vars = [-1, -2, -3]
    dist.execute(q)
    assert q.result.status_code != 0


def test_dist_capacity_retry(world, monkeypatch):
    """Tiny capacity classes force exchange + expansion overflow retries."""
    from wukong_tpu.config import Global

    ss, cpu, dist = world
    monkeypatch.setattr(dist, "cap_min", 32)
    dist._fn_cache.clear()
    text = open(f"{BASIC}/lubm_q2").read()
    qc = Parser(ss).parse(text)
    heuristic_plan(qc)
    cpu.execute(qc, from_proxy=False)
    qd = Parser(ss).parse(text)
    heuristic_plan(qd)
    qd.result.blind = True
    dist.execute(qd)
    assert qd.result.status_code == 0
    assert qd.result.nrows == qc.result.nrows


def test_dist_larger_scale_deep_chain(world, eight_cpu_devices):
    """LUBM-2 across 8 shards: deeper chains, multiple exchanges, real skew."""
    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
    from wukong_tpu.store.gstore import build_all_partitions, build_partition

    triples, _ = generate_lubm(2, seed=9)
    ss2 = VirtualLubmStrings(2, seed=9)
    stores = build_all_partitions(triples, 8)
    dist = DistEngine(stores, ss2, make_mesh(8))
    cpu = CPUEngine(build_partition(triples, 0, 1), ss2)
    for qn in ("lubm_q1", "lubm_q7"):
        text = open(f"{BASIC}/{qn}").read()
        qc = Parser(ss2).parse(text)
        heuristic_plan(qc)
        cpu.execute(qc, from_proxy=False)
        qd = Parser(ss2).parse(text)
        heuristic_plan(qd)
        qd.result.blind = True
        dist.execute(qd)
        assert qd.result.status_code == 0, (qn, qd.result.status_code)
        assert qd.result.nrows == qc.result.nrows, qn


def test_dist_filter_and_projection(world):
    """FILTER + DISTINCT/projection run host-side after the distributed BGP."""
    ss, cpu, dist = world
    text = """
    PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
    PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
    SELECT DISTINCT ?Y1 WHERE {
        ?X ub:worksFor <http://www.Department0.University0.edu> .
        ?X rdf:type ub:FullProfessor .
        ?X ub:name ?Y1 .
        FILTER regex(?Y1, "FullProfessor[0-2]")
    }"""
    qc = Parser(ss).parse(text)
    heuristic_plan(qc)
    cpu.execute(qc)
    qd = Parser(ss).parse(text)
    heuristic_plan(qd)
    dist.execute(qd)
    assert qd.result.status_code == 0
    got = sorted(map(tuple, qd.result.table.tolist()))
    want = sorted(map(tuple, qc.result.table.tolist()))
    assert got == want and len(got) == 3


def test_dist_top_level_union(world):
    """union/q1: each branch runs distributed, results merge host-side."""
    ss, cpu, dist = world
    text = open(
        "/root/reference/scripts/sparql_query/lubm/union/q1").read()
    qc = Parser(ss).parse(text)
    heuristic_plan(qc)
    cpu.execute(qc)
    assert qc.result.status_code == 0
    qd = Parser(ss).parse(text)
    heuristic_plan(qd)
    dist.execute(qd)
    assert qd.result.status_code == 0
    got = sorted(map(tuple, qd.result.table.tolist()))
    want = sorted(map(tuple, qc.result.table.tolist()))
    assert got == want and len(got) > 0


def test_dist_union_branch_filters(world):
    """Branch-level FILTERs inside a distributed UNION must be applied."""
    ss, cpu, dist = world
    text = """
    PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
    PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
    SELECT ?X ?Y WHERE {
        { ?X rdf:type ub:Course . ?X ub:name ?Y .
          FILTER regex(?Y, "Course1.*") }
        UNION
        { ?X rdf:type ub:GraduateCourse . ?X ub:name ?Y . }
    }"""
    qc = Parser(ss).parse(text)
    heuristic_plan(qc)
    cpu.execute(qc)
    qd = Parser(ss).parse(text)
    heuristic_plan(qd)
    dist.execute(qd)
    assert qd.result.status_code == 0
    got = sorted(map(tuple, qd.result.table.tolist()))
    want = sorted(map(tuple, qc.result.table.tolist()))
    assert got == want and 0 < len(got)


# ---------------------------------------------------------------------------
# distributed v2: OPTIONAL / nested UNION / attributes (round-2 VERDICT #3)
# ---------------------------------------------------------------------------

OPTIONAL_DIR = "/root/reference/scripts/sparql_query/lubm/optional"
UNION_DIR = "/root/reference/scripts/sparql_query/lubm/union"
ATTR_DIR = "/root/reference/scripts/sparql_query/lubm/attr"
UB = "http://swat.cse.lehigh.edu/onto/univ-bench.owl#"
RDF = "http://www.w3.org/1999/02/22-rdf-syntax-ns#"


def _rows_of(res):
    return sorted(map(tuple, np.asarray(res.table).tolist()))


def _compare(world, text):
    ss, cpu, dist = world
    qc = Parser(ss).parse(text)
    heuristic_plan(qc)
    cpu.execute(qc)
    qd = Parser(ss).parse(text)
    heuristic_plan(qd)
    dist.execute(qd)
    assert qc.result.status_code == 0, f"cpu failed: {qc.result.status_code}"
    assert qd.result.status_code == 0, f"dist failed: {qd.result.status_code}"
    assert _rows_of(qc.result) == _rows_of(qd.result), (
        f"cpu {qc.result.nrows} rows vs dist {qd.result.nrows}")
    return qd


@pytest.mark.parametrize("qn", ["q1", "q1s0", "q1s1", "q2", "q2s1", "q3",
                                "q4", "q5"])
def test_dist_optional_suite(world, qn):
    # q5 has no required patterns: the parser promotes the leading OPTIONAL
    # to the base (reference planner behavior), so it runs everywhere
    _compare(world, open(f"{OPTIONAL_DIR}/{qn}").read())


@pytest.mark.parametrize("qn", ["q1", "q2"])
def test_dist_union_suite(world, qn):
    _compare(world, open(f"{UNION_DIR}/{qn}").read())


def test_dist_union_seeded_by_patterns(world):
    """UNION branches seeded by a preceding BGP (inherit_union semantics)."""
    text = f"""PREFIX ub: <{UB}>
    SELECT ?X ?Y ?Z WHERE {{
        ?X ub:memberOf ?Y .
        {{ ?X ub:undergraduateDegreeFrom ?Z . }}
        UNION {{ ?X ub:mastersDegreeFrom ?Z . }}
    }}"""
    q = _compare(world, text)
    assert q.result.nrows > 0


def test_dist_optional_with_blanks_then_filter(world):
    """OPTIONAL + bound() FILTER over the BLANK-filled column."""
    text = f"""PREFIX ub: <{UB}>
    SELECT ?S ?UG ?DOC WHERE {{
        ?S ub:undergraduateDegreeFrom ?UG .
        OPTIONAL {{ ?S ub:doctoralDegreeFrom ?DOC }} .
        FILTER (!bound(?DOC))
    }}"""
    _compare(world, text)


@pytest.fixture(scope="module")
def attr_world(eight_cpu_devices):
    from wukong_tpu.loader.lubm import generate_lubm_attrs

    triples, _ = generate_lubm(1, seed=42)
    attrs = generate_lubm_attrs(1, seed=42)
    ss = VirtualLubmStrings(1, seed=42)
    g1 = build_partition(triples, 0, 1, attr_triples=attrs)
    stores = build_all_partitions(triples, 8, attr_triples=attrs)
    dist = DistEngine(stores, ss, make_mesh(8))
    cpu = CPUEngine(g1, ss)
    return ss, cpu, dist


@pytest.mark.parametrize("qn", ["lubm_attr_q1", "lubm_attr_q2", "lubm_attr_q3"])
def test_dist_attr_suite(attr_world, qn, monkeypatch):
    from wukong_tpu.config import Global

    monkeypatch.setattr(Global, "enable_vattr", True)
    ss, cpu, dist = attr_world
    text = open(f"{ATTR_DIR}/{qn}").read()
    qc = Parser(ss).parse(text)
    heuristic_plan(qc)
    cpu.execute(qc)
    qd = Parser(ss).parse(text)
    heuristic_plan(qd)
    dist.execute(qd)
    assert qc.result.status_code == 0
    assert qd.result.status_code == 0
    assert _rows_of(qc.result) == _rows_of(qd.result)
    assert np.allclose(np.sort(np.asarray(qc.result.attr_table), axis=0),
                       np.sort(np.asarray(qd.result.attr_table), axis=0))


def test_dist_blind_optional_union_silent_parity(world):
    """Reference silent mode works for ANY shape (it executes and just never
    ships the table, query.hpp:619-630): blind + OPTIONAL must return the
    true row count with an empty table, matching the non-blind row count."""
    ss, cpu, dist = world
    text = f"""PREFIX ub: <{UB}>
    SELECT ?S ?UG ?DOC WHERE {{
        ?S ub:undergraduateDegreeFrom ?UG .
        OPTIONAL {{ ?S ub:doctoralDegreeFrom ?DOC }} .
    }}"""
    qfull = Parser(ss).parse(text)
    heuristic_plan(qfull)
    dist.execute(qfull)
    assert qfull.result.status_code == 0

    q = Parser(ss).parse(text)
    heuristic_plan(q)
    q.result.blind = True
    dist.execute(q)
    assert q.result.status_code == 0
    assert q.result.nrows == qfull.result.nrows > 0
    assert q.result.table.size == 0  # the table itself is never shipped


def test_dist_optional_filter_on_parent_var(world):
    """OPTIONAL group whose FILTER references a var bound only by the parent."""
    text = f"""PREFIX ub: <{UB}>
    SELECT ?S ?UG ?DOC WHERE {{
        ?S ub:undergraduateDegreeFrom ?UG .
        OPTIONAL {{ ?S ub:doctoralDegreeFrom ?DOC . FILTER(?UG != ?DOC) }} .
    }}"""
    _compare(world, text)


def test_dist_skew_aware_exchange_no_retry(eight_cpu_devices):
    """Hub-skewed exchanges: the multiplicity-bound capacity estimate must
    absorb a University0-style hot destination on the FIRST attempt (the
    reference absorbs skew via work stealing, engine.hpp:186-207)."""
    from wukong_tpu.loader.generic_rdf import generate_generic
    from wukong_tpu.sparql.ir import Pattern, SPARQLQuery

    triples, meta = generate_generic(20_000, n_preds=8, n_types=4, seed=5)
    g1 = build_partition(triples, 0, 1)
    stores = build_all_partitions(triples, 8)
    dist = DistEngine(stores, None, make_mesh(8))
    # two-hop through the hub-attracting object column: the exchange keys on
    # a column whose values concentrate into hubs
    from wukong_tpu.types import TYPE_ID

    pids = np.unique(triples[:, 1])
    pids = [int(p) for p in pids if p != TYPE_ID][:2]

    def mk():
        q = SPARQLQuery()
        q.pattern_group.patterns = [
            Pattern(pids[0], 0, 0, -1),  # __PREDICATE__ index start
            Pattern(-1, pids[0], 1, -2),  # expand: objects (hub-skewed)
            Pattern(-2, pids[1], 1, -3),  # exchange on the hub column
        ]
        q.result.nvars = 3
        q.result.required_vars = [-1, -2, -3]
        return q

    builds = []
    orig = dist._build_plan

    def spy(q, cap_override, n_steps=None, seed=None):
        builds.append(1)
        return orig(q, cap_override, n_steps, seed)

    dist._build_plan = spy
    qd = mk()
    dist.execute(qd, from_proxy=False)
    assert qd.result.status_code == 0
    assert len(builds) == 1, f"capacity retries happened: {len(builds) - 1}"

    # the multiplicity bound must cover the true hot-destination load even
    # where the naive est//D*4 slack would not (it matters at pod-scale D,
    # where 4/D of the inflated estimate undershoots a dominant hub)
    plan = orig(mk(), {}, n_steps=3)
    exch_step = plan.steps[2]
    assert exch_step.exch_cap > 0
    hub_edges = triples[triples[:, 1] == pids[0]][:, 2]
    hot_mult = int(np.bincount(hub_edges - hub_edges.min()).max())
    assert exch_step.exch_cap >= hot_mult

    cpu = CPUEngine(g1, None)
    qc = mk()
    cpu.execute(qc, from_proxy=False)
    got = sorted(map(tuple, qd.result.table.tolist()))
    want = sorted(map(tuple, qc.result.table.tolist()))
    assert got == want


def test_preshard_multihost_load_matches_global(tmp_path, eight_cpu_devices):
    """Per-host loader sharding: 2 hosts x 4 shards, each host builds its
    partitions from ITS file only; the assembled cluster is segment-identical
    to a global build and answers queries on the 8-way mesh."""
    from wukong_tpu.loader.base import load_host_partitions, preshard_dataset
    from wukong_tpu.loader.lubm import write_dataset

    src = tmp_path / "ds"
    shard_dir = tmp_path / "sharded"
    write_dataset(str(src), 1, seed=9)
    meta = preshard_dataset(str(src), str(shard_dir), num_hosts=2,
                            shards_per_host=4)
    assert meta["num_hosts"] == 2

    stores = []
    for h in range(2):  # each host loads independently
        stores.extend(load_host_partitions(str(shard_dir), h))
    assert [g.sid for g in stores] == list(range(8))
    # attribute triples must survive presharding (subject-owner placement)
    assert any(g.attrs for g in stores)

    from wukong_tpu.loader.base import load_triples

    triples = load_triples(str(src))
    want = build_all_partitions(triples, 8)
    for g, w in zip(stores, want):
        assert set(g.segments) == set(w.segments), g.sid
        for k in w.segments:
            assert np.array_equal(g.segments[k].keys, w.segments[k].keys)
            assert np.array_equal(g.segments[k].edges, w.segments[k].edges)
        for k in w.index:
            assert np.array_equal(np.sort(g.index[k]), np.sort(w.index[k]))

    ss = VirtualLubmStrings(1, seed=9)
    dist = DistEngine(stores, ss, make_mesh(8))
    cpu = CPUEngine(build_partition(triples, 0, 1), ss)
    text = open(f"{BASIC}/lubm_q4").read()
    qd = Parser(ss).parse(text)
    heuristic_plan(qd)
    dist.execute(qd)
    qc = Parser(ss).parse(text)
    heuristic_plan(qc)
    cpu.execute(qc)
    assert qd.result.status_code == 0
    assert _rows_of(qd.result) == _rows_of(qc.result)


def test_dist_versatile_kuu(world):
    """Distributed VERSATILE ?x ?p ?y (x bound): each shard expands its
    combined adjacency inside the compiled chain — beyond the reference,
    whose accelerator refuses every versatile shape. Exact row parity with
    the single-host CPU kernels, including a continuation step."""
    _compare(world, f"""PREFIX ub: <{UB}>
    SELECT ?X ?P ?Y WHERE {{
        ?X ub:worksFor <http://www.Department0.University0.edu> .
        ?X ?P ?Y .
    }}""")
    # continuation anchored on the versatile VALUE column
    _compare(world, f"""PREFIX rdf: <{RDF}>
    PREFIX ub: <{UB}>
    SELECT ?X ?P ?Y WHERE {{
        ?X ub:worksFor <http://www.Department0.University0.edu> .
        ?X ?P ?Y .
        ?Y rdf:type ub:Course .
    }}""")


def test_dist_versatile_probe_bound(eight_cpu_devices):
    """The compiled versatile step must bake the COMBINED segment's probe
    bound, not a missing segment(pid=0)'s default of 1 — on this world the
    versatile hash table needs 3 probe rounds, so a baked max_probe=1
    silently drops every key outside its home bucket (a real bug once)."""
    from wukong_tpu.loader.generic_rdf import generate_generic
    from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
    from wukong_tpu.types import OUT, TYPE_ID

    triples, meta = generate_generic(20_000, n_preds=8, n_types=4, seed=5)
    stores = build_all_partitions(triples, 8)
    dist = DistEngine(stores, None, make_mesh(8))
    assert dist.sstore.versatile_segment(int(OUT)).max_probe > 1

    pids = [int(p) for p in np.unique(triples[:, 1]) if p != TYPE_ID][:1]

    def mk():
        q = SPARQLQuery()
        q.pattern_group.patterns = [
            Pattern(pids[0], 0, 0, -1),   # __PREDICATE__ index start
            Pattern(-1, -2, OUT, -3)]     # versatile ?x ?p ?y
        q.result.nvars = 3
        q.result.required_vars = [-1, -2, -3]
        return q

    qd = mk()
    dist.execute(qd, from_proxy=False)
    assert qd.result.status_code == 0
    cpu = CPUEngine(build_partition(triples, 0, 1), None)
    qc = mk()
    cpu.execute(qc, from_proxy=False)
    assert _rows_of(qd.result) == _rows_of(qc.result)
    assert qc.result.nrows > 0


def test_dist_c2k_mid_chain(world):
    """const_to_known mid-chain (sparql.hpp:138-163's c2k): a const-subject
    pattern whose object is already bound runs as a reverse-segment member
    step inside the compiled chain (patterns built in index form so the
    c2k stays mid-chain — heuristic_plan would hoist the const start)."""
    from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
    from wukong_tpu.types import IN, OUT, TYPE_ID

    ss, cpu, dist = world
    fp = ss.str2id("<http://swat.cse.lehigh.edu/onto/univ-bench.owl#FullProfessor>")
    works = ss.str2id("<http://swat.cse.lehigh.edu/onto/univ-bench.owl#worksFor>")
    fp0 = ss.str2id("<http://www.Department0.University0.edu/FullProfessor0>")

    def mk():
        q = SPARQLQuery()
        q.pattern_group.patterns = [
            Pattern(fp, TYPE_ID, IN, -1),    # type-index start -> ?X
            Pattern(-1, works, OUT, -2),     # ?X worksFor ?D
            Pattern(fp0, works, OUT, -2),    # c2k: FP0 worksFor ?D (bound)
        ]
        q.result.nvars = 2
        q.result.required_vars = [-1, -2]
        return q

    qc, qd = mk(), mk()
    cpu.execute(qc, from_proxy=False)
    dist.execute(qd, from_proxy=False)
    assert qd.result.status_code == 0
    assert _rows_of(qd.result) == _rows_of(qc.result)
    assert qc.result.nrows > 0  # FullProfessors of Department0.University0


def test_dist_seeded_union_c2k_branch(world):
    """UNION branches whose FIRST pattern is const-subject/bound-object run
    distributed off the seeded parent rows (widened seed-anchor resolution)."""
    from wukong_tpu.sparql.ir import Pattern, PatternGroup, SPARQLQuery
    from wukong_tpu.types import IN, OUT, TYPE_ID

    ss, cpu, dist = world
    ap = ss.str2id("<http://swat.cse.lehigh.edu/onto/univ-bench.owl#AssociateProfessor>")
    works = ss.str2id("<http://swat.cse.lehigh.edu/onto/univ-bench.owl#worksFor>")
    fp0 = ss.str2id("<http://www.Department0.University0.edu/FullProfessor0>")
    fp1 = ss.str2id("<http://www.Department1.University0.edu/FullProfessor0>")

    def mk():
        q = SPARQLQuery()
        q.pattern_group.patterns = [
            Pattern(ap, TYPE_ID, IN, -1),
            Pattern(-1, works, OUT, -2),
        ]
        for c in (fp0, fp1):
            u = PatternGroup()
            u.patterns = [Pattern(c, works, OUT, -2)]  # seeded c2k branch
            q.pattern_group.unions.append(u)
        q.result.nvars = 2
        q.result.required_vars = [-1, -2]
        return q

    qc, qd = mk(), mk()
    cpu.execute(qc, from_proxy=False)
    dist.execute(qd, from_proxy=False)
    assert qd.result.status_code == 0
    assert _rows_of(qd.result) == _rows_of(qc.result)
    assert qc.result.nrows > 0


def test_dist_versatile_const_shapes(world):
    """Distributed const_unknown_const and known_unknown_const: owner-shard
    CSR start / expand2 + equality fold inside the compiled chain."""
    from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
    from wukong_tpu.types import IN, OUT, TYPE_ID

    ss, cpu, dist = world
    dept0 = ss.str2id("<http://www.Department0.University0.edu>")
    univ0 = ss.str2id("<http://www.University0.edu>")
    fp = ss.str2id("<http://swat.cse.lehigh.edu/onto/univ-bench.owl#FullProfessor>")
    works = ss.str2id("<http://swat.cse.lehigh.edu/onto/univ-bench.owl#worksFor>")

    def run(eng, pats, req):
        q = SPARQLQuery()
        q.result.nvars = len(req)
        q.pattern_group.patterns = [Pattern(*p) for p in pats]
        q.result.required_vars = list(req)
        eng.execute(q, from_proxy=False)
        assert q.result.status_code == 0, q.result.status_code
        cols = [q.result.var2col(v) for v in req]
        return sorted(map(tuple, np.asarray(q.result.table)[:, cols].tolist()))

    def cmp(pats, req, name):
        a = run(cpu, pats, req)
        b = run(dist, pats, req)
        assert a == b, (name, len(a), len(b))
        assert len(a) > 0, (name, "vacuous: empty result")

    # const_unknown_const start: Dept0 ?P Univ0
    cmp([(dept0, -9, OUT, univ0)], [-9], "c_u_c")
    # versatile const start continuing into a distributed chain: everyone
    # with an edge INTO Dept0, then where they work
    cmp([(dept0, -9, IN, -1), (-1, works, OUT, -2)], [-9, -1, -2],
        "c_u_u_then_chain")
    # known_unknown_const mid-chain inside the compiled shard_map chain
    cmp([(fp, TYPE_ID, IN, -1), (-1, -9, OUT, univ0)], [-1, -9], "k_u_c")
    # continuation after the fold
    cmp([(fp, TYPE_ID, IN, -1), (-1, -9, OUT, univ0), (-1, works, OUT, -2)],
        [-1, -9, -2], "k_u_c_then_expand")


def test_learned_caps_tighten_steady_state(world):
    """Successful chains record EXACT capacity classes per pattern key: the
    second run of an exchange-bearing query compiles at capacities no
    larger than (usually far below) the estimate-driven first run, with
    identical results; an injected undersized class still self-corrects
    through the overflow retry."""
    ss, cpu, dist = world
    dist._learned_caps.clear()
    text = open(f"{BASIC}/lubm_q7").read()

    def run():
        q = Parser(ss).parse(text)
        heuristic_plan(q)
        q.result.blind = True
        dist.execute(q, from_proxy=False)
        assert q.result.status_code == 0
        return q.result.nrows, dist.last_chain_stats

    rows1, st1 = run()
    assert dist._learned_caps  # learning happened
    rows2, st2 = run()
    assert rows2 == rows1
    caps1 = [s["cap"] for s in st1["steps"]]
    caps2 = [s["cap"] for s in st2["steps"]]
    assert all(c2 <= c1 for c1, c2 in zip(caps1, caps2))
    ex1 = [s["exch_cap"] for s in st1["steps"] if "exch_cap" in s]
    ex2 = [s["exch_cap"] for s in st2["steps"] if "exch_cap" in s]
    assert ex1 and all(c2 <= c1 for c1, c2 in zip(ex1, ex2))
    # run 2's classes are exact: every load fits its (tight) class
    for s in st2["steps"]:
        assert s["rows_peak_shard"] <= s["cap"]
        if "exch_cap" in s:
            assert s["exch_peak_dest"] <= s["exch_cap"]
    # undersized injection on a LEARNED chain: retry restores correctness
    dist.force_cap_override = {("cap", 1): 2}
    rows3, st3 = run()
    assert rows3 == rows1 and st3["retries"] >= 1


# ----------------------------------------------------------------------
# round-5 in-place owner-routed fast path (reference need_fork_join,
# sparql.hpp:802-814; proxy owner routing, proxy.hpp:201-219)
# ----------------------------------------------------------------------
def _rows_over_shared_vars(q):
    cols = [q.result.v2c_map[v] for v in sorted(q.result.v2c_map)]
    return sorted(map(tuple, np.asarray(q.result.table)[:, cols].tolist()))


def test_inplace_routes_agree_with_collective(world):
    """Light const-start chains route in place (zero collectives) and must
    produce identical rows to the sharded chain — the both-routes
    verification the round-4 verdict asked the suite to carry."""
    from wukong_tpu.config import Global
    from wukong_tpu.types import NORMAL_ID_START

    ss, cpu, dist = world
    for qn in ("lubm_q4", "lubm_q5", "lubm_q6"):
        text = open(f"{BASIC}/{qn}").read()
        q1 = Parser(ss).parse(text)
        heuristic_plan(q1)
        first = q1.pattern_group.patterns[0]
        Global.enable_dist_inplace = True
        try:
            dist.execute(q1)
        finally:
            Global.enable_dist_inplace = False
        st = dist.last_chain_stats or {}
        assert q1.result.status_code == 0, qn
        if first.subject >= NORMAL_ID_START and first.predicate > 0:
            assert st.get("mode") == "inplace", (qn, st)
        q2 = Parser(ss).parse(text)
        heuristic_plan(q2)
        dist.execute(q2)  # collective (autouse fixture pinned the flag off)
        assert q2.result.status_code == 0, qn
        assert _rows_over_shared_vars(q1) == _rows_over_shared_vars(q2), qn


def test_inplace_overflow_falls_back_to_collective(world):
    """A chain whose live table outgrows dist_inplace_rows mid-walk aborts
    the in-place route and re-runs through the collective path with
    identical results (the fork-join analogue of need_fork_join)."""
    from wukong_tpu.config import Global

    ss, cpu, dist = world
    text = """PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
    SELECT ?X ?Y WHERE {
        ?X ub:subOrganizationOf <http://www.University0.edu> .
        ?Y ub:memberOf ?X .
    }"""
    q0 = Parser(ss).parse(text)
    heuristic_plan(q0)
    first = q0.pattern_group.patterns[0]
    fan = len(cpu.g.get_triples(first.subject, first.predicate,
                                first.direction))
    assert fan > 0
    qc = Parser(ss).parse(text)
    heuristic_plan(qc)
    cpu.execute(qc, from_proxy=False)
    assert qc.result.nrows > fan  # the expansion that must trip the abort

    Global.enable_dist_inplace = True
    old_thr = Global.dist_inplace_rows
    Global.dist_inplace_rows = fan  # entry passes; first expansion overflows
    try:
        qd = Parser(ss).parse(text)
        heuristic_plan(qd)
        dist.execute(qd, from_proxy=False)
    finally:
        Global.dist_inplace_rows = old_thr
        Global.enable_dist_inplace = False
    assert qd.result.status_code == 0
    st = dist.last_chain_stats or {}
    assert st.get("mode") != "inplace", st  # retreated to the sharded chain
    assert qd.result.nrows == qc.result.nrows


def test_inplace_seeded_union_child(world):
    """Seeded (UNION) children with small parent tables also ride the
    in-place route; merged rows must match the collective run."""
    from wukong_tpu.config import Global

    ss, cpu, dist = world
    text = """PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
    SELECT ?X ?Y WHERE {
        ?X ub:worksFor <http://www.Department0.University0.edu> .
        { ?X ub:teacherOf ?Y . } UNION { ?Y ub:advisor ?X . }
    }"""
    Global.enable_dist_inplace = True
    try:
        q1 = Parser(ss).parse(text)
        heuristic_plan(q1)
        dist.execute(q1)
    finally:
        Global.enable_dist_inplace = False
    assert q1.result.status_code == 0
    q2 = Parser(ss).parse(text)
    heuristic_plan(q2)
    dist.execute(q2)
    assert q2.result.status_code == 0
    assert q1.result.nrows > 0
    assert _rows_over_shared_vars(q1) == _rows_over_shared_vars(q2)


def test_inplace_attr_tail_and_blind(world):
    """In-place prefix + owner-routed attr tail + blind count parity."""
    from wukong_tpu.config import Global

    ss, cpu, dist = world
    text = open(f"{BASIC}/lubm_q4").read()
    Global.enable_dist_inplace = True
    try:
        qb = Parser(ss).parse(text)
        heuristic_plan(qb)
        qb.result.blind = True
        dist.execute(qb)
    finally:
        Global.enable_dist_inplace = False
    qc = Parser(ss).parse(text)
    heuristic_plan(qc)
    cpu.execute(qc, from_proxy=False)
    assert qb.result.status_code == 0
    assert qb.result.nrows == qc.result.nrows
    assert qb.result.table.shape[0] == 0  # blind: the table never ships


def test_dist_cap_memo_roundtrip(world, tmp_path):
    """Learned capacity classes persist across engines/processes: a fresh
    engine loading the memo starts at the exact classes (round-5 cold-start
    fix); in-process learning wins over a stale memo (setdefault)."""
    ss, cpu, dist = world
    text = open(f"{BASIC}/lubm_q7").read()
    q = Parser(ss).parse(text)
    heuristic_plan(q)
    q.result.blind = True
    dist.execute(q, from_proxy=False)
    assert q.result.status_code == 0 and dist._learned_caps
    path = str(tmp_path / "caps.json")
    dist.save_cap_memo(path)

    fresh = DistEngine(dist.sstore.stores, ss, dist.mesh)
    fresh.load_cap_memo(path)
    assert fresh._learned_caps == dist._learned_caps
    # in-process learning is not clobbered by a later load
    key = next(iter(fresh._learned_caps))
    fresh._learned_caps[key] = {("cap", 0): 1024}
    fresh.load_cap_memo(path)
    assert fresh._learned_caps[key] == {("cap", 0): 1024}
