"""Dynamic (incremental) store: online inserts, gsck, device-cache invalidation."""

import numpy as np
import pytest

from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.engine.tpu import TPUEngine
from wukong_tpu.loader.lubm import P, T, VirtualLubmStrings, generate_lubm
from wukong_tpu.store.checker import check_cross_partition, check_partition
from wukong_tpu.store.dynamic import insert_triples
from wukong_tpu.store.gstore import build_all_partitions, build_partition
from wukong_tpu.types import IN, OUT, TYPE_ID


@pytest.fixture()
def world():
    triples, lay = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    return triples, lay, g, ss


def test_insert_equals_bulk_build(world):
    """bulk(all) == bulk(half) + insert(half), segment by segment."""
    triples, lay, g_full, ss = world
    half = len(triples) // 2
    rng = np.random.default_rng(0)
    perm = rng.permutation(len(triples))
    a, b = triples[perm[:half]], triples[perm[half:]]
    g = build_partition(a, 0, 1)
    insert_triples(g, b)
    assert set(g.segments) == set(g_full.segments)
    for k in g_full.segments:
        assert np.array_equal(g.segments[k].keys, g_full.segments[k].keys), k
        assert np.array_equal(g.segments[k].edges, g_full.segments[k].edges), k
    for k in g_full.index:
        assert np.array_equal(np.sort(g.index[k]), np.sort(g_full.index[k])), k
    assert check_partition(g) == []


def test_insert_new_predicate_and_type(world):
    triples, lay, g, ss = world
    NEW_P, NEW_T = 90, 91
    v1, v2 = 1 << 20, (1 << 20) + 1
    batch = np.asarray([[v1, NEW_P, v2], [v1, TYPE_ID, NEW_T]], dtype=np.int64)
    insert_triples(g, batch)
    assert g.get_triples(v1, NEW_P, OUT).tolist() == [v2]
    assert g.get_triples(v2, NEW_P, IN).tolist() == [v1]
    assert g.get_index(NEW_T, IN).tolist() == [v1]
    assert g.get_index(NEW_P, IN).tolist() == [v1]
    assert check_partition(g) == []


def test_multi_partition_insert_consistent(world):
    triples, lay, g, ss = world
    stores = build_all_partitions(triples[: len(triples) // 2], 4)
    for st in stores:
        insert_triples(st, triples[len(triples) // 2:])
    assert check_cross_partition(stores) == []


def test_device_cache_invalidation(world):
    triples, lay, g, ss = world
    tpu = TPUEngine(g, ss)
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.sparql.parser import Parser

    text = """PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
    SELECT ?X WHERE { ?X ub:worksFor <http://www.Department0.University0.edu> . }"""
    q = Parser(ss).parse(text)
    heuristic_plan(q)
    tpu.execute(q)
    before = q.result.nrows
    # a new professor joins dept0
    d0 = ss.str2id("<http://www.Department0.University0.edu>")
    newv = 1 << 22
    insert_triples(g, np.asarray([[newv, P["worksFor"], d0]], dtype=np.int64))
    q2 = Parser(ss).parse(text)
    heuristic_plan(q2)
    tpu.execute(q2)
    assert q2.result.nrows == before + 1  # stale staging would miss the insert


def test_dedup_on_insert(world):
    triples, lay, g, ss = world
    d0 = int(lay.dept_id[0])
    fp0 = int(lay.fac_base[0])
    n0 = len(g.get_triples(fp0, P["worksFor"], OUT))
    insert_triples(g, np.asarray([[fp0, P["worksFor"], d0]], dtype=np.int64),
                   dedup=True)
    assert len(g.get_triples(fp0, P["worksFor"], OUT)) == n0  # already present


def test_insert_returns_actual_new_edges(world):
    triples, lay, g, ss = world
    from wukong_tpu.loader.lubm import P
    d0 = int(lay.dept_id[0])
    fp0 = int(lay.fac_base[0])
    dup = np.asarray([[fp0, P["worksFor"], d0]], dtype=np.int64)
    assert insert_triples(g, dup, dedup=True) == 0  # already present
    new = np.asarray([[1 << 23, P["worksFor"], d0]], dtype=np.int64)
    assert insert_triples(g, new, dedup=True) == 1


def test_insert_keep_duplicates(world):
    triples, lay, g, ss = world
    from wukong_tpu.loader.lubm import P
    d0 = int(lay.dept_id[0])
    fp0 = int(lay.fac_base[0])
    n0 = len(g.get_triples(fp0, P["worksFor"], OUT))
    insert_triples(g, np.asarray([[fp0, P["worksFor"], d0]], dtype=np.int64),
                   dedup=False)
    assert len(g.get_triples(fp0, P["worksFor"], OUT)) == n0 + 1


def test_delta_segment_lazy_merge_amortized():
    """N insert batches + 1 read = exactly ONE materialization (SURVEY §7.7:
    delta segments + periodic merge, not O(segment) per batch)."""
    import numpy as np

    from wukong_tpu.store.dynamic import DeltaCSRSegment
    from wukong_tpu.store.segment import CSRSegment

    base = CSRSegment.from_pairs(
        np.arange(1000, dtype=np.int64) % 100 + (1 << 17),
        np.arange(1000, dtype=np.int64) + (1 << 18))
    seg = DeltaCSRSegment(base)
    merges = [0]
    orig = DeltaCSRSegment._mat

    def spy(self):
        if self._pending:
            merges[0] += 1
        return orig(self)

    DeltaCSRSegment._mat = spy
    try:
        for i in range(50):  # 50 write batches: no materialization
            ks = np.asarray([(1 << 17) + i], dtype=np.int64)
            vs = np.asarray([(1 << 19) + i], dtype=np.int64)
            assert seg.append(ks, vs, dedup=True) == 1
            assert seg.append(ks, vs, dedup=True) == 0  # delta-visible dedup
        assert merges[0] == 0  # appends alone never merge
        assert seg._n_pending == 50
        assert seg.num_edges == base.num_edges + 50  # exact, no merge needed
        assert seg._pending  # still unmerged

        got = seg.lookup((1 << 17) + 3)  # first read materializes
        assert merges[0] == 1  # exactly ONE merge for 50 write batches
    finally:
        DeltaCSRSegment._mat = orig
    assert (1 << 19) + 3 in got.tolist()
    assert not seg._pending and seg._n_pending == 0
    # merged arrays identical to a from-scratch build
    full_k = np.concatenate([np.repeat(base.keys, np.diff(base.offsets)),
                             np.arange(50, dtype=np.int64) + (1 << 17)])
    full_v = np.concatenate([base.edges,
                             np.arange(50, dtype=np.int64) + (1 << 19)])
    want = CSRSegment.from_pairs(full_k, full_v)
    assert np.array_equal(seg.keys, want.keys)
    assert np.array_equal(seg.edges, want.edges)
