import pytest

from wukong_tpu import types
from wukong_tpu.config import GlobalConfig
from wukong_tpu.utils.errors import ErrorCode, WukongError, assert_ec


def test_id_space_split():
    assert types.PREDICATE_ID == 0
    assert types.TYPE_ID == 1
    assert types.NORMAL_ID_START == 1 << 17
    assert types.is_idx_id(5)
    assert not types.is_idx_id(1 << 17)
    assert types.is_var(-3)
    assert not types.is_var(7)


def test_dirs():
    assert types.IN == 0 and types.OUT == 1
    assert types.reverse_dir(types.IN) == types.OUT
    assert types.reverse_dir(types.OUT) == types.IN


def test_config_parse_and_immutability():
    cfg = GlobalConfig()
    cfg.finalize()
    cfg.load_str("global_num_engines 16\nglobal_mt_threshold 64\n# comment\n")
    assert cfg.num_engines == 16
    assert cfg.mt_threshold == 16  # clamped to num_engines
    cfg.load_str("global_silent off", runtime=True)
    assert cfg.silent is False
    with pytest.raises(ValueError):
        cfg.load_str("global_num_engines 2", runtime=True)
    with pytest.raises(KeyError):
        cfg.set("no_such_key", "1")


def test_error_codes():
    with pytest.raises(WukongError) as e:
        assert_ec(False, ErrorCode.VERTEX_INVALID, "col missing")
    assert e.value.code == ErrorCode.VERTEX_INVALID
