"""Fingerprint-packed hash probe (tpu_kernels._hash_find_fp).

The fp probe must be bit-identical to the classic 8-lane probe on found/
start/degree for arbitrary key sets — including buckets with duplicate
fingerprints (the fp_dup candidate bound) and probing keys absent from the
table whose fingerprint collides with a present key (verification must
reject them)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from wukong_tpu.engine import tpu_kernels as K  # noqa: E402
from wukong_tpu.engine.device_store import build_hash_table, fp_words  # noqa: E402


def _mk_table(keys, degs):
    offsets = np.zeros(len(keys) + 1, dtype=np.int64)
    np.cumsum(degs, out=offsets[1:])
    bkey, bstart, bdeg, max_probe = build_hash_table(
        np.asarray(keys, dtype=np.int64), offsets)
    w0, w1, dup = fp_words(bkey)
    return (jnp.asarray(bkey.reshape(-1)), jnp.asarray(bstart.reshape(-1)),
            jnp.asarray(bdeg.reshape(-1)), jnp.asarray(w0), jnp.asarray(w1),
            max_probe, dup)


def _both(bk, bs, bd, w0, w1, mp, dup, cur, n):
    valid = jnp.arange(len(cur), dtype=jnp.int32) < n
    f0, s0, d0 = K._hash_find(bk, bs, bd, cur, valid, mp)
    f1, s1, d1 = K._hash_find_fp(bk, bs, bd, w0, w1, cur, valid, mp, dup)
    return (np.asarray(f0), np.asarray(s0), np.asarray(d0),
            np.asarray(f1), np.asarray(s1), np.asarray(d1))


def test_fp_probe_matches_classic_random():
    rng = np.random.default_rng(0)
    keys = np.unique(rng.integers(1, 1 << 30, 5000))
    degs = rng.integers(0, 50, len(keys))
    bk, bs, bd, w0, w1, mp, dup = _mk_table(keys, degs)
    # probe a mix of present and absent keys
    cur_np = np.concatenate([
        rng.choice(keys, 4000),
        rng.integers(1, 1 << 30, 4192)]).astype(np.int32)
    cur = jnp.asarray(cur_np)
    f0, s0, d0, f1, s1, d1 = _both(bk, bs, bd, w0, w1, mp, dup,
                                   cur, len(cur) - 100)
    np.testing.assert_array_equal(f0, f1)
    np.testing.assert_array_equal(s0, s1)
    np.testing.assert_array_equal(d0, d1)


def test_fp_probe_handles_fp_collisions_in_bucket():
    """Construct keys guaranteed to share fingerprints within a bucket and
    assert fp_dup > 1 is honored (no false negatives)."""
    # find keys with equal (bucket, fingerprint) pairs by brute force
    M = np.uint32(2654435761)
    F = np.uint32(0x9E3779B1)
    NB = 2  # force tiny bucket count: every key lands in bucket 0 or 1
    cand = np.arange(1, 4000, dtype=np.uint32)
    b = (cand * M) & np.uint32(NB - 1)
    fp = ((cand * F) >> 24) & np.uint32(0xFF)
    # pick a (bucket, fp) pair with >= 3 members
    from collections import defaultdict

    groups = defaultdict(list)
    for k, bb, ff in zip(cand, b, fp):
        groups[(int(bb), int(ff))].append(int(k))
    trip = next(v for v in groups.values() if len(v) >= 3)[:3]
    other = [int(k) for k in cand[:20] if int(k) not in trip][:5]
    keys = np.asarray(sorted(trip + other), dtype=np.int64)
    degs = np.arange(1, len(keys) + 1)
    bk, bs, bd, w0, w1, mp, dup = _mk_table(keys, degs)
    assert dup >= 2  # the construction actually exercises the dup path
    cur = jnp.asarray(np.concatenate([keys, [977777]]).astype(np.int32))
    f0, s0, d0, f1, s1, d1 = _both(bk, bs, bd, w0, w1, mp, dup,
                                   cur, len(cur))
    np.testing.assert_array_equal(f0, f1)
    np.testing.assert_array_equal(s0, s1)
    np.testing.assert_array_equal(d0, d1)


def test_fp_probe_absent_key_with_colliding_fp_rejected():
    """An absent probe key whose fingerprint matches a stored key must be
    rejected by the bkey verification gather."""
    M = np.uint32(2654435761)
    F = np.uint32(0x9E3779B1)
    stored = 12345
    NBguess = 2
    sb = (np.uint32(stored) * M) & np.uint32(NBguess - 1)
    sf = ((np.uint32(stored) * F) >> 24) & np.uint32(0xFF)
    imposter = None
    for k in range(1, 200000):
        if k == stored:
            continue
        if ((np.uint32(k) * M) & np.uint32(NBguess - 1)) == sb and \
                (((np.uint32(k) * F) >> 24) & np.uint32(0xFF)) == sf:
            imposter = k
            break
    assert imposter is not None
    keys = np.asarray([stored], dtype=np.int64)
    bk, bs, bd, w0, w1, mp, dup = _mk_table(keys, np.asarray([7]))
    cur = jnp.asarray(np.asarray([stored, imposter], dtype=np.int32))
    f0, s0, d0, f1, s1, d1 = _both(bk, bs, bd, w0, w1, mp, dup, cur, 2)
    np.testing.assert_array_equal(f0, f1)
    assert bool(f1[0]) and not bool(f1[1])


def test_engine_results_identical_with_and_without_fp(tmp_path):
    """Full engine A/B: enable_fp_probe on/off must give identical results."""
    from wukong_tpu.config import Global
    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.sparql.parser import Parser
    from wukong_tpu.store.gstore import build_partition

    triples, _ = generate_lubm(1, seed=0)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=0)
    text = open(
        "/root/reference/scripts/sparql_query/lubm/basic/lubm_q7").read()
    results = {}
    for flag in (True, False):
        old = Global.enable_fp_probe
        Global.enable_fp_probe = flag
        try:
            eng = TPUEngine(g, ss)
            q = Parser(ss).parse(text)
            heuristic_plan(q)
            eng.execute(q, from_proxy=False)
            assert q.result.status_code == 0
            results[flag] = (q.result.nrows,
                             set(map(tuple,
                                     np.asarray(q.result.table).tolist())))
        finally:
            Global.enable_fp_probe = old
    assert results[True] == results[False]
