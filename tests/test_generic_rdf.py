"""DBpedia-shaped mixed workload: complex types, hub skew, engine equivalence."""

import numpy as np
import pytest

from bgp_oracle import TripleIndex, eval_bgp
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.engine.tpu import TPUEngine
from wukong_tpu.loader.generic_rdf import generate_generic
from wukong_tpu.planner.optimizer import Planner
from wukong_tpu.planner.stats import Stats
from wukong_tpu.sparql.ir import NO_RESULT, Pattern, PatternGroup, SPARQLQuery
from wukong_tpu.store.checker import check_cross_partition, check_partition
from wukong_tpu.store.gstore import build_all_partitions, build_partition
from wukong_tpu.types import IN, OUT, TYPE_ID


_fuzz_dist_cache: dict = {}


@pytest.fixture(scope="module")
def world():
    triples, meta = generate_generic(20_000, n_preds=80, n_types=20, seed=5)
    g = build_partition(triples, 0, 1)
    stats = Stats.generate(triples)
    return triples, meta, g, stats


def test_store_consistency(world):
    triples, meta, g, stats = world
    assert check_partition(g) == []
    stores = build_all_partitions(triples, 4)
    assert check_cross_partition(stores) == []


def test_complex_types_synthesized(world):
    triples, meta, g, stats = world
    # multi-typed and untyped entities must produce complex type ids (<0)
    assert any(t < 0 for t in stats.tyscount)
    assert stats.complex_members  # at least one multi-type composition
    # every complex member set contains real type ids
    for cid, members in stats.complex_members.items():
        assert all(m >= 0 for m in members)


def test_planner_on_heterogeneous_graph(world):
    triples, meta, g, stats = world
    planner = Planner(stats)
    idx = TripleIndex(triples)
    # mixed query: hub anchor + type filter + expansion
    hub = meta["hubs"][0]
    pid = int(triples[triples[:, 1] > TYPE_ID][0, 1])
    q = SPARQLQuery()
    q.pattern_group.patterns = [
        Pattern(-1, pid, OUT, hub),
        Pattern(-1, TYPE_ID, OUT, -2),
    ]
    q.result.nvars = 2
    q.result.required_vars = [-1, -2]
    raw = [(p.subject, p.predicate, p.object) for p in q.pattern_group.patterns]
    assert planner.generate_plan(q)
    eng = CPUEngine(g, None)
    eng.execute(q, from_proxy=False)
    assert q.result.status_code == 0
    cols = [q.result.v2c_map[-1], q.result.v2c_map[-2]]
    got = sorted(map(tuple, q.result.table[:, cols].tolist()))
    want = sorted(eval_bgp(idx, raw, [-1, -2]))
    assert got == want


def test_tpu_matches_cpu_on_hub_query(world):
    triples, meta, g, stats = world
    hub = meta["hubs"][0]
    pid = int(triples[triples[:, 1] > TYPE_ID][0, 1])
    mk = lambda: _mk_query(hub, pid)
    qc, qt = mk(), mk()
    CPUEngine(g, None).execute(qc, from_proxy=False)
    TPUEngine(g, None, stats=stats).execute(qt, from_proxy=False)
    assert qt.result.status_code == 0
    assert sorted(map(tuple, qt.result.table.tolist())) == \
        sorted(map(tuple, qc.result.table.tolist()))


def _mk_query(hub, pid):
    q = SPARQLQuery()
    q.pattern_group.patterns = [Pattern(hub, pid, IN, -1)]
    q.result.nvars = 1
    q.result.required_vars = [-1]
    return q


def _fuzz_dist(triples):
    """Module-cached 8-way DistEngine (one build for all fuzz seeds)."""
    from wukong_tpu.parallel.dist_engine import DistEngine
    from wukong_tpu.parallel.mesh import make_mesh

    if "dist" not in _fuzz_dist_cache:
        _fuzz_dist_cache["dist"] = DistEngine(
            build_all_partitions(triples, 8), None, make_mesh(8))
    return _fuzz_dist_cache["dist"]


def _mk_bgp_query(raw, req):
    """(s, p, o) pattern triples (OUT direction) -> executable query."""
    q = SPARQLQuery()
    q.pattern_group.patterns = [Pattern(s, p, OUT, o) for (s, p, o) in raw]
    q.result.nvars = len(req)
    q.result.required_vars = list(req)
    return q


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_random_bgps_all_engines(world, seed, eight_cpu_devices):
    """Differential fuzz: random BGP shapes (chains, stars, const anchors,
    k2k/k2c closures, type filters) planned by the type-centric Planner and
    executed by CPU and TPU engines — both must match the independent
    nested-loop oracle exactly. Broadens correctness evidence beyond the
    hand-picked suites."""
    triples, meta, g, stats = world
    rng = np.random.default_rng(1000 + seed)
    idx = TripleIndex(triples)
    planner = Planner(stats)
    cpu = CPUEngine(g, None)
    tpu = TPUEngine(g, None, stats=stats)
    dist = _fuzz_dist(triples)
    pids = [int(p) for p in np.unique(triples[:, 1]) if p != TYPE_ID]
    norm = triples[triples[:, 1] != TYPE_ID]
    typed = triples[triples[:, 1] == TYPE_ID]

    def random_bgp():
        """2-4 patterns forming a connected shape: var-var or const-anchored
        start, expansions, k2k closures, k2c consts, rdf:type filters."""
        n_pat = int(rng.integers(2, 5))
        row = norm[rng.integers(0, len(norm))]  # real edge: non-trivial start
        if rng.random() < 0.3:  # const-anchored start
            pats = [(int(row[0]), int(row[1]), -1)]
            bound = [-1]
            nxt = -2
        else:
            pats = [(-1, int(row[1]), -2)]
            bound = [-1, -2]
            nxt = -3
        for _ in range(n_pat - 1):
            a = int(rng.choice(bound))
            pid = int(rng.choice(pids))
            kind = rng.random()
            if kind < 0.45:  # expand to a fresh var
                pats.append((a, pid, nxt) if rng.random() < 0.5
                            else (nxt, pid, a))
                bound.append(nxt)
                nxt -= 1
            elif kind < 0.6:  # rdf:type filter on a bound var
                t = int(typed[rng.integers(0, len(typed)), 2])
                pats.append((a, int(TYPE_ID), t))
            elif kind < 0.8 and len(bound) >= 2:  # k2k closure
                b = int(rng.choice([v for v in bound if v != a]))
                pats.append((a, pid, b))
            else:  # k2c against a real object of this pid
                objs = norm[norm[:, 1] == pid][:, 2]
                pats.append((a, pid, int(objs[rng.integers(0, len(objs))])))
        return pats, sorted(set(bound), reverse=True)

    for _ in range(4):
        raw, req = random_bgp()
        want = sorted(eval_bgp(idx, raw, req))
        engines = [("cpu", cpu), ("tpu", tpu)]
        if raw[0][0] > 0:  # const-anchored: dist-plannable shape
            # both distributed routes: the default (in-place owner-routed
            # when light) and the pinned collective shard_map chain
            engines.append(("dist", dist))
            engines.append(("dist-collective", dist))
        outs = {}
        for name, eng in engines:
            q = _mk_bgp_query(raw, req)
            assert planner.generate_plan(q)
            if name == "dist-collective":
                from wukong_tpu.config import Global

                Global.enable_dist_inplace = False
                try:
                    eng.execute(q)
                finally:
                    Global.enable_dist_inplace = True
            else:
                eng.execute(q)
            assert q.result.status_code == 0, (name, raw)
            cols = [q.result.var2col(v) for v in req]
            outs[name] = sorted(
                map(tuple, np.asarray(q.result.table)[:, cols].tolist()))
        for name, rows in outs.items():
            assert rows == want, f"{name} diverged on {raw}"


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_versatile_shapes_all_engines(world, seed, eight_cpu_devices):
    """Differential fuzz over the VERSATILE (unbound-predicate) shapes:
    const_unknown_unknown / const_unknown_const starts, known_unknown_const
    folds, known_unknown_unknown mid-chain — CPU, TPU and distributed
    engines (every shape is const-anchored, hence dist-plannable) vs the
    nested-loop oracle. OUT direction only: the combined adjacency includes
    rdf:type OUT edges, matching the raw-triple oracle (the IN side
    excludes them by design)."""
    triples, meta, g, stats = world
    rng = np.random.default_rng(7000 + seed)
    idx = TripleIndex(triples)
    cpu = CPUEngine(g, None)
    tpu = TPUEngine(g, None, stats=stats)
    dist = _fuzz_dist(triples)
    pids = [int(p) for p in np.unique(triples[:, 1]) if p != TYPE_ID]
    norm = triples[triples[:, 1] != TYPE_ID]

    def shapes():
        row = norm[rng.integers(0, len(norm))]
        s0, p0, o0 = int(row[0]), int(row[1]), int(row[2])
        row2 = norm[rng.integers(0, len(norm))]
        pid = int(rng.choice(pids))
        # a second-hop object reachable from o0 (=> the k_u_c fold below is
        # non-empty for at least the o0 row); fall back to an arbitrary one
        hop2 = norm[norm[:, 0] == o0]
        o2 = int(hop2[0, 2]) if len(hop2) else int(row2[2])
        return [
            # versatile const start, then a normal expand off the value
            [(s0, -20, -1), (-1, pid, -2)],
            # const_unknown_const (real edge => non-empty)
            [(s0, -20, o0)],
            # known_unknown_const fold mid-chain (reachable object)
            [(s0, p0, -1), (-1, -20, o2)],
            # known_unknown_unknown mid-chain off a const-anchored start
            [(s0, p0, -1), (-1, -20, -21)],
            # k_u_c against an arbitrary (often non-matching) object
            [(s0, p0, -1), (-1, -20, int(row2[2]))],
        ]

    for raw in shapes():
        req = sorted({v for pat in raw for v in pat if v < 0}, reverse=True)
        want = sorted(eval_bgp(idx, raw, req))
        for name, eng in (("cpu", cpu), ("tpu", tpu), ("dist", dist),
                          ("dist-collective", dist)):
            q = _mk_bgp_query(raw, req)
            if name == "dist-collective":
                from wukong_tpu.config import Global

                Global.enable_dist_inplace = False
                try:
                    eng.execute(q, from_proxy=False)
                finally:
                    Global.enable_dist_inplace = True
            else:
                eng.execute(q, from_proxy=False)
            assert q.result.status_code == 0, (name, raw)
            cols = [q.result.var2col(v) for v in req]
            got = sorted(
                map(tuple, np.asarray(q.result.table)[:, cols].tolist()))
            assert got == want, f"{name} diverged on {raw}"


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_union_optional_all_engines(world, seed, eight_cpu_devices):
    """Differential fuzz over UNION/OPTIONAL composition: random anchored
    parents with random seeded branches/groups must agree across the CPU
    (in-place masking), TPU (seeded device children + left join) and
    distributed (shard_map children + left join) engines — three
    independent formulations of the same relation."""
    triples, meta, g, stats = world
    rng = np.random.default_rng(3000 + seed)
    cpu = CPUEngine(g, None)
    tpu = TPUEngine(g, None, stats=stats)
    dist = _fuzz_dist(triples)
    pids = [int(p) for p in np.unique(triples[:, 1]) if p != TYPE_ID]
    norm = triples[triples[:, 1] != TYPE_ID]

    def rand_case():
        # var ids must be CONTIGUOUS -1..-n (the parser convention the
        # engines' union merge iterates over)
        row = norm[rng.integers(0, len(norm))]
        pats = [(int(row[0]), int(row[1]), -1)]
        bound = [-1]
        nxt = -2
        if rng.random() < 0.5:  # optional second parent hop
            pats.append((-1, int(rng.choice(pids)), nxt))
            bound.append(nxt)
            nxt -= 1
        unions, optionals = [], []
        n_u = int(rng.integers(0, 3))
        if n_u:  # anchored 1-pattern branches binding ONE shared var
            v = nxt
            nxt -= 1
            for _ in range(n_u):
                a = int(rng.choice(bound))
                unions.append([(a, int(rng.choice(pids)), v)])
        for _ in range(int(rng.integers(0, 3))):  # optional groups
            a = int(rng.choice(bound))
            grp = [(a, int(rng.choice(pids)), nxt)]
            nxt -= 1
            if rng.random() < 0.4:  # 2-hop group
                grp.append((grp[0][2], int(rng.choice(pids)), nxt))
                nxt -= 1
            optionals.append(grp)
        return pats, unions, optionals

    for _ in range(3):
        pats, unions, optionals = rand_case()
        all_vars = sorted({v for src in ([pats] + unions + optionals)
                           for p in src for v in p if v < 0}, reverse=True)

        def mk():
            q = SPARQLQuery()
            q.result.nvars = len(all_vars)
            q.pattern_group.patterns = [Pattern(s, p, OUT, o)
                                        for (s, p, o) in pats]
            for b in unions:
                u = PatternGroup()
                u.patterns = [Pattern(s, p, OUT, o) for (s, p, o) in b]
                q.pattern_group.unions.append(u)
            for grp in optionals:
                og = PatternGroup()
                og.patterns = [Pattern(s, p, OUT, o) for (s, p, o) in grp]
                q.pattern_group.optional.append(og)
            q.result.required_vars = list(all_vars)
            return q

        outs = {}
        for name, eng in (("cpu", cpu), ("tpu", tpu), ("dist", dist)):
            q = mk()
            eng.execute(q, from_proxy=False)
            assert q.result.status_code == 0, \
                (name, pats, unions, optionals, q.result.status_code)
            cols = [q.result.var2col(v) for v in all_vars]
            assert all(c != NO_RESULT for c in cols), (name, cols)
            outs[name] = sorted(
                map(tuple, np.asarray(q.result.table)[:, cols].tolist()))
        assert outs["tpu"] == outs["cpu"], \
            ("tpu", pats, unions, optionals,
             len(outs["tpu"]), len(outs["cpu"]))
        assert outs["dist"] == outs["cpu"], \
            ("dist", pats, unions, optionals,
             len(outs["dist"]), len(outs["cpu"]))


def test_versatile_in_union_and_optional_children(world, eight_cpu_devices):
    """VERSATILE patterns inside UNION branches and OPTIONAL groups: the
    three engines route children through entirely different machinery
    (host kernels / device expand2 / shard_map expand_versatile) and must
    agree on the composed result."""
    triples, meta, g, stats = world
    cpu = CPUEngine(g, None)
    tpu = TPUEngine(g, None, stats=stats)
    dist = _fuzz_dist(triples)
    norm = triples[triples[:, 1] != TYPE_ID]
    row = norm[0]
    c, p0 = int(row[0]), int(row[1])

    def mk(unions, optional):
        q = SPARQLQuery()
        q.pattern_group.patterns = [Pattern(c, p0, OUT, -1)]
        if unions:
            for _ in range(2):
                u = PatternGroup()
                u.patterns = [Pattern(-1, -2, OUT, -3)]
                q.pattern_group.unions.append(u)
        if optional:
            og = PatternGroup()
            og.patterns = [Pattern(-1, -4 if unions else -2,
                                   OUT, -5 if unions else -3)]
            q.pattern_group.optional.append(og)
        q.result.required_vars = sorted(
            {v for pt in (q.pattern_group.patterns
                          + [x for u in q.pattern_group.unions
                             for x in u.patterns]
                          + [x for o in q.pattern_group.optional
                             for x in o.patterns])
             for v in (pt.subject, pt.predicate, pt.object) if v < 0},
            reverse=True)
        q.result.nvars = len(q.result.required_vars)
        return q

    for unions, optional in ((True, False), (False, True), (True, True)):
        outs = {}
        for name, eng in (("cpu", cpu), ("tpu", tpu), ("dist", dist)):
            q = mk(unions, optional)
            eng.execute(q, from_proxy=False)
            assert q.result.status_code == 0, (name, unions, optional)
            cols = [q.result.var2col(v) for v in q.result.required_vars]
            assert all(col != NO_RESULT for col in cols), (name, cols)
            outs[name] = sorted(
                map(tuple, np.asarray(q.result.table)[:, cols].tolist()))
        assert outs["cpu"] == outs["tpu"] == outs["dist"], (unions, optional)
        assert len(outs["cpu"]) > 0, (unions, optional)
