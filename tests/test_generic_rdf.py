"""DBpedia-shaped mixed workload: complex types, hub skew, engine equivalence."""

import numpy as np
import pytest

from bgp_oracle import TripleIndex, eval_bgp
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.engine.tpu import TPUEngine
from wukong_tpu.loader.generic_rdf import generate_generic
from wukong_tpu.planner.optimizer import Planner
from wukong_tpu.planner.stats import Stats
from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
from wukong_tpu.store.checker import check_cross_partition, check_partition
from wukong_tpu.store.gstore import build_all_partitions, build_partition
from wukong_tpu.types import IN, OUT, TYPE_ID


@pytest.fixture(scope="module")
def world():
    triples, meta = generate_generic(20_000, n_preds=80, n_types=20, seed=5)
    g = build_partition(triples, 0, 1)
    stats = Stats.generate(triples)
    return triples, meta, g, stats


def test_store_consistency(world):
    triples, meta, g, stats = world
    assert check_partition(g) == []
    stores = build_all_partitions(triples, 4)
    assert check_cross_partition(stores) == []


def test_complex_types_synthesized(world):
    triples, meta, g, stats = world
    # multi-typed and untyped entities must produce complex type ids (<0)
    assert any(t < 0 for t in stats.tyscount)
    assert stats.complex_members  # at least one multi-type composition
    # every complex member set contains real type ids
    for cid, members in stats.complex_members.items():
        assert all(m >= 0 for m in members)


def test_planner_on_heterogeneous_graph(world):
    triples, meta, g, stats = world
    planner = Planner(stats)
    idx = TripleIndex(triples)
    # mixed query: hub anchor + type filter + expansion
    hub = meta["hubs"][0]
    pid = int(triples[triples[:, 1] > TYPE_ID][0, 1])
    q = SPARQLQuery()
    q.pattern_group.patterns = [
        Pattern(-1, pid, OUT, hub),
        Pattern(-1, TYPE_ID, OUT, -2),
    ]
    q.result.nvars = 2
    q.result.required_vars = [-1, -2]
    raw = [(p.subject, p.predicate, p.object) for p in q.pattern_group.patterns]
    assert planner.generate_plan(q)
    eng = CPUEngine(g, None)
    eng.execute(q, from_proxy=False)
    assert q.result.status_code == 0
    cols = [q.result.v2c_map[-1], q.result.v2c_map[-2]]
    got = sorted(map(tuple, q.result.table[:, cols].tolist()))
    want = sorted(eval_bgp(idx, raw, [-1, -2]))
    assert got == want


def test_tpu_matches_cpu_on_hub_query(world):
    triples, meta, g, stats = world
    hub = meta["hubs"][0]
    pid = int(triples[triples[:, 1] > TYPE_ID][0, 1])
    mk = lambda: _mk_query(hub, pid)
    qc, qt = mk(), mk()
    CPUEngine(g, None).execute(qc, from_proxy=False)
    TPUEngine(g, None, stats=stats).execute(qt, from_proxy=False)
    assert qt.result.status_code == 0
    assert sorted(map(tuple, qt.result.table.tolist())) == \
        sorted(map(tuple, qc.result.table.tolist()))


def _mk_query(hub, pid):
    q = SPARQLQuery()
    q.pattern_group.patterns = [Pattern(hub, pid, IN, -1)]
    q.result.nvars = 1
    q.result.required_vars = [-1]
    return q
