"""Pinned golden result counts at LUBM-40 (docs/performance `#R` methodology).

The reference's per-commit perf reports record expected result counts per
query (e.g. docs/performance/S1C24-LUBM2560-20181203.md `#R` columns) — the
de-facto regression harness. These counts were recorded ONCE from the CPU
oracle at LUBM-40 (synthesizer DATASET_VERSION=2, seed=0) and pinned, so an
engine regression surfaces even where the nested-loop-join oracle (used at
LUBM-1) would be too slow to run.
"""

import pytest

from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.engine.tpu import TPUEngine
from wukong_tpu.loader.lubm import DATASET_VERSION, VirtualLubmStrings, generate_lubm
from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.sparql.parser import Parser
from wukong_tpu.store.gstore import build_partition

BASIC = "/root/reference/scripts/sparql_query/lubm/basic"

# (query, rows) at LUBM-40 seed=0 — recorded from the CPU oracle, v2 dataset
GOLDEN_LUBM40 = {
    "lubm_q1": 2587,
    "lubm_q2": 43172,
    "lubm_q3": 0,
    "lubm_q4": 8,
    "lubm_q5": 15,
    "lubm_q6": 208,
    "lubm_q7": 1217,
}


@pytest.fixture(scope="module")
def world40():
    assert DATASET_VERSION == 2, "re-record GOLDEN_LUBM40 for the new dataset"
    triples, _ = generate_lubm(40, seed=0)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(40, seed=0)
    return g, ss


@pytest.mark.parametrize("qn", sorted(GOLDEN_LUBM40))
def test_golden_counts_cpu(world40, qn):
    g, ss = world40
    q = Parser(ss).parse(open(f"{BASIC}/{qn}").read())
    heuristic_plan(q)
    q.result.blind = True
    CPUEngine(g, ss).execute(q)
    assert q.result.status_code == 0
    assert q.result.nrows == GOLDEN_LUBM40[qn]


@pytest.fixture(scope="module")
def tpu40(world40):
    g, ss = world40
    return TPUEngine(g, ss)


@pytest.mark.parametrize("qn", sorted(GOLDEN_LUBM40))
def test_golden_counts_tpu(world40, tpu40, qn):
    g, ss = world40
    q = Parser(ss).parse(open(f"{BASIC}/{qn}").read())
    heuristic_plan(q)
    q.result.blind = True
    tpu40.execute(q)
    assert q.result.status_code == 0
    assert q.result.nrows == GOLDEN_LUBM40[qn]


def test_golden_counts_batched_heavy(world40, tpu40):
    """The batched index chain reproduces the pinned count per instance."""
    g, ss = world40
    q = Parser(ss).parse(open(f"{BASIC}/lubm_q7").read())
    heuristic_plan(q)
    q.result.blind = True
    counts = tpu40.execute_batch_index(q, 2)
    assert counts.tolist() == [GOLDEN_LUBM40["lubm_q7"]] * 2
