"""Hand-computed golden results on a hand-written NT dataset.

Round-2 verdict Weak #5: every golden count so far was recorded FROM the CPU
oracle, so nothing tied any answer to data a human has checked. This file is
that tie: a tiny university written out triple by triple below, converted by
the REAL datagen pipeline (loader/datagen.py, the generate_data.cpp
analogue), loaded through the real loader/store, and queried — with every
expected answer derived BY HAND in the comments, the way the reference's
docs/performance #R tables pin result sizes.

World (9 entities, written as visible NT):
  profs:    P1 teaches C1, C2;   P2 teaches C3.          (type Professor)
  students: S1 takes C1, C3;     S2 takes C1;  S3 takes C2.  (type Student)
  advisors: S1 -> P1, S2 -> P1, S3 -> P2.
  courses:  C1, C2, C3.                                   (type Course)
  ages:     S1 21, S2 22, S3 23 (xsd:int attributes).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EX = "http://example.org/"
NT = "".join(
    f"<{EX}{s}> <{EX if p not in ('type',) else ''}"
    for s, p in ()) or None  # placeholder, real text below

TRIPLES = """\
<http://example.org/P1> <http://www.w3.org/1999/02/22-rdf-syntax-ns#type> <http://example.org/Professor> .
<http://example.org/P2> <http://www.w3.org/1999/02/22-rdf-syntax-ns#type> <http://example.org/Professor> .
<http://example.org/S1> <http://www.w3.org/1999/02/22-rdf-syntax-ns#type> <http://example.org/Student> .
<http://example.org/S2> <http://www.w3.org/1999/02/22-rdf-syntax-ns#type> <http://example.org/Student> .
<http://example.org/S3> <http://www.w3.org/1999/02/22-rdf-syntax-ns#type> <http://example.org/Student> .
<http://example.org/C1> <http://www.w3.org/1999/02/22-rdf-syntax-ns#type> <http://example.org/Course> .
<http://example.org/C2> <http://www.w3.org/1999/02/22-rdf-syntax-ns#type> <http://example.org/Course> .
<http://example.org/C3> <http://www.w3.org/1999/02/22-rdf-syntax-ns#type> <http://example.org/Course> .
<http://example.org/P1> <http://example.org/teacherOf> <http://example.org/C1> .
<http://example.org/P1> <http://example.org/teacherOf> <http://example.org/C2> .
<http://example.org/P2> <http://example.org/teacherOf> <http://example.org/C3> .
<http://example.org/S1> <http://example.org/takesCourse> <http://example.org/C1> .
<http://example.org/S1> <http://example.org/takesCourse> <http://example.org/C3> .
<http://example.org/S2> <http://example.org/takesCourse> <http://example.org/C1> .
<http://example.org/S3> <http://example.org/takesCourse> <http://example.org/C2> .
<http://example.org/S1> <http://example.org/advisor> <http://example.org/P1> .
<http://example.org/S2> <http://example.org/advisor> <http://example.org/P1> .
<http://example.org/S3> <http://example.org/advisor> <http://example.org/P2> .
<http://example.org/S1> <http://example.org/age> "21"^^<http://www.w3.org/2001/XMLSchema#int> .
<http://example.org/S2> <http://example.org/age> "22"^^<http://www.w3.org/2001/XMLSchema#int> .
<http://example.org/S3> <http://example.org/age> "23"^^<http://www.w3.org/2001/XMLSchema#int> .
"""

PREFIX = "PREFIX ex: <http://example.org/>\n" \
    "PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>\n"


@pytest.fixture(scope="module")
def world(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("handnt")
    nt_dir = tmp / "nt"
    id_dir = tmp / "id"
    nt_dir.mkdir()
    (nt_dir / "uni0.nt").write_text(TRIPLES)
    r = subprocess.run(
        [sys.executable, "-m", "wukong_tpu.loader.datagen",
         str(nt_dir), str(id_dir)],
        capture_output=True,
        env=dict(os.environ,
                 PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                               "")))
    assert r.returncode == 0, r.stderr.decode()

    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.loader.base import load_attr_triples, load_triples
    from wukong_tpu.store.gstore import build_partition
    from wukong_tpu.store.string_server import StringServer

    ss = StringServer(str(id_dir))
    triples = load_triples(str(id_dir))
    attrs = load_attr_triples(str(id_dir))
    g = build_partition(triples, 0, 1, attrs)
    return ss, CPUEngine(g, ss), TPUEngine(g, ss)


def _run(ss, eng, text, order_cols=True):
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.sparql.parser import Parser

    q = Parser(ss).parse(PREFIX + text)
    heuristic_plan(q)
    eng.execute(q)
    assert q.result.status_code == 0
    cols = [q.result.var2col(v) for v in q.result.required_vars
            if not q.result.is_attr_var(v)]
    rows = [tuple(ss.id2str(int(x)) for x in row)
            for row in np.asarray(q.result.table)[:, cols]]
    return sorted(rows), q


def _u(name):
    return f"<{EX}{name}>"


@pytest.mark.parametrize("engine", ["cpu", "tpu"])
def test_students_of_P1_courses(world, engine):
    """?s takes ?c, P1 teaches ?c.
    By hand: P1 teaches C1, C2. takers(C1) = {S1, S2}; takers(C2) = {S3}.
    => (S1,C1), (S2,C1), (S3,C2)."""
    ss, cpu, tpu = world
    rows, _ = _run(ss, cpu if engine == "cpu" else tpu, """
    SELECT ?s ?c WHERE {
        ?s ex:takesCourse ?c .
        ex:P1 ex:teacherOf ?c .
    }""")
    assert rows == sorted([(_u("S1"), _u("C1")), (_u("S2"), _u("C1")),
                           (_u("S3"), _u("C2"))])


@pytest.mark.parametrize("engine", ["cpu", "tpu"])
def test_advisor_teaches_taken_course(world, engine):
    """The LUBM-q2 shape: ?s advisor ?p, ?p teacherOf ?c, ?s takesCourse ?c.
    By hand: S1(adv P1) takes C1 (P1 teaches) -> hit; takes C3 (P2) -> no.
    S2(adv P1) takes C1 -> hit. S3(adv P2) takes C2 (P1 teaches) -> no.
    => (S1,P1,C1), (S2,P1,C1)."""
    ss, cpu, tpu = world
    rows, _ = _run(ss, cpu if engine == "cpu" else tpu, """
    SELECT ?s ?p ?c WHERE {
        ?s ex:advisor ?p .
        ?p ex:teacherOf ?c .
        ?s ex:takesCourse ?c .
    }""")
    assert rows == sorted([(_u("S1"), _u("P1"), _u("C1")),
                           (_u("S2"), _u("P1"), _u("C1"))])


def test_type_index_and_distinct(world):
    """DISTINCT teachers of courses taken by Students.
    By hand: courses taken = {C1 (S1,S2), C2 (S3), C3 (S1)};
    teachers: C1->P1, C2->P1, C3->P2 => DISTINCT {P1, P2}."""
    ss, cpu, _ = world
    rows, _ = _run(ss, cpu, """
    SELECT DISTINCT ?p WHERE {
        ?s rdf:type ex:Student .
        ?s ex:takesCourse ?c .
        ?p ex:teacherOf ?c .
    }""")
    assert rows == sorted([(_u("P1"),), (_u("P2"),)])


def test_optional_left_join(world):
    """Professors with OPTIONAL advisees.
    By hand: P1 advised by S1, S2; P2 by S3 — every prof matched, 3 rows."""
    ss, cpu, _ = world
    rows, _ = _run(ss, cpu, """
    SELECT ?p ?s WHERE {
        ?p rdf:type ex:Professor .
        OPTIONAL { ?s ex:advisor ?p }
    }""")
    assert rows == sorted([(_u("P1"), _u("S1")), (_u("P1"), _u("S2")),
                           (_u("P2"), _u("S3"))])


def test_attr_filter_age(world):
    """Students with age > 21. By hand: S2 (22), S3 (23)."""
    from wukong_tpu.config import Global

    old = Global.enable_vattr
    Global.enable_vattr = True
    try:
        ss, cpu, _ = world
        rows, q = _run(ss, cpu, """
        SELECT ?s ?a WHERE {
            ?s rdf:type ex:Student .
            ?s ex:age ?a .
            FILTER(?a > 21)
        }""")
        got_s = sorted(r[0] for r in rows)
        assert got_s == [_u("S2"), _u("S3")]
        ages = sorted(float(a) for a in
                      np.asarray(q.result.attr_table).ravel())
        assert ages == [22.0, 23.0]
    finally:
        Global.enable_vattr = old
