"""HDFS dataset source (loader/hdfs.py — the hdfs_loader.hpp analogue).

A fake `hdfs` CLI on PATH serves files out of a local directory, so the test
exercises the real subprocess plumbing (ls -C listing, -get staging, warm
cache, gating errors) without a Hadoop install — the fake-cluster philosophy
of tests/conftest.py applied to the storage layer.
"""

import os
import stat

import numpy as np
import pytest

from wukong_tpu.loader import hdfs
from wukong_tpu.utils.errors import WukongError

FAKE_HDFS = r"""#!/bin/sh
# fake `hdfs dfs` CLI: maps hdfs://fake/<path> onto $FAKE_HDFS_ROOT/<path>.
# -ls prints real `hdfs dfs -ls` shaped lines (permission string first, path
# last; directories lead with 'd') so list_dir's file/dir split is exercised.
[ "$1" = "dfs" ] || exit 2
shift
case "$1" in
  -ls)
    dir="${2#hdfs://fake}"
    for f in "$FAKE_HDFS_ROOT$dir"/*; do
      [ -e "$f" ] || continue
      if [ -d "$f" ]; then perm="drwxr-xr-x"; else perm="-rw-r--r--"; fi
      echo "$perm   3 user group  42 2026-01-01 00:00 hdfs://fake$dir/$(basename "$f")"
    done
    ;;
  -get)
    src="${2#hdfs://fake}"
    cp -r "$FAKE_HDFS_ROOT$src" "$3"
    ;;
  *) exit 2 ;;
esac
"""


@pytest.fixture
def fake_hdfs(tmp_path, monkeypatch):
    """Install the fake CLI and a remote root; reset the probe cache."""
    bindir = tmp_path / "bin"
    bindir.mkdir()
    exe = bindir / "hdfs"
    exe.write_text(FAKE_HDFS)
    exe.chmod(exe.stat().st_mode | stat.S_IEXEC)
    root = tmp_path / "remote"
    (root / "data").mkdir(parents=True)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_HDFS_ROOT", str(root))
    monkeypatch.delenv("WUKONG_HDFS_CMD", raising=False)
    old = dict(hdfs._state)
    hdfs._state.update(cmd=None, probed=False)
    yield root / "data"
    hdfs._state.update(old)


def _write_dataset(d, triples):
    np.save(str(d / "id_triples.npy"), np.asarray(triples, dtype=np.int64))
    (d / "str_index").write_text("<p1>\t131073\n")
    (d / "ignored.log").write_text("not a dataset file\n")


def test_gated_when_no_client(monkeypatch, tmp_path):
    monkeypatch.setenv("PATH", str(tmp_path))  # nothing on PATH
    monkeypatch.delenv("WUKONG_HDFS_CMD", raising=False)
    old = dict(hdfs._state)
    hdfs._state.update(cmd=None, probed=False)
    try:
        assert not hdfs.hdfs_available()
        with pytest.raises(WukongError):
            hdfs.list_dir("hdfs://fake/data")
    finally:
        hdfs._state.update(old)


def test_fetch_and_load_roundtrip(fake_hdfs, tmp_path):
    tri = [[200000, 131073, 200001], [200001, 131073, 200002]]
    _write_dataset(fake_hdfs, tri)
    staged = hdfs.fetch_dataset("hdfs://fake/data", str(tmp_path / "stage"))
    assert sorted(os.listdir(staged)) == ["id_triples.npy", "str_index"]

    from wukong_tpu.loader.base import load_triples

    got = load_triples(staged)
    assert got.tolist() == tri

    # warm cache: corrupt the remote file; a re-fetch must NOT re-download
    np.save(str(fake_hdfs / "id_triples.npy"), np.zeros((1, 3), np.int64))
    hdfs.fetch_dataset("hdfs://fake/data", str(tmp_path / "stage"))
    assert load_triples(staged).tolist() == tri


def test_resolve_passthrough_and_scheme(fake_hdfs, tmp_path):
    assert hdfs.resolve_dataset_dir("/local/path") == "/local/path"
    _write_dataset(fake_hdfs, [[200000, 131073, 200001]])
    staged = hdfs.resolve_dataset_dir("hdfs://fake/data")
    assert os.path.exists(os.path.join(staged, "id_triples.npy"))
    # distinct URIs never share a staging dir (hash tag, not lossy munging)
    (fake_hdfs.parent / "data_b").mkdir()
    _write_dataset(fake_hdfs.parent / "data_b", [[200007, 131073, 200008]])
    staged_b = hdfs.resolve_dataset_dir("hdfs://fake/data_b")
    assert staged_b != staged

    from wukong_tpu.loader.base import load_triples

    assert load_triples(staged_b).tolist() == [[200007, 131073, 200008]]


def test_subdirectory_is_skipped(fake_hdfs, tmp_path):
    """A directory whose name matches the wanted prefixes (e.g. `preshard/`)
    must not be fetched: `-get` copies directories recursively, leaving a
    subdir the flat POSIX staging pipeline chokes on (advisor r2 #3)."""
    _write_dataset(fake_hdfs, [[200000, 131073, 200001]])
    sub = fake_hdfs / "preshard"
    sub.mkdir()
    (sub / "junk").write_text("nested\n")
    staged = hdfs.fetch_dataset("hdfs://fake/data", str(tmp_path / "stage"))
    assert sorted(os.listdir(staged)) == ["id_triples.npy", "str_index"]


def test_empty_remote_dir_raises(fake_hdfs):
    (fake_hdfs / "readme.log").write_text("nothing useful\n")
    with pytest.raises(WukongError):
        hdfs.fetch_dataset("hdfs://fake/data")


def test_console_accepts_hdfs_uri(fake_hdfs, tmp_path):
    """End-to-end: console one-shot over an hdfs:// dataset URI."""
    from wukong_tpu.loader.lubm import write_dataset
    from wukong_tpu.runtime.console import main as console_main

    local = tmp_path / "lubm1"
    write_dataset(str(local), 1, seed=0)
    for name in os.listdir(local):
        (fake_hdfs / name).write_bytes((local / name).read_bytes())

    cfg = tmp_path / "config"
    cfg.write_text("global_enable_tpu 0\n")
    from wukong_tpu.config import Global

    # console_main loads the config into the process-wide Global
    # singleton — restore the knob it flips, or every later test module
    # in a one-shot run sees enable_tpu off (the heavy-lane batcher
    # admission was the first to notice)
    prev = Global.enable_tpu
    try:
        assert console_main([str(cfg), "hdfs://fake/data",
                             "-c", "store-stat"]) == 0
    finally:
        Global.enable_tpu = prev
