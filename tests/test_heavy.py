"""Heavy-lane serving (runtime/batcher.py HeavyGroup + scheduler heavy lane).

Pins the PR's contract: fused index-origin dispatches settle every waiter
with counts byte-identical to sequential execution (and to the independent
BGP oracle), the split path's slice-range parts sum exactly through the
gather barrier, a member's deadline/budget degrades only that member, a
failed or killed slice falls back per-slice without stranding a waiter,
the scheduler's weighted heavy lane never occupies every engine, the slice
count is plan-cache-backed (no more per-query-object ``_heavy_b``), and
plan-time lane routing keeps wide const-start templates out of light fused
groups.
"""

import threading
import time

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.engine.tpu import TPUEngine
from wukong_tpu.loader.lubm import UB, VirtualLubmStrings, generate_lubm
from wukong_tpu.planner.optimizer import Planner
from wukong_tpu.planner.stats import Stats
from wukong_tpu.runtime.batcher import (
    HeavyGroup,
    _HeavySlice,
    _Pending,
    batchable,
    heavy_batchable,
    heavy_key,
)
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.runtime.resilience import Deadline
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.utils.errors import ErrorCode, WukongError

pytestmark = pytest.mark.batch

RDF_TYPE = "<http://www.w3.org/1999/02/22-rdf-syntax-ns#type>"


@pytest.fixture(autouse=True, scope="module")
def _lockdep_checked():
    """The heavy-lane suite runs fully checked: the gather barrier's slice
    locks, the scheduler's heavy-lane lock, and the batcher condition all
    feed the lockdep acquisition-order graph on every test."""
    from wukong_tpu.analysis import lockdep

    lockdep.install(True)
    yield
    try:
        assert lockdep.cycles() == [], lockdep.cycles()
        assert lockdep.leaf_violations() == [], lockdep.leaf_violations()
    finally:
        lockdep.install(False)


@pytest.fixture(scope="module")
def world():
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    stats = Stats.generate(triples)
    proxy = Proxy(g, ss, CPUEngine(g, ss), TPUEngine(g, ss, stats=stats),
                  planner=Planner(stats))
    return {"g": g, "ss": ss, "proxy": proxy, "triples": triples,
            "stats": stats}


@pytest.fixture(autouse=True)
def _knobs_reset(monkeypatch):
    """Every test starts and ends at the defaults (enable_tpu pinned on:
    heavy admission needs the device engine, and an earlier module's
    console run may have loaded a config that turned it off)."""
    monkeypatch.setattr(Global, "enable_batching", False)
    monkeypatch.setattr(Global, "enable_tpu", True)
    monkeypatch.setattr(Global, "heavy_lane", True)
    monkeypatch.setattr(Global, "heavy_split_threshold", 100000)
    monkeypatch.setattr(Global, "heavy_split_max", 4)
    yield


def _heavy_text(world, cls="GraduateStudent"):
    return (f"SELECT ?x ?y WHERE {{ ?x {RDF_TYPE} <{UB}{cls}> . "
            f"?x <{UB}takesCourse> ?y . }}")


def _light_text(world):
    """A const-start 1-hop (the light serving template shape)."""
    from wukong_tpu.types import OUT

    ss, g = world["ss"], world["g"]
    pid = ss.str2id(f"<{UB}memberOf>")
    dept = int(np.asarray(g.get_index(pid, OUT))[0])
    return f"SELECT ?s WHERE {{ ?s <{UB}memberOf> {ss.id2str(dept)} . }}"


def _planned(proxy, text, blind=True, deadline=None):
    q = proxy._parse_text(text)
    proxy._plan_prepared(q, blind, None)
    q.deadline = deadline
    return q


def _counter(name, **labels):
    from wukong_tpu.obs import get_registry

    m = get_registry()._metrics.get(name)
    if m is None:
        return 0.0
    return m.value(**labels) if labels else m.value()


# ---------------------------------------------------------------------------
# recognition + routing
# ---------------------------------------------------------------------------

def test_heavy_batchable_recognition(world):
    proxy = world["proxy"]
    q = _planned(proxy, _heavy_text(world))
    assert q.start_from_index()
    assert heavy_batchable(q)
    assert not batchable(q)
    # non-blind: the sliced dispatch returns counts, not tables
    assert not heavy_batchable(_planned(proxy, _heavy_text(world),
                                        blind=False))
    # const-start light template is not heavy-batchable
    light = _planned(proxy, f"SELECT ?s WHERE {{ ?s {RDF_TYPE} "
                            f"<{UB}FullProfessor> . }}")
    assert heavy_batchable(light)  # 1-hop index scan still qualifies
    # filters need the materialized table
    filt = _planned(proxy, f"SELECT ?x ?y WHERE {{ ?x {RDF_TYPE} "
                           f"<{UB}GraduateStudent> . ?x <{UB}takesCourse> "
                           f"?y . FILTER (?x != ?y) }}")
    assert not heavy_batchable(filt)


def test_heavy_key_groups_identical_templates_only(world):
    proxy = world["proxy"]
    a1 = _planned(proxy, _heavy_text(world, "GraduateStudent"))
    a2 = _planned(proxy, _heavy_text(world, "GraduateStudent"))
    b = _planned(proxy, _heavy_text(world, "UndergraduateStudent"))
    assert heavy_key(a1) == heavy_key(a2)
    assert heavy_key(a1) != heavy_key(b)


def test_classify_lane_routes_index_origin_heavy(world):
    proxy = world["proxy"]
    hq = _planned(proxy, _heavy_text(world))
    assert hq.lane == "heavy"
    lq = _planned(proxy, _light_text(world))
    assert lq.lane == "light"


def test_heavy_routed_const_template_bypasses_light_coalescer(
        world, monkeypatch):
    """A const-start template the optimizer estimates past
    heavy_rows_threshold is tagged heavy and must not join a light fused
    group (heavy_route bypass)."""
    proxy = world["proxy"]
    monkeypatch.setattr(Global, "enable_batching", True)
    monkeypatch.setattr(Global, "heavy_rows_threshold", 1)
    proxy._plan_cache.clear()  # lane memos were recorded at the default
    q = _planned(proxy, _light_text(world))
    assert q.lane == "heavy" and batchable(q)
    before = _counter("wukong_batch_bypass_total", reason="heavy_route")
    assert proxy.batcher().offer(q) is None
    assert _counter("wukong_batch_bypass_total",
                    reason="heavy_route") == before + 1
    proxy._plan_cache.clear()  # drop the threshold=1 lane memos


# ---------------------------------------------------------------------------
# fused heavy dispatch: byte-identical counts
# ---------------------------------------------------------------------------

def test_fused_heavy_counts_match_sequential_and_oracle(world, monkeypatch):
    from tests.bgp_oracle import TripleIndex, eval_bgp

    proxy, ss = world["proxy"], world["ss"]
    text = _heavy_text(world)
    seq = proxy.serve_query(text, blind=True)
    assert seq.result.status_code == ErrorCode.SUCCESS
    want = seq.result.nrows
    assert want > 0
    # the independent oracle agrees with sequential execution
    idx = TripleIndex(world["triples"])
    type_pid = ss.str2id(RDF_TYPE)
    grad = ss.str2id(f"<{UB}GraduateStudent>")
    takes = ss.str2id(f"<{UB}takesCourse>")
    oracle = eval_bgp(idx, [(-1, type_pid, grad), (-1, takes, -2)], [-1, -2])
    assert len(oracle) == want

    monkeypatch.setattr(Global, "enable_batching", True)
    monkeypatch.setattr(Global, "batch_window_us", 100_000)
    before = _counter("wukong_batch_heavy_fused_total")
    out = [None] * 5
    def go(i):
        out[i] = proxy.serve_query(text, blind=True)
    ths = [threading.Thread(target=go, args=(i,)) for i in range(len(out))]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    for i, q in enumerate(out):
        assert q.result.status_code == ErrorCode.SUCCESS, i
        assert q.result.nrows == want, i
    # at least one fused dispatch served multiple members
    assert _counter("wukong_batch_heavy_fused_total") > before


def test_mt_sliced_parts_sum_to_full_total(world):
    """The split path's primitive: mt_factor carrier copies of an
    index-origin batch partition the index list exactly."""
    import copy

    proxy = world["proxy"]
    q = _planned(proxy, _heavy_text(world))
    full = int(np.asarray(
        proxy.tpu.execute_batch_index(q, 8, slice_mode=True)).sum())
    parts = []
    for k in range(3):
        qk = copy.deepcopy(q)
        qk.mt_factor, qk.mt_tid = 3, k
        parts.append(int(np.asarray(
            proxy.tpu.execute_batch_index(qk, 8, slice_mode=True)).sum()))
    assert sum(parts) == full
    assert all(p > 0 for p in parts)


# ---------------------------------------------------------------------------
# member deadline/budget isolation inside a heavy group
# ---------------------------------------------------------------------------

def test_heavy_member_deadline_degrades_only_that_member(world):
    proxy = world["proxy"]
    text = _heavy_text(world)
    bt = proxy.batcher()
    t_frozen = [0.0]
    expired = Deadline(timeout_ms=1, clock=lambda: t_frozen[0])
    t_frozen[0] = 10.0  # expired before the flush
    members = [
        _Pending(_planned(proxy, text)),
        _Pending(_planned(proxy, text, deadline=expired)),
        _Pending(_planned(proxy, text)),
    ]
    HeavyGroup(members, bt, engine=None).run(None)
    ok0, bad, ok2 = (m.q.result for m in members)
    assert ok0.status_code == ErrorCode.SUCCESS and ok0.nrows > 0
    assert ok2.status_code == ErrorCode.SUCCESS and ok2.nrows == ok0.nrows
    assert bad.status_code == ErrorCode.QUERY_TIMEOUT
    assert not bad.complete


def test_heavy_member_budget_charged_per_member(world):
    proxy = world["proxy"]
    text = _heavy_text(world)
    bt = proxy.batcher()
    members = [
        _Pending(_planned(proxy, text)),
        _Pending(_planned(proxy, text, deadline=Deadline(budget_rows=1))),
    ]
    HeavyGroup(members, bt, engine=None).run(None)
    ok, bad = (m.q.result for m in members)
    assert ok.status_code == ErrorCode.SUCCESS and ok.nrows > 0
    assert bad.status_code == ErrorCode.BUDGET_EXCEEDED
    assert not bad.complete


# ---------------------------------------------------------------------------
# split groups: gather barrier + chaos
# ---------------------------------------------------------------------------

def test_split_group_gather_barrier_counts_identical(world, monkeypatch):
    proxy = world["proxy"]
    text = _heavy_text(world)
    want = proxy.serve_query(text, blind=True).result.nrows
    pool = proxy.engine_pool()
    monkeypatch.setattr(Global, "enable_batching", True)
    monkeypatch.setattr(Global, "batch_window_us", 100_000)
    monkeypatch.setattr(Global, "heavy_split_threshold", 1)
    monkeypatch.setattr(Global, "heavy_split_max", 2)
    before = _counter("wukong_batch_heavy_dispatch_total", mode="split")
    out = [None] * 4
    def go(i):
        out[i] = proxy.serve_query(text, blind=True)
    ths = [threading.Thread(target=go, args=(i,)) for i in range(len(out))]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    for i, q in enumerate(out):
        assert q.result.status_code == ErrorCode.SUCCESS, i
        assert q.result.nrows == want, i
    assert _counter("wukong_batch_heavy_dispatch_total",
                    mode="split") > before
    # a SINGLE huge heavy query also takes the split path (solo fuse)
    solo = proxy.serve_query(text, blind=True)
    assert solo.result.status_code == ErrorCode.SUCCESS
    assert solo.result.nrows == want


@pytest.mark.chaos
def test_injected_heavy_dispatch_fault_retries_per_slice(world, monkeypatch):
    """A transient fault at the batch.heavy.dispatch site fails ONE slice;
    the gather barrier re-runs it inline — every waiter settles with the
    correct count (fallback per-slice, not per-group)."""
    from wukong_tpu.runtime import faults
    from wukong_tpu.runtime.faults import FaultPlan, FaultSpec

    proxy = world["proxy"]
    text = _heavy_text(world)
    want = proxy.serve_query(text, blind=True).result.nrows
    proxy.engine_pool()  # split needs live engines
    monkeypatch.setattr(Global, "enable_batching", True)
    monkeypatch.setattr(Global, "batch_window_us", 100_000)
    monkeypatch.setattr(Global, "heavy_split_threshold", 1)
    monkeypatch.setattr(Global, "heavy_split_max", 2)
    before = _counter("wukong_batch_heavy_fallback_total",
                      reason="slice_retry")
    prev = faults.active()
    faults.install(FaultPlan([FaultSpec("batch.heavy.dispatch",
                                        "transient", count=1)]))
    try:
        out = [None] * 3
        def go(i):
            out[i] = proxy.serve_query(text, blind=True)
        ths = [threading.Thread(target=go, args=(i,)) for i in range(3)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
    finally:
        faults.install(prev)
    for i, q in enumerate(out):
        assert q.result.status_code == ErrorCode.SUCCESS, i
        assert q.result.nrows == want, i
    assert _counter("wukong_batch_heavy_fallback_total",
                    reason="slice_retry") == before + 1


@pytest.mark.chaos
def test_engine_death_mid_split_dispatch_no_stranded_waiters(
        world, monkeypatch):
    """One engine of a split group dies mid-dispatch (a thread-killing
    exception inside the slice run): the scheduler's death handler fails
    the in-flight slice, the gather barrier re-runs it inline, every
    waiter settles, and the pool respawns the engine."""
    proxy = world["proxy"]
    text = _heavy_text(world)
    want = proxy.serve_query(text, blind=True).result.nrows
    pool = proxy.engine_pool()
    monkeypatch.setattr(Global, "enable_batching", True)
    monkeypatch.setattr(Global, "batch_window_us", 100_000)
    monkeypatch.setattr(Global, "heavy_split_threshold", 1)
    monkeypatch.setattr(Global, "heavy_split_max", 2)

    killed = []
    orig_run = _HeavySlice.run

    def dying_run(self, engine=None):
        # the first pool-dispatched slice (mt_tid > 0) kills its engine
        # thread — SystemExit is not an Exception, so it escapes the
        # engine loop's per-item guard and reaches the death handler
        if self.fq.mt_tid > 0 and not killed:
            if self.claim():
                killed.append(True)
                raise SystemExit("engine killed mid-dispatch")
        return orig_run(self, engine)

    monkeypatch.setattr(_HeavySlice, "run", dying_run)
    respawns_before = _counter("wukong_pool_engine_respawns_total")
    out = [None] * 3
    def go(i):
        out[i] = proxy.serve_query(text, blind=True)
    ths = [threading.Thread(target=go, args=(i,)) for i in range(3)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(timeout=60)
    assert killed  # the scenario actually fired
    for i, q in enumerate(out):
        assert q is not None, f"stranded waiter {i}"
        assert q.result.status_code == ErrorCode.SUCCESS, i
        assert q.result.nrows == want, i
    # the dying slice crashed its engine thread; the pool respawned it
    assert _counter("wukong_pool_engine_respawns_total") > respawns_before
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if all(h["alive"] for h in pool.health().values()):
            break
        time.sleep(0.05)
    assert all(h["alive"] for h in pool.health().values())


# ---------------------------------------------------------------------------
# scheduler: weighted heavy lane
# ---------------------------------------------------------------------------

class _Probe:
    """A fire-and-forget heavy-lane item recording run concurrency."""

    lane = "heavy"

    def __init__(self, state, hold_s=0.15):
        self.state = state
        self.hold_s = hold_s
        self.done = threading.Event()

    def run(self, engine=None):
        with self.state["lock"]:
            self.state["cur"] += 1
            self.state["max"] = max(self.state["max"], self.state["cur"])
        time.sleep(self.hold_s)
        with self.state["lock"]:
            self.state["cur"] -= 1
        self.done.set()

    def fail_all(self, exc):
        self.done.set()


def test_heavy_lane_weighted_cap_and_no_light_starvation(world, monkeypatch):
    from wukong_tpu.runtime.scheduler import EnginePool

    monkeypatch.setattr(Global, "heavy_lane_pct", 50)
    pool = EnginePool(num_engines=2,
                      make_engine=lambda tid: CPUEngine(world["g"],
                                                        world["ss"]))
    pool.start()
    try:
        assert pool._heavy_cap() == 1  # 2 engines x 50% = 1
        state = {"cur": 0, "max": 0, "lock": threading.Lock()}
        probes = [_Probe(state) for _ in range(4)]
        for p in probes:
            pool.submit(p, lane="heavy")
        # with a heavy backlog occupying its one allowed engine, a light
        # interactive query still gets served promptly by the other
        q = _planned(world["proxy"], _light_text(world))
        t0 = time.monotonic()
        qid = pool.submit(q)
        pool.wait(qid, timeout=10)
        light_latency = time.monotonic() - t0
        for p in probes:
            assert p.done.wait(timeout=20)
        assert state["max"] <= 1  # the weighted cap held
        assert light_latency < 2 * sum(p.hold_s for p in probes)
    finally:
        pool.stop()


# ---------------------------------------------------------------------------
# plan-cache-backed slice sizing (the retired q._heavy_b hack)
# ---------------------------------------------------------------------------

def test_heavy_index_batch_memoized_in_plan_cache(world, monkeypatch):
    proxy = world["proxy"]
    q = _planned(proxy, _heavy_text(world))
    calls = []
    orig = type(proxy.tpu).suggest_index_batch

    def spy(self, qq, cap=1024):
        calls.append(cap)
        return orig(self, qq, cap=cap)

    monkeypatch.setattr(type(proxy.tpu), "suggest_index_batch", spy)
    proxy._plan_cache.clear()
    b1 = proxy.heavy_index_batch(q)
    b2 = proxy.heavy_index_batch(q)
    assert b1 == b2
    assert 1 <= b1 <= Global.heavy_batch_max
    assert len(calls) == 1  # second lookup hit the plan cache
    # the planned query object carries no mutable sizing state anymore
    assert not hasattr(q, "_heavy_b")


def test_emulator_heavy_route_decision_replaces_sentinel(world, monkeypatch):
    """A device failure records an explicit per-class route decision
    ("pool"), not a -1 sentinel on the shared query object."""
    from wukong_tpu.runtime.emulator import Emulator
    from wukong_tpu.runtime.monitor import Monitor

    proxy = world["proxy"]
    emu = Emulator(proxy)
    q0 = _planned(proxy, _heavy_text(world))
    emu._p_cap = 1
    emu._mixed_fail = {}
    emu._heavy_route = {}
    emu._planned = [("heavy", None, q0)]
    emu._probs = np.asarray([1.0])
    emu._served = 0
    emu.class_mode = {}
    rng = np.random.default_rng(0)

    monkeypatch.setattr(
        type(proxy.tpu), "execute_batch_index",
        lambda self, q, B, slice_mode=False: (_ for _ in ()).throw(
            WukongError(ErrorCode.UNKNOWN_PATTERN, "device refused")))
    assert emu._device_batch("heavy", None, q0, rng, 8, cls=0) is False
    assert emu._heavy_route[0] == "pool"
    assert not hasattr(q0, "_heavy_b")
    # routed to the pool, the device path is never tried again
    assert emu._device_batch("heavy", None, q0, rng, 8, cls=0) is False


# ---------------------------------------------------------------------------
# observability: /top lanes + Monitor rolling line
# ---------------------------------------------------------------------------

def test_top_lane_view_and_monitor_line(world, monkeypatch):
    from wukong_tpu.obs.profile import render_top

    proxy = world["proxy"]
    proxy.engine_pool()  # the per-lane depth gauge needs a live pool
    monkeypatch.setattr(Global, "enable_batching", True)
    monkeypatch.setattr(Global, "batch_window_us", 50_000)
    out = [None] * 3
    text = _heavy_text(world)
    def go(i):
        out[i] = proxy.serve_query(text, blind=True)
    ths = [threading.Thread(target=go, args=(i,)) for i in range(3)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert all(q.result.status_code == ErrorCode.SUCCESS for q in out)
    txt, js = render_top(k=4)
    assert "depth[heavy]" in js["lanes"]
    assert "LANES" in txt
    lines = proxy.monitor.lane_lines()
    assert lines and "HeavyLane" in lines[0]


def test_heavy_lane_off_bypasses(world, monkeypatch):
    """heavy_lane off: index-origin queries bypass the batcher (the PR 4
    posture) and still execute correctly."""
    proxy = world["proxy"]
    monkeypatch.setattr(Global, "enable_batching", True)
    monkeypatch.setattr(Global, "heavy_lane", False)
    q = _planned(proxy, _heavy_text(world))
    before = _counter("wukong_batch_bypass_total", reason="shape")
    assert proxy.batcher().offer(q) is None
    assert _counter("wukong_batch_bypass_total", reason="shape") == before + 1
    out = proxy.serve_query(_heavy_text(world), blind=True)
    assert out.result.status_code == ErrorCode.SUCCESS
    assert out.result.nrows > 0
