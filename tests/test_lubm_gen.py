import numpy as np
import pytest

from wukong_tpu.loader import lubm
from wukong_tpu.loader.lubm import (
    P,
    T,
    VirtualLubmStrings,
    generate_lubm,
    lubm_counts,
    lubm_layout,
    write_dataset,
)
from wukong_tpu.types import NORMAL_ID_START, TYPE_ID


@pytest.fixture(scope="module")
def lubm1():
    return generate_lubm(1, seed=42)


def test_determinism():
    t1, _ = generate_lubm(1, seed=7)
    t2, _ = generate_lubm(1, seed=7)
    assert np.array_equal(t1, t2)
    t3, _ = generate_lubm(1, seed=8)
    assert not np.array_equal(t1, t3)


def test_id_spaces(lubm1):
    triples, lay = lubm1
    s, p, o = triples[:, 0], triples[:, 1], triples[:, 2]
    assert (s >= NORMAL_ID_START).all()  # subjects are normal vertices
    assert (p < NORMAL_ID_START).all() and (p >= 1).all()  # predicates are index ids
    # objects: type triples -> index ids, others -> normal ids
    is_type = p == TYPE_ID
    assert (o[is_type] < NORMAL_ID_START).all()
    assert (o[~is_type] >= NORMAL_ID_START).all()
    assert (s < lay.id_end).all() and (o < lay.id_end).all()


def test_cardinalities(lubm1):
    triples, lay = lubm1
    c = lay.counts
    p, o = triples[:, 1], triples[:, 2]
    is_type = p == TYPE_ID
    type_counts = {t: int((o[is_type] == t).sum()) for t in set(T.values())}
    assert type_counts[T["University"]] == 1
    assert type_counts[T["Department"]] == c.D
    assert 15 <= c.D <= 25
    assert type_counts[T["FullProfessor"]] == int(c.n_fp.sum())
    assert type_counts[T["UndergraduateStudent"]] == int(c.n_ug.sum())
    assert type_counts[T["Course"]] == int(c.n_course.sum())
    # every faculty worksFor exactly one department
    n_fac = int(c.n_fac.sum())
    assert int((p == P["worksFor"]).sum()) == n_fac
    # UG takesCourse between 2 and 4 (duplicates may reduce but >= 1)
    tc = triples[p == P["takesCourse"]]
    ug_tc = tc[tc[:, 0] < lay.gs_base.min()]
    per_student = np.bincount(ug_tc[:, 0] - ug_tc[:, 0].min())
    per_student = per_student[per_student > 0]
    assert per_student.min() >= 1 and per_student.max() <= 4


def test_virtual_strings_roundtrip(lubm1):
    triples, lay = lubm1
    vs = VirtualLubmStrings(1, seed=42)
    rng = np.random.default_rng(0)
    ids = np.unique(np.concatenate([triples[:, 0], triples[:, 2]]))
    sample = rng.choice(ids, size=200, replace=False)
    for vid in sample:
        s = vs.id2str(int(vid))
        assert vs.str2id(s) == int(vid), (vid, s)
    # well-known query constants resolve
    assert vs.str2id("<http://www.University0.edu>") == lay.univ_base
    assert vs.str2id("<http://www.Department0.University0.edu>") == int(lay.dept_id[0])
    d0fp0 = vs.str2id("<http://www.Department0.University0.edu/FullProfessor0>")
    assert d0fp0 == int(lay.fac_base[0])
    with pytest.raises(KeyError):
        vs.str2id("<http://www.University999.edu>")
    with pytest.raises(KeyError):
        vs.str2id("<http://nonsense>")


def test_write_dataset_roundtrip(tmp_path):
    meta = write_dataset(str(tmp_path), 1, seed=3, fmt="npy")
    tri = np.load(tmp_path / "id_triples.npy")
    assert len(tri) == meta["num_triples"]
    assert (tmp_path / "str_index").exists()
    assert (tmp_path / "str_normal_virtual").exists()
    # text format matches npy content
    write_dataset(str(tmp_path / "txt"), 1, seed=3, fmt="text")
    rows = []
    for f in sorted((tmp_path / "txt").glob("id_uni*.nt")):
        for line in f.read_text().splitlines():
            rows.append(tuple(int(x) for x in line.split("\t")))
    assert sorted(rows) == sorted(map(tuple, tri.tolist()))


def test_index_strings_table():
    rows = lubm.index_strings()
    assert rows[0] == ("__PREDICATE__", 0)
    assert rows[1][1] == 1
    ids = [i for _, i in rows]
    assert ids == list(range(len(ids)))  # dense, in order
