"""Analytic LUBM segment headers + the LUBM-10240 HBM budget (round-4
verdict #3: the north-star scale must at least be PLANNED — capacity
classes and staged-segment footprints derived from exact synthesized
headers, asserted to fit v5e-8 HBM — even though its ~68 GB store cannot
be built on this machine's disk).

Two layers:
1. `lubm_headers` validity: at a scale small enough to build for real,
   every header is an upper bound on the built store's segment (keys,
   edges, max degree), covers every segment the store builds, and stays
   tight (<= 1.5x on edges) — so the 10240 numbers are trustworthy.
2. LUBM-10240 budget walk, mirroring tests/test_at_scale_2560.py's math
   (HBM_BUDGET.md): per-chain staged pins + chain state + sort workspace,
   single-chip and 8-way-sharded, against v5e's 16 GiB/chip.
"""

import numpy as np
import pytest

from wukong_tpu.loader.lubm import generate_lubm, lubm_headers
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.types import IN, NORMAL_ID_START, OUT

BASIC = "/root/reference/scripts/sparql_query/lubm/basic"
HBM_BYTES = 16 * 2**30  # v5e: 16 GiB HBM per chip
MESH_D = 8  # v5e-8


def _pow2(n: int) -> int:
    p = 1
    while p < max(n, 1):
        p *= 2
    return p


def _staged_bytes(nk: int, ne: int) -> int:
    """Staged merge form (device_store._stage_merge): edges+ekey int32
    pow2-padded (8 B/edge) + skey/sstart/sdeg int32 pow2-padded (12 B/key)."""
    return 12 * _pow2(nk) + 8 * _pow2(ne)


@pytest.mark.parametrize("scale", [1, 8])
def test_headers_upper_bound_real_store(scale):
    h = lubm_headers(scale)
    triples, _lay = generate_lubm(scale, seed=0)
    g = build_partition(triples, 0, 1)
    for (pid, d), (nk, ne, md) in h["segs"].items():
        seg = g.segments.get((pid, d))
        if seg is None:
            continue  # header may bound a segment the data didn't produce
        real_k, real_e = len(seg.keys), len(seg.edges)
        real_md = int(np.max(np.diff(seg.offsets))) if real_k else 0
        assert nk >= real_k, (pid, d, nk, real_k)
        assert ne >= real_e, (pid, d, ne, real_e)
        assert md >= real_md, (pid, d, md, real_md)
        assert ne <= max(real_e, 1) * 1.5 + 64, \
            (pid, d, "header too loose", ne, real_e)
    # full coverage: every built segment has a header
    missing = [k for k in g.segments if k not in h["segs"]]
    assert not missing, missing
    # type index counts exact
    for t, n in h["type_index"].items():
        real = len(g.get_index(t, IN))
        assert real <= n <= real * 1.001 + 2, (t, n, real)


@pytest.fixture(scope="module")
def headers_10240():
    return lubm_headers(10240)


def test_10240_magnitudes(headers_10240):
    """Sanity-pin the scale: ~4x LUBM-2560 (582 M stored edges there)."""
    tot = headers_10240["totals"]
    assert 1.1e9 < tot["triples"] < 1.7e9
    assert 1.8e8 < tot["entities"] < 2.6e8


def _plans_10240():
    """L1-L7 plans for the budget walk. heuristic_plan needs no stats file;
    plan SHAPES are scale-invariant in LUBM (all cardinality ratios are
    constants of the generator), so the chains sized here are the chains
    the bench would run."""
    from wukong_tpu.loader.lubm import VirtualLubmStrings
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.sparql.parser import Parser

    ss = VirtualLubmStrings(10240, seed=0)
    out = []
    for k in range(1, 8):
        q = Parser(ss).parse(open(f"{BASIC}/lubm_q{k}").read())
        heuristic_plan(q)
        if any(p.predicate < 0 for p in q.pattern_group.patterns):
            continue  # host-path shape: no device chain to budget
        out.append((f"lubm_q{k}", q))
    return out


def test_10240_planned_chains_fit_v5e8(headers_10240):
    """Every bench chain's pins + state + workspace fit ONE v5e chip when
    the store is sharded 8 ways (the reference's own 10240 numbers are
    from a multi-node cluster: S5C24(MEEPO)-LUBM10240-20181212.md) —
    the v5e-8 deployment plan is feasible."""
    from wukong_tpu.config import Global
    from wukong_tpu.engine.tpu_merge import MergeExecutor

    segs = {k: (nk, ne) for k, (nk, ne, _md) in headers_10240["segs"].items()}
    cap_max = Global.table_capacity_max
    level_bytes = 2 * 4 * cap_max
    report = {}
    for qn, q in _plans_10240():
        pats = q.pattern_group.patterns
        index_mode = pats[0].subject < NORMAL_ID_START
        folds = MergeExecutor._plan_folds(pats, index_mode=index_mode)
        pins = MergeExecutor._chain_pins(pats, folds, index_mode=index_mode)
        pin_bytes = 0
        for key in pins:
            if key[0] in ("mrg", "mrgf"):
                nk, ne = segs.get((key[1], key[2]), (0, 0))
                pin_bytes += _staged_bytes(nk, ne)
            elif key[0] == "rev":
                nk, _ = segs.get((key[1], key[2]), (0, 0))
                pin_bytes += 4 * _pow2(nk)
        expands = sum(1 for (_s, _p, kind, _f) in MergeExecutor.classify(
            pats, folds, index_mode) if kind == "expand")
        state = (expands + 1) * level_bytes
        workspace = 3 * level_bytes
        # 8-way sharding: segment arrays split ~1/D per chip (hash
        # placement; 1.3x slack covers skew + pow2 re-padding), chain
        # state + workspace are per-shard already (per-shard capacity
        # classes cap at table_capacity_max)
        shard_pins = int(pin_bytes / MESH_D * 1.3)
        need = shard_pins + state + workspace
        report[qn] = (pin_bytes, need)
        assert need <= HBM_BYTES, (
            f"{qn}@10240 on v5e-8: shard pins {shard_pins / 2**30:.2f} GiB"
            f" + state {state / 2**30:.2f} + workspace "
            f"{workspace / 2**30:.2f} GiB > 16 GiB")
    # single-chip feasibility is informational: the lights must fit a
    # single chip outright (their pins are the small segments)
    for qn in ("lubm_q4", "lubm_q5", "lubm_q6"):
        if qn in report:
            pin_bytes, _ = report[qn]
            assert pin_bytes + 4 * level_bytes <= HBM_BYTES, \
                f"{qn}@10240 single-chip: {pin_bytes / 2**30:.2f} GiB pins"


def test_10240_staged_all_needs_sharding(headers_10240):
    """Staged-ALL at 10240 exceeds one chip (documents WHY the deployment
    is v5e-8) but fits the 8-chip mesh with margin."""
    total = sum(_staged_bytes(nk, ne)
                for nk, ne, _md in headers_10240["segs"].values())
    assert total > HBM_BYTES  # one chip cannot hold the whole store
    assert total / MESH_D * 1.3 < HBM_BYTES  # v5e-8 holds it sharded
