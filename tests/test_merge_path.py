"""Sort-merge batch executor (engine/tpu_merge.py) vs the v1 probe path and
the CPU oracle.

The merge path answers the same batched queries with gather-free kernels
(tpu_kernels.py merge_*); these tests pin exact per-instance counts across
all three executors on LUBM-1, plus the edge cases that differ structurally
from v1: deferred filter masks, capacity memoization, estimate-driven
compaction, and missing segments.
"""

import glob
import os

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.engine.tpu import TPUEngine
from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.sparql.parser import Parser
from wukong_tpu.store.gstore import build_partition

BASIC = "/root/reference/scripts/sparql_query/lubm/basic"
# the benchmark set; q8+ (versatile / attr shapes) are host-path queries
QUERIES = [f"{BASIC}/lubm_q{k}" for k in range(1, 8)]


@pytest.fixture(scope="module")
def world():
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    return g, ss


@pytest.fixture(scope="module")
def engines(world):
    g, ss = world
    return CPUEngine(g, ss), TPUEngine(g, ss)


def _parse(ss, qfile):
    q = Parser(ss).parse(open(qfile).read())
    heuristic_plan(q)
    q.result.blind = True
    return q


@pytest.fixture
def merge_flag():
    old = Global.enable_merge_join
    yield
    Global.enable_merge_join = old


@pytest.mark.parametrize("qfile", QUERIES,
                         ids=[os.path.basename(f) for f in QUERIES])
def test_merge_matches_v1_and_oracle(engines, world, qfile, merge_flag):
    cpu, tpu = engines
    g, ss = world
    oracle = _parse(ss, qfile)
    oracle.result.blind = False
    cpu.execute(oracle)
    assert oracle.result.status_code == 0
    want = oracle.result.nrows

    q = _parse(ss, qfile)
    index_start = q.start_from_index()
    B = 3
    per_mode = {}
    for flag in (True, False):
        Global.enable_merge_join = flag
        qx = _parse(ss, qfile)
        if index_start:
            counts = tpu.execute_batch_index(qx, B)
        else:
            const = qx.pattern_group.patterns[0].subject
            counts = tpu.execute_batch(
                qx, np.full(B, const, dtype=np.int64))
        per_mode[flag] = counts.tolist()
    assert per_mode[True] == per_mode[False] == [want] * B

    if index_start:  # slice mode partitions the same total
        Global.enable_merge_join = True
        qs = _parse(ss, qfile)
        counts = tpu.execute_batch_index(qs, B, slice_mode=True)
        assert int(counts.sum()) == want


def test_capacity_memo_learns_and_reuses(engines, world):
    """Second run of the same (query, B) starts from learned exact caps —
    no overflow retry, same counts."""
    _, tpu = engines
    _, ss = world
    q = _parse(ss, f"{BASIC}/lubm_q7")
    c1 = tpu.execute_batch_index(q, 2)
    key = tpu.merge._key(q.pattern_group.patterns, 2, "rep")
    assert key in tpu.merge._cap_memo
    memo = dict(tpu.merge._cap_memo[key])
    q2 = _parse(ss, f"{BASIC}/lubm_q7")
    c2 = tpu.execute_batch_index(q2, 2)
    assert c1.tolist() == c2.tolist()
    assert tpu.merge._cap_memo[key] == memo


def test_merge_missing_segment_yields_zero(engines, world):
    """An expansion over a predicate with no segment produces 0 rows per
    instance (not an error)."""
    from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
    from wukong_tpu.types import IN, OUT, TYPE_ID

    _, tpu = engines
    g, _ = world
    # University members exist; predicate id 999 has no segment
    q = SPARQLQuery()
    q.pattern_group.patterns = [Pattern(17, TYPE_ID, IN, -1),
                                Pattern(-1, 999, OUT, -2)]
    q.result.nvars = 2
    q.result.required_vars = [-1, -2]
    q.result.blind = True
    counts = tpu.execute_batch_index(q, 2)
    assert counts.tolist() == [0, 0]


def test_run_batch_const_many_pipelines(engines, world):
    """K in-flight const batches, one sync: counts match the sequential
    path, including when a batch in the window overflows (slow-path redo)."""
    _, tpu = engines
    g, ss = world
    q = _parse(ss, f"{BASIC}/lubm_q4")
    const = q.pattern_group.patterns[0].subject
    consts = np.full(5, const, dtype=np.int64)
    want = tpu.execute_batch(q, consts).tolist()
    many = tpu.merge.run_batch_const_many(q, [consts] * 3)
    assert [m.tolist() for m in many] == [want] * 3

    # cold memo: the window must still return exact counts via the redo path
    tpu.merge._cap_memo.clear()
    many = tpu.merge.run_batch_const_many(q, [consts] * 2)
    assert [m.tolist() for m in many] == [want] * 2


def test_const_list_matches_contains_many_all_routes(world):
    """const_list (the k2c merge relation) must agree with the CPU oracle's
    _contains_many on every routing branch — type OUT/IN, versatile
    PREDICATE_ID both directions, and normal segments both directions."""
    from wukong_tpu.types import IN, OUT, PREDICATE_ID, TYPE_ID

    g, ss = world
    cpu = CPUEngine(g, ss)
    tpu = TPUEngine(g, ss)
    ids = np.unique(np.concatenate(
        [s.keys[:50] for s in list(g.segments.values())[:6]]))
    cases = [(TYPE_ID, OUT, 17), (TYPE_ID, IN, int(ids[0])),
             (PREDICATE_ID, OUT, 7), (PREDICATE_ID, IN, 7),
             (7, OUT, int(g.segments[(7, IN)].keys[0])),
             (7, IN, int(g.segments[(7, OUT)].keys[0]))]
    for pid, d, const in cases:
        oracle = cpu._contains_many(
            ids, pid, d, np.full(len(ids), const, dtype=np.int64))
        lst, real = tpu.dstore.const_list(pid, d, const)
        got = np.isin(ids, np.asarray(lst)[:real])
        assert got.tolist() == oracle.tolist(), (pid, d, const)


def test_merge_forced_compaction_matches(engines, world, monkeypatch):
    """Filter steps that trigger the estimate-driven compact branch keep
    exact counts (root-level and mid-chain rebasing)."""
    _, tpu = engines
    _, ss = world
    q = _parse(ss, f"{BASIC}/lubm_q1")
    want = tpu.execute_batch_index(q, 2).tolist()
    # force every membership step to compact into a tiny class, then let the
    # overflow-retry loop discover the exact capacities
    monkeypatch.setattr(
        TPUEngine, "_chain_estimates",
        lambda self, pats: {k: 1.0 for k in range(len(pats))})
    tpu.merge._cap_memo.clear()
    q2 = _parse(ss, f"{BASIC}/lubm_q1")
    got = tpu.execute_batch_index(q2, 2).tolist()
    assert got == want


@pytest.mark.parametrize("qfile", QUERIES,
                         ids=[os.path.basename(f) for f in QUERIES])
def test_stream_expand_in_executor(engines, world, qfile, monkeypatch):
    """Force the Pallas streaming expand (interpret mode) through the whole
    merge executor: counts must match the oracle for every benchmark query.
    Slice mode keeps step-1 anchors distinct (pure stream arm); replicate
    mode duplicates them uniformly B times (B <= MDUP exercises the m-hot
    arm, beyond it the in-cond XLA fallback)."""
    from wukong_tpu.engine import tpu_stream

    cpu, tpu = engines
    g, ss = world
    monkeypatch.setattr(tpu_stream, "FORCE_INTERPRET", True)
    # density gate off so even sparse expands take the kernel
    monkeypatch.setattr(tpu_stream, "want_stream",
                        lambda est, ne, cap: cap % tpu_stream.TILE == 0)

    oracle = _parse(ss, qfile)
    oracle.result.blind = False
    cpu.execute(oracle)
    want = oracle.result.nrows

    q = _parse(ss, qfile)
    Global.enable_merge_join = True
    if q.start_from_index():
        counts = tpu.execute_batch_index(q, 2, slice_mode=True)
        assert int(counts.sum()) == want
        from wukong_tpu.engine.tpu_stream import MDUP

        q2 = _parse(ss, qfile)
        counts = tpu.execute_batch_index(q2, MDUP)  # m-hot at the exact cap
        assert counts.tolist() == [want] * MDUP
        q3 = _parse(ss, qfile)
        counts = tpu.execute_batch_index(q3, MDUP + 2)  # beyond: XLA arm
        assert counts.tolist() == [want] * (MDUP + 2)
    else:
        const = q.pattern_group.patterns[0].subject
        counts = tpu.execute_batch(q, np.full(2, const, dtype=np.int64))
        assert counts.tolist() == [want] * 2


def test_run_batch_index_many_matches_single(engines, world):
    """K windowed replicate heavy batches == K independent run_batch_index."""
    g, ss = world
    cpu, tpu = engines
    q = Parser(ss).parse(open(f"{BASIC}/lubm_q7").read())
    heuristic_plan(q)
    single = tpu.merge.run_batch_index(q, 4, False)
    many = tpu.execute_batch_index_many(q, 4, 3)
    assert len(many) == 3
    for counts in many:
        assert np.array_equal(np.asarray(counts), np.asarray(single))


def test_bytes_model_roofline(engines, world, monkeypatch):
    """The host-side HBM-traffic model (bench roofline fields): after a run,
    bytes_model reports the staged segment sizes actually in the device
    cache plus a capacity-driven table-state term, and scales its table term
    with B (capacity classes are per-batch). The lookup dispatch is pinned
    to the merge arm so the segment term's B-invariance assertion holds
    (the backend-aware factor can legitimately flip arms between capacity
    classes, changing what the model counts as streamed)."""
    from wukong_tpu.engine.tpu_merge import MergeExecutor

    monkeypatch.setattr(MergeExecutor, "PROBE_LOOKUP_FACTOR", 1 << 60)
    _, tpu = engines
    _, ss = world
    tpu.merge._cap_memo.clear()  # memoized caps were learned on other arms
    q = _parse(ss, f"{BASIC}/lubm_q7")
    tpu.execute_batch_index(q, 2)
    bm = tpu.merge.bytes_model(q, 2, "rep")
    assert bm is not None
    assert bm["total_bytes"] == bm["segment_bytes"] + bm["table_bytes"]
    assert bm["segment_bytes"] > 0 and bm["table_bytes"] > 0
    # segment term counts what the kernels READ (expand skips ekey, k2k
    # skips the key arrays), so it is bounded above by the staged bytes of
    # the chain's pinned segments — all still cache-resident after the run
    folds = tpu.merge._plan_folds(q.pattern_group.patterns, index_mode=True)
    staged = 0
    for key in tpu.merge._chain_pins(q.pattern_group.patterns, folds,
                                     index_mode=True):
        seg = tpu.dstore._cache.get(key)
        if seg is not None:
            staged += seg.nbytes
        ent = tpu.dstore._index_cache.get(key)
        if ent is not None:
            staged += int(ent[0].size) * 4
    # + the init index list (idx key, not a chain pin)
    p0 = q.pattern_group.patterns[0]
    ent = tpu.dstore._index_cache.get(
        ("idx", int(p0.subject), int(p0.direction)))
    if ent is not None:
        staged += int(ent[0].size) * 4
    assert 0 < bm["segment_bytes"] <= staged
    # B-scaling: the table term grows with the batch, segments do not
    q2 = _parse(ss, f"{BASIC}/lubm_q7")
    tpu.execute_batch_index(q2, 4)
    bm4 = tpu.merge.bytes_model(q2, 4, "rep")
    assert bm4["table_bytes"] > bm["table_bytes"]
    assert bm4["segment_bytes"] == bm["segment_bytes"]
    # out-of-scope chains (versatile predicates) return None
    from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
    from wukong_tpu.types import OUT

    qv = SPARQLQuery()
    qv.pattern_group.patterns = [Pattern(17, -3, OUT, -1)]
    qv.result.nvars = 1
    assert tpu.merge.bytes_model(qv, 2, "rep") is None


@pytest.mark.parametrize("qfile", QUERIES,
                         ids=[os.path.basename(f) for f in QUERIES])
def test_probe_lookup_path_matches(world, qfile, monkeypatch):
    """Force the probe-lookup arm for EVERY expand (factor 0: any segment
    'wins') and pin count equality with the CPU oracle — the sort-vs-probe
    dispatch must be invisible to results. A fresh engine avoids cap-memo
    crosstalk with the suite's shared engine; pins are checked to stage the
    BUCKET forms the probe path actually reads."""
    from wukong_tpu.engine.tpu_merge import MergeExecutor

    g, ss = world
    cpu = CPUEngine(g, ss)
    tpu = TPUEngine(g, ss)
    monkeypatch.setattr(MergeExecutor, "PROBE_LOOKUP_FACTOR", 0)

    oracle = _parse(ss, qfile)
    oracle.result.blind = False
    cpu.execute(oracle)
    want = oracle.result.nrows

    q = _parse(ss, qfile)
    B = 3
    if q.start_from_index():
        counts = tpu.execute_batch_index(q, B)
        mode = "rep"
    else:
        counts = tpu.execute_batch(
            q, np.full(B, q.pattern_group.patterns[0].subject,
                       dtype=np.int64))
        mode = "const"
    assert counts.tolist() == [want] * B
    # pins include the bucket forms ((pid, d) / ("segf", ...)) for every
    # expand; with probing forced, exactly those are what the run staged
    pats = q.pattern_group.patterns
    index_mode = mode == "rep"
    folds = tpu.merge._plan_folds(pats, index_mode=index_mode)
    pins = tpu.merge._chain_pins(pats, folds, index_mode=index_mode)
    expand_pins = [k for k in pins
                   if not (isinstance(k[0], str)
                           and k[0] in ("mrg", "mrgf", "rev"))]
    assert expand_pins, "no bucket-form pins for a chain with expands"
    for k in expand_pins:
        assert k in tpu.dstore._cache, f"pin {k} not staged by the run"
    # and the traffic model prices the probe path (no full-segment stream)
    bm = tpu.merge.bytes_model(q, B, mode)
    assert bm is not None and bm["total_bytes"] > 0


def test_run_batch_const_mixed_cross_class(engines, world):
    """ONE flight spanning DIFFERENT templates (the emulator's cross-class
    window): counts must match the per-class sequential path, including
    when a job in the flight overflows (slow-path redo) and when a
    planner-empty or merge-unsupported job is mixed in via the engine
    wrapper."""
    _, tpu = engines
    g, ss = world
    jobs = []
    want = []
    for qn in ("lubm_q4", "lubm_q5", "lubm_q6"):
        q = _parse(ss, f"{BASIC}/{qn}")
        const = q.pattern_group.patterns[0].subject
        consts = np.full(4, const, dtype=np.int64)
        want.append(tpu.execute_batch(q, consts).tolist())  # learns caps
        jobs.append((q, consts))
    got = tpu.merge.run_batch_const_mixed(jobs)
    assert [r.tolist() for r in got] == want
    # cold-memo flight: redo path must still produce exact counts
    tpu.merge._cap_memo.clear()
    got = tpu.merge.run_batch_const_mixed(jobs)
    assert [r.tolist() for r in got] == want
    # engine wrapper: same jobs through execute_batch_mixed
    got = tpu.execute_batch_mixed(jobs)
    assert [r.tolist() for r in got] == want


@pytest.mark.parametrize("seed", range(8))
def test_probe_vs_merge_arm_fuzz(seed, monkeypatch):
    """Differential fuzz of the lookup-dispatch arms on random worlds:
    the SAME random chain through (a) every expand/member forced onto the
    probe/binary-search arms and (b) every step forced onto the sort-merge
    arms must agree with each other AND with the independent BGP oracle.
    Random shapes cover expand-expand, expand-k2c, and k2k back-edges
    (LUBM's fixed shapes never vary the dispatch boundary)."""
    from tests.bgp_oracle import TripleIndex, eval_bgp
    from wukong_tpu.engine.tpu_merge import MergeExecutor
    from wukong_tpu.loader.generic_rdf import generate_generic
    from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
    from wukong_tpu.types import IN, OUT, TYPE_ID

    rng = np.random.default_rng(4200 + seed)
    triples, meta = generate_generic(4000, n_preds=6, n_types=3,
                                     seed=100 + seed)
    g = build_partition(triples, 0, 1)
    pids = [int(p) for p in np.unique(triples[:, 1]) if p != TYPE_ID]
    types = sorted(g.type_ids)
    tid = int(rng.choice(types))
    p1, p2 = (int(x) for x in rng.choice(pids, 2, replace=False))
    d1, d2 = int(rng.integers(2)), int(rng.integers(2))
    shape = int(rng.integers(3))
    pats = [Pattern(tid, TYPE_ID, IN, -1), Pattern(-1, p1, d1, -2)]
    if shape == 0:
        pats.append(Pattern(-2, p2, d2, -3))
        nv = 3
    elif shape == 1:  # k2c on the root var: a real const filter
        seg = g.segments.get((p2, OUT))
        const = (int(np.asarray(seg.edges)[rng.integers(seg.num_edges)])
                 if seg is not None and seg.num_edges else int(types[0]))
        pats.append(Pattern(-1, p2, OUT, const))
        nv = 2
    else:  # k2k back-edge
        pats.append(Pattern(-2, p2, d2, -1))
        nv = 2

    def mk():
        q = SPARQLQuery()
        q.pattern_group.patterns = [Pattern(p.subject, p.predicate,
                                            p.direction, p.object)
                                    for p in pats]
        q.result.nvars = nv
        q.result.required_vars = [-(i + 1) for i in range(nv)]
        q.result.blind = True
        return q

    B = 3
    got = {}
    for name, factor in (("probe", 0), ("merge", 1 << 60)):
        monkeypatch.setattr(MergeExecutor, "PROBE_LOOKUP_FACTOR", factor)
        eng = TPUEngine(g, None)
        got[name] = eng.execute_batch_index(mk(), B).tolist()
    assert got["probe"] == got["merge"], (seed, shape, got)

    # ground truth: the independent nested-loop oracle over raw triples
    def raw(p):
        if p.predicate == TYPE_ID and int(p.direction) == IN:
            return (p.object, TYPE_ID, p.subject)
        if int(p.direction) == OUT:
            return (p.subject, p.predicate, p.object)
        return (p.object, p.predicate, p.subject)

    idx = TripleIndex(triples)
    want = len(eval_bgp(idx, [raw(p) for p in pats],
                        [-(i + 1) for i in range(nv)]))
    assert got["probe"] == [want] * B, (seed, shape, want, got["probe"])
