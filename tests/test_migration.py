"""Elastic data plane (ISSUE 12): the live shard-migration actuator.

Acceptance surface: `MigrationExecutor` drives the advisor's
MigrationPlans through the crash-safe clone -> catch-up -> cutover ->
retire state machine; every query served during a migration is
byte-identical to an unmigrated oracle and `complete=True`; injected
faults (and a kill) at each of clone, catch-up, and cutover either
resume to completion or abort with the donor-side `gstore_digest`
unchanged and ZERO lost mutations — writes issued during every phase
are present after recovery; the `migration_enable` knob off leaves the
serving path and advisor posture exactly at the PR 11 observe-only
behavior; phase transitions journal `shard.migrate.*` events with shard
correlation keys (`/events -K migrate` selects the timeline); in-flight
state rides `/plan`, `/healthz` (degraded-not-dead), and the Monitor's
`Migration[...]` line; and the migration-safety analysis gate holds the
invariants statically. The whole module runs fully lockdep-checked.
"""

import os

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
from wukong_tpu.obs.events import get_journal, render_events
from wukong_tpu.obs.heat import get_heat
from wukong_tpu.obs.metrics import get_registry
from wukong_tpu.obs.placement import (
    MigrationPlan,
    get_advisor,
    get_lineage,
    render_plan,
)
from wukong_tpu.obs.tsdb import get_tsdb
from wukong_tpu.parallel.sharded_store import ShardedDeviceStore
from wukong_tpu.runtime import faults
from wukong_tpu.runtime.emulator import Emulator, _probe_read
from wukong_tpu.runtime.faults import FaultPlan, FaultSpec
from wukong_tpu.runtime.migration import (
    MIGRATION_PHASES,
    MigrationExecutor,
    get_migrator,
    maybe_start_migration,
)
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.store.dynamic import insert_batch_into, insert_triples
from wukong_tpu.store.gstore import build_partition, hash_mod
from wukong_tpu.store.persist import gstore_digest
from wukong_tpu.utils.errors import WukongError
from wukong_tpu.utils.timer import get_usec

pytestmark = pytest.mark.chaos

N_SHARDS = 4
DONOR = 3
RECIPIENT = 2


@pytest.fixture(autouse=True, scope="module")
def _lockdep_checked():
    """The migration suite runs fully lockdep-checked (the chaos-suite
    posture): the cutover/state locks are declared leaves, so any
    acquisition under them — or any cycle through the WAL mutation
    lock — fails the module teardown."""
    from wukong_tpu.analysis import lockdep

    lockdep.install(True)
    yield
    try:
        assert lockdep.cycles() == [], lockdep.cycles()
        assert lockdep.leaf_violations() == [], lockdep.leaf_violations()
    finally:
        lockdep.install(False)


@pytest.fixture(scope="module")
def world():
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    return {"g": g, "ss": ss, "triples": triples}


@pytest.fixture(scope="module")
def proxy(world):
    return Proxy(world["g"], world["ss"],
                 CPUEngine(world["g"], world["ss"]))


@pytest.fixture(autouse=True)
def _hygiene(monkeypatch):
    """Knobs at defaults (migration DISARMED — each test arms
    explicitly), every process-wide singleton clean, no fault plan or
    WAL leaking across tests."""
    monkeypatch.setattr(Global, "migration_enable", False)
    monkeypatch.setattr(Global, "migration_rotate_reads", True)
    monkeypatch.setattr(Global, "placement_interval_s", 0)
    monkeypatch.setattr(Global, "wal_dir", "")
    monkeypatch.setattr(Global, "enable_events", True)
    monkeypatch.setattr(Global, "enable_tsdb", True)
    get_migrator().reset()
    get_advisor().reset()
    get_lineage().reset()
    get_journal().clear()
    get_heat().reset()
    get_tsdb().reset()
    faults.clear()
    yield
    faults.clear()
    get_migrator().reset()


class _Mesh:
    devices = np.empty(N_SHARDS, dtype=object)


def _sstore(world):
    stores = [build_partition(world["triples"], i, N_SHARDS)
              for i in range(N_SHARDS)]
    return ShardedDeviceStore(stores, _Mesh(), replication_factor=1)


def _plan(donor=DONOR, recipient=RECIPIENT) -> MigrationPlan:
    return MigrationPlan(
        plan_id="mp-test", t_us=get_usec(), donor_shard=donor,
        recipient_host=recipient, predicted_move_bytes=1 << 20,
        bytes_source="estimate", donor_rate_per_s=4.0,
        mean_rate_per_s=1.0, imbalance_before=2.5, imbalance_after=1.5,
        window_s=60.0, inputs={}, reason="test")


def _edges(k: int, shard: int = DONOR, base: int = 100000) -> np.ndarray:
    """k synthetic edges whose subjects hash onto ``shard``."""
    out = []
    s = base
    while len(out) < k:
        if hash_mod(np.array([s]), N_SHARDS)[0] == shard:
            out.append((s, 17, s))
        s += 1
    return np.asarray(out, dtype=np.int64)


def _fetch(sstore, shard=DONOR):
    return sstore._fetch_shard(shard, _probe_read, "migtest")


def _arm(mig, sstore, monkeypatch, proxy=None):
    monkeypatch.setattr(Global, "migration_enable", True)
    mig.attach(sstore=sstore, owner=proxy)


# ---------------------------------------------------------------------------
# the off-knob posture: PR 11's observe-only behavior, pinned
# ---------------------------------------------------------------------------

def test_disabled_executor_refuses_and_posture_unchanged(world):
    sstore = _sstore(world)
    mig = get_migrator()
    mig.attach(sstore=sstore)
    with pytest.raises(WukongError, match="migration_enable is off"):
        mig.run_plan(_plan())
    # nothing moved, nothing journaled, nothing enrolled: the serving
    # path is exactly the static-hash PR 11 world
    assert sstore.placement == {} and sstore.rotation == {}
    assert get_journal().last(kind="shard.migrate") == []
    assert mig.status()["in_flight"] is False
    # and the boot helper refuses to start the actuator loop
    assert maybe_start_migration(sstore) is None


def test_disabled_advisor_stays_observe_only(world):
    """With the knob off the advisor still emits plans but the store
    stays bit-untouched — `run_hotspot`'s observe-only proof."""
    sstore = _sstore(world)
    fp = [(id(g), gstore_digest(g)) for g in sstore.stores]
    adv = get_advisor()
    adv.attach_store(sstore)
    adv.advise_once()  # whatever it decides, it must only *say* it
    assert [(id(g), gstore_digest(g)) for g in sstore.stores] == fp
    assert sstore.placement == {}


# ---------------------------------------------------------------------------
# the happy path
# ---------------------------------------------------------------------------

def test_full_migration_happy_path(world, monkeypatch):
    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    donor_store = sstore.stores[DONOR]
    d0 = gstore_digest(donor_store)
    before = get_registry().counter(
        "wukong_migrations_total",
        labels=("outcome",)).value(outcome="completed")
    job = mig.run_plan(_plan())
    assert job.phase == "done" and job.attempts == 1
    # read path swapped: new primary object, placement notes the host,
    # the donor copy demoted to a read-rotation replica on its old host
    assert sstore.stores[DONOR] is not donor_store
    assert sstore.placement == {DONOR: RECIPIENT}
    assert [h for h, _g in sstore.rotation[DONOR]] == [DONOR]
    # the copy is byte-identical and the donor was never written
    assert gstore_digest(sstore.stores[DONOR]) == d0
    assert gstore_digest(donor_store) == d0
    # post-move lineage observed immediately at cutover
    rec = get_lineage().report()[DONOR]
    assert rec["primary_host"] == RECIPIENT
    assert rec["rotation_hosts"] == [DONOR]
    # completion metrics
    reg = get_registry()
    assert reg.counter("wukong_migrations_total", labels=("outcome",)
                       ).value(outcome="completed") == before + 1
    assert job.bytes_moved > 0
    # every phase journaled, shard-correlated, cross-linked from the job
    kinds = [e.kind for e in get_journal().last(kind="shard.migrate",
                                                shard=DONOR)]
    assert kinds == ["shard.migrate.start", "shard.migrate.catchup",
                     "shard.migrate.cutover", "shard.migrate.retire"]
    assert len(job.event_ids) == 4
    assert all(get_journal().find(ev) is not None for ev in job.event_ids)


def test_rotate_off_retires_donor_outright(world, monkeypatch):
    sstore = _sstore(world)
    monkeypatch.setattr(Global, "migration_rotate_reads", False)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    job = mig.run_plan(_plan())
    assert job.phase == "done" and job.rotated is False
    assert sstore.rotation == {}
    assert sstore.placement == {DONOR: RECIPIENT}


def test_serving_byte_identical_through_every_phase(world, monkeypatch):
    """The tentpole's serving contract: a probe through the normal
    resilience fetch path after every phase returns bytes equal to the
    pre-migration oracle, complete=True throughout."""
    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    oracle, ok = _fetch(sstore)
    assert ok
    seen = {}

    def hook(phase, _job):
        out, complete = _fetch(sstore)
        seen[phase] = bool(complete) and np.array_equal(out, oracle)

    job = mig.run_plan(_plan(), phase_hook=hook)
    assert job.phase == "done"
    assert set(seen) == set(MIGRATION_PHASES)
    assert all(seen.values()), seen
    # and after the move settles, both rotation turns stay identical
    for _ in range(2 * len(sstore.rotation.get(DONOR, ())) + 2):
        out, complete = _fetch(sstore)
        assert complete and np.array_equal(out, oracle)


def test_wal_catchup_replays_tail_and_dual_writes(world, monkeypatch,
                                                  tmp_path):
    """Writes landing between snapshot and catch-up arrive via WAL-tail
    replay; writes landing after catch-up arrive via the dual-write
    sink — the recipient ends exactly one-application equal to an
    oracle partition."""
    monkeypatch.setattr(Global, "wal_dir", str(tmp_path / "wal"))
    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    w_clone, w_catchup = _edges(1, base=100000), _edges(1, base=101000)

    def hook(phase, _job):
        if phase == "clone":  # in the WAL tail the catch-up must replay
            insert_batch_into(list(sstore.stores), w_clone)
        elif phase == "catchup":  # dual-write window
            insert_batch_into(list(sstore.stores), w_catchup)

    job = mig.run_plan(_plan(), phase_hook=hook)
    assert job.phase == "done"
    # seq_clone is the WAL high-water mark at the snapshot (-1 on a
    # fresh log); exactly the one post-snapshot batch replays
    assert job.replayed == 1
    oracle = build_partition(world["triples"], DONOR, N_SHARDS)
    insert_triples(oracle, w_clone, check_ids=False)
    insert_triples(oracle, w_catchup, check_ids=False)
    assert gstore_digest(sstore.stores[DONOR]) == gstore_digest(oracle)
    # the rotation copy (the old donor) saw both writes too — rotated
    # reads must never serve stale data
    (_h, rot), = sstore.rotation[DONOR]
    assert gstore_digest(rot) == gstore_digest(oracle)


def test_stream_epoch_dual_applies_during_window(world, monkeypatch,
                                                 tmp_path):
    """A stream epoch committed during the dual-write window reaches the
    recipient through `migration_sinks()` (no epoch lost), exercising
    the ingest path's fan-out rather than `insert_batch_into`'s."""
    from wukong_tpu.store.dynamic import (
        deroll_migration_sink,
        enroll_migration_sink,
        migration_sinks,
    )
    from wukong_tpu.store.persist import clone_gstore
    from wukong_tpu.store.wal import mutation_lock
    from wukong_tpu.stream.ingest import StreamIngestor

    sstore = _sstore(world)
    recipient = clone_gstore(sstore.stores[DONOR])
    with mutation_lock():
        enroll_migration_sink(("migrate", DONOR), recipient)
    try:
        ing = StreamIngestor(list(sstore.stores))
        batch = _edges(2, base=102000)
        rec = ing.commit_epoch(batch)
        # the sink is a transient mirror of a store already counted:
        # n_inserted reports each edge once, not once-per-copy
        assert rec.n_inserted == len(batch)
        with mutation_lock():
            assert migration_sinks() == [recipient]
    finally:
        with mutation_lock():
            deroll_migration_sink(("migrate", DONOR))
    oracle = build_partition(world["triples"], DONOR, N_SHARDS)
    insert_triples(oracle, batch, check_ids=False)
    assert gstore_digest(recipient) == gstore_digest(oracle)


# ---------------------------------------------------------------------------
# chaos: injected faults at each phase abort cleanly back to the donor
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("site,kind", [
    ("migration.clone", "transient"),
    ("migration.catchup", "transient"),
    ("migration.cutover", "shard_down"),
])
def test_fault_at_each_phase_aborts_with_donor_untouched(
        world, monkeypatch, site, kind):
    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    donor_store = sstore.stores[DONOR]
    d0 = gstore_digest(donor_store)
    aborts0 = get_registry().counter(
        "wukong_migration_aborts_total",
        labels=("cause",)).value(cause="injected_fault")
    faults.install(FaultPlan([FaultSpec(site, kind)], seed=0))
    with pytest.raises((faults.TransientFault, faults.ShardDown)):
        mig.run_plan(_plan())
    faults.clear()
    job = mig.job()
    assert job.phase == "aborted" and job.abort_cause == "injected_fault"
    # rolled back to the donor: same primary object, digest unchanged,
    # no placement/rotation residue, no dual sink leaked
    from wukong_tpu.store.dynamic import migration_sinks
    from wukong_tpu.store.wal import mutation_lock

    assert sstore.stores[DONOR] is donor_store
    assert gstore_digest(donor_store) == d0
    assert sstore.placement == {} and sstore.rotation == {}
    with mutation_lock():
        assert migration_sinks() == []
    # the abort journaled with its phase, and the metric names the cause
    (ev,) = get_journal().last(kind="shard.migrate.abort")
    assert ev.shard == DONOR
    assert ev.attrs["at_phase"] == site.split(".")[1]
    assert get_registry().counter(
        "wukong_migration_aborts_total", labels=("cause",)
    ).value(cause="injected_fault") == aborts0 + 1
    # serving still complete and byte-identical after the abort
    out, complete = _fetch(sstore)
    assert complete and np.array_equal(out, _probe_read(donor_store))


def test_fault_mid_flight_write_survives_abort(world, monkeypatch,
                                               tmp_path):
    """Zero lost mutations on the ABORT path: a write issued after the
    snapshot is in the donor (the only copy that matters once the
    migration rolls back)."""
    monkeypatch.setattr(Global, "wal_dir", str(tmp_path / "wal"))
    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    w = _edges(1, base=103000)
    faults.install(FaultPlan(
        [FaultSpec("migration.catchup", "transient")], seed=0))
    with pytest.raises(faults.TransientFault):
        mig.run_plan(_plan(),
                     phase_hook=lambda ph, _j: insert_batch_into(
                         list(sstore.stores), w) if ph == "clone" else None)
    faults.clear()
    oracle = build_partition(world["triples"], DONOR, N_SHARDS)
    insert_triples(oracle, w, check_ids=False)
    assert gstore_digest(sstore.stores[DONOR]) == gstore_digest(oracle)


def test_abort_after_published_cutover_swaps_back(world, monkeypatch):
    """A failure AFTER the read path swapped (here: a crashing phase
    hook) rolls the publication back: donor primary restored, rotation
    dropped, fan-out rebound — the full abort-and-rollback contract."""
    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    donor_store = sstore.stores[DONOR]
    d0 = gstore_digest(donor_store)

    def hook(phase, _job):
        if phase == "cutover":
            raise RuntimeError("operator pulled the plug")

    with pytest.raises(RuntimeError):
        mig.run_plan(_plan(), phase_hook=hook)
    job = mig.job()
    assert job.phase == "aborted"
    assert sstore.stores[DONOR] is donor_store
    assert gstore_digest(donor_store) == d0
    assert sstore.placement.get(DONOR, DONOR) == DONOR
    assert sstore.rotation == {}
    (ev,) = get_journal().last(kind="shard.migrate.abort")
    assert ev.attrs["swapped_back"] is True
    out, complete = _fetch(sstore)
    assert complete and np.array_equal(out, _probe_read(donor_store))


def test_concurrent_abort_stops_the_driver(world, monkeypatch):
    """`migrate -abort` landing while the driver is mid-flight: the
    state machine must never roll forward past the abort — no cutover
    publishes, the job lands in history exactly once, and the driver
    surfaces the abort instead of completing the migration."""
    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    donor_store = sstore.stores[DONOR]

    def hook(phase, _job):
        if phase == "clone":  # the operator wins the race
            assert mig.abort(cause="operator").phase == "aborted"

    with pytest.raises(WukongError, match="aborted"):
        mig.run_plan(_plan(), phase_hook=hook)
    job = mig.job()
    assert job.phase == "aborted" and job.abort_cause == "operator"
    assert sstore.stores[DONOR] is donor_store
    assert sstore.placement == {} and sstore.rotation == {}
    with mig._lock:
        assert sum(1 for j in mig._history if j is job) == 1


def test_abort_after_retire_keeps_recipient_serving(world, monkeypatch):
    """An abort landing after retire already released the donor (rotate
    off) has nothing to roll back TO: the recipient must stay primary —
    never a None primary — and the shard keeps serving identically."""
    monkeypatch.setattr(Global, "migration_rotate_reads", False)
    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    oracle, _ok = _fetch(sstore)

    def hook(phase, _job):
        if phase == "retire":
            raise RuntimeError("late failure")

    with pytest.raises(RuntimeError):
        mig.run_plan(_plan(), phase_hook=hook)
    assert mig.job().phase == "aborted"
    assert sstore.stores[DONOR] is not None
    assert sstore.placement == {DONOR: RECIPIENT}
    out, complete = _fetch(sstore)
    assert complete and np.array_equal(out, oracle)


def test_remigration_grows_the_rotation_set(world, monkeypatch):
    """A second migration of an already-rotated shard APPENDS to the
    rotation (serving set k -> k+1, exactly the advisor's predicted-
    balance model), and aborting a third move restores the second's
    serving set — earlier rotation copies are never silently dropped."""
    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    oracle, _ok = _fetch(sstore)
    mig.run_plan(_plan())                           # 3 -> host 2
    mig.run_plan(_plan(donor=DONOR, recipient=1))   # 3 -> host 1
    assert sstore.placement == {DONOR: 1}
    assert [h for h, _g in sstore.rotation[DONOR]] == [DONOR, RECIPIENT]

    def hook(phase, _job):
        if phase == "cutover":
            raise RuntimeError("boom")

    with pytest.raises(RuntimeError):               # 3 -> host 0, aborted
        mig.run_plan(_plan(donor=DONOR, recipient=0), phase_hook=hook)
    assert sstore.placement == {DONOR: 1}
    assert [h for h, _g in sstore.rotation[DONOR]] == [DONOR, RECIPIENT]
    for _ in range(6):  # every rotation turn serves identical bytes
        out, complete = _fetch(sstore)
        assert complete and np.array_equal(out, oracle)


def test_operator_abort_via_executor(world, monkeypatch):
    """`migrate -abort` semantics: abort with nothing in flight is a
    clean no-op; a second abort after an abort is too."""
    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    assert mig.abort(cause="operator") is None
    job = mig.run_plan(_plan())
    assert job.phase == "done"
    assert mig.abort(cause="operator") is None  # done: nothing to abort


# ---------------------------------------------------------------------------
# the kill drill: crash (no rollback) at each phase, then resume
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("site", ["migration.clone", "migration.catchup",
                                  "migration.cutover"])
def test_kill_at_each_phase_resumes_with_zero_lost_writes(
        world, monkeypatch, tmp_path, site):
    """The crash-safety drill: a kill at any phase leaves a resumable
    job; writes issued before the crash AND between crash and resume
    are all present exactly once after roll-forward (dedup off, so a
    double-application would change the digest)."""
    monkeypatch.setattr(Global, "wal_dir", str(tmp_path / "wal"))
    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    writes = [_edges(1, base=104000), _edges(1, base=105000)]
    faults.install(FaultPlan([FaultSpec(site, "transient")], seed=0))
    with pytest.raises(faults.TransientFault):
        mig.run_plan(_plan(), rollback=False,
                     phase_hook=lambda ph, _j: insert_batch_into(
                         list(sstore.stores), writes[0],
                         dedup=False) if ph == "clone" else None)
    faults.clear()
    job = mig.job()
    assert job.phase == site.split(".")[1]  # crashed in place, resumable
    # a write lands while the migration is down
    insert_batch_into(list(sstore.stores), writes[1], dedup=False)
    job = mig.resume(phase_hook=lambda ph, _j: None)
    assert job.phase == "done" and job.attempts == 2
    oracle = build_partition(world["triples"], DONOR, N_SHARDS)
    for w in (writes if site != "migration.clone" else writes[1:]):
        # a clone-phase crash happens BEFORE the hook ever fired, so
        # only the while-down write exists in that schedule
        insert_triples(oracle, w, dedup=False, check_ids=False)
    assert gstore_digest(sstore.stores[DONOR]) == gstore_digest(oracle)
    (_h, rot), = sstore.rotation[DONOR]
    assert gstore_digest(rot) == gstore_digest(oracle)
    assert sstore.placement == {DONOR: RECIPIENT}


def test_resume_guards(world, monkeypatch):
    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    with pytest.raises(WukongError, match="no crashed migration"):
        mig.resume()
    mig.run_plan(_plan())
    with pytest.raises(WukongError, match="no crashed migration"):
        mig.resume()  # done jobs don't resume


def test_second_plan_refused_while_in_flight(world, monkeypatch):
    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    hits = []

    def hook(phase, _job):
        if phase == "clone" and not hits:
            hits.append(phase)
            with pytest.raises(WukongError, match="already in flight"):
                mig.run_plan(_plan(donor=1, recipient=0))

    job = mig.run_plan(_plan(), phase_hook=hook)
    assert hits and job.phase == "done"
    assert sstore.placement == {DONOR: RECIPIENT}  # only the first plan ran


def test_plan_validation(world, monkeypatch):
    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    with pytest.raises(WukongError, match="donor shard"):
        mig.run_plan(_plan(donor=99))
    with pytest.raises(WukongError, match="recipient host"):
        mig.run_plan(_plan(recipient=99))
    detached = MigrationExecutor()
    with pytest.raises(WukongError, match="no live sharded store"):
        monkeypatch.setattr(Global, "migration_enable", True)
        detached.run_plan(_plan())


# ---------------------------------------------------------------------------
# surfaces: events filter, /plan, /healthz, Monitor, metrics, console
# ---------------------------------------------------------------------------

def test_events_migrate_filter(world, monkeypatch):
    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    mig.run_plan(_plan())
    # `/events -K migrate`: the dotted-segment filter selects the whole
    # shard.migrate.* timeline (so does the full `-K shard.migrate`
    # prefix and an exact `-K shard.migrate.cutover`)
    _text, js = render_events(kind="migrate")
    assert set(js["counts"]) == {
        "shard.migrate.start", "shard.migrate.catchup",
        "shard.migrate.cutover", "shard.migrate.retire"}
    assert all(e["shard"] == DONOR for e in js["events"])
    assert [e.kind for e in get_journal().last(kind="shard.migrate")] == \
        [e["kind"] for e in js["events"]]
    (cut,) = get_journal().last(kind="shard.migrate.cutover")
    assert cut.attrs["recipient_host"] == RECIPIENT
    assert cut.attrs["pause_us"] >= 0
    # unrelated kinds stay out of the filtered view
    assert "shard.migrate.abort" not in js["counts"]


def test_plan_surface_healthz_and_monitor_mid_flight(world, monkeypatch):
    """Mid-migration: /plan shows IN FLIGHT, /healthz reports the shard
    degraded-not-dead, Monitor prints a Migration[...] line; all three
    go quiet once the migration settles."""
    from wukong_tpu.obs.httpd import health_report
    from wukong_tpu.runtime.monitor import Monitor

    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    mon = Monitor()
    seen = {}

    def hook(phase, _job):
        if phase != "cutover":
            return
        text, js = render_plan(advise=False)
        rep = health_report()
        seen["plan"] = "migration IN FLIGHT" in text
        seen["plan_js"] = js["migration"]["in_flight"]
        seen["healthz_live"] = rep["live"]
        seen["healthz"] = rep["degraded"].get("migration")
        seen["monitor"] = mon.migration_lines()

    mig.run_plan(_plan(), phase_hook=hook)
    assert seen["plan"] and seen["plan_js"]
    assert seen["healthz_live"] is True  # degraded, never dead
    assert seen["healthz"] == {"shard": DONOR, "phase": "cutover",
                               "recipient_host": RECIPIENT}
    assert seen["monitor"] and "Migration[" in seen["monitor"][0]
    # settled: every surface quiet again
    text, js = render_plan(advise=False)
    assert "IN FLIGHT" not in text and js["migration"]["in_flight"] is False
    assert js["migration"]["last"]["phase"] == "done"
    assert "migration" not in health_report()["degraded"]
    assert mon.migration_lines() == []


def test_phase_gauge_tracks_the_state_machine(world, monkeypatch):
    from wukong_tpu.runtime.migration import _phase_gauge

    sstore = _sstore(world)
    mig = get_migrator()
    _arm(mig, sstore, monkeypatch)
    assert _phase_gauge() == 0.0
    gauges = {}
    mig.run_plan(_plan(), phase_hook=lambda ph, _j: gauges.setdefault(
        ph, _phase_gauge()))
    # the hook fires with the phase still current: 1-based phase index
    assert gauges == {ph: float(i + 1)
                      for i, ph in enumerate(MIGRATION_PHASES)}
    assert _phase_gauge() == 0.0


def test_console_migrate_verb_surfaces(proxy, capsys, monkeypatch):
    """The operator verbs stay safe with no dist world attached: status
    prints, abort is a no-op, a sweep reports no plan, and the armed-off
    posture surfaces the refusal as a console error, not a crash."""
    from wukong_tpu.runtime.console import Console

    con = Console(proxy)
    assert con.run_command("migrate -s -j") is True
    out = capsys.readouterr().out
    assert '"in_flight": false' in out
    assert con.run_command("migrate -abort") is True  # no flight: no-op
    assert con.run_command("migrate") is True  # no advisor data -> no plan
    monkeypatch.setattr(Global, "migration_enable", True)
    assert con.run_command("migrate") is True  # still no plan; no crash


def test_actuator_loop_start_stop(world, monkeypatch):
    """`maybe_start_migration` arms the background loop only when both
    knobs ask for it, and supersedes the observe-only advisor loop (one
    sweeper, not two)."""
    sstore = _sstore(world)
    monkeypatch.setattr(Global, "migration_enable", True)
    monkeypatch.setattr(Global, "placement_interval_s", 60)
    mig = maybe_start_migration(sstore)
    try:
        assert mig is not None and mig._thread is not None
        assert get_advisor()._thread is None  # the advisor loop yielded
    finally:
        get_migrator().stop()
    assert get_migrator()._thread is None


# ---------------------------------------------------------------------------
# the executed rebalance drill (ROADMAP item 3 acceptance, armed)
# ---------------------------------------------------------------------------

def test_rebalance_drill_executes_and_rebalances(world, proxy,
                                                 monkeypatch):
    """The hot-spot drill flipped from observe-only to executed: the
    actuator migrates the advisor's donor shard, every probe during the
    migration is byte-identical, and the post-move host imbalance lands
    under placement_imbalance_x (bench.py --rebalance's contract)."""
    monkeypatch.setattr(Global, "migration_enable", True)
    sstore = _sstore(world)
    emu = Emulator(proxy)
    rep = emu.run_rebalance(n_ops=900, zipf_a=1.6, seed=7, sstore=sstore)
    assert rep["executed"] and rep["plan_donor_is_hot"]
    assert rep["queries_identical"], rep["probes"]
    assert set(rep["probes"]) == set(MIGRATION_PHASES) | {"post"}
    assert rep["rebalanced"] and rep["decision_after"] == "balanced"
    assert rep["imbalance_after"] < rep["imbalance_before"]
    assert rep["rebalance_gain"] > 1.0
    assert rep["job"]["phase"] == "done"
    assert rep["job"]["bytes_moved"] > 0
    assert rep["store_untouched"] is False  # the drill MOVED the store
    assert sstore.placement == {rep["hot"]: rep["plan"]["recipient_host"]}


def test_rebalance_drill_refuses_when_disarmed(world, proxy):
    """migration_enable off: the drill raises at run_plan — the
    observe-only posture holds even through the bench entrypoint."""
    sstore = _sstore(world)
    emu = Emulator(proxy)
    with pytest.raises(WukongError, match="migration_enable is off"):
        emu.run_rebalance(n_ops=600, zipf_a=1.6, seed=7, sstore=sstore)
    assert sstore.placement == {}


# ---------------------------------------------------------------------------
# the migration-safety analysis gate (pos/neg fixtures + repo clean)
# ---------------------------------------------------------------------------

def test_migration_gate_fixtures(tmp_path):
    from wukong_tpu.analysis import run_analysis

    def write(tree: dict) -> str:
        root = tmp_path / "pkg"
        for rel, src in tree.items():
            p = root / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(src)
        return str(root)

    bad = write({
        "runtime/migration.py": (
            "MIGRATION_PHASES = ('clone', 'cutover')\n"
            "def _phase_cutover(job):\n"
            "    emit_event('shard.migrate.cutover', shard=1)\n"
            "    swap()\n"
            "lock = make_lock('migration.state')\n"),
        "parallel/sharded_store.py": (
            "def cutover_shard(i, store):\n"
            "    stores[i] = store\n")})
    msgs = "\n".join(str(v) for v in run_analysis(
        bad, plugins=["migration-safety"]))
    assert "shard.migrate.start" in msgs      # unjournaled transition
    assert "shard.migrate.abort" in msgs
    assert "_phase_cutover" in msgs           # unguarded cutover path
    assert "cutover_shard" in msgs
    assert "migration.state" in msgs          # undeclared leaf lock

    good = write({
        "runtime/migration.py": (
            "MIGRATION_PHASES = ('clone', 'catchup', 'cutover', 'retire')\n"
            "declare_leaf('migration.state')\n"
            "lock = make_lock('migration.state')\n"
            "def run(job):\n"
            "    emit_event('shard.migrate.start', shard=1)\n"
            "    emit_event('shard.migrate.catchup', shard=1)\n"
            "    emit_event('shard.migrate.retire', shard=1)\n"
            "    emit_event('shard.migrate.abort', shard=1)\n"
            "def _phase_cutover(job):  # guarded by: the migration lock\n"
            "    emit_event('shard.migrate.cutover', shard=1)\n"),
        "parallel/sharded_store.py": (
            "def cutover_shard(self, i, store):\n"
            "    with self._migration_lock:\n"
            "        self.stores[i] = store\n")})
    assert run_analysis(good, plugins=["migration-safety"]) == []
    # a tree without an actuator is out of the gate's scope
    empty = str(tmp_path / "empty")
    os.makedirs(empty, exist_ok=True)
    assert run_analysis(empty, plugins=["migration-safety"]) == []


def test_repo_migration_gate_clean():
    from wukong_tpu.analysis import run_analysis

    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "wukong_tpu")
    assert run_analysis(pkg, plugins=["migration-safety"]) == []
