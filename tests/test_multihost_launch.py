"""Multi-host launch rehearsal (reference: scripts/run.sh:41-44 mpiexec +
core/wukong.cpp:102-104 rank assignment).

Two REAL OS processes bring up `jax.distributed` on the CPU backend
(coordinator + num_processes + process_id = the mpiexec contract), see the
combined global device set, load their own per-host preshard files
(loader/base.py preshard_dataset/load_host_partitions — the offline analogue
of base_loader.hpp's RDMA shuffle), build the global mesh via
`init_multihost`/`make_mesh`, and run one compiled cross-process collective
over it. This is the cheap rehearsal that catches jax.distributed API drift
before multi-host hardware ever appears (round-2 verdict missing #4)."""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, sys

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
coord = sys.argv[3]
shard_dir = sys.argv[4]

from wukong_tpu.parallel.mesh import init_multihost, make_mesh

init_multihost(coordinator=coord, num_processes=nproc, process_id=pid)
import jax

n_local = len(jax.local_devices())
n_global = len(jax.devices())
assert jax.process_index() == pid, (jax.process_index(), pid)

# per-host preshard load: this host reads ONLY its own file
from wukong_tpu.loader.base import load_host_partitions

parts = load_host_partitions(shard_dir, host_id=pid)
local_edges = [sum(s.num_edges for s in g.segments.values()) for g in parts]
assert [g.sid for g in parts] == [pid * len(parts) + k
                                  for k in range(len(parts))]

# one compiled cross-process collective over the global mesh: every process
# must see the whole cluster's edge count from its local shards alone
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

mesh = make_mesh(n_global)
arrs = [jax.device_put(jnp.asarray([e], jnp.int32), d)
        for e, d in zip(local_edges, jax.local_devices())]
ga = jax.make_array_from_single_device_arrays(
    (n_global,), NamedSharding(mesh, P("x")), arrs)
total = int(jax.jit(jnp.sum)(ga))
print(json.dumps({"pid": pid, "n_local": n_local, "n_global": n_global,
                  "local_edges": sum(local_edges), "global_edges": total}),
      flush=True)
"""


def test_two_process_cpu_rehearsal(tmp_path):
    from wukong_tpu.loader.base import load_triples, preshard_dataset
    from wukong_tpu.loader.lubm import write_dataset
    from wukong_tpu.store.gstore import build_all_partitions

    # offline steps, as on a real cluster: datagen then preshard for 2 hosts
    src = tmp_path / "src"
    write_dataset(str(src), 1, seed=0)
    shard_dir = tmp_path / "presharded"
    preshard_dataset(str(src), str(shard_dir), num_hosts=2, shards_per_host=2)

    # expected cluster-wide edge total from a single-process global build
    expected = sum(
        sum(s.num_edges for s in g.segments.values())
        for g in build_all_partitions(load_triples(str(src)), 4))

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"

    worker_py = tmp_path / "worker.py"
    worker_py.write_text(WORKER)
    env_base = dict(os.environ)
    procs = []
    for pid in range(2):
        env = dict(env_base,
                   JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="",
                   PYTHONPATH=REPO + os.pathsep
                   + env_base.get("PYTHONPATH", ""))
        env["XLA_FLAGS"] = (
            " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if "device_count" not in f)
            + " --xla_force_host_platform_device_count=2").strip()
        procs.append(subprocess.Popen(
            [sys.executable, str(worker_py), str(pid), "2", coord,
             str(shard_dir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-host rehearsal timed out")
        assert p.returncode == 0, err.decode()[-2000:]
        outs.append(json.loads(out.decode().strip().splitlines()[-1]))

    # both processes saw the SAME global world: 2 local + 2 remote devices
    for o in outs:
        assert o["n_local"] == 2 and o["n_global"] == 4, o
    # the collective agreed across processes and matches the global build
    assert outs[0]["global_edges"] == outs[1]["global_edges"] == expected
    # per-host loads are real partitions of it, loaded independently
    assert (outs[0]["local_edges"] + outs[1]["local_edges"] == expected)
    assert min(o["local_edges"] for o in outs) > 0


CHAIN_WORKER = r"""
import json, sys

pid = int(sys.argv[1])
nproc = int(sys.argv[2])
coord = sys.argv[3]
src = sys.argv[4]

from wukong_tpu.parallel.mesh import init_multihost, make_mesh

init_multihost(coordinator=coord, num_processes=nproc, process_id=pid)
import jax

from wukong_tpu.utils.compilecache import setup_persistent_cache

setup_persistent_cache()
n_global = len(jax.devices())

# SPMD discipline: every controller builds the SAME stores deterministically
# and traces the SAME chains in the same order (wukong.cpp:102-104 — every
# rank runs the identical engine binary over its partition)
from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.base import load_triples
from wukong_tpu.loader.lubm import VirtualLubmStrings
from wukong_tpu.parallel.dist_engine import DistEngine
from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.sparql.parser import Parser
from wukong_tpu.store.gstore import build_all_partitions

Global.enable_dist_inplace = False  # the POINT is cross-process collectives
triples = load_triples(src)
ss = VirtualLubmStrings(1, seed=0)
stores = build_all_partitions(triples, n_global)
dist = DistEngine(stores, ss, make_mesh(n_global))

BASIC = "/root/reference/scripts/sparql_query/lubm/basic"
rows = {}
for qn in ("lubm_q4", "lubm_q6", "lubm_q2"):
    q = Parser(ss).parse(open(f"{BASIC}/{qn}").read())
    heuristic_plan(q)
    q.result.blind = True
    dist.execute(q, from_proxy=False)
    assert q.result.status_code == 0, (qn, q.result.status_code)
    st = dist.last_chain_stats or {}
    assert st.get("mode") != "inplace"
    rows[qn] = int(q.result.nrows)
print(json.dumps({"pid": pid, "n_global": n_global, "rows": rows}),
      flush=True)
"""


def test_two_process_query_chains(tmp_path):
    """Full SPARQL chains ACROSS two real OS processes (2 x 2 devices):
    compiled shard_map chains whose all-to-all exchanges cross the process
    boundary, oracle-checked against a single-process CPU run — the
    strongest multi-chip correctness statement this environment can make
    (round-4 verdict #4 / next #5)."""
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.loader.base import load_triples
    from wukong_tpu.loader.lubm import VirtualLubmStrings, write_dataset
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.sparql.parser import Parser
    from wukong_tpu.store.gstore import build_partition

    src = tmp_path / "src"
    write_dataset(str(src), 1, seed=0)

    # oracle rows from a single-process single-partition CPU run
    ss = VirtualLubmStrings(1, seed=0)
    g1 = build_partition(load_triples(str(src)), 0, 1)
    cpu = CPUEngine(g1, ss)
    basic = "/root/reference/scripts/sparql_query/lubm/basic"
    want = {}
    for qn in ("lubm_q4", "lubm_q6", "lubm_q2"):
        q = Parser(ss).parse(open(f"{basic}/{qn}").read())
        heuristic_plan(q)
        q.result.blind = True
        cpu.execute(q, from_proxy=False)
        want[qn] = int(q.result.nrows)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    worker_py = tmp_path / "chain_worker.py"
    worker_py.write_text(CHAIN_WORKER)
    env_base = dict(os.environ)
    procs = []
    for pid in range(2):
        env = dict(env_base,
                   JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="",
                   PYTHONPATH=REPO + os.pathsep
                   + env_base.get("PYTHONPATH", ""))
        env["XLA_FLAGS"] = (
            " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if "device_count" not in f)
            + " --xla_force_host_platform_device_count=2").strip()
        procs.append(subprocess.Popen(
            [sys.executable, str(worker_py), str(pid), "2", coord,
             str(src)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=900)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("cross-process chain rehearsal timed out")
        assert p.returncode == 0, err.decode()[-3000:]
        outs.append(json.loads(out.decode().strip().splitlines()[-1]))

    for o in outs:
        assert o["n_global"] == 4, o
        assert o["rows"] == want, (o["rows"], want)
