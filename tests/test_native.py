"""Native C++ host runtime vs numpy fallbacks (equivalence + performance)."""

import numpy as np
import pytest

from wukong_tpu import native
from wukong_tpu.engine.device_store import BUCKET, _next_pow2


@pytest.fixture(scope="module")
def lib():
    lib = native.get_lib()
    if lib is None:
        pytest.skip("no C++ toolchain available")
    return lib


def test_parse_id_triples(lib, tmp_path):
    rng = np.random.default_rng(0)
    tri = rng.integers(0, 1 << 40, (5000, 3)).astype(np.int64)
    path = tmp_path / "id_x.nt"
    with open(path, "w") as f:
        for s, p, o in tri.tolist():
            f.write(f"{s}\t{p}\t{o}\n")
    got = native.parse_id_triples(str(path))
    assert np.array_equal(got, tri)


def test_parse_handles_blank_lines_and_crlf(lib, tmp_path):
    path = tmp_path / "id_y.nt"
    path.write_text("1\t2\t3\r\n\n4 5 6\n7\t8\t9")
    got = native.parse_id_triples(str(path))
    assert got.tolist() == [[1, 2, 3], [4, 5, 6], [7, 8, 9]]


def test_bucket_table_matches_numpy(lib):
    # compare against the pure-numpy placement (bit-identical policy)
    import wukong_tpu.native as nat
    from wukong_tpu.engine import device_store as ds

    rng = np.random.default_rng(1)
    keys = np.sort(rng.choice(1 << 30, 20000, replace=False)).astype(np.int64)
    degs = rng.integers(1, 9, len(keys))
    offsets = np.zeros(len(keys) + 1, dtype=np.int64)
    np.cumsum(degs, out=offsets[1:])
    NB = max(_next_pow2((len(keys) + 3) // 4), 2)
    got = nat.build_bucket_table_native(keys, offsets, NB)
    assert got is not None
    # force the numpy path
    old = nat.build_bucket_table_native
    try:
        nat.build_bucket_table_native = lambda *a, **k: None
        want = ds.build_hash_table(keys, offsets, num_buckets=NB)
    finally:
        nat.build_bucket_table_native = old
    for a, b in zip(got, want):
        if isinstance(a, np.ndarray):
            assert np.array_equal(a, b)
        else:
            assert a == b


def test_sort_triples_matches_lexsort(lib):
    rng = np.random.default_rng(2)
    n = 100000
    p = rng.integers(0, 40, n).astype(np.int64)
    s = rng.integers(0, 1 << 33, n).astype(np.int64)
    o = rng.integers(0, 1 << 33, n).astype(np.int64)
    perm = native.sort_triples_perm(p, s, o)
    assert perm is not None
    want = np.lexsort((o, s, p))
    # stable sorts over identical keys -> identical permutations
    assert np.array_equal(perm, want)


def test_store_build_identical_with_and_without_native(lib):
    from wukong_tpu.loader.lubm import generate_lubm
    from wukong_tpu.store.gstore import build_partition
    import wukong_tpu.native as nat

    triples, _ = generate_lubm(1, seed=3)
    g_native = build_partition(triples, 0, 2)
    old_sort, old_bt = nat.sort_triples_perm, nat.build_bucket_table_native
    try:
        nat.sort_triples_perm = lambda *a: None
        nat.build_bucket_table_native = lambda *a, **k: None
        g_numpy = build_partition(triples, 0, 2)
    finally:
        nat.sort_triples_perm, nat.build_bucket_table_native = old_sort, old_bt
    assert set(g_native.segments) == set(g_numpy.segments)
    for k in g_native.segments:
        a, b = g_native.segments[k], g_numpy.segments[k]
        assert np.array_equal(a.keys, b.keys)
        assert np.array_equal(a.offsets, b.offsets)
        assert np.array_equal(a.edges, b.edges)


def test_parse_rejects_ragged_lines(lib, tmp_path):
    path = tmp_path / "id_bad.nt"
    path.write_text("1\t2\t3\n4\t5\n6\t7\t8\n")  # middle line truncated
    with pytest.raises(ValueError):
        native.parse_id_triples(str(path))


def test_sort_triples_int32_path_matches_lexsort(lib):
    """The int32 variant (billion-triple builds: no upcast copies, int32
    perm/scratch) must produce the same stable permutation as lexsort and
    the int64 path."""
    rng = np.random.default_rng(3)
    n = 100000
    p = rng.integers(0, 40, n).astype(np.int32)
    s = rng.integers(0, 2**31 - 2, n).astype(np.int32)
    o = rng.integers(0, 2**31 - 2, n).astype(np.int32)
    perm = native.sort_triples_perm(p, s, o)
    assert perm is not None and perm.dtype == np.int32
    want = np.lexsort((o, s, p))
    assert np.array_equal(perm.astype(np.int64), want)
    # mixed dtypes fall back to the int64 path, same order
    perm64 = native.sort_triples_perm(p.astype(np.int64), s, o)
    assert perm64.dtype == np.int64
    assert np.array_equal(perm64, want)


def test_sort_triples_int32_stability_on_equal_keys(lib):
    one = np.zeros(7, np.int32)
    t3 = np.arange(7, dtype=np.int32)
    perm = native.sort_triples_perm(one, one, t3)
    assert np.array_equal(perm.astype(np.int64), np.arange(7))
    # all three equal: identity (stability)
    perm = native.sort_triples_perm(one, one, one)
    assert np.array_equal(perm.astype(np.int64), np.arange(7))


def test_store_build_int32_triples_matches_int64(lib):
    """build_partition on int32 triples (the at-scale diet) must produce
    stores identical to the int64 build — including TYPE_ID triples, the
    type index, and every VERSATILE structure (the exact paths the
    one-direction-at-a-time build reorder hoisted)."""
    from wukong_tpu.store.gstore import build_partition
    from wukong_tpu.types import IN, OUT, TYPE_ID

    rng = np.random.default_rng(4)
    n = 20000
    NORM = 1 << 17
    triples = np.stack([
        rng.integers(NORM, NORM + 5000, n),
        rng.integers(2, 30, n),
        rng.integers(NORM, NORM + 5000, n),
    ], axis=1)
    # type triples: (s, TYPE_ID, type-id) with type ids below NORMAL_ID_START
    ttr = np.stack([
        rng.integers(NORM, NORM + 5000, 3000),
        np.full(3000, TYPE_ID),
        rng.integers(2, 12, 3000),
    ], axis=1)
    triples = np.concatenate([triples, ttr])
    g64 = build_partition(triples.astype(np.int64), 0, 2, versatile=True)
    g32 = build_partition(triples.astype(np.int32), 0, 2, versatile=True)
    assert (TYPE_ID, OUT) in g64.segments  # the fixture really has types
    assert set(g64.segments) == set(g32.segments)
    for k in g64.segments:
        a, b = g64.segments[k], g32.segments[k]
        assert np.array_equal(a.keys, np.asarray(b.keys, np.int64))
        assert np.array_equal(a.offsets, b.offsets)
        assert np.array_equal(a.edges, np.asarray(b.edges, np.int64))
    assert set(g64.index) == set(g32.index)
    for k in g64.index:
        assert np.array_equal(g64.index[k],
                              np.asarray(g32.index[k], np.int64))
    assert g64.type_ids == g32.type_ids
    # versatile: vp CSRs + v/t/p sets
    for d in (OUT, IN):
        a, b = g64.vp[d], g32.vp[d]
        assert np.array_equal(a.keys, np.asarray(b.keys, np.int64))
        assert np.array_equal(a.offsets, b.offsets)
        assert np.array_equal(a.edges, np.asarray(b.edges, np.int64))
    assert np.array_equal(g64.v_set, np.asarray(g32.v_set, np.int64))
    assert np.array_equal(g64.t_set, np.asarray(g32.t_set, np.int64))
    assert np.array_equal(g64.p_set, np.asarray(g32.p_set, np.int64))
