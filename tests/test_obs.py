"""Observability subsystem: tracing, metrics registry, flight recorder.

Covers the ISSUE 3 acceptance surface: a traced query carries proxy /
queue / per-BGP-step (rows in/out) / shard-fetch spans; under an installed
FaultPlan the retry attempts and breaker events appear as span events
(chaos-marked); a deadline-expired query auto-dumps its trace through the
flight recorder; and MetricsRegistry.render_prometheus round-trips the
golden exposition format.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
from wukong_tpu.obs import (
    MetricsRegistry,
    QueryTrace,
    activate,
    chrome_trace_events,
    get_recorder,
    get_registry,
    maybe_start_trace,
)
from wukong_tpu.obs.recorder import FlightRecorder
from wukong_tpu.runtime import faults
from wukong_tpu.runtime.faults import FaultPlan, FaultSpec, TransientFault
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.runtime.resilience import CircuitBreaker, Deadline, retry_call
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.utils.errors import ErrorCode

pytestmark = pytest.mark.obs

PREFIX = """
PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
"""
Q_CHAIN = PREFIX + """SELECT ?X ?Y WHERE {
    ?X ub:memberOf ?Y .
    ?Y ub:subOrganizationOf ?Z .
}"""


@pytest.fixture(scope="module")
def world():
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    return g, ss


@pytest.fixture()
def proxy(world):
    g, ss = world
    return Proxy(g, ss, CPUEngine(g, ss))


@pytest.fixture(autouse=True)
def _tracing_hygiene(monkeypatch):
    """Each test opts into tracing explicitly; the recorder starts empty
    and no fault plan leaks across tests."""
    monkeypatch.setattr(Global, "enable_tracing", False)
    monkeypatch.setattr(Global, "trace_sample_every", 1)
    monkeypatch.setattr(Global, "trace_dump_dir", "")
    get_recorder().clear()
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# metrics registry + golden Prometheus exposition format
# ---------------------------------------------------------------------------

GOLDEN = """\
# HELP q_latency_us Latency
# TYPE q_latency_us histogram
q_latency_us_bucket{le="10"} 2
q_latency_us_bucket{le="100"} 3
q_latency_us_bucket{le="+Inf"} 4
q_latency_us_sum 1157.5
q_latency_us_count 4
# HELP queries_total Queries served
# TYPE queries_total counter
queries_total{status="SUCCESS"} 3
queries_total{status="TIMEOUT"} 1
# HELP queue_depth Waiting queries
# TYPE queue_depth gauge
queue_depth 7
"""


def test_prometheus_golden_roundtrip():
    reg = MetricsRegistry()
    c = reg.counter("queries_total", "Queries served", labels=("status",))
    c.labels(status="SUCCESS").inc()
    c.labels(status="SUCCESS").inc(2)
    c.labels(status="TIMEOUT").inc()
    reg.gauge("queue_depth", "Waiting queries").set(7)
    h = reg.histogram("q_latency_us", "Latency", buckets=(10, 100))
    h.observe(3)
    h.observe(4.5)
    h.observe(50)
    h.observe(1100)
    assert reg.render_prometheus() == GOLDEN


def test_registry_get_or_create_and_snapshot():
    reg = MetricsRegistry()
    a = reg.counter("x_total", "x")
    b = reg.counter("x_total")
    assert a is b  # same family: cached handles and lookups converge
    a.inc(5)
    snap = reg.snapshot()
    assert snap["x_total"]["series"][0]["value"] == 5
    with pytest.raises(ValueError):
        reg.gauge("x_total")  # kind mismatch is a programming error
    with pytest.raises(ValueError):
        reg.counter("bad name!")
    with pytest.raises(ValueError):
        a.inc(-1)  # counters only go up


def test_registry_reset_keeps_cached_handles():
    """reset() zeroes in place: module-level cached handles and fresh
    lookups must keep converging on the same (zeroed) series."""
    reg = MetricsRegistry()
    c = reg.counter("t_total")
    h = reg.histogram("t_lat", buckets=(10,))
    c.inc(3)
    h.observe(5)
    reg.reset()
    assert reg.counter("t_total") is c  # same family object survives
    assert c.value() == 0
    assert reg.snapshot()["t_lat"]["series"][0]["count"] == 0
    c.inc()  # the old handle still feeds the exported series
    assert reg.snapshot()["t_total"]["series"][0]["value"] == 1


def test_gauge_callback_and_labeled_callback():
    reg = MetricsRegistry()
    reg.gauge("depth").set_function(lambda: 42)
    reg.gauge("open_keys", labels=("name",)).set_function(
        lambda: {("dist.shard",): 3})
    text = reg.render_prometheus()
    assert "depth 42" in text
    assert 'open_keys{name="dist.shard"} 3' in text


def test_labeled_gauge_callback_drops_absent_series():
    """The callback's return IS the series set: a dead breaker/pool must
    disappear from the export, not linger at its last value."""
    reg = MetricsRegistry()
    g = reg.gauge("open_keys", labels=("name",))
    state = {("a",): 1}
    g.set_function(lambda: dict(state))
    assert 'open_keys{name="a"} 1' in reg.render_prometheus()
    state.clear()
    assert 'name="a"' not in reg.render_prometheus()


def test_histogram_bulk_observe():
    reg = MetricsRegistry()
    h = reg.histogram("lat", buckets=(10,))
    h.observe(5, count=100)  # one call per device batch, not per query
    snap = reg.snapshot()["lat"]["series"][0]
    assert snap["count"] == 100 and snap["sum"] == 500


# ---------------------------------------------------------------------------
# trace context basics
# ---------------------------------------------------------------------------

def test_trace_spans_nest_and_summarize():
    tr = QueryTrace(kind="query")
    with tr.span("a"):
        with tr.span("b", step=1):
            tr.event("ev", k=2)
    assert [s.name for s in tr.spans] == ["a", "b"]
    assert tr.spans[0].depth == 0 and tr.spans[1].depth == 1
    assert tr.spans[1].events[0][1] == "ev"
    s = tr.step_summary()
    assert s["a"]["count"] == 1 and s["b"]["count"] == 1
    evs = chrome_trace_events([tr])
    assert any(e["ph"] == "X" and e["name"] == "a" for e in evs)
    assert any(e["ph"] == "i" and e["name"] == "ev" for e in evs)


def test_maybe_start_trace_respects_knobs(monkeypatch):
    assert maybe_start_trace() is None  # default off: zero-overhead path
    monkeypatch.setattr(Global, "enable_tracing", True)
    assert maybe_start_trace() is not None
    monkeypatch.setattr(Global, "trace_sample_every", 4)
    got = sum(maybe_start_trace() is not None for _ in range(16))
    assert got == 4  # 1 in N sampling


def test_step_trace_shim_retired():
    """runtime/tracing.py carried a deprecation shim for one release
    (PR 3); PR 7 retired it — the import must fail with a pointer to the
    canonical homes, and the canonical StepTrace must still work."""
    with pytest.raises(ImportError, match="wukong_tpu.obs.trace"):
        import wukong_tpu.runtime.tracing  # noqa: F401
    from wukong_tpu.obs.trace import StepTrace

    tr = StepTrace()
    with tr.span("expand"):
        pass
    assert tr.summary()["expand"]["count"] == 1


# ---------------------------------------------------------------------------
# end-to-end: traced query through the proxy (acceptance span set)
# ---------------------------------------------------------------------------

def test_traced_query_has_proxy_and_step_spans(proxy, monkeypatch):
    monkeypatch.setattr(Global, "enable_tracing", True)
    q = proxy.run_single_query(Q_CHAIN, device="cpu", blind=True)
    assert q.result.status_code == ErrorCode.SUCCESS
    tr = get_recorder().last(1)[0]
    assert tr.status == "SUCCESS"
    names = [s.name for s in tr.spans]
    assert "proxy.parse" in names and "proxy.plan" in names
    assert "cpu.execute" in names
    steps = [s for s in tr.spans if s.name == "cpu.step"]
    assert len(steps) == 3  # one span per BGP step
    for sp in steps:  # rows in/out recorded at step granularity
        assert "rows_in" in sp.attrs and "rows_out" in sp.attrs
    assert steps[0].attrs["rows_in"] == 0
    assert steps[-1].attrs["rows_out"] == q.result.nrows
    # reply status reached the registry
    assert get_registry().counter(
        "wukong_queries_total", labels=("status", "tenant")).value(
            status="SUCCESS", tenant="default") >= 1


def test_traced_query_through_engine_pool_has_queue_span(world, monkeypatch):
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.runtime.scheduler import EnginePool
    from wukong_tpu.sparql.parser import Parser

    g, ss = world
    monkeypatch.setattr(Global, "enable_tracing", True)
    pool = EnginePool(num_engines=2,
                      make_engine=lambda tid: CPUEngine(g, ss))
    pool.start()
    try:
        q = Parser(ss).parse(Q_CHAIN)
        heuristic_plan(q)
        q.result.blind = True
        q.trace = maybe_start_trace(kind="query")
        out = pool.wait(pool.submit(q), timeout=30)
        assert out.result.status_code == ErrorCode.SUCCESS
        names = [s.name for s in q.trace.spans]
        assert "pool.queue" in names  # queue wait is its own span
        qs = next(s for s in q.trace.spans if s.name == "pool.queue")
        assert "engine" in qs.attrs  # closed by the popping engine thread
        assert "cpu.execute" in names
    finally:
        pool.stop()


# ---------------------------------------------------------------------------
# chaos: retry attempts / breaker events / fault sites land on the trace
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_retry_and_fault_events_appear_in_trace(monkeypatch):
    monkeypatch.setattr(Global, "retry_base_ms", 1)
    monkeypatch.setattr(Global, "retry_max_ms", 2)
    faults.install(FaultPlan([FaultSpec("dist.shard_fetch", "transient",
                                        count=2)], seed=0))
    tr = QueryTrace(kind="query")

    def attempt():
        faults.site("dist.shard_fetch", shard=3)
        return "ok"

    with activate(tr), tr.span("shard.fetch", shard=3):
        out = retry_call(attempt, site="dist.shard_fetch[3]",
                         retry_on=(TransientFault,))
    assert out == "ok"
    evs = tr.event_names()
    assert evs.count("fault.injected") == 2  # both injected transients
    assert evs.count("retry") == 2  # ...and both retry attempts
    sp = tr.spans[0]
    assert {n for (_t, n, _a) in sp.events} == {"fault.injected", "retry"}


@pytest.mark.chaos
def test_env_fault_plan_events_appear_in_trace(proxy, monkeypatch):
    """The WUKONG_FAULT_PLAN env form (acceptance wording): a traced query
    through the proxy while the pool.execute site faults carries the
    injected-fault and retry evidence on its trace."""
    monkeypatch.setattr(Global, "enable_tracing", True)
    monkeypatch.setattr(Global, "retry_base_ms", 1)
    monkeypatch.setattr(Global, "retry_max_ms", 2)
    monkeypatch.setenv("WUKONG_FAULT_PLAN",
                       "seed=3;stream.ingest:transient,count=1")
    monkeypatch.setitem(faults._state, "plan", None)
    monkeypatch.setitem(faults._state, "env_checked", False)
    from wukong_tpu.stream import StreamContext

    g, _ss = proxy.g, proxy.str_server
    ctx = StreamContext([g], proxy.str_server)
    ctx.feed(np.asarray([[131072, 2, 131073]], dtype=np.int64))
    tr = next(t for t in reversed(get_recorder().last())
              if t.kind == "stream")
    evs = tr.event_names()
    assert "fault.injected" in evs and "retry" in evs


@pytest.mark.chaos
def test_breaker_trip_and_close_events_appear_in_trace():
    clock = [0.0]
    br = CircuitBreaker(threshold=2, cooldown_ms=1000,
                        clock=lambda: clock[0])
    tr = QueryTrace(kind="query")
    with activate(tr), tr.span("shard.fetch", shard=0):
        br.record_failure(0)
        br.record_failure(0)  # trips
        clock[0] = 2.0  # past cooldown: half-open probe allowed
        assert br.allow(0)
        br.record_success(0)  # closes
    evs = tr.event_names()
    assert "breaker.trip" in evs and "breaker.close" in evs
    assert get_registry().counter(
        "wukong_breaker_trips_total", labels=("key",)).value(key="0") >= 1


@pytest.mark.chaos
def test_chaos_sharded_fetch_spans_in_dist_trace(world, monkeypatch):
    """Integration: a traced query over the sharded store under an
    installed FaultPlan carries shard.fetch spans whose events show the
    injected faults and retries."""
    from wukong_tpu.parallel.sharded_store import ShardedDeviceStore

    class _Mesh:  # only .devices.size is consulted by the store
        devices = np.empty(1, dtype=object)

    g, ss = world
    monkeypatch.setattr(Global, "retry_base_ms", 1)
    monkeypatch.setattr(Global, "retry_max_ms", 2)
    store = ShardedDeviceStore.__new__(ShardedDeviceStore)
    store.stores = [g]
    store.breaker = CircuitBreaker()
    store.degraded_shards = set()
    store.failover_shards = set()
    store.replicas = {}
    store.rotation = {}
    store._rotation_rr = {}
    store._event_noted = {}
    faults.install(FaultPlan([FaultSpec("dist.shard_fetch", "transient",
                                        count=1)], seed=0))
    tr = QueryTrace(kind="query")
    with activate(tr):
        out, ok = store._fetch_shard(0, lambda g: "csr", "segment(7,0)")
    assert (out, ok) == ("csr", True)
    [sp] = [s for s in tr.spans if s.name == "shard.fetch"]
    assert sp.attrs["shard"] == 0 and sp.attrs["ok"] is True
    evs = [n for (_t, n, _a) in sp.events]
    assert "fault.injected" in evs and "retry" in evs


# ---------------------------------------------------------------------------
# flight recorder: ring, dump-on-timeout, slow-query threshold
# ---------------------------------------------------------------------------

def test_flight_recorder_ring_is_bounded_and_searchable():
    rec = FlightRecorder(capacity=4)
    for i in range(8):
        rec.on_complete(QueryTrace(kind="query", qid=100 + i))
    assert len(rec.last()) == 4  # bounded ring
    assert rec.find(107) is not None  # by qid
    assert rec.find(rec.last(1)[0].trace_id) is not None  # by trace id
    assert rec.find(100) is None  # evicted


def test_flight_recorder_dumps_on_timeout(proxy, monkeypatch, tmp_path):
    """A deadline-expired query auto-dumps its trace: in-memory AND as a
    JSON file when trace_dump_dir is set (ISSUE 3 acceptance)."""
    import wukong_tpu.runtime.proxy as proxy_mod

    monkeypatch.setattr(Global, "enable_tracing", True)
    monkeypatch.setattr(Global, "trace_dump_dir", str(tmp_path))

    class _Clock:  # expires after the first engine-side check
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            self.t += 0.6
            return self.t

    monkeypatch.setattr(
        proxy_mod.Deadline, "from_config",
        classmethod(lambda cls: Deadline(timeout_ms=1, clock=_Clock())))
    q = proxy.run_single_query(Q_CHAIN, device="cpu", blind=True)
    assert q.result.status_code == ErrorCode.QUERY_TIMEOUT
    rec = get_recorder()
    reasons = [r for r, _t in rec.dumps]
    assert "QUERY_TIMEOUT" in reasons
    files = os.listdir(tmp_path)
    assert len(files) == 1 and files[0].startswith("trace_")
    import json

    dump = json.load(open(tmp_path / files[0]))
    assert dump["reason"] == "QUERY_TIMEOUT"
    assert any(s["name"] == "cpu.execute" for s in dump["spans"])


def test_flight_recorder_slow_query_threshold(monkeypatch):
    monkeypatch.setattr(Global, "trace_slow_ms", 0)  # threshold off
    rec = FlightRecorder(capacity=8)
    tr = QueryTrace(kind="query")
    rec.on_complete(tr, ErrorCode.SUCCESS)
    assert not rec.dumps
    monkeypatch.setattr(Global, "trace_slow_ms", 1)
    slow = QueryTrace(kind="query")
    slow.t0_us -= 5_000  # pretend it ran 5ms
    rec.on_complete(slow, ErrorCode.SUCCESS)
    assert [r for r, _t in rec.dumps] == ["SLOW_QUERY"]


# ---------------------------------------------------------------------------
# stream epochs are traced too
# ---------------------------------------------------------------------------

def test_stream_epoch_traced(world, monkeypatch):
    from wukong_tpu.stream import StreamContext

    g, ss = world
    monkeypatch.setattr(Global, "enable_tracing", True)
    triples, _ = generate_lubm(1, seed=42)
    ctx = StreamContext([build_partition(triples[:100], 0, 1)], ss)
    ctx.register(PREFIX + "SELECT ?X ?Y WHERE { ?X ub:memberOf ?Y . }")
    ctx.feed(triples[100:200])
    tr = next(t for t in reversed(get_recorder().last())
              if t.kind == "stream")
    names = [s.name for s in tr.spans]
    assert "stream.ingest" in names and "stream.eval" in names
    assert "stream.eval_query" in names  # per-standing-query span


# ---------------------------------------------------------------------------
# tooling satellites: lint gate + overhead guard
# ---------------------------------------------------------------------------

def test_lint_obs_gate():
    """No bare print() in library code outside report paths — run the
    actual gate script the way CI would."""
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "lint_obs.py")
    proc = subprocess.run([sys.executable, script], capture_output=True,
                          text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_parse_failure_still_reaches_reply_observability(proxy, monkeypatch):
    """A query that dies in parse/plan (no reply object exists) must still
    land on the flight recorder and the status counter — a syntax-error
    storm is an operational signal, not a silent gap."""
    from wukong_tpu.utils.errors import WukongError

    monkeypatch.setattr(Global, "enable_tracing", True)
    with pytest.raises(WukongError):
        proxy.run_single_query("SELECT ?x WHERE { broken", device="cpu")
    [tr] = get_recorder().last(1)
    assert tr.status == "SYNTAX_ERROR"
    assert get_registry().counter(
        "wukong_queries_total", labels=("status", "tenant")).value(
            status="SYNTAX_ERROR", tenant="default") >= 1


def test_tracing_off_leaves_query_untouched(proxy):
    """Default path: no trace object reaches the query, no recorder entry
    (the zero-overhead contract the bench guard quantifies)."""
    q = proxy.run_single_query(Q_CHAIN, device="cpu", blind=True)
    assert q.result.status_code == ErrorCode.SUCCESS
    assert getattr(q, "trace", None) is None
    assert get_recorder().last() == []


# ---------------------------------------------------------------------------
# ROADMAP follow-up (e): HTTP scrape endpoint + periodic snapshot-to-file
# ---------------------------------------------------------------------------

def test_metrics_http_endpoint(monkeypatch):
    """GET /metrics serves the Prometheus exposition, /metrics.json the
    snapshot; metrics_port=0 (the default) starts nothing."""
    import json as _json
    import socket
    import urllib.request

    from wukong_tpu.obs import maybe_start_metrics_http, stop_metrics_http

    assert maybe_start_metrics_http(port=0) is None  # default: off
    with socket.socket() as s:  # find a free port
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    srv = maybe_start_metrics_http(port=port)
    assert srv is not None
    try:
        get_registry().counter("wukong_obs_http_probe_total", "probe").inc()
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "# TYPE wukong_obs_http_probe_total counter" in body
        js = _json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics.json", timeout=5).read())
        assert js["wukong_obs_http_probe_total"]["kind"] == "counter"
        # idempotent: a second start reuses the running server
        assert maybe_start_metrics_http(port=port) is srv
    finally:
        stop_metrics_http()


def test_metrics_snapshotter_writes_file(tmp_path):
    import json as _json

    from wukong_tpu.obs import MetricsSnapshotter

    path = tmp_path / "soak_metrics.json"
    snap = MetricsSnapshotter(str(path), interval_s=0.1)
    get_registry().counter("wukong_obs_snap_probe_total", "probe").inc(3)
    snap.start()
    deadline = time.time() + 5
    while not path.exists() and time.time() < deadline:
        time.sleep(0.05)
    snap.stop()
    data = _json.loads(path.read_text())
    assert data["wukong_obs_snap_probe_total"]["series"][0]["value"] == 3.0
