"""Placement observatory (ISSUE 11): metrics history, cluster event
journal, and the observe-only migration advisor.

Acceptance surface: the tsdb ring converts counters to windowed rates and
histogram buckets to windowed percentiles (/history + `history` verb);
lifecycle events land in one ordered journal with shard/tenant/qid
correlation keys — a forced breaker-trip -> failover -> heal sequence
reads as exactly that sequence, and SLO_BURN flight-recorder dumps
reference their triggering event id; the PlacementAdvisor reads
PLACEMENT_INPUTS through the tsdb trend windows and emits a literal
MigrationPlan (hot-spot drill: top donor = the seeded hot shard,
predicted bytes within 25% of the donor's checkpoint size, store
bit-untouched); /healthz splits readiness from liveness; trace_dump_max
bounds the dump dir; concurrent scrapes of every endpoint during serving
are crash-free under the lockdep checker; and the placement-telemetry
analysis gate holds the surface statically.
"""

import dataclasses
import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.lubm import UB, VirtualLubmStrings, generate_lubm
from wukong_tpu.obs import QueryTrace, get_recorder, get_registry
from wukong_tpu.obs.events import EventJournal, emit_event, get_journal, render_events
from wukong_tpu.obs.heat import get_heat
from wukong_tpu.obs.placement import (
    MIGRATION_PLAN_FIELDS,
    MigrationPlan,
    PlacementAdvisor,
    ShardLineage,
    get_advisor,
    get_lineage,
    render_plan,
)
from wukong_tpu.obs.tsdb import MetricsTSDB, get_tsdb, render_history
from wukong_tpu.runtime import faults
from wukong_tpu.runtime.faults import FaultPlan, FaultSpec
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.store.gstore import build_partition

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True, scope="module")
def _lockdep_checked():
    """The observatory suite runs fully lockdep-checked (the chaos-suite
    posture): every lock created during the module feeds the
    acquisition-order graph, so the concurrent-scrape test doubles as a
    lock-order regression test. Teardown asserts zero cycles and zero
    declared-leaf inversions."""
    from wukong_tpu.analysis import lockdep

    lockdep.install(True)
    yield
    try:
        assert lockdep.cycles() == [], lockdep.cycles()
        assert lockdep.leaf_violations() == [], lockdep.leaf_violations()
    finally:
        lockdep.install(False)


@pytest.fixture(scope="module")
def world():
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    return {"g": g, "ss": ss, "triples": triples}


@pytest.fixture(scope="module")
def proxy(world):
    return Proxy(world["g"], world["ss"],
                 CPUEngine(world["g"], world["ss"]))


@pytest.fixture(autouse=True)
def _hygiene(monkeypatch):
    """Observatory knobs at defaults; every process-wide ring/ledger
    clean; no fault plan leaks across tests."""
    monkeypatch.setattr(Global, "enable_tracing", False)
    monkeypatch.setattr(Global, "trace_dump_dir", "")
    monkeypatch.setattr(Global, "enable_events", True)
    monkeypatch.setattr(Global, "enable_tsdb", True)
    get_recorder().clear()
    get_heat().reset()
    get_tsdb().reset()
    get_journal().clear()
    get_advisor().reset()
    get_lineage().reset()
    faults.clear()
    yield
    faults.clear()


class _Mesh4:
    devices = np.empty(4, dtype=object)


def _sstore(world, n=4, replication_factor=1):
    from wukong_tpu.parallel.sharded_store import ShardedDeviceStore

    stores = [build_partition(world["triples"], i, n) for i in range(n)]
    return ShardedDeviceStore(stores, _Mesh4(),
                              replication_factor=replication_factor)


# ---------------------------------------------------------------------------
# tsdb: windowed rates, percentiles, retention, /history
# ---------------------------------------------------------------------------

def test_tsdb_counter_rate_over_window():
    c = get_registry().counter("wukong_test_obsv_total", "t",
                               labels=("who",))
    ts = MetricsTSDB(interval_s=1, retention_s=600)
    c.labels(who="a").inc(10)
    ts.sample_once(now_us=1_000_000)
    c.labels(who="a").inc(30)
    c.labels(who="b").inc(5)
    ts.sample_once(now_us=11_000_000)
    # delta 30 over 10s, summed over matching label subsets
    assert ts.rate("wukong_test_obsv_total", who="a") == pytest.approx(3.0)
    by = ts.rate_by_label("wukong_test_obsv_total", "who")
    assert by["a"] == pytest.approx(3.0)
    assert by["b"] == pytest.approx(0.5)
    # a single sample answers no rate
    ts2 = MetricsTSDB()
    ts2.sample_once()
    assert ts2.rate("wukong_test_obsv_total") is None


def test_tsdb_retention_evicts_old_samples():
    ts = MetricsTSDB(interval_s=1, retention_s=10)
    for t_s in (0, 4, 8, 12, 16, 20):
        ts.sample_once(now_us=t_s * 1_000_000)
    # everything older than 20 - 10 = 10s is gone
    assert len(ts) == 3  # t = 12, 16, 20
    assert ts.span_s() == pytest.approx(8.0)


def test_tsdb_histogram_quantile_windowed():
    h = get_registry().histogram("wukong_test_obsv_lat_us", "t")
    ts = MetricsTSDB(interval_s=1, retention_s=600)
    h.observe(50, count=100)  # pre-window history must not leak in
    ts.sample_once(now_us=1_000_000)
    for v in (200, 200, 200, 50_000):
        h.observe(v)
    ts.sample_once(now_us=2_000_000)
    p50 = ts.quantile("wukong_test_obsv_lat_us", 0.5)
    p99 = ts.quantile("wukong_test_obsv_lat_us", 0.99)
    # 3 of 4 in-window observations land in the (100, 400] bucket
    assert 100 < p50 <= 400
    assert p99 > 6_400  # the 50ms outlier dominates the tail
    # the 100 pre-window observations at 50us would have dragged p50
    # under 100 if the window leaked
    assert ts.quantile("wukong_test_obsv_lat_us", 0.5,
                       window_s=1e9) is not None


def test_history_report_and_render():
    c = get_registry().counter("wukong_test_obsv_total", "t",
                               labels=("who",))
    ts = get_tsdb()
    ts.sample_once(now_us=1_000_000)
    c.labels(who="hist").inc(42)
    ts.sample_once(now_us=2_000_000)
    text, js = render_history(8)
    assert "COUNTER RATES" in text and "GAUGES" in text
    assert js["samples"] == 2
    names = [r["name"] for r in js["counters"]]
    assert "wukong_test_obsv_total" in names


# ---------------------------------------------------------------------------
# event journal: ring, ids, correlation keys, JSONL, knob
# ---------------------------------------------------------------------------

def test_event_journal_ring_and_filters():
    j = EventJournal(capacity=4)
    ids = [j.emit("breaker.trip", shard=i % 2, key=str(i))
           for i in range(6)]
    evs = j.last()
    assert len(evs) == 4  # bounded ring keeps the newest
    assert [e.seq for e in evs] == sorted(e.seq for e in evs)  # ordered
    assert all(e.event_id.startswith("ev") for e in evs)
    assert ids[-1] == evs[-1].event_id
    assert {e.shard for e in j.last(shard=1)} == {1}
    assert j.find(ids[-1]) is not None
    assert j.find(ids[0]) is None  # evicted
    assert j.counts() == {"breaker.trip": 4}


def test_event_journal_jsonl_mirror(tmp_path):
    path = str(tmp_path / "events.jsonl")
    j = EventJournal(capacity=8, log_path=path)
    eid = j.emit("slo.burn", tenant="gold", fast_burn=15.0)
    j.emit("wal.rotate", path="seg")
    lines = [json.loads(ln) for ln in open(path).read().splitlines()]
    assert len(lines) == 2
    assert lines[0]["event_id"] == eid
    assert lines[0]["tenant"] == "gold"
    assert lines[0]["attrs"]["fast_burn"] == 15.0
    j.close()


def test_journal_jsonl_failed_write_closes_handle(tmp_path):
    # a full disk drops the mirror handle — but must CLOSE it, not leak
    # the fd to GC timing in the middle of the very storm filling the disk
    path = str(tmp_path / "events.jsonl")
    j = EventJournal(capacity=8, log_path=path)
    j.emit("unit.probe", shard=1)  # opens the handle

    class _Boom:
        closed = False

        def write(self, s):
            raise OSError(28, "No space left on device")

        def close(self):
            self.closed = True

    boom = _Boom()
    j._fh = boom
    eid = j.emit("unit.probe", shard=2)  # write fails, emit still journals
    assert eid is not None and j.find(eid) is not None
    assert boom.closed and j._fh is None


def test_emit_event_knob_off(monkeypatch):
    monkeypatch.setattr(Global, "enable_events", False)
    assert emit_event("breaker.trip", shard=1) is None
    assert get_journal().counts() == {}


# ---------------------------------------------------------------------------
# acceptance: breaker-trip -> failover -> heal as an ordered, shard-
# correlated timeline (the forced sequence of the ISSUE's criterion)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_breaker_failover_heal_event_correlation(world, monkeypatch):
    from wukong_tpu.runtime.recovery import RecoveryManager
    from wukong_tpu.store.persist import clone_gstore

    monkeypatch.setattr(Global, "retry_base_ms", 1)
    monkeypatch.setattr(Global, "retry_max_ms", 2)
    sstore = _sstore(world)
    sstore.replicas = {0: [(1, clone_gstore(sstore.stores[0]))]}
    faults.install(FaultPlan([FaultSpec("dist.shard_fetch", "shard_down",
                                        shard=0)], seed=0))
    # shard_down is non-retryable: one breaker failure per fetch; the
    # default threshold (3) trips on the third fetch. Every fetch still
    # serves from the replica (failover), results complete.
    for _ in range(4):
        out, ok = sstore._fetch_shard(0, lambda g: np.arange(4), "t")
        assert ok
    faults.clear()  # "the dead host is replaced"
    rm = RecoveryManager(lambda: list(sstore.stores), sstore=sstore)
    healed = rm.heal_once(force=True)
    assert healed == [0]

    evs = get_journal().last(shard=0)
    kinds = [e.kind for e in evs]
    assert all(e.shard == 0 for e in evs)
    # the ordered story: ONE failover edge (not one event per fetch —
    # a down primary under load must not churn the ring), the trip, heal
    assert kinds.count("shard.failover") == 1
    assert "shard.failover" in kinds and "breaker.trip" in kinds
    assert "shard.rebuild" in kinds and "shard.heal" in kinds
    assert "breaker.close" in kinds
    assert kinds.index("breaker.trip") < kinds.index("breaker.close")
    # promote closes the breaker, then journals the rebuild + the heal
    assert kinds.index("breaker.close") <= kinds.index("shard.rebuild")
    assert kinds.index("shard.rebuild") <= kinds.index("shard.heal")
    # the journal's seq order IS chronological order
    assert [e.seq for e in evs] == sorted(e.seq for e in evs)
    # /events renders the same filtered timeline
    text, js = render_events(shard=0)
    assert "shard.failover" in text and "breaker.trip" in text
    # the lineage ledger saw the failover and the heal
    rep = get_lineage().report()
    assert rep[0]["last_failover_us"] > 0
    assert rep[0]["last_heal_us"] > 0


# ---------------------------------------------------------------------------
# acceptance: SLO_BURN / LATENCY_REGRESSION dumps reference their
# triggering event id
# ---------------------------------------------------------------------------

def test_slo_burn_dump_references_event_id(tmp_path, monkeypatch):
    from wukong_tpu.obs.slo import SLOSpec, SLOTracker

    monkeypatch.setattr(Global, "trace_dump_dir", str(tmp_path))
    monkeypatch.setattr(Global, "slo_dump_cooldown_s", 3600)
    t = SLOTracker(window=128)
    t.register(SLOSpec("gold", 0.95, 0.0, 0.999))
    tr = QueryTrace(kind="query", tenant="gold")
    tr.finish("ERROR")
    verdicts = [t.observe("gold", 1000, ok=False, trace=tr)
                for _ in range(40)]
    [v] = [v for v in verdicts if v is not None]
    assert v["event_id"]  # the verdict names its journal event
    ev = get_journal().find(v["event_id"])
    assert ev is not None and ev.kind == "slo.burn" and ev.tenant == "gold"
    meta = [m for m in get_recorder().dump_meta if m["reason"] == "SLO_BURN"]
    assert len(meta) == 1 and meta[0]["event_id"] == v["event_id"]
    # the on-disk dump JSON cross-links too
    doc = json.load(open(tmp_path / f"trace_{tr.trace_id}.json"))
    assert doc["event_id"] == v["event_id"]


def test_latency_regression_dump_references_event_id(monkeypatch):
    from wukong_tpu.obs.profile import LatencyAttributor

    monkeypatch.setattr(Global, "attribution_min_samples", 8)
    attr = LatencyAttributor(window=64)

    def fake(total_us):
        tr = QueryTrace(kind="query", tenant="acme")
        tr.finish("SUCCESS")
        tr.t1_us = tr.t0_us + total_us
        return tr

    for _ in range(16):
        assert attr.observe(fake(1_000), "tmpl") is None
    v = attr.observe(fake(100_000), "tmpl")  # >> baseline p95
    assert v is not None and v["reason"] == "P95_DRIFT"
    assert v["event_id"]
    ev = get_journal().find(v["event_id"])
    assert ev is not None and ev.kind == "latency.regression"
    assert get_recorder().dump_meta[-1]["event_id"] == v["event_id"]


def test_auto_dump_journals_trace_dump_event():
    """A dump with no upstream trigger (slow query / failure code) still
    lands one correlated journal entry of its own."""
    tr = QueryTrace(kind="query", tenant="acme")
    tr.finish("SUCCESS")
    get_recorder().dump(tr, "SLOW_QUERY")
    meta = get_recorder().dump_meta[-1]
    assert meta["event_id"]
    ev = get_journal().find(meta["event_id"])
    assert ev is not None and ev.kind == "trace.dump"
    assert ev.attrs["reason"] == "SLOW_QUERY" and ev.tenant == "acme"


# ---------------------------------------------------------------------------
# satellite: flight-recorder dump-dir retention (trace_dump_max)
# ---------------------------------------------------------------------------

def test_trace_dump_dir_retention(tmp_path, monkeypatch):
    monkeypatch.setattr(Global, "trace_dump_dir", str(tmp_path))
    monkeypatch.setattr(Global, "trace_dump_max", 3)
    traces = []
    for _ in range(6):
        tr = QueryTrace(kind="query")
        tr.finish("SUCCESS")
        get_recorder().dump(tr, "SLOW_QUERY")
        traces.append(tr.trace_id)
    names = sorted(os.listdir(tmp_path))
    assert len(names) == 3
    # the newest three survive, the oldest were evicted
    assert names == sorted(f"trace_{t}.json" for t in traces[-3:])
    # 0 = unbounded (the legacy behavior)
    monkeypatch.setattr(Global, "trace_dump_max", 0)
    for _ in range(4):
        tr = QueryTrace(kind="query")
        tr.finish("SUCCESS")
        get_recorder().dump(tr, "SLOW_QUERY")
    assert len(os.listdir(tmp_path)) == 7


# ---------------------------------------------------------------------------
# WAL lifecycle events: rotation + torn tail
# ---------------------------------------------------------------------------

def test_wal_rotation_and_torn_tail_events(tmp_path):
    from wukong_tpu.store.wal import WriteAheadLog

    wal = WriteAheadLog(str(tmp_path), segment_bytes=256)
    for i in range(6):
        wal.append("insert", triples=np.zeros((4, 3), dtype=np.int64),
                   dedup=False)
    wal.close()
    assert get_journal().counts().get("wal.rotate", 0) >= 1
    # tear the tail segment: re-opening truncates AND journals it
    segs = sorted(p for p in os.listdir(tmp_path) if p.endswith(".log"))
    tail = os.path.join(str(tmp_path), segs[-1])
    with open(tail, "r+b") as f:
        f.truncate(os.path.getsize(tail) - 3)
    WriteAheadLog(str(tmp_path), segment_bytes=256)
    torn = get_journal().last(kind="wal.torn_tail")
    assert torn and torn[-1].attrs["where"] == "open"


# ---------------------------------------------------------------------------
# acceptance: the hot-spot drill end to end (advisor + observe-only proof)
# ---------------------------------------------------------------------------

def test_hotspot_drill_advisor_plan(world, proxy, tmp_path):
    """ROADMAP item 3's acceptance fixture: the Zipfian scenario's
    MigrationPlan names the seeded hot shard as top donor, predicts move
    bytes within 25% of the donor's measured checkpoint size, and leaves
    the store bit-untouched (store-version equality)."""
    from wukong_tpu.runtime.emulator import Emulator
    from wukong_tpu.runtime.recovery import RecoveryManager

    sstore = _sstore(world)
    rm = RecoveryManager(lambda: list(sstore.stores), sstore=sstore,
                         ckpt_dir=str(tmp_path))
    ckpt = rm.checkpoint()
    assert get_journal().counts().get("checkpoint.write") == 1
    rep = Emulator(proxy).run_hotspot(n_ops=800, zipf_a=1.6, seed=7,
                                      sstore=sstore)
    assert rep["ranked"][0] == rep["hot"]
    plan = rep["plan"]
    assert plan is not None and rep["plan_donor_is_hot"]
    assert plan["donor_shard"] == rep["hot"]
    assert rep["store_untouched"]
    # predicted bytes come from the measured checkpoint part size and
    # land within the 25% acceptance band of the actual file
    assert plan["bytes_source"] == "checkpoint"
    from wukong_tpu.store.persist import checkpoint_part_path

    actual = os.path.getsize(checkpoint_part_path(ckpt, rep["hot"]))
    assert abs(plan["predicted_move_bytes"] - actual) <= 0.25 * actual
    # the band's real teeth: the never-checkpointed fallback (the live
    # store's memory_bytes estimate) must ALSO stay within 25% of what
    # a checkpoint actually measures — the checkpoint path is exact by
    # construction, the estimate path is the one that can drift
    est = sstore.stores[rep["hot"]].memory_bytes()
    assert abs(est - actual) <= 0.25 * actual
    # the recipient is a host that does not already hold the donor
    assert plan["recipient_host"] != plan["donor_shard"]
    # the advisor's read surface is the declared placement input
    assert plan["inputs"]["metric"] == "wukong_shard_heat_fetches_total"
    # /plan (no fresh sweep) surfaces the scenario's plan
    text, js = render_plan(advise=False)
    assert f"donor shard       {plan['donor_shard']}" in text
    assert js["status"]["plan"]["plan_id"] == plan["plan_id"]


def test_advisor_balanced_emits_no_plan(world):
    ts = MetricsTSDB(interval_s=1, retention_s=600)
    sstore = _sstore(world)
    adv = PlacementAdvisor(sstore=sstore, tsdb=ts,
                           lineage=ShardLineage())
    ts.sample_once()
    for i in range(4):
        for _ in range(10):
            sstore._fetch_shard(i, lambda g: np.arange(8), "t")
    # a RETIRED world's shard label (9 does not exist in this 4-shard
    # store) must not skew the live topology's imbalance score
    get_registry().counter("wukong_shard_heat_fetches_total",
                           labels=("shard", "kind")).labels(
        shard=9, kind="primary").inc(500)
    ts.sample_once()
    assert adv.advise_once() is None
    assert adv.status()["decision"] == "balanced"
    assert adv.status()["imbalance"] < 2.0


def test_advisor_no_samples_no_data(world):
    adv = PlacementAdvisor(sstore=_sstore(world),
                           tsdb=MetricsTSDB(), lineage=ShardLineage())
    assert adv.advise_once() is None
    assert adv.status()["decision"] == "no_data"


def test_advisor_no_store_refuses_stale_labels(world):
    # heat labels outlive the stores that minted them: an on-demand sweep
    # (/plan?sweep=1, the console verb) after the world retired must not
    # turn the dead world's residual window rates into a MigrationPlan
    ts = MetricsTSDB(interval_s=1, retention_s=600)
    adv = PlacementAdvisor(tsdb=ts, lineage=ShardLineage())
    sstore = _sstore(world)
    ts.sample_once()
    for _ in range(50):
        sstore._fetch_shard(3, lambda g: np.arange(8), "t")
    ts.sample_once()
    del sstore  # the world retires; its label rates stay in the window
    assert adv.advise_once() is None
    assert adv.status()["decision"] == "no_store"


def test_gstore_digest_detects_raw_array_write(world):
    # the hotspot drill's observe-only proof: a raw in-place write (no
    # version bump) must flip the digest, and restoring it must restore
    # the digest (deterministic walk)
    from wukong_tpu.store.persist import gstore_digest

    g = build_partition(world["triples"], 0, 4)
    d0 = gstore_digest(g)
    assert gstore_digest(g) == d0
    arr = next(a for a in g.index.values() if a.size)
    arr[0] += 1
    assert gstore_digest(g) != d0
    arr[0] -= 1
    assert gstore_digest(g) == d0


def test_advisor_colocated_donor_on_overloaded_host():
    # once a control plane co-locates shards, the trigger is HOST
    # imbalance — the donor must come from the overloaded host, not be
    # the globally hottest shard (which can sit on a healthy host)
    lin = ShardLineage()
    lin.note_placement(0, 0)
    lin.note_placement(1, 0)  # host 0 serves shards 0+1: 60/s total
    lin.note_placement(2, 1)  # host 1 serves the hottest SHARD: 31/s
    lin.note_placement(3, 2)
    lin.note_placement(4, 3)
    adv = PlacementAdvisor(lineage=lin)
    rates = {0: 30.0, 1: 30.0, 2: 31.0, 3: 0.0, 4: 0.0}
    decision, imb, plan = adv._decide(rates, 300.0, lin)
    assert decision == "planned"
    assert plan.donor_shard in (0, 1)  # NOT shard 2
    assert plan.recipient_host not in (0,)  # off the overloaded host
    assert plan.imbalance_after < plan.imbalance_before


def test_migration_plan_fields_match_registry():
    assert set(MIGRATION_PLAN_FIELDS) == {
        f.name for f in dataclasses.fields(MigrationPlan)}


# ---------------------------------------------------------------------------
# /healthz readiness split + the observatory endpoints
# ---------------------------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(port, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5).read().decode()


def test_observatory_endpoints_and_healthz(world, monkeypatch):
    from wukong_tpu.obs import (
        maybe_start_metrics_http,
        register_health_source,
        stop_metrics_http,
    )

    get_tsdb().sample_once()
    get_tsdb().sample_once()
    emit_event("shard.degraded", shard=2)
    port = _free_port()
    assert maybe_start_metrics_http(port=port) is not None
    try:
        assert "COUNTER RATES" in _get(port, "/history")
        js = json.loads(_get(port, "/history.json?k=4"))
        assert js["samples"] >= 2
        body = _get(port, "/events")
        assert "shard.degraded" in body
        ejs = json.loads(_get(port, "/events.json"))
        assert ejs["counts"].get("shard.degraded") == 1
        assert "wukong-plan" in _get(port, "/plan")
        # healthz: live + ready by default (JSON body, 200)
        h = json.loads(_get(port, "/healthz"))
        assert h["live"] is True and h["ready"] is True
        # a degraded probe flips readiness; liveness stays 200 until the
        # knob opts into load-balancer drain semantics
        register_health_source("test-probe", lambda: {"bad": 1})
        try:
            h = json.loads(_get(port, "/healthz"))
            assert h["ready"] is False
            assert h["degraded"]["test-probe"] == {"bad": 1}
            monkeypatch.setattr(Global, "health_ready_503", True)
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(port, "/healthz")
            assert ei.value.code == 503
            assert json.loads(ei.value.read())["ready"] is False
        finally:
            register_health_source("test-probe", lambda: None)
    finally:
        stop_metrics_http()


def test_healthz_reports_open_breakers(world, monkeypatch):
    from wukong_tpu.obs import health_report
    from wukong_tpu.runtime.monitor import Monitor

    monkeypatch.setattr(Global, "retry_base_ms", 1)
    monkeypatch.setattr(Global, "retry_max_ms", 2)
    sstore = _sstore(world)
    mon = Monitor()
    mon.attach_breaker("dist.shard", sstore.breaker)
    faults.install(FaultPlan([FaultSpec("dist.shard_fetch", "shard_down",
                                        shard=1)], seed=0))
    for _ in range(4):  # trips the per-shard breaker (threshold 3)
        sstore._fetch_shard(1, lambda g: np.arange(4), "t")
    rep = health_report()
    assert rep["live"] and not rep["ready"]
    assert rep["degraded"]["open_breakers"] >= 1


# ---------------------------------------------------------------------------
# satellite: concurrent httpd scrapes while the serving loop runs
# ---------------------------------------------------------------------------

def test_concurrent_scrapes_during_serving(world, proxy):
    """Parallel /metrics, /top, /slo, /history, /events scrapes while
    closed-loop serving threads run: crash-free, every response 200, and
    the module's lockdep fixture asserts no ordering findings."""
    from wukong_tpu.obs import maybe_start_metrics_http, stop_metrics_http
    from wukong_tpu.types import OUT

    ss, g = world["ss"], world["g"]
    pid = ss.str2id(f"<{UB}advisor>")
    anchors = np.asarray(g.get_index(pid, OUT))[:8]
    texts = [f"SELECT ?s WHERE {{ ?s <{UB}advisor> "
             f"{ss.id2str(int(a))} . }}" for a in anchors]
    port = _free_port()
    assert maybe_start_metrics_http(port=port) is not None
    stop = threading.Event()
    errors: list = []

    def serve(k):
        i = 0
        while not stop.is_set():
            try:
                proxy.serve_query(texts[i % len(texts)], blind=True)
            except Exception as e:
                errors.append(("serve", repr(e)))
            i += 1

    def scrape(path):
        n = 0
        while not stop.is_set():
            try:
                _get(port, path)
            except Exception as e:
                errors.append((path, repr(e)))
            n += 1
            get_tsdb().sample_once()

    paths = ["/metrics", "/top", "/slo", "/history", "/events"]
    threads = ([threading.Thread(target=serve, args=(k,), daemon=True)
                for k in range(2)]
               + [threading.Thread(target=scrape, args=(p,), daemon=True)
                  for p in paths])
    try:
        for t in threads:
            t.start()
        time.sleep(1.5)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        stop_metrics_http()
    assert errors == [], errors[:4]
    # bounded memory: the ring's count cap holds even though the scrape
    # threads sampled far faster than the nominal interval
    assert len(get_tsdb()) <= int(
        Global.tsdb_retention_s / max(Global.tsdb_interval_s, 1)) + 8


# ---------------------------------------------------------------------------
# Monitor lines + console verbs
# ---------------------------------------------------------------------------

def test_monitor_events_and_placement_lines():
    from wukong_tpu.runtime.monitor import Monitor

    mon = Monitor()
    assert mon.events_lines() == []  # quiet while nothing happened
    assert mon.placement_lines() == []
    emit_event("breaker.trip", shard=3, key="3")
    emit_event("shard.failover", shard=3, replica=1)
    [line] = mon.events_lines()
    assert line.startswith("Events[") and "shard.failover:1" in line
    adv = get_advisor()
    with adv._lock:
        adv._last_plan = MigrationPlan(
            plan_id="mp1", t_us=1, donor_shard=3, recipient_host=1,
            predicted_move_bytes=2 << 20, bytes_source="checkpoint",
            donor_rate_per_s=9.0, mean_rate_per_s=3.0,
            imbalance_before=3.0, imbalance_after=1.5, window_s=300.0)
    [pl] = mon.placement_lines()
    assert "donor shard 3 -> host 1" in pl and "2.0 MiB" in pl


def test_console_config_flip_starts_sampler(proxy, monkeypatch):
    """enable_tsdb is runtime-mutable BOTH ways: flipping it on via the
    console's `config -s` must start the sampler thread, not wait for a
    process restart (the running-thread direction idles per tick)."""
    from wukong_tpu.obs import tsdb as tsdb_mod
    from wukong_tpu.obs.tsdb import stop_tsdb
    from wukong_tpu.runtime.console import Console

    monkeypatch.setattr(Global, "enable_tsdb", False)
    stop_tsdb()
    assert tsdb_mod._sampler is None
    con = Console(proxy)
    con.run_command("config -s enable_tsdb true")
    try:
        assert Global.enable_tsdb is True
        assert tsdb_mod._sampler is not None  # started by the flip
    finally:
        stop_tsdb()


def test_console_verbs(proxy, capsys):
    from wukong_tpu.runtime.console import Console

    get_tsdb().sample_once()
    get_tsdb().sample_once()
    emit_event("checkpoint.write", parts=4)
    con = Console(proxy)
    con.run_command("history -k 4")
    con.run_command("events")
    con.run_command("plan -n")
    out = capsys.readouterr().out
    assert "wukong-history" in out
    assert "checkpoint.write" in out
    assert "wukong-plan" in out
    con.run_command("events -j")
    assert "counts" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# the placement-telemetry analysis gate (pos/neg fixtures)
# ---------------------------------------------------------------------------

def test_placement_telemetry_gate_fixtures(tmp_path):
    from wukong_tpu.analysis import run_analysis

    def write(tree: dict) -> str:
        root = tmp_path / "pkg"
        for rel, src in tree.items():
            p = root / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(src)
        return str(root)

    bad = write({
        "obs/heat.py": "PLACEMENT_INPUTS = {'fetches': 'wukong_ok_total'}\n",
        "obs/placement.py": (
            "MIGRATION_PLAN_FIELDS = ('donor', 'stale_entry')\n"
            "class MigrationPlan:\n"
            "    donor: int\n"
            "    extra: int\n"
            "def advise(ts):\n"
            "    return ts.rate_by_label('wukong_rogue_total', 'shard')\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self.plans = {}\n"
            "        self.lock = make_lock('placement.x')\n")})
    out = run_analysis(bad, plugins=["placement-telemetry"])
    msgs = "\n".join(str(v) for v in out)
    assert "stale_entry" in msgs      # registry entry with no field
    assert "'extra'" in msgs          # field missing from the registry
    assert "wukong_rogue_total" in msgs  # undeclared trend read
    assert "A.plans" in msgs          # unannotated shared structure
    assert "placement.x" in msgs      # undeclared leaf lock

    good = write({
        "obs/heat.py": "PLACEMENT_INPUTS = {'fetches': 'wukong_ok_total'}\n",
        "obs/placement.py": (
            "MIGRATION_PLAN_FIELDS = ('donor',)\n"
            "declare_leaf('placement.x')\n"
            "class MigrationPlan:\n"
            "    donor: int\n"
            "def advise(ts):\n"
            "    return ts.rate_by_label('wukong_ok_total', 'shard')\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self.plans = {}  # guarded by: _lock\n"
            "        self.lock = make_lock('placement.x')\n")})
    assert run_analysis(good, plugins=["placement-telemetry"]) == []


def test_repo_placement_gate_clean():
    from wukong_tpu.analysis import run_analysis

    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "wukong_tpu")
    assert run_analysis(pkg, plugins=["placement-telemetry"]) == []
