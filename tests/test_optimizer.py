"""Type-centric stats + cost-based planner on LUBM-1."""

import glob
import os

import numpy as np
import pytest

from bgp_oracle import TripleIndex, eval_bgp
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.lubm import P, T, VirtualLubmStrings, generate_lubm
from wukong_tpu.planner.optimizer import Planner, make_planner
from wukong_tpu.planner.stats import Stats
from wukong_tpu.sparql.parser import Parser
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.types import IN, OUT, TYPE_ID

BASIC = "/root/reference/scripts/sparql_query/lubm/basic"


@pytest.fixture(scope="module")
def world():
    triples, lay = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    stats = Stats.generate(triples)
    return triples, lay, g, ss, stats


def test_tyscount_exact(world):
    triples, lay, g, ss, stats = world
    c = lay.counts
    assert stats.tyscount[T["FullProfessor"]] == int(c.n_fp.sum())
    assert stats.tyscount[T["UndergraduateStudent"]] == int(c.n_ug.sum())
    assert stats.tyscount[T["Department"]] == c.D


def test_pstype_and_fine_type(world):
    triples, lay, g, ss, stats = world
    # every worksFor subject is faculty; every object a Department
    h = stats.pstype[P["worksFor"]]
    fac_types = {T["FullProfessor"], T["AssociateProfessor"],
                 T["AssistantProfessor"], T["Lecturer"]}
    assert set(h) <= fac_types
    assert set(stats.potype[P["worksFor"]]) == {T["Department"]}
    # fine_type: FullProfessor --worksFor--> Department, fanout 1
    ft = stats.fine_type[(T["FullProfessor"], P["worksFor"], OUT)]
    assert set(ft) == {T["Department"]}
    assert ft[T["Department"]] == stats.tyscount[T["FullProfessor"]]


def test_stats_persistence(world, tmp_path):
    triples, lay, g, ss, stats = world
    path = str(tmp_path / "statfile")
    stats.save(path)
    st2 = Stats.load(path)
    assert st2.tyscount == stats.tyscount
    assert st2.pstype == stats.pstype
    assert st2.fine_type == stats.fine_type
    assert np.array_equal(st2.vtype, stats.vtype)


QUERIES = [f for f in sorted(glob.glob(f"{BASIC}/lubm_q*")) if os.path.isfile(f)]


@pytest.mark.parametrize("qfile", QUERIES,
                         ids=[os.path.basename(f) for f in QUERIES])
def test_planner_plans_are_correct(world, qfile):
    """Cost-based plans produce oracle-correct results for the whole suite."""
    triples, lay, g, ss, stats = world
    idx = TripleIndex(triples)
    planner = Planner(stats)
    q = Parser(ss).parse(open(qfile).read())
    raw = [(p.subject, p.predicate, p.object) for p in q.pattern_group.patterns]
    assert planner.generate_plan(q)
    eng = CPUEngine(g, ss)
    eng.execute(q)
    assert q.result.status_code == 0, q.result.status_code
    got = sorted(map(tuple, q.result.table.tolist()))
    want = sorted(eval_bgp(idx, raw, q.result.required_vars))
    assert got == want, f"{qfile}: {len(got)} vs {len(want)}"


def test_planner_picks_selective_start(world):
    """q4: const dept start (10 rows) must beat the FullProfessor type index."""
    triples, lay, g, ss, stats = world
    planner = Planner(stats)
    q = Parser(ss).parse(open(f"{BASIC}/lubm_q4").read())
    planner.generate_plan(q)
    first = q.pattern_group.patterns[0]
    assert first.subject >= (1 << 17)  # starts from the const department


def test_planner_q2_starts_from_course_index(world):
    triples, lay, g, ss, stats = world
    planner = Planner(stats)
    q = Parser(ss).parse(open(f"{BASIC}/lubm_q2").read())
    planner.generate_plan(q)
    first = q.pattern_group.patterns[0]
    assert first.subject == T["Course"] and first.predicate == TYPE_ID


def test_make_planner_with_statfile(world, tmp_path):
    triples, lay, g, ss, stats = world
    path = str(tmp_path / "statfile")
    p1 = make_planner(triples, path)
    assert os.path.exists(path + ".npz")
    p2 = make_planner(None, path)  # loads without triples
    assert p2.stats.tyscount == p1.stats.tyscount


def test_store_load_stat_console(world, tmp_path):
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.runtime.console import Console
    from wukong_tpu.runtime.proxy import Proxy

    triples, lay, g, ss, stats = world
    proxy = Proxy(g, ss, CPUEngine(g, ss))
    proxy.planner = Planner(stats)
    c = Console(proxy, stats_path=str(tmp_path / "statfile"))
    assert c.run_command("store-stat")
    assert (tmp_path / "statfile.npz").exists()
    proxy.planner = None
    assert c.run_command("load-stat")
    assert proxy.planner is not None
    assert proxy.planner.stats.tyscount == stats.tyscount


def test_planner_readonly_statfile(world):
    from wukong_tpu.planner.optimizer import make_planner

    triples, lay, g, ss, stats = world
    p = make_planner(triples, "/proc/definitely/not/writable/statfile")
    assert p.stats.tyscount  # degraded to in-memory stats, no crash


# ---------------------------------------------------------------------------
# plan quality: joint type table vs the osdi16 manual plans (VERDICT #4)
# ---------------------------------------------------------------------------


def _peak_intermediate(g, ss, q):
    """Execute pattern-by-pattern, tracking the peak intermediate row count."""
    from wukong_tpu.engine.cpu import CPUEngine

    eng = CPUEngine(g, ss)
    peak = 0
    while not q.done_patterns():
        eng._execute_one_pattern(q)
        peak = max(peak, q.result.nrows)
    return peak


@pytest.mark.parametrize("qn", ["lubm_q1", "lubm_q2", "lubm_q3", "lubm_q7"])
def test_plan_quality_vs_osdi16(qn):
    """The cost-based plan's peak intermediate must be within 1.5x of the
    reference's hand-tuned osdi16 plan (planner.hpp joint type table)."""
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.planner.plan_file import set_plan
    from wukong_tpu.planner.stats import Stats
    from wukong_tpu.sparql.parser import Parser
    from wukong_tpu.store.gstore import build_partition

    basic = "/root/reference/scripts/sparql_query/lubm/basic"
    triples, _ = generate_lubm(1, seed=42)
    ss = VirtualLubmStrings(1, seed=42)
    g = build_partition(triples, 0, 1)
    stats = Stats.generate(triples)
    text = open(f"{basic}/{qn}").read()

    qm = Parser(ss).parse(text)
    assert set_plan(qm.pattern_group, open(f"{basic}/osdi16_plan/{qn}.fmt").read())
    manual_peak = _peak_intermediate(g, ss, qm)

    qp = Parser(ss).parse(text)
    assert Planner(stats).generate_plan(qp)
    planner_peak = _peak_intermediate(g, ss, qp)

    # same final answer either way
    CPUEngine(g, ss)._final_process(qm)
    CPUEngine(g, ss)._final_process(qp)
    assert sorted(map(tuple, qm.result.table.tolist())) == \
        sorted(map(tuple, qp.result.table.tolist()))
    assert planner_peak <= manual_peak * 1.5 + 64, (
        f"{qn}: planner peak {planner_peak} vs osdi16 {manual_peak}")


def test_planner_const_subject_mid_plan():
    """Const-subject membership mid-plan must be estimable (not a silent
    heuristic fallback)."""
    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.planner.stats import Stats
    from wukong_tpu.sparql.parser import Parser

    triples, lay = generate_lubm(1, seed=42)
    ss = VirtualLubmStrings(1, seed=42)
    stats = Stats.generate(triples)
    fp0 = ss.id2str(int(lay.fac_base[0]))
    q = Parser(ss).parse(f"""
        PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
        PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
        SELECT ?X WHERE {{
            ?X rdf:type ub:Course .
            {fp0} ub:teacherOf ?X .
        }}""")
    pl = Planner(stats)
    # _plan_group must not throw (generate_plan would silently fall back)
    best = pl._plan_group(q.pattern_group)
    assert best is not None


def test_planner_k2c_untyped_anchor_not_free():
    """k2c selectivity over untyped rows must use global density, not 0."""
    from wukong_tpu.loader.lubm import generate_lubm
    from wukong_tpu.planner.optimizer import Planner, _State
    from wukong_tpu.planner.stats import Stats
    from wukong_tpu.loader.lubm import P
    from wukong_tpu.sparql.ir import Pattern
    from wukong_tpu.types import OUT

    triples, _ = generate_lubm(1, seed=42)
    stats = Stats.generate(triples)
    pl = Planner(stats)
    state = _State(rows=1000.0, vars=(-1,), ttab={(0,): 1000.0},
                   cost=0.0, plan=[(None, None)])
    # membership against an arbitrary const under a real predicate
    const = int(triples[triples[:, 1] == P["memberOf"]][0, 2])
    step = pl._estimate_step(state, Pattern(-1, P["memberOf"], OUT, const))
    assert step is not None
    pe = stats.pred_edges[P["memberOf"]]
    sp = stats.distinct_subj[P["memberOf"]]
    op = stats.distinct_obj[P["memberOf"]]
    want = 1000.0 * min((pe / op) / sp, 1.0)
    assert abs(step.rows - want) / max(want, 1e-9) < 1e-6


def test_empty_query_shortcircuit_q3(world, monkeypatch):
    """q3 (UndergraduateStudent with undergraduateDegreeFrom) is provably
    empty in LUBM: only GraduateStudents carry that predicate. The planner
    must prove it (reference planner.hpp:1505-1509 "identified empty result
    query") and engines must skip execution — round-2 bench spent 169 ms
    producing q3's zero rows."""
    triples, lay, g, ss, stats = world
    planner = Planner(stats)
    q = Parser(ss).parse(open(f"{BASIC}/lubm_q3").read())
    planner.generate_plan(q)
    assert q.planner_empty
    # non-empty queries must NOT be marked (q1/q2 have results at LUBM-1)
    for qn in ("lubm_q1", "lubm_q2", "lubm_q4", "lubm_q7"):
        qq = Parser(ss).parse(open(f"{BASIC}/{qn}").read())
        planner.generate_plan(qq)
        assert not qq.planner_empty, qn

    from wukong_tpu.config import Global

    # soundness first: the full chain (short-circuit off) agrees
    eng = CPUEngine(g, ss)
    Global.enable_empty_shortcircuit = False
    try:
        q2 = Parser(ss).parse(open(f"{BASIC}/lubm_q3").read())
        planner.generate_plan(q2)
        eng.execute(q2)
        assert q2.result.get_row_num() == 0
    finally:
        Global.enable_empty_shortcircuit = True

    # structural proof that execution is skipped (a wall-clock bound would
    # flake on loaded CI hosts): the pattern machinery must never run
    def _boom(self, _q):
        raise AssertionError("short-circuit did not engage")

    monkeypatch.setattr(CPUEngine, "_execute_patterns", _boom)
    eng.execute(q)
    assert q.result.status_code == 0
    assert q.result.get_row_num() == 0
    assert q.pattern_step == len(q.pattern_group.patterns)


def test_empty_shortcircuit_batch_paths(world):
    """The batched device paths return zero counts without staging."""
    from wukong_tpu.engine.tpu import TPUEngine

    triples, lay, g, ss, stats = world
    planner = Planner(stats)
    eng = TPUEngine(g, ss, stats=stats)
    q = Parser(ss).parse(open(f"{BASIC}/lubm_q3").read())
    planner.generate_plan(q)
    assert q.planner_empty
    q.result.blind = True
    counts = eng.execute_batch_index(q, 8)
    assert counts.shape == (8,) and int(np.sum(counts)) == 0
