import glob

import pytest

from wukong_tpu.loader.lubm import P, T, VirtualLubmStrings
from wukong_tpu.sparql.ir import FilterType
from wukong_tpu.sparql.parser import Parser, SPARQLSyntaxError
from wukong_tpu.types import OUT, PREDICATE_ID, TYPE_ID
from wukong_tpu.utils.errors import ErrorCode, WukongError

LUBM_Q4 = """
PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
SELECT ?X ?Y1 ?Y2 ?Y3 WHERE {
    ?X  ub:worksFor  <http://www.Department0.University0.edu>  .
    ?X  rdf:type  ub:FullProfessor  .
    ?X  ub:name  ?Y1  .
    ?X  ub:emailAddress  ?Y2  .
    ?X  ub:telephone  ?Y3  .
}
"""


@pytest.fixture(scope="module")
def ss():
    return VirtualLubmStrings(1, seed=42)


@pytest.fixture(scope="module")
def parser(ss):
    return Parser(ss)


def test_parse_q4(parser, ss):
    q = parser.parse(LUBM_Q4)
    pats = q.pattern_group.patterns
    assert len(pats) == 5
    d0 = ss.str2id("<http://www.Department0.University0.edu>")
    assert pats[0].subject == -1 and pats[0].predicate == P["worksFor"]
    assert pats[0].object == d0 and pats[0].direction == OUT
    assert pats[1].predicate == TYPE_ID and pats[1].object == T["FullProfessor"]
    assert q.result.required_vars == [-1, -2, -3, -4]
    assert q.result.nvars == 4


def test_parse_all_reference_lubm_queries(ss):
    """Every basic LUBM query from the reference suite parses."""
    files = sorted(glob.glob("/root/reference/scripts/sparql_query/lubm/basic/lubm_q*"))
    files = [f for f in files if "plan" not in f]
    assert len(files) == 12
    for f in files:
        p = Parser(ss)
        q = p.parse(open(f).read())
        assert q.pattern_group.patterns


def test_variable_predicate(parser):
    q = Parser(parser.str_server).parse(
        "SELECT ?X ?P WHERE { ?X ?P <http://www.Department0.University0.edu> . }")
    pat = q.pattern_group.patterns[0]
    assert pat.predicate < 0  # variable predicate


def test_predicate_keyword(ss):
    q = Parser(ss).parse(
        "PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>\n"
        "SELECT ?X WHERE { ?X __PREDICATE__ ub:subOrganizationOf . }")
    pat = q.pattern_group.patterns[0]
    assert pat.predicate == PREDICATE_ID
    assert pat.object == P["subOrganizationOf"]


def test_union_optional_filter(ss):
    q = Parser(ss).parse("""
        PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
        PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
        SELECT DISTINCT ?X WHERE {
            { ?X rdf:type ub:Course . } UNION { ?X rdf:type ub:GraduateCourse . }
            OPTIONAL { ?X ub:name ?N . }
            FILTER ( bound(?N) && ?X != ?N )
        } ORDER BY DESC(?X) LIMIT 10 OFFSET 2
        """)
    assert len(q.pattern_group.unions) == 2
    assert len(q.pattern_group.optional) == 1
    assert len(q.pattern_group.filters) == 1
    f = q.pattern_group.filters[0]
    assert f.type == FilterType.And
    assert f.arg1.type == FilterType.Builtin_bound
    assert q.distinct and q.limit == 10 and q.offset == 2
    assert q.orders[0].descending


def test_template_placeholder(ss):
    tmpl = Parser(ss).parse_template("""
        PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
        PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
        SELECT ?X WHERE {
            ?X ub:takesCourse %ub:GraduateCourse .
            ?X rdf:type ub:GraduateStudent .
        }""")
    assert tmpl.ptypes == [T["GraduateCourse"]]
    assert tmpl.pos == [(0, "object")]
    import numpy as np

    tmpl.candidates = [np.array([12345, 67890])]
    q = tmpl.instantiate(np.random.default_rng(0))
    assert q.pattern_group.patterns[0].object in (12345, 67890)


def test_syntax_errors(ss):
    with pytest.raises(SPARQLSyntaxError):
        Parser(ss).parse("SELECT WHERE { }")
    with pytest.raises(SPARQLSyntaxError):
        Parser(ss).parse("SELECT ?X WHERE { ?X }")
    with pytest.raises(WukongError) as e:
        Parser(ss).parse("SELECT ?X WHERE { ?X <http://unknown.pred> ?Y . }")
    assert e.value.code == ErrorCode.UNKNOWN_SUB


def test_wrong_suite_parse_behavior(ss):
    """The reference 'wrong' suite: only `syntax` fails at parse time; q1-q4
    parse fine and fail later at plan/execution (wrong/README.md)."""
    base = "/root/reference/scripts/sparql_query/lubm/wrong"
    with pytest.raises(SPARQLSyntaxError):
        Parser(ss).parse(open(f"{base}/syntax").read())
    for name in ("q1", "q2", "q3", "q4"):
        q = Parser(ss).parse(open(f"{base}/{name}").read())
        assert q.pattern_group.patterns
