"""GStore persistence round-trip (bench depends on the store cache)."""

import numpy as np

from wukong_tpu.loader.lubm import generate_lubm, generate_lubm_attrs
from wukong_tpu.store.checker import check_partition
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.store.persist import load_gstore, save_gstore


def test_gstore_roundtrip(tmp_path):
    triples, _ = generate_lubm(1, seed=13)
    attrs = generate_lubm_attrs(1, seed=13)
    g = build_partition(triples, 0, 2, attr_triples=attrs)
    path = str(tmp_path / "p0")
    save_gstore(g, path)
    g2 = load_gstore(path)
    assert g2.sid == g.sid and g2.num_workers == g.num_workers
    assert set(g2.segments) == set(g.segments)
    for k in g.segments:
        assert np.array_equal(g2.segments[k].keys, g.segments[k].keys)
        assert np.array_equal(g2.segments[k].offsets, g.segments[k].offsets)
        assert np.array_equal(g2.segments[k].edges, g.segments[k].edges)
    assert set(g2.index) == set(g.index)
    for k in g.index:
        assert np.array_equal(g2.index[k], g.index[k])
    assert g2.type_ids == g.type_ids
    assert set(g2.vp) == set(g.vp)
    for d in g.vp:
        assert np.array_equal(g2.vp[d].keys, g.vp[d].keys)
        assert np.array_equal(g2.vp[d].edges, g.vp[d].edges)
    assert np.array_equal(g2.v_set, g.v_set)
    assert set(g2.attrs) == set(g.attrs)
    for a in g.attrs:
        assert np.array_equal(g2.attrs[a].keys, g.attrs[a].keys)
        assert np.array_equal(g2.attrs[a].values, g.attrs[a].values)
        assert g2.attrs[a].type == g.attrs[a].type
    assert check_partition(g2) == []


def test_loaded_store_queries_identically(tmp_path):
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.loader.lubm import VirtualLubmStrings
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.sparql.parser import Parser

    triples, _ = generate_lubm(1, seed=13)
    g = build_partition(triples, 0, 1)
    path = str(tmp_path / "p0")
    save_gstore(g, path)
    g2 = load_gstore(path)
    ss = VirtualLubmStrings(1, seed=13)
    text = open("/root/reference/scripts/sparql_query/lubm/basic/lubm_q4").read()
    rows = []
    for store in (g, g2):
        q = Parser(ss).parse(text)
        heuristic_plan(q)
        CPUEngine(store, ss).execute(q)
        assert q.result.status_code == 0
        rows.append(sorted(map(tuple, q.result.table.tolist())))
    assert rows[0] == rows[1]
