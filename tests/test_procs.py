"""Multi-process data plane tests (PR 20).

Three layers, cheapest first:

- **Framing goldens** — the wire protocol's frame codec and message
  registry, exercised as pure functions (no sockets): roundtrip, the
  torn-tail contract (only the unacknowledged trailing message drops),
  structured FRAME_TOO_LARGE / TRANSPORT_CORRUPT errors.
- **Wire serving in-process** — a real TCP exchange against the worker's
  serve loop run in a thread (deterministic chaos on the
  transport.connect/send/recv fault sites, structured error propagation,
  byte-identity of every op vs its loopback execution).
- **Process supervision** — real spawn-context workers: checkpoint boot,
  WAL-tail replay, digest-gated peering, SIGKILL + restart recovery, the
  heartbeat failure detector, and the emulator's kill-a-process drill.
"""

import random
import socket
import threading
import time

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.runtime import faults
from wukong_tpu.runtime.faults import FaultPlan, FaultSpec
from wukong_tpu.runtime.transport import (
    FRAME_MAGIC,
    MESSAGE_REGISTRY,
    OP_HANDLERS,
    FrameDecoder,
    LoopbackTransport,
    SocketTransport,
    decode_frames,
    encode_frame,
    make_transport,
    pack_error,
    pack_message,
    pack_reply,
    run_op,
    unpack_message,
    unpack_reply,
)
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.store.persist import gstore_digest
from wukong_tpu.types import IN, OUT
from wukong_tpu.utils.errors import (
    ErrorCode,
    FrameTooLarge,
    RetryExhausted,
    TransportCorrupt,
    WukongError,
)

pytestmark = pytest.mark.proc


@pytest.fixture(autouse=True, scope="module")
def _lockdep_checked():
    """The whole multi-process suite runs under the lockdep runtime
    checker: transport per-connection locks and the supervisor/worker
    state locks are declared leaves — teardown asserts no order cycles
    and no leaf inversions were recorded by any drill."""
    from wukong_tpu.analysis import lockdep

    lockdep.install(True)
    yield
    try:
        assert lockdep.cycles() == [], lockdep.cycles()
        assert lockdep.leaf_violations() == [], lockdep.leaf_violations()
    finally:
        lockdep.install(False)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# a tiny partitioned world (numpy-only — workers must not need jax)
# ---------------------------------------------------------------------------

D = 4


def _triples():
    rng = np.random.default_rng(7)
    n = 400
    s = rng.integers(1000, 1400, size=n)
    p = rng.integers(2, 6, size=n)
    o = rng.integers(1000, 1400, size=n)
    return np.stack([s, p, o], axis=1).astype(np.int64)


@pytest.fixture(scope="module")
def stores():
    t = _triples()
    return [build_partition(t, i, D) for i in range(D)]


@pytest.fixture(scope="module")
def g0(stores):
    return stores[0]


# ---------------------------------------------------------------------------
# framing goldens (pure functions, no sockets)
# ---------------------------------------------------------------------------

def test_frame_roundtrip():
    payloads = [b"", b"x", b"hello wire" * 100]
    buf = b"".join(encode_frame(p) for p in payloads)
    out, consumed = decode_frames(buf)
    assert out == payloads
    assert consumed == len(buf)


def test_torn_frame_drops_only_unacknowledged_message():
    f1, f2 = encode_frame(b"first"), encode_frame(b"second-message")
    for cut in range(1, len(f2)):
        out, consumed = decode_frames(f1 + f2[:cut])
        assert out == [b"first"]  # every byte before the tear parses
        assert consumed == len(f1)  # ... and the torn tail stays buffered
    # completing the tail recovers the message: nothing acknowledged lost
    dec = FrameDecoder()
    assert dec.feed(f1 + f2[:5]) == [b"first"]
    assert dec.feed(f2[5:]) == [b"second-message"]


def test_frame_decoder_byte_at_a_time():
    frames = [encode_frame(b"a" * 37), encode_frame(b""), encode_frame(b"z")]
    dec = FrameDecoder()
    got = []
    for b in b"".join(frames):
        got += dec.feed(bytes([b]))
    assert got == [b"a" * 37, b"", b"z"]


def test_bad_magic_is_structured_corruption():
    with pytest.raises(TransportCorrupt) as ei:
        decode_frames(b"XXXX" + encode_frame(b"p")[4:])
    assert ei.value.code == ErrorCode.TRANSPORT_CORRUPT


def test_crc_mismatch_is_structured_corruption():
    f = bytearray(encode_frame(b"payload-bytes"))
    f[-1] ^= 0xFF  # flip one payload byte of a COMPLETE frame
    with pytest.raises(TransportCorrupt):
        decode_frames(bytes(f))


def test_oversized_frame_raises_structured_error_naming_the_limit():
    # encode side: the sender refuses what the receiver would refuse
    with pytest.raises(FrameTooLarge) as ei:
        encode_frame(b"x" * 100, max_bytes=64)
    assert ei.value.code == ErrorCode.FRAME_TOO_LARGE
    assert "transport_max_frame_mb" in str(ei.value)
    # decode side: a hostile/corrupt declared length is refused up front
    frame = encode_frame(b"y" * 100)
    with pytest.raises(FrameTooLarge) as ei:
        decode_frames(frame, max_bytes=64)
    assert "transport_max_frame_mb" in str(ei.value)
    # the knob is the default limit for both sides
    old = Global.transport_max_frame_mb
    Global.transport_max_frame_mb = 0
    try:
        with pytest.raises(FrameTooLarge):
            encode_frame(b"over the knob")
    finally:
        Global.transport_max_frame_mb = old


def test_frame_magic_is_stable():
    # the wire format is a compatibility surface: changing it silently
    # partitions old/new processes mid-upgrade
    assert FRAME_MAGIC == b"WKTX"
    assert encode_frame(b"q")[:4] == b"WKTX"


# ---------------------------------------------------------------------------
# message registry: every declared op roundtrips both sides
# ---------------------------------------------------------------------------

#: sample request args per op (plain ints by schema design)
_SAMPLE_ARGS = {
    "ping": (7,),
    "segment": (3, OUT),
    "versatile": (IN,),
    "index": (2, IN),
    "digest": (),
    "sync": (5,),
    "snapshot": (),
}


def test_registry_and_handlers_cover_the_same_ops():
    assert set(MESSAGE_REGISTRY) == set(OP_HANDLERS)
    assert set(MESSAGE_REGISTRY) == set(_SAMPLE_ARGS)


@pytest.mark.parametrize("op", sorted(MESSAGE_REGISTRY))
def test_pack_unpack_roundtrip_every_message_type(op):
    args = _SAMPLE_ARGS[op]
    pack, unpack = MESSAGE_REGISTRY[op]
    assert unpack(pack(args)) == tuple(int(a) for a in args)
    # and through the full request envelope + frame codec
    frame = encode_frame(pack_message(op, 3, args))
    (payload,), _ = decode_frames(frame)
    got_op, got_sid, got_args = unpack_message(payload)
    assert (got_op, got_sid) == (op, 3)
    assert got_args == tuple(int(a) for a in args)


def test_unpack_message_rejects_malformed_payloads():
    with pytest.raises(TransportCorrupt):
        unpack_message(b"\x00not-a-pickle")
    with pytest.raises(TransportCorrupt):
        unpack_message(pack_reply("wrong-shape"))
    with pytest.raises(TransportCorrupt):  # undeclared op
        unpack_message(pack_message("segment", 0, (1, 0))
                       .replace(b"segment", b"zegment"))


def test_reply_envelope_ok_err_unknown():
    assert unpack_reply(pack_reply({"a": 1})) == {"a": 1}
    with pytest.raises(WukongError) as ei:
        unpack_reply(pack_error(int(ErrorCode.SHARD_UNAVAILABLE), "gone"))
    assert ei.value.code == ErrorCode.SHARD_UNAVAILABLE
    with pytest.raises(TransportCorrupt):
        unpack_reply(b"\x80\x04N.")  # pickled None: unknown reply kind


def test_run_op_executes_every_declared_op(g0):
    keys, offs, edges = run_op("segment", g0, 3, OUT)
    assert len(offs) == len(keys) + 1 and len(edges) == offs[-1]
    missing = run_op("segment", g0, 999, OUT)  # absent segment: empty CSR
    assert len(missing[0]) == 0 and list(missing[1]) == [0]
    idx = run_op("index", g0, 3, IN)
    assert idx.dtype == np.int32
    vkeys, _voffs, _vedges, _vpred = run_op("versatile", g0, OUT)
    assert vkeys is not None
    assert run_op("digest", g0) == int(gstore_digest(g0))
    pong = run_op("ping", g0, 42)
    assert pong == {"sid": 0, "version": int(getattr(g0, "version", 0)),
                    "seq": 42}
    assert run_op("sync", g0, 5) == 0  # loopback: nothing to catch up
    from wukong_tpu.store.persist import gstore_from_bytes

    blob = run_op("snapshot", g0)
    assert gstore_digest(gstore_from_bytes(blob)) == gstore_digest(g0)
    with pytest.raises(WukongError):
        run_op("no-such-op", g0)


# ---------------------------------------------------------------------------
# transports: loopback default, socket local-fallback, mode knob
# ---------------------------------------------------------------------------

def test_make_transport_mode_knob():
    assert make_transport().mode == "loopback"  # the zero-touch default
    old = Global.transport_mode
    try:
        Global.transport_mode = "socket"
        assert isinstance(make_transport(), SocketTransport)
        Global.transport_mode = "carrier-pigeon"
        with pytest.raises(WukongError) as ei:
            make_transport()
        assert ei.value.code == ErrorCode.UNSUPPORTED_SHAPE
    finally:
        Global.transport_mode = old


def test_loopback_fetch_is_direct_execution(g0):
    lo = LoopbackTransport()
    a = lo.fetch(0, g0, "segment", (3, OUT))
    b = run_op("segment", g0, 3, OUT)
    for x, y in zip(a, b):
        assert np.array_equal(x, y)
    assert lo.dispatch(lambda u, v: u + v, 2, 3) == 5


def test_loopback_snapshot_is_an_independent_clone(g0):
    snap = LoopbackTransport().snapshot(0, g0)
    assert snap is not g0
    assert gstore_digest(snap) == gstore_digest(g0)


def test_peerless_socket_transport_serves_locally(g0):
    """Flipping transport_mode=socket with no workers up must stay
    byte-identical: the parent's copy is authoritative."""
    tr = SocketTransport()
    try:
        a = tr.fetch(0, g0, "segment", (3, OUT))
        b = run_op("segment", g0, 3, OUT)
        for x, y in zip(a, b):
            assert np.array_equal(x, y)
        assert gstore_digest(tr.snapshot(0, g0)) == gstore_digest(g0)
    finally:
        tr.close()


# ---------------------------------------------------------------------------
# wire serving against the worker loop, in-process (threaded server)
# ---------------------------------------------------------------------------

@pytest.fixture()
def wire(g0):
    """A real TCP server speaking the framed protocol, serving shard 0
    from a thread — the worker's serve loop without the process."""
    from wukong_tpu.runtime.procs import _serve_connection, _WorkerState

    state = _WorkerState({0: g0}, applied_seq=-1, wal_dir="")
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind(("127.0.0.1", 0))
    server.listen(8)

    def accept_loop():
        while True:
            try:
                cli, _ = server.accept()
            except OSError:
                return
            threading.Thread(target=_serve_connection, args=(cli, state),
                             daemon=True).start()

    threading.Thread(target=accept_loop, daemon=True).start()
    tr = SocketTransport()
    addr = ("127.0.0.1", server.getsockname()[1])
    tr.register_peer(0, addr)
    yield tr, addr
    tr.close()
    server.close()


def test_wire_fetch_matches_loopback_byte_for_byte(wire, g0):
    tr, _addr = wire
    for op, args in (("segment", (3, OUT)), ("segment", (4, IN)),
                     ("index", (2, IN)), ("versatile", (OUT,))):
        remote = tr.fetch(0, g0, op, args)
        local = run_op(op, g0, *args)
        if isinstance(local, tuple):
            for x, y in zip(remote, local):
                assert np.array_equal(np.asarray(x), np.asarray(y))
        else:
            assert np.array_equal(np.asarray(remote), np.asarray(local))
    assert tr.fetch(0, g0, "digest", ()) == int(gstore_digest(g0))
    assert gstore_digest(tr.snapshot(0, g0)) == gstore_digest(g0)


def test_wire_error_propagates_structured(wire, g0):
    tr, addr = wire
    tr.register_peer(5, addr)  # the worker does not own shard 5
    with pytest.raises(WukongError) as ei:
        tr._retry_call(5, "digest", ())
    assert ei.value.code == ErrorCode.SHARD_UNAVAILABLE
    assert "shard 5" in str(ei.value)


def test_transport_connect_fault_retries_through(wire, g0):
    plan = FaultPlan([FaultSpec("transport.connect", "transient", count=1)],
                     seed=0)
    faults.install(plan)
    assert tr_fetch_digest(wire, g0)  # first connect faulted, retry wins
    assert ("transport.connect", None, "transient") in plan.history


def test_transport_send_fault_drops_connection_and_retries(wire, g0):
    tr, _ = wire
    tr.fetch(0, g0, "digest", ())  # warm the connection
    plan = FaultPlan([FaultSpec("transport.send", "transient", count=1)],
                     seed=0)
    faults.install(plan)
    assert tr_fetch_digest(wire, g0)
    assert plan.history and plan.history[0][0] == "transport.send"


def test_transport_recv_fault_drops_connection_and_retries(wire, g0):
    plan = FaultPlan([FaultSpec("transport.recv", "transient", count=1)],
                     seed=0)
    faults.install(plan)
    assert tr_fetch_digest(wire, g0)
    assert plan.history and plan.history[0][0] == "transport.recv"


def tr_fetch_digest(wire, g0) -> bool:
    tr, _ = wire
    return tr.fetch(0, g0, "digest", ()) == int(gstore_digest(g0))


def test_dead_peer_exhausts_retries_with_transient_faults(g0, monkeypatch):
    """A peer that is simply gone (connection refused) must surface as
    retry exhaustion — the sharded store's resilience ladder then owns
    rotation/failover, exactly as for an in-proc shard fault."""
    monkeypatch.setattr(Global, "retry_base_ms", 1)
    monkeypatch.setattr(Global, "retry_max_ms", 2)
    sink = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sink.bind(("127.0.0.1", 0))
    dead = ("127.0.0.1", sink.getsockname()[1])
    sink.close()  # nothing listens here any more
    tr = SocketTransport(connect_timeout_ms=200)
    tr.register_peer(0, dead)
    try:
        with pytest.raises(RetryExhausted):
            tr._retry_call(0, "digest", ())
    finally:
        tr.close()


# ---------------------------------------------------------------------------
# retry_call audit: no sleep after the final attempt
# ---------------------------------------------------------------------------

def test_retry_call_never_sleeps_after_the_final_attempt():
    """attempts=N means exactly N calls and N-1 backoffs: sleeping after
    the last failure would add a full backoff window of dead latency to
    every exhausted retry (and stall the caller's failover)."""
    from wukong_tpu.runtime.faults import TransientFault
    from wukong_tpu.runtime.resilience import retry_call

    calls, sleeps = [], []

    def boom():
        calls.append(1)
        raise TransientFault("always down")

    with pytest.raises(RetryExhausted):
        retry_call(boom, site="test.audit", attempts=4, base_ms=1, max_ms=2,
                   rng=random.Random(0), sleep=sleeps.append)
    assert len(calls) == 4
    assert len(sleeps) == 3  # N-1: no backoff after the last failure


# ---------------------------------------------------------------------------
# process supervision: spawn, WAL-tail sync, kill, restart, heartbeat
# ---------------------------------------------------------------------------

def _mk_sstore(stores):
    from wukong_tpu.parallel.sharded_store import ShardedDeviceStore

    class _Mesh:
        devices = np.empty(D, dtype=object)

    return ShardedDeviceStore(list(stores), _Mesh(), replication_factor=1)


@pytest.fixture()
def proc_world(tmp_path, monkeypatch):
    """A supervisor-ready world: fresh partitions (module stores stay
    pristine), an active WAL, and a slow heartbeat so tests drive
    kill/restart deterministically."""
    from wukong_tpu.store.wal import reset_wal

    monkeypatch.setattr(Global, "proc_workers", 2)
    monkeypatch.setattr(Global, "proc_heartbeat_ms", 60_000)
    monkeypatch.setattr(Global, "proc_restart_backoff_ms", 1)
    monkeypatch.setattr(Global, "wal_dir", str(tmp_path / "wal"))
    t = _triples()
    stores = [build_partition(t, i, D) for i in range(D)]
    ss = _mk_sstore(stores)
    yield ss, str(tmp_path / "ckpt")
    Global.wal_dir = ""
    reset_wal()


def test_supervisor_spawn_serve_sync_kill_restart(proc_world):
    from wukong_tpu.runtime.procs import ProcSupervisor
    from wukong_tpu.store.dynamic import insert_batch_into

    ss, ckpt_dir = proc_world
    sup = ProcSupervisor(ss, ckpt_dir)
    sup.start()
    try:
        # every shard recovered digest-identical from the checkpoint and
        # got peered; the sstore now speaks the socket transport
        assert ss.transport is sup.transport
        assert all(sup.transport.peer_for(s) is not None for s in range(D))
        assert sorted(sup.groups) == [0, 1]
        # wire fetches are byte-identical to the parent's local execution
        for sid in range(D):
            a = ss.transport.fetch(sid, ss.stores[sid], "segment", (3, OUT))
            b = run_op("segment", ss.stores[sid], 3, OUT)
            for x, y in zip(a, b):
                assert np.array_equal(x, y)
        # WAL is the mutation transport: a durable insert after boot
        # reaches every worker via the sync op, proven by digests
        batch = np.array([[2000, 3, 2001], [2002, 4, 2003]], dtype=np.int64)
        insert_batch_into(list(ss.stores), batch, dedup=False)
        sup.sync()
        for gid in sup.groups:
            want = {sid: int(gstore_digest(ss.stores[sid]))
                    for sid in sorted(sup.groups[gid].serving)}
            assert sup.worker_digests(gid) == want
        # SIGKILL one worker: its shards fall back to the parent through
        # the resilience ladder (peers deregister only on restart)
        gid = sup.group_of(0)
        dead_pid = sup.kill(gid)
        assert dead_pid > 0
        # restart = the full crash-recovery path: newest checkpoint +
        # WAL-tail replay (the post-boot insert!), digest-gated rejoin
        assert sup.restart(gid) is True
        want = {sid: int(gstore_digest(ss.stores[sid]))
                for sid in sorted(sup.groups[gid].serving)}
        assert sup.worker_digests(gid) == want
        assert all(sup.transport.peer_for(s) is not None
                   for s in sup.groups[gid].shard_ids)
    finally:
        sup.stop()
    # stop() restores the loopback transport: zero-touch both ways
    assert ss.transport.mode == "loopback"


def test_heartbeat_detects_death_and_restarts(proc_world, monkeypatch):
    from wukong_tpu.obs.metrics import get_registry
    from wukong_tpu.runtime.procs import ProcSupervisor

    ss, ckpt_dir = proc_world
    monkeypatch.setattr(Global, "proc_workers", 1)
    monkeypatch.setattr(Global, "proc_heartbeat_ms", 50)
    monkeypatch.setattr(Global, "proc_heartbeat_misses", 2)
    reg = get_registry()
    m_restarts = reg.counter("wukong_proc_restarts_total",
                             "Worker processes restarted by the supervisor",
                             labels=("group",))
    r0 = m_restarts.value(group="0")
    sup = ProcSupervisor(ss, ckpt_dir)
    sup.start()
    try:
        pid0 = sup.groups[0].proc.pid
        sup.kill(0)
        deadline = time.time() + 30
        while time.time() < deadline:
            grp = sup.groups[0]
            if (grp.proc is not None and grp.proc.pid != pid0
                    and grp.serving):
                break
            time.sleep(0.05)
        else:
            pytest.fail("heartbeat never restarted the killed worker")
        assert m_restarts.value(group="0") - r0 >= 1
        assert sup.worker_digests(0) == {
            sid: int(gstore_digest(ss.stores[sid]))
            for sid in sorted(sup.groups[0].serving)}
    finally:
        sup.stop()


# ---------------------------------------------------------------------------
# the kill-a-process drill, end to end (emulator + replicated dist world)
# ---------------------------------------------------------------------------

@pytest.mark.recovery
def test_kill_a_process_drill(tmp_path, monkeypatch, eight_cpu_devices):
    """ISSUE 20 acceptance: SIGKILL a worker mid-query-stream — every
    reply stays complete=True and byte-identical to the loopback oracle
    via replica failover; the restarted worker rejoins after checkpoint +
    WAL-tail replay, digest-identical; stop() restores loopback."""
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
    from wukong_tpu.parallel.dist_engine import DistEngine
    from wukong_tpu.parallel.mesh import make_mesh
    from wukong_tpu.runtime.emulator import Emulator
    from wukong_tpu.runtime.proxy import Proxy
    from wukong_tpu.store.gstore import build_all_partitions
    from wukong_tpu.store.wal import reset_wal

    monkeypatch.setattr(Global, "enable_tpu", False)
    monkeypatch.setattr(Global, "enable_dist_inplace", False)
    monkeypatch.setattr(Global, "replication_factor", 2)
    monkeypatch.setattr(Global, "proc_workers", 2)
    # the drill drives kill/restart itself: keep the heartbeat out of it
    monkeypatch.setattr(Global, "proc_heartbeat_ms", 60_000)
    monkeypatch.setattr(Global, "proc_restart_backoff_ms", 1)
    monkeypatch.setattr(Global, "retry_base_ms", 1)
    monkeypatch.setattr(Global, "retry_max_ms", 4)
    monkeypatch.setattr(Global, "wal_dir", str(tmp_path / "wal"))
    triples, _ = generate_lubm(1, seed=42)
    ss = VirtualLubmStrings(1, seed=42)
    dist = DistEngine(build_all_partitions(triples, 8), ss, make_mesh(8))
    assert dist.sstore.replication_factor == 2
    g = build_partition(triples, 0, 1)
    proxy = Proxy(g, ss, CPUEngine(g, ss), None, dist)
    try:
        report = Emulator(proxy).run_proc_drill(str(tmp_path / "ckpt"),
                                                rounds=2)
        assert report["proc_identical"] is True
        assert report["outage"]["complete"] is True
        assert report["outage"]["identical"] is True
        assert report["outage"]["failovers"] > 0
        assert report["rejoin"]["ok"] is True
        assert report["rejoin"]["wal_replayed"] is True
        assert report["rejoin"]["digests_match"] is True
        assert report["rejoin"]["repeered"] is True
        assert report["rejoin"]["restarts"] >= 1
        assert report["recovered"]["complete"] is True
        assert report["recovered"]["identical"] is True
        assert report["loopback_restored"]["mode"] == "loopback"
        assert report["loopback_restored"]["identical"] is True
    finally:
        proxy.recovery().stop()
        Global.wal_dir = ""
        reset_wal()
