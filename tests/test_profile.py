"""Introspection plane (ISSUE 7): EXPLAIN / EXPLAIN ANALYZE, per-shard
heat telemetry, /top, latency attribution + regression sentinel.

Acceptance surface: EXPLAIN renders the planner's per-step cost/cardinality
estimates (golden-pinned); ANALYZE joins actual per-step rows/wall-time
against them on chain/const/index shapes and its latency decomposition
covers >=90% of end-to-end wall time; batched members are attributed via
their FusedGroup's dispatch span; heat counters account primary/failover/
degraded fetch outcomes (chaos-marked); the Zipfian hot-spot scenario
ranks the hot shard first with load-rate CDFs separating hot from cold;
/top scrapes; the regression sentinel trips and auto-dumps through the
flight recorder; and scripts/bench_report.py trends + checks the BENCH
artifacts.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.lubm import UB, VirtualLubmStrings, generate_lubm
from wukong_tpu.obs import QueryTrace, get_recorder, get_registry
from wukong_tpu.obs.heat import get_heat, payload_size
from wukong_tpu.obs.profile import (
    LatencyAttributor,
    decompose,
    get_attributor,
    render_top,
)
from wukong_tpu.runtime import faults
from wukong_tpu.runtime.faults import FaultPlan, FaultSpec
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.utils.errors import ErrorCode

pytestmark = pytest.mark.obs

PREFIX = """
PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
"""
Q_CHAIN = PREFIX + """SELECT ?X ?Y WHERE {
    ?X ub:memberOf ?Y .
    ?Y ub:subOrganizationOf ?Z .
}"""
Q_TYPE = PREFIX + """SELECT ?X WHERE {
    ?X rdf:type ub:FullProfessor .
    ?X ub:worksFor ?D .
}"""


@pytest.fixture(scope="module")
def world():
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    return {"g": g, "ss": ss, "triples": triples}


@pytest.fixture(scope="module")
def proxy(world):
    from wukong_tpu.planner.optimizer import make_planner

    p = Proxy(world["g"], world["ss"],
              CPUEngine(world["g"], world["ss"]))
    p.planner = make_planner(world["triples"])
    return p


@pytest.fixture(autouse=True)
def _hygiene(monkeypatch):
    """Tracing knobs at defaults; recorder/attributor/heat state clean;
    no fault plan leaks across tests."""
    monkeypatch.setattr(Global, "enable_tracing", False)
    monkeypatch.setattr(Global, "trace_sample_every", 1)
    monkeypatch.setattr(Global, "trace_dump_dir", "")
    monkeypatch.setattr(Global, "enable_attribution", False)
    get_recorder().clear()
    get_attributor().reset()
    get_heat().reset()
    faults.clear()
    yield
    faults.clear()


def _const_texts(world, n=2):
    """Same-template const-start chain texts (the batchable shape)."""
    from wukong_tpu.types import OUT

    ss, g = world["ss"], world["g"]
    pid = ss.str2id(f"<{UB}memberOf>")
    depts = np.asarray(g.get_index(pid, OUT))[:n]
    return [
        f"SELECT ?s ?c WHERE {{ ?s <{UB}memberOf> {ss.id2str(int(d))} . "
        f"?s <{UB}takesCourse> ?c . }}" for d in depts]


# ---------------------------------------------------------------------------
# EXPLAIN: golden output + estimate parity with the planner
# ---------------------------------------------------------------------------

EXPLAIN_GOLDEN = """\
EXPLAIN
step  pattern                                    est_rows   est_cost
   0  (11 0 IN -2)                                  275.0      614.0
   1  (-2 11 OUT -3)                                275.0      889.0
   2  (-2 7 IN -1)                                7,473.0   15,285.0
planner: cost-based, strategy: walk, est total cost 16,788.0"""


def test_explain_golden(proxy):
    r = proxy.explain_query(Q_CHAIN)
    assert r["mode"] == "EXPLAIN"
    assert r["rendered"] == EXPLAIN_GOLDEN


def test_explain_estimates_match_planner(proxy):
    """The EXPLAIN surface and the capacity-sizing estimate_chain must
    come from one cardinality model (the refactor's contract)."""
    r = proxy.explain_query(Q_CHAIN)
    q = proxy._parse_text(Q_CHAIN)
    proxy._plan_prepared(q, True, None)
    ests = proxy.planner.estimate_chain(q.pattern_group.patterns)
    assert [s["est_rows"] for s in r["steps"]] == pytest.approx(ests)


def test_explain_without_planner_renders_dashes(world):
    p2 = Proxy(world["g"], world["ss"],
               CPUEngine(world["g"], world["ss"]))  # no planner
    r = p2.explain_query(Q_CHAIN)
    assert r["planner"] == "heuristic/none"
    assert all("est_rows" not in s for s in r["steps"])
    assert "-" in r["rendered"]


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE: estimate-vs-actual join on chain / const / index shapes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("shape", ["chain", "const", "index"])
def test_analyze_joins_estimates_and_actuals(proxy, world, shape):
    text = {"chain": Q_CHAIN, "index": Q_TYPE,
            "const": _const_texts(world, 1)[0]}[shape]
    r = proxy.explain_query(text, analyze=True, device="cpu")
    assert r["mode"] == "EXPLAIN ANALYZE"
    assert r["status"] == "SUCCESS"
    # every step joined: estimates AND actuals keyed on step index
    for k, s in enumerate(r["steps"]):
        assert s["step"] == k
        assert s["est_rows"] > 0
        assert s["rows_out"] is not None and s["time_us"] is not None
    assert r["steps"][-1]["rows_out"] == r["rows"]
    # the forced trace reached the flight recorder
    assert get_recorder().find(r["trace_id"]) is not None


def test_analyze_decomposition_covers_90pct(proxy):
    """Acceptance: `console analyze` on a LUBM chain query shows per-step
    estimated vs actual cardinalities and a latency decomposition whose
    components sum to >=90% of end-to-end wall time."""
    r = proxy.explain_query(Q_CHAIN, analyze=True, device="cpu")
    d = r["decomposition"]
    assert d["covered_frac"] >= 0.90
    comp = d["components"]
    assert comp["execute"] > 0 and comp["parse"] >= 0 and comp["plan"] >= 0
    assert sum(comp.values()) + d["other_us"] <= d["total_us"] * 1.01
    assert "est_rows" in r["steps"][0] and r["steps"][0]["rows_out"] >= 0
    assert "latency:" in r["rendered"]


def test_console_analyze_and_top_verbs(proxy, tmp_path, capsys):
    from wukong_tpu.runtime.console import Console

    qf = tmp_path / "q.sparql"
    qf.write_text(Q_CHAIN)
    con = Console(proxy)
    con.run_command(f"analyze -f {qf} -d cpu")
    out = capsys.readouterr().out
    assert "EXPLAIN ANALYZE" in out and "latency:" in out
    con.run_command("explain -f " + str(qf))
    assert "EXPLAIN" in capsys.readouterr().out
    con.run_command("top -k 4")
    out = capsys.readouterr().out
    assert "SHARDS" in out and "TEMPLATES" in out and "LANES" in out


# ---------------------------------------------------------------------------
# batched-member attribution (via the FusedGroup dispatch span)
# ---------------------------------------------------------------------------

def test_batched_member_attribution(proxy, world):
    from wukong_tpu.runtime.batcher import FusedGroup, QueryBatcher, _Pending

    texts = _const_texts(world, 2)
    members = []
    for t in texts:
        q = proxy._parse_text(t)
        proxy._plan_prepared(q, True, None)
        q.deadline = None
        q.trace = QueryTrace(kind="query", text=t)
        members.append(_Pending(q))
    b = QueryBatcher(proxy.cpu)
    try:
        FusedGroup(members, b, engine=None).run(proxy.cpu)
    finally:
        b.close()
    for m in members:
        assert m.q.result.status_code == ErrorCode.SUCCESS
        m.trace.finish("SUCCESS")
        evs = [(sp.name, sp.attrs) for sp in m.trace.spans]
        settled = [a for (n, a) in evs if n == "batch.settled"]
        assert settled and settled[0]["dispatch_us"] > 0
        d = decompose(m.trace)
        # no execute span of its own: the FusedGroup's dispatch span
        # duration becomes the member's execute component
        assert d["components"]["execute"] == settled[0]["dispatch_us"]


# ---------------------------------------------------------------------------
# per-shard heat: counters, failover kinds (chaos), hot-spot scenario
# ---------------------------------------------------------------------------

class _Mesh4:
    devices = np.empty(4, dtype=object)


def _sstore(world, n=4):
    from wukong_tpu.parallel.sharded_store import ShardedDeviceStore

    stores = [build_partition(world["triples"], i, n) for i in range(n)]
    return ShardedDeviceStore(stores, _Mesh4(), replication_factor=1)


def test_heat_charges_primary_fetches(world):
    sstore = _sstore(world)
    for i in (0, 0, 0, 1):
        sstore._fetch_shard(i, lambda g: np.arange(64), "t")
    rep = get_heat().report()
    assert rep["ranked"][0]["shard"] == 0
    assert rep["shards"][0]["fetches"] == 3
    assert rep["shards"][0]["by_kind"]["primary"] == 3
    assert rep["shards"][1]["rows"] == 64
    assert rep["shards"][1]["bytes"] == np.arange(64).nbytes
    # the wukong_shard_heat_* metrics carry the same numbers
    m = get_registry().counter("wukong_shard_heat_fetches_total",
                               labels=("shard", "kind"))
    assert m.value(shard="0", kind="primary") >= 3


def test_heat_off_knob_skips_charging(world, monkeypatch):
    monkeypatch.setattr(Global, "enable_heat", False)
    sstore = _sstore(world)
    sstore._fetch_shard(2, lambda g: np.arange(8), "t")
    assert get_heat().report()["ranked"] == []


@pytest.mark.chaos
def test_heat_counters_under_failover(world, monkeypatch):
    """A downed primary served by a replica charges kind=failover; with no
    replica it charges kind=degraded — the heat plane sees the outage the
    way placement must (a hot shard in failover is the migration signal)."""
    from wukong_tpu.store.persist import clone_gstore

    monkeypatch.setattr(Global, "retry_base_ms", 1)
    monkeypatch.setattr(Global, "retry_max_ms", 2)
    sstore = _sstore(world)
    sstore.replicas = {0: [(1, clone_gstore(sstore.stores[0]))]}
    faults.install(FaultPlan([FaultSpec("dist.shard_fetch", "shard_down",
                                        shard=0)], seed=0))
    out, ok = sstore._fetch_shard(0, lambda g: np.arange(4), "t")
    assert ok and len(out) == 4
    faults.install(FaultPlan([FaultSpec("dist.shard_fetch", "shard_down",
                                        shard=3)], seed=0))
    out, ok = sstore._fetch_shard(3, lambda g: np.arange(4), "t")
    assert not ok
    rep = get_heat().report()
    assert rep["shards"][0]["by_kind"]["failover"] == 1
    assert rep["shards"][3]["by_kind"]["degraded"] == 1
    assert rep["shards"][3]["rows"] == 0  # empty substitution has no rows


def test_hotspot_scenario_ranks_hot_shard_first(world, proxy):
    """Acceptance + ROADMAP item 3 fixture: the Zipfian skewed-workload
    run must rank the hot shard first, and the per-shard load-rate CDFs
    must separate hot from cold."""
    from wukong_tpu.runtime.emulator import Emulator

    sstore = _sstore(world)
    emu = Emulator(proxy)
    rep = emu.run_hotspot(n_ops=600, zipf_a=1.6, seed=7, sstore=sstore)
    assert rep["ranked"][0] == rep["hot"]
    assert rep["separation"] > 1.5
    shards = rep["report"]["shards"]
    hot_p50 = shards[rep["hot"]]["load_rate_cdf"][0.5]
    for s, d in shards.items():
        if s != rep["hot"] and d["load_rate_cdf"]:
            assert hot_p50 > d["load_rate_cdf"][0.5]
    # the hot shard carries the load share a Zipf(1.6) head implies
    assert shards[rep["hot"]]["share"] > 0.5


def test_top_endpoint_scrape(world):
    """GET /top (plain text) and /top.json (structured) serve the heat
    report through the metrics endpoint."""
    import socket
    import urllib.request

    from wukong_tpu.obs import maybe_start_metrics_http, stop_metrics_http

    sstore = _sstore(world)
    for i in (1, 1, 2):
        sstore._fetch_shard(i, lambda g: np.arange(16), "t")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    assert maybe_start_metrics_http(port=port) is not None
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/top", timeout=5).read().decode()
        assert "SHARDS" in body and "TEMPLATES" in body and "LANES" in body
        js = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/top.json?k=2", timeout=5).read())
        assert js["shards"]["ranked"][0]["shard"] == 1
        assert len(js["shards"]["ranked"]) <= 2
    finally:
        stop_metrics_http()


# ---------------------------------------------------------------------------
# latency attribution + regression sentinel
# ---------------------------------------------------------------------------

def _fake_trace(total_us, parse_us, execute_us):
    tr = QueryTrace(kind="query")
    sp = tr.start_span("proxy.parse")
    tr.end_span(sp)
    sp.t1_us = sp.t0_us + parse_us
    sp2 = tr.start_span("cpu.execute")
    tr.end_span(sp2)
    sp2.t1_us = sp2.t0_us + execute_us
    tr.finish("SUCCESS")
    tr.t1_us = tr.t0_us + total_us
    return tr


def test_regression_sentinel_p95_trip_dumps_trace(monkeypatch):
    monkeypatch.setattr(Global, "attribution_min_samples", 8)
    monkeypatch.setattr(Global, "attribution_p95_drift_pct", 100)
    att = LatencyAttributor(window=64)
    for _ in range(10):
        assert att.observe(_fake_trace(1000, 100, 850), "T") is None
    slow = _fake_trace(5000, 120, 4800)
    v = att.observe(slow, "T")
    assert v is not None and v["reason"] == "P95_DRIFT"
    assert ("LATENCY_REGRESSION", slow) in list(get_recorder().dumps)
    assert get_registry().counter(
        "wukong_latency_regressions_total",
        labels=("template",)).value(template="T") >= 1


def test_regression_sentinel_component_shift(monkeypatch):
    monkeypatch.setattr(Global, "attribution_min_samples", 8)
    monkeypatch.setattr(Global, "attribution_share_drift_pct", 25)
    monkeypatch.setattr(Global, "attribution_p95_drift_pct", 10_000)
    att = LatencyAttributor(window=64)
    for _ in range(10):
        att.observe(_fake_trace(1000, 100, 850), "T")
    # same total (p95 quiet) but parse's share jumped 10% -> 60%
    v = att.observe(_fake_trace(1000, 600, 350), "T")
    assert v is not None and v["reason"] == "COMPONENT_SHIFT"
    assert v["component"] == "parse" and v["share_drift_pts"] > 25


def test_attribution_via_proxy_feeds_top(proxy, monkeypatch):
    monkeypatch.setattr(Global, "enable_tracing", True)
    monkeypatch.setattr(Global, "enable_attribution", True)
    for _ in range(3):
        q = proxy.run_single_query(Q_CHAIN, device="cpu", blind=True)
        assert q.result.status_code == ErrorCode.SUCCESS
    rep = get_attributor().report()
    assert rep and rep[0]["count"] == 3
    assert rep[0]["top_component"] == "execute"
    text, js = render_top()
    assert js["templates"][0]["count"] == 3
    assert "sig:" in text  # the template key reached the rendered table


def test_attribution_off_is_untouched(proxy, monkeypatch):
    monkeypatch.setattr(Global, "enable_tracing", True)
    proxy.run_single_query(Q_CHAIN, device="cpu", blind=True)
    assert get_attributor().report() == []


# ---------------------------------------------------------------------------
# satellites: payload sizing, heat-telemetry gate, bench_report
# ---------------------------------------------------------------------------

def test_payload_size_shapes():
    a = np.arange(10, dtype=np.int64)
    assert payload_size((a, a[:3])) == (10, a.nbytes + a[:3].nbytes)
    assert payload_size(a) == (10, a.nbytes)
    assert payload_size(None) == (0, 0)
    assert payload_size((None, "x")) == (0, 0)


def test_heat_telemetry_gate_fixtures(tmp_path):
    """The new analysis gate: an unregistered placement-input metric and
    an unannotated shared structure are violations; the clean shape is
    not."""
    from wukong_tpu.analysis import run_analysis

    def write(tree: dict) -> str:
        root = tmp_path / "pkg"
        for rel, src in tree.items():
            p = root / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(src)
        return str(root)

    bad = write({"obs/heat.py": (
        "PLACEMENT_INPUTS = {'fetches': 'wukong_nope_total'}\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self.shards = {}\n"
        "        self.lock = make_lock('heat.x')\n")})
    out = run_analysis(bad, plugins=["heat-telemetry"])
    msgs = "\n".join(str(v) for v in out)
    assert "wukong_nope_total" in msgs  # unregistered placement input
    assert "A.shards" in msgs  # unannotated shared structure
    assert "heat.x" in msgs  # undeclared leaf lock

    good = write({"obs/heat.py": (
        "PLACEMENT_INPUTS = {'fetches': 'wukong_ok_total'}\n"
        "declare_leaf('heat.x')\n"
        "reg.counter('wukong_ok_total')\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self.shards = {}  # guarded by: _lock\n"
        "        self.lock = make_lock('heat.x')\n")})
    assert run_analysis(good, plugins=["heat-telemetry"]) == []


def test_bench_report_trend_and_check(tmp_path):
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "bench_report.py")
    d = tmp_path / "b"
    d.mkdir()
    (d / "BENCH_X_r01.json").write_text(
        json.dumps({"metric": "m", "value": 100.0, "unit": "us"}))
    (d / "BENCH_X_r02.json").write_text(
        json.dumps({"metric": "m", "value": 90.0, "unit": "us"}))
    ok = subprocess.run([sys.executable, script, "--dir", str(d), "--check"],
                        capture_output=True, text=True)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    md = (d / "BENCH_TRAJECTORY.md").read_text()
    assert "BENCH_X" in md and "r01:100.0" in md
    js = json.loads((d / "BENCH_TRAJECTORY.json").read_text())
    assert js["series"]["BENCH_X"]["direction"] == -1
    # a >20% latency regression on the newest rung fails --check
    (d / "BENCH_X_r03.json").write_text(
        json.dumps({"metric": "m", "value": 130.0, "unit": "us"}))
    bad = subprocess.run([sys.executable, script, "--dir", str(d),
                          "--check"], capture_output=True, text=True)
    assert bad.returncode == 1
    assert "REGRESSION" in bad.stderr


def test_monitor_heat_lines(world):
    from wukong_tpu.runtime.monitor import Monitor

    mon = Monitor()
    assert mon.heat_lines() == []  # quiet with nothing charged
    sstore = _sstore(world)
    sstore._fetch_shard(2, lambda g: np.arange(4), "t")
    lines = mon.heat_lines(k=2)
    assert len(lines) == 1 and "2:1" in lines[0]
    assert 2 in mon.shard_load_cdfs()
