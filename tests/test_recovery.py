"""Fault tolerance & recovery: WAL, checkpoint/restore, crash drills.

Covers the durability layer end to end, deterministically and host-only
(no device mesh — the distributed failover tests ride in test_chaos.py):

- WAL unit behavior: append/replay round trips, segment rotation, torn-tail
  tolerance vs mid-segment corruption, truncation behind checkpoints, the
  fsync policy knob, and the ``wal.append`` fault site's
  fail-before-acknowledge contract.
- persist.py hardening: versioned header, per-array checksums, structured
  CHECKPOINT_CORRUPT on truncation/tampering, newer-major refusal, legacy
  bundle acceptance, clone_gstore isolation.
- THE crash-restart determinism drill: ingest a dynamic batch + stream
  epochs, checkpoint mid-stream, hard-drop the store objects mid-epoch via
  an injected fault, recover fresh objects from checkpoint+WAL, and assert
  query results, CSR segment bytes, standing-query sinks, and the epoch
  counter are all byte-identical to an uninterrupted oracle run.
- scheduler: capped exponential idle backoff bounds + the background
  rebuild lane's fire-and-forget contract.
- lint gate 3: mutation paths must route through the WAL append hook.
"""

import os
import threading

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.runtime import faults
from wukong_tpu.runtime.faults import FaultPlan, FaultSpec, TransientFault
from wukong_tpu.runtime.recovery import RebuildJob, RecoveryManager
from wukong_tpu.runtime.scheduler import EnginePool
from wukong_tpu.sparql.parser import Parser
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.store.persist import (
    FORMAT_VERSION,
    clone_gstore,
    load_gstore,
    restore_gstore_into,
    save_gstore,
)
from wukong_tpu.store.wal import (
    WriteAheadLog,
    active_wal,
    maybe_wal_append,
    reset_wal,
)
from wukong_tpu.stream import StreamContext
from wukong_tpu.utils.errors import CheckpointCorrupt, ErrorCode

pytestmark = pytest.mark.recovery


@pytest.fixture(autouse=True, scope="module")
def _lockdep_checked():
    """PR 6: the recovery suite runs with the lockdep runtime checker on —
    WAL/checkpoint/heal locking (incl. the mutation-lock ordering) is
    regression-checked by every test here. Teardown asserts zero
    order cycles and zero declared-leaf inversions."""
    from wukong_tpu.analysis import lockdep

    lockdep.install(True)
    yield
    try:
        assert lockdep.cycles() == [], lockdep.cycles()
        assert lockdep.leaf_violations() == [], lockdep.leaf_violations()
    finally:
        lockdep.install(False)

QDEPT = """
PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
SELECT ?X WHERE {
    ?X ub:worksFor <http://www.Department0.University0.edu> .
    ?X rdf:type ub:FullProfessor .
}
"""
QSTAND = """
PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
SELECT ?X ?Y WHERE { ?X ub:memberOf ?Y . }
"""


@pytest.fixture(autouse=True)
def _clean_durability_knobs():
    faults.clear()
    yield
    faults.clear()
    Global.wal_dir = ""
    Global.checkpoint_dir = ""
    Global.wal_sync = "none"
    Global.checkpoint_interval_s = 0
    reset_wal()


@pytest.fixture(scope="module")
def lubm_world():
    triples, _ = generate_lubm(1, seed=42)
    ss = VirtualLubmStrings(1, seed=42)
    return triples, ss


def _tri(*rows):
    return np.asarray(rows, dtype=np.int64)


# ---------------------------------------------------------------------------
# WAL unit behavior
# ---------------------------------------------------------------------------

def test_wal_append_replay_roundtrip(tmp_path):
    w = WriteAheadLog(str(tmp_path), sync="none")
    t0 = _tri([70000, 17, 70001], [70002, 17, 70003])
    s0 = w.append("insert", triples=t0, dedup=True)
    s1 = w.append("epoch", triples=t0[:1], dedup=True, ts=3.5, epoch=1)
    assert (s0, s1) == (0, 1)
    w.close()
    recs = list(WriteAheadLog(str(tmp_path)).replay())
    assert [r.seq for r in recs] == [0, 1]
    assert recs[0].kind == "insert" and recs[1].kind == "epoch"
    assert np.array_equal(recs[0].payload["triples"], t0)
    assert recs[1].payload["ts"] == 3.5 and recs[1].payload["epoch"] == 1


def test_wal_rotation_and_seq_continuity(tmp_path):
    w = WriteAheadLog(str(tmp_path), segment_bytes=512)
    for i in range(16):
        w.append("insert", triples=_tri([70000 + i, 17, 70001]), dedup=True)
    assert len(w._segments()) > 1  # rotated
    w.close()
    w2 = WriteAheadLog(str(tmp_path), segment_bytes=512)
    assert w2.next_seq == 16  # scan resumes the counter across segments
    assert [r.seq for r in w2.replay(after_seq=9)] == list(range(10, 16))


def test_wal_truncate_behind_checkpoint(tmp_path):
    w = WriteAheadLog(str(tmp_path), segment_bytes=256)
    for i in range(12):
        w.append("insert", triples=_tri([70000 + i, 17, 70001]), dedup=True)
    before = len(w._segments())
    removed = w.truncate_upto(7)
    assert removed > 0 and len(w._segments()) == before - removed
    # records past the checkpoint stay fully replayable
    assert [r.seq for r in w.replay(after_seq=7)] == list(range(8, 12))


def test_wal_seq_namespace_survives_full_truncation(tmp_path):
    """truncate_upto must never delete the newest segment: with every
    segment gone a restart would hand out seqs from 0 again while
    checkpoint manifests still record the old high-water mark — replay
    would filter the restarted acknowledged records out silently."""
    w = WriteAheadLog(str(tmp_path), segment_bytes=256)
    for i in range(6):
        w.append("insert", triples=_tri([70000 + i, 17, 70001]), dedup=True)
    w.close()
    w2 = WriteAheadLog(str(tmp_path))  # fresh process: no active handle
    w2.truncate_upto(w2.next_seq - 1)  # a checkpoint covered everything
    assert w2._segments()  # the newest segment anchors the namespace
    w3 = WriteAheadLog(str(tmp_path))
    assert w3.next_seq == 6  # seqs continue, never restart at 0
    assert w3.append("insert", triples=_tri([70009, 17, 70001]),
                     dedup=True) == 6
    w3.close()


def test_wal_torn_tail_drops_only_unacknowledged_record(tmp_path):
    w = WriteAheadLog(str(tmp_path))
    for i in range(4):
        w.append("insert", triples=_tri([70000 + i, 17, 70001]), dedup=True)
    w.close()
    path = w._segments()[-1][1]
    with open(path, "r+b") as f:  # crash mid-append: final record torn
        f.truncate(os.path.getsize(path) - 5)
    assert [r.seq for r in WriteAheadLog(str(tmp_path)).replay()] == [0, 1, 2]


def test_wal_reopen_after_torn_tail_appends_safely(tmp_path):
    """Resuming appends on a torn segment must first truncate the torn
    bytes — otherwise the new ACKNOWLEDGED record lands behind garbage and
    the next replay dies on a mid-segment CRC error (losing it)."""
    w = WriteAheadLog(str(tmp_path))
    for i in range(3):
        w.append("insert", triples=_tri([70000 + i, 17, 70001]), dedup=True)
    w.close()
    path = w._segments()[-1][1]
    with open(path, "r+b") as f:  # crash mid-append: record 2 torn
        f.truncate(os.path.getsize(path) - 4)
    w2 = WriteAheadLog(str(tmp_path))
    assert w2.next_seq == 2  # the torn record was never acknowledged
    s = w2.append("insert", triples=_tri([70009, 17, 70001]), dedup=True)
    w2.close()
    recs = list(WriteAheadLog(str(tmp_path)).replay())
    assert [r.seq for r in recs] == [0, 1, 2]
    assert np.array_equal(recs[-1].payload["triples"],
                          _tri([70009, 17, 70001]))
    assert s == 2


def test_wal_sync_knob_is_live(tmp_path, monkeypatch):
    """`config -s wal_sync always` on a running system must take effect on
    the NEXT append, not at the next restart."""
    calls = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (calls.append(fd),
                                                 real_fsync(fd))[1])
    Global.wal_sync = "none"
    w = WriteAheadLog(str(tmp_path))  # no explicit sync: follows the knob
    w.append("insert", triples=_tri([70000, 17, 70001]), dedup=True)
    assert calls == []
    Global.wal_sync = "always"
    w.append("insert", triples=_tri([70001, 17, 70001]), dedup=True)
    assert len(calls) == 1
    w.close()


def test_wal_mid_segment_corruption_is_structured(tmp_path):
    w = WriteAheadLog(str(tmp_path))
    for i in range(4):
        w.append("insert", triples=_tri([70000 + i, 17, 70001]), dedup=True)
    w.close()
    path = w._segments()[-1][1]
    data = bytearray(open(path, "rb").read())
    data[len(data) // 3] ^= 0xFF  # flip a byte well before the tail
    open(path, "wb").write(bytes(data))
    with pytest.raises(CheckpointCorrupt) as ei:
        list(WriteAheadLog(str(tmp_path)).replay())
    assert ei.value.code == ErrorCode.CHECKPOINT_CORRUPT
    assert path in str(ei.value)


@pytest.mark.chaos
def test_wal_fsync_always_fsyncs_every_append(tmp_path, monkeypatch):
    calls = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (calls.append(fd),
                                                 real_fsync(fd))[1])
    w = WriteAheadLog(str(tmp_path), sync="always")
    for i in range(3):
        w.append("insert", triples=_tri([70000 + i, 17, 70001]), dedup=True)
    assert len(calls) == 3
    w.close()
    # none: no fsync at all
    calls.clear()
    w2 = WriteAheadLog(str(tmp_path), sync="none")
    w2.append("insert", triples=_tri([70009, 17, 70001]), dedup=True)
    assert calls == []
    w2.close()


def test_wal_bad_sync_policy_rejected(tmp_path):
    with pytest.raises(ValueError):
        WriteAheadLog(str(tmp_path), sync="sometimes")


@pytest.mark.chaos
def test_wal_append_fault_leaves_store_and_log_untouched(tmp_path,
                                                         lubm_world):
    """An injected wal.append failure must fail the commit BEFORE any
    mutation: the batch was never acknowledged, nothing to replay."""
    triples, ss = lubm_world
    Global.wal_dir = str(tmp_path / "wal")
    g = build_partition(triples, 0, 1)
    sc = StreamContext([g], ss)
    v0 = getattr(g, "version", 0)
    faults.install(FaultPlan([FaultSpec("wal.append", "shard_down")]))
    with pytest.raises(Exception):
        sc.feed(_tri([70000, 17, 70001]))
    faults.clear()
    assert getattr(g, "version", 0) == v0  # store untouched
    assert sc.epoch == 0  # never acknowledged
    assert list(active_wal().replay()) == []  # nothing durable either


def test_maybe_wal_append_noop_when_off():
    Global.wal_dir = ""
    reset_wal()
    assert maybe_wal_append("insert", _tri([70000, 17, 70001]), True) is None
    assert active_wal() is None


def test_wal_suppress_blocks_hook(tmp_path):
    Global.wal_dir = str(tmp_path)
    reset_wal()
    wal = active_wal()
    with wal.suppress():
        assert maybe_wal_append("insert", _tri([70000, 17, 70001]),
                                True) is None
    assert maybe_wal_append("insert", _tri([70000, 17, 70001]),
                            True) == 0


# ---------------------------------------------------------------------------
# persist.py hardening
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def saved_bundle(lubm_world, tmp_path_factory):
    triples, _ = lubm_world
    g = build_partition(triples, 0, 2)
    path = str(tmp_path_factory.mktemp("persist") / "p0")
    save_gstore(g, path)
    return g, path + ".npz"


def test_persist_roundtrip_carries_version_header(saved_bundle):
    import json

    g, path = saved_bundle
    meta = json.loads(bytes(np.load(path)["_meta"]).decode())
    assert meta["format"] == "wukong-gstore"
    assert meta["version"] == list(FORMAT_VERSION)
    assert meta["checksums"]  # every payload array is covered
    g2 = load_gstore(path)
    assert set(g2.segments) == set(g.segments)
    for k in g.segments:
        assert np.array_equal(g2.segments[k].edges, g.segments[k].edges)


def test_persist_truncated_bundle_is_structured(saved_bundle, tmp_path):
    _, path = saved_bundle
    bad = str(tmp_path / "trunc.npz")
    data = open(path, "rb").read()
    open(bad, "wb").write(data[: len(data) // 2])
    with pytest.raises(CheckpointCorrupt) as ei:
        load_gstore(bad)
    assert ei.value.code == ErrorCode.CHECKPOINT_CORRUPT
    assert bad in str(ei.value)


def test_persist_tampered_array_names_the_culprit(saved_bundle, tmp_path):
    _, path = saved_bundle
    z = np.load(path)
    arrays = {n: z[n] for n in z.files}
    victim = next(n for n in arrays if n.startswith("seg") and
                  arrays[n].size > 0)
    arrays[victim] = arrays[victim].copy()
    arrays[victim].flat[0] += 1  # checksum now stale
    bad = str(tmp_path / "tampered")
    np.savez(bad, **arrays)
    with pytest.raises(CheckpointCorrupt) as ei:
        load_gstore(bad)
    assert victim in str(ei.value)


def test_persist_refuses_newer_major(saved_bundle, tmp_path):
    import json

    _, path = saved_bundle
    z = np.load(path)
    arrays = {n: z[n] for n in z.files}
    meta = json.loads(bytes(arrays["_meta"]).decode())
    meta["version"] = [FORMAT_VERSION[0] + 1, 0]
    arrays["_meta"] = np.frombuffer(json.dumps(meta).encode(),
                                    dtype=np.uint8)
    bad = str(tmp_path / "future")
    np.savez(bad, **arrays)
    with pytest.raises(CheckpointCorrupt) as ei:
        load_gstore(bad)
    assert "newer" in str(ei.value)


def test_persist_legacy_bundle_still_loads(saved_bundle, tmp_path):
    import json

    g, path = saved_bundle
    z = np.load(path)
    arrays = {n: z[n] for n in z.files}
    meta = json.loads(bytes(arrays["_meta"]).decode())
    for k in ("format", "version", "checksums", "store_version"):
        meta.pop(k, None)  # a bundle written before this PR
    arrays["_meta"] = np.frombuffer(json.dumps(meta).encode(),
                                    dtype=np.uint8)
    old = str(tmp_path / "legacy")
    np.savez(old, **arrays)
    g2 = load_gstore(old)
    assert set(g2.segments) == set(g.segments)


def test_restore_into_rejects_partition_mismatch(saved_bundle):
    _, path = saved_bundle  # sid=0, num_workers=2
    other = build_partition(_tri([70000, 17, 70001]), 1, 2)
    with pytest.raises(CheckpointCorrupt):
        restore_gstore_into(other, path)


def test_clone_gstore_isolates_mutations(lubm_world):
    from wukong_tpu.store.dynamic import insert_triples
    from wukong_tpu.types import OUT, TYPE_ID

    triples, _ = lubm_world
    g = build_partition(triples, 0, 1)
    mirror = clone_gstore(g)
    # pick a normal predicate segment and insert a brand-new edge between
    # existing vertices into the PRIMARY only
    key = next(k for k in g.segments if k[0] != TYPE_ID and k[1] == OUT)
    pid = key[0]
    s = int(np.asarray(g.segments[key].keys)[0])
    before = np.asarray(mirror.segments[key].edges).copy()
    insert_triples(g, _tri([s, pid, s]), dedup=False)
    assert np.array_equal(np.asarray(mirror.segments[key].edges), before)
    assert getattr(mirror, "version", 0) != getattr(g, "version", 0)


# ---------------------------------------------------------------------------
# THE crash-restart determinism drill (ISSUE acceptance)
# ---------------------------------------------------------------------------

def _query_rows(g, ss):
    q = Parser(ss).parse(QDEPT)
    heuristic_plan(q)
    CPUEngine(g, ss).execute(q)
    assert q.result.status_code == ErrorCode.SUCCESS
    return sorted(map(tuple, q.result.table.tolist()))


def _segment_bytes(g):
    return {k: (np.asarray(s.keys).tobytes(),
                np.asarray(s.offsets).tobytes(),
                np.asarray(s.edges).tobytes())
            for k, s in g.segments.items()}


def test_crash_restart_is_byte_identical_to_oracle(lubm_world, tmp_path):
    from wukong_tpu.store.dynamic import insert_batch_into

    triples, ss = lubm_world
    rng = np.random.default_rng(7)
    batch = triples[rng.integers(0, len(triples), 40)]
    epochs = [triples[rng.integers(0, len(triples), 30)] for _ in range(5)]

    # ---- oracle: uninterrupted run, no WAL/checkpoint ----
    g_o = build_partition(triples, 0, 1)
    sc_o = StreamContext([g_o], ss)
    qid = sc_o.register(QSTAND)
    insert_batch_into([g_o], batch, dedup=True)
    for i, e in enumerate(epochs):
        sc_o.feed(e, ts=float(i))
    oracle_rows = _query_rows(g_o, ss)
    oracle_sink = sc_o.poll(qid)

    # ---- crashed run: WAL on, checkpoint mid-stream, die mid-epoch ----
    Global.wal_dir = str(tmp_path / "wal")
    Global.checkpoint_dir = str(tmp_path / "ckpt")
    reset_wal()
    g_c = build_partition(triples, 0, 1)
    sc_c = StreamContext([g_c], ss)
    assert sc_c.register(QSTAND) == qid
    rm_c = RecoveryManager([g_c], stream=sc_c)
    insert_batch_into([g_c], batch, dedup=True)
    for i, e in enumerate(epochs[:2]):
        sc_c.feed(e, ts=float(i))
    rm_c.checkpoint()
    sc_c.feed(epochs[2], ts=2.0)
    # hard-drop mid-epoch: the insert dies AFTER the WAL append — the
    # store objects are abandoned exactly as a process kill would leave
    # them (epoch 4 durable but unapplied)
    faults.install(FaultPlan([FaultSpec("dynamic.insert", "shard_down")]))
    with pytest.raises(Exception):
        sc_c.feed(epochs[3], ts=3.0)
    faults.clear()
    del g_c, sc_c, rm_c

    # ---- restart: fresh objects, recover from checkpoint + WAL tail ----
    g_r = build_partition(triples, 0, 1)
    sc_r = StreamContext([g_r], ss)
    rm_r = RecoveryManager([g_r], stream=sc_r)
    stats = rm_r.recover()
    assert stats["checkpoint"] is not None
    assert stats["standing_queries"] == 1
    assert stats["replayed"]["epoch"] == 2  # epoch 3 live + epoch 4 redo
    assert sc_r.epoch == 4
    # the crash swallowed epoch 5 before it was ever offered — feed it now
    # like the resumed source would
    sc_r.feed(epochs[4], ts=4.0)

    assert _query_rows(g_r, ss) == oracle_rows
    st_o, st_r = _segment_bytes(g_o), _segment_bytes(g_r)
    assert set(st_o) == set(st_r)
    assert all(st_o[k] == st_r[k] for k in st_o)  # byte-identical CSR
    sink_r = sc_r.poll(qid)
    assert len(sink_r) == len(oracle_sink)
    for a, b in zip(oracle_sink, sink_r):
        assert (a.epoch, a.sign) == (b.epoch, b.sign)
        assert np.array_equal(a.rows, b.rows)


def test_ghost_epoch_record_never_shadows_acknowledged_one(lubm_world,
                                                           tmp_path):
    """A commit that fails AFTER its WAL append leaves a ghost record
    reusing the next commit's epoch number. Replay must still apply the
    later ACKNOWLEDGED epoch (at-least-once: the ghost may appear, the
    acknowledged batch may never be lost)."""
    triples, ss = lubm_world
    Global.wal_dir = str(tmp_path / "wal")
    reset_wal()
    g = build_partition(triples, 0, 1)
    sc = StreamContext([g], ss)
    qid = sc.register(QSTAND)
    sc.feed(triples[:20], ts=0.0)
    # ghost: the append lands (seq durable), the insert dies, epoch stays 1
    faults.install(FaultPlan([FaultSpec("dynamic.insert", "shard_down")]))
    with pytest.raises(Exception):
        sc.feed(triples[20:40], ts=1.0)
    faults.clear()
    assert sc.epoch == 1
    # acknowledged: epoch 2 commits with DIFFERENT triples
    acked = triples[40:60]
    sc.feed(acked, ts=2.0)
    want_rows = set(map(tuple, _query_rows(g, ss)))
    want_standing = set(map(tuple, sc.continuous.result_set(qid).tolist()))

    g2 = build_partition(triples, 0, 1)
    sc2 = StreamContext([g2], ss)
    # no checkpoint in this scenario, so the registry does not ride along:
    # the client re-registers on restart, then the WAL tail replays
    assert sc2.register(QSTAND) == qid
    stats = RecoveryManager([g2], stream=sc2).recover()
    assert stats["replayed"]["epoch"] == 3  # epoch 1, ghost, acknowledged
    assert sc2.epoch == 2  # forced numbering: the ghost shares epoch 2
    # every acknowledged row is present; the ghost's extras may appear too
    # (unacknowledged-may-appear is the documented contract)
    assert want_rows <= set(map(tuple, _query_rows(g2, ss)))
    got_standing = set(map(tuple,
                           sc2.continuous.result_set(qid).tolist()))
    assert want_standing <= got_standing


def test_recover_without_checkpoint_replays_full_wal(lubm_world, tmp_path):
    from wukong_tpu.store.dynamic import insert_batch_into

    triples, ss = lubm_world
    Global.wal_dir = str(tmp_path / "wal")
    reset_wal()
    g1 = build_partition(triples, 0, 1)
    batch = _tri([70000, 17, 70001], [70002, 17, 70001])
    insert_batch_into([g1], batch, dedup=True)
    rows1 = _query_rows(g1, ss)
    # restart with no checkpoint at all: WAL alone must rebuild the state
    g2 = build_partition(triples, 0, 1)
    stats = RecoveryManager([g2]).recover()
    assert stats["checkpoint"] is None
    assert stats["replayed"]["insert"] == 1
    assert _query_rows(g2, ss) == rows1
    assert _segment_bytes(g1) == _segment_bytes(g2)


def test_checkpoint_truncates_covered_wal(lubm_world, tmp_path):
    from wukong_tpu.store.dynamic import insert_batch_into

    triples, ss = lubm_world
    Global.wal_dir = str(tmp_path / "wal")
    Global.checkpoint_dir = str(tmp_path / "ckpt")
    reset_wal()
    active_wal().segment_bytes = 256  # force rotation at test scale
    g = build_partition(triples, 0, 1)
    for i in range(8):
        insert_batch_into([g], _tri([70000 + i, 17, 70001]), dedup=True)
    segs_before = len(active_wal()._segments())
    assert segs_before > 1
    RecoveryManager([g]).checkpoint()
    assert len(active_wal()._segments()) < segs_before


@pytest.mark.chaos
def test_checkpoint_write_fault_leaves_no_partial_bundle(lubm_world,
                                                         tmp_path):
    triples, ss = lubm_world
    Global.checkpoint_dir = str(tmp_path / "ckpt")
    g = build_partition(triples, 0, 1)
    rm = RecoveryManager([g])
    faults.install(FaultPlan([FaultSpec("checkpoint.write", "shard_down")]))
    with pytest.raises(Exception):
        rm.checkpoint()
    faults.clear()
    # the fault fired before any bytes landed: nothing to mistake for a
    # valid (or half-written) bundle on the next recover
    assert rm.newest_checkpoint() is None
    assert rm.checkpoint()  # healthy path works right after
    assert rm.newest_checkpoint() is not None


def test_checkpoint_retention_keeps_fallback_replayable(lubm_world,
                                                        tmp_path):
    """Only the newest CKPT_RETAIN bundles survive, and the WAL is
    truncated behind the OLDEST retained one — so falling back from a
    corrupt newest bundle always still has its replay tail."""
    from wukong_tpu.runtime.recovery import CKPT_RETAIN
    from wukong_tpu.store.dynamic import insert_batch_into

    triples, ss = lubm_world
    Global.wal_dir = str(tmp_path / "wal")
    Global.checkpoint_dir = str(tmp_path / "ckpt")
    reset_wal()
    active_wal().segment_bytes = 256
    g = build_partition(triples, 0, 1)
    rm = RecoveryManager([g])
    for i in range(CKPT_RETAIN + 2):
        insert_batch_into([g], _tri([70000 + i, 17, 70001]), dedup=True)
        rm.checkpoint()
    bundles = list(rm._checkpoints())
    assert len(bundles) == CKPT_RETAIN
    oldest_seq = min(int(m["wal_seq"]) for _p, m in bundles)
    # every retained bundle's tail is fully available: contiguous from
    # its high-water mark onward
    seqs = [r.seq for r in active_wal().replay(after_seq=oldest_seq)]
    assert seqs == list(range(oldest_seq + 1, active_wal().next_seq))


def test_recover_falls_back_to_older_checkpoint_on_corrupt_parts(
        lubm_world, tmp_path):
    """README promise: a corrupt newest bundle is skipped in favor of an
    older one — including PAYLOAD corruption, not just a bad manifest —
    and a failed candidate must not leave stores half-restored."""
    from wukong_tpu.store.dynamic import insert_batch_into
    from wukong_tpu.store.persist import checkpoint_part_path

    triples, ss = lubm_world
    Global.wal_dir = str(tmp_path / "wal")
    Global.checkpoint_dir = str(tmp_path / "ckpt")
    reset_wal()
    g = build_partition(triples, 0, 1)
    rm = RecoveryManager([g])
    ck1 = rm.checkpoint()
    insert_batch_into([g], _tri([70000, 17, 70001]), dedup=True)
    ck2 = rm.checkpoint()
    rows_want = _query_rows(g, ss)
    # tamper the NEWEST bundle's partition payload
    part = checkpoint_part_path(ck2, 0)
    data = bytearray(open(part, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(part, "wb").write(bytes(data))

    g2 = build_partition(triples, 0, 1)
    stats = RecoveryManager([g2]).recover()
    assert stats["checkpoint"] == ck1  # fell back past the corrupt ck2
    # ck1 predates the insert; the WAL tail replays it back on top
    assert stats["replayed"]["insert"] >= 1
    assert _query_rows(g2, ss) == rows_want
    assert _segment_bytes(g) == _segment_bytes(g2)


def test_vstore_checkpoint_plus_wal_tail_is_byte_identical(lubm_world,
                                                           tmp_path):
    """The vector plane rides the same recovery contract as triples: a
    checkpoint carrying the embedding block + a 'vector' WAL tail
    (upsert AND tombstone records) replays to a store whose embeddings
    are BYTE-identical to an uninterrupted oracle run — and a k-NN scan
    over the recovered store returns exactly the oracle's answer."""
    from wukong_tpu.loader.datagen import make_vectors
    from wukong_tpu.vector import knn as vknn
    from wukong_tpu.vector.vstore import attach_vstore, upsert_batch_into

    triples, ss = lubm_world
    DIM = 8
    ids_a = np.arange(70000, 70050, dtype=np.int64)
    ids_b = np.arange(70025, 70070, dtype=np.int64)  # overlap rewrites

    # ---- oracle: uninterrupted, no durability machinery ----
    g_o = build_partition(triples, 0, 1)
    attach_vstore(g_o, DIM)
    upsert_batch_into([g_o], ids_a, make_vectors(ids_a, DIM))
    upsert_batch_into([g_o], ids_b, make_vectors(ids_b, DIM, seed=5))
    upsert_batch_into([g_o], ids_a[::4], tombstone=True)
    anchor = np.asarray(g_o.vstore.get(70060))
    want_v, want_s, _ = vknn.scan_topk(g_o.vstore, anchor, 10, "cosine")

    # ---- durable run: checkpoint after batch 1, crash after the tail ----
    Global.wal_dir = str(tmp_path / "wal")
    Global.checkpoint_dir = str(tmp_path / "ckpt")
    reset_wal()
    g_c = build_partition(triples, 0, 1)
    attach_vstore(g_c, DIM)
    upsert_batch_into([g_c], ids_a, make_vectors(ids_a, DIM))
    RecoveryManager([g_c]).checkpoint()
    upsert_batch_into([g_c], ids_b, make_vectors(ids_b, DIM, seed=5))
    upsert_batch_into([g_c], ids_a[::4], tombstone=True)
    del g_c  # abandon the objects, as a process kill would

    # ---- restart: fresh world, checkpoint + vector WAL tail ----
    g_r = build_partition(triples, 0, 1)
    stats = RecoveryManager([g_r]).recover()
    assert stats["checkpoint"] is not None
    assert stats["replayed"]["vector"] == 2  # batch 2 + the tombstones
    vo, vr = g_o.vstore, g_r.vstore
    assert vr.digest() == vo.digest()  # slot layout + bytes identical
    assert np.array_equal(vr.vids, vo.vids)
    assert vr.vecs.tobytes() == vo.vecs.tobytes()
    assert np.array_equal(vr.alive, vo.alive)
    assert vr.live_count() == vo.live_count()
    got_v, got_s, _ = vknn.scan_topk(vr, anchor, 10, "cosine")
    assert np.array_equal(got_v, want_v)
    assert got_s.tobytes() == want_s.tobytes()  # same kernel, same bytes


def test_recover_without_checkpoint_replays_vector_records(lubm_world,
                                                           tmp_path):
    """No checkpoint at all: the full-WAL path must rebuild the vstore
    from its 'vector' records alone (Global.enable_vectors stays off —
    replay must not depend on the serving knob)."""
    from wukong_tpu.loader.datagen import make_vectors
    from wukong_tpu.vector.vstore import attach_vstore, upsert_batch_into

    triples, ss = lubm_world
    Global.wal_dir = str(tmp_path / "wal")
    reset_wal()
    g1 = build_partition(triples, 0, 1)
    attach_vstore(g1, 8)
    vids = np.arange(70000, 70030, dtype=np.int64)
    upsert_batch_into([g1], vids, make_vectors(vids, 8))
    g2 = build_partition(triples, 0, 1)
    stats = RecoveryManager([g2]).recover()
    assert stats["checkpoint"] is None
    assert stats["replayed"]["vector"] == 1
    assert g2.vstore.digest() == g1.vstore.digest()


def test_stream_registry_state_roundtrip(lubm_world):
    triples, ss = lubm_world
    g = build_partition(triples, 0, 1)
    sc = StreamContext([g], ss)
    qid = sc.register(QSTAND, callback=lambda d: None)
    sc.feed(triples[:25])
    state = sc.continuous.export_state()
    g2 = build_partition(triples, 0, 1)
    sc2 = StreamContext([g2], ss)
    sc2.continuous.import_state(state)
    sq1 = sc.continuous.queries[qid]
    sq2 = sc2.continuous.queries[qid]
    assert sq1.seen == sq2.seen
    assert len(sq1.sink) == len(sq2.sink)
    assert sq2.callback is None  # closures don't survive restarts
    assert sc2.continuous._next_qid == sc.continuous._next_qid


# ---------------------------------------------------------------------------
# scheduler: idle backoff + rebuild lane
# ---------------------------------------------------------------------------

def test_idle_backoff_caps_at_deep_relax():
    # the capped exponential (ROADMAP follow-up i): deep cap, tiny floor
    assert EnginePool.IDLE_SNOOZE_MIN_US == 10
    assert EnginePool.IDLE_SNOOZE_MAX_US >= 10_000


def test_wake_on_submit_from_deep_idle():
    """An engine sleeping at the deep cap must pick up a submit
    immediately (the semaphore IS the wake event), not after the cap."""
    import time

    class Eng:
        def execute(self, q):
            return ("done", q)

    pool = EnginePool(num_engines=2, make_engine=lambda tid: Eng())
    pool.start()
    try:
        time.sleep(0.3)  # engines relax to the deep cap
        t0 = time.monotonic()
        qid = pool.submit(object())
        out = pool.wait(qid, timeout=5.0)
        dt = time.monotonic() - t0
        assert out[0] == "done"
        # generous bound (slow CI): far below a multi-cap poll delay,
        # proving the wake came from the semaphore, not the timeout
        assert dt < 1.0
    finally:
        pool.stop()


def test_rebuild_lane_executes_jobs_in_background():
    class Eng:
        def execute(self, q):
            return q

    pool = EnginePool(num_engines=2, make_engine=lambda tid: Eng())
    pool.start()
    try:
        ran = threading.Event()
        job = RebuildJob(lambda: ran.set(), label="t")
        assert pool.submit(job, lane="rebuild") == -1
        assert job.done.wait(5.0) and ran.is_set()
        assert pool.poll() == []  # fire-and-forget: no pool-side result
    finally:
        pool.stop()


def test_rebuild_lane_settled_on_dead_pool():
    pool = EnginePool(num_engines=1, make_engine=lambda tid: None)
    pool._dead[0] = True  # whole pool dead, nothing running
    job = RebuildJob(lambda: None, label="t")
    pool.submit(job, lane="rebuild")
    assert job.done.wait(1.0)  # fail_all settled it instead of stranding


# ---------------------------------------------------------------------------
# lint gate 3: mutation paths route through the WAL hook
# ---------------------------------------------------------------------------

def test_lint_wal_gate_clean_on_repo():
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "lint_obs", os.path.join(root, "scripts", "lint_obs.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.violations(os.path.join(root, "wukong_tpu")) == []


def test_lint_wal_gate_flags_unhooked_mutation(tmp_path):
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "lint_obs", os.path.join(root, "scripts", "lint_obs.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "sneaky.py").write_text(
        "def hot_path(g, t):\n"
        "    insert_triples(g, t)\n")
    bad = mod.violations(str(pkg))
    assert len(bad) == 1 and "WAL append hook" in bad[0]
    # the hook in the same top-level function satisfies the gate
    (pkg / "sneaky.py").write_text(
        "def hot_path(g, t):\n"
        "    maybe_wal_append('insert', t, True)\n"
        "    insert_triples(g, t)\n")
    assert mod.violations(str(pkg)) == []
