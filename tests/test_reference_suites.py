"""Robustness sweep: every query in the reference's LUBM sub-suites.

The reference validates these suites manually against its console
(scripts/sparql_query/lubm/{union,optional,filter,order,dedup,attr,batch}).
Here every file must either execute cleanly (status SUCCESS) on our LUBM-1
world or fail with a *clean* WukongError (e.g. UNKNOWN_SUB for constants our
synthesized data doesn't contain) — never crash.
"""

import glob
import os

import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.sparql.parser import Parser
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.utils.errors import ErrorCode, WukongError

SUITES = "/root/reference/scripts/sparql_query/lubm"

FILES = sorted(
    f for suite in ("union", "optional", "filter", "order", "dedup", "attr")
    for f in glob.glob(f"{SUITES}/{suite}/*")
    if os.path.isfile(f) and not f.endswith(".md") and "README" not in f)


@pytest.fixture(scope="module")
def world():
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    return g, ss


@pytest.mark.parametrize(
    "qfile", FILES,
    ids=[f"{os.path.basename(os.path.dirname(f))}-{os.path.basename(f)}"
         for f in FILES])
def test_suite_query_executes_or_fails_cleanly(world, qfile, monkeypatch):
    g, ss = world
    monkeypatch.setattr(Global, "enable_vattr", True)
    text = open(qfile).read()
    try:
        q = Parser(ss).parse(text)
    except WukongError as e:
        # constants absent from synthesized data / parser-rejected shapes
        assert e.code in (ErrorCode.UNKNOWN_SUB, ErrorCode.SYNTAX_ERROR), qfile
        return
    try:
        heuristic_plan(q)
    except WukongError as e:
        assert e.code == ErrorCode.UNKNOWN_PLAN, qfile
        return
    eng = CPUEngine(g, ss)
    eng.execute(q)
    # engine failures must be clean status codes, never raised exceptions
    assert isinstance(q.result.status_code, ErrorCode), qfile


def test_union_suite_counts(world):
    """union/q1: |Course ∪ University names| == |Course names| + |Univ names|."""
    g, ss = world
    text = open(f"{SUITES}/union/q1").read()
    q = Parser(ss).parse(text)
    heuristic_plan(q)
    CPUEngine(g, ss).execute(q)
    assert q.result.status_code == 0
    from wukong_tpu.loader.lubm import P, T
    from wukong_tpu.types import IN

    n_course = len(g.get_index(T["Course"], IN))
    n_univ_named = len(g.get_index(T["University"], IN))  # all have names
    assert q.result.nrows == n_course + n_univ_named
