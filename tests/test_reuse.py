"""Serving-cache observatory (ISSUE 13): template popularity ledger,
observe-only shadow cache, and reuse/invalidation telemetry.

Acceptance surface: the shadow cache's hit/miss/evict/invalidate stream
matches a hand-simulated key trace; every store-mutation path (dynamic
insert batch, stream epoch, migration cutover) kills the stale shadow
keys and journals a ``cache.invalidate`` event with the version edge;
uncacheable-shape classification agrees with the plan cache's refusal
rules on every class; tenant attribution and bounded template
cardinality hold; ``/cache`` scrapes (incl. concurrently with live
serving) are crash-free under the lockdep checker; the off knob is
zero-touch; ``Emulator.run_readmostly`` predicts >=0.5 hit rate on the
Zipfian mix with the store digest bit-untouched; and the
``cache-coherence`` analysis gate holds the surface statically. The
whole module runs fully lockdep-checked.
"""

import json
import os
import socket
import threading
import urllib.request

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.lubm import UB, VirtualLubmStrings, generate_lubm
from wukong_tpu.obs.events import get_journal
from wukong_tpu.obs.metrics import get_registry
from wukong_tpu.obs.reuse import (
    CACHE_INPUTS,
    INVALIDATION_CAUSES,
    OVERFLOW_TEMPLATE,
    ReuseObservatory,
    ShadowCache,
    TemplatePopularityLedger,
    classify,
    get_reuse,
    maybe_note_invalidation,
    render_cache,
)
from wukong_tpu.obs.tsdb import get_tsdb
from wukong_tpu.runtime import faults
from wukong_tpu.runtime.batcher import (
    build_plan_recipe,
    snapshot_patterns,
    template_signature,
)
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.sparql.ir import Pattern, PatternGroup, SPARQLQuery
from wukong_tpu.store.dynamic import insert_batch_into
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.store.persist import gstore_digest
from wukong_tpu.types import NORMAL_ID_START, OUT
from wukong_tpu.utils.errors import ErrorCode

pytestmark = pytest.mark.reuse


@pytest.fixture(autouse=True, scope="module")
def _lockdep_checked():
    """The reuse suite runs fully lockdep-checked (the observatory-suite
    posture): the ledger/shadow leaf locks feed the acquisition-order
    graph, so the concurrent-scrape test doubles as a lock-order
    regression test."""
    from wukong_tpu.analysis import lockdep

    lockdep.install(True)
    yield
    try:
        assert lockdep.cycles() == [], lockdep.cycles()
        assert lockdep.leaf_violations() == [], lockdep.leaf_violations()
    finally:
        lockdep.install(False)


@pytest.fixture(scope="module")
def world():
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    return {"g": g, "ss": ss, "triples": triples}


@pytest.fixture(scope="module")
def proxy(world):
    return Proxy(world["g"], world["ss"],
                 CPUEngine(world["g"], world["ss"]))


@pytest.fixture(scope="module")
def texts(world):
    g, ss = world["g"], world["ss"]
    pid = ss.str2id(f"<{UB}advisor>")
    anchors = np.asarray(g.get_index(pid, OUT))
    return [f"SELECT ?s WHERE {{ ?s <{UB}advisor> "
            f"{ss.id2str(int(a))} . }}" for a in anchors[:64]]


@pytest.fixture(autouse=True)
def _hygiene(monkeypatch):
    """Reuse knobs at defaults, every process-wide ring clean, no fault
    plan leaking across tests."""
    monkeypatch.setattr(Global, "enable_reuse", True)
    monkeypatch.setattr(Global, "reuse_sample_every", 1)
    monkeypatch.setattr(Global, "enable_events", True)
    monkeypatch.setattr(Global, "enable_tracing", False)
    get_reuse().reset()
    get_journal().clear()
    get_tsdb().reset()
    faults.clear()
    yield
    faults.clear()
    get_reuse().reset()


def _const_query(c0: int = NORMAL_ID_START + 5, pred: int = 17):
    """A planned-shape const-start query (the cacheable exemplar)."""
    q = SPARQLQuery()
    q.pattern_group = PatternGroup(
        patterns=[Pattern(subject=c0, predicate=pred, direction=OUT,
                          object=-1)])
    q.result.nvars = 1
    q.result.required_vars = [-1]
    return q


# ---------------------------------------------------------------------------
# uncacheable-shape classification: parity with PlanCache's rules
# ---------------------------------------------------------------------------

def _recipe_refuses(q) -> bool:
    """True when the plan cache would refuse this query too (signature
    missing, or build_plan_recipe returning None)."""
    sig = template_signature(q)
    if sig is None:
        return True
    return build_plan_recipe(snapshot_patterns(q), q) is None


def test_classify_cacheable_and_recipe_agree():
    q = _const_query()
    key, reason = classify(q)
    assert key is not None and reason is None
    assert not _recipe_refuses(q)
    # the key is exactly the item-7 material: sig digest + consts +
    # filters + projection + blind
    digest, consts, _filters, rvars, blind = key
    assert digest.startswith("sig:")
    assert consts == (NORMAL_ID_START + 5,)
    assert rvars == (-1,)


@pytest.mark.parametrize("mutate,reason", [
    (lambda q: q.pattern_group.unions.append(PatternGroup()), "shape"),
    (lambda q: setattr(q, "planner_empty", True), "planner_empty"),
    (lambda q: setattr(q, "corun_enabled", True), "corun"),
])
def test_classify_refusals_mirror_plan_cache(mutate, reason):
    q = _const_query()
    mutate(q)
    key, got = classify(q)
    assert key is None and got == reason
    assert _recipe_refuses(q)  # the plan cache refuses the same shape


def test_classify_ambiguous_const_parity():
    """A duplicated abstracted constant is positionally ambiguous for
    the plan recipe AND for the result-cache key."""
    c = NORMAL_ID_START + 9
    q = SPARQLQuery()
    q.pattern_group = PatternGroup(patterns=[
        Pattern(subject=c, predicate=17, direction=OUT, object=-1),
        Pattern(subject=c, predicate=19, direction=OUT, object=-2),
    ])
    q.result.nvars = 2
    q.result.required_vars = [-1, -2]
    key, reason = classify(q)
    assert key is None and reason == "ambiguous_const"
    assert _recipe_refuses(q)


def test_observe_partial_and_error_are_uncacheable():
    obs = ReuseObservatory(window=64, capacity=64)
    q = _const_query()
    q.result.status_code = ErrorCode.QUERY_TIMEOUT
    q.result.complete = False
    obs.observe(q, "default", version=0)
    q2 = _const_query()
    q2.result.status_code = ErrorCode.SUCCESS
    q2.result.complete = False
    obs.observe(q2, "default", version=0)
    st = obs.shadow.stats()
    assert st["hits"] + st["misses"] == 0  # no probe for either reply
    ranked = obs.ledger.report(k=4)["ranked"]
    assert ranked and not ranked[0]["cacheable"]
    by_reason = ranked[0]["uncacheable_by_reason"]
    assert by_reason.get("error") == 1 and by_reason.get("partial") == 1


# ---------------------------------------------------------------------------
# shadow cache: oracle trace, version kills, eviction
# ---------------------------------------------------------------------------

def test_shadow_matches_hand_simulated_trace():
    """Drive a scripted (key, version) trace through the shadow cache and
    through a hand-rolled LRU simulation; the outcome streams must be
    identical, including the capacity-forced evictions."""
    sh = ShadowCache(capacity=3)
    trace = [("a", 1), ("b", 1), ("a", 1), ("c", 1), ("d", 1), ("b", 1),
             ("a", 1), ("a", 1), ("d", 1), ("c", 1)]
    sim: dict = {}
    want = []
    for key, v in trace:
        k = (key, v)
        if k in sim:
            want.append("hit")
            sim.pop(k)
            sim[k] = True  # move to end (python dicts keep order)
        else:
            want.append("miss")
            sim[k] = True
            while len(sim) > 3:
                sim.pop(next(iter(sim)))
    got = ["hit" if sh.probe(key, v, rows=2, nbytes=16) else "miss"
           for key, v in trace]
    assert got == want
    st = sh.stats()
    assert st["hits"] == want.count("hit")
    assert st["misses"] == want.count("miss")
    assert st["keys"] == len(sim) and st["keys"] <= 3
    assert st["evicts"] == want.count("miss") - 3 + (3 - len(sim))
    # bytes saved = hits x the simulated payload size
    assert st["bytes_saved"] == 16 * want.count("hit")


def test_shadow_version_kill_is_selective_and_purge_total():
    sh = ShadowCache(capacity=16)
    sh.probe("a", 1, 1, 8)
    sh.probe("b", 1, 1, 8)
    sh.probe("c", 2, 1, 8)
    killed = sh.invalidate(2, "insert")
    assert killed == 2  # the two v1 keys die; the v2 key survives
    assert sh.stats()["keys"] == 1
    assert sh.probe("c", 2, 1, 8) is True  # survivor still hits
    killed = sh.invalidate(None, "restore")  # conservative full purge
    assert killed == 1 and sh.stats()["keys"] == 0


def test_shadow_staleness_histogram_observes_edges():
    def count():
        s = get_registry().snapshot()["wukong_reuse_staleness_s"]
        return s["series"][0]["count"] if s["series"] else 0

    before = count()
    sh = ShadowCache(capacity=4)
    sh.invalidate(1, "insert")
    sh.invalidate(2, "insert")  # the second edge observes the window
    assert count() == before + 1


# ---------------------------------------------------------------------------
# ledger: popularity, tenants, cardinality, zipf
# ---------------------------------------------------------------------------

def test_ledger_tenant_attribution_and_versions():
    led = TemplatePopularityLedger(window=32)
    for _ in range(3):
        led.charge("sig:aaaa0001", "gold", version=7)
    led.charge("sig:aaaa0001", "bulk", version=8)
    r = led.report(k=2)["ranked"][0]
    assert r["reads"] == 4
    assert r["tenants"] == {"gold": 3, "bulk": 1}
    assert r["last_version"] == 8


def test_ledger_bounded_template_cardinality():
    led = TemplatePopularityLedger(window=8, max_templates=2)
    assert led.charge("t1", "d", 0) == "t1"
    assert led.charge("t2", "d", 0) == "t2"
    assert led.charge("t3", "d", 0) == OVERFLOW_TEMPLATE
    assert led.charge("t1", "d", 0) == "t1"  # known labels keep counting
    rep = led.report(k=8)
    assert {r["template"] for r in rep["ranked"]} == {
        "t1", "t2", OVERFLOW_TEMPLATE}


def test_ledger_zipf_alpha_estimate():
    led = TemplatePopularityLedger(window=8)
    for rank, reads in enumerate([1000, 500, 333, 250, 200], start=1):
        for _ in range(reads):
            led.charge(f"t{rank}", "d", 0)
    assert led.zipf_alpha() == pytest.approx(1.0, abs=0.1)
    # degenerate rankings answer 0, never a fit over <3 points
    led2 = TemplatePopularityLedger(window=8)
    led2.charge("only", "d", 0)
    assert led2.zipf_alpha() == 0.0


# ---------------------------------------------------------------------------
# invalidation telemetry: every mutation path lands the event
# ---------------------------------------------------------------------------

def _serve_all(proxy, texts, n=None):
    for t in texts[:n] if n else texts:
        q = proxy.serve_query(t, blind=True)
        assert q.result.status_code == ErrorCode.SUCCESS


def test_dynamic_insert_kills_and_journals(proxy, world, texts):
    _serve_all(proxy, texts, n=12)
    st0 = get_reuse().shadow.stats()
    assert st0["keys"] >= 12  # distinct consts = distinct shadow keys
    batch = world["triples"][:64]
    insert_batch_into([world["g"]], batch, dedup=False)
    evs = get_journal().last(kind="cache.invalidate")
    assert evs, "no cache.invalidate journaled by the insert path"
    ev = evs[-1]
    assert ev.attrs["cause"] == "insert"
    assert ev.attrs["killed"] >= 12
    assert ev.attrs["version_to"] == world["g"].version
    assert get_reuse().shadow.stats()["keys"] == 0
    # the next read of the same template misses (new version), then hits
    q = proxy.serve_query(texts[0], blind=True)
    assert q.result.status_code == ErrorCode.SUCCESS
    st1 = get_reuse().shadow.stats()
    proxy.serve_query(texts[0], blind=True)
    st2 = get_reuse().shadow.stats()
    assert st1["misses"] > st0["misses"]
    assert st2["hits"] == st1["hits"] + 1


def test_stream_epoch_kills_and_journals(proxy, world, texts):
    _serve_all(proxy, texts, n=6)
    assert get_reuse().shadow.stats()["keys"] >= 6
    proxy.stream_feed(world["triples"][:32])
    evs = get_journal().last(kind="cache.invalidate")
    assert evs and evs[-1].attrs["cause"] == "epoch"
    assert evs[-1].attrs["killed"] >= 6
    assert "epoch" in evs[-1].attrs
    assert get_reuse().shadow.stats()["keys"] == 0


N_SHARDS = 4


class _Mesh:
    devices = np.empty(N_SHARDS, dtype=object)


@pytest.mark.chaos
def test_migration_cutover_purges_and_journals(world, monkeypatch):
    """The read-path swap is a conservative purge: the clone's version
    counter travels with the bytes, so the swap itself is the edge."""
    from wukong_tpu.obs.placement import MigrationPlan
    from wukong_tpu.parallel.sharded_store import ShardedDeviceStore
    from wukong_tpu.runtime.migration import get_migrator
    from wukong_tpu.utils.timer import get_usec

    stores = [build_partition(world["triples"], i, N_SHARDS)
              for i in range(N_SHARDS)]
    sstore = ShardedDeviceStore(stores, _Mesh(), replication_factor=1)
    monkeypatch.setattr(Global, "migration_enable", True)
    monkeypatch.setattr(Global, "wal_dir", "")
    mig = get_migrator()
    mig.reset()
    mig.attach(sstore=sstore)
    get_reuse().shadow.probe("k1", 0, 1, 8)
    get_reuse().shadow.probe("k2", 0, 1, 8)
    plan = MigrationPlan(
        plan_id="mp-reuse", t_us=get_usec(), donor_shard=3,
        recipient_host=2, predicted_move_bytes=1 << 20,
        bytes_source="estimate", donor_rate_per_s=4.0,
        mean_rate_per_s=1.0, imbalance_before=2.5, imbalance_after=1.5,
        window_s=60.0, inputs={}, reason="reuse-test")
    try:
        job = mig.run_plan(plan)
        assert job.phase == "done"
    finally:
        mig.reset()
    evs = get_journal().last(kind="cache.invalidate")
    assert evs and evs[-1].attrs["cause"] == "cutover"
    assert evs[-1].shard == 3
    assert evs[-1].attrs["version_to"] == "purge"
    assert evs[-1].attrs["killed"] == 2
    assert get_reuse().shadow.stats()["keys"] == 0


def test_invalidation_causes_registry_is_live():
    """Every declared cause round-trips through the hook; an undeclared
    cause is the gate's business, not the runtime's."""
    for cause in INVALIDATION_CAUSES:
        maybe_note_invalidation(cause, version=None)
    kinds = [e.attrs["cause"]
             for e in get_journal().last(kind="cache.invalidate")]
    assert kinds == list(INVALIDATION_CAUSES)


# ---------------------------------------------------------------------------
# the proxy reply hook: popularity + tenants end to end
# ---------------------------------------------------------------------------

def test_reply_hook_popularity_and_tenants(proxy, texts):
    for k, t in enumerate(texts[:10]):
        proxy.serve_query(t, blind=True,
                          tenant="gold" if k % 2 else "bulk")
    rep = get_reuse().report(k=4)
    pop = rep["popularity"]
    assert pop["total_reads"] == 10
    # all 10 texts are one TEMPLATE (consts abstracted) — the ledger
    # collapses them; the shadow cache keeps 10 distinct keys
    assert pop["templates"] == 1
    r = pop["ranked"][0]
    assert r["template"].startswith("sig:")
    assert r["tenants"] == {"gold": 5, "bulk": 5}
    assert r["cacheable"] is True
    assert rep["shadow"]["keys"] == 10


def test_cache_inputs_all_registered():
    snap = get_registry().snapshot()
    missing = [m for m in CACHE_INPUTS.values() if m not in snap]
    assert missing == [], missing


def test_trend_reads_through_tsdb(proxy, texts):
    """The trend read rides the GLOBAL tsdb ring, whose background
    sampler (started by the proxy) appends REAL-timestamp samples —
    synthetic now_us markers here would be evicted as ancient the moment
    a real tick lands, so the brackets use real time and the assertions
    check shape, not exact rates."""
    from wukong_tpu.obs.reuse import reuse_trend

    ts = get_tsdb()
    ts.sample_once()
    _serve_all(proxy, texts, n=8)
    ts.sample_once()
    trend = reuse_trend()
    assert trend.get("reads_per_s", 0) > 0
    assert trend.get("probes_per_s", 0) > 0
    # probes = hit + miss only (8 distinct consts -> 8 misses here);
    # reads and probes moved in lockstep inside the bracket
    assert trend["probes_per_s"] == pytest.approx(trend["reads_per_s"],
                                                  rel=0.01)


# ---------------------------------------------------------------------------
# parse/plan cache result metrics
# ---------------------------------------------------------------------------

def test_parse_plan_cache_result_metrics(proxy, texts):
    m_parse = get_registry().counter("wukong_parse_cache_total",
                                     labels=("result",))
    m_plan = get_registry().counter("wukong_plan_cache_total",
                                    labels=("result",))
    text = texts[-1]
    p_hit0 = m_parse.value(result="hit")
    proxy.serve_query(text, blind=True)
    proxy.serve_query(text, blind=True)
    assert m_parse.value(result="hit") >= p_hit0 + 1
    inv0 = m_plan.value(result="invalidated")
    proxy._plan_cache.clear()  # the store-change contract
    assert m_plan.value(result="invalidated") > inv0
    # hit rates surface on /top's template section and /cache
    from wukong_tpu.obs.profile import render_top
    from wukong_tpu.obs.reuse import cache_hit_rates

    rates = cache_hit_rates()
    assert rates["parse"]["hit_rate"] is not None
    text_out, js = render_top()
    assert "caches:" in text_out and "parse" in text_out
    assert js["caches"]["parse"]["total"] > 0


# ---------------------------------------------------------------------------
# off-knob zero-touch
# ---------------------------------------------------------------------------

def test_off_knob_is_zero_touch(proxy, world, texts, monkeypatch):
    monkeypatch.setattr(Global, "enable_reuse", False)
    _serve_all(proxy, texts, n=4)
    assert maybe_note_invalidation("insert", version=1) == 0
    insert_batch_into([world["g"]], world["triples"][:8], dedup=True)
    st = get_reuse().shadow.stats()
    assert st["hits"] + st["misses"] == 0 and st["keys"] == 0
    assert get_reuse().ledger.report(k=4)["total_reads"] == 0
    assert get_journal().last(kind="cache.invalidate") == []


def test_probe_sampling_knob(proxy, texts, monkeypatch):
    monkeypatch.setattr(Global, "reuse_sample_every", 4)
    _serve_all(proxy, texts, n=8)
    rep = get_reuse().report(k=2)
    assert rep["popularity"]["total_reads"] == 8  # ledger always charges
    st = rep["shadow"]
    assert st["hits"] + st["misses"] == 2  # 1-in-4 probes
    assert rep["sample_every"] == 4


# ---------------------------------------------------------------------------
# surfaces: /cache scrape, console verb, Monitor line
# ---------------------------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(port, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5).read().decode()


def test_cache_scrape_and_concurrent_serving(proxy, texts, monkeypatch):
    from wukong_tpu.obs import maybe_start_metrics_http, stop_metrics_http

    port = _free_port()
    monkeypatch.setattr(Global, "metrics_host", "127.0.0.1")
    assert maybe_start_metrics_http(port=port) is not None
    try:
        _serve_all(proxy, texts, n=8)
        body = _get(port, "/cache")
        assert "wukong-cache" in body and "SHADOW" in body
        js = json.loads(_get(port, "/cache.json"))
        assert js["shadow"]["misses"] >= 8
        assert js["popularity"]["ranked"][0]["template"].startswith("sig:")
        assert js["inputs"] == CACHE_INPUTS
        # concurrent scrape under live serving: crash-free, every scrape
        # a 200 (the lockdep module fixture asserts zero findings)
        errors = []

        def scraper():
            try:
                for _ in range(12):
                    json.loads(_get(port, "/cache.json"))
            except Exception as e:  # pragma: no cover - failure surface
                errors.append(e)

        def server():
            try:
                for t in texts[:24]:
                    proxy.serve_query(t, blind=True)
            except Exception as e:  # pragma: no cover - failure surface
                errors.append(e)

        threads = [threading.Thread(target=scraper) for _ in range(2)] + [
            threading.Thread(target=server) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert errors == []
    finally:
        stop_metrics_http()


def test_console_cache_verb(proxy, texts, capsys):
    from wukong_tpu.runtime.console import Console

    _serve_all(proxy, texts, n=4)
    con = Console(proxy)
    assert con.run_command("cache") is True
    out = capsys.readouterr().out
    assert "wukong-cache" in out and "TEMPLATES by reads" in out
    assert con.run_command("cache -j -k 2") is True
    js = json.loads(capsys.readouterr().out)
    assert js["shadow"]["misses"] >= 4


def test_monitor_cache_line(proxy, texts):
    from wukong_tpu.runtime.monitor import Monitor

    mon = Monitor()
    assert mon.cache_lines() == []  # quiet before traffic
    _serve_all(proxy, texts, n=6)
    proxy.serve_query(texts[0], blind=True)  # one hit for the rate
    lines = mon.cache_lines()
    assert len(lines) == 1 and lines[0].startswith("Cache[shadow ")
    assert "killed" in lines[0]


def test_render_cache_off_knob_says_so(monkeypatch):
    monkeypatch.setattr(Global, "enable_reuse", False)
    text, js = render_cache()
    assert "enable_reuse is OFF" in text
    assert js["enabled"] is False


# ---------------------------------------------------------------------------
# run_readmostly acceptance (item 7's fixture, scaled down)
# ---------------------------------------------------------------------------

def test_run_readmostly_acceptance(world):
    from wukong_tpu.runtime.emulator import Emulator

    # a PRIVATE world: the write phase mutates the store, and the
    # module-scoped fixtures must stay pristine for the other tests
    g = build_partition(world["triples"], 0, 1)
    ss = world["ss"]
    proxy = Proxy(g, ss, CPUEngine(g, ss))
    pid = ss.str2id(f"<{UB}advisor>")
    anchors = np.asarray(g.get_index(pid, OUT))
    texts = [f"SELECT ?s WHERE {{ ?s <{UB}advisor> "
             f"{ss.id2str(int(a))} . }}" for a in anchors[:48]]
    digest0 = gstore_digest(g)
    emu = Emulator(proxy)
    rep = emu.run_readmostly(
        texts, reads=150, warmup_reads=80, write_rates=(0.0, 0.1),
        zipf_a=1.3, seed=3, write_batch=world["triples"][:512],
        batch_rows=16, tenants=["gold", "bulk"])
    assert rep["predicted_hit_rate"] is not None
    assert rep["predicted_hit_rate"] >= 0.5
    assert rep["degrades"] is True
    assert rep["store_untouched"] is True
    # the write phase really killed keys and really mutated the store
    wp = rep["phases"][1]
    assert wp["writes"] > 0 and wp["keys_killed"] > 0
    assert wp["hit_rate"] <= rep["predicted_hit_rate"] + 0.05
    assert gstore_digest(g) != digest0
    # write-side events landed on the same timeline as the reads
    causes = {e.attrs["cause"]
              for e in get_journal().last(kind="cache.invalidate")}
    assert "insert" in causes
    # tenant attribution rode along
    r = rep["report"]["popularity"]["ranked"][0]
    assert set(r["tenants"]) == {"gold", "bulk"}


# ---------------------------------------------------------------------------
# the cache-coherence analysis gate (pos/neg fixtures)
# ---------------------------------------------------------------------------

def test_cache_coherence_gate_fixtures(tmp_path):
    from wukong_tpu.analysis import run_analysis

    def write(tree: dict) -> str:
        import shutil

        root = tmp_path / "pkg"
        if root.exists():
            shutil.rmtree(root)
        for rel, src in tree.items():
            p = root / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(src)
        return str(root)

    bad = write({
        "obs/reuse.py": (
            "CACHE_INPUTS = {'pop': 'wukong_nope_total'}\n"
            "INVALIDATION_CAUSES = ('insert', 'ghost')\n"
            "def trend(ts):\n"
            "    return ts.rate('wukong_rogue_total')\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self.keys = {}\n"
            "        self.lock = make_lock('reuse.x')\n"),
        "store/dynamic.py": (
            "def insert_batch(stores):\n"
            "    for g in stores:\n"
            "        insert_triples(g)\n"
            "def other():\n"
            "    maybe_note_invalidation('insert')\n"
            "    maybe_note_invalidation('bogus')\n")})
    out = run_analysis(bad, plugins=["cache-coherence"])
    msgs = "\n".join(str(v) for v in out)
    assert "wukong_nope_total" in msgs   # input with no registered metric
    assert "'ghost'" in msgs             # declared cause with no call site
    assert "'bogus'" in msgs             # undeclared cause at a call site
    assert "wukong_rogue_total" in msgs  # undeclared trend read
    assert "without a cache-invalidation note" in msgs  # unhooked insert
    assert "A.keys" in msgs              # unannotated shared structure
    assert "reuse.x" in msgs             # undeclared leaf lock

    good = write({
        "obs/reuse.py": (
            "CACHE_INPUTS = {'pop': 'wukong_ok_total'}\n"
            "INVALIDATION_CAUSES = ('insert',)\n"
            "declare_leaf('reuse.x')\n"
            "def reg(r):\n"
            "    return r.counter('wukong_ok_total', 'h')\n"
            "def trend(ts):\n"
            "    return ts.rate('wukong_ok_total')\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self.keys = {}  # guarded by: _lock\n"
            "        self.lock = make_lock('reuse.x')\n"),
        "store/dynamic.py": (
            "def insert_batch(stores):\n"
            "    for g in stores:\n"
            "        insert_triples(g)\n"
            "    maybe_note_invalidation('insert')\n")})
    assert run_analysis(good, plugins=["cache-coherence"]) == []


def test_repo_cache_gate_clean():
    from wukong_tpu.analysis import run_analysis

    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "wukong_tpu")
    assert run_analysis(pkg, plugins=["cache-coherence"]) == []
