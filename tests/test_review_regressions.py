"""Regression tests for review findings on the data layer."""

import pytest

from wukong_tpu.config import GlobalConfig
from wukong_tpu.loader.lubm import VirtualLubmStrings
from wukong_tpu.types import is_tpid


def test_config_clamp_order_independent():
    a = GlobalConfig(); a.finalize()
    a.load_str("global_mt_threshold 64\nglobal_num_engines 16")
    b = GlobalConfig(); b.finalize()
    b.load_str("global_num_engines 16\nglobal_mt_threshold 64")
    assert a.mt_threshold == b.mt_threshold == 16


def test_config_unknown_key_warns_and_continues():
    cfg = GlobalConfig(); cfg.finalize()
    cfg.load_str("global_silent off\nglobal_not_a_real_knob 1\nglobal_mt_threshold 2")
    assert cfg.silent is False and cfg.mt_threshold == 2


def test_config_bad_value_applies_nothing():
    cfg = GlobalConfig(); cfg.finalize()
    before = cfg.silent
    with pytest.raises(ValueError):
        cfg.load_str("global_silent off\nglobal_mt_threshold banana")
    assert cfg.silent is before


def test_virtual_strings_out_of_range_email():
    vs = VirtualLubmStrings(1)
    assert not vs.exist('"email0@Department0.University99.edu"')
    assert not vs.exist('"email0@Department99.University0.edu"')
    assert not vs.exist('"email9999999@Department0.University0.edu"')


def test_is_tpid_excludes_reserved():
    assert not is_tpid(0) and not is_tpid(1)
    assert is_tpid(2) and not is_tpid(1 << 17)


def test_empty_segment_vectorized_paths():
    import numpy as np

    from wukong_tpu.store.segment import CSRSegment

    seg = CSRSegment.empty()
    _, deg = seg.lookup_many(np.array([1, 2]))
    assert deg.tolist() == [0, 0]
    assert seg.contains_pair(np.array([1]), np.array([2])).tolist() == [False]


def test_datagen_prefix_attr_entity_not_split(tmp_path):
    from wukong_tpu.loader.datagen import convert_dir

    src = tmp_path / "nt"
    src.mkdir()
    (src / "f.nt").write_text(
        "@prefix ex: <http://ex.org/> .\n"
        "ex:a <http://ex.org/p> ex:b .\n"
        'ex:a <http://ex.org/age> "40"^^xsd:int .\n')
    convert_dir(str(src), str(tmp_path / "id"))
    norm = (tmp_path / "id" / "str_normal").read_text()
    lines = [l for l in norm.splitlines() if l]
    assert len(lines) == 2  # <http://ex.org/a> and <http://ex.org/b>, no ex:a
    assert all(l.startswith("<http://ex.org/") for l in lines)


def _lubm1_world():
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
    from wukong_tpu.store.gstore import build_partition

    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    return g, ss, CPUEngine(g, ss)


UB = "http://swat.cse.lehigh.edu/onto/univ-bench.owl#"


def test_heuristic_pred_var_const_subject_known_object():
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.sparql.parser import Parser

    g, ss, eng = _lubm1_world()
    d0 = "<http://www.Department0.University0.edu>"
    text = f"""SELECT ?u ?p WHERE {{
        {d0} <{UB}subOrganizationOf> ?u .
        {d0} ?p ?u . }}"""
    q = Parser(ss).parse(text)
    heuristic_plan(q)
    eng.execute(q)
    assert q.result.status_code == 0
    assert q.result.nrows == 1  # (University0, subOrganizationOf)


def test_plan_file_order_validation():
    from wukong_tpu.planner.plan_file import set_plan
    from wukong_tpu.sparql.parser import Parser

    _, ss, _ = _lubm1_world()
    q = Parser(ss).parse(
        f"SELECT ?x WHERE {{ ?x <{UB}subOrganizationOf> <http://www.University0.edu> . }}")
    assert not set_plan(q.pattern_group, "0 >\n")
    assert not set_plan(q.pattern_group, "5 >\n")


def test_filter_bound_and_order_by_unbound_var():
    from wukong_tpu.sparql.parser import Parser
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.utils.errors import ErrorCode

    g, ss, eng = _lubm1_world()
    q = Parser(ss).parse(
        f"SELECT ?d WHERE {{ ?d <{UB}subOrganizationOf> <http://www.University0.edu> . "
        "FILTER(bound(?zz)) }")
    heuristic_plan(q)
    eng.execute(q)
    assert q.result.status_code == 0 and q.result.nrows == 0
    q2 = Parser(ss).parse(
        f"SELECT ?d WHERE {{ ?d <{UB}subOrganizationOf> <http://www.University0.edu> . }}"
        " ORDER BY ?zz")
    heuristic_plan(q2)
    eng.execute(q2)
    assert q2.result.status_code == ErrorCode.VERTEX_INVALID


def test_template_in_union_rejected():
    import pytest

    from wukong_tpu.sparql.parser import Parser, SPARQLSyntaxError

    _, ss, _ = _lubm1_world()
    text = f"""SELECT ?x WHERE {{
        {{ ?x <{UB}takesCourse> %<{UB}GraduateCourse> . }}
        UNION {{ ?x <{UB}takesCourse> %<{UB}Course> . }} }}"""
    with pytest.raises(SPARQLSyntaxError):
        Parser(ss).parse_template(text.replace(f"%<{UB}", "%ub:").replace(">", ">", 1))


def test_template_in_union_rejected_pname():
    import pytest

    from wukong_tpu.sparql.parser import Parser, SPARQLSyntaxError

    _, ss, _ = _lubm1_world()
    text = f"""PREFIX ub: <{UB}>
    SELECT ?x WHERE {{
        {{ ?x ub:takesCourse %ub:GraduateCourse . }}
        UNION {{ ?x ub:takesCourse %ub:Course . }} }}"""
    with pytest.raises(SPARQLSyntaxError):
        Parser(ss).parse_template(text)


def test_execute_batch_reanchor_on_const():
    """A follow-up pattern anchored on the start constant must work in batch."""
    import numpy as np

    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.loader.lubm import P
    from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
    from wukong_tpu.types import IN, OUT

    g, ss, cpu = _lubm1_world()
    tpu = TPUEngine(g, ss)
    d0 = ss.str2id("<http://www.Department0.University0.edu>")
    d1 = ss.str2id("<http://www.Department1.University0.edu>")
    # { %D worksFor<- ?x . %D memberOf<- ?y } — both steps anchor on the const
    q = SPARQLQuery()
    q.pattern_group.patterns = [
        Pattern(d0, P["worksFor"], IN, -1),
        Pattern(d0, P["memberOf"], IN, -2),
    ]
    counts = tpu.execute_batch(q, np.asarray([d0, d1], dtype=np.int64))
    for i, dd in enumerate((d0, d1)):
        staff = len(g.get_triples(dd, P["worksFor"], IN))
        members = len(g.get_triples(dd, P["memberOf"], IN))
        assert counts[i] == staff * members


def test_execute_batch_rejects_versatile():
    import numpy as np
    import pytest

    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
    from wukong_tpu.types import IN
    from wukong_tpu.utils.errors import WukongError

    g, ss, cpu = _lubm1_world()
    tpu = TPUEngine(g, ss)
    d0 = ss.str2id("<http://www.Department0.University0.edu>")
    q = SPARQLQuery()
    q.pattern_group.patterns = [Pattern(d0, -5, IN, -1)]  # versatile pred var
    with pytest.raises(WukongError):
        tpu.execute_batch(q, np.asarray([d0], dtype=np.int64))


def test_distinct_with_hidden_columns():
    """DISTINCT must dedup projected tuples even when a hidden column
    separates duplicates in sort order."""
    import numpy as np

    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.sparql.ir import Result, SPARQLQuery

    _, ss, eng = _lubm1_world()
    q = SPARQLQuery()
    q.distinct = True
    res = q.result
    res.nvars = 2
    res.required_vars = [-2]
    res.v2c_map = {-1: 0, -2: 1}
    res.col_num = 2
    res.set_table(np.asarray([[1, 9], [2, 7], [3, 9]], dtype=np.int64))
    eng._final_process(q)
    assert sorted(r[0] for r in q.result.table.tolist()) == [7, 9]


# ---- round-2 ADVICE fixes -------------------------------------------------


def test_parser_semicolon_comma_shorthand():
    """';' predicate-object-list and ',' object-list shorthand
    (SPARQLParser.hpp:771-809)."""
    import numpy as np

    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
    from wukong_tpu.sparql.parser import Parser
    from wukong_tpu.store.gstore import build_partition

    triples, _ = generate_lubm(1, seed=7)
    ss = VirtualLubmStrings(1, seed=7)
    g = build_partition(triples, 0, 1)
    long_form = """
        PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
        PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
        SELECT ?x ?y WHERE {
          ?x rdf:type ub:GraduateStudent .
          ?x ub:memberOf ?y .
          ?x ub:undergraduateDegreeFrom ?z .
        }"""
    short_form = """
        PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
        SELECT ?x ?y WHERE {
          ?x a ub:GraduateStudent ;
             ub:memberOf ?y ;
             ub:undergraduateDegreeFrom ?z ; .
        }"""
    ql = Parser(ss).parse(long_form)
    qs = Parser(ss).parse(short_form)
    assert [(p.subject, p.predicate, p.object) for p in ql.pattern_group.patterns] \
        == [(p.subject, p.predicate, p.object) for p in qs.pattern_group.patterns]
    from wukong_tpu.planner.heuristic import heuristic_plan

    heuristic_plan(ql)
    heuristic_plan(qs)
    CPUEngine(g, ss).execute(ql)
    CPUEngine(g, ss).execute(qs)
    assert ql.result.nrows == qs.result.nrows > 0

    # ',' object list
    q = Parser(ss).parse("""
        PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
        SELECT ?x WHERE { ?x ub:memberOf ?y , ?z . }""")
    pats = q.pattern_group.patterns
    assert len(pats) == 2
    assert pats[0].subject == pats[1].subject
    assert pats[0].predicate == pats[1].predicate
    assert pats[0].object != pats[1].object
    del np


def test_vid_range_guard():
    import numpy as np
    import pytest

    from wukong_tpu.store.gstore import build_partition
    from wukong_tpu.utils.errors import WukongError

    bad = np.array([[2**31 + 5, 17, 1 << 17]], dtype=np.int64)
    with pytest.raises(WukongError):
        build_partition(bad, 0, 1)


def test_sharded_store_version_invalidation(eight_cpu_devices):
    """Direct insert_triples on shard stores must invalidate stacked segments
    and compiled plans (ADVICE round 1, sharded_store.py finding)."""
    import numpy as np

    from wukong_tpu.loader.lubm import P, VirtualLubmStrings, generate_lubm
    from wukong_tpu.parallel.dist_engine import DistEngine
    from wukong_tpu.parallel.mesh import make_mesh
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.sparql.parser import Parser
    from wukong_tpu.store.dynamic import insert_triples
    from wukong_tpu.store.gstore import build_all_partitions

    triples, _ = generate_lubm(1, seed=3)
    ss = VirtualLubmStrings(1, seed=3)
    D = 4
    stores = build_all_partitions(triples, D)
    dist = DistEngine(stores, ss, make_mesh(D))
    text = """
        PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
        SELECT ?x ?y WHERE { ?x ub:memberOf ?y . }"""

    def run():
        q = Parser(ss).parse(text)
        heuristic_plan(q)
        dist.execute(q)
        assert q.result.status_code == 0
        return q.result.nrows

    n0 = run()
    # new memberOf edges, inserted directly into the shard stores
    new = np.array([[8, P["memberOf"], 9], [10, P["memberOf"], 9]],
                   dtype=np.int64)
    for g in stores:
        insert_triples(g, new)
    assert run() == n0 + 2


def test_device_store_index_lru_evictable():
    import numpy as np

    from wukong_tpu.engine.device_store import DeviceStore
    from wukong_tpu.loader.lubm import P, generate_lubm
    from wukong_tpu.store.gstore import build_partition
    from wukong_tpu.types import IN

    triples, _ = generate_lubm(1, seed=5)
    g = build_partition(triples, 0, 1)
    ds = DeviceStore(g, budget_bytes=1)  # evict everything not pinned
    ds.index_list(P["memberOf"], IN)
    ds.index_list(P["worksFor"], IN)
    # index stagings must be reclaimable: budget enforcement drops them
    assert len(ds._index_cache) <= 1
    assert ds.bytes_used <= max(
        (v[0].size * 4 for v in ds._index_cache.values()), default=0)
    del np


def test_expand_total_saturates_on_int32_wrap():
    """advisor r2 #1: an expansion total past 2^31 must saturate to INT32_MAX
    (tripping the host's exceeds-capacity error) instead of wrapping to a
    value that silently passes the `total > cap` check."""
    import jax.numpy as jnp
    import numpy as np

    from wukong_tpu.engine.tpu_kernels import INT32_MAX, _saturate_total

    deg = jnp.full(4096, 1 << 20, jnp.int32)  # exact total = 2^32 > int32
    cum = jnp.cumsum(deg)
    assert int(cum[-1]) >= 0  # the wrapped value IS deceptive (== 0 here)
    assert int(_saturate_total(cum)) == INT32_MAX
    ok = jnp.cumsum(jnp.full(1024, 7, jnp.int32))
    assert int(_saturate_total(ok)) == 7 * 1024


def test_merge_chain_pins_match_staged_keys():
    """advisor r2 #2: the merge chain must pin the keys it actually stages —
    folded expands use ("mrgf", ...) and k2c steps use ("rev", ...); pinning
    only ("mrg", ...) leaves them evictable mid-chain."""
    from wukong_tpu.engine.tpu_merge import MergeExecutor
    from wukong_tpu.sparql.ir import Pattern
    from wukong_tpu.types import IN, OUT, TYPE_ID

    # q3-shaped: index start, expands with foldable type filters, k2k, plus
    # a k2c on the ROOT var (no producing expand -> a real "rev" staging)
    pats = [Pattern(-1, TYPE_ID, IN, 300000),      # index start (consumed)
            Pattern(-1, 140000, OUT, -2),          # expand ?x -> ?y
            Pattern(-2, TYPE_ID, OUT, 300001),     # folds into expand 1
            Pattern(-1, 140001, OUT, -3),          # expand ?x -> ?z
            Pattern(-3, 140002, OUT, -2),          # k2k pair membership
            Pattern(-3, TYPE_ID, OUT, 300002),     # folds into expand 3
            Pattern(-1, 140003, OUT, 200123)]      # root k2c -> "rev" list
    folds = MergeExecutor._plan_folds(pats, index_mode=True)
    pins = MergeExecutor._chain_pins(pats, folds, index_mode=True)
    # expands pin BOTH the merge and the bucket ("segf") forms: the live
    # sort-vs-probe decision may stage either, and unstaged pins are free
    assert ("mrgf", 140000, int(OUT), ((int(TYPE_ID), int(OUT), 300001),)) \
        in pins
    assert ("segf", 140000, int(OUT), ((int(TYPE_ID), int(OUT), 300001),)) \
        in pins
    assert ("mrgf", 140001, int(OUT), ((int(TYPE_ID), int(OUT), 300002),)) \
        in pins
    assert ("segf", 140001, int(OUT), ((int(TYPE_ID), int(OUT), 300002),)) \
        in pins
    # k2k pins both forms too (probe-member arm)
    assert ("mrg", 140002, int(OUT)) in pins
    assert (140002, int(OUT)) in pins
    assert ("rev", 140003, int(OUT), 200123) in pins
    # folded steps must NOT appear as separate pins
    assert not any(k[0] == "rev" and k[-1] in (300001, 300002) for k in pins)
    assert len(pins) == 7
