"""Regression tests for review findings on the data layer."""

import pytest

from wukong_tpu.config import GlobalConfig
from wukong_tpu.loader.lubm import VirtualLubmStrings
from wukong_tpu.types import is_tpid


def test_config_clamp_order_independent():
    a = GlobalConfig(); a.finalize()
    a.load_str("global_mt_threshold 64\nglobal_num_engines 16")
    b = GlobalConfig(); b.finalize()
    b.load_str("global_num_engines 16\nglobal_mt_threshold 64")
    assert a.mt_threshold == b.mt_threshold == 16


def test_config_unknown_key_warns_and_continues():
    cfg = GlobalConfig(); cfg.finalize()
    cfg.load_str("global_silent off\nglobal_not_a_real_knob 1\nglobal_mt_threshold 2")
    assert cfg.silent is False and cfg.mt_threshold == 2


def test_config_bad_value_applies_nothing():
    cfg = GlobalConfig(); cfg.finalize()
    before = cfg.silent
    with pytest.raises(ValueError):
        cfg.load_str("global_silent off\nglobal_mt_threshold banana")
    assert cfg.silent is before


def test_virtual_strings_out_of_range_email():
    vs = VirtualLubmStrings(1)
    assert not vs.exist('"email0@Department0.University99.edu"')
    assert not vs.exist('"email0@Department99.University0.edu"')
    assert not vs.exist('"email9999999@Department0.University0.edu"')


def test_is_tpid_excludes_reserved():
    assert not is_tpid(0) and not is_tpid(1)
    assert is_tpid(2) and not is_tpid(1 << 17)
