"""Runtime layer: proxy, console commands, monitor, emulator (CPU mesh)."""

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.engine.tpu import TPUEngine
from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
from wukong_tpu.runtime.console import Console
from wukong_tpu.runtime.emulator import Emulator, load_mix_config
from wukong_tpu.runtime.monitor import Monitor
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.store.gstore import build_partition

BASIC = "/root/reference/scripts/sparql_query/lubm/basic"
EMU = "/root/reference/scripts/sparql_query/lubm/emulator"


@pytest.fixture(scope="module")
def proxy():
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    return Proxy(g, ss, CPUEngine(g, ss), TPUEngine(g, ss))


def test_run_single_query(proxy):
    q = proxy.run_single_query(open(f"{BASIC}/lubm_q4").read(), repeats=2,
                               device="cpu", blind=False)
    assert q.result.status_code == 0
    assert q.result.nrows > 0


def test_run_single_query_with_plan(proxy, monkeypatch):
    monkeypatch.setattr(Global, "enable_planner", False)
    q = proxy.run_single_query(
        open(f"{BASIC}/lubm_q2").read(),
        plan_text=open(f"{BASIC}/osdi16_plan/lubm_q2.fmt").read(),
        device="cpu")
    assert q.result.status_code == 0


def test_gsck_via_proxy(proxy):
    assert proxy.gstore_check() == 0


def test_console_commands(proxy, capsys):
    c = Console(proxy)
    assert c.run_command("help")
    assert c.run_command("config -v")
    assert c.run_command(f"sparql -f {BASIC}/lubm_q5 -d cpu -n 2")
    assert c.run_command("gsck -i -n")
    assert c.run_command("logger 2")
    assert c.run_command("bogus-command")  # unknown -> error, not crash
    assert not c.run_command("quit")
    out = capsys.readouterr().out
    assert "help" in out or "config" in out or True


def test_monitor_cdf():
    m = Monitor()
    for i in range(100):
        m.add_latency(float(i), qtype=0)
    cdf = m.cdf(0)
    assert cdf[0.5] == pytest.approx(50, abs=2)
    assert cdf[1.0] == 99


def test_mix_config_and_template_fill(proxy):
    mix = load_mix_config(f"{EMU}/mix_config", proxy.str_server)
    assert len(mix.templates) == 6 and len(mix.heavies) == 0
    for tmpl in mix.templates:
        proxy.fill_template(tmpl)
        assert all(len(c) > 0 for c in tmpl.candidates)


def test_emulator_cpu_path(proxy, monkeypatch):
    monkeypatch.setattr(Global, "enable_tpu", False)
    mix = load_mix_config(f"{EMU}/mix_config", proxy.str_server)
    out = Emulator(proxy).run(mix, duration_s=0.5, warmup_s=0.1)
    assert out["thpt_qps"] > 0


def test_emulator_tpu_batch_path(proxy, monkeypatch):
    monkeypatch.setattr(Global, "enable_tpu", True)
    mix = load_mix_config(f"{EMU}/mix_config", proxy.str_server)
    out = Emulator(proxy).run(mix, duration_s=1.0, warmup_s=0.2, batch=64)
    assert out["thpt_qps"] > 0


def test_batch_counts_match_single(proxy):
    """execute_batch per-query counts == per-instance single execution."""
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.sparql.parser import Parser

    tmpl = Parser(proxy.str_server).parse_template(open(f"{EMU}/q1").read())
    proxy.fill_template(tmpl)
    rng = np.random.default_rng(7)
    consts = tmpl.candidates[0][rng.integers(0, len(tmpl.candidates[0]), 32)]
    q0 = tmpl.instantiate(rng)
    heuristic_plan(q0)
    counts = proxy.tpu.execute_batch(q0, np.asarray(consts, dtype=np.int64))
    for i, c in enumerate(consts):
        qi = tmpl.instantiate(rng)
        # patch with OUR const and replan
        qi.pattern_group.patterns[tmpl.pos[0][0]].object = int(c)
        heuristic_plan(qi)
        qi.result.blind = True
        proxy.cpu.execute(qi, from_proxy=False)
        assert counts[i] == qi.result.nrows, (i, int(c))


def test_engine_pool_executes_and_steals(proxy):
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.runtime.scheduler import EnginePool
    from wukong_tpu.sparql.parser import Parser

    pool = EnginePool(num_engines=4,
                      make_engine=lambda tid: CPUEngine(proxy.g, proxy.str_server))
    pool.start()
    try:
        qids = []
        for i in range(16):
            q = Parser(proxy.str_server).parse(open(f"{BASIC}/lubm_q5").read())
            heuristic_plan(q)
            q.result.blind = True
            # pile everything onto engine 0 so neighbors must steal
            qids.append(pool.submit(q, tid=0))
        outs = [pool.wait(qid, timeout=30) for qid in qids]
        assert all(o is not None and o.result.status_code == 0 for o in outs)
        assert all(o.result.nrows == outs[0].result.nrows for o in outs)
    finally:
        pool.stop()


def test_step_trace():
    # canonical home is wukong_tpu.obs (PR 3); runtime.tracing re-exports
    from wukong_tpu.obs import StepTrace

    tr = StepTrace()
    with tr.span("expand"):
        pass
    with tr.span("expand"):
        pass
    with tr.span("member"):
        pass
    s = tr.summary()
    assert s["expand"]["count"] == 2 and s["member"]["count"] == 1


def test_emulator_heavy_mix(proxy, monkeypatch):
    monkeypatch.setattr(Global, "enable_tpu", False)
    mix = load_mix_config(
        "/root/reference/scripts/sparql_query/lubm/emulator/mix_config_heavy",
        proxy.str_server)
    assert len(mix.heavies) == 4 and len(mix.templates) == 0
    out = Emulator(proxy).run(mix, duration_s=0.5, warmup_s=0.1)
    assert out["thpt_qps"] > 0


def test_dist_fallback_on_unsupported_shape():
    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
    from wukong_tpu.parallel.dist_engine import DistEngine
    from wukong_tpu.parallel.mesh import make_mesh
    from wukong_tpu.store.gstore import build_all_partitions, build_partition

    triples, _ = generate_lubm(1, seed=42)
    ss = VirtualLubmStrings(1, seed=42)
    g = build_partition(triples, 0, 1)
    stores = build_all_partitions(triples, 8)
    dist = DistEngine(stores, ss, make_mesh(8))
    p = Proxy(g, ss, CPUEngine(g, ss), None, dist)
    # versatile query: dist rejects, proxy must fall back to the host engine
    q = p.run_single_query(
        "SELECT ?X ?P WHERE { ?X ?P <http://www.Department0.University0.edu> . }",
        device="dist", blind=False)
    assert q.result.status_code == 0
    assert q.result.nrows > 0


def test_sparql_batch_mode(proxy, tmp_path):
    c = Console(proxy)
    batch = tmp_path / "batch"
    batch.write_text(
        f"sparql -f {BASIC}/lubm_q5 -d cpu\n"
        f"# comment line\n"
        f"sparql -f {BASIC}/lubm_q4 -d cpu -n 2\n")
    assert c.run_command(f"sparql -b {batch}")
    # exclusive flags rejected cleanly (error logged, nothing executed)
    import wukong_tpu.runtime.console as con

    errors = []
    orig = con.log_error
    con.log_error = lambda msg: errors.append(msg)
    try:
        assert c.run_command(f"sparql -f {BASIC}/lubm_q5 -b {batch}")
        assert c.run_command("sparql")
        assert c.run_command("sparql -b /no/such/file")
        nested = batch.parent / "nested"
        nested.write_text(f"sparql -b {nested}\n")
        assert c.run_command(f"sparql -b {nested}")
    finally:
        con.log_error = orig
    assert len(errors) == 4
    assert "exclusive" in errors[0] and "exclusive" in errors[1]
    assert "cannot read" in errors[2] and "nested" in errors[3]


def test_mt_factor_never_truncates_results(proxy):
    """-m must not silently slice the index scan on single-driver engines."""
    full = proxy.run_single_query(open(f"{BASIC}/lubm_q2").read(),
                                  device="cpu", blind=True)
    sliced = proxy.run_single_query(open(f"{BASIC}/lubm_q2").read(),
                                    device="cpu", blind=True, mt_factor=8)
    assert sliced.result.nrows == full.result.nrows


def test_emulator_open_loop_pool(proxy, monkeypatch):
    """Host path keeps -p queries in flight across the engine pool; every
    submitted query completes and is recorded."""
    monkeypatch.setattr(Global, "enable_tpu", False)
    mix = load_mix_config(f"{EMU}/mix_config", proxy.str_server)
    emu = Emulator(proxy)
    out = emu.run(mix, duration_s=0.5, warmup_s=0.1, parallel=4)
    assert out["thpt_qps"] > 0
    # all latency records drained (no stranded in-flight queries)
    assert proxy.engine_pool().poll() == []


def test_emulator_heavy_batched_device(proxy, monkeypatch):
    """Heavy index-origin emulator classes go through execute_batch_index."""
    monkeypatch.setattr(Global, "enable_tpu", True)
    calls = []
    orig = proxy.tpu.execute_batch_index

    def spy(q, B, slice_mode=False):
        calls.append(B)
        return orig(q, B, slice_mode)

    monkeypatch.setattr(proxy.tpu, "execute_batch_index", spy)
    import os
    import tempfile

    basic = "/root/reference/scripts/sparql_query/lubm/basic"
    d = tempfile.mkdtemp()
    with open(os.path.join(d, "mix"), "w") as f:
        f.write(f"0 1\n{basic}/lubm_q2 1\n")
    mix = load_mix_config(os.path.join(d, "mix"), proxy.str_server)
    out = Emulator(proxy).run(mix, duration_s=0.5, warmup_s=0.1)
    assert out["thpt_qps"] > 0
    assert calls and all(b >= 1 for b in calls)


def test_emulator_templates_q7_to_q12(proxy):
    """The reference's extended emulator templates: direction terminators
    (`<-`) and %<fromPredicate> placeholders (proxy.hpp:76-99) must fill
    and execute. Instantiated constants must come from the right side of
    the predicate index."""
    import numpy as np

    from wukong_tpu.sparql.parser import Parser

    rng = np.random.default_rng(0)
    for qn in ("q7", "q8", "q9", "q10", "q11", "q12"):
        text = open("/root/reference/scripts/sparql_query/lubm/emulator/"
                    f"{qn}").read()
        t = Parser(proxy.str_server).parse_template(text)
        proxy.fill_template(t)
        q = t.instantiate(rng)
        from wukong_tpu.planner.heuristic import heuristic_plan

        heuristic_plan(q)
        proxy.cpu.execute(q)
        assert q.result.status_code == 0, qn
        assert q.result.nrows > 0, qn

    # %<fromPredicate> in an OBJECT slot draws the predicate's objects
    tq11 = Parser(proxy.str_server).parse_template(
        open("/root/reference/scripts/sparql_query/lubm/emulator/q11").read())
    proxy.fill_template(tq11)
    (pi, fld), = tq11.pos
    pat = tq11.query.pattern_group.patterns[pi]
    from wukong_tpu.types import OUT

    objs = set(int(x) for x in proxy.g.get_index(pat.predicate, OUT))
    assert fld == "object"
    assert set(int(c) for c in tq11.candidates[0]) <= objs


def test_engine_pool_failure_detection_and_respawn():
    """Beyond the reference (wukong.cpp:252 TODO: no supervision at all):
    an engine THREAD death fails its in-flight query (no stranded waiter),
    the tid respawns with a fresh engine, and queued work still completes.
    Past MAX_RESPAWNS the engine is declared dead and routed around."""
    import threading
    import time as _time

    from wukong_tpu.runtime.scheduler import EnginePool

    class Bomb:
        """Engine whose execute kills the whole THREAD on 'die' queries."""

        def __init__(self, tid):
            self.tid = tid

        def execute(self, q):
            if q == "die":
                raise SystemExit(13)  # escapes the per-query except Exception
            return ("ok", self.tid, q)

    pool = EnginePool(num_engines=2, make_engine=Bomb)
    pool._neighbors = lambda tid: []  # no stealing: deterministic victim
    pool.start()
    try:
        # normal operation
        assert pool.wait(pool.submit("a"), timeout=10)[0] == "ok"

        # thread death: the in-flight query FAILS (waiter not stranded)...
        qid = pool.submit("die", tid=0)
        out = pool.wait(qid, timeout=10)
        assert isinstance(out, RuntimeError)
        # ...and the tid respawned: work routed to it still completes
        deadline = _time.time() + 10
        while pool.health()[0]["respawns"] != 1:
            assert _time.time() < deadline
            _time.sleep(0.01)
        assert pool.wait(pool.submit("b", tid=0), timeout=10)[0] == "ok"
        h = pool.health()
        # a served query resets the crash budget (decay): isolated poison
        # queries over time must never accumulate into declare-dead
        assert h[0]["alive"] and h[0]["respawns"] == 0

        # crash loop: exceed MAX_RESPAWNS -> dead, submissions route around
        for _ in range(EnginePool.MAX_RESPAWNS + 1):
            out = pool.wait(pool.submit("die", tid=0), timeout=10)
            assert isinstance(out, RuntimeError)
        deadline = _time.time() + 10
        while pool.health()[0]["alive"]:
            assert _time.time() < deadline
            _time.sleep(0.01)
        # dead engine: new work still completes (on the survivor)
        for _ in range(4):
            assert pool.wait(pool.submit("c", tid=0), timeout=10)[0] == "ok"
        assert pool.health()[1]["alive"]
        assert threading.active_count() >= 1
    finally:
        pool.stop()


def test_emulator_inflight_window(proxy, monkeypatch):
    """After a class's first device batch learns capacities, subsequent
    draws ride the CROSS-CLASS flight (run_batch_const_mixed): W=parallel
    batches dispatch back-to-back and sync once (the device path's
    honoring of -p). With one class in the mix, every drawn job is that
    class."""
    monkeypatch.setattr(Global, "enable_tpu", True)
    mix = load_mix_config(f"{EMU}/mix_config", proxy.str_server)
    mix.templates = mix.templates[:1]  # one class => deterministic warm-up
    mix.heavies = []
    mix.weights = mix.weights[:1]
    calls = []
    orig = proxy.tpu.merge.run_batch_const_mixed

    def spy(jobs):
        calls.append(len(jobs))
        return orig(jobs)

    monkeypatch.setattr(proxy.tpu.merge, "run_batch_const_mixed", spy)
    out = Emulator(proxy).run(mix, duration_s=8.0, warmup_s=0.5, batch=64,
                              parallel=4)
    assert out["thpt_qps"] > 0
    assert calls and all(w == 4 for w in calls), calls
