"""Materialized-view serving plane (ISSUE 14): the version-keyed result
cache and incrementally-maintained hot-template views.

Acceptance surface: a cache hit rebuilds a byte-identical reply (table
bytes, projection map, counts) and the zero-parse fast path serves
repeated texts without touching the parser; admission follows the
popularity ledger's verdicts read through ``CACHE_INPUTS``; concurrent
misses on one key collapse onto a single execution; every journaled
mutation edge reaches the actuator — insert/epoch edges kill
stale-version entries (or re-key them when the view's semi-naive delta
evaluation proves the template untouched), cutover/restore purge
conservatively with served replies byte-identical throughout (the PR 12
kill-and-resume posture); promotion honors the delta planner's
rejection rules and the maintenance-cost demotion; real-vs-shadow
divergence is counted; the ``/cache`` report, console verb, and Monitor
line surface the real cache next to the shadow; and
``Emulator.run_readmostly(cached=True, views=True)`` proves the
end-to-end contract. The whole module runs fully lockdep-checked.
"""

import threading
import time

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.lubm import UB, VirtualLubmStrings, generate_lubm
from wukong_tpu.obs.events import get_journal
from wukong_tpu.obs.reuse import (
    CACHE_INPUTS,
    INVALIDATION_CAUSES,
    get_reuse,
    render_cache,
)
from wukong_tpu.obs.tsdb import get_tsdb
from wukong_tpu.runtime import faults
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.serve import get_serve
from wukong_tpu.serve.result_cache import (
    CONSUMED_INPUTS,
    MUTATION_EDGES,
    divergence_total,
)
from wukong_tpu.store.dynamic import insert_batch_into
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.store.persist import gstore_digest
from wukong_tpu.types import OUT
from wukong_tpu.utils.errors import ErrorCode

pytestmark = pytest.mark.serve


@pytest.fixture(autouse=True, scope="module")
def _lockdep_checked():
    """The serve suite runs fully lockdep-checked: serve.cache is a
    declared leaf (dict updates only), serve.views is an ordinary
    tracked lock held across delta evaluation — any acquisition under
    the leaf, or any cycle through the WAL mutation lock, fails the
    module teardown."""
    from wukong_tpu.analysis import lockdep

    lockdep.install(True)
    yield
    try:
        assert lockdep.cycles() == [], lockdep.cycles()
        assert lockdep.leaf_violations() == [], lockdep.leaf_violations()
    finally:
        lockdep.install(False)


@pytest.fixture(scope="module")
def world():
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    return {"g": g, "ss": ss, "triples": triples}


@pytest.fixture(scope="module")
def proxy(world):
    return Proxy(world["g"], world["ss"],
                 CPUEngine(world["g"], world["ss"]))


@pytest.fixture(scope="module")
def texts(world):
    g, ss = world["g"], world["ss"]
    pid = ss.str2id(f"<{UB}advisor>")
    anchors = np.asarray(g.get_index(pid, OUT))
    return [f"SELECT ?s WHERE {{ ?s <{UB}advisor> "
            f"{ss.id2str(int(a))} . }}" for a in anchors[:32]]


@pytest.fixture(autouse=True)
def _hygiene(world, monkeypatch):
    """Cache armed, views DISARMED (each rung-ii test arms explicitly),
    plane re-attached to the module world, every process-wide ring
    clean, no fault plan leaking across tests."""
    monkeypatch.setattr(Global, "enable_result_cache", True)
    monkeypatch.setattr(Global, "enable_views", False)
    monkeypatch.setattr(Global, "enable_reuse", True)
    monkeypatch.setattr(Global, "reuse_sample_every", 1)
    monkeypatch.setattr(Global, "result_cache_min_reads", 1)
    monkeypatch.setattr(Global, "view_promote_edges", 1)
    monkeypatch.setattr(Global, "enable_events", True)
    monkeypatch.setattr(Global, "enable_tracing", False)
    plane = get_serve()
    plane.reset()
    plane.views.attach(world["g"], world["ss"])
    get_reuse().reset()
    get_journal().clear()
    get_tsdb().reset()
    faults.clear()
    yield
    faults.clear()
    plane.reset()
    get_reuse().reset()


def _unrelated(world, k: int = 4):
    """k triples whose predicate is NOT advisor (edges that cannot touch
    the advisor-template views)."""
    ss = world["ss"]
    pid = ss.str2id(f"<{UB}advisor>")
    t = world["triples"]
    return t[t[:, 1] != pid][:k]


def _matching(world, text_anchor: int):
    """One triple matching (·, advisor, anchor) — a duplicate edge that
    adds a duplicate row to the template's uncached reply."""
    ss = world["ss"]
    pid = ss.str2id(f"<{UB}advisor>")
    t = world["triples"]
    g = world["g"]
    anchors = np.asarray(g.get_index(pid, OUT))
    c0 = int(anchors[text_anchor])
    return t[(t[:, 1] == pid) & (t[:, 2] == c0)][:1]


def _oracle(proxy, text):
    """Uncached execution through the same parse/plan path."""
    q = proxy._parse_text(text)
    proxy._plan_prepared(q, True, None)
    proxy.cpu.execute(q)
    return q


def _same_reply(qa, qb) -> bool:
    ra, rb = qa.result, qb.result
    return (ra.status_code == rb.status_code
            and ra.nrows == rb.nrows and ra.col_num == rb.col_num
            and ra.v2c_map == rb.v2c_map
            and np.array_equal(np.asarray(ra.table), np.asarray(rb.table)))


# ---------------------------------------------------------------------------
# rung i: the result cache
# ---------------------------------------------------------------------------

def test_off_knob_is_byte_for_byte_inert(proxy, texts, monkeypatch):
    monkeypatch.setattr(Global, "enable_result_cache", False)
    rc = get_serve().cache
    before = rc.stats()
    q = proxy.serve_query(texts[0], blind=True)
    assert q.result.status_code == ErrorCode.SUCCESS
    after = rc.stats()
    assert (after["hits"], after["misses"], after["fills"]) == \
        (before["hits"], before["misses"], before["fills"])


def test_hit_serves_identical_bytes_and_fast_path_skips_parse(
        proxy, texts):
    rc = get_serve().cache
    q1 = proxy.serve_query(texts[0], blind=True)
    assert q1.__dict__.get("_rc_probe") == "miss"
    q2 = proxy.serve_query(texts[0], blind=True)
    assert q2.__dict__.get("_rc_probe") == "hit"
    assert _same_reply(q1, q2)
    oq = _oracle(proxy, texts[0])
    assert _same_reply(q2, oq)
    st = rc.stats()
    assert st["hits"] >= 1 and st["fills"] == 1 and st["entries"] == 1
    # the fast path never parses: a poisoned parser goes unnoticed
    def boom(text):
        raise AssertionError("fast path touched the parser")

    orig = proxy._parse_text
    proxy._parse_text = boom
    try:
        q3 = proxy.serve_query(texts[0], blind=True)
    finally:
        proxy._parse_text = orig
    assert q3.__dict__.get("_rc_probe") == "hit"
    assert _same_reply(q1, q3)
    # the cached table is write-protected: a consumer cannot corrupt it
    with pytest.raises(ValueError):
        q3.result.table[0, 0] = 7


def test_cached_table_survives_consumer_with_projection(proxy, texts):
    """Non-blind replies cache separately from blind ones (blind is part
    of the key) and carry the projected table."""
    qb = proxy.serve_query(texts[0], blind=True)
    qn = proxy.serve_query(texts[0], blind=False)
    assert qn.__dict__.get("_rc_probe") == "miss"  # different key
    qn2 = proxy.serve_query(texts[0], blind=False)
    assert qn2.__dict__.get("_rc_probe") == "hit"
    assert _same_reply(qn, qn2)
    assert qb.result.blind and not qn2.result.blind


def test_modifier_shapes_are_refused(proxy, texts):
    rc = get_serve().cache
    t = texts[0] + " LIMIT 3"
    r0 = rc.stats()["refused"]
    proxy.serve_query(t, blind=True)
    proxy.serve_query(t, blind=True)
    st = rc.stats()
    assert st["refused"] >= r0 + 2
    assert st["entries"] == 0  # nothing cached for the LIMIT shape


def test_partial_or_error_reply_is_never_filled(proxy, texts):
    """A deadline-truncated or failed reply must not enter the cache
    (the reply-side uncacheable classes)."""
    from wukong_tpu.serve.result_cache import ResultCache

    rc = ResultCache()
    q = _oracle(proxy, texts[0])
    q.result.complete = False
    assert rc.fill(("sig:x", (1,), "", (-1,), True), 0, q) is False
    q.result.complete = True
    q.result.status_code = ErrorCode.QUERY_TIMEOUT
    assert rc.fill(("sig:x", (1,), "", (-1,), True), 0, q) is False
    assert rc.stats()["entries"] == 0 and rc.stats()["refused"] == 2


def test_admission_reads_ledger_verdict(proxy, texts, monkeypatch):
    """result_cache_min_reads gates fills on the popularity ledger's
    arrival verdict, read through the CACHE_INPUTS map."""
    monkeypatch.setattr(Global, "result_cache_min_reads", 3)
    rc = get_serve().cache
    proxy.serve_query(texts[0], blind=True)  # reads+1 = 1 < 3: refused
    assert rc.stats()["fills"] == 0
    proxy.serve_query(texts[0], blind=True)  # reads+1 = 2 < 3: refused
    assert rc.stats()["fills"] == 0
    proxy.serve_query(texts[0], blind=True)  # reads+1 = 3: admitted
    assert rc.stats()["fills"] == 1
    q = proxy.serve_query(texts[0], blind=True)
    assert q.__dict__.get("_rc_probe") == "hit"


def test_insert_edge_kills_plain_entries(proxy, world, texts):
    rc = get_serve().cache
    proxy.serve_query(texts[0], blind=True)
    assert rc.stats()["entries"] == 1
    insert_batch_into(proxy._insert_targets(), _unrelated(world),
                      dedup=False)
    st = rc.stats()
    assert st["entries"] == 0 and st["killed"] >= 1  # no view: all die
    q = proxy.serve_query(texts[0], blind=True)
    assert q.__dict__.get("_rc_probe") == "miss"  # refilled at the new
    assert _same_reply(q, _oracle(proxy, texts[0]))  # version, correct


def test_request_collapsing_one_execution_many_waiters(proxy, texts):
    rc = get_serve().cache
    calls = []
    orig = proxy.cpu.execute

    def slow(q, **kw):
        calls.append(1)
        time.sleep(0.2)
        return orig(q, **kw)

    proxy.cpu.execute = slow
    results = [None] * 4
    try:
        def worker(i):
            results[i] = proxy.serve_query(texts[1], blind=True)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        threads[0].start()
        for _ in range(200):  # wait for the leader to be in flight
            if rc.stats()["inflight"]:
                break
            time.sleep(0.005)
        for t in threads[1:]:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        proxy.cpu.execute = orig
    assert sum(calls) == 1  # ONE execution served every waiter
    st = rc.stats()
    assert st["collapsed"] == 3 and st["fills"] == 1
    for r in results[1:]:
        assert r is not None and _same_reply(results[0], r)


# ---------------------------------------------------------------------------
# rung ii: materialized views
# ---------------------------------------------------------------------------

def test_view_promotion_survival_and_touch(proxy, world, texts,
                                           monkeypatch):
    monkeypatch.setattr(Global, "enable_views", True)
    rc, vr = get_serve().cache, get_serve().views
    proxy.serve_query(texts[0], blind=True)  # fill v0
    insert_batch_into(proxy._insert_targets(), _unrelated(world),
                      dedup=False)  # edge 1: entry dies (no view yet)
    proxy.serve_query(texts[0], blind=True)  # refill -> vote 1 -> promote
    assert vr.count() == 1
    insert_batch_into(proxy._insert_targets(), _unrelated(world),
                      dedup=False)  # edge 2: delta eval proves untouched
    assert rc.stats()["entries"] == 1  # the entry SURVIVED the write
    q = proxy.serve_query(texts[0], blind=True)
    assert q.__dict__.get("_rc_probe") == "hit"
    assert _same_reply(q, _oracle(proxy, texts[0]))
    # a matching duplicate edge derives a row -> touched -> refresh
    nrows0 = q.result.nrows
    insert_batch_into(proxy._insert_targets(), _matching(world, 0),
                      dedup=False)
    assert rc.stats()["entries"] == 0  # touched: the entry dropped
    q2 = proxy.serve_query(texts[0], blind=True)
    assert q2.__dict__.get("_rc_probe") == "miss"
    assert q2.result.nrows == nrows0 + 1  # the duplicate row appears
    assert _same_reply(q2, _oracle(proxy, texts[0]))
    st = vr.stats()
    assert st["views"][0]["survived"] >= 1
    assert st["views"][0]["touched"] == 1


def test_view_rejection_rules_ban_back_to_plain_entries(
        proxy, world, texts, monkeypatch):
    """A variable-predicate template is rung-i cacheable but has no
    incremental semantics — registration rejects it and the template
    stays a plain (version-keyed) cache entry."""
    monkeypatch.setattr(Global, "enable_views", True)
    ss = world["ss"]
    pid = ss.str2id(f"<{UB}advisor>")
    anchors = np.asarray(world["g"].get_index(pid, OUT))
    t = f"SELECT ?s ?p WHERE {{ ?s ?p {ss.id2str(int(anchors[0]))} . }}"
    vr = get_serve().views
    proxy.serve_query(t, blind=True)  # fill
    insert_batch_into(proxy._insert_targets(), _unrelated(world),
                      dedup=False)
    proxy.serve_query(t, blind=True)  # refill -> vote -> promotion try
    assert vr.count() == 0
    assert vr.stats()["rejected"] == 1 and vr.stats()["banned"] == 1
    # still a working plain entry at the current version
    q = proxy.serve_query(t, blind=True)
    assert q.__dict__.get("_rc_probe") == "hit"


def test_view_demoted_when_every_edge_touches_it(proxy, world, texts,
                                                 monkeypatch):
    monkeypatch.setattr(Global, "enable_views", True)
    monkeypatch.setattr(Global, "view_demote_touch_pct", 60)
    rc, vr = get_serve().cache, get_serve().views
    proxy.serve_query(texts[2], blind=True)
    insert_batch_into(proxy._insert_targets(), _unrelated(world),
                      dedup=False)
    proxy.serve_query(texts[2], blind=True)  # promote
    assert vr.count() == 1
    match = _matching(world, 2)
    for _ in range(9):  # every edge derives a row: pure maintenance cost
        insert_batch_into(proxy._insert_targets(), match, dedup=False)
    assert vr.count() == 0
    st = vr.stats()
    assert st["demoted"] == 1 and st["banned"] >= 1
    # demoted means plain entries again: correctness unchanged
    q = proxy.serve_query(texts[2], blind=True)
    assert _same_reply(q, _oracle(proxy, texts[2]))


def test_stream_epoch_edge_maintains_views(proxy, world, texts,
                                           monkeypatch):
    monkeypatch.setattr(Global, "enable_views", True)
    rc, vr = get_serve().cache, get_serve().views
    proxy.serve_query(texts[3], blind=True)
    proxy.stream_feed(_unrelated(world, 6))  # epoch edge 1
    proxy.serve_query(texts[3], blind=True)  # refill -> promote
    assert vr.count() == 1
    proxy.stream_feed(_unrelated(world, 6))  # epoch edge 2: untouched
    assert rc.stats()["entries"] >= 1
    q = proxy.serve_query(texts[3], blind=True)
    assert q.__dict__.get("_rc_probe") == "hit"
    assert _same_reply(q, _oracle(proxy, texts[3]))
    causes = {e.attrs["cause"]
              for e in get_journal().last(kind="cache.invalidate")}
    assert "epoch" in causes


def test_lagged_entry_never_rekeys_past_an_unjudged_edge(
        proxy, world, texts, monkeypatch):
    """An entry whose fill raced an earlier edge (resident at an OLDER
    version than the immediate pre-edge one) must DROP on the next edge
    even when that edge's view verdict says survivor: survivorship
    proves only the current batch changed nothing — an intermediate
    touching edge was never judged against this entry."""
    monkeypatch.setattr(Global, "enable_views", True)
    rc = get_serve().cache
    proxy.serve_query(texts[9], blind=True)
    insert_batch_into(proxy._insert_targets(), _unrelated(world),
                      dedup=False)
    proxy.serve_query(texts[9], blind=True)  # promote + refill
    assert get_serve().views.count() == 1
    # simulate the racing fill: age the resident entry one extra version
    # (as if it had been filled before an edge the view never judged)
    with rc._lock:
        (key, ent), = rc._entries.items()
        ent.version -= 1
    insert_batch_into(proxy._insert_targets(), _unrelated(world),
                      dedup=False)  # survivor verdict, but entry lagged
    assert rc.stats()["entries"] == 0  # dropped, not re-keyed
    q = proxy.serve_query(texts[9], blind=True)
    assert q.__dict__.get("_rc_probe") == "miss"
    assert _same_reply(q, _oracle(proxy, texts[9]))


# ---------------------------------------------------------------------------
# chaos / recovery drills: cutover + restore purge, byte-identical serving
# ---------------------------------------------------------------------------

def _sstore(world, n_shards=4):
    from wukong_tpu.parallel.sharded_store import ShardedDeviceStore

    class _Mesh:
        devices = np.empty(n_shards, dtype=object)

    stores = [build_partition(world["triples"], i, n_shards)
              for i in range(n_shards)]
    return ShardedDeviceStore(stores, _Mesh(), replication_factor=1)


def _mig_plan(donor=3, recipient=2):
    from wukong_tpu.obs.placement import MigrationPlan
    from wukong_tpu.utils.timer import get_usec

    return MigrationPlan(
        plan_id="mp-serve", t_us=get_usec(), donor_shard=donor,
        recipient_host=recipient, predicted_move_bytes=1 << 20,
        bytes_source="estimate", donor_rate_per_s=4.0,
        mean_rate_per_s=1.0, imbalance_before=2.5, imbalance_after=1.5,
        window_s=60.0, inputs={}, reason="serve-drill")


def test_migration_cutover_purges_and_serving_stays_identical(
        proxy, world, texts, monkeypatch):
    from wukong_tpu.runtime.migration import get_migrator

    rc = get_serve().cache
    oracle0 = _oracle(proxy, texts[4])
    q0 = proxy.serve_query(texts[4], blind=True)
    proxy.serve_query(texts[4], blind=True)  # resident + hit
    assert rc.stats()["entries"] == 1
    sstore = _sstore(world)
    mig = get_migrator()
    mig.reset()
    monkeypatch.setattr(Global, "migration_enable", True)
    mig.attach(sstore=sstore, owner=None)
    purges0 = rc.stats()["purges"]
    job = mig.run_plan(_mig_plan())
    assert job.phase == "done"
    st = rc.stats()
    assert st["purges"] == purges0 + 1 and st["entries"] == 0
    # served replies byte-identical through the purge
    q1 = proxy.serve_query(texts[4], blind=True)
    assert q1.__dict__.get("_rc_probe") == "miss"
    assert _same_reply(q1, q0) and _same_reply(q1, oracle0)
    mig.reset()


def test_migration_abort_rollback_also_purges(proxy, world, texts,
                                              monkeypatch):
    """The PR 12 kill-and-resume posture: a fault at the cutover aborts
    with the donor untouched; the published-then-rolled-back read path
    purges the cache on BOTH swaps, and serving stays byte-identical."""
    from wukong_tpu.runtime.faults import FaultPlan, FaultSpec
    from wukong_tpu.runtime.migration import get_migrator

    rc = get_serve().cache
    q0 = proxy.serve_query(texts[5], blind=True)
    proxy.serve_query(texts[5], blind=True)
    sstore = _sstore(world)
    donor_digest = gstore_digest(sstore.stores[3])
    mig = get_migrator()
    mig.reset()
    monkeypatch.setattr(Global, "migration_enable", True)
    mig.attach(sstore=sstore, owner=None)
    faults.install(FaultPlan(
        [FaultSpec("migration.cutover", "shard_down")], seed=0))
    with pytest.raises(Exception):
        mig.run_plan(_mig_plan())
    faults.clear()
    assert mig.job().phase == "aborted"
    assert gstore_digest(sstore.stores[3]) == donor_digest
    q1 = proxy.serve_query(texts[5], blind=True)
    assert _same_reply(q1, q0)
    assert _same_reply(q1, _oracle(proxy, texts[5]))
    mig.reset()


def test_recovery_restore_purges_and_rebuilds(world, texts, tmp_path,
                                              monkeypatch):
    """Cache + views under RecoveryManager restore: conservative purge
    (cause ``restore``), then refills byte-identical to the restored
    world's uncached execution."""
    monkeypatch.setattr(Global, "enable_views", True)
    monkeypatch.setattr(Global, "wal_dir", str(tmp_path / "wal"))
    monkeypatch.setattr(Global, "checkpoint_dir", str(tmp_path / "ckpt"))
    from wukong_tpu.store.wal import reset_wal

    reset_wal()
    g = build_partition(world["triples"], 0, 1)
    ss = world["ss"]
    p = Proxy(g, ss, CPUEngine(g, ss))  # attach binds the plane to g
    rc = get_serve().cache
    pid = ss.str2id(f"<{UB}advisor>")
    anchors = np.asarray(g.get_index(pid, OUT))
    t = (f"SELECT ?s WHERE {{ ?s <{UB}advisor> "
         f"{ss.id2str(int(anchors[0]))} . }}")
    p.serve_query(t, blind=True)
    p.recovery().checkpoint()
    insert_batch_into(p._insert_targets(), _unrelated(world),
                      dedup=False)
    q_pre = p.serve_query(t, blind=True)  # refill at the new version
    assert rc.stats()["entries"] == 1
    purges0 = rc.stats()["purges"]
    p.recover()
    st = rc.stats()
    assert st["purges"] == purges0 + 1 and st["entries"] == 0
    causes = {e.attrs["cause"]
              for e in get_journal().last(kind="cache.invalidate")}
    assert "restore" in causes
    # post-restore: WAL replayed the insert, so the refilled reply is
    # byte-identical to BOTH the pre-restore reply and a fresh oracle
    q_post = p.serve_query(t, blind=True)
    assert q_post.__dict__.get("_rc_probe") == "miss"
    assert _same_reply(q_post, q_pre)
    assert _same_reply(q_post, _oracle(p, t))
    reset_wal()


# ---------------------------------------------------------------------------
# observability surfaces + contracts
# ---------------------------------------------------------------------------

def test_divergence_counter_fires_on_disagreement(proxy, texts,
                                                  monkeypatch):
    """Shrink the shadow ring to 1 key: the real cache keeps hitting
    where the shadow keeps missing — every disagreement on the same
    probe counts."""
    from wukong_tpu.obs.reuse import ReuseObservatory
    import wukong_tpu.obs.reuse as reuse_mod

    obs = ReuseObservatory(capacity=1)
    monkeypatch.setattr(reuse_mod, "_observatory", obs)
    d0 = divergence_total()
    for _ in range(3):
        proxy.serve_query(texts[6], blind=True)
        proxy.serve_query(texts[7], blind=True)
    assert divergence_total() > d0


def test_cache_report_and_monitor_surface_the_real_cache(proxy, texts):
    proxy.serve_query(texts[8], blind=True)
    proxy.serve_query(texts[8], blind=True)
    text, js = render_cache(4)
    assert "REAL" in text and "views" in text
    assert js["real"]["enabled"] is True
    assert js["real"]["cache"]["hits"] >= 1
    assert "divergence" in js["real"]
    lines = proxy.monitor.cache_lines()
    assert any("Cache[real" in ln for ln in lines)
    assert any("Cache[shadow" in ln for ln in lines)


def test_consumer_contracts_are_literal_and_closed():
    """Runtime mirror of the cache-coherence gate's serve-plane half."""
    assert set(MUTATION_EDGES) == set(INVALIDATION_CAUSES)
    assert set(CONSUMED_INPUTS) <= set(CACHE_INPUTS)


def test_read_cache_input_rejects_undeclared_signals():
    from wukong_tpu.obs.reuse import read_cache_input

    with pytest.raises(KeyError):
        read_cache_input("not_a_signal")
    v = read_cache_input("template_popularity", template="sig:zzzz")
    assert v == {"reads": 0, "rate_qps": 0.0, "cacheable": True}


def test_serve_gate_holds_on_the_live_tree():
    import os

    from wukong_tpu.analysis import run_analysis

    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "wukong_tpu")
    assert run_analysis(pkg, plugins=["cache-coherence"]) == []


# ---------------------------------------------------------------------------
# the acceptance fixture, small: cached read-mostly end to end
# ---------------------------------------------------------------------------

def test_run_readmostly_cached_acceptance(world, monkeypatch):
    from wukong_tpu.runtime.emulator import Emulator

    monkeypatch.setattr(Global, "views_max", 128)
    # a PRIVATE world: the write phases mutate the store
    g = build_partition(world["triples"], 0, 1)
    ss = world["ss"]
    p = Proxy(g, ss, CPUEngine(g, ss))
    pid = ss.str2id(f"<{UB}advisor>")
    anchors = np.asarray(g.get_index(pid, OUT))
    texts = [f"SELECT ?s WHERE {{ ?s <{UB}advisor> "
             f"{ss.id2str(int(a))} . }}" for a in anchors[:48]]
    emu = Emulator(p)
    rep = emu.run_readmostly(
        texts, reads=120, warmup_reads=60, write_rates=(0.0, 0.1),
        zipf_a=1.3, seed=3, write_batch=world["triples"][:512],
        batch_rows=16, tenants=["gold", "bulk"],
        cached=True, views=True)
    real = rep["real"]
    assert real["identical"] is True and real["mismatches"] == 0
    assert real["hit_rate"] is not None
    assert real["beats_shadow"] is True
    assert rep["store_untouched"] is True
    # rung ii flattened the write-phase collapse: the 10%-write real hit
    # rate stays far above the shadow's version-keyed prediction
    wp = next(p_ for p_ in rep["phases"] if p_["write_rate"] > 0)
    assert wp["real_hit_rate"] is not None
    assert wp["real_hit_rate"] >= wp["hit_rate"]
    assert real["views"]["registered"] > 0
    # the knobs were restored by the drill
    assert Global.enable_result_cache is True  # the hygiene fixture's
