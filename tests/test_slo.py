"""Tenant-aware SLO plane (ISSUE 10): per-tenant accounting, error
budgets, burn-rate sentinels, and the overload signal bus.

Acceptance surface: a tenant identity threads proxy -> pool -> reply
(stamped on the query, the trace, and every reply-side metric, bounded
to ``max_tenants`` label values with an ``__overflow__`` bucket);
``SLOTracker`` computes compliance / remaining error budget /
multi-window burn rates against config- or runtime-registered specs; the
burn sentinel counts ``wukong_slo_burn_alerts_total{tenant,window}`` and
dumps exactly one attributable trace per cooldown window; every
``ADMISSION_INPUTS`` entry is backed by a registered metric;
``Emulator.run_tenants`` (3 conflicting tenant classes, chaos variant)
is ROADMAP item 4's acceptance fixture; the off knob degrades every hook
to one check; and the ``slo-telemetry`` analysis gate holds the surface
statically. Satellite: the WCOJ measured-blowup feedback loop demotes
over-predicted templates to the walk.
"""

import json
import time

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.lubm import UB, VirtualLubmStrings, generate_lubm
from wukong_tpu.obs import QueryTrace, get_recorder, get_registry
from wukong_tpu.obs.metrics import MetricsRegistry
from wukong_tpu.obs.slo import (
    ADMISSION_INPUTS,
    OVERFLOW_TENANT,
    SLOSpec,
    SLOTracker,
    get_overload,
    get_slo,
    parse_specs,
    render_slo,
    reset_labels,
    tenant_label,
)
from wukong_tpu.runtime import faults
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.runtime.resilience import Deadline
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.utils.errors import ErrorCode

pytestmark = pytest.mark.slo

PREFIX = """
PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
"""
Q_CHAIN = PREFIX + """SELECT ?X ?Y WHERE {
    ?X ub:memberOf ?Y .
    ?Y ub:subOrganizationOf ?Z .
}"""


@pytest.fixture(scope="module")
def world():
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    return {"g": g, "ss": ss, "triples": triples}


@pytest.fixture(scope="module")
def proxy(world):
    from wukong_tpu.planner.optimizer import make_planner

    p = Proxy(world["g"], world["ss"],
              CPUEngine(world["g"], world["ss"]))
    p.planner = make_planner(world["triples"])
    return p


@pytest.fixture(autouse=True)
def _hygiene(monkeypatch):
    """Accounting knobs at defaults; tracker/signals/labels/recorder
    clean; no fault plan leaks across tests."""
    monkeypatch.setattr(Global, "enable_tracing", False)
    monkeypatch.setattr(Global, "trace_sample_every", 1)
    monkeypatch.setattr(Global, "enable_tenant_accounting", True)
    monkeypatch.setattr(Global, "max_tenants", 64)
    monkeypatch.setattr(Global, "slo_specs", "")
    get_slo().reset()
    get_overload().reset()
    reset_labels()
    get_recorder().clear()
    faults.clear()
    yield
    get_slo().reset()
    get_overload().reset()
    reset_labels()
    faults.clear()


# ---------------------------------------------------------------------------
# tenant identity threading: proxy -> query -> trace -> metrics
# ---------------------------------------------------------------------------

def test_tenant_threads_query_trace_and_metrics(proxy, monkeypatch):
    monkeypatch.setattr(Global, "enable_tracing", True)
    m = get_registry().counter("wukong_queries_total",
                               labels=("status", "tenant"))
    before = m.value(status="SUCCESS", tenant="gold")
    q = proxy.serve_query(Q_CHAIN, blind=True, tenant="gold")
    assert q.result.status_code == ErrorCode.SUCCESS
    assert q.tenant == "gold"
    [tr] = get_recorder().last(1)
    assert tr.tenant == "gold"
    assert tr.to_dict()["tenant"] == "gold"
    assert m.value(status="SUCCESS", tenant="gold") == before + 1
    # the reply landed on the tenant latency histogram + the SLO tracker
    c = get_slo().compliance("gold")
    assert c is not None and c["samples"] == 1


def test_default_tenant_path_unchanged(proxy):
    q = proxy.run_single_query(Q_CHAIN, device="cpu", blind=True)
    assert q.result.status_code == ErrorCode.SUCCESS
    assert q.tenant == "default"
    assert get_slo().compliance("default")["samples"] >= 1


def test_parse_error_still_reaches_tenant_accounting(proxy):
    from wukong_tpu.utils.errors import WukongError

    with pytest.raises(WukongError):
        proxy.serve_query("SELECT ?x WHERE { broken", tenant="gold")
    c = get_slo().compliance("gold")
    assert c is not None and c["errors"] == 1
    # the in-flight slot was released on the error path too
    assert get_overload().report()["tenants"]["gold"]["inflight"] == 0


# ---------------------------------------------------------------------------
# bounded label cardinality
# ---------------------------------------------------------------------------

def test_overflow_bucket_bounds_cardinality(monkeypatch):
    monkeypatch.setattr(Global, "max_tenants", 2)
    assert tenant_label("a") == "a"
    assert tenant_label("b") == "b"
    assert tenant_label("c") == OVERFLOW_TENANT
    assert tenant_label("a") == "a"  # seen tenants keep their label
    assert tenant_label(None) == OVERFLOW_TENANT  # "default" past the cap


def test_prometheus_golden_with_tenant_labels_and_overflow():
    reg = MetricsRegistry()
    c = reg.counter("wukong_queries_total",
                    "Proxy queries by reply status and tenant",
                    labels=("status", "tenant"))
    c.labels(status="SUCCESS", tenant="gold").inc(3)
    c.labels(status="SUCCESS", tenant=OVERFLOW_TENANT).inc()
    golden = (
        "# HELP wukong_queries_total Proxy queries by reply status and tenant\n"
        "# TYPE wukong_queries_total counter\n"
        'wukong_queries_total{status="SUCCESS",tenant="__overflow__"} 1\n'
        'wukong_queries_total{status="SUCCESS",tenant="gold"} 3\n')
    assert reg.render_prometheus() == golden


# ---------------------------------------------------------------------------
# SLO specs, compliance, error budget, burn rates
# ---------------------------------------------------------------------------

def test_parse_specs_forms():
    specs = parse_specs("gold:95:50:0.999; bulk:99:0:0.9")
    assert specs[0] == SLOSpec("gold", 0.95, 50.0, 0.999)
    assert specs[1].percentile == 0.99 and specs[1].latency_ms == 0.0
    with pytest.raises(ValueError):
        parse_specs("gold:95:50")  # missing availability


def test_config_declared_specs_apply(monkeypatch):
    monkeypatch.setattr(Global, "slo_specs", "cfg:95:100:0.99")
    t = SLOTracker(window=64)
    t.observe("cfg", 1000, ok=True)
    c = t.compliance("cfg")
    assert c["spec"] == {"percentile": 0.95, "latency_ms": 100.0,
                         "availability": 0.99}


def test_compliance_budget_and_burn_math():
    t = SLOTracker(window=128)
    t.register(SLOSpec("a", percentile=0.95, latency_ms=0.0,
                       availability=0.9))
    for i in range(20):
        t.observe("a", 1000, ok=(i % 2 == 0))  # 50% bad, budget 10%
    c = t.compliance("a")
    assert c["compliance"] == 0.5
    # burn = bad_frac / budget = 0.5 / 0.1 = 5 on both windows
    assert c["burn"]["fast"] == pytest.approx(5.0)
    assert c["burn"]["slow"] == pytest.approx(5.0)
    # budget remaining = 1 - 0.5/0.1 = -4 (overdrawn 4x)
    assert c["error_budget_remaining"] == pytest.approx(-4.0)


def test_latency_target_counts_as_bad():
    t = SLOTracker(window=64)
    t.register(SLOSpec("a", percentile=0.95, latency_ms=1.0,
                       availability=0.5))
    t.observe("a", 500, ok=True)     # under 1ms: good
    t.observe("a", 5000, ok=True)    # over 1ms: bad despite SUCCESS
    c = t.compliance("a")
    assert c["compliance"] == 0.5


def test_parse_specs_percent_availability_normalized():
    """'99.9' availability must mean three nines, not a 1e-9 budget that
    pages on every blip; junk availability is a config error."""
    [sp] = parse_specs("gold:95:50:99.9")
    assert sp.availability == pytest.approx(0.999)
    with pytest.raises(ValueError):
        parse_specs("gold:95:50:0")
    with pytest.raises(ValueError):
        parse_specs("gold:95:50:150")


def test_burn_windows_see_different_history():
    """The fast and slow windows must diverge: a 5-minute all-bad burst
    after an hour of clean traffic is a fast-window cliff but a diluted
    slow-window burn. (A raw sample deque capped at slo_window made both
    windows read the same recent samples at any real qps — the bucketed
    ring is the fix.)"""
    from wukong_tpu.obs.slo import _TenantSLO

    st = _TenantSLO(window=64)
    now = 10_000_000_000_000  # synthetic clock, us
    for t in range(now - 3_600_000_000, now - 300_000_000, 10_000_000):
        st.buckets.append((t, 10, 0))    # clean hour
    for t in range(now - 300_000_000, now, 10_000_000):
        st.buckets.append((t, 10, 10))   # all-bad 5-minute tail
    fast, n_fast = SLOTracker._burn(st, now, 300, 0.1)
    slow, n_slow = SLOTracker._burn(st, now, 3600, 0.1)
    assert fast == pytest.approx(10.0, rel=0.15)  # 100% bad / 10% budget
    assert slow < fast / 5  # diluted by the clean hour
    assert n_slow > n_fast


def test_repeats_validation_does_not_leak_inflight(proxy):
    from wukong_tpu.utils.errors import WukongError

    with pytest.raises(WukongError):
        proxy.run_single_query(Q_CHAIN, repeats=0, tenant="leaky")
    assert "leaky" not in get_overload().report()["tenants"]


def test_no_spec_no_burn_no_alert():
    t = SLOTracker(window=64)
    for _ in range(30):
        assert t.observe("anon", 1000, ok=False) is None
    c = t.compliance("anon")
    assert c["spec"] is None and "burn" not in c


# ---------------------------------------------------------------------------
# the burn-rate sentinel
# ---------------------------------------------------------------------------

def test_burn_sentinel_trips_counts_and_dumps(monkeypatch):
    monkeypatch.setattr(Global, "slo_dump_cooldown_s", 3600)
    t = SLOTracker(window=128)
    t.register(SLOSpec("gold", 0.95, 0.0, 0.999))
    tr = QueryTrace(kind="query", tenant="gold")
    tr.finish("ERROR")
    verdicts = [t.observe("gold", 1000, ok=False,
                          trace=tr) for _ in range(40)]
    trips = [v for v in verdicts if v is not None]
    # one trip for the whole burst (cooldown holds), both windows counted
    assert len(trips) == 1
    assert trips[0]["windows"] == ("fast", "slow")
    assert trips[0]["fast_burn"] >= Global.slo_burn_fast_x
    m = get_registry().counter("wukong_slo_burn_alerts_total",
                               labels=("tenant", "window"))
    assert m.value(tenant="gold", window="fast") >= 1
    assert m.value(tenant="gold", window="slow") >= 1
    # exactly ONE attributable dump per cooldown window
    dumps = [(r, d) for (r, d) in get_recorder().dumps if r == "SLO_BURN"]
    assert len(dumps) == 1 and dumps[0][1].tenant == "gold"


def test_burn_sentinel_min_samples_floor():
    t = SLOTracker(window=64)
    t.register(SLOSpec("a", 0.95, 0.0, 0.999))
    # a handful of bad replies must not page (BURN_MIN_SAMPLES floor)
    for _ in range(8):
        assert t.observe("a", 1000, ok=False) is None


def test_burn_sentinel_cooldown_rearms(monkeypatch):
    monkeypatch.setattr(Global, "slo_dump_cooldown_s", 0)
    t = SLOTracker(window=128)
    t.register(SLOSpec("a", 0.95, 0.0, 0.999))
    verdicts = [t.observe("a", 1000, ok=False) for _ in range(40)]
    # with no cooldown, every observe past the sample floor re-trips
    assert len([v for v in verdicts if v is not None]) > 1


def test_burn_sentinel_budget_absorbs_fault_rate():
    """The conflicting-SLO property: the same bad-reply rate trips a
    three-nines tenant and leaves a one-nine tenant quiet."""
    t = SLOTracker(window=256)
    t.register(SLOSpec("strict", 0.95, 0.0, 0.999))
    t.register(SLOSpec("loose", 0.95, 0.0, 0.5))
    strict = loose = 0
    for i in range(100):
        bad = i % 4 == 0  # 25% bad
        if t.observe("strict", 1000, ok=not bad) is not None:
            strict += 1
        if t.observe("loose", 1000, ok=not bad) is not None:
            loose += 1
    assert strict >= 1 and loose == 0


# ---------------------------------------------------------------------------
# the overload signal bus
# ---------------------------------------------------------------------------

def test_admission_inputs_backed_by_registered_metrics(proxy):
    """Runtime parity of the ADMISSION_INPUTS contract: every named
    metric exists in the live registry (the slo-telemetry gate holds the
    same statically)."""
    import wukong_tpu.runtime.scheduler  # noqa: F401 (registers gauges)

    snap = get_registry().snapshot()
    for signal, metric in ADMISSION_INPUTS.items():
        assert metric in snap, (signal, metric)


def test_overload_inflight_and_arrival_ewma():
    sig = get_overload()
    sig.note_admit("t1")
    sig.note_admit("t1")
    assert sig.report()["tenants"]["t1"]["inflight"] == 2
    assert sig.inflight_series()[("t1",)] == 2
    sig.note_done("t1")
    assert sig.report()["tenants"]["t1"]["inflight"] == 1
    # two arrivals = one gap = a live arrival-rate EWMA
    assert sig.report()["tenants"]["t1"]["arrival_qps"] > 0


def test_pool_queue_delay_and_utilization(world):
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.runtime.scheduler import EnginePool, _pool_utilization
    from wukong_tpu.sparql.parser import Parser

    g, ss = world["g"], world["ss"]
    get_overload().reset()
    pool = EnginePool(num_engines=2,
                      make_engine=lambda tid: CPUEngine(g, ss))
    pool.start()
    try:
        q = Parser(ss).parse(Q_CHAIN)
        heuristic_plan(q)
        q.result.blind = True
        out = pool.wait(pool.submit(q), timeout=30)
        assert out.result.status_code == ErrorCode.SUCCESS
        lanes = get_overload().lane_delay_series()
        assert ("default",) in lanes and lanes[("default",)] > 0
        assert 0.0 <= _pool_utilization() <= 1.0
    finally:
        pool.stop()


def test_pool_shed_counts_cause_and_tenant(world):
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.runtime.scheduler import EnginePool
    from wukong_tpu.sparql.parser import Parser
    from wukong_tpu.utils.errors import QueryTimeout

    g, ss = world["g"], world["ss"]
    m = get_registry().counter("wukong_shed_total",
                               labels=("cause", "tenant"))
    before = m.value(cause="queue_deadline", tenant="gold")
    pool = EnginePool(num_engines=1,
                      make_engine=lambda tid: CPUEngine(g, ss))
    pool.start()
    try:
        q = Parser(ss).parse(Q_CHAIN)
        heuristic_plan(q)
        q.result.blind = True
        q.tenant = "gold"
        q.deadline = Deadline(timeout_ms=1)
        time.sleep(0.02)  # expire in the queue
        out = pool.wait(pool.submit(q), timeout=30)
        assert isinstance(out, QueryTimeout)
        assert m.value(cause="queue_deadline", tenant="gold") == before + 1
    finally:
        pool.stop()


# ---------------------------------------------------------------------------
# the off knob: zero-cost accounting bypass
# ---------------------------------------------------------------------------

def test_off_knob_touches_nothing(proxy, monkeypatch):
    monkeypatch.setattr(Global, "enable_tenant_accounting", False)
    q = proxy.serve_query(Q_CHAIN, blind=True, tenant="ghost")
    assert q.result.status_code == ErrorCode.SUCCESS
    assert q.tenant == "ghost"  # the identity still rides the query
    assert get_slo().compliance("ghost") is None
    assert "ghost" not in get_overload().report()["tenants"]
    lanes = get_overload().lane_delay_series()
    assert lanes == {}


# ---------------------------------------------------------------------------
# surfaces: /slo endpoint, console verb, Monitor line
# ---------------------------------------------------------------------------

def test_slo_endpoint_scrape(proxy):
    import socket
    import urllib.request

    from wukong_tpu.obs import maybe_start_metrics_http, stop_metrics_http

    get_slo().register(SLOSpec("gold", 0.95, 50.0, 0.99))
    proxy.serve_query(Q_CHAIN, blind=True, tenant="gold")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    assert maybe_start_metrics_http(port=port) is not None
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/slo", timeout=5).read().decode()
        assert "wukong-slo" in body and "gold" in body
        js = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/slo.json", timeout=5).read())
        rows = {r["tenant"]: r for r in js["tenants"]}
        assert rows["gold"]["spec"]["availability"] == 0.99
        assert "error_budget_remaining" in rows["gold"]
        assert "burn" in rows["gold"]
        assert js["signals"]["inputs"] == ADMISSION_INPUTS
    finally:
        stop_metrics_http()


def test_console_slo_verb_and_tenant_flag(proxy, tmp_path, capsys):
    from wukong_tpu.runtime.console import Console

    qf = tmp_path / "q.sparql"
    qf.write_text(Q_CHAIN)
    con = Console(proxy)
    con.run_command(f"sparql -f {qf} -d cpu -t acme")
    assert get_slo().compliance("acme")["samples"] == 1
    con.run_command("slo -k 4")
    out = capsys.readouterr().out
    assert "wukong-slo" in out and "acme" in out


def test_monitor_slo_lines():
    from wukong_tpu.runtime.monitor import Monitor

    mon = Monitor()
    assert mon.slo_lines() == []  # quiet with no spec'd tenants
    get_slo().register(SLOSpec("gold", 0.95, 0.0, 0.99))
    for i in range(10):
        get_slo().observe("gold", 1000, ok=i % 2 == 0)
    lines = mon.slo_lines()
    assert len(lines) == 1
    assert lines[0].startswith("SLO[") and "gold" in lines[0]
    assert "burn" in lines[0]


def test_render_slo_empty_state():
    text, js = render_slo()
    assert "no tenant replies observed" in text
    assert js["tenants"] == []
    assert js["signals"]["inputs"] == ADMISSION_INPUTS


# ---------------------------------------------------------------------------
# Emulator.run_tenants — item 4's acceptance fixture
# ---------------------------------------------------------------------------

def _serving_texts(world, n=6):
    from wukong_tpu.types import OUT

    ss, g = world["ss"], world["g"]
    pid = ss.str2id(f"<{UB}advisor>")
    anchors = np.asarray(g.get_index(pid, OUT))[:n]
    return [f"SELECT ?s WHERE {{ ?s <{UB}advisor> "
            f"{ss.id2str(int(a))} . }}" for a in anchors]


def test_run_tenants_conflicting_slos(proxy, world):
    """Acceptance: 3 conflicting tenant classes produce per-tenant
    compliance / error budget / burn rates in the scenario result and
    /slo.json."""
    from wukong_tpu.runtime.emulator import Emulator

    out = Emulator(proxy).run_tenants(
        _serving_texts(world), duration_s=0.8, warmup_s=0.1, seed=3)
    assert set(out["tenants"]) == {"gold", "silver", "bulk"}
    for name, d in out["tenants"].items():
        assert d["served"] > 0, name
        slo = d["slo"]
        assert slo["spec"] is not None
        assert slo["compliance"] is not None
        assert "error_budget_remaining" in slo
        assert set(slo["burn"]) == {"fast", "slow"}
    # the same numbers are in the /slo.json body the scrape serves
    rows = {r["tenant"]: r for r in out["slo_json"]["tenants"]}
    assert set(rows) >= {"gold", "silver", "bulk"}
    assert out["qps"] > 0 and out["chaos"] is False


@pytest.mark.chaos
def test_run_tenants_chaos_trips_sentinel_with_one_dump(proxy, world):
    """Acceptance: the chaos variant (transient faults at proxy.serve,
    the same rate for every tenant) trips the burn sentinel only for
    tenants whose budget cannot absorb it, and dumps exactly one
    attributable trace per tenant per cooldown window."""
    from wukong_tpu.runtime.emulator import Emulator

    out = Emulator(proxy).run_tenants(
        _serving_texts(world), duration_s=1.2, warmup_s=0.1,
        chaos=True, chaos_p=0.3, seed=3)
    assert out["alerts"]["gold"] >= 1      # budget 0.001: burn ~300x
    assert out["alerts"]["bulk"] == 0      # budget 0.1: burn ~3x < slow_x
    assert out["burn_dumps"], "chaos must dump at least one trace"
    per_tenant: dict = {}
    for d in out["burn_dumps"]:
        assert d["tenant"] in ("gold", "silver")
        per_tenant[d["tenant"]] = per_tenant.get(d["tenant"], 0) + 1
    # one dump per tenant per cooldown window (cooldown >> run duration)
    assert all(n == 1 for n in per_tenant.values()), per_tenant
    # the injected faults also burned availability in the tracker
    assert out["tenants"]["gold"]["slo"]["compliance"] < 1.0


# ---------------------------------------------------------------------------
# satellites: dump attribution, wcoj feedback, the slo-telemetry gate
# ---------------------------------------------------------------------------

def test_flight_recorder_dump_carries_tenant():
    tr = QueryTrace(kind="query", tenant="acme")
    tr.finish("SUCCESS")
    get_recorder().dump(tr, "SLO_BURN")
    [(reason, dumped)] = list(get_recorder().dumps)
    assert reason == "SLO_BURN" and dumped.tenant == "acme"
    assert dumped.to_dict()["tenant"] == "acme"


def test_regression_sentinel_verdict_carries_tenant(monkeypatch):
    from wukong_tpu.obs.profile import LatencyAttributor

    monkeypatch.setattr(Global, "attribution_min_samples", 4)

    def fake(total_us):
        tr = QueryTrace(kind="query", tenant="acme")
        sp = tr.start_span("cpu.execute")
        tr.end_span(sp)
        sp.t1_us = sp.t0_us + int(total_us * 0.9)
        tr.finish("SUCCESS")
        tr.t1_us = tr.t0_us + total_us
        return tr

    att = LatencyAttributor(window=32)
    for _ in range(6):
        att.observe(fake(1000), "T")
    v = att.observe(fake(50_000), "T")
    assert v is not None and v["tenant"] == "acme"


def test_wcoj_measured_feedback_demotes_to_walk(monkeypatch):
    """Satellite: a template auto-routed wcoj on the over-predicted
    estimate is demoted to the walk once the measured prefix blowup
    shows wcoj did not keep intermediates near the fragment."""
    from wukong_tpu.loader.datagen import generate_triangle
    from wukong_tpu.planner.optimizer import Planner
    from wukong_tpu.planner.stats import Stats
    from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
    from wukong_tpu.types import OUT

    monkeypatch.setattr(Global, "wcoj_min_rows", 1)
    triples, spec = generate_triangle(m=200, noise=4, seed=0)
    g = build_partition(triples, 0, 1)
    stats = Stats.generate(triples)
    p = Proxy(g, None, CPUEngine(g))
    p.planner = Planner(stats)

    def planned():
        q = SPARQLQuery()
        q.pattern_group.patterns = [Pattern(s, pr, OUT, o)
                                    for (s, pr, o) in spec["patterns"]]
        q.result.nvars = len(spec["vars"])
        q.result.required_vars = list(spec["vars"])
        q.result.blind = True
        p.planner.generate_plan(q)
        return q

    q = planned()
    q.join_strategy = p.classify_join_strategy(q)
    assert q.join_strategy == "wcoj"  # the estimate routes wcoj
    p._serve_execute(q, p.cpu)
    assert q.result.status_code == ErrorCode.SUCCESS
    # the REAL triangle keeps its prefix near the fragment: no demotion
    assert p.classify_join_strategy(planned()) == "wcoj"
    # a measured prefix blowup past wcoj_ratio demotes the template
    q2 = planned()
    q2.join_stats = [
        {"level": 0, "var": -1, "rows_in": 1, "rows_out": 5000,
         "candidates": 5000, "probes": 1, "time_us": 10},
        {"level": 1, "var": -2, "rows_in": 5000, "rows_out": 100,
         "candidates": 5100, "probes": 2, "time_us": 10}]
    q2.result.status_code = ErrorCode.SUCCESS
    before = get_registry().counter("wukong_join_demotions_total").value()
    p._record_wcoj_feedback(q2)
    assert p.classify_join_strategy(planned()) == "walk"
    assert get_registry().counter(
        "wukong_join_demotions_total").value() == before + 1
    # the measurement itself is introspectable through the plan cache
    key = (*p._plan_version(), "auto", int(Global.wcoj_ratio),
           int(Global.wcoj_min_rows))
    from wukong_tpu.runtime.batcher import template_signature

    assert p._plan_cache.aux(
        "wcoj_measured", template_signature(q2), key,
        lambda: None) == 50.0


def test_proxy_serve_fault_site_is_injectable(proxy):
    """The chaos scenario's injection point: a transient fault at
    proxy.serve surfaces as a client-visible error reply that reaches
    tenant accounting."""
    from wukong_tpu.runtime.faults import FaultPlan, FaultSpec, TransientFault

    faults.install(FaultPlan(
        [FaultSpec("proxy.serve", "transient", p=1.0, count=1)], seed=0))
    with pytest.raises(TransientFault):
        proxy.serve_query(Q_CHAIN, blind=True, tenant="gold")
    c = get_slo().compliance("gold")
    assert c["errors"] == 1
    # the plan is exhausted (count=1): the next query serves normally
    q = proxy.serve_query(Q_CHAIN, blind=True, tenant="gold")
    assert q.result.status_code == ErrorCode.SUCCESS


def test_slo_telemetry_gate_fixtures(tmp_path):
    """The new analysis gate: an unregistered admission-input metric, an
    unannotated shared structure, and an undeclared leaf lock are
    violations; the clean shape is not."""
    from wukong_tpu.analysis import run_analysis

    def write(tree: dict) -> str:
        root = tmp_path / "pkg"
        for rel, src in tree.items():
            p = root / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(src)
        return str(root)

    bad = write({"obs/slo.py": (
        "ADMISSION_INPUTS = {'shed': 'wukong_nope_total'}\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self.tenants = {}\n"
        "        self.lock = make_lock('slo.x')\n")})
    out = run_analysis(bad, plugins=["slo-telemetry"])
    msgs = "\n".join(str(v) for v in out)
    assert "wukong_nope_total" in msgs  # unregistered admission input
    assert "A.tenants" in msgs  # unannotated shared structure
    assert "slo.x" in msgs  # undeclared leaf lock

    good = write({"obs/slo.py": (
        "ADMISSION_INPUTS = {'shed': 'wukong_ok_total'}\n"
        "declare_leaf('slo.x')\n"
        "reg.counter('wukong_ok_total')\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self.tenants = {}  # guarded by: _lock\n"
        "        self.lock = make_lock('slo.x')\n")})
    assert run_analysis(good, plugins=["slo-telemetry"]) == []

    # a tree without an SLO plane is not checked (partial fixtures)
    empty = write({"other.py": "x = 1\n"})
    assert run_analysis(empty, plugins=["slo-telemetry"]) == []
