"""Stats.generate scale fast paths (round 5): the per-vertex frozenset
loops OOM-killed the LUBM-10240 run (220 M typed vertices -> ~44 GB of
Python sets), so single-typed worlds and large untyped-with-out-edges
populations take vectorized paths. These tests pin the vectorized
signature grouping against an independent brute-force implementation."""

import numpy as np

from wukong_tpu.planner.stats import Stats
from wukong_tpu.types import NORMAL_ID_START, TYPE_ID


def _world_with_big_untyped(n_untyped=250_000, seed=0):
    """A few typed vertices + a large untyped population with out-edges —
    drives the vectorized signature branch (> 200k out-edged untyped)."""
    rng = np.random.default_rng(seed)
    base = NORMAL_ID_START
    typed = base + np.arange(50)
    t_id = 40
    untyped = base + 50 + np.arange(n_untyped)
    preds = 2 + np.arange(5)
    rows = [np.stack([typed, np.full(50, TYPE_ID), np.full(50, t_id)], 1)]
    # each untyped subject: 1-3 distinct predicates toward typed targets
    k = rng.integers(1, 4, n_untyped)
    subs = np.repeat(untyped, k)
    # distinct preds per subject via offset trick
    b0 = rng.integers(0, 5, n_untyped)
    step = rng.integers(1, 3, n_untyped)
    j = np.concatenate([np.arange(x) for x in k])
    psel = preds[(np.repeat(b0, k) + j * np.repeat(step, k)) % 5]
    objs = typed[rng.integers(0, 50, len(subs))]
    rows.append(np.stack([subs, psel, objs], 1))
    # plus literals that are objects only (no out-edges at all)
    lits = base + 50 + n_untyped + np.arange(1000)
    rows.append(np.stack([typed[rng.integers(0, 50, 1000)],
                          np.full(1000, int(preds[0])), lits], 1))
    return np.unique(np.concatenate(rows), axis=0)


def test_vectorized_untyped_signature_matches_bruteforce():
    triples = _world_with_big_untyped()
    st = Stats.generate(triples)

    # brute force: group untyped subjects by their out-predicate SET
    s, p, o = triples[:, 0], triples[:, 1], triples[:, 2]
    typed_set = set(s[p == TYPE_ID].tolist())
    psets: dict[int, frozenset] = {}
    for si, pi in zip(s.tolist(), p.tolist()):
        if pi != TYPE_ID and si not in typed_set:
            psets.setdefault(si, set())
    for si, pi in zip(s.tolist(), p.tolist()):
        if pi != TYPE_ID and si not in typed_set:
            psets[si].add(pi)
    all_vs = set(s.tolist()) | {x for x in o.tolist()
                                if x >= NORMAL_ID_START}
    no_out = all_vs - typed_set - set(psets)
    groups: dict[frozenset, set] = {}
    for v, ps in psets.items():
        groups.setdefault(frozenset(ps), set()).add(v)
    if no_out:
        groups.setdefault(frozenset(), set()).update(no_out)

    # same partition: vertices share a Stats class iff they share a pset
    cls_of = {int(v): st.type_of(int(v))
              for v in (set(psets) | no_out)}
    assert all(c < 0 for c in cls_of.values())  # complex ids
    seen = {}
    for key, members in groups.items():
        cids = {cls_of[v] for v in members}
        assert len(cids) == 1, f"group {key} split across classes"
        cid = cids.pop()
        assert cid not in seen, f"classes {key} and {seen[cid]} merged"
        seen[cid] = key
        assert st.tyscount[cid] == len(members)


def test_single_typed_fast_path_counts():
    from wukong_tpu.loader.lubm import generate_lubm

    triples, _ = generate_lubm(1, seed=0)
    st = Stats.generate(triples)
    s, p, o = triples[:, 0], triples[:, 1], triples[:, 2]
    want = dict(zip(*np.unique(o[p == TYPE_ID], return_counts=True)))
    for t, c in want.items():
        assert st.tyscount[int(t)] == int(c)
    # one shared class for the literal pools (objects with no out-edges)
    neg = [t for t in st.tyscount if t < 0]
    assert len(neg) == 1
    typed_n = len(np.unique(s[p == TYPE_ID]))
    assert len(st.vtype_ids) == typed_n + st.tyscount[neg[0]]
