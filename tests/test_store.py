import numpy as np
import pytest

from wukong_tpu.loader.lubm import P, T, generate_lubm, write_dataset
from wukong_tpu.store.checker import check_cross_partition, check_partition
from wukong_tpu.store.gstore import build_all_partitions, build_partition
from wukong_tpu.store.segment import CSRSegment
from wukong_tpu.store.string_server import StringServer
from wukong_tpu.types import IN, OUT, PREDICATE_ID, TYPE_ID


@pytest.fixture(scope="module")
def lubm1():
    return generate_lubm(1, seed=42)


@pytest.fixture(scope="module")
def stores(lubm1):
    triples, _ = lubm1
    return build_all_partitions(triples, 4)


def test_csr_segment_basics():
    k = np.array([5, 3, 5, 3, 9], dtype=np.int64)
    v = np.array([1, 2, 4, 2, 7], dtype=np.int64)
    seg = CSRSegment.from_pairs(k, v)
    assert seg.keys.tolist() == [3, 5, 9]
    assert seg.lookup(3).tolist() == [2]  # deduped
    assert seg.lookup(5).tolist() == [1, 4]
    assert seg.lookup(42).tolist() == []
    start, deg = seg.lookup_many(np.array([3, 42, 9]))
    assert deg.tolist() == [1, 0, 1]
    ok = seg.contains_pair(np.array([5, 5, 3, 42]), np.array([4, 2, 2, 1]))
    assert ok.tolist() == [True, False, True, False]


def test_partition_covers_all_triples(lubm1, stores):
    triples, _ = lubm1
    # total OUT edges across partitions == unique triples
    uniq = len(np.unique(triples.view([("s", np.int64), ("p", np.int64), ("o", np.int64)])))
    total_out = sum(
        seg.num_edges for g in stores for (pid, d), seg in g.segments.items() if d == OUT
    )
    assert total_out == uniq


def test_lookup_semantics(lubm1, stores):
    triples, lay = lubm1
    s, p, o = triples[:, 0], triples[:, 1], triples[:, 2]
    # pick a professor and check worksFor
    fp0 = int(lay.fac_base[0])
    g = stores[fp0 % 4]
    dept = g.get_triples(fp0, P["worksFor"], OUT)
    assert dept.tolist() == [int(lay.dept_id[0])]
    # reverse direction from the department's owner
    gd = stores[int(lay.dept_id[0]) % 4]
    members = gd.get_triples(int(lay.dept_id[0]), P["worksFor"], IN)
    expected = np.sort(s[(p == P["worksFor"]) & (o == lay.dept_id[0])])
    assert members.tolist() == expected.tolist()
    # type list
    types = g.get_triples(fp0, TYPE_ID, OUT)
    assert types.tolist() == [T["FullProfessor"]]


def test_type_index_distributed(lubm1, stores):
    triples, lay = lubm1
    s, p, o = triples[:, 0], triples[:, 1], triples[:, 2]
    t_fp = T["FullProfessor"]
    all_fps = np.sort(s[(p == TYPE_ID) & (o == t_fp)])
    got = np.sort(np.concatenate([g.get_index(t_fp, IN) for g in stores]))
    assert got.tolist() == all_fps.tolist()
    # each member lives on its subject-hash owner
    for g in stores:
        members = g.get_index(t_fp, IN)
        assert (members % 4 == g.sid).all()


def test_pred_index(lubm1, stores):
    triples, _ = lubm1
    s, p, o = triples[:, 0], triples[:, 1], triples[:, 2]
    pid = P["advisor"]
    subj = np.unique(s[p == pid])
    got = np.sort(np.concatenate([g.get_index(pid, IN) for g in stores]))
    assert got.tolist() == subj.tolist()
    obj = np.unique(o[p == pid])
    got_o = np.sort(np.concatenate([g.get_index(pid, OUT) for g in stores]))
    assert got_o.tolist() == obj.tolist()


def test_versatile_pred_lists(lubm1, stores):
    triples, lay = lubm1
    fp0 = int(lay.fac_base[0])
    g = stores[fp0 % 4]
    preds = g.get_triples(fp0, PREDICATE_ID, OUT)
    assert TYPE_ID in preds  # OUT list includes rdf:type
    assert P["worksFor"] in preds and P["teacherOf"] in preds
    # IN pred list of a department: no TYPE_ID (type triples skipped on pos side)
    d0 = int(lay.dept_id[0])
    gd = stores[d0 % 4]
    in_preds = gd.get_triples(d0, PREDICATE_ID, IN)
    assert TYPE_ID not in in_preds
    assert P["worksFor"] in in_preds and P["memberOf"] in in_preds


def test_gsck_clean(stores):
    for g in stores:
        assert check_partition(g) == []
    assert check_cross_partition(stores) == []


def test_gsck_detects_corruption(lubm1):
    triples, _ = lubm1
    g = build_partition(triples, 0, 1)
    # corrupt: drop a vertex from a type index list
    key = next(k for k in g.index if k[0] in g.type_ids and len(g.index[k]) > 2)
    g.index[key] = g.index[key][:-1]
    assert any("missing from tidx" in e for e in check_partition(g))


def test_vid_range_rejects_out_of_range_ids():
    from wukong_tpu.store.gstore import check_vid_range
    from wukong_tpu.utils.errors import WukongError

    check_vid_range(np.empty((0, 3), dtype=np.int64))  # empty: fine
    ok = np.array([[1, 2, 3]], dtype=np.int64)
    check_vid_range(ok)
    # >= 2^31 - 1 collides with the int32 device padding sentinel
    with pytest.raises(WukongError):
        check_vid_range(np.array([[1, 2, 2**31 - 1]], dtype=np.int64))
    # negative ids violate the native radix sort's unsigned-digit contract
    # (the np.lexsort fallback would order them correctly — a silent
    # toolchain-dependent store divergence unless rejected here)
    with pytest.raises(WukongError):
        check_vid_range(np.array([[1, 2, -5]], dtype=np.int64))


def test_string_server_virtual(tmp_path, lubm1):
    write_dataset(str(tmp_path), 1, seed=42, fmt="npy")
    ss = StringServer(str(tmp_path))
    _, lay = lubm1
    assert ss.str2id("<http://www.University0.edu>") == lay.univ_base
    assert ss.str2id("__PREDICATE__") == 0
    ub = "http://swat.cse.lehigh.edu/onto/univ-bench.owl#"
    assert ss.str2id(f"<{ub}worksFor>") == P["worksFor"]
    assert ss.id2str(T["Course"]) == f"<{ub}Course>"
    assert ss.exist("<http://www.University0.edu>")
    assert not ss.exist("<http://bogus>")


def test_loader_roundtrip(tmp_path, lubm1):
    from wukong_tpu.loader.base import load_dataset, load_triples

    triples, _ = lubm1
    write_dataset(str(tmp_path), 1, seed=42, fmt="npy")
    loaded = load_triples(str(tmp_path))
    assert np.array_equal(np.sort(loaded, axis=0), np.sort(triples, axis=0))
    stores = load_dataset(str(tmp_path), 2)
    assert len(stores) == 2
    assert check_cross_partition(stores) == []
