"""Streaming subsystem: delta-vs-oracle correctness, windows, ingest chaos.

The acceptance bar (ISSUE 2): standing-query results after N streamed epochs
must be byte-identical to a from-scratch run of the same query on the final
graph — checked here for one-hop, chain, const-anchored, and FILTER shapes,
plus a windowed query whose oracle is the surviving window contents after
retractions. Chaos tests drive the `stream.ingest` / `dynamic.insert` fault
sites through the ingest retry path.
"""

import numpy as np
import pytest

from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.runtime import faults
from wukong_tpu.runtime.faults import FaultPlan, FaultSpec, TransientFault
from wukong_tpu.runtime.monitor import Monitor
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.runtime.resilience import CircuitBreaker
from wukong_tpu.runtime.scheduler import EnginePool
from wukong_tpu.sparql.parser import Parser
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.stream import (
    EpochWindow,
    FileSource,
    ReplaySource,
    StreamContext,
    WindowSpec,
)
from wukong_tpu.utils.errors import ErrorCode, RetryExhausted, WukongError

pytestmark = pytest.mark.stream

PREFIX = """
PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
"""
Q_ONEHOP = PREFIX + "SELECT ?X ?Y WHERE { ?X ub:memberOf ?Y . }"
Q_CHAIN = PREFIX + """SELECT ?X ?Y ?Z WHERE {
    ?X ub:memberOf ?Y .
    ?Y ub:subOrganizationOf ?Z .
}"""
Q_CONST = PREFIX + """SELECT ?X WHERE {
    ?X ub:worksFor <http://www.Department0.University0.edu> .
    ?X rdf:type ub:FullProfessor .
}"""
Q_FILTER = PREFIX + """SELECT ?X ?Y ?Z WHERE {
    ?X ub:advisor ?Y .
    ?X ub:memberOf ?Z .
    FILTER ( ?Y != ?Z )
}"""


@pytest.fixture(scope="module")
def world():
    triples, lay = generate_lubm(1, seed=42)
    ss = VirtualLubmStrings(1, seed=42)
    rng = np.random.default_rng(0)
    perm = rng.permutation(len(triples))
    return triples, ss, perm


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.clear()
    yield
    faults.clear()


def full_run(triples, ss, text) -> np.ndarray:
    """Oracle: from-scratch evaluation on a freshly-built partition,
    projected to the required vars, distinct, row-sorted."""
    g = build_partition(triples, 0, 1)
    q = Parser(ss).parse(text)
    heuristic_plan(q)
    q.result.blind = True
    CPUEngine(g, ss).execute(q, from_proxy=False)
    cols = [q.result.var2col(v) for v in q.result.required_vars]
    if q.result.nrows == 0:
        return np.empty((0, len(cols)), dtype=np.int64)
    return np.unique(q.result.table[:, cols], axis=0)


def split(triples, perm, n_base):
    return triples[perm[:n_base]], triples[perm[n_base:]]


# ---------------------------------------------------------------------------
# delta-vs-oracle: streamed epochs == from-scratch run on the final graph
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("text", [Q_ONEHOP, Q_CHAIN, Q_CONST, Q_FILTER],
                         ids=["onehop", "chain", "const", "filter"])
def test_delta_matches_oracle(world, text):
    triples, ss, perm = world
    base, live = split(triples, perm, len(triples) // 2)
    ctx = StreamContext([build_partition(base, 0, 1)], ss)
    qid = ctx.register(text)
    recs = ctx.feed_source(ReplaySource(live, batch_size=4096))
    assert len(recs) == -(-len(live) // 4096)  # every batch became an epoch
    oracle = full_run(triples, ss, text)
    got = ctx.result_set(qid)
    assert got.shape == oracle.shape
    assert np.array_equal(got, oracle)  # byte-identical
    # the default poll returns the full history (incl. the registration
    # snapshot): append-only +1 deltas that sum to the result set
    deltas = ctx.poll(qid)
    assert all(d.sign == +1 for d in deltas)
    assert sum(len(d.rows) for d in deltas) == len(oracle)


def test_registration_snapshot_seeds_base_results(world):
    """Results already derivable at registration time appear without any
    epoch — and streaming on top never re-emits them."""
    triples, ss, perm = world
    base, live = split(triples, perm, len(triples) // 2)
    ctx = StreamContext([build_partition(base, 0, 1)], ss)
    qid = ctx.register(Q_ONEHOP)
    snap = ctx.result_set(qid)
    assert np.array_equal(snap, full_run(base, ss, Q_ONEHOP))
    ctx.feed_source(ReplaySource(live, batch_size=8192))
    seen = set()
    for d in ctx.poll(qid):
        rows = set(map(tuple, d.rows.tolist()))
        assert not rows & seen  # no row is ever emitted twice
        seen |= rows


def test_epoch_order_invariance(world):
    """Different batch sizes (= different epoch boundaries) converge to the
    identical standing result."""
    triples, ss, perm = world
    base, live = split(triples, perm, len(triples) // 2)
    results = []
    for bs in (1024, 16384):
        ctx = StreamContext([build_partition(base, 0, 1)], ss)
        qid = ctx.register(Q_CHAIN)
        ctx.feed_source(ReplaySource(live, batch_size=bs))
        results.append(ctx.result_set(qid))
    assert np.array_equal(results[0], results[1])


def test_poll_since_epoch_and_unregister(world):
    triples, ss, perm = world
    base, live = split(triples, perm, len(triples) - 3000)
    ctx = StreamContext([build_partition(base, 0, 1)], ss)
    qid = ctx.register(Q_ONEHOP)
    ctx.feed_source(ReplaySource(live, batch_size=1000))
    assert ctx.epoch == 3
    all_deltas = ctx.poll(qid)
    # default poll covers the registration snapshot (epoch 0 here) — the
    # same coverage a late registrant would see — and a cursor filters it
    assert all_deltas[0].epoch == 0
    late = ctx.poll(qid, since_epoch=2)
    assert late == [d for d in all_deltas if d.epoch > 2]
    # pruning behind the cursor frees history without touching the result
    before = len(ctx.result_set(qid))
    assert ctx.prune(qid, upto_epoch=2) == len(
        [d for d in all_deltas if d.epoch <= 2])
    assert ctx.poll(qid) == late
    assert len(ctx.result_set(qid)) == before
    ctx.unregister(qid)
    with pytest.raises(WukongError):
        ctx.poll(qid)
    with pytest.raises(WukongError):
        ctx.unregister(qid)


# ---------------------------------------------------------------------------
# windows: retirement, retraction, windowed oracle
# ---------------------------------------------------------------------------

def test_epoch_window_sliding():
    w = EpochWindow(spec=WindowSpec(size=3, slide=1))
    retired = {e: [r for r, _ in w.add(e, np.empty((0, 3), dtype=np.int64))]
               for e in range(1, 6)}
    assert retired == {1: [], 2: [], 3: [], 4: [1], 5: [2]}
    assert w.live_epochs() == [3, 4, 5]


def test_epoch_window_tumbling():
    w = EpochWindow(spec=WindowSpec.tumbling(2))
    retired = {e: [r for r, _ in w.add(e, np.empty((0, 3), dtype=np.int64))]
               for e in range(1, 7)}
    # the previous window retires in bulk as soon as the next one opens —
    # a mid-window epoch never sees an already-reported window
    assert retired == {1: [], 2: [], 3: [1, 2], 4: [], 5: [3, 4], 6: []}
    assert w.live_epochs() == [5, 6]


def test_window_spec_validation():
    with pytest.raises(ValueError):
        WindowSpec(size=0)
    with pytest.raises(ValueError):
        WindowSpec(size=2, slide=3)
    with pytest.raises(WukongError):
        StreamContext([build_partition(
            np.asarray([[5, 1, 6]], dtype=np.int64), 0, 1)]).register(
                Q_ONEHOP, window="not-a-spec")


def _surviving(batches, spec: WindowSpec):
    """Independent re-derivation of the documented retirement rule."""
    live = []
    for e, batch in enumerate(batches, start=1):
        live.append((e, batch))
        cutoff = (e - 1) // spec.slide * spec.slide - (spec.size - spec.slide)
        live = [ent for ent in live if ent[0] > cutoff]
    return np.concatenate([b for _, b in live])


@pytest.mark.parametrize("spec", [WindowSpec(size=3, slide=1),
                                  WindowSpec.tumbling(2)],
                         ids=["sliding", "tumbling"])
def test_windowed_delta_matches_window_oracle(world, spec):
    """After retractions, the standing result is byte-identical to a
    from-scratch run over base_triples + the surviving window epochs."""
    triples, ss, perm = world
    base, live = split(triples, perm, len(triples) // 2)
    live = live[:12000]
    ctx = StreamContext([build_partition(base, 0, 1)], ss)
    qid = ctx.register(Q_ONEHOP, window=spec, base_triples=base)
    batches = [b for _, b in ReplaySource(live, batch_size=2000)]
    for b in batches:
        ctx.feed(b)
    assert ctx.epoch == 6  # enough epochs that the window closed and retired
    deltas = ctx.poll(qid)  # full history incl. the registration snapshot
    assert any(d.sign == -1 for d in deltas)  # retraction actually happened
    oracle = full_run(np.concatenate([base, _surviving(batches, spec)]),
                      ss, Q_ONEHOP)
    assert np.array_equal(ctx.result_set(qid), oracle)
    # replaying the sink (additions minus retractions) rebuilds the set
    acc: set = set()
    for d in deltas:
        rows = set(map(tuple, d.rows.tolist()))
        acc = acc | rows if d.sign > 0 else acc - rows
    assert np.array_equal(np.asarray(sorted(acc), dtype=np.int64), oracle)


def test_sliding_window_incremental_retraction_oracle(world, monkeypatch):
    """PR 9 follow-up (b): per-result support counting makes retirement
    incremental. After EVERY epoch of a sliding window the standing set
    must match the from-scratch oracle over base + surviving epochs —
    including chain results whose derivations span epochs (they retract
    exactly when their oldest contributing epoch retires). The full-
    refresh fallback is disabled after registration, so this passes only
    if the incremental path (overdelete + support + re-derive) carries
    every retirement alone."""
    triples, ss, perm = world
    base, live = split(triples, perm, len(triples) // 2)
    live = live[:12000]
    spec = WindowSpec(size=3, slide=1)
    ctx = StreamContext([build_partition(base, 0, 1)], ss)
    qid = ctx.register(Q_CHAIN, window=spec, base_triples=base)

    def _no_refresh(*a, **k):  # any fallback is a silent perf regression
        raise AssertionError("full-refresh fallback used")

    monkeypatch.setattr(ctx.continuous, "_snapshot", _no_refresh)
    batches = [b for _, b in ReplaySource(live, batch_size=2000)]
    retracted = 0
    for k, b in enumerate(batches):
        ctx.feed(b)
        oracle = full_run(
            np.concatenate([base, _surviving(batches[:k + 1], spec)]),
            ss, Q_CHAIN)
        assert np.array_equal(ctx.result_set(qid), oracle), f"epoch {k + 1}"
        retracted += sum(len(d.rows) for d in ctx.poll(qid)
                         if d.sign == -1)
    assert retracted > 0  # retirement actually retracted rows
    # the sink replay (additions minus retractions) rebuilds the set
    acc: set = set()
    for d in ctx.poll(qid):
        rows = set(map(tuple, d.rows.tolist()))
        acc = acc | rows if d.sign > 0 else acc - rows
    assert np.array_equal(np.asarray(sorted(acc), dtype=np.int64),
                          ctx.result_set(qid))


def test_support_index_counts_and_base_fastpath():
    """SupportIndex unit semantics: live-epoch evidence counts, the
    base-supported permanent rows, and evidence-exhaustion on retire."""
    from wukong_tpu.stream.windows import SupportIndex

    si = SupportIndex()
    si.note_base({(1,), (2,)})
    si.note_epoch(1, {(2,), (3,), (4,)})
    si.note_epoch(2, {(3,)})
    assert si.support_of((3,)) == 2  # two live epochs derived it
    assert si.support_of((2,)) == 2  # base + epoch 1
    assert si.support_of((1,)) == 1  # base only
    dead = si.retire([1])
    # (4,) lost its only evidence; (3,) still has epoch 2; (2,) is
    # base-supported and never reported dead
    assert dead == {(4,)}
    assert si.support_of((3,)) == 1
    assert si.retire([2]) == {(3,)}
    si.note_epoch(3, {(5,)})
    si.reset()
    assert si.support_of((5,)) == 0 and si.support_of((1,)) == 1


def test_tumbling_mid_window_never_joins_previous_window(world):
    """At a mid-window epoch a tumbling query's result must reflect ONLY
    the current (open) window — never transient rows joined against the
    previous, already-retired window."""
    triples, ss, perm = world
    base, live = split(triples, perm, len(triples) // 2)
    live = live[:6000]
    spec = WindowSpec.tumbling(2)
    ctx = StreamContext([build_partition(base, 0, 1)], ss)
    qid = ctx.register(Q_ONEHOP, window=spec, base_triples=base)
    batches = [b for _, b in ReplaySource(live, batch_size=2000)]
    for b in batches:
        ctx.feed(b)
    assert ctx.epoch == 3  # mid-window: window [3,4] is open with only 3
    oracle = full_run(np.concatenate([base, batches[2]]), ss, Q_ONEHOP)
    assert np.array_equal(ctx.result_set(qid), oracle)


# ---------------------------------------------------------------------------
# sources
# ---------------------------------------------------------------------------

def test_replay_source_batching_and_ts():
    src = ReplaySource(np.arange(21, dtype=np.int64).reshape(7, 3),
                       batch_size=3, start_ts=10.0, ts_step=0.5)
    got = list(src)
    assert [ts for ts, _ in got] == [10.0, 10.5, 11.0]
    assert [len(b) for _, b in got] == [3, 3, 1]
    with pytest.raises(WukongError):
        ReplaySource(np.arange(8), batch_size=2)
    with pytest.raises(WukongError):
        ReplaySource(np.arange(9).reshape(3, 3), batch_size=0)


def test_file_source_three_and_four_col(tmp_path):
    f3 = tmp_path / "id_uni0.nt"
    np.savetxt(f3, np.asarray([[5, 1, 6], [7, 1, 8], [9, 1, 10]]), fmt="%d")
    got = list(FileSource(str(f3), batch_size=2))
    assert [len(b) for _, b in got] == [2, 1]
    # 4-col: rows regrouped per timestamp, epochs never mix timestamps
    f4 = tmp_path / "id_ts"
    f4.mkdir()
    rows = np.asarray([[5, 1, 6, 2], [7, 1, 8, 1], [9, 1, 10, 2],
                       [11, 1, 12, 1]])
    np.savetxt(f4 / "id_all.nt", rows, fmt="%d")
    got = list(FileSource(str(f4), batch_size=10))
    assert [ts for ts, _ in got] == [1.0, 2.0]
    assert sorted(got[0][1][:, 0].tolist()) == [7, 11]
    assert sorted(got[1][1][:, 0].tolist()) == [5, 9]
    empty = tmp_path / "empty-dir"
    empty.mkdir()
    with pytest.raises(WukongError):
        list(FileSource(str(empty)))


# ---------------------------------------------------------------------------
# registration-time rejections: structured errors, never silent wrong answers
# ---------------------------------------------------------------------------

def _ctx(world):
    triples, ss, perm = world
    base, _ = split(triples, perm, 2000)
    return StreamContext([build_partition(base, 0, 1)], ss)


def test_reject_limit_offset(world):
    with pytest.raises(WukongError) as ei:
        _ctx(world).register(Q_ONEHOP + " LIMIT 5")
    assert ei.value.code == ErrorCode.UNSUPPORTED_SHAPE


def test_reject_cartesian_product(world):
    q = PREFIX + """SELECT ?X ?Z WHERE {
        ?X ub:memberOf ?Y .
        ?Z ub:worksFor ?W .
    }"""
    with pytest.raises(WukongError) as ei:
        _ctx(world).register(q)
    assert ei.value.code == ErrorCode.UNSUPPORTED_SHAPE


def test_reject_fully_constant_pattern(world):
    q = PREFIX + """SELECT ?X WHERE {
        <http://www.Department0.University0.edu>
            ub:subOrganizationOf <http://www.University0.edu> .
        ?X ub:worksFor <http://www.Department0.University0.edu> .
    }"""
    with pytest.raises(WukongError) as ei:
        _ctx(world).register(q)
    assert ei.value.code == ErrorCode.UNSUPPORTED_SHAPE


# ---------------------------------------------------------------------------
# chaos: ingest fault sites through the retry path
# ---------------------------------------------------------------------------

@pytest.mark.chaos
@pytest.mark.parametrize("site", ["stream.ingest", "dynamic.insert"])
def test_transient_ingest_fault_retried_to_oracle(world, site):
    """Transient faults at either ingest-path site are retried (dedup makes
    the replay idempotent) and the standing result still matches the
    oracle exactly."""
    triples, ss, perm = world
    base, live = split(triples, perm, len(triples) // 2)
    ctx = StreamContext([build_partition(base, 0, 1)], ss)
    qid = ctx.register(Q_CHAIN)
    faults.install(FaultPlan([FaultSpec(site, "transient", count=2)], seed=7))
    recs = ctx.feed_source(ReplaySource(live, batch_size=8192))
    assert faults.active().specs[0].fired == 2
    assert [r.epoch for r in recs] == list(range(1, len(recs) + 1))
    assert np.array_equal(ctx.result_set(qid), full_run(triples, ss, Q_CHAIN))


@pytest.mark.chaos
def test_retried_partial_multi_store_ingest_counts_every_edge(world):
    """A transient after the first store committed must not lose that
    store's edges from the epoch's n_inserted accounting (the replay
    dedups them to 0)."""
    from wukong_tpu.store.gstore import build_all_partitions

    triples, ss, perm = world
    base, live = split(triples, perm, len(triples) // 2)
    batch = live[:2000]

    def run(spec):
        stores = build_all_partitions(base, 2)
        ctx = StreamContext(stores, ss)
        faults.install(FaultPlan([spec] if spec else [], seed=7))
        return ctx.feed(batch).n_inserted

    clean = run(None)
    # fault only the SECOND store's insert: store 0 commits, then the epoch
    # retries and store 0's replay dedups to 0
    faulted = run(FaultSpec("dynamic.insert", "transient", count=1, shard=1))
    assert faults.active().specs[0].fired == 1
    assert faulted == clean


@pytest.mark.chaos
def test_windowed_query_survives_window_insert_fault(world):
    """A transient at the windowed query's private window-store insert
    (after the main store committed) must not escape feed() or corrupt
    window bookkeeping — the epoch commits and the result still matches
    the surviving-window oracle."""
    triples, ss, perm = world
    base, live = split(triples, perm, len(triples) // 2)
    live = live[:12000]
    spec = WindowSpec(size=3, slide=1)
    ctx = StreamContext([build_partition(base, 0, 1)], ss)
    qid = ctx.register(Q_ONEHOP, window=spec, base_triples=base)
    # each epoch fires dynamic.insert twice (main store, then window
    # store); after=1 + every-other targeting hits only window inserts
    faults.install(FaultPlan([FaultSpec("dynamic.insert", "transient",
                                        after=1, count=3)], seed=7))
    batches = [b for _, b in ReplaySource(live, batch_size=2000)]
    for b in batches:
        ctx.feed(b)
    assert faults.active().specs[0].fired == 3
    assert ctx.epoch == 6
    oracle = full_run(np.concatenate([base, _surviving(batches, spec)]),
                      ss, Q_ONEHOP)
    assert np.array_equal(ctx.result_set(qid), oracle)


@pytest.mark.chaos
def test_non_dedup_ingest_does_not_retry(world):
    """Without dedup a replayed batch would double-append, so transients
    surface to the caller instead of being retried."""
    triples, ss, perm = world
    base, live = split(triples, perm, 4000)
    ctx = StreamContext([build_partition(base, 0, 1)], ss, dedup=False)
    faults.install(FaultPlan([FaultSpec("stream.ingest", "transient",
                                        count=1)], seed=7))
    with pytest.raises(TransientFault):
        ctx.feed(live[:100])
    assert ctx.epoch == 0  # the failed batch never became an epoch
    ctx.feed(live[:100])  # next attempt (fault budget spent) commits
    assert ctx.epoch == 1


@pytest.mark.chaos
def test_persistent_ingest_fault_exhausts_retries(world):
    triples, ss, perm = world
    base, live = split(triples, perm, 4000)
    ctx = StreamContext([build_partition(base, 0, 1)], ss)
    faults.install(FaultPlan([FaultSpec("stream.ingest", "transient")],
                             seed=7))
    with pytest.raises(RetryExhausted):
        ctx.feed(live[:100])
    assert ctx.epoch == 0


def test_ingest_rejects_negative_ids(world):
    ctx = _ctx(world)
    with pytest.raises(WukongError):
        ctx.feed(np.asarray([[-1, 1, 5]], dtype=np.int64))
    with pytest.raises(WukongError):
        ctx.feed(np.arange(8, dtype=np.int64).reshape(2, 4))


# ---------------------------------------------------------------------------
# runtime integration: stream lane, proxy verbs, monitor
# ---------------------------------------------------------------------------

def test_stream_lane_matches_inline(world):
    """Delta queries routed through the engine pool's low-priority stream
    lane produce the identical standing result, while the pool keeps
    serving interactive queries."""
    triples, ss, perm = world
    base, live = split(triples, perm, len(triples) // 2)
    g = build_partition(base, 0, 1)
    pool = EnginePool(num_engines=2,
                      make_engine=lambda tid: CPUEngine(g, ss))
    pool.start()
    try:
        ctx = StreamContext([g], ss, pool=pool)
        qid = ctx.register(Q_CHAIN)
        for _, batch in ReplaySource(live, batch_size=8192):
            ctx.feed(batch)
        # interactive one-shot rides the same pool, default lane
        q = Parser(ss).parse(Q_ONEHOP)
        heuristic_plan(q)
        q.result.blind = True
        out = pool.wait(pool.submit(q), timeout=60)
        assert out.result.status_code == ErrorCode.SUCCESS
        assert np.array_equal(ctx.result_set(qid),
                              full_run(triples, ss, Q_CHAIN))
    finally:
        pool.stop()


def test_inline_eval_crash_degrades_not_escapes(world):
    """An engine crash during one standing query's inline delta eval must
    not escape feed() (the main store already committed) or starve the
    other registered queries."""
    triples, ss, perm = world
    base, live = split(triples, perm, len(triples) // 2)
    ctx = StreamContext([build_partition(base, 0, 1)], ss)
    q1 = ctx.register(Q_ONEHOP)
    q2 = ctx.register(Q_CHAIN)
    real = ctx.continuous.engine.execute
    calls = {"n": 0}

    def boom(q, from_proxy=True):
        calls["n"] += 1
        if calls["n"] == 1:  # q1's first term of the first epoch
            raise RuntimeError("injected eval crash")
        return real(q, from_proxy=from_proxy)

    ctx.continuous.engine.execute = boom
    recs = ctx.feed_source(ReplaySource(live, batch_size=8192))
    assert [r.epoch for r in recs] == list(range(1, len(recs) + 1))
    assert ctx.continuous.queries[q1].degraded_epochs == 1
    assert ctx.continuous.queries[q2].degraded_epochs == 0
    # the unaffected query still matches the oracle exactly
    assert np.array_equal(ctx.result_set(q2), full_run(triples, ss, Q_CHAIN))


def test_stream_lane_starvation_bounded_wait(world, monkeypatch):
    """A starved stream lane must not block feed() forever: the wait is
    bounded, the epoch degrades, and the abandoned completion is reaped on
    a later epoch instead of leaking."""
    import time

    import wukong_tpu.stream.continuous as cont

    triples, ss, perm = world
    base, live = split(triples, perm, 4000)
    g = build_partition(base, 0, 1)

    class Slow:
        def __init__(self):
            self.inner = CPUEngine(g, ss)

        def execute(self, q):
            time.sleep(0.3)
            return self.inner.execute(q)

    monkeypatch.setattr(cont, "STREAM_WAIT_TIMEOUT_S", 0.01)
    pool = EnginePool(num_engines=1, make_engine=lambda tid: Slow())
    pool.start()
    try:
        ctx = StreamContext([g], ss, pool=pool)
        qid = ctx.register(Q_ONEHOP)
        rec = ctx.feed(live[:500])  # returns despite the slow engine
        assert rec.epoch == 1
        assert ctx.continuous.queries[qid].degraded_epochs == 1
        assert len(ctx.continuous._abandoned) == 1
        time.sleep(0.5)  # let the slow execution finish
        ctx.feed(np.empty((0, 3), dtype=np.int64))  # reaps on next epoch
        assert ctx.continuous._abandoned == []
    finally:
        pool.stop()


def test_stream_lane_completions_skip_poll():
    """poll() (the emulator's open-loop receive side) must never consume
    stream-lane completions — they stay claimable by the stream context's
    wait() even when both share one pool."""
    import time

    class Echo:
        def execute(self, q):
            return q

    pool = EnginePool(num_engines=1, make_engine=lambda tid: Echo())
    pool.start()
    try:
        q = type("Q", (), {"deadline": None})()
        h = pool.submit(q, lane="stream")
        deadline = time.time() + 10
        while not pool._done[h].is_set() and time.time() < deadline:
            time.sleep(0.005)
        drained = pool.poll()
        assert all(qid != h for qid, _ in drained)
        assert pool.wait(h, timeout=10) is q
    finally:
        pool.stop()


def test_proxy_stream_verbs(world):
    triples, ss, perm = world
    base, live = split(triples, perm, len(triples) // 2)
    proxy = Proxy(build_partition(base, 0, 1), ss)
    qid = proxy.stream_register(Q_CONST)
    for _, batch in ReplaySource(live, batch_size=8192):
        proxy.stream_feed(batch)
    deltas = proxy.stream_poll(qid)
    assert all(d.sign == +1 for d in deltas)
    got = proxy.stream_context().result_set(qid)
    assert np.array_equal(got, full_run(triples, ss, Q_CONST))
    # monitor saw every epoch
    stats = proxy.monitor.stream_stats()
    assert stats["epochs"] == proxy.stream_context().epoch
    assert stats["triples"] == len(live)
    assert stats["lag_us_cdf"]  # populated CDF
    proxy.stream_unregister(qid)
    with pytest.raises(WukongError):
        proxy.stream_poll(qid)


def test_monitor_share_observability():
    """The emulator's per-run monitor adopts the proxy monitor's stream
    stats + breakers, so epochs recorded proxy-side are visible to the
    rolling-report printer."""
    shared, private = Monitor(), Monitor()
    private.share_observability(shared)
    shared.record_stream_epoch(n_triples=10, ingest_us=5, eval_us=7,
                               lag_us=12)
    assert private.stream_stats()["epochs"] == 1
    br = CircuitBreaker(threshold=1, cooldown_ms=1000, clock=lambda: 0.0)
    shared.attach_breaker("dist.shard", br)
    br.record_failure(0)
    assert private.breaker_report()  # visible through the adopted registry
    # per-query counters stay private
    shared.add_latency(100)
    assert private.cnt == 0


def test_monitor_breaker_surface():
    clock = [0.0]
    br = CircuitBreaker(threshold=2, cooldown_ms=1000,
                        clock=lambda: clock[0])
    mon = Monitor()
    mon.attach_breaker("dist.shard", br)
    assert mon.breaker_report() == []  # no tracked keys yet
    br.record_success(0)
    br.record_failure(1)
    br.record_failure(1)  # trips shard 1
    clock[0] = 0.5
    s = mon.breaker_summary()["dist.shard"]
    assert (s["closed"], s["open"], s["half_open"]) == (1, 1, 0)
    assert s["last_trip_age_s"] == pytest.approx(0.5)
    [line] = mon.breaker_report()
    assert "1 closed" in line and "1 open" in line and "last trip" in line
    clock[0] = 2.0  # past cooldown: the tripped key is probe-able
    assert mon.breaker_summary()["dist.shard"]["half_open"] == 1


# ---------------------------------------------------------------------------
# timestamped datagen replay (PR 3 satellite: ROADMAP PR 2 follow-up c)
# ---------------------------------------------------------------------------

def test_datagen_timestamped_filesource_epoch_assignment(tmp_path, world):
    """datagen --timestamps emits shuffled 4-col rows; FileSource replay
    must regroup them into per-timestamp epochs in timestamp order."""
    from wukong_tpu.loader.datagen import convert_dir

    src = tmp_path / "nt"
    src.mkdir()
    # TWO source files: datagen writes one id_* file per source file, each
    # spanning the same epoch range — grouping must be global, not per file
    with open(src / "uni0.nt", "w") as f:
        for i in range(40):
            f.write(f"<http://e/s{i}> <http://e/p> <http://e/o{i}> .\n")
    with open(src / "uni1.nt", "w") as f:
        for i in range(40, 64):
            f.write(f"<http://e/s{i}> <http://e/p> <http://e/o{i}> .\n")
    dst = tmp_path / "ids"
    meta = convert_dir(str(src), str(dst), timestamps=5, ts_seed=7)
    assert meta["timestamps"] == 5
    raw = np.concatenate([
        np.loadtxt(dst / "id_uni0.nt", dtype=np.int64, ndmin=2),
        np.loadtxt(dst / "id_uni1.nt", dtype=np.int64, ndmin=2)])
    assert raw.shape[1] == 4  # 4-column s p o ts form
    ts = raw[:, 3]
    assert len(np.unique(ts)) > 1  # several distinct epochs...
    assert not np.all(ts[:-1] <= ts[1:])  # ...arriving OUT of order
    got = list(FileSource(str(dst), batch_size=1000))
    # epoch assignment: one batch per distinct timestamp, sorted by ts,
    # and each batch holds exactly the rows stamped with that ts
    assert [t for t, _ in got] == sorted(np.unique(ts).tolist())
    for t, batch in got:
        expect = raw[ts == int(t)][:, :3]
        assert sorted(map(tuple, batch.tolist())) == \
            sorted(map(tuple, expect.tolist()))
    # and the whole replay commits cleanly as epochs
    ctx = StreamContext([build_partition(np.empty((0, 3), np.int64), 0, 1)],
                        None)
    recs = ctx.feed_source(FileSource(str(dst), batch_size=1000))
    assert [r.ts for r in recs] == [t for t, _ in got]
    assert sum(r.n_triples for r in recs) == len(raw)


# ---------------------------------------------------------------------------
# push-mode sinks (PR 3 satellite: ROADMAP PR 2 follow-up d)
# ---------------------------------------------------------------------------

def test_push_callback_mirrors_poll(world):
    triples, ss, perm = world
    base, live = split(triples, perm, len(triples) - 400)
    batches = [live[i:i + 128] for i in range(0, len(live), 128)]
    got = []
    ctx = StreamContext([build_partition(base, 0, 1)], ss)
    qid = ctx.register(Q_ONEHOP, callback=got.append)
    # the registration snapshot is pushed too (epoch 0 for early registrants)
    assert [d.epoch for d in got] == [d.epoch for d in ctx.poll(qid)]
    for b in batches:
        ctx.feed(b)
    pulled = ctx.poll(qid)
    assert len(got) == len(pulled)
    for cb, pl in zip(got, pulled):
        assert cb.epoch == pl.epoch and cb.sign == pl.sign
        assert np.array_equal(cb.rows, pl.rows)


def test_push_callback_exception_contained(world):
    from wukong_tpu.obs import get_registry

    triples, ss, perm = world
    base, live = split(triples, perm, len(triples) - 400)
    batches = [live[i:i + 128] for i in range(0, len(live), 128)]

    def bad_sink(delta):
        raise RuntimeError("subscriber crashed")

    ctx = StreamContext([build_partition(base, 0, 1)], ss)
    qid = ctx.register(Q_ONEHOP, callback=bad_sink)
    before = get_registry().counter(
        "wukong_stream_callback_errors_total").value()
    for b in batches:
        ctx.feed(b)  # must not raise: callback errors are contained
    sq = ctx.continuous.queries[qid]
    assert sq.callback_errors > 0
    assert get_registry().counter(
        "wukong_stream_callback_errors_total").value() > before
    # the pull surface stayed correct despite the crashing subscriber
    merged = np.concatenate([base] + batches)
    assert np.array_equal(ctx.result_set(qid), full_run(merged, ss, Q_ONEHOP))
    # and a non-callable callback is a structured registration error
    with pytest.raises(WukongError):
        ctx.register(Q_ONEHOP, callback="not-callable")
