"""Adversarial interpret-mode fuzz for the Pallas streaming kernels
(round-4 verdict #7): the edges HARDWARE will hit, pinned as stream ==
merge_expand equality BEFORE the first real-Mosaic run. Families:

- capacity overflow landing mid-tile / exactly at the flush boundary
- runs straddling tile boundaries (deg == TILE, TILE±1, k*TILE+r)
- duplicate-anchor multiplicity exactly mdup (m-hot arm) and mdup+1
  (in-cond XLA fallback) for every supported cap
- edge/key values adjacent to the INT32_MAX pad sentinel
- empty/degenerate segments and frontiers (0 keys, all-zero degrees,
  n == 0, n == C, all-dead live mask, all-miss anchors)

Every case asserts identical (total, out_n) and bag equality of
(val, parent); distinct-anchor and beyond-mdup cases (XLA arm) assert
bitwise equality too. `_emit_kernel_m`'s nblk multi-flush loop is the
subtlest code in the repo — these are its regression armor.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from wukong_tpu.engine.tpu_kernels import INT32_MAX, merge_expand  # noqa: E402
from wukong_tpu.engine.tpu_stream import MDUP, TILE, stream_expand  # noqa: E402


def _segment(keys, degs, edge_fn=None, rng=None):
    """Staged MergeSegment arrays from explicit keys/degrees. edge_fn(i)
    gives the i-th edge value (default: random legal ids)."""
    keys = np.asarray(keys, np.int64)
    degs = np.asarray(degs, np.int64)
    offs = np.concatenate([[0], np.cumsum(degs)])
    ne = int(offs[-1])
    if edge_fn is None:
        rng = rng or np.random.default_rng(0)
        edges = rng.integers(0, 2**31 - 2, size=ne, dtype=np.int64)
    else:
        edges = np.asarray([edge_fn(i) for i in range(ne)], np.int64)
    Kp = 1 << max(int(max(len(keys), 1) - 1).bit_length(), 1)
    Ep = 1 << max(int(max(ne, 1) - 1).bit_length(), 3)
    sk = np.full(Kp, INT32_MAX, np.int32)
    sk[: len(keys)] = keys
    ss = np.zeros(Kp, np.int32)
    ss[: len(keys)] = offs[:-1]
    sd = np.zeros(Kp, np.int32)
    sd[: len(keys)] = degs
    e = np.full(Ep, INT32_MAX, np.int32)
    e[:ne] = edges
    return sk, ss, sd, e


def _frontier(anchors, C, live=None):
    anchors = np.asarray(anchors, np.int64)
    n = len(anchors)
    cur = np.full(C, INT32_MAX, np.int32)
    cur[:n] = anchors
    lv = np.ones(C, bool) if live is None else np.asarray(live, bool)
    return cur, n, lv


def _check(sk, ss, sd, e, cur, n, live, cap, mdup=MDUP, mxu=None,
           expect_bitwise=False):
    a = merge_expand(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
                     jnp.asarray(e), jnp.asarray(cur), jnp.int32(n),
                     jnp.asarray(live), cap_out=cap)
    b = stream_expand(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
                      jnp.asarray(e), jnp.asarray(cur), jnp.int32(n),
                      jnp.asarray(live), cap_out=cap, interpret=True,
                      mdup=mdup, mxu=mxu)
    av, ap, an, at = [np.asarray(x) for x in a]
    bv, bp, bn, bt = [np.asarray(x) for x in b]
    assert int(at) == int(bt), f"totals {int(at)} != {int(bt)}"
    assert int(an) == int(bn), f"out_n {int(an)} != {int(bn)}"
    k = int(an)
    if expect_bitwise:
        assert np.array_equal(av, bv) and np.array_equal(ap, bp)
    elif int(at) <= cap:
        assert (sorted(zip(av[:k].tolist(), ap[:k].tolist()))
                == sorted(zip(bv[:k].tolist(), bp[:k].tolist())))
    # else: duplicate-anchor OVERFLOW — the m-hot arm (edge-repeat order)
    # and the XLA emit (run-repeat order) truncate DIFFERENT prefixes of
    # the same bag; emitted content beyond-capacity is discarded by
    # contract (the host retries at exact capacity), so only the totals
    # comparison above is meaningful
    return int(at), k


# ---------------------------------------------------------------------------
# A. capacity overflow mid-tile / at the flush boundary
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("cap_tiles,deg,extra", [
    (1, 7, 3), (1, TILE - 1, 5), (1, 3 * TILE + 17, 0),
    (2, 13, 9), (2, TILE, 1), (4, TILE // 2 + 1, 2),
    (4, 2 * TILE + 3, 0), (8, 61, 50),
], ids=lambda v: str(v))
def test_overflow_mid_tile(cap_tiles, deg, extra):
    """total > cap with the cutoff landing inside a tile and inside a run:
    totals must agree exactly (the host retry signal) and the first `cap`
    outputs must be the same bag."""
    nkeys = 40
    keys = np.arange(10, 10 + nkeys)
    degs = np.full(nkeys, deg)
    if extra:
        degs[nkeys // 2] += extra  # make the cap boundary land mid-run
    sk, ss, sd, e = _segment(keys, degs)
    cur, n, live = _frontier(keys, C=64)
    cap = cap_tiles * TILE
    total, k = _check(sk, ss, sd, e, cur, n, live, cap,
                      expect_bitwise=True)
    assert total > cap and k == cap  # genuinely overflowed mid-stream


@pytest.mark.parametrize("delta", [-1, 0, 1], ids=["cap-1", "cap", "cap+1"])
def test_total_at_flush_boundary(delta):
    """total exactly at / one off the capacity: the last flush block is
    full, exactly empty, or one element over."""
    cap = 2 * TILE
    want_total = cap + delta
    keys = np.arange(5, 5 + 8)
    degs = np.full(8, want_total // 8)
    degs[-1] += want_total - int(degs.sum())
    sk, ss, sd, e = _segment(keys, degs)
    cur, n, live = _frontier(keys, C=16)
    total, k = _check(sk, ss, sd, e, cur, n, live, cap, expect_bitwise=True)
    assert total == want_total and k == min(cap, want_total)


# ---------------------------------------------------------------------------
# B. runs straddling tile boundaries
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("degs", [
    [TILE, TILE, TILE],                     # runs exactly tile-aligned
    [TILE - 1, 2, TILE - 1, 2],             # every run crosses a boundary
    [1, TILE, 1, TILE, 1],                  # alternation re-misaligns
    [3 * TILE + 17, 5],                     # one run spans >3 tiles
    [TILE // 2] * 7,                        # half-tile phase walk
    [2 * TILE, 1, 2 * TILE - 1],            # mixed large spans
], ids=["aligned", "minus1", "alt", "giant", "half", "mixed"])
def test_runs_straddle_tiles(degs):
    keys = np.arange(100, 100 + len(degs))
    sk, ss, sd, e = _segment(keys, degs)
    cur, n, live = _frontier(keys, C=16)
    cap = 1 << max(int(sum(degs) - 1).bit_length(), 9)
    total, _ = _check(sk, ss, sd, e, cur, n, live, cap, expect_bitwise=True)
    assert total == sum(degs)


@pytest.mark.parametrize("seed", range(6))
def test_straddle_fuzz_partial_live(seed):
    """Random tile-hostile degree mixes with dead rows in the frontier."""
    rng = np.random.default_rng(900 + seed)
    nkeys = int(rng.integers(8, 60))
    degs = rng.choice([1, 2, TILE - 1, TILE, TILE + 1, TILE // 2 + 1],
                      size=nkeys)
    keys = np.sort(rng.choice(50_000, nkeys, replace=False))
    sk, ss, sd, e = _segment(keys, degs, rng=rng)
    live = rng.random(128) > 0.3
    cur, n, _ = _frontier(keys[: min(nkeys, 127)], C=128)
    cap = 1 << max(int(max(int(degs.sum()), 1) - 1).bit_length(), 9)
    _check(sk, ss, sd, e, cur, n, live, cap, expect_bitwise=True)


# ---------------------------------------------------------------------------
# C. multiplicity exactly mdup (m-hot) and mdup+1 (in-cond fallback)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("mdup", [1, 2, 4, 8])
@pytest.mark.parametrize("off", [0, 1], ids=["at-cap", "over-cap"])
def test_multiplicity_at_mdup_boundary(mdup, off):
    """m = mdup streams through the m-hot plane; m = mdup+1 must take the
    XLA arm (bitwise). Both bags must match merge_expand."""
    rng = np.random.default_rng(42 + mdup)
    nkeys = 24
    keys = np.arange(50, 50 + nkeys)
    degs = rng.integers(1, 9, nkeys)
    sk, ss, sd, e = _segment(keys, degs, rng=rng)
    m = mdup + off
    anchors = np.repeat(keys[:10], m)
    rng.shuffle(anchors)
    cur, n, live = _frontier(anchors, C=256)
    total, _ = _check(sk, ss, sd, e, cur, n, live, cap=1 << 11, mdup=mdup,
                      expect_bitwise=(off == 1))
    assert total == int(degs[:10].sum()) * m


@pytest.mark.parametrize("mdup", [2, 4])
@pytest.mark.parametrize("mxu", [False, True], ids=["vpu", "mxu"])
def test_mixed_multiplicities_under_mdup(mdup, mxu):
    """Multiplicities 1..mdup mixed in one frontier, both compaction
    backends, overflow engaged (cap < total) — the m-hot accumulator's
    multi-block flush under pressure."""
    rng = np.random.default_rng(77 * mdup + int(mxu))
    nkeys = 32
    keys = np.arange(1000, 1000 + nkeys)
    degs = rng.integers(1, 2 * TILE // 8, nkeys)
    sk, ss, sd, e = _segment(keys, degs, rng=rng)
    reps = (np.arange(nkeys) % mdup) + 1
    anchors = np.repeat(keys, reps)
    rng.shuffle(anchors)
    cur, n, live = _frontier(anchors[:255], C=256)
    _check(sk, ss, sd, e, cur, n, live, cap=TILE, mdup=mdup, mxu=mxu)


# ---------------------------------------------------------------------------
# D. values adjacent to the INT32_MAX pad sentinel
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("val", [INT32_MAX - 1, INT32_MAX - 2],
                         ids=["max-1", "max-2"])
def test_edge_values_near_sentinel(val):
    """Legal edge values one off the padding sentinel must be emitted, not
    confused with padding."""
    keys = [7, 9]
    sk, ss, sd, e = _segment(keys, [3, 2],
                             edge_fn=lambda i: val - (i % 2))
    cur, n, live = _frontier(keys, C=8)
    total, k = _check(sk, ss, sd, e, cur, n, live, cap=TILE,
                      expect_bitwise=True)
    assert total == 5 and k == 5


def test_key_values_near_sentinel():
    """Segment keys adjacent to INT32_MAX: lookup and run selection must
    not treat them as pad keys."""
    keys = [INT32_MAX - 3, INT32_MAX - 2]
    sk, ss, sd, e = _segment(keys, [4, 3])
    cur, n, live = _frontier([INT32_MAX - 2, INT32_MAX - 3, 5], C=8)
    total, _ = _check(sk, ss, sd, e, cur, n, live, cap=TILE,
                      expect_bitwise=True)
    assert total == 7


def test_anchor_values_near_sentinel_miss():
    """Anchors near the sentinel that MISS the segment must emit nothing
    (no accidental pad-row match)."""
    sk, ss, sd, e = _segment([10, 20], [2, 2])
    cur, n, live = _frontier([INT32_MAX - 1, INT32_MAX - 2], C=8)
    total, k = _check(sk, ss, sd, e, cur, n, live, cap=TILE,
                      expect_bitwise=True)
    assert total == 0 and k == 0


# ---------------------------------------------------------------------------
# E. empty / degenerate segments and frontiers
# ---------------------------------------------------------------------------
def test_zero_key_segment():
    sk, ss, sd, e = _segment([], [])
    cur, n, live = _frontier([1, 2, 3], C=8)
    total, k = _check(sk, ss, sd, e, cur, n, live, cap=TILE,
                      expect_bitwise=True)
    assert total == 0 and k == 0


def test_all_zero_degrees():
    sk, ss, sd, e = _segment([5, 6, 7], [0, 0, 0])
    cur, n, live = _frontier([5, 6, 7], C=8)
    total, k = _check(sk, ss, sd, e, cur, n, live, cap=TILE,
                      expect_bitwise=True)
    assert total == 0 and k == 0


def test_zero_frontier_nonempty_segment():
    sk, ss, sd, e = _segment([5, 6], [3, 3])
    cur, n, live = _frontier([], C=8)
    total, k = _check(sk, ss, sd, e, cur, n, live, cap=TILE,
                      expect_bitwise=True)
    assert total == 0 and k == 0


def test_all_dead_live_mask():
    sk, ss, sd, e = _segment([5, 6], [3, 3])
    cur, n, live = _frontier([5, 6], C=8, live=np.zeros(8, bool))
    total, k = _check(sk, ss, sd, e, cur, n, live, cap=TILE,
                      expect_bitwise=True)
    assert total == 0 and k == 0


def test_full_frontier_no_pad_rows():
    """n == C: no padding rows at all in the frontier."""
    rng = np.random.default_rng(5)
    keys = np.arange(100, 164)
    sk, ss, sd, e = _segment(keys, rng.integers(1, 6, 64), rng=rng)
    cur, n, live = _frontier(keys, C=64)
    assert n == 64
    _check(sk, ss, sd, e, cur, n, live, cap=1 << 9, expect_bitwise=True)


def test_single_row_single_edge():
    sk, ss, sd, e = _segment([5], [1], edge_fn=lambda i: 42)
    cur, n, live = _frontier([5], C=8)
    total, k = _check(sk, ss, sd, e, cur, n, live, cap=TILE,
                      expect_bitwise=True)
    assert total == 1 and k == 1


# ---------------------------------------------------------------------------
# F. randomized adversarial mixes (everything at once)
# ---------------------------------------------------------------------------
def _mix_case(seed: int):
    """One randomized adversarial mix: tile-hostile degrees,
    sentinel-adjacent values, duplicate anchors at random multiplicity,
    partial live, caps at/below total, random mdup, both backends. Shared
    by the fuzzer and the seed-pinned regression tests so a pinned seed
    keeps reproducing ITS scenario even if either test's assertions
    change (the draw sequence lives here and only here)."""
    rng = np.random.default_rng(7000 + seed)
    nkeys = int(rng.integers(4, 80))
    degs = rng.choice([0, 1, 2, TILE - 1, TILE, TILE + 1, 37], size=nkeys,
                      p=[.1, .2, .2, .1, .1, .1, .2])
    keys = np.sort(rng.choice(
        np.concatenate([np.arange(1, 60_000),
                        np.array([INT32_MAX - 2, INT32_MAX - 3])]),
        nkeys, replace=False))
    big = rng.integers(0, 2**31 - 2, size=max(int(degs.sum()), 1),
                       dtype=np.int64)
    big[rng.integers(0, len(big), size=max(len(big) // 10, 1))] = \
        INT32_MAX - 1
    sk, ss, sd, e = _segment(keys, degs, edge_fn=lambda i: int(big[i]))
    mdup = int(rng.choice([1, 2, 4, 8]))
    m = int(rng.integers(1, mdup + 2))
    npick = int(rng.integers(1, max(nkeys // 2, 2)))
    picks = rng.choice(keys, size=npick, replace=False)
    anchors = np.repeat(picks, m)[:255]
    # sprinkle misses (incl. sentinel-adjacent)
    miss = rng.choice([123_456_789, INT32_MAX - 4], size=min(10, 255), )
    anchors = np.concatenate([anchors, miss])[:255]
    rng.shuffle(anchors)
    C = 256
    live = rng.random(C) > rng.random() * 0.5
    cur, n, _ = _frontier(anchors, C=C)
    cap = int(rng.choice([TILE, 2 * TILE, 1 << 12]))
    mxu = bool(rng.integers(0, 2))
    return dict(keys=keys, degs=degs, sk=sk, ss=ss, sd=sd, e=e, cur=cur,
                n=n, live=live, cap=cap, mdup=mdup, m=m, mxu=mxu)


def _expect_bitwise(keys, degs, cur, n, live, mdup) -> bool:
    """Mirror stream_expand's arm dispatch EXACTLY (tpu_stream.py):

    - `dup` fires on any duplicate LIVE FOUND anchor — key present in the
      segment, degree irrelevant (the kernel's adjacency test runs before
      deg filtering);
    - with duplicates, the m-hot arm runs when `mmax` — the max per-key
      multiplicity over LIVE, MATCHED, deg>0 anchors — is <= mdup.

    Bitwise equality with merge_expand is only promised on the
    distinct-anchor stream arm (no live found duplicate) and on the XLA
    fallback (mmax > mdup); the m-hot arm is bag-order (edge-repeat).
    Live-masking can trim a constructed m > mdup frontier back into m-hot
    range — found by the round-5 fresh-seed soak at seed 7218."""
    deg_of = dict(zip(keys.tolist(), np.asarray(degs).tolist()))
    found_cnt: dict = {}  # live anchors on keys PRESENT in the segment
    run_cnt: dict = {}  # live anchors on keys with deg > 0
    for i in range(int(n)):
        if live[i]:
            a = int(cur[i])
            if a in deg_of:
                found_cnt[a] = found_cnt.get(a, 0) + 1
                if deg_of[a] > 0:
                    run_cnt[a] = run_cnt.get(a, 0) + 1
    dup = max(found_cnt.values(), default=0) >= 2
    mmax = max(run_cnt.values(), default=0)
    return (not dup) or mmax > mdup


@pytest.mark.parametrize("seed", range(10))
def test_adversarial_mix_fuzz(seed):
    """Randomized adversarial mixes (everything at once); the bitwise-vs-
    bag expectation mirrors the kernel's actual arm dispatch."""
    c = _mix_case(seed)
    _check(c["sk"], c["ss"], c["sd"], c["e"], c["cur"], c["n"], c["live"],
           c["cap"], mdup=c["mdup"], mxu=c["mxu"],
           expect_bitwise=_expect_bitwise(
               c["keys"], c["degs"], c["cur"], c["n"], c["live"],
               c["mdup"]))


def test_live_masked_multiplicity_takes_mhot_arm():
    """Soak regression (seed 7218): anchors constructed at multiplicity 3
    with mdup=2, but live-masking leaves max TWO live copies per key — the
    kernel takes the m-hot arm (bag semantics), and the old assumption
    that constructed m > mdup implies the bitwise XLA fallback is wrong.
    Overflow additionally makes the two arms truncate different prefixes,
    which only the totals contract covers."""
    c = _mix_case(218)
    assert c["m"] > c["mdup"]  # the trap: constructed mult says fallback..
    bw = _expect_bitwise(c["keys"], c["degs"], c["cur"], c["n"], c["live"],
                         c["mdup"])
    assert not bw  # ...but the effective live multiplicity says m-hot
    total, k = _check(c["sk"], c["ss"], c["sd"], c["e"], c["cur"], c["n"],
                      c["live"], c["cap"], mdup=c["mdup"], mxu=c["mxu"],
                      expect_bitwise=bw)
    assert total > c["cap"]  # the overflow half of the scenario is real
