"""tpu_stream.stream_expand vs tpu_kernels.merge_expand (interpret mode).

The streaming emitter must be a bit-identical drop-in for the XLA merge
emit: same (val, parent, out_n, total) for distinct-anchor frontiers, same
via its lax.cond fallback when anchors repeat. Segments are random CSRs
shaped like the staged MergeSegment arrays (pow2-padded, INT32_MAX pads).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from wukong_tpu.engine.tpu_kernels import INT32_MAX, merge_expand  # noqa: E402
from wukong_tpu.engine.tpu_stream import TILE, stream_expand  # noqa: E402


def _mk_segment(rng, nkeys, max_deg):
    """Random CSR segment in staged MergeSegment form (pow2 pads)."""
    keys = np.sort(rng.choice(200_000, size=nkeys, replace=False)).astype(
        np.int32)
    degs = rng.integers(0, max_deg + 1, size=nkeys)
    offs = np.concatenate([[0], np.cumsum(degs)]).astype(np.int64)
    edges = rng.integers(0, 2**31 - 1, size=int(offs[-1]), dtype=np.int64)
    Kp = 1 << max(int(nkeys - 1).bit_length(), 1)
    Ep = 1 << max(int(len(edges) - 1).bit_length(), 8)
    sk = np.full(Kp, INT32_MAX, np.int32)
    sk[:nkeys] = keys
    ss = np.zeros(Kp, np.int32)
    ss[:nkeys] = offs[:-1]
    sd = np.zeros(Kp, np.int32)
    sd[:nkeys] = degs
    e = np.full(Ep, INT32_MAX, np.int32)
    e[:len(edges)] = edges
    return sk, ss, sd, e, keys, offs


def _run_both(sk, ss, sd, e, cur, n, live, cap):
    a = merge_expand(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
                     jnp.asarray(e), jnp.asarray(cur), jnp.int32(n),
                     jnp.asarray(live), cap_out=cap)
    b = stream_expand(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
                      jnp.asarray(e), jnp.asarray(cur), jnp.int32(n),
                      jnp.asarray(live), cap_out=cap, interpret=True)
    return [np.asarray(x) for x in a], [np.asarray(x) for x in b]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_stream_matches_merge_distinct_anchors(seed):
    rng = np.random.default_rng(seed)
    sk, ss, sd, e, keys, offs = _mk_segment(rng, nkeys=300, max_deg=9)
    C = 512
    # distinct anchors: a sample of keys + some misses, no repeats
    pool = np.concatenate([keys, np.setdiff1d(
        rng.choice(200_000, 400, replace=False), keys)])
    cur = np.full(C, INT32_MAX, np.int32)
    n = 300
    cur[:n] = rng.choice(pool, size=n, replace=False)
    live = np.ones(C, bool)
    live[rng.integers(0, n, 20)] = False  # folded-filter mask
    (av, ap, an, at), (bv, bp, bn, bt) = _run_both(
        sk, ss, sd, e, cur, n, live, cap=1 << 12)
    assert int(at) == int(bt) and int(an) == int(bn)
    assert np.array_equal(av, bv)
    assert np.array_equal(ap, bp)
    assert int(at) > 0  # the case actually expanded something


def _multiset(v, p, n):
    return sorted(zip(v[:n].tolist(), p[:n].tolist()))


def test_stream_duplicate_anchors_mhot():
    """Multiplicity <= MDUP streams through the m-hot arm: same (val,
    parent) BAG as the XLA emit (edge-repeat vs run-repeat order)."""
    rng = np.random.default_rng(7)
    sk, ss, sd, e, keys, offs = _mk_segment(rng, nkeys=64, max_deg=5)
    C = 256
    picks = rng.choice(keys, size=30, replace=False)
    reps = rng.integers(1, 5, size=30)  # multiplicities 1..4
    anchors = np.repeat(picks, reps)
    n = len(anchors)
    cur = np.full(C, INT32_MAX, np.int32)
    cur[:n] = anchors
    live = np.ones(C, bool)
    (av, ap, an, at), (bv, bp, bn, bt) = _run_both(
        sk, ss, sd, e, cur, n, live, cap=1 << 12)
    assert int(at) == int(bt) and int(an) == int(bn)
    assert int(at) > 0
    assert _multiset(av, ap, an) == _multiset(bv, bp, bn)


def test_stream_duplicate_anchors_mhot_off_bitwise():
    """mhot=False restores the XLA fallback: bit-identical on duplicates."""
    from wukong_tpu.engine.tpu_stream import stream_expand as se

    rng = np.random.default_rng(7)
    sk, ss, sd, e, keys, offs = _mk_segment(rng, nkeys=64, max_deg=5)
    C = 256
    cur = np.full(C, INT32_MAX, np.int32)
    n = 100
    cur[:n] = rng.choice(keys, size=n, replace=True)  # repeats guaranteed
    cur[1] = cur[0]
    live = np.ones(C, bool)
    a = merge_expand(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
                     jnp.asarray(e), jnp.asarray(cur), jnp.int32(n),
                     jnp.asarray(live), cap_out=1 << 12)
    b = se(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
           jnp.asarray(e), jnp.asarray(cur), jnp.int32(n),
           jnp.asarray(live), cap_out=1 << 12, interpret=True, mhot=False)
    for x, y in zip(a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y))


def test_stream_high_multiplicity_falls_back_bitwise():
    """Multiplicity > MDUP takes the XLA arm: bit-identical again."""
    from wukong_tpu.engine.tpu_stream import MDUP

    rng = np.random.default_rng(9)
    sk, ss, sd, e, keys, offs = _mk_segment(rng, nkeys=64, max_deg=5)
    C = 256
    cur = np.full(C, INT32_MAX, np.int32)
    hot = keys[np.argmax(sd[:64])]
    n = MDUP + 8
    cur[:n] = hot  # one key far beyond the m-hot cap
    live = np.ones(C, bool)
    (av, ap, an, at), (bv, bp, bn, bt) = _run_both(
        sk, ss, sd, e, cur, n, live, cap=1 << 12)
    assert int(at) == int(bt) and int(an) == int(bn)
    assert np.array_equal(av, bv)
    assert np.array_equal(ap, bp)


@pytest.mark.parametrize("seed", range(6))
def test_stream_mhot_fuzz(seed):
    """Randomized duplicate-anchor frontiers (mixed multiplicities 1..MDUP,
    hub degrees, partial live masks, both compaction backends): the m-hot
    bag must equal the XLA emit's bag, totals identical."""
    rng = np.random.default_rng(500 + seed)
    nkeys = int(rng.integers(16, 400))
    max_deg = int(rng.integers(1, 20))
    sk, ss, sd, e, keys, offs = _mk_segment(rng, nkeys=nkeys, max_deg=max_deg)
    C = int(rng.choice([256, 1024]))
    npick = int(rng.integers(1, min(C // 4, nkeys) + 1))
    picks = rng.choice(keys, size=npick, replace=False)
    reps = rng.integers(1, 5, size=npick)
    anchors = np.repeat(picks, reps)[: C - 1]
    rng.shuffle(anchors)  # duplicates need not be row-adjacent
    n = len(anchors)
    cur = np.full(C, INT32_MAX, np.int32)
    cur[:n] = anchors
    live = rng.random(C) > rng.random() * 0.4
    mxu = bool(rng.integers(0, 2))
    a = merge_expand(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
                     jnp.asarray(e), jnp.asarray(cur), jnp.int32(n),
                     jnp.asarray(live), cap_out=1 << 13)
    b = stream_expand(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
                      jnp.asarray(e), jnp.asarray(cur), jnp.int32(n),
                      jnp.asarray(live), cap_out=1 << 13, interpret=True,
                      mxu=mxu)
    av, ap, an, at = [np.asarray(x) for x in a]
    bv, bp, bn, bt = [np.asarray(x) for x in b]
    assert int(at) == int(bt) and int(an) == int(bn)
    assert _multiset(av, ap, int(an)) == _multiset(bv, bp, int(bn))


def test_stream_empty_and_all_miss():
    rng = np.random.default_rng(3)
    sk, ss, sd, e, keys, offs = _mk_segment(rng, nkeys=50, max_deg=4)
    C = 256
    cur = np.full(C, INT32_MAX, np.int32)
    live = np.ones(C, bool)
    # n = 0
    (_, _, an, at), (_, _, bn, bt) = _run_both(
        sk, ss, sd, e, cur, 0, live, cap=1 << 10)
    assert int(at) == 0 and int(bt) == 0 and int(bn) == 0
    # all misses
    cur[:40] = np.arange(40, dtype=np.int32) + 500_000
    (_, _, an, at), (_, _, bn, bt) = _run_both(
        sk, ss, sd, e, cur, 40, live, cap=1 << 10)
    assert int(at) == 0 and int(bt) == 0


def test_stream_overflow_totals_agree():
    """total > cap_out must be reported identically (the host retry
    signal); emitted values beyond capacity are unused by contract."""
    rng = np.random.default_rng(11)
    sk, ss, sd, e, keys, offs = _mk_segment(rng, nkeys=128, max_deg=40)
    C = 256
    cur = np.full(C, INT32_MAX, np.int32)
    cur[:128] = keys
    live = np.ones(C, bool)
    cap = TILE  # tiny capacity to force overflow
    (_, _, an, at), (_, _, bn, bt) = _run_both(
        sk, ss, sd, e, cur, 128, live, cap=cap)
    assert int(at) == int(bt)
    assert int(at) > cap
    assert int(an) == int(bn) == cap


def test_stream_tiny_segment_single_tile():
    """E < TILE pads up to one tile."""
    sk = np.asarray([5, 9, INT32_MAX, INT32_MAX], np.int32)
    ss = np.asarray([0, 3, 0, 0], np.int32)
    sd = np.asarray([3, 2, 0, 0], np.int32)
    e = np.full(8, INT32_MAX, np.int32)
    e[:5] = [10, 11, 12, 20, 21]
    cur = np.full(8, INT32_MAX, np.int32)
    cur[:2] = [9, 5]
    live = np.ones(8, bool)
    (av, ap, an, at), (bv, bp, bn, bt) = _run_both(
        sk, ss, sd, e, cur, 2, live, cap=1 << 10)
    assert int(bt) == 5 and int(bn) == 5
    assert np.array_equal(av, bv) and np.array_equal(ap, bp)
    # key-sorted emission: key 5's run (parent row 1) precedes key 9's
    assert bv[:5].tolist() == [10, 11, 12, 20, 21]
    assert bp[:5].tolist() == [1, 1, 1, 0, 0]


def test_stream_multi_tile_carries():
    """Runs spanning tile boundaries + many tiles exercise the SMEM
    carries and the accumulator flush path."""
    rng = np.random.default_rng(13)
    nkeys = 500
    keys = np.sort(rng.choice(100_000, nkeys, replace=False)).astype(np.int32)
    degs = rng.integers(1, 8, nkeys)
    # one huge run spanning several tiles
    degs[100] = 3 * TILE + 17
    offs = np.concatenate([[0], np.cumsum(degs)])
    E = int(offs[-1])
    edges = rng.integers(0, 2**31 - 1, E, dtype=np.int64).astype(np.int32)
    Kp = 512
    Ep = 1 << int(E - 1).bit_length()
    sk = np.full(Kp, INT32_MAX, np.int32)
    sk[:nkeys] = keys
    ss = np.zeros(Kp, np.int32)
    ss[:nkeys] = offs[:-1]
    sd = np.zeros(Kp, np.int32)
    sd[:nkeys] = degs
    e = np.full(Ep, INT32_MAX, np.int32)
    e[:E] = edges
    C = 1024
    cur = np.full(C, INT32_MAX, np.int32)
    n = 400
    cur[:n] = rng.choice(keys, size=n, replace=False)
    live = np.ones(C, bool)
    (av, ap, an, at), (bv, bp, bn, bt) = _run_both(
        sk, ss, sd, e, cur, n, live, cap=1 << 13)
    assert int(at) == int(bt) and int(an) == int(bn)
    assert np.array_equal(av, bv)
    assert np.array_equal(ap, bp)


def test_mxu_and_vpu_compaction_agree():
    """Both compaction backends (MXU matmul on 16-bit halves vs VPU masked
    reductions) must emit identical results."""
    rng = np.random.default_rng(21)
    sk, ss, sd, e, keys, offs = _mk_segment(rng, nkeys=200, max_deg=7)
    C = 512
    cur = np.full(C, INT32_MAX, np.int32)
    n = 180
    cur[:n] = rng.choice(keys, size=n, replace=False)
    live = np.ones(C, bool)
    args = [jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
            jnp.asarray(e), jnp.asarray(cur), jnp.int32(n),
            jnp.asarray(live)]
    a = stream_expand(*args, cap_out=1 << 12, interpret=True, mxu=True)
    b = stream_expand(*args, cap_out=1 << 12, interpret=True, mxu=False)
    for x, y in zip(a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y))
    assert int(a[3]) > 0


@pytest.mark.parametrize("seed", range(8))
def test_stream_fuzz_random_shapes(seed):
    """Randomized shapes: segment sizes, degree skew (hub runs), frontier
    density, live masks, both compaction backends — all must match the XLA
    emit exactly."""
    rng = np.random.default_rng(100 + seed)
    nkeys = int(rng.integers(8, 600))
    max_deg = int(rng.integers(1, 30))
    sk, ss, sd, e, keys, offs = _mk_segment(rng, nkeys=nkeys, max_deg=max_deg)
    C = int(rng.choice([64, 256, 1024]))
    n = int(rng.integers(0, min(C, nkeys) + 1))
    cur = np.full(C, INT32_MAX, np.int32)
    if n:
        cur[:n] = rng.choice(keys, size=n, replace=False)
    live = rng.random(C) > rng.random() * 0.5
    mxu = bool(rng.integers(0, 2))
    a = merge_expand(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
                     jnp.asarray(e), jnp.asarray(cur), jnp.int32(n),
                     jnp.asarray(live), cap_out=1 << 13)
    b = stream_expand(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
                      jnp.asarray(e), jnp.asarray(cur), jnp.int32(n),
                      jnp.asarray(live), cap_out=1 << 13, interpret=True,
                      mxu=mxu)
    assert int(a[3]) == int(b[3]) and int(a[2]) == int(b[2])
    assert np.array_equal(np.asarray(a[0]), np.asarray(b[0]))
    assert np.array_equal(np.asarray(a[1]), np.asarray(b[1]))


@pytest.mark.parametrize("mdup", [2, 8])
def test_stream_mdup_override(mdup):
    """Non-default multiplicity caps: the mdup-scaled accumulator/flush
    logic must stay bag-equal to the XLA emit for multiplicities within
    the cap, and beyond-cap frontiers must still fall back bit-identical."""
    rng = np.random.default_rng(21)
    sk, ss, sd, e, keys, offs = _mk_segment(rng, nkeys=80, max_deg=6)
    C = 512
    picks = rng.choice(keys, size=40, replace=False)
    anchors = np.repeat(picks, mdup)  # multiplicity exactly at the cap
    n = len(anchors)
    cur = np.full(C, INT32_MAX, np.int32)
    cur[:n] = anchors
    live = np.ones(C, bool)
    a = merge_expand(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
                     jnp.asarray(e), jnp.asarray(cur), jnp.int32(n),
                     jnp.asarray(live), cap_out=1 << 13)
    b = stream_expand(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
                      jnp.asarray(e), jnp.asarray(cur), jnp.int32(n),
                      jnp.asarray(live), cap_out=1 << 13, interpret=True,
                      mdup=mdup)
    av, ap, an, at = [np.asarray(x) for x in a]
    bv, bp, bn, bt = [np.asarray(x) for x in b]
    assert int(at) == int(bt) and int(an) == int(bn) and int(at) > 0
    assert _multiset(av, ap, int(an)) == _multiset(bv, bp, int(bn))
    # one past the cap: the XLA arm takes over, bit-identical
    anchors2 = np.repeat(picks[:30], mdup + 1)
    n2 = len(anchors2)
    cur2 = np.full(C, INT32_MAX, np.int32)
    cur2[:n2] = anchors2
    a = merge_expand(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
                     jnp.asarray(e), jnp.asarray(cur2), jnp.int32(n2),
                     jnp.asarray(live), cap_out=1 << 13)
    b = stream_expand(jnp.asarray(sk), jnp.asarray(ss), jnp.asarray(sd),
                      jnp.asarray(e), jnp.asarray(cur2), jnp.int32(n2),
                      jnp.asarray(live), cap_out=1 << 13, interpret=True,
                      mdup=mdup)
    for x, y in zip(a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y))


def test_stream_mdup_env(monkeypatch):
    from wukong_tpu.engine.tpu_stream import MDUP, stream_mdup

    monkeypatch.delenv("WUKONG_STREAM_MDUP", raising=False)
    assert stream_mdup() == MDUP
    monkeypatch.setenv("WUKONG_STREAM_MDUP", "8")
    assert stream_mdup() == 8
    monkeypatch.setenv("WUKONG_STREAM_MDUP", "bogus")
    assert stream_mdup() == MDUP
    monkeypatch.setenv("WUKONG_STREAM_MDUP", "99")
    assert stream_mdup() == 16  # clamped
