"""Whole-plan compiled template execution (ISSUE 19 acceptance).

The acceptance bar: the fused XLA program returns BYTE-IDENTICAL result
rows — including row order — to the host walk across chain, const-start,
index-start, filter (known-known / known-const / const-known) and
projection shapes plus six cyclic cases; a compile-time or mid-flight
dispatch fault degrades the SAME query to the walk (SUCCESS, identical
bytes, fallback counted, per-template demotion latched); a dynamic
insert makes stale programs unreachable and re-arms the latch; the
program cache evicts under ``template_budget_mb``; and the stream-epoch
/ view-maintenance device frontier is byte-identical to the host
oracle. The serve-path drills run fully lockdep-checked.
"""

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.engine.template_compile import (
    TEMPLATE_ROUTES,
    TemplateCompiledEngine,
    choose_template_route,
    demotion_report,
    extract_template,
    is_demoted,
    latch_demotion,
    reset_demotions,
)
from wukong_tpu.loader.datagen import (
    CyclicStrings,
    cyclic_query_text,
    generate_clique4,
    generate_diamond,
    generate_triangle,
)
from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.planner.optimizer import Planner
from wukong_tpu.planner.stats import Stats
from wukong_tpu.runtime import faults
from wukong_tpu.runtime.faults import FaultPlan, FaultSpec
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.types import IN, OUT, PREDICATE_ID
from wukong_tpu.utils.errors import ErrorCode

pytestmark = pytest.mark.template

WORLDS = {
    "triangle": lambda: generate_triangle(m=60, noise=3, seed=1),
    "diamond": lambda: generate_diamond(m=40, noise=2, seed=1),
    "clique4": lambda: generate_clique4(n=120, fan=6, ncliques=8, seed=1),
}


@pytest.fixture(scope="module", params=sorted(WORLDS))
def world(request):
    triples, meta = WORLDS[request.param]()
    g = build_partition(triples, 0, 1)
    stats = Stats.generate(triples)
    return request.param, triples, g, stats, meta


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Every test starts with no fault plan, no demotion latches, a
    clean observatory, and the template knobs at their defaults
    (monkeypatch rolls any per-test knob override back)."""
    from wukong_tpu.obs.device import get_device_obs

    faults.clear()
    reset_demotions()
    get_device_obs().reset()
    monkeypatch.setattr(Global, "template_device", "auto")
    monkeypatch.setattr(Global, "template_min_rows", 4096)
    monkeypatch.setattr(Global, "template_capacity_retries", 3)
    monkeypatch.setattr(Global, "template_budget_mb", 256)
    monkeypatch.setattr(Global, "template_demote_eff", 0.02)
    monkeypatch.setattr(Global, "join_strategy", "auto")
    monkeypatch.setattr(Global, "join_device_min_candidates", 65536)
    yield
    faults.clear()
    reset_demotions()
    get_device_obs().reset()


def mkq(meta, blind=False) -> SPARQLQuery:
    q = SPARQLQuery()
    q.pattern_group.patterns = [Pattern(s, p, OUT, o)
                                for (s, p, o) in meta["patterns"]]
    q.result.nvars = len(meta["vars"])
    q.result.required_vars = list(meta["vars"])
    q.result.blind = blind
    return q


def handq(pats, vars_, blind=False) -> SPARQLQuery:
    """A query with an explicit pattern order (no planner reordering):
    the shape-matrix tests pin each fused op kind this way."""
    q = SPARQLQuery()
    q.pattern_group.patterns = [Pattern(s, p, d, o) for (s, p, d, o) in pats]
    q.result.nvars = len(vars_)
    q.result.required_vars = list(vars_)
    q.result.blind = blind
    return q


def assert_identical(qh: SPARQLQuery, qc: SPARQLQuery) -> None:
    """Byte identity INCLUDING row order — the compiled path's contract
    is the host walk's exact reply, not a row-set match."""
    assert qh.result.status_code == qc.result.status_code
    assert qh.result.nrows == qc.result.nrows
    assert qh.result.col_num == qc.result.col_num
    assert qh.result.v2c_map == qc.result.v2c_map
    th = np.asarray(qh.result.table)
    tc = np.asarray(qc.result.table)
    assert th.dtype == tc.dtype
    assert th.shape == tc.shape
    assert np.array_equal(th, tc)


def run_pair(g, build, plan=False):
    """(host walk, compiled) executions of the same query builder."""
    qh = build()
    if plan:
        heuristic_plan(qh)
    CPUEngine(g).execute(qh)
    qc = build()
    if plan:
        heuristic_plan(qc)
    served = TemplateCompiledEngine(g).try_execute(qc)
    return qh, qc, served


# ---------------------------------------------------------------------------
# byte identity: six cyclic cases (three worlds x projected/blind)
# ---------------------------------------------------------------------------

def test_compiled_matches_walk_cyclic(world):
    name, _triples, g, _stats, meta = world
    qh, qc, served = run_pair(g, lambda: mkq(meta), plan=True)
    assert served, name
    assert qc._template_compiled
    assert_identical(qh, qc)


def test_compiled_matches_walk_cyclic_blind(world):
    """Blind replies take the unfused path: the full table plus the
    host engine's ``_final_process`` replayed verbatim."""
    name, _triples, g, _stats, meta = world
    qh, qc, served = run_pair(g, lambda: mkq(meta, blind=True), plan=True)
    assert served, name
    assert qh.result.status_code == qc.result.status_code
    assert qh.result.nrows == qc.result.nrows, name


# ---------------------------------------------------------------------------
# byte identity: the fused-op shape matrix (hand-ordered plans)
# ---------------------------------------------------------------------------

def _tri_world():
    triples, meta = generate_triangle(m=60, noise=3, seed=1)
    return triples, build_partition(triples, 0, 1), meta


def test_const_start_chain_identity():
    triples, g, _meta = _tri_world()
    a = int(triples[triples[:, 1] == 2][0, 0])
    qh, qc, served = run_pair(
        g, lambda: handq([(a, 2, OUT, -1), (-1, 3, OUT, -2)], [-1, -2]))
    assert served
    spec = extract_template(handq([(a, 2, OUT, -1), (-1, 3, OUT, -2)],
                                  [-1, -2]))
    assert [op[0] for op in spec[0]] == ["const_list", "expand"]
    assert_identical(qh, qc)


def test_index_start_chain_identity():
    _triples, g, _meta = _tri_world()
    pats = [(2, PREDICATE_ID, IN, -1), (-1, 2, OUT, -2), (-2, 3, OUT, -3)]
    qh, qc, served = run_pair(g, lambda: handq(pats, [-1, -2, -3]))
    assert served
    spec = extract_template(handq(pats, [-1, -2, -3]))
    assert [op[0] for op in spec[0]] == ["index", "expand", "expand"]
    assert_identical(qh, qc)


def test_filter_pair_const_identity():
    triples, g, _meta = _tri_world()
    c = int(triples[triples[:, 1] == 4][0, 2])
    pats = [(2, PREDICATE_ID, IN, -1), (-1, 2, OUT, -2), (-1, 4, OUT, c)]
    qh, qc, served = run_pair(g, lambda: handq(pats, [-1, -2]))
    assert served
    spec = extract_template(handq(pats, [-1, -2]))
    assert [op[0] for op in spec[0]] == ["index", "expand",
                                         "filter_pair_const"]
    assert qh.result.nrows > 0  # a vacuous filter proves nothing
    assert_identical(qh, qc)


def test_filter_member_identity():
    triples, g, _meta = _tri_world()
    a = int(triples[triples[:, 1] == 2][0, 0])
    pats = [(3, PREDICATE_ID, IN, -1), (a, 2, OUT, -1)]
    qh, qc, served = run_pair(g, lambda: handq(pats, [-1]))
    assert served
    spec = extract_template(handq(pats, [-1]))
    assert [op[0] for op in spec[0]] == ["index", "filter_member"]
    assert qh.result.nrows > 0
    assert_identical(qh, qc)


def test_projection_subset_fused_identity():
    """A strict-subset projection fuses on device (only the projected
    columns come back) and still matches the walk's reply bytes."""
    _triples, g, _meta = _tri_world()
    pats = [(2, PREDICATE_ID, IN, -1), (-1, 2, OUT, -2), (-2, 3, OUT, -3)]
    qh, qc, served = run_pair(g, lambda: handq(pats, [-3]))
    assert served
    spec = extract_template(handq(pats, [-3]))
    assert spec[2] == (2,)  # proj fused to the one required column
    assert qc.result.col_num == 1
    assert_identical(qh, qc)


def test_distinct_replays_host_final_process():
    """DISTINCT keeps the full fused table and replays the host
    ``_final_process`` verbatim — reply bytes identical to the walk."""
    _triples, g, _meta = _tri_world()
    pats = [(2, PREDICATE_ID, IN, -1), (-1, 2, OUT, -2)]

    def build():
        q = handq(pats, [-2])
        q.distinct = True
        return q

    qh, qc, served = run_pair(g, build)
    assert served
    assert extract_template(build())[2] is None  # proj NOT fused
    assert_identical(qh, qc)


def test_unsupported_shapes_leave_query_untouched():
    """FILTER / OPTIONAL / deadline shapes are refused (False) with the
    query untouched — the walk owns them, nothing is latched."""
    _triples, g, _meta = _tri_world()
    eng = TemplateCompiledEngine(g)

    q = handq([(2, PREDICATE_ID, IN, -1), (-1, 2, OUT, -2)], [-1, -2])
    q.pattern_group.filters = [object()]
    assert not eng.try_execute(q)
    assert q.pattern_step == 0 and q.result.table.size == 0

    q2 = handq([(2, PREDICATE_ID, IN, -1), (-1, 2, OUT, -2)], [-1, -2])
    q2.mt_factor = 4
    assert not eng.try_execute(q2)
    assert demotion_report() == {}  # refusal is not a failure


# ---------------------------------------------------------------------------
# capacity classes: retry growth + overflow ceiling
# ---------------------------------------------------------------------------

def test_capacity_retry_regrows_and_matches(monkeypatch):
    """Deliberately undersized capacity classes overflow, regrow
    (``_grow_caps``) and converge to the identical reply — the good
    classes are memoized so the next query dispatches once."""
    from wukong_tpu.obs.device import get_device_obs, read_device_input

    monkeypatch.setattr(Global, "enable_device_obs", True)
    get_device_obs().reset()
    _triples, g, meta = _tri_world()

    def build():
        q = mkq(meta)
        heuristic_plan(q)
        return q

    spec, _v2c, _proj, _width = extract_template(build())
    eng = TemplateCompiledEngine(g)
    version = eng._version()
    eng._good_caps[(spec, version)] = (128, 64, 64)  # far too small
    qc = build()
    assert eng.try_execute(qc)
    qh = build()
    CPUEngine(g).execute(qh)
    assert_identical(qh, qc)
    counts = read_device_input("dispatches", "template.plan")
    assert int(counts["count"]) >= 2  # at least one overflow retry
    assert eng._good_caps[(spec, version)] != (128, 64, 64)


def test_overflow_past_ceiling_degrades_on_serve_path():
    """When the capacity ceiling makes the template untenable the serve
    path degrades to the walk — SUCCESS, identical bytes, fallback
    counted, per-template demotion latched."""
    proxy, text = _mk_tri_proxy()
    Global.join_strategy = "walk"
    Global.template_device = "host"
    qw = proxy.run_single_query(text, blind=False)
    Global.template_device = "device"
    old_max = Global.table_capacity_max
    old_min = Global.table_capacity_min
    Global.table_capacity_min = 64
    Global.table_capacity_max = 128
    try:
        before = _fallbacks(proxy)
        q = proxy.run_single_query(text, blind=False)
    finally:
        Global.table_capacity_max = old_max
        Global.table_capacity_min = old_min
    assert q.result.status_code == ErrorCode.SUCCESS
    assert not getattr(q, "_template_compiled", False)
    assert_identical(qw, q)
    assert _fallbacks(proxy) == before + 1
    assert "TemplateOverflow" in demotion_report().values()


# ---------------------------------------------------------------------------
# the route chooser (TEMPLATE_ROUTES contract)
# ---------------------------------------------------------------------------

def test_route_chooser_knobs_and_thresholds():
    sig = ("t", 1)
    Global.template_device = "host"
    assert choose_template_route(sig, 10 ** 6) == "host"
    Global.template_device = "device"
    assert choose_template_route(sig, None) == "device"
    Global.template_device = "auto"
    Global.template_min_rows = 1000
    assert choose_template_route(sig, 999) == "host"
    assert choose_template_route(sig, None) == "host"
    assert choose_template_route(sig, 1000) == "device"
    assert set(TEMPLATE_ROUTES) == {"device", "host", "latched_host"}


def test_demotion_latch_and_store_version_rearm():
    sig = ("t", 2)
    latch_demotion(sig, "compile_failed", version=7)
    assert is_demoted(sig, 7)
    Global.template_device = "device"
    assert choose_template_route(sig, 10 ** 6, version=7) == "latched_host"
    # a store mutation re-arms the device attempt
    assert not is_demoted(sig, 8)
    assert choose_template_route(sig, 10 ** 6, version=8) == "device"
    assert "compile_failed" in demotion_report().values()
    reset_demotions()
    assert demotion_report() == {}


def test_low_efficiency_feedback_latches_host(monkeypatch):
    """Measured demotion: a template site whose warm padding efficiency
    collapsed (read ONLY through ``read_device_input``) latches host
    after enough dispatches."""
    from wukong_tpu.obs.device import get_device_obs, maybe_device_dispatch

    monkeypatch.setattr(Global, "enable_device_obs", True)
    get_device_obs().reset()
    Global.template_device = "auto"
    Global.template_min_rows = 1
    Global.template_demote_eff = 0.5
    sig = ("t", 3)
    for _ in range(8):
        maybe_device_dispatch("template.plan", template="tx", live=1,
                              capacity=4096, wall_us=10, nbytes=0)
    assert choose_template_route(sig, 10 ** 6, version=0) == "latched_host"
    assert "low_efficiency" in demotion_report().values()


# ---------------------------------------------------------------------------
# serve-path: chaos degrade, invalidation, feedback, EXPLAIN (lockdep)
# ---------------------------------------------------------------------------

@pytest.fixture()
def lockdep_checked():
    from wukong_tpu.analysis import lockdep

    lockdep.install(True)
    yield
    try:
        assert lockdep.cycles() == [], lockdep.cycles()
        assert lockdep.leaf_violations() == [], lockdep.leaf_violations()
    finally:
        lockdep.install(False)


def _mk_tri_proxy():
    triples, meta = generate_triangle(m=60, noise=3, seed=1)
    g = build_partition(triples, 0, 1)
    ss = CyclicStrings(meta)
    stats = Stats.generate(triples)
    proxy = Proxy(g, ss, cpu_engine=CPUEngine(g, ss),
                  planner=Planner(stats))
    return proxy, cyclic_query_text(meta)


@pytest.fixture()
def tri_proxy():
    return _mk_tri_proxy()


def _fallbacks(proxy) -> float:
    total = 0.0
    for s in proxy.metrics.snapshot().get(
            "wukong_template_fallback_total", {}).get("series", []):
        total += s["value"]
    return total


def test_serve_path_routes_device_and_matches_walk(tri_proxy,
                                                   lockdep_checked):
    proxy, text = tri_proxy
    Global.join_strategy = "walk"
    Global.template_device = "host"
    qw = proxy.run_single_query(text, blind=False)
    assert getattr(qw, "template_route", None) == "host"
    Global.template_device = "device"
    qd = proxy.run_single_query(text, blind=False)
    assert qd.template_route == "device"
    assert qd._template_compiled
    assert_identical(qw, qd)


@pytest.mark.chaos
@pytest.mark.parametrize("site", ["template.compile", "template.dispatch"])
def test_template_fault_degrades_to_walk_and_latches(tri_proxy, site,
                                                     lockdep_checked):
    """An injected compile-time or MID-FLIGHT dispatch transient fires
    with the query untouched; the serve path degrades the SAME query to
    the walk (SUCCESS, identical bytes, fallback counted) and latches
    the per-template demotion so the next query never re-pays the
    failed device attempt."""
    proxy, text = tri_proxy
    Global.join_strategy = "walk"
    Global.template_device = "host"
    qw = proxy.run_single_query(text, blind=False)
    Global.template_device = "device"
    before = _fallbacks(proxy)
    faults.install(FaultPlan([FaultSpec(site=site, kind="transient")],
                             seed=7))
    q = proxy.run_single_query(text, blind=False)
    faults.clear()
    assert q.result.status_code == ErrorCode.SUCCESS
    assert q.result.complete
    assert not getattr(q, "_template_compiled", False)
    assert_identical(qw, q)
    assert _fallbacks(proxy) == before + 1
    assert "TransientFault" in demotion_report().values()
    # the latch routes the next same-template query straight to host
    q2 = proxy.run_single_query(text, blind=False)
    assert q2.template_route == "latched_host"
    assert_identical(qw, q2)


def test_store_version_invalidation_via_dynamic_insert(tri_proxy,
                                                       lockdep_checked):
    """A dynamic insert bumps the store version: the next compiled
    execution sees the new rows (stale programs are unreachable AND
    reaped from the cache) — byte-identical to the host walk on the
    mutated store."""
    from wukong_tpu.store.dynamic import insert_triples
    from wukong_tpu.types import NORMAL_ID_START

    proxy, text = tri_proxy
    Global.join_strategy = "walk"
    Global.template_device = "device"
    base = proxy.run_single_query(text, blind=False)
    assert base._template_compiled
    a, b, c = (NORMAL_ID_START + 7001, NORMAL_ID_START + 7002,
               NORMAL_ID_START + 7003)
    insert_triples(proxy.g, np.asarray(
        [[a, 2, b], [b, 3, c], [a, 4, c]], dtype=np.int64))
    q = proxy.run_single_query(text, blind=False)
    assert q._template_compiled
    rows = set(map(tuple, q.result.table.tolist()))
    base_rows = set(map(tuple, base.result.table.tolist()))
    assert rows - base_rows == {(a, b, c)}
    Global.template_device = "host"
    qw = proxy.run_single_query(text, blind=False)
    assert_identical(qw, q)
    # every cached program is keyed at the post-insert version
    eng = proxy.template_engine()
    version = int(proxy.g.version)
    assert eng.program_count() >= 1
    assert all(k[1] == version for k in eng._programs)


def test_small_measured_feedback_demotes_auto_route(tri_proxy,
                                                    monkeypatch):
    """Under ``auto`` a successful compiled run whose MEASURED live
    rows undershoot ``template_min_rows`` latches the template back to
    host — the estimate over-predicted."""
    from wukong_tpu.obs.device import get_device_obs

    monkeypatch.setattr(Global, "enable_device_obs", True)
    get_device_obs().reset()
    proxy, text = tri_proxy
    Global.join_strategy = "walk"
    Global.template_device = "auto"
    Global.template_min_rows = 1000  # est 1500 routes device; live 715
    q = proxy.run_single_query(text, blind=False)
    assert q.template_route == "device"
    assert q._template_compiled
    assert "small_measured" in demotion_report().values()
    q2 = proxy.run_single_query(text, blind=False)
    assert q2.template_route == "latched_host"


def test_explain_renders_template_compiled_route(tri_proxy, monkeypatch):
    """EXPLAIN / EXPLAIN ANALYZE (satellite b): the route line says
    ``template-compiled`` and the per-step device table carries the
    whole-plan compiled row."""
    from wukong_tpu.obs.device import get_device_obs

    monkeypatch.setattr(Global, "enable_device_obs", True)
    get_device_obs().reset()
    proxy, text = tri_proxy
    Global.join_strategy = "walk"
    Global.template_device = "device"
    rep = proxy.explain_query(text, analyze=True)
    assert rep["route"] == "template-compiled"
    assert "route: template-compiled" in rep["rendered"]
    steps = [r for r in rep.get("device_steps", [])
             if r.get("site") == "template.plan"]
    assert len(steps) == 1  # the whole plan is ONE dispatch
    assert steps[0]["live"] == 715


# ---------------------------------------------------------------------------
# program cache: residency budget eviction
# ---------------------------------------------------------------------------

def test_budget_eviction_under_template_budget_mb(monkeypatch):
    """Two oversized programs cannot co-reside under a 1 MB budget: the
    LRU victim is evicted with its bytes charged on the residency
    ledger (kind ``template``)."""
    from wukong_tpu.obs.device import get_device_obs, read_device_input

    monkeypatch.setattr(Global, "enable_device_obs", True)
    monkeypatch.setattr(Global, "template_budget_mb", 1)
    monkeypatch.setattr(Global, "table_capacity_min", 1 << 16)
    get_device_obs().reset()
    triples, g, _meta = _tri_world()
    a = int(triples[triples[:, 1] == 2][0, 0])
    eng = TemplateCompiledEngine(g)
    q1 = handq([(a, 2, OUT, -1), (-1, 3, OUT, -2)], [-1, -2])
    assert eng.try_execute(q1)
    assert eng.program_count() == 1
    t1 = read_device_input("resident_bytes").get("template", 0)
    q2 = handq([(2, PREDICATE_ID, IN, -1), (-1, 2, OUT, -2)], [-1, -2])
    assert eng.try_execute(q2)
    assert eng.program_count() == 1  # the first program was evicted
    cached = sum(p.nbytes for p in eng._programs.values())
    t2 = read_device_input("resident_bytes").get("template", 0)
    assert t2 == cached  # the victim's bytes were charged back (evict)
    assert t2 < t1 + cached  # ... not accumulated alongside the fill
    # the evicted template re-executes correctly (cache miss, restage)
    q3 = handq([(a, 2, OUT, -1), (-1, 3, OUT, -2)], [-1, -2])
    qh = handq([(a, 2, OUT, -1), (-1, 3, OUT, -2)], [-1, -2])
    CPUEngine(g).execute(qh)
    assert eng.try_execute(q3)
    assert_identical(qh, q3)


def test_program_key_includes_route_knobs():
    """A runtime knob flip can never serve a program chosen under
    different routing rules: the knob set joins the cache key."""
    from wukong_tpu.engine.template_compile import _program_key

    Global.template_device = "auto"
    k1 = _program_key(("t",), 0, (1024,))
    Global.template_device = "device"
    k2 = _program_key(("t",), 0, (1024,))
    assert k1 != k2
    assert _program_key(("t",), 1, (1024,)) != k2  # version joins too


# ---------------------------------------------------------------------------
# consumers: stream-epoch + view-maintenance device frontier
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def lubm_world():
    from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm

    triples, _lay = generate_lubm(1, seed=42)
    ss = VirtualLubmStrings(1, seed=42)
    rng = np.random.default_rng(0)
    perm = rng.permutation(len(triples))
    return triples, ss, perm


PREFIX = """
PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
"""
Q_CHAIN = PREFIX + """SELECT ?X ?Y ?Z WHERE {
    ?X ub:memberOf ?Y .
    ?Y ub:subOrganizationOf ?Z .
}"""
Q_ONEHOP = PREFIX + "SELECT ?X ?Y WHERE { ?X ub:advisor ?Y . }"


def _stream_result(triples, ss, perm, text, knob):
    from wukong_tpu.stream import ReplaySource, StreamContext

    Global.template_device = knob
    base = triples[perm[:len(triples) // 2]]
    live = triples[perm[len(triples) // 2:]]
    ctx = StreamContext([build_partition(base, 0, 1)], ss)
    qid = ctx.register(text)
    ctx.feed_source(ReplaySource(live, batch_size=4096))
    return ctx.result_set(qid)


@pytest.mark.stream
def test_stream_epoch_device_frontier_matches_host_oracle(lubm_world,
                                                          monkeypatch):
    """The fully device-evaluated stream frontier (``template_device
    device`` forces the fused seed extraction for every epoch) converges
    to the byte-identical standing result of the host path."""
    from wukong_tpu.obs.device import get_device_obs, read_device_input

    monkeypatch.setattr(Global, "enable_device_obs", True)
    get_device_obs().reset()
    triples, ss, perm = lubm_world
    host = _stream_result(triples, ss, perm, Q_CHAIN, "host")
    dev = _stream_result(triples, ss, perm, Q_CHAIN, "device")
    assert host.shape == dev.shape
    assert np.array_equal(host, dev)
    counts = read_device_input("dispatches", "stream.seed_extract")
    assert int(counts["count"]) > 0  # the device frontier actually ran


@pytest.mark.stream
def test_device_seed_extract_gating_and_parity(lubm_world):
    """The fused extraction is knob-gated (host -> None, auto under the
    amortization floor -> None) and byte-identical to ``match_delta``
    per term when it runs."""
    from wukong_tpu.stream.continuous import (device_seed_extract,
                                              match_delta)

    triples, ss, _perm = lubm_world
    from wukong_tpu.sparql.parser import Parser

    q = Parser(ss).parse(Q_CHAIN)
    pats = list(q.pattern_group.patterns)
    batch = triples[:4096]

    Global.template_device = "host"
    assert device_seed_extract(pats, batch) is None
    Global.template_device = "auto"
    Global.join_device_min_candidates = 1 << 60
    assert device_seed_extract(pats, batch) is None

    Global.template_device = "device"
    seeds = device_seed_extract(pats, batch)
    assert seeds is not None and len(seeds) == len(pats)
    for (vars_d, seed_d), pat in zip(seeds, pats):
        vars_h, seed_h = match_delta(pat, batch)
        assert vars_d == vars_h
        assert np.array_equal(seed_d, seed_h)


@pytest.mark.serve
def test_view_maintenance_device_union_matches_host(lubm_world,
                                                    monkeypatch):
    """Consumer 3: an epoch's per-view semi-naive term unions batch into
    one fused device frontier — survivor decisions and the standing
    seen-set stay byte-identical to the host path."""
    from wukong_tpu.serve.views import ViewRegistry

    monkeypatch.setattr(Global, "enable_views", True)
    monkeypatch.setattr(Global, "enable_device_obs", True)
    triples, ss, perm = lubm_world
    base = triples[perm[:len(triples) // 2]]
    batches = [triples[perm[len(triples) // 2:len(triples) // 2 + 2048]],
               triples[perm[len(triples) // 2 + 2048:
                            len(triples) // 2 + 4096]]]

    def drive(knob):
        Global.template_device = knob
        g = build_partition(base, 0, 1)
        vr = ViewRegistry()
        vr.attach(g, ss)
        assert vr.promote(("m-chain",), Q_CHAIN)
        assert vr.promote(("m-onehop",), Q_ONEHOP)
        out = []
        for i, batch in enumerate(batches):
            out.append(vr.on_mutation(batch, version=i + 1))
        seen = {m: sorted(vr._ce.queries[v.qid].seen)
                for m, v in vr._views.items()}
        return out, seen

    host_surv, host_seen = drive("host")
    dev_surv, dev_seen = drive("device")
    assert host_surv == dev_surv
    assert host_seen == dev_seen


# ---------------------------------------------------------------------------
# consumer: device-side slice settlement in the distributed join
# ---------------------------------------------------------------------------

def test_dist_settle_device_concat_matches_host(monkeypatch):
    """Consumer 1: the gather thread's slice settlement concatenates
    padded per-slice tables on device — byte-identical (row order
    included) to ``np.concatenate`` over the same slices."""
    from wukong_tpu.join.dist import DistributedWCOJExecutor

    rng = np.random.default_rng(3)
    slices = [rng.integers(0, 1 << 20, size=(n, 3)).astype(np.int64)
              for n in (17, 1, 63, 9)]
    host = np.concatenate(slices, axis=0)

    dj = DistributedWCOJExecutor.__new__(DistributedWCOJExecutor)
    dj._settle_broken = False
    monkeypatch.setattr(Global, "template_device", "device")
    out = dj._settle(list(slices), 3)
    assert out.dtype == np.int64
    assert np.array_equal(out, host)
    monkeypatch.setattr(Global, "template_device", "host")
    out_h = dj._settle(list(slices), 3)
    assert np.array_equal(out_h, host)
