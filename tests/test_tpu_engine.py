"""TPU engine vs CPU oracle on the LUBM basic suite (virtual CPU devices)."""

import glob
import os

import numpy as np
import pytest

from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.engine.tpu import TPUEngine
from wukong_tpu.loader.lubm import VirtualLubmStrings, generate_lubm
from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.planner.plan_file import set_plan
from wukong_tpu.sparql.parser import Parser
from wukong_tpu.store.gstore import build_partition

BASIC = "/root/reference/scripts/sparql_query/lubm/basic"


@pytest.fixture(scope="module")
def world():
    triples, _ = generate_lubm(1, seed=42)
    g = build_partition(triples, 0, 1)
    ss = VirtualLubmStrings(1, seed=42)
    return g, ss


@pytest.fixture(scope="module")
def engines(world):
    g, ss = world
    return CPUEngine(g, ss), TPUEngine(g, ss)


def _both(engines, ss, text, plan=None):
    cpu, tpu = engines
    rows = {}
    for name, eng in (("cpu", cpu), ("tpu", tpu)):
        q = Parser(ss).parse(text)
        if plan:
            assert set_plan(q.pattern_group, open(plan).read())
        else:
            heuristic_plan(q)
        eng.execute(q)
        assert q.result.status_code == 0, (name, q.result.status_code)
        rows[name] = sorted(map(tuple, q.result.table.tolist()))
    return rows["cpu"], rows["tpu"]


QUERIES = [f for f in sorted(glob.glob(f"{BASIC}/lubm_q*")) if os.path.isfile(f)]


@pytest.mark.parametrize("qfile", QUERIES, ids=[os.path.basename(f) for f in QUERIES])
def test_tpu_matches_cpu_basic_suite(engines, world, qfile):
    _, ss = world
    cpu_rows, tpu_rows = _both(engines, ss, open(qfile).read())
    assert cpu_rows == tpu_rows, (
        f"{os.path.basename(qfile)}: cpu {len(cpu_rows)} rows "
        f"vs tpu {len(tpu_rows)} rows")


OSDI_PLANS = sorted(glob.glob(f"{BASIC}/osdi16_plan/lubm_q*.fmt"))


@pytest.mark.parametrize("pfile", OSDI_PLANS,
                         ids=[os.path.basename(f) for f in OSDI_PLANS])
def test_tpu_matches_cpu_osdi_plans(engines, world, pfile):
    _, ss = world
    qname = os.path.basename(pfile)[:-4]
    cpu_rows, tpu_rows = _both(engines, ss, open(f"{BASIC}/{qname}").read(), pfile)
    assert cpu_rows == tpu_rows


def test_capacity_overflow_retry(world):
    """Force a tiny starting capacity so expansion must regrow mid-query."""
    from wukong_tpu.config import Global

    g, ss = world
    old = Global.table_capacity_min
    Global.table_capacity_min = 16
    try:
        tpu = TPUEngine(g, ss)
        tpu.cap_min = 16
        cpu = CPUEngine(g, ss)
        text = open(f"{BASIC}/lubm_q2").read()
        qc = Parser(ss).parse(text)
        heuristic_plan(qc)
        cpu.execute(qc)
        qt = Parser(ss).parse(text)
        heuristic_plan(qt)
        tpu.execute(qt)
        assert qt.result.nrows == qc.result.nrows
        assert sorted(map(tuple, qt.result.table.tolist())) == \
            sorted(map(tuple, qc.result.table.tolist()))
    finally:
        Global.table_capacity_min = old


def test_segment_cache_reuse_and_eviction(world):
    g, ss = world
    tpu = TPUEngine(g, ss, budget_bytes=1 << 20)
    text = open(f"{BASIC}/lubm_q4").read()
    for _ in range(2):
        q = Parser(ss).parse(text)
        heuristic_plan(q)
        tpu.execute(q)
        assert q.result.status_code == 0
    assert tpu.dstore.bytes_used <= (1 << 20) + 4 * (1 << 16)  # budget + slack


def test_stats_capacity_estimation_reduces_retries(world):
    """With planner stats, q2-style expansions should need no capacity retry."""
    from wukong_tpu.engine import tpu_kernels as K
    from wukong_tpu.loader.lubm import generate_lubm
    from wukong_tpu.planner.stats import Stats

    g, ss = world
    triples, _ = generate_lubm(1, seed=42)
    stats = Stats.generate(triples)
    calls = []
    orig = K.expand

    def counting_expand(*a, **k):
        calls.append(k.get("cap_out"))
        return orig(*a, **k)

    text = open(f"{BASIC}/lubm_q2").read()
    try:
        K.expand = counting_expand
        tpu = TPUEngine(g, ss, stats=stats)
        q = Parser(ss).parse(text)
        heuristic_plan(q)
        q.result.blind = True
        tpu.execute(q)
        with_stats = len(calls)
        calls.clear()
        tpu2 = TPUEngine(g, ss)  # no stats
        q2 = Parser(ss).parse(text)
        heuristic_plan(q2)
        q2.result.blind = True
        tpu2.execute(q2)
        without = len(calls)
    finally:
        K.expand = orig
    assert q.result.nrows == q2.result.nrows
    assert with_stats <= without  # stats never add retries


HEAVIES = [f"{BASIC}/lubm_q{k}" for k in (1, 2, 3, 7)]


@pytest.mark.parametrize("qfile", HEAVIES,
                         ids=[os.path.basename(f) for f in HEAVIES])
def test_batch_index_replicate_and_slice(engines, world, qfile):
    """Batched index-origin (heavy) execution: every replicated instance
    reproduces the single-query count; slices partition it."""
    cpu, tpu = engines
    _, ss = world
    text = open(qfile).read()

    q = Parser(ss).parse(text)
    heuristic_plan(q)
    cpu.execute(q)
    assert q.result.status_code == 0
    want = q.result.nrows

    B = 4
    qb = Parser(ss).parse(text)
    heuristic_plan(qb)
    qb.result.blind = True
    counts = tpu.execute_batch_index(qb, B)
    assert counts.shape == (B,)
    assert counts.tolist() == [want] * B

    qs = Parser(ss).parse(text)
    heuristic_plan(qs)
    qs.result.blind = True
    counts = tpu.execute_batch_index(qs, B, slice_mode=True)
    assert int(counts.sum()) == want


def test_suggest_index_batch(engines, world):
    _, tpu = engines
    _, ss = world
    q = Parser(ss).parse(open(f"{BASIC}/lubm_q2").read())
    heuristic_plan(q)
    b = tpu.suggest_index_batch(q)
    assert 1 <= b <= 1024


def test_prefetch_pipelining_stages_chain_segments(engines, world, monkeypatch):
    """gpu_enable_pipeline stages every chain segment before dispatch."""
    from wukong_tpu.config import Global
    from wukong_tpu.engine.tpu import TPUEngine

    g, ss = world
    monkeypatch.setattr(Global, "gpu_enable_pipeline", True)
    tpu = TPUEngine(g, ss)
    staged = []
    orig = tpu.dstore.prefetch
    monkeypatch.setattr(tpu.dstore, "prefetch",
                        lambda pats: (staged.append(1), orig(pats))[1])
    q = Parser(ss).parse(open(f"{BASIC}/lubm_q4").read())
    heuristic_plan(q)
    tpu.execute(q)
    assert q.result.status_code == 0 and staged

    monkeypatch.setattr(Global, "gpu_enable_pipeline", False)
    staged.clear()
    q = Parser(ss).parse(open(f"{BASIC}/lubm_q4").read())
    heuristic_plan(q)
    tpu.execute(q)
    assert q.result.status_code == 0 and not staged


def test_pallas_probe_matches_xla(world):
    """Pallas probe kernel (interpret mode) == the XLA _hash_find path."""
    import jax.numpy as jnp
    import numpy as np

    from wukong_tpu.engine import tpu_kernels as K
    from wukong_tpu.engine.device_store import DeviceStore
    from wukong_tpu.loader.lubm import P
    from wukong_tpu.types import OUT

    g, ss = world
    seg = DeviceStore(g).segment(P["memberOf"], OUT)
    rng = np.random.default_rng(3)
    C = 2048
    keys = np.asarray(g.segments[(P["memberOf"], OUT)].keys)
    cur = np.concatenate([
        rng.choice(keys, C // 2),                  # hits
        rng.integers(1 << 22, 1 << 23, C // 2),    # misses
    ]).astype(np.int32)
    rng.shuffle(cur)
    n = C - 17  # some dead tail rows
    valid = np.arange(C) < n

    fx, sx, dx = K._hash_find(seg.bkey, seg.bstart, seg.bdeg,
                              jnp.asarray(cur), jnp.asarray(valid),
                              seg.max_probe)
    fp, sp, dp = K.pallas_probe(seg.bkey, seg.bstart, seg.bdeg,
                                jnp.asarray(cur), jnp.int32(n),
                                seg.max_probe, interpret=True)
    assert np.array_equal(np.asarray(fx), np.asarray(fp))
    assert np.array_equal(np.asarray(sx), np.asarray(sp))
    assert np.array_equal(np.asarray(dx), np.asarray(dp))


def test_versatile_kuu_on_device(world):
    """VERSATILE known_unknown_unknown (?x ?p ?y, x bound) runs on the
    device chain via the combined-adjacency segment + expand2 — beyond the
    reference, whose GPU engine refuses every versatile shape
    (gpu_engine.hpp:267-333). Results must match the CPU kernels exactly."""
    from wukong_tpu.planner.heuristic import heuristic_plan
    from wukong_tpu.sparql.parser import Parser

    g, ss = world
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.engine.tpu import TPUEngine

    cpu = CPUEngine(g, ss)
    tpu = TPUEngine(g, ss)
    text = """
    PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
    SELECT ?X ?P ?Y WHERE {
        ?X ub:worksFor <http://www.Department0.University0.edu> .
        ?X ?P ?Y .
    }"""

    qc = Parser(ss).parse(text)
    heuristic_plan(qc)
    cpu.execute(qc)
    assert qc.result.status_code == 0 and qc.result.nrows > 0

    qt = Parser(ss).parse(text)
    heuristic_plan(qt)
    tpu.execute(qt)
    assert qt.result.status_code == 0
    import numpy as np

    def rows(q):
        cols = [q.result.var2col(v) for v in q.result.required_vars]
        return sorted(map(tuple, np.asarray(q.result.table)[:, cols].tolist()))

    assert rows(qt) == rows(qc)
    # and the chain actually used the device path: the versatile combined
    # segment must be staged
    assert ("vpv", 1) in tpu.dstore._cache  # OUT direction

    # continuation after the versatile step (filter on the new value col)
    text2 = """
    PREFIX rdf: <http://www.w3.org/1999/02/22-rdf-syntax-ns#>
    PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
    SELECT ?X ?P ?Y WHERE {
        ?X ub:worksFor <http://www.Department0.University0.edu> .
        ?X ?P ?Y .
        ?Y rdf:type ub:Course .
    }"""
    qc2 = Parser(ss).parse(text2)
    heuristic_plan(qc2)
    cpu.execute(qc2)
    qt2 = Parser(ss).parse(text2)
    heuristic_plan(qt2)
    tpu.execute(qt2)
    assert qt2.result.status_code == 0
    assert rows(qt2) == rows(qc2)
    assert qc2.result.nrows > 0


def test_versatile_const_shapes_on_device(world):
    """The remaining VERSATILE shapes run on the device chain too:
    const_unknown_unknown / const_unknown_const start via a host CSR init
    (sparql.hpp:246-290), known_unknown_const mid-chain via expand2 + an
    equality fold on the value row (sparql.hpp:651-699). The reference GPU
    engine refuses all of these; ours must match the CPU kernels exactly."""
    from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
    from wukong_tpu.types import IN, OUT, TYPE_ID

    g, ss = world
    cpu = CPUEngine(g, ss)
    tpu = TPUEngine(g, ss)
    dept0 = ss.str2id("<http://www.Department0.University0.edu>")
    univ0 = ss.str2id("<http://www.University0.edu>")
    fp = ss.str2id("<http://swat.cse.lehigh.edu/onto/univ-bench.owl#FullProfessor>")

    def run(eng, pats, req):
        q = SPARQLQuery()
        q.result.nvars = len(req)
        q.pattern_group.patterns = [Pattern(*p) for p in pats]
        q.result.required_vars = list(req)
        eng.execute(q, from_proxy=False)
        assert q.result.status_code == 0, q.result.status_code
        cols = [q.result.var2col(v) for v in req]
        return sorted(map(tuple, np.asarray(q.result.table)[:, cols].tolist()))

    def cmp(pats, req, name):
        a = run(cpu, pats, req)
        b = run(tpu, pats, req)
        assert a == b, (name, len(a), len(b))
        assert len(a) > 0, (name, "vacuous: empty result")
        return a

    # const_unknown_unknown start: Dept0 ?P ?Y (full combined adjacency)
    cmp([(dept0, -9, OUT, -1)], [-9, -1], "c_u_u")
    # const_unknown_const: Dept0 ?P Univ0 (= subOrganizationOf)
    got = cmp([(dept0, -9, OUT, univ0)], [-9], "c_u_c")
    sub = ss.str2id(
        "<http://swat.cse.lehigh.edu/onto/univ-bench.owl#subOrganizationOf>")
    assert (sub,) in got
    # known_unknown_const mid-chain: FullProfessors with any edge to Univ0
    # (degreeFrom flavors) — type-index start keeps the k_u_c mid-chain
    cmp([(fp, TYPE_ID, IN, -1), (-1, -9, OUT, univ0)], [-1, -9], "k_u_c")
    # and a continuation AFTER the fold (normal expand on the filtered rows)
    works = ss.str2id("<http://swat.cse.lehigh.edu/onto/univ-bench.owl#worksFor>")
    cmp([(fp, TYPE_ID, IN, -1), (-1, -9, OUT, univ0),
         (-1, works, OUT, -2)], [-1, -9, -2], "k_u_c_then_expand")


def test_union_children_ride_device_chain(world):
    """Seeded UNION branches route back through the TPU engine: the branch
    plans anchor on inherited bindings (no whole-graph index start), the
    parent table uploads once, and the branch segments stage on device."""
    from wukong_tpu.planner.heuristic import heuristic_plan

    g, ss = world
    cpu = CPUEngine(g, ss)
    tpu = TPUEngine(g, ss)
    text = """PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
    SELECT ?X ?Y ?Z WHERE {
        ?X ub:memberOf ?Y .
        { ?X ub:undergraduateDegreeFrom ?Z . }
        UNION { ?X ub:mastersDegreeFrom ?Z . }
    }"""
    qc = Parser(ss).parse(text)
    heuristic_plan(qc)
    cpu.execute(qc)
    qt = Parser(ss).parse(text)
    heuristic_plan(qt)
    # anchored branches plan as one k2u each, no index start prepended
    assert all(len(u.patterns) == 1 and u.patterns[0].subject == -1
               for u in qt.pattern_group.unions)
    tpu.execute(qt)
    assert qt.result.status_code == 0
    a = sorted(map(tuple, np.asarray(qc.result.table).tolist()))
    b = sorted(map(tuple, np.asarray(qt.result.table).tolist()))
    assert a == b and len(a) > 0
    ug = ss.str2id(
        "<http://swat.cse.lehigh.edu/onto/univ-bench.owl#undergraduateDegreeFrom>")
    ms = ss.str2id(
        "<http://swat.cse.lehigh.edu/onto/univ-bench.owl#mastersDegreeFrom>")
    staged = {k[:2] for k in tpu.dstore._cache if isinstance(k, tuple)}
    assert any(k[0] == ug for k in staged)  # branch BGPs ran on device
    assert any(k[0] == ms for k in staged)


def test_optional_leftjoin_on_device(world):
    """OPTIONAL groups sharing a bound var run as dedup-seeded device
    children + host left join (the shared formulation); the full reference
    optional suite, including the promoted-base q5, matches CPU."""
    import glob

    from wukong_tpu.planner.heuristic import heuristic_plan

    g, ss = world
    cpu = CPUEngine(g, ss)
    tpu = TPUEngine(g, ss)
    for qf in sorted(
            glob.glob("/root/reference/scripts/sparql_query/lubm/optional/q*")):
        if "fmt" in qf or "manual" in qf:
            continue
        text = open(qf).read()
        qc = Parser(ss).parse(text)
        heuristic_plan(qc)
        cpu.execute(qc)
        assert qc.result.status_code == 0, qf
        qt = Parser(ss).parse(text)
        heuristic_plan(qt)
        tpu.execute(qt)
        assert qt.result.status_code == 0, qf
        a = sorted(map(tuple, np.asarray(qc.result.table).tolist()))
        b = sorted(map(tuple, np.asarray(qt.result.table).tolist()))
        assert a == b and len(a) > 0, qf
    # the seeded child must actually stage its segment on device
    text = """PREFIX ub: <http://swat.cse.lehigh.edu/onto/univ-bench.owl#>
    SELECT ?S ?UG WHERE {
        ?S ub:memberOf ?D .
        OPTIONAL { ?S ub:undergraduateDegreeFrom ?UG }
    }"""
    tpu2 = TPUEngine(g, ss)
    qt = Parser(ss).parse(text)
    heuristic_plan(qt)
    tpu2.execute(qt)
    assert qt.result.status_code == 0
    ug = ss.str2id(
        "<http://swat.cse.lehigh.edu/onto/univ-bench.owl#undergraduateDegreeFrom>")
    staged = {k[:2] for k in tpu2.dstore._cache if isinstance(k, tuple)}
    assert any(k[0] == ug for k in staged)
