"""Hybrid graph+vector subsystem (wukong_tpu/vector/): vstore semantics,
the batched k-NN operator's route identity, knn() composition with BGPs,
the serving-path integration, and the durability seams.

Acceptance surface (ISSUE 17):

- k-NN results exact vs a NumPy brute-force oracle on all three metrics,
  including the canonical ``(score desc, vid asc)`` tie policy;
- both composition directions (rank-then-pattern / pattern-then-rank)
  byte-identical between the host and device routes, and between the CPU
  and device engines;
- a device-path failure demotes to the host kernels with the answer
  intact and the template's memoized route flipped to host;
- the ``vector.upsert`` fault site fires BEFORE the WAL append — an
  injected failure leaves the WAL and every vstore untouched;
- ``enable_vectors off`` refuses knn() and leaves the graph path
  zero-touch;
- migration dual-write sinks mirror vector batches.
"""

import threading

import numpy as np
import pytest

from wukong_tpu.config import Global
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.engine.tpu import TPUEngine
from wukong_tpu.loader.datagen import (
    CyclicStrings,
    generate_triangle,
    make_vectors,
)
from wukong_tpu.runtime import faults
from wukong_tpu.runtime.faults import FaultPlan, FaultSpec, TransientFault
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.sparql.parser import Parser, SPARQLSyntaxError
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.store.wal import active_wal, reset_wal
from wukong_tpu.types import NORMAL_ID_START
from wukong_tpu.utils.errors import ErrorCode, WukongError
from wukong_tpu.vector import knn as vknn
from wukong_tpu.vector.vstore import (
    VectorStore,
    apply_vector_record,
    attach_vstore,
    upsert_batch_into,
)

pytestmark = pytest.mark.vector

DIM = 8


@pytest.fixture(autouse=True, scope="module")
def _lockdep_checked():
    """The vector plane introduces two leaf locks (vector.slots /
    vector.slice); the whole suite runs under the lockdep checker so
    every scan/upsert doubles as a lock-order regression test."""
    from wukong_tpu.analysis import lockdep

    lockdep.install(True)
    yield
    try:
        assert lockdep.cycles() == [], lockdep.cycles()
        assert lockdep.leaf_violations() == [], lockdep.leaf_violations()
    finally:
        lockdep.install(False)


@pytest.fixture(autouse=True)
def _clean_knobs():
    faults.clear()
    yield
    faults.clear()
    Global.enable_vectors = False
    Global.vector_dim = 64
    Global.knn_metric = "cosine"
    Global.knn_device = "auto"
    Global.knn_split_threshold = 65536
    Global.wal_dir = ""
    reset_wal()
    vknn._DEVICE_FAIL_HOOK = None


@pytest.fixture(scope="module")
def tri_world():
    triples, meta = generate_triangle(64, noise=2, seed=1)
    return triples, meta


def _hybrid_world(tri_world):
    """A fresh single-partition triangle world with every vertex
    embedded (id-keyed clustered vectors) and both engines attached."""
    triples, meta = tri_world
    g = build_partition(triples, 0, 1)
    ss = CyclicStrings(meta)
    attach_vstore(g, DIM)
    vids = np.arange(NORMAL_ID_START, NORMAL_ID_START + 192,
                     dtype=np.int64)
    upsert_batch_into([g], vids, make_vectors(vids, DIM))
    proxy = Proxy(g, ss, cpu_engine=CPUEngine(g, ss),
                  tpu_engine=TPUEngine(g, ss))
    return g, ss, proxy


def _rand_store(n=300, dim=DIM, seed=3, dead_every=7):
    rng = np.random.default_rng(seed)
    vs = VectorStore(0, 1, dim)
    vids = np.arange(n, dtype=np.int64)
    vs.upsert(vids, rng.standard_normal((n, dim)).astype(np.float32))
    vs.tombstone(vids[::dead_every])
    return vs


def _oracle_topk(vs, anchor, k, metric):
    """Independent brute-force oracle (different formulation from
    knn.scores on purpose: per-row python loop, l2 as ascending
    distance)."""
    vids, vecs, alive, _v = vs.snapshot()
    anchor = np.asarray(anchor, dtype=np.float64)
    out = []
    for vid, vec, ok in zip(vids, vecs, alive):
        if not ok:
            continue
        v = vec.astype(np.float64)
        if metric == "dot":
            s = float(v @ anchor)
        elif metric == "cosine":
            s = float((v @ anchor)
                      / max(np.linalg.norm(v) * np.linalg.norm(anchor),
                            1e-12))
        else:  # l2, ranked by negative squared distance
            s = -float(np.sum((v - anchor) ** 2))
        out.append((s, int(vid)))
    out.sort(key=lambda t: (-t[0], t[1]))
    return np.asarray([vid for _s, vid in out[:k]], dtype=np.int64)


# ---------------------------------------------------------------------------
# kernel parity: host oracle, device identity, tie policy
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("metric", vknn.KNN_METRICS)
def test_topk_host_matches_bruteforce_oracle(metric):
    vs = _rand_store()
    anchor = np.asarray(vs.get(1))
    got_v, got_s = vknn.topk_host(*vs.snapshot()[:3], anchor, 10, metric)
    assert np.array_equal(got_v, _oracle_topk(vs, anchor, 10, metric))
    assert np.all(np.diff(got_s.astype(np.float64)) <= 1e-6)  # desc


@pytest.mark.parametrize("metric", vknn.KNN_METRICS)
def test_topk_device_byte_identical_to_host(metric):
    vs = _rand_store(n=257)  # straddles a pad_pow2 capacity boundary
    anchor = np.asarray(vs.get(2))
    hv, hs = vknn.topk_host(*vs.snapshot()[:3], anchor, 12, metric)
    dv, ds = vknn.topk_device(*vs.snapshot()[:3], anchor, 12, metric)
    assert np.array_equal(hv, dv)
    assert np.allclose(hs, ds, rtol=1e-5, atol=1e-5)


def test_tie_break_vid_ascending_on_both_routes():
    # 16 identical vectors: every score ties, so the canonical order is
    # pure vid-ascending — on BOTH kernels
    vs = VectorStore(0, 1, DIM)
    vids = np.asarray([40, 7, 23, 1, 99, 5, 60, 2,
                       81, 3, 12, 44, 9, 71, 30, 18], dtype=np.int64)
    vs.upsert(vids, np.ones((16, DIM), dtype=np.float32))
    want = np.sort(vids)[:6]
    anchor = np.ones(DIM, dtype=np.float32)
    for fn in (vknn.topk_host, vknn.topk_device):
        got_v, _ = fn(*vs.snapshot()[:3], anchor, 6, "cosine")
        assert np.array_equal(got_v, want), fn.__name__


def test_topk_excludes_tombstoned_and_caps_k():
    vs = _rand_store(n=20, dead_every=2)  # 10 live slots
    anchor = np.asarray(vs.get(1))
    got_v, _ = vknn.topk_host(*vs.snapshot()[:3], anchor, 50, "dot")
    assert len(got_v) == 10  # k capped at the live population
    assert not (set(got_v.tolist()) & set(range(0, 20, 2)))


# ---------------------------------------------------------------------------
# route seam: demotion, slicing
# ---------------------------------------------------------------------------

def test_scan_topk_demotes_device_failure_to_host():
    vs = _rand_store()
    anchor = np.asarray(vs.get(4))
    want_v, want_s, none = vknn.scan_topk(vs, anchor, 5, "cosine",
                                          route="host")
    assert none is None

    def boom():
        raise RuntimeError("injected device failure")

    vknn._DEVICE_FAIL_HOOK = boom
    try:
        got_v, got_s, demoted = vknn.scan_topk(vs, anchor, 5, "cosine",
                                               route="device")
    finally:
        vknn._DEVICE_FAIL_HOOK = None
    assert demoted == "RuntimeError"
    assert np.array_equal(got_v, want_v)
    assert np.allclose(got_s, want_s)


class _InlinePool:
    """Minimal heavy-lane pool: runs each submitted slice on a thread
    (the claim/gather barrier is what's under test, not scheduling)."""

    def __init__(self):
        self.submitted = 0

    def submit(self, item, lane=None):
        assert lane == "heavy"
        self.submitted += 1
        threading.Thread(target=item.run, daemon=True).start()


@pytest.mark.parametrize("parts", [2, 5])
def test_sliced_topk_equals_single_scan(parts):
    vs = _rand_store(n=400)
    anchor = np.asarray(vs.get(8))
    want_v, want_s, _ = vknn.scan_topk(vs, anchor, 9, "l2", route="host")
    pool = _InlinePool()
    got_v, got_s, demoted = vknn.sliced_topk(pool, vs, anchor, 9, "l2",
                                             "host", parts)
    assert pool.submitted == parts - 1  # gather thread works slice 0
    assert demoted is None
    assert np.array_equal(got_v, want_v)
    assert np.allclose(got_s, want_s)


def test_sliced_topk_per_slice_device_fallback():
    vs = _rand_store(n=200)
    anchor = np.asarray(vs.get(8))
    want_v, _, _ = vknn.scan_topk(vs, anchor, 7, "cosine", route="host")

    def boom():
        raise RuntimeError("slice device failure")

    vknn._DEVICE_FAIL_HOOK = boom
    try:
        got_v, _, demoted = vknn.sliced_topk(_InlinePool(), vs, anchor,
                                             7, "cosine", "device", 3)
    finally:
        vknn._DEVICE_FAIL_HOOK = None
    assert demoted == "RuntimeError"  # latched for the proxy's feedback
    assert np.array_equal(got_v, want_v)


# ---------------------------------------------------------------------------
# vstore semantics
# ---------------------------------------------------------------------------

def test_vstore_upsert_dedup_tombstone_revive():
    vs = VectorStore(0, 1, DIM)
    v0 = np.zeros((1, DIM), dtype=np.float32)
    v1 = np.ones((1, DIM), dtype=np.float32)
    # in-batch dedup: the LAST occurrence wins (upsert semantics)
    vs.upsert([5, 5], np.concatenate([v0, v1]))
    assert vs.n_slots() == 1 and np.array_equal(np.asarray(vs.get(5)),
                                                v1[0])
    ver = vs.version
    vs.tombstone([5])
    assert vs.get(5) is None and vs.live_count() == 0
    assert vs.version == ver + 1
    vs.upsert([5], v0)  # revive in place: no new slot
    assert vs.n_slots() == 1 and np.array_equal(np.asarray(vs.get(5)),
                                                v0[0])


def test_vstore_ownership_filter_partitions_like_triples():
    stores = [VectorStore(sid, 4, DIM) for sid in range(4)]
    vids = np.arange(100, dtype=np.int64)
    vecs = np.ones((100, DIM), dtype=np.float32)
    written = [vs.upsert(vids, vecs) for vs in stores]
    assert sum(written) == 100  # exact partition, no overlap
    assert all(w > 0 for w in written)


def test_vstore_snapshot_arrays_immutable_and_stable():
    vs = _rand_store(n=50)
    vids, vecs, alive, ver = vs.snapshot()
    with pytest.raises((ValueError, RuntimeError)):
        vecs[0, 0] = 99.0
    vs.upsert([500], np.zeros((1, DIM), dtype=np.float32))
    # the racing upsert published NEW arrays; the held snapshot is intact
    assert len(vids) == 50 and vs.n_slots() == 51
    assert vs.snapshot()[3] == ver + 1


def test_vstore_rejects_dim_mismatch_and_bad_ids():
    vs = VectorStore(0, 1, DIM)
    with pytest.raises(WukongError):
        vs.upsert([1], np.zeros((1, DIM + 1), dtype=np.float32))
    with pytest.raises(WukongError):
        upsert_batch_into([], np.asarray([-1]),
                          np.zeros((1, DIM), dtype=np.float32))


def test_wal_replayed_store_digest_identical(tmp_path):
    Global.wal_dir = str(tmp_path)
    reset_wal()
    g = build_partition(np.asarray([[NORMAL_ID_START, 2,
                                     NORMAL_ID_START + 1]],
                                   dtype=np.int64), 0, 1)
    attach_vstore(g, DIM)
    vids = np.arange(NORMAL_ID_START, NORMAL_ID_START + 40,
                     dtype=np.int64)
    upsert_batch_into([g], vids, make_vectors(vids, DIM))
    upsert_batch_into([g], vids[::3], tombstone=True)
    recs = [r for r in active_wal().replay() if r.kind == "vector"]
    assert len(recs) == 2
    g2 = build_partition(np.asarray([[NORMAL_ID_START, 2,
                                      NORMAL_ID_START + 1]],
                                    dtype=np.int64), 0, 1)
    for r in recs:  # replay attaches on demand (fresh-world contract)
        apply_vector_record(g2, r.payload)
    assert g2.vstore.digest() == g.vstore.digest()
    assert g2.vstore.live_count() == g.vstore.live_count()


# ---------------------------------------------------------------------------
# the vector.upsert fault site (KNOWN_FAULT_SITES chaos drill)
# ---------------------------------------------------------------------------

def test_vector_upsert_fault_leaves_wal_and_vstore_untouched(tmp_path):
    """The 'vector.upsert' site fires BEFORE the WAL append: an injected
    failure must leave the WAL record count AND every vstore byte
    untouched — the batch was never acknowledged, so there is nothing to
    replay and nothing to roll back."""
    Global.wal_dir = str(tmp_path)
    reset_wal()
    g = build_partition(np.asarray([[NORMAL_ID_START, 2,
                                     NORMAL_ID_START + 1]],
                                   dtype=np.int64), 0, 1)
    attach_vstore(g, DIM)
    vids = np.arange(NORMAL_ID_START, NORMAL_ID_START + 20,
                     dtype=np.int64)
    upsert_batch_into([g], vids, make_vectors(vids, DIM))
    digest0 = g.vstore.digest()
    vver0 = g.vstore.version
    gver0 = g.version
    wal_count0 = len(list(active_wal().replay()))

    faults.install(FaultPlan([FaultSpec("vector.upsert", "transient")],
                             seed=0))
    with pytest.raises(TransientFault):
        upsert_batch_into([g], vids, make_vectors(vids, DIM, seed=9))
    faults.clear()

    assert len(list(active_wal().replay())) == wal_count0
    assert g.vstore.digest() == digest0
    assert g.vstore.version == vver0 and g.version == gver0
    # the plan is gone: the same batch now commits durably
    assert upsert_batch_into([g], vids,
                             make_vectors(vids, DIM, seed=9)) == 20
    assert len(list(active_wal().replay())) == wal_count0 + 1


# ---------------------------------------------------------------------------
# migration dual-write
# ---------------------------------------------------------------------------

def test_migration_sink_mirrors_vector_batches():
    from wukong_tpu.store.dynamic import (
        deroll_migration_sink,
        enroll_migration_sink,
    )
    from wukong_tpu.store.wal import mutation_lock

    g1 = build_partition(np.asarray([[NORMAL_ID_START, 2,
                                      NORMAL_ID_START + 1]],
                                    dtype=np.int64), 0, 1)
    g2 = build_partition(np.asarray([[NORMAL_ID_START, 2,
                                      NORMAL_ID_START + 1]],
                                    dtype=np.int64), 0, 1)
    attach_vstore(g1, DIM)
    with mutation_lock():
        enroll_migration_sink("test-vector-sink", g2)
    try:
        vids = np.arange(NORMAL_ID_START, NORMAL_ID_START + 16,
                         dtype=np.int64)
        total = upsert_batch_into([g1], vids, make_vectors(vids, DIM))
        assert total == 16  # the sink mirror is excluded from the count
    finally:
        with mutation_lock():
            deroll_migration_sink("test-vector-sink")
    assert getattr(g2, "vstore", None) is not None  # attach-on-demand
    assert g2.vstore.digest() == g1.vstore.digest()


# ---------------------------------------------------------------------------
# parser: the knn() clause
# ---------------------------------------------------------------------------

def _parse(ss, text):
    return Parser(ss).parse(text)


def test_parser_knn_iri_anchor_and_modes(tri_world):
    ss = CyclicStrings(tri_world[1])
    q = _parse(ss, "SELECT ?a ?b WHERE { knn(?a, <urn:cyc:v:0>, 5) . "
                   "?a <urn:cyc:p:p1> ?b }")
    assert q.knn is not None and q.knn.k == 5
    assert q.knn.anchor_vid == NORMAL_ID_START
    assert q.knn.mode == "rank_then_pattern"
    q = _parse(ss, "SELECT ?a ?b WHERE { ?a <urn:cyc:p:p1> ?b . "
                   "knn(?a, <urn:cyc:v:0>, 5) }")
    assert q.knn.mode == "pattern_then_rank"
    q = _parse(ss, "SELECT ?a WHERE { knn(?a, <urn:cyc:v:3>, 7, l2) }")
    assert q.knn.mode == "scan" and q.knn.metric == "l2"


def test_parser_knn_literal_vector_anchor(tri_world):
    ss = CyclicStrings(tri_world[1])
    q = _parse(ss, "SELECT ?a WHERE { knn(?a, (0.5 -1 0.25), 3, dot) }")
    assert q.knn.anchor_vid is None
    assert np.allclose(q.knn.anchor_vec, [0.5, -1.0, 0.25])


@pytest.mark.parametrize("bad", [
    # two clauses
    "SELECT ?a WHERE { knn(?a, <urn:cyc:v:0>, 5) . "
    "knn(?a, <urn:cyc:v:1>, 5) }",
    # k < 1
    "SELECT ?a WHERE { knn(?a, <urn:cyc:v:0>, 0) }",
    # unknown metric
    "SELECT ?a WHERE { knn(?a, <urn:cyc:v:0>, 5, manhattan) }",
    # empty literal vector
    "SELECT ?a WHERE { knn(?a, (), 5) }",
    # nested group
    "SELECT ?a ?b WHERE { { knn(?a, <urn:cyc:v:0>, 5) . "
    "?a <urn:cyc:p:p1> ?b } UNION { ?a <urn:cyc:p:p2> ?b } }",
])
def test_parser_knn_refusals(tri_world, bad):
    ss = CyclicStrings(tri_world[1])
    with pytest.raises(SPARQLSyntaxError):
        _parse(ss, bad)


# ---------------------------------------------------------------------------
# composition through the serving path: modes, routes, engines
# ---------------------------------------------------------------------------

Q_RANK_THEN_PATTERN = ("SELECT ?a ?b WHERE { knn(?a, <urn:cyc:v:0>, 6) "
                       ". ?a <urn:cyc:p:p1> ?b }")
Q_PATTERN_THEN_RANK = ("SELECT ?a ?b WHERE { ?a <urn:cyc:p:p1> ?b . "
                       "knn(?a, <urn:cyc:v:0>, 6) }")
Q_SCAN = "SELECT ?a WHERE { knn(?a, <urn:cyc:v:0>, 6) }"


@pytest.mark.parametrize("text,mode", [
    (Q_RANK_THEN_PATTERN, "rank_then_pattern"),
    (Q_PATTERN_THEN_RANK, "pattern_then_rank"),
    (Q_SCAN, "scan"),
])
def test_compositions_byte_identical_across_routes_and_engines(
        tri_world, text, mode):
    g, ss, proxy = _hybrid_world(tri_world)
    Global.enable_vectors = True
    tables = {}
    for route in ("host", "device"):
        Global.knn_device = route
        for device in ("cpu", "tpu"):
            q = proxy.serve_query(text, blind=False, device=device)
            assert q.result.status_code == ErrorCode.SUCCESS
            assert q.knn_mode == mode
            assert q.knn_route == route
            tables[(route, device)] = np.array(q.result.table)
    base = tables[("host", "cpu")]
    assert base.size  # the composition produced rows
    for key, table in tables.items():
        assert np.array_equal(table, base), key


def test_rank_then_pattern_restricts_to_topk_seeds(tri_world):
    g, ss, proxy = _hybrid_world(tri_world)
    Global.enable_vectors = True
    anchor = np.asarray(g.vstore.get(NORMAL_ID_START))
    seeds, _s, _d = vknn.scan_topk(g.vstore, anchor, 6, "cosine")
    q = proxy.serve_query(Q_RANK_THEN_PATTERN, blind=False)
    got_a = set(q.result.table[:, q.result.var2col(-1)].tolist())
    assert got_a and got_a <= set(seeds.tolist())


def test_pattern_then_rank_filters_binding_set(tri_world):
    g, ss, proxy = _hybrid_world(tri_world)
    Global.enable_vectors = True
    plain = proxy.serve_query("SELECT ?a ?b WHERE "
                              "{ ?a <urn:cyc:p:p1> ?b }", blind=False)
    ranked = proxy.serve_query(Q_PATTERN_THEN_RANK, blind=False)
    col = ranked.result.var2col(-1)
    kept = set(ranked.result.table[:, col].tolist())
    assert 0 < len(kept) <= 6  # at most k distinct survivors
    assert ranked.result.table.shape[0] < plain.result.table.shape[0]


def test_knn_refused_when_vectors_off(tri_world):
    g, ss, proxy = _hybrid_world(tri_world)
    assert Global.enable_vectors is False
    with pytest.raises(WukongError) as ei:
        proxy.serve_query(Q_SCAN, blind=True)
    assert ei.value.code == ErrorCode.ATTR_DISABLE


def test_vectors_off_graph_path_zero_touch(tri_world):
    """With the knob off, a knn-free graph query must touch nothing in
    the vector plane: identical reply bytes and frozen wukong_vector_*
    counters."""
    from wukong_tpu.obs.metrics import get_registry

    g, ss, proxy = _hybrid_world(tri_world)
    text = "SELECT ?a ?b WHERE { ?a <urn:cyc:p:p1> ?b }"
    reg = get_registry()

    def vec_counts():
        return {n: [s.get("value", s.get("count"))
                    for s in fam["series"]]
                for n, fam in reg.snapshot().items()
                if n.startswith("wukong_vector_")}

    Global.enable_vectors = True
    on = proxy.serve_query(text, blind=False)
    before = vec_counts()
    Global.enable_vectors = False
    off = proxy.serve_query(text, blind=False)
    assert vec_counts() == before
    assert np.array_equal(on.result.table, off.result.table)


def test_device_demotion_feedback_pins_route_to_host(tri_world):
    """knn_device auto + a device failure: the engine latches the
    demotion, the proxy flips the template's memoized route to host, and
    the SAME template's next query plans route=host up front."""
    g, ss, proxy = _hybrid_world(tri_world)
    Global.enable_vectors = True
    Global.knn_device = "auto"
    Global.knn_split_threshold = 1  # every scan is "wide enough" for device

    def boom():
        raise RuntimeError("injected device failure")

    vknn._DEVICE_FAIL_HOOK = boom
    try:
        q = proxy.serve_query(Q_RANK_THEN_PATTERN, blind=False)
    finally:
        vknn._DEVICE_FAIL_HOOK = None
    assert q.result.status_code == ErrorCode.SUCCESS  # degraded, not broken
    assert q.knn_route == "device" and q.knn_demoted is not None
    q2 = proxy.serve_query(Q_RANK_THEN_PATTERN, blind=False)
    assert q2.knn_route == "host"  # the memo absorbed the demotion
    assert np.array_equal(q2.result.table, q.result.table)


def test_explain_renders_knn_estimate_line(tri_world):
    g, ss, proxy = _hybrid_world(tri_world)
    Global.enable_vectors = True
    r = proxy.explain_query(Q_RANK_THEN_PATTERN)
    assert r["knn"]["mode"] == "rank_then_pattern"
    assert r["knn"]["k"] == 6
    assert r["knn"]["est_rows"] == g.vstore.live_count()
    assert r["knn"]["est_bytes"] == g.vstore.live_count() * DIM * 4
    assert "knn:" in r["rendered"] and "est_rows=192" in r["rendered"]


def test_result_cache_key_separates_knn_variants(tri_world):
    """Two queries differing only in the knn clause (anchor / k) must
    classify to different reuse keys; vector mutations are a declared
    invalidation cause."""
    from wukong_tpu.obs.reuse import INVALIDATION_CAUSES, classify

    g, ss, proxy = _hybrid_world(tri_world)
    Global.enable_vectors = True
    assert "vector" in INVALIDATION_CAUSES
    qa = proxy._parse_text(Q_RANK_THEN_PATTERN)
    qb = proxy._parse_text(Q_RANK_THEN_PATTERN.replace(", 6)", ", 7)"))
    qc = proxy._parse_text(Q_RANK_THEN_PATTERN.replace(
        "<urn:cyc:v:0>", "<urn:cyc:v:1>"))
    keys = set()
    for q in (qa, qb, qc):
        key, reason = classify(q)
        assert reason is None
        keys.add(key)
    assert len(keys) == 3


# ---------------------------------------------------------------------------
# the GraphRAG serving loop (Emulator.run_graphrag)
# ---------------------------------------------------------------------------

def test_run_graphrag_mixed_loop_serves_both_kinds(tri_world):
    from wukong_tpu.runtime.emulator import Emulator

    g, ss, proxy = _hybrid_world(tri_world)
    Global.enable_vectors = True
    graph_texts = ["SELECT ?a ?b WHERE { ?a <urn:cyc:p:p1> ?b }"]
    tmpl = "SELECT ?a ?b WHERE { knn(?a, {anchor}, 4) . " \
           "?a <urn:cyc:p:p1> ?b }"
    anchors = [f"<urn:cyc:v:{i}>" for i in range(8)]
    out = Emulator(proxy).run_graphrag(
        graph_texts, tmpl, anchors, duration_s=0.4, warmup_s=0.1,
        clients=2, seed=7)
    assert out["errors"] == 0
    assert out["hybrid"]["served"] > 0 and out["graph"]["served"] > 0
