"""WatDiv-family synthesizer: dataset, templates, engines, emulator-style batch."""

import numpy as np
import pytest

from bgp_oracle import TripleIndex, eval_bgp
from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.engine.tpu import TPUEngine
from wukong_tpu.loader.watdiv import (
    TEMPLATES,
    VirtualWatdivStrings,
    generate_watdiv,
    write_dataset,
)
from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.runtime.proxy import Proxy
from wukong_tpu.sparql.parser import Parser
from wukong_tpu.store.gstore import build_partition
from wukong_tpu.types import IN


@pytest.fixture(scope="module")
def world():
    triples, lay = generate_watdiv(20, seed=1)
    g = build_partition(triples, 0, 1)
    ss = VirtualWatdivStrings(20, seed=1)
    idx = TripleIndex(triples)
    return triples, lay, g, ss, idx


def test_scale_and_roundtrip(world):
    triples, lay, g, ss, idx = world
    assert len(triples) > 50_000
    # string roundtrip over a sample
    rng = np.random.default_rng(0)
    ids = np.unique(np.concatenate([triples[:, 0], triples[:, 2]]))
    for vid in rng.choice(ids, 100, replace=False):
        if ss.exist_id(int(vid)):
            assert ss.str2id(ss.id2str(int(vid))) == int(vid)


@pytest.mark.parametrize("name", sorted(TEMPLATES))
def test_templates_parse_fill_and_run(world, name):
    triples, lay, g, ss, idx = world
    proxy = Proxy(g, ss, CPUEngine(g, ss), TPUEngine(g, ss))
    tmpl = Parser(ss).parse_template(TEMPLATES[name])
    proxy.fill_template(tmpl)
    rng = np.random.default_rng(3)
    q = tmpl.instantiate(rng)
    raw = [(p.subject, p.predicate, p.object) for p in q.pattern_group.patterns]
    heuristic_plan(q)
    proxy.cpu.execute(q)
    assert q.result.status_code == 0
    got = sorted(map(tuple, q.result.table.tolist()))
    want = sorted(eval_bgp(idx, raw, q.result.required_vars))
    assert got == want


def test_tpu_matches_cpu_on_watdiv(world):
    triples, lay, g, ss, idx = world
    tpu = TPUEngine(g, ss)
    cpu = CPUEngine(g, ss)
    proxy = Proxy(g, ss, cpu, tpu)
    tmpl = Parser(ss).parse_template(TEMPLATES["F1"])
    proxy.fill_template(tmpl)
    rng = np.random.default_rng(5)
    qc = tmpl.instantiate(rng)
    heuristic_plan(qc)
    cpu.execute(qc)
    # same instance through the TPU engine
    qt = tmpl.instantiate(np.random.default_rng(5))
    heuristic_plan(qt)
    tpu.execute(qt)
    assert qt.result.status_code == 0
    assert sorted(map(tuple, qt.result.table.tolist())) == \
        sorted(map(tuple, qc.result.table.tolist()))


def test_write_dataset(tmp_path):
    meta = write_dataset(str(tmp_path), 5, seed=2)
    assert (tmp_path / "id_triples.npy").exists()
    assert (tmp_path / "queries" / "S1").exists()
    assert meta["num_triples"] > 10_000
